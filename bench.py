#!/usr/bin/env python
"""Benchmark: ResNet-50 training throughput on the available chip(s).

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric: ResNet-50 images/sec/chip (the BASELINE.json:2 primary metric),
steady-state window excluding compilation (BASELINE.md reporting rules).
``vs_baseline``: measured MFU / 0.50 — the north-star "≥50% MFU" target
(BASELINE.json:5); the reference publishes no absolute number to compare
against (BASELINE.json:13 "published": {}).

All diagnostics go to stderr; stdout carries only the JSON line.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from distributed_tensorflow_tpu.utils import benchmarking as bm

    bm.honor_env_platform()
    import jax.numpy as jnp
    import numpy as np
    import optax

    from distributed_tensorflow_tpu.models import common
    from distributed_tensorflow_tpu.models.resnet import (
        ResNet50, ResNetConfig, flops_per_example,
    )
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh, describe
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )
    from distributed_tensorflow_tpu.utils import flops as flops_lib

    # Robust TPU detection for tunneled platforms lives in
    # utils/benchmarking.py, shared with tools/bench_bert.py.
    devices, n_chips, platform, on_tpu = bm.describe_devices()
    log(f"bench devices: {devices} (platform={platform})")
    # A CPU row captured because a chip session held the lease is not a
    # "relay down" row: the TPU evidence is being produced concurrently
    # by the session. Stamp that context so the driver row can't be
    # misread (VERDICT r4 weak #1). DTF_CHIP_PINNED is set by
    # pin_cpu_if_locked AT the pin decision — re-probing the lock here
    # could disagree with the reason this process is on CPU — and
    # pin_is_current bounds an ANCESTOR's stamp by pid+age so a child
    # spawned long after the session ended can't inherit the claim
    # (ADVICE r5).
    from distributed_tensorflow_tpu.utils.chip_lock import pin_is_current

    session_live = not on_tpu and pin_is_current()
    if session_live:
        log("chip session live: this CPU row ran concurrently with an "
            "on-chip measurement session (see the current round's "
            "artifacts/onchip_* directory for its rows)")

    # Per-chip batch sized for a v5e (16 GiB HBM) bf16 train step; tiny on
    # CPU so the fallback run finishes fast.
    per_chip_batch = int(os.environ.get("BENCH_BATCH", "256" if on_tpu else "8"))
    image = 224 if on_tpu else 64
    # Round-2 tuning (PERF_NOTES.md): space-to-depth stem + bf16 BN output
    # measured +28% over the round-1 config; batch 256/chip is the knee
    # (384/512/1024 all slower per image — HBM pressure). The BENCH_* env
    # knobs exist so tools/ablate_resnet.py can sweep variants through THIS
    # harness instead of duplicating it.
    stem = os.environ.get("BENCH_STEM", "space_to_depth" if on_tpu else "conv")
    norm_dtype = os.environ.get("BENCH_NORM_DTYPE") or None
    global_batch = per_chip_batch * n_chips

    mesh = build_mesh(MeshSpec(data=-1))
    log(f"mesh: {describe(mesh)}  global_batch={global_batch}  image={image}")

    from distributed_tensorflow_tpu.train import OptimizerConfig, make_optimizer
    from jax.sharding import NamedSharding

    rng = np.random.RandomState(0)
    measured = int(os.environ.get("BENCH_STEPS", "20"))
    dbg = os.environ.get("BENCH_DEBUG_METRICS", "0") == "1"

    def make_cfg(block_impl):
        return (
            ResNetConfig(stem=stem, norm_dtype=norm_dtype,
                         block_impl=block_impl)
            if on_tpu
            else ResNetConfig(
                stage_sizes=(1, 1, 1, 1), width=16, num_classes=100,
                dtype="float32", stem=stem, norm_dtype=norm_dtype,
                block_impl=block_impl,
            )
        )

    def measure_resident(block_impl):
        """Build model+state+step for one block impl and time the
        resident-batch window. Returns (cfg, state, step, steps/sec)."""
        cfg = make_cfg(block_impl)
        model = ResNet50(cfg, mesh)
        loss_fn = common.classification_loss_fn(model)
        # the exact optimizer the resnet50_imagenet workload uses
        # (coupled L2 on kernels, fused into the update pass)
        tx = make_optimizer(OptimizerConfig(
            name="momentum", learning_rate=0.1, momentum=0.9,
            weight_decay=1e-4,
        ))
        state, specs = init_train_state(
            common.make_init_fn(model, (image, image, 3)), tx, mesh,
            jax.random.PRNGKey(0),
        )
        step = jit_train_step(
            make_train_step(loss_fn, tx, StepOptions(
                compute_grad_norm=dbg, check_grads_finite=dbg)),
            mesh, specs,
        )
        batch = {
            # bf16 images on TPU: halves host->HBM bytes; the first conv
            # casts anyway
            "image": rng.randn(global_batch, image, image, 3)
            .astype(np.float32)
            .astype(jnp.bfloat16 if on_tpu else np.float32),
            "label": rng.randint(0, cfg.num_classes, global_batch)
            .astype(np.int32),
        }
        batch = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, sh.batch_spec(np.ndim(x)))
            ),
            batch,
        )
        # Timing sync MUST fetch a value (tunneled platforms): see
        # utils/benchmarking.timed_steps, shared with tools/bench_bert.py.
        state, steps_per_sec, _ = bm.timed_steps(
            step, state, lambda: batch, warmup=3, measured=measured,
            log=lambda m: log(f"[{block_impl}] {m}"),
        )
        return cfg, state, step, steps_per_sec

    pinned_impl = os.environ.get("BENCH_BLOCK_IMPL")
    # BENCH_FORCE_AB=1: run the A/B selection on CPU too (plumbing test
    # — the branch must not first execute inside a scarce chip window)
    force_ab = os.environ.get("BENCH_FORCE_AB") == "1"
    alt = None  # (impl, steps_per_sec) of the losing variant, if A/B'd
    if pinned_impl or (not on_tpu and not force_ab):
        impl = pinned_impl or "standard"
        cfg, state, step, steps_per_sec = measure_resident(impl)
    else:
        # Unpinned on TPU: time BOTH block impls and report the faster —
        # a default that has never been timed end-to-end must not be
        # able to silently regress the round's headline number (round-3
        # lesson: the fused default first compiled at bench shapes after
        # 2 rounds). Each probe variant is FREED before the next build
        # (per-chip batch 256 is the HBM knee; a second resident train
        # state would bias the comparison), then the winner is rebuilt
        # fresh for the headline + fed windows.
        def probe(impl):
            try:
                out = measure_resident(impl)
            except Exception:
                import traceback

                traceback.print_exc(file=sys.stderr)
                log(f"{impl}-blocks measurement failed")
                return None
            rate = out[3]
            del out
            jax.clear_caches()  # drop the probe's executables/buffers
            return rate

        rates = {impl: probe(impl) for impl in ("fused", "standard")}
        if rates["standard"] is None and rates["fused"] is None:
            raise RuntimeError("both block impls failed to measure")
        winner = max((i for i in rates if rates[i] is not None),
                     key=lambda i: rates[i])
        loser = {"fused": "standard", "standard": "fused"}[winner]
        if rates[loser] is not None:
            alt = (loser, rates[loser])
        log(f"block-impl A/B: fused={rates['fused']} "
            f"standard={rates['standard']} -> {winner}")
        cfg, state, step, steps_per_sec = measure_resident(winner)
    images_per_sec = steps_per_sec * global_batch
    images_per_sec_per_chip = images_per_sec / n_chips

    # ---- pipeline-fed window (VERDICT round-1 item 3) -------------------
    # Same jit step, but every batch flows host->device through the
    # Prefetcher. Two modes:
    #   default   — K pre-staged bf16 numpy batches (transfer + dispatch
    #               overlap is what's being proven; decode outside)
    #   BENCH_DATA=jpeg — every batch decodes from a JPEG record file
    #               built at setup (VERDICT r2 item 2: decode INSIDE the
    #               measured window, through the production
    #               JpegClassificationDataset thread-pool path)
    from distributed_tensorflow_tpu.data import Prefetcher

    img_dtype = jnp.bfloat16 if on_tpu else np.float32
    fed_data = os.environ.get("BENCH_DATA", "synthetic")
    if fed_data == "jpeg":
        import tempfile

        from distributed_tensorflow_tpu.data.jpeg_records import (
            JpegClassificationDataset, make_jpeg_record_file,
        )

        n_src = max(512, 2 * global_batch)
        src_size = image + 32  # decode-then-crop, the ImageNet shape flow
        # JPEG-compressible synthetic content (8x block upsample): pure
        # noise would decode slower than any real photo; blocks land
        # between noise and natural-image decode cost
        small = rng.randint(0, 255, (n_src, src_size // 8, src_size // 8, 3))
        src_imgs = np.kron(
            small, np.ones((1, 8, 8, 1), np.uint8)
        ).astype(np.uint8)[:, :src_size, :src_size]
        rec = os.path.join(tempfile.mkdtemp(prefix="bench_jpeg_"), "rec")
        make_jpeg_record_file(rec, src_imgs, rng.randint(
            0, cfg.num_classes, n_src))
        ds = JpegClassificationDataset(rec, image, global_batch, train=True)
        # standalone host decode rate: the fed window's ceiling is
        # min(device rate, this). On the tunneled rig the host is a
        # single core, so a low fed efficiency there reads as HOST-bound
        # (cores), not a framework defect — this number disambiguates.
        import time as _time

        ds.batch(0)  # warm pool/caches
        t0 = _time.perf_counter()
        ds.batch(1)
        host_decode_rate = global_batch / (_time.perf_counter() - t0)
        log(f"jpeg-fed: {n_src} records at {src_size}px -> decode+augment "
            f"to {image}px inside the measured window "
            f"(decoder={ds.decoder}, host decode "
            f"{host_decode_rate:.0f} img/s on {os.cpu_count()} cores)")
        fed_data = f"jpeg/{ds.decoder}"

        def host_stream():
            i = 0
            while True:
                b = ds.batch(i)
                b["image"] = b["image"].astype(img_dtype)
                yield b
                i += 1

        # shardings only need shapes/dtypes — don't pay a decode here
        probe = {
            "image": np.zeros((global_batch, image, image, 3), img_dtype),
            "label": np.zeros((global_batch,), np.int32),
        }
    else:
        host_batches = []
        for k in range(4):
            host_batches.append({
                "image": rng.randn(global_batch, image, image, 3)
                .astype(np.float32).astype(img_dtype),
                "label": rng.randint(0, cfg.num_classes, global_batch)
                .astype(np.int32),
            })

        def host_stream():
            i = 0
            while True:
                yield host_batches[i % len(host_batches)]
                i += 1

        probe = host_batches[0]

    shardings = jax.tree.map(
        lambda x: NamedSharding(mesh, sh.batch_spec(np.ndim(x))),
        probe,
    )
    # BENCH_PUT_SYNC=1: force each transfer to COMPLETE inside the
    # prefetch thread (block_until_ready on the put) instead of lazily at
    # step dispatch — the A/B knob for the round-2 tunneled-TPU fed
    # anomaly (0.044 efficiency attributed to dependent-dispatch
    # transfer; PERF_NOTES.md round-2)
    put_sync = os.environ.get("BENCH_PUT_SYNC") == "1"

    def put(b):
        dev = jax.tree.map(jax.device_put, b, shardings)
        if put_sync:
            jax.block_until_ready(dev)
        return dev

    fed = iter(Prefetcher(host_stream(), depth=2, transform=put))
    state, fed_steps_per_sec, _ = bm.timed_steps(
        step, state, lambda: next(fed), warmup=2, measured=measured, log=log,
    )
    fed_images_per_sec_per_chip = fed_steps_per_sec * global_batch / n_chips
    pipeline_efficiency = fed_steps_per_sec / steps_per_sec
    log(f"pipeline-fed: steps/sec={fed_steps_per_sec:.3f} "
        f"({pipeline_efficiency:.1%} of resident-batch)")
    # flops_per_example is fwd-only (framework contract, utils/flops.py);
    # the SHARED helper obs/goodput.train_mfu applies the fwd+bwd
    # multiplier and publishes the `mfu` gauge into the process registry,
    # so this JSON line and a scrape can never disagree.
    from distributed_tensorflow_tpu.obs import goodput
    from distributed_tensorflow_tpu.obs.registry import default_registry

    peak = flops_lib.peak_flops_per_chip(devices[0])
    mfu = goodput.train_mfu(
        flops_per_example(cfg, image) * global_batch, steps_per_sec,
        n_chips=n_chips, peak_per_chip=peak, registry=default_registry(),
    )
    log(f"steps/sec={steps_per_sec:.3f} images/sec/chip={images_per_sec_per_chip:.1f} "
        f"MFU={mfu:.3f} (peak={peak:.3g})")

    # provenance block (obs/scaling.py): the shared stamp that keeps a
    # CPU-fallback row from ever reading as a TPU number (BENCH_r02-r05)
    from distributed_tensorflow_tpu.obs import scaling

    print(json.dumps(scaling.stamp_provenance({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(images_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "mfu": round(mfu, 4),
        "platform": platform,
        "n_chips": n_chips,
        "global_batch": global_batch,
        "image_size": image,
        "full_resnet50": bool(on_tpu),
        "stem": cfg.stem,
        "norm_dtype": cfg.norm_dtype or cfg.dtype,
        "block_impl": cfg.block_impl,
        "pipeline_fed_images_per_sec_per_chip":
            round(fed_images_per_sec_per_chip, 2),
        "pipeline_efficiency": round(pipeline_efficiency, 4),
        "fed_data": fed_data,
        **({"chip_session_live": True} if session_live else {}),
        **({"alt_block_impl": alt[0],
            "alt_images_per_sec_per_chip":
                round(alt[1] * global_batch / n_chips, 2)}
           if alt else {}),
        **({"host_decode_images_per_sec": round(host_decode_rate, 1),
            "host_cores": os.cpu_count()}
           if fed_data.startswith("jpeg") else {}),
    }, mesh)))


if __name__ == "__main__":
    _pinned = "BENCH_BLOCK_IMPL" in os.environ
    # Honest CPU row instead of hanging the driver when the relay is down
    # (probe + explicit-pin contract: utils/benchmarking.py).
    from distributed_tensorflow_tpu.utils.benchmarking import (
        fall_back_to_cpu_if_unreachable,
    )

    fall_back_to_cpu_if_unreachable(log=log)
    try:
        main()
    except Exception:
        if _pinned:
            raise
        # The fused-kernel default must never cost the round its perf
        # number: on any failure, replace this process (releasing the
        # device lease) with a standard-blocks run.
        import traceback

        traceback.print_exc(file=sys.stderr)
        log("bench failed with default blocks; retrying with standard")
        os.environ["BENCH_BLOCK_IMPL"] = "standard"
        # deliberately NOT skipping the probe: the failure may BE the
        # relay dying mid-run, and the retry must re-detect that.
        os.environ.pop("BENCH_SKIP_PROBE", None)
        os.execv(sys.executable, [sys.executable, os.path.abspath(__file__)])
