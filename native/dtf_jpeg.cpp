// Native JPEG decode stage for the input pipeline (SURVEY.md §2a 'Input
// pipeline'; the reference ran per-worker tf.data decode_jpeg C++ kernels
// — this is the framework's equivalent hot loop).
//
// Built separately from dtf_runtime.cpp because it links -ljpeg (the
// system libjpeg); runtime/native.py's core library keeps its
// no-external-deps invariant and data/native_jpeg.py degrades to the PIL
// path when this library can't build.
//
// C ABI (ctypes, see data/native_jpeg.py):
//   dtf_jpeg_dims   — parse headers only: [h, w] per stream (cheap).
//   dtf_jpeg_decode_crop_resize — per stream: decode (libjpeg, with the
//       scale_denom fast path when the crop is much larger than the
//       target), crop rect (y, x, ch, cw in FULL-RES coords), bilinear
//       resize to out_size x out_size RGB u8.
//
// Crop POLICY (what rect, which flips) stays in Python
// (data/augment.py sample_crop_rect) — this file only executes pixels,
// so the augmentation recipe has exactly one definition.

#include <cstddef>
#include <cstdio>  // jpeglib.h needs size_t/FILE declared first

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
  ErrMgr* err = reinterpret_cast<ErrMgr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decode one JPEG stream to RGB. Returns false on corrupt input. When
// min(crop_h, crop_w) / out_size >= 2, asks libjpeg for a 1/2, 1/4 or
// 1/8-scale decode (DCT-domain downscale — the big win over a
// full-res decode + resize) and maps the crop rect into scaled coords.
bool decode_rgb(const uint8_t* data, int64_t len, int denom,
                std::vector<uint8_t>& pixels, int& h, int& w) {
  jpeg_decompress_struct cinfo;
  ErrMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = on_error;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  // a corrupt/hostile header can declare up to 65500x65500 (~12.8 GB
  // RGB) — cap the decoded frame so a bad stream is a zero-fill
  // failure, not a bad_alloc that escapes the worker thread
  constexpr uint64_t kMaxPixels = 128ull * 1024 * 1024;  // 128 MPix
  if (static_cast<uint64_t>(cinfo.image_height) * cinfo.image_width >
      kMaxPixels) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  cinfo.scale_num = 1;
  cinfo.scale_denom = denom;
  jpeg_start_decompress(&cinfo);
  h = static_cast<int>(cinfo.output_height);
  w = static_cast<int>(cinfo.output_width);
  pixels.resize(static_cast<size_t>(h) * w * 3);
  JSAMPROW row;
  while (cinfo.output_scanline < cinfo.output_height) {
    row = pixels.data() + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear resize of an RGB crop (src coords) into out (S x S x 3).
// align_corners=false convention (pixel centers), matching the usual
// image-resampling grid; numerics differ from PIL's filtered resample
// by design — each decoder is its own deterministic stream.
void resize_bilinear(const uint8_t* src, int sh, int sw, int y0, int x0,
                     int ch, int cw, int out_size, uint8_t* out) {
  const float sy = static_cast<float>(ch) / out_size;
  const float sx = static_cast<float>(cw) / out_size;
  for (int oy = 0; oy < out_size; ++oy) {
    float fy = (oy + 0.5f) * sy - 0.5f + y0;
    fy = std::max(static_cast<float>(y0),
                  std::min(fy, static_cast<float>(y0 + ch - 1)));
    int iy = static_cast<int>(fy);
    iy = std::min(iy, sh - 1);
    int iy1 = std::min(iy + 1, std::min(y0 + ch - 1, sh - 1));
    float wy = fy - iy;
    for (int ox = 0; ox < out_size; ++ox) {
      float fx = (ox + 0.5f) * sx - 0.5f + x0;
      fx = std::max(static_cast<float>(x0),
                    std::min(fx, static_cast<float>(x0 + cw - 1)));
      int ix = static_cast<int>(fx);
      ix = std::min(ix, sw - 1);
      int ix1 = std::min(ix + 1, std::min(x0 + cw - 1, sw - 1));
      float wx = fx - ix;
      const uint8_t* p00 = src + (static_cast<size_t>(iy) * sw + ix) * 3;
      const uint8_t* p01 = src + (static_cast<size_t>(iy) * sw + ix1) * 3;
      const uint8_t* p10 = src + (static_cast<size_t>(iy1) * sw + ix) * 3;
      const uint8_t* p11 = src + (static_cast<size_t>(iy1) * sw + ix1) * 3;
      uint8_t* o = out + (static_cast<size_t>(oy) * out_size + ox) * 3;
      for (int c = 0; c < 3; ++c) {
        float v = (1 - wy) * ((1 - wx) * p00[c] + wx * p01[c]) +
                  wy * ((1 - wx) * p10[c] + wx * p11[c]);
        o[c] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// Header-only pass: dims[2*i] = height, dims[2*i+1] = width. Returns the
// number of unparsable streams (their dims are set to 0).
int dtf_jpeg_dims(const uint8_t* data, const int64_t* offsets,
                  const int64_t* lengths, int64_t n, int64_t* dims) {
  int failures = 0;
  for (int64_t i = 0; i < n; ++i) {
    jpeg_decompress_struct cinfo;
    ErrMgr err;
    cinfo.err = jpeg_std_error(&err.pub);
    err.pub.error_exit = on_error;
    if (setjmp(err.jump)) {
      jpeg_destroy_decompress(&cinfo);
      dims[2 * i] = dims[2 * i + 1] = 0;
      ++failures;
      continue;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data + offsets[i]),
                 static_cast<unsigned long>(lengths[i]));
    jpeg_read_header(&cinfo, TRUE);
    dims[2 * i] = cinfo.image_height;
    dims[2 * i + 1] = cinfo.image_width;
    jpeg_destroy_decompress(&cinfo);
  }
  return failures;
}

// rects: int64 [n, 4] = (y, x, ch, cw) per image in FULL-RESOLUTION
// coordinates. out: u8 [n, out_size, out_size, 3]. Returns the number of
// failed streams (their output slots are zeroed).
int dtf_jpeg_decode_crop_resize(const uint8_t* data, const int64_t* offsets,
                                const int64_t* lengths, const int64_t* rects,
                                int64_t n, int out_size, uint8_t* out,
                                int n_threads) {
  std::atomic<int> failures{0};
  std::atomic<int64_t> next{0};
  const size_t out_stride = static_cast<size_t>(out_size) * out_size * 3;

  auto worker = [&]() {
    std::vector<uint8_t> pixels;
    for (;;) {
      const int64_t i = next.fetch_add(1);
      if (i >= n) return;
      int64_t y = rects[4 * i], x = rects[4 * i + 1];
      int64_t ch = rects[4 * i + 2], cw = rects[4 * i + 3];
      // DCT-domain downscale: largest denom in {1,2,4,8} keeping the
      // scaled crop at least out_size on its short side
      int denom = 1;
      while (denom < 8 &&
             std::min(ch, cw) / (denom * 2) >= static_cast<int64_t>(out_size))
        denom *= 2;
      int h = 0, w = 0;
      bool ok;
      try {
        ok = decode_rgb(data + offsets[i], lengths[i], denom, pixels, h, w);
      } catch (...) {  // bad_alloc etc. must not escape the thread
        ok = false;
      }
      if (!ok) {
        std::memset(out + i * out_stride, 0, out_stride);
        ++failures;
        continue;
      }
      // map the rect into scaled coords (libjpeg rounds output dims UP:
      // out = ceil(full / denom)), clamping to the decoded frame
      int64_t sy = y / denom, sx = x / denom;
      int64_t sch = std::max<int64_t>(1, ch / denom);
      int64_t scw = std::max<int64_t>(1, cw / denom);
      sy = std::min<int64_t>(sy, h - 1);
      sx = std::min<int64_t>(sx, w - 1);
      sch = std::min<int64_t>(sch, h - sy);
      scw = std::min<int64_t>(scw, w - sx);
      resize_bilinear(pixels.data(), h, w, static_cast<int>(sy),
                      static_cast<int>(sx), static_cast<int>(sch),
                      static_cast<int>(scw), out_size,
                      out + i * out_stride);
    }
  };

  const int nt = std::max(1, std::min<int>(n_threads, n));
  std::vector<std::thread> threads;
  threads.reserve(nt - 1);
  for (int t = 1; t < nt; ++t) threads.emplace_back(worker);
  worker();
  for (auto& t : threads) t.join();
  return failures.load();
}

}  // extern "C"
