// dtf_runtime — native host-side runtime for the TPU framework.
//
// The reference framework's host data plane is C++ (SURVEY.md §2b bold
// rows): FIFOQueue/ConditionalAccumulator kernels ($TF/python/ops/
// data_flow_ops.py:774,1386 wrap C++ kernels), QueueRunner threads, and
// the Saver's C++ IO kernels. On TPU the *device* data plane is XLA/ICI,
// but the host side still needs native muscle: feeding batches at HBM
// rates (SURVEY.md §7 ranks input starvation the #1 hard part) and
// writing checkpoint shards without stalling the step loop.
//
// Components (all C ABI, consumed via ctypes from
// distributed_tensorflow_tpu/runtime/):
//
//  1. Record loader: mmap'd fixed-size-record file → shuffled, sharded,
//     batched byte buffers, assembled by a worker pool and handed over a
//     bounded ordered queue (the native descendant of FIFOQueue +
//     QueueRunner, minus the graph).
//  2. File IO: checksummed atomic write (tmp + fsync + rename) and read
//     with CRC verification — the Saver-kernel analog used by the
//     checkpoint tensor store.
//
// Determinism contract: the epoch shuffle is a Fisher–Yates driven by
// SplitMix64, reimplemented bit-for-bit in runtime/loader.py's Python
// fallback, so native and fallback paths yield identical batches.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// SplitMix64 + Fisher–Yates (mirrored in runtime/loader.py)
// ---------------------------------------------------------------------------

inline uint64_t splitmix64(uint64_t& state) {
  uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

void epoch_permutation(int64_t n, uint64_t seed, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  uint64_t s = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(splitmix64(s) % static_cast<uint64_t>(i + 1));
    std::swap(out[i], out[j]);
  }
}

// ---------------------------------------------------------------------------
// CRC32 (reflected, poly 0xEDB88320 — zlib-compatible)
// ---------------------------------------------------------------------------

uint32_t crc32_table[256];
std::once_flag crc_once;

void crc32_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
}

uint32_t crc32(const uint8_t* data, int64_t n, uint32_t crc = 0) {
  std::call_once(crc_once, crc32_init);
  crc = ~crc;
  for (int64_t i = 0; i < n; ++i)
    crc = crc32_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// ---------------------------------------------------------------------------
// Loader
// ---------------------------------------------------------------------------

struct Batch {
  std::vector<uint8_t> data;
  int64_t index = -1;
};

struct Loader {
  // immutable config
  int fd = -1;
  const uint8_t* base = nullptr;  // mmap
  int64_t file_bytes = 0;
  int64_t record_bytes = 0;
  int64_t n_records = 0;        // total in file
  int64_t batch_records = 0;    // records per (local) batch
  int64_t shard = 0, n_shards = 1;
  uint64_t seed = 0;
  int depth = 2;

  // derived
  int64_t shard_records = 0;    // records this shard sees per epoch
  int64_t batches_per_epoch = 0;

  // pipeline state
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_produce, cv_consume;
  std::deque<Batch*> ready;     // ordered by batch index
  int64_t next_to_hand = 0;     // next batch index produced
  int64_t next_to_take = 0;     // next batch index the consumer gets
  std::vector<Batch*> freelist;
  std::atomic<bool> stop{false};

  // epoch permutation cache (guarded by mu)
  int64_t perm_epoch = -1;
  std::vector<int64_t> perm;

  ~Loader() {
    {
      std::lock_guard<std::mutex> l(mu);
      stop.store(true);
    }
    cv_produce.notify_all();
    cv_consume.notify_all();
    for (auto& t : workers) t.join();
    for (auto* b : freelist) delete b;
    for (auto* b : ready) delete b;
    if (base) munmap(const_cast<uint8_t*>(base), file_bytes);
    if (fd >= 0) close(fd);
  }

  const std::vector<int64_t>& epoch_perm(int64_t epoch) {
    // caller holds mu
    if (epoch != perm_epoch) {
      perm.resize(n_records);
      epoch_permutation(n_records, seed + static_cast<uint64_t>(epoch), perm.data());
      perm_epoch = epoch;
    }
    return perm;
  }

  // record indices of global batch `bi` for this shard
  void batch_indices(int64_t bi, int64_t* out) {
    std::lock_guard<std::mutex> l(mu);
    int64_t epoch = bi / batches_per_epoch;
    int64_t pos = bi % batches_per_epoch;
    const auto& p = epoch_perm(epoch);
    // strided shard slice of the shuffled order (disjoint across shards)
    for (int64_t r = 0; r < batch_records; ++r) {
      int64_t k = (pos * batch_records + r) * n_shards + shard;
      out[r] = p[k];
    }
  }

  void fill(Batch* b, int64_t bi) {
    b->index = bi;
    b->data.resize(batch_records * record_bytes);
    std::vector<int64_t> idx(batch_records);
    batch_indices(bi, idx.data());
    for (int64_t r = 0; r < batch_records; ++r) {
      std::memcpy(b->data.data() + r * record_bytes,
                  base + idx[r] * record_bytes, record_bytes);
    }
  }

  void worker_loop() {
    while (true) {
      Batch* b = nullptr;
      int64_t bi = -1;
      {
        std::unique_lock<std::mutex> l(mu);
        cv_produce.wait(l, [&] {
          return stop.load() ||
                 (!freelist.empty() &&
                  next_to_hand - next_to_take < depth);
        });
        if (stop.load()) return;
        b = freelist.back();
        freelist.pop_back();
        bi = next_to_hand++;
      }
      fill(b, bi);
      {
        std::lock_guard<std::mutex> l(mu);
        // insert ordered by batch index
        auto it = ready.begin();
        while (it != ready.end() && (*it)->index < b->index) ++it;
        ready.insert(it, b);
      }
      cv_consume.notify_all();
    }
  }

  Batch* next() {
    std::unique_lock<std::mutex> l(mu);
    int64_t want = next_to_take;
    cv_consume.wait(l, [&] {
      return stop.load() ||
             (!ready.empty() && ready.front()->index == want);
    });
    if (stop.load()) return nullptr;
    Batch* b = ready.front();
    ready.pop_front();
    next_to_take++;
    l.unlock();
    // taking a batch lowers the in-flight count — wake a producer (without
    // this, consumers that hold several batches before releasing any would
    // deadlock the pipeline)
    cv_produce.notify_one();
    return b;
  }

  void release(Batch* b) {
    {
      std::lock_guard<std::mutex> l(mu);
      freelist.push_back(b);
    }
    cv_produce.notify_all();
  }
};

}  // namespace

extern "C" {

// ----- loader -------------------------------------------------------------

void* dtf_loader_create(const char* path, int64_t record_bytes,
                        int64_t batch_records, int n_threads, int depth,
                        uint64_t seed, int64_t shard, int64_t n_shards,
                        int64_t start_batch) {
  // Validate every divisor before use: the ABI promises nullptr on bad
  // args, not SIGFPE. (The Python wrapper checks too, but direct C callers
  // hit the divisions below.)
  if (record_bytes <= 0 || batch_records <= 0 || n_shards <= 0 ||
      shard < 0 || shard >= n_shards || start_batch < 0) {
    return nullptr;
  }
  auto* L = new Loader();
  L->next_to_hand = L->next_to_take = start_batch;
  L->fd = open(path, O_RDONLY);
  if (L->fd < 0) { delete L; return nullptr; }
  struct stat st;
  if (fstat(L->fd, &st) != 0) { delete L; return nullptr; }
  L->file_bytes = st.st_size;
  L->record_bytes = record_bytes;
  L->n_records = st.st_size / record_bytes;
  L->batch_records = batch_records;
  L->shard = shard;
  L->n_shards = n_shards;
  L->seed = seed;
  L->depth = depth < 1 ? 1 : depth;
  L->shard_records = L->n_records / n_shards;
  L->batches_per_epoch = L->shard_records / batch_records;
  if (L->batches_per_epoch < 1 || L->n_records < 1) { delete L; return nullptr; }
  L->base = static_cast<const uint8_t*>(
      mmap(nullptr, L->file_bytes, PROT_READ, MAP_PRIVATE, L->fd, 0));
  if (L->base == MAP_FAILED) { L->base = nullptr; delete L; return nullptr; }
  madvise(const_cast<uint8_t*>(L->base), L->file_bytes, MADV_WILLNEED);
  for (int i = 0; i < L->depth + 1; ++i) L->freelist.push_back(new Batch());
  if (n_threads < 1) n_threads = 1;
  // at most `depth` batches are ever in flight, so extra workers would
  // only sleep — cap instead of wasting threads
  if (n_threads > L->depth) n_threads = L->depth;
  for (int i = 0; i < n_threads; ++i)
    L->workers.emplace_back([L] { L->worker_loop(); });
  return L;
}

int64_t dtf_loader_batches_per_epoch(void* h) {
  return static_cast<Loader*>(h)->batches_per_epoch;
}

int64_t dtf_loader_n_records(void* h) {
  return static_cast<Loader*>(h)->n_records;
}

// Blocks until the next in-order batch is ready; returns an opaque batch
// handle (data pointer via dtf_batch_data). NULL after destroy.
void* dtf_loader_next(void* h) { return static_cast<Loader*>(h)->next(); }

const uint8_t* dtf_batch_data(void* b) {
  return static_cast<Batch*>(b)->data.data();
}

int64_t dtf_batch_index(void* b) { return static_cast<Batch*>(b)->index; }

void dtf_loader_release(void* h, void* b) {
  static_cast<Loader*>(h)->release(static_cast<Batch*>(b));
}

void dtf_loader_destroy(void* h) { delete static_cast<Loader*>(h); }

// Test hook: record indices for global batch `bi` (len = batch_records).
void dtf_loader_batch_indices(void* h, int64_t bi, int64_t* out) {
  static_cast<Loader*>(h)->batch_indices(bi, out);
}

// Exposed for fallback-parity tests.
void dtf_epoch_permutation(int64_t n, uint64_t seed, int64_t* out) {
  epoch_permutation(n, seed, out);
}

// ----- checksummed atomic file IO ----------------------------------------

// Layout: [payload][8-byte magic "DTFCKPT1"][8-byte LE length][4-byte CRC32]
// Write to <path>.tmp, fsync, rename — a crashed writer never corrupts an
// existing shard (the Saver's atomic-write discipline).
int dtf_write_file(const char* path, const void* data, int64_t nbytes) {
  std::string tmp = std::string(path) + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  const uint8_t* p = static_cast<const uint8_t*>(data);
  int64_t off = 0;
  while (off < nbytes) {
    ssize_t w = write(fd, p + off, nbytes - off);
    if (w < 0) { close(fd); return -2; }
    off += w;
  }
  const char magic[8] = {'D', 'T', 'F', 'C', 'K', 'P', 'T', '1'};
  uint64_t len = static_cast<uint64_t>(nbytes);
  uint32_t crc = crc32(p, nbytes);
  if (write(fd, magic, 8) != 8 ||
      write(fd, &len, 8) != 8 ||
      write(fd, &crc, 4) != 4) { close(fd); return -3; }
  if (fsync(fd) != 0) { close(fd); return -4; }
  close(fd);
  if (rename(tmp.c_str(), path) != 0) return -5;
  return 0;
}

// Returns payload size, or <0 on error (-2 bad trailer, -3 CRC mismatch).
// Pass out=NULL to query the size.
int64_t dtf_read_file(const char* path, void* out, int64_t cap) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 20) { close(fd); return -2; }
  int64_t payload = st.st_size - 20;
  uint8_t trailer[20];
  if (pread(fd, trailer, 20, payload) != 20 ||
      std::memcmp(trailer, "DTFCKPT1", 8) != 0) { close(fd); return -2; }
  uint64_t len;
  uint32_t crc;
  std::memcpy(&len, trailer + 8, 8);
  std::memcpy(&crc, trailer + 16, 4);
  if (static_cast<int64_t>(len) != payload) { close(fd); return -2; }
  if (out == nullptr) { close(fd); return payload; }
  if (cap < payload) { close(fd); return -4; }
  int64_t off = 0;
  uint8_t* o = static_cast<uint8_t*>(out);
  while (off < payload) {
    ssize_t r = pread(fd, o + off, payload - off, off);
    if (r <= 0) { close(fd); return -5; }
    off += r;
  }
  close(fd);
  if (crc32(o, payload) != crc) return -3;
  return payload;
}

uint32_t dtf_crc32(const void* data, int64_t n) {
  return crc32(static_cast<const uint8_t*>(data), n);
}

}  // extern "C"
