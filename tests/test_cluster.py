"""Cluster bootstrap: pod auto-detection + config plumbing (SURVEY.md §2b
'Cluster bootstrap' row; VERDICT round-1 item 5 — the TPUClusterResolver
analog must engage without hand-exported env vars)."""

import pytest

from distributed_tensorflow_tpu.parallel import cluster


@pytest.fixture
def fresh_cluster(monkeypatch):
    """Reset the idempotence latch and capture initialize calls."""
    calls = []

    def fake_init(*a, **kw):
        calls.append((a, kw))

    monkeypatch.setattr(cluster, "_initialized", False)
    import jax

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.delenv("MEGASCALE_COORDINATOR_ADDRESS", raising=False)
    yield calls
    monkeypatch.setattr(cluster, "_initialized", False)


def test_single_process_no_init(fresh_cluster):
    cluster.initialize()
    assert fresh_cluster == []


def test_explicit_coordinator(fresh_cluster):
    cluster.initialize(cluster.ClusterConfig(
        coordinator_address="10.0.0.1:1234", num_processes=2, process_id=1,
    ))
    (a, kw), = fresh_cluster
    assert kw["coordinator_address"] == "10.0.0.1:1234"
    assert kw["num_processes"] == 2 and kw["process_id"] == 1


def test_pod_markers_trigger_argless_init(fresh_cluster, monkeypatch):
    """Multi-host TPU pod: TPU_WORKER_HOSTNAMES lists >1 peer → argless
    initialize (metadata autodetection)."""
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0,host-1,host-2,host-3")
    cluster.initialize()
    assert fresh_cluster == [((), {})]


def test_single_host_tpu_vm_no_init(fresh_cluster, monkeypatch):
    """One hostname (single-host TPU VM): no distributed init needed."""
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0")
    cluster.initialize()
    assert fresh_cluster == []


def test_megascale_marker_triggers_init(fresh_cluster, monkeypatch):
    monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "coord:8080")
    cluster.initialize()
    assert fresh_cluster == [((), {})]


def test_auto_detect_never(fresh_cluster, monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0,host-1")
    cluster.initialize(cluster.ClusterConfig(auto_detect="never"))
    assert fresh_cluster == []


def test_cluster_config_plumbed_from_cli(fresh_cluster):
    """--cluster.* and --mesh.dcn_* reach their destinations through the
    workload config tree (round-1 Weak #7: ClusterConfig was unreachable)."""
    from distributed_tensorflow_tpu.utils import config as config_lib
    from distributed_tensorflow_tpu.workloads import mnist_mlp

    cfg = config_lib.apply_overrides(
        mnist_mlp.default_config(),
        ["--cluster.coordinator_address=10.1.1.1:9",
         "--cluster.num_processes=4",
         "--cluster.process_id=0",
         "--mesh.dcn_data=2"],
    )
    assert cfg.cluster.coordinator_address == "10.1.1.1:9"
    assert cfg.mesh.dcn_data == 2 and cfg.mesh.num_slices == 2
    cluster.initialize(cfg.cluster)
    (a, kw), = fresh_cluster
    assert kw["coordinator_address"] == "10.1.1.1:9"
    assert kw["num_processes"] == 4


def test_compilation_cache_config(tmp_path, monkeypatch):
    """ClusterConfig.compilation_cache_dir populates a persistent XLA
    cache: a second jit of the same program writes nothing new."""
    import os

    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.parallel import cluster

    # same env isolation as the fresh_cluster fixture: never let pod
    # markers route this into a real jax.distributed.initialize()
    for var in ("COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
                "MEGASCALE_COORDINATOR_ADDRESS",
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"):
        monkeypatch.delenv(var, raising=False)
    cache = tmp_path / "xla_cache"
    monkeypatch.setattr(cluster, "_initialized", False)
    cluster.initialize(cluster.ClusterConfig(
        auto_detect="never", compilation_cache_dir=str(cache)))
    try:
        jax.jit(lambda x: x * 3 + 1)(jnp.arange(7.0)).block_until_ready()
        entries = set(os.listdir(cache))
        assert entries, "no cache entries written"
        jax.clear_caches()
        jax.jit(lambda x: x * 3 + 1)(jnp.arange(7.0)).block_until_ready()
        assert set(os.listdir(cache)) == entries  # hit, not re-write
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        monkeypatch.setattr(cluster, "_initialized", False)
