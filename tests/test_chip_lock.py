"""Chip-session lock: a concurrent process cannot steal the device lease.

Round-3 post-mortem (PERF_NOTES.md): a builder-side script initialized
the accelerator platform mid-benchmark and cost the round its BERT/GPT
suite. These tests pin the mechanism that makes that impossible for any
process importing the framework (VERDICT r3 item 2).
"""

import os
import subprocess
import sys
import time

import pytest

from distributed_tensorflow_tpu.utils import chip_lock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SESSION_SH = os.path.join(REPO, "tools", "chip_session.sh")


def _spawn_sleeper():
    return subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"])


def _subenv(lock_file, **extra):
    """Env for a child that simulates an ambient (axon-capable) process:
    no JAX_PLATFORMS pin, no session exemption, test lock path."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "DTF_CHIP_SESSION")}
    env["DTF_CHIP_LOCK"] = str(lock_file)
    env.update(extra)
    return env


def _platform_after_import(lock_file, **extra):
    """What backend does a fresh framework-importing process end up
    configured for? (Reads config only — never initializes a backend, so
    the probe can't itself contend for a real lease.)"""
    out = subprocess.run(
        [sys.executable, "-c",
         "import distributed_tensorflow_tpu, jax; "
         "print('PLATFORMS=' + repr(jax.config.jax_platforms))"],
        capture_output=True, text=True, timeout=120,
        env=_subenv(lock_file, **extra), cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    line = [l for l in out.stdout.splitlines() if l.startswith("PLATFORMS=")][-1]
    return line.split("=", 1)[1], out.stderr


def test_live_lock_pins_importer_to_cpu(tmp_path):
    lock = tmp_path / "chip.lock"
    holder = _spawn_sleeper()
    try:
        lock.write_text(str(holder.pid))
        platforms, stderr = _platform_after_import(lock)
        assert platforms == "'cpu'", (platforms, stderr)
        assert "pinning this process to CPU" in stderr
    finally:
        holder.kill()
        holder.wait()


def test_session_children_are_exempt(tmp_path):
    lock = tmp_path / "chip.lock"
    holder = _spawn_sleeper()
    try:
        lock.write_text(str(holder.pid))
        platforms, stderr = _platform_after_import(lock, DTF_CHIP_SESSION="1")
        assert platforms != "'cpu'", (platforms, stderr)
        assert "pinning" not in stderr
    finally:
        holder.kill()
        holder.wait()


def test_stale_lock_ignored_and_cleaned(tmp_path, monkeypatch):
    lock = tmp_path / "chip.lock"
    dead = _spawn_sleeper()
    dead.kill()
    dead.wait()
    lock.write_text(str(dead.pid))
    monkeypatch.setenv("DTF_CHIP_LOCK", str(lock))
    monkeypatch.delenv("DTF_CHIP_SESSION", raising=False)
    assert chip_lock.lock_holder() is None
    assert not lock.exists()  # best-effort cleanup happened


def test_garbage_and_absent_lock(tmp_path, monkeypatch):
    lock = tmp_path / "chip.lock"
    monkeypatch.setenv("DTF_CHIP_LOCK", str(lock))
    monkeypatch.delenv("DTF_CHIP_SESSION", raising=False)
    assert chip_lock.lock_holder() is None  # absent
    lock.write_text("not-a-pid")
    assert chip_lock.lock_holder() is None  # garbage
    assert not chip_lock.pin_cpu_if_locked(log=lambda s: None)


def test_pytest_rig_is_cpu_pinned_regardless():
    # The test conftest pins CPU unconditionally before any backend init;
    # a concurrent `pytest` run can therefore never contend for the lease
    # even without the lock.
    import jax

    assert jax.config.jax_platforms == "cpu"


def test_session_env_file_lifecycle(tmp_path):
    """The session writes a sourceable env file (JAX_PLATFORMS=cpu) for
    ad-hoc shells while it runs, and removes it on exit (VERDICT r4
    item 4 — the bare-`import jax` hole)."""
    lock = tmp_path / "chip.lock"
    out = subprocess.run(
        ["bash", SESSION_SH, "bash", "-c", f'cat "{lock}.env"'],
        env=_subenv(lock), capture_output=True, text=True, timeout=30,
    )
    assert out.returncode == 0, out.stderr
    assert "export JAX_PLATFORMS=cpu" in out.stdout
    assert not (tmp_path / "chip.lock.env").exists()  # removed on exit


@pytest.mark.slow
def test_sourced_env_file_pins_bare_jax(tmp_path):
    """The judge's scenario: while a session is live, a SEPARATE shell
    that follows the protocol (source the env file) gets CPU devices
    from a bare `import jax; jax.devices()`."""
    lock = tmp_path / "chip.lock"
    first = subprocess.Popen(
        ["bash", SESSION_SH, "bash", "-c", "echo started; sleep 30"],
        env=_subenv(lock), stdout=subprocess.PIPE, text=True,
    )
    try:
        assert first.stdout.readline().strip() == "started"
        out = subprocess.run(
            ["bash", "-c",
             f'source "{lock}.env"; '
             f'"{sys.executable}" -c "import jax; print(jax.devices())"'],
            env=_subenv(lock), capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "CpuDevice" in out.stdout, out.stdout
    finally:
        first.kill()
        first.wait()


@pytest.mark.slow
def test_chip_session_sh_mutual_exclusion(tmp_path):
    lock = tmp_path / "chip.lock"
    env = _subenv(lock)
    first = subprocess.Popen(
        ["bash", SESSION_SH, "bash", "-c",
         f"echo started; sleep 20"],
        env=env, stdout=subprocess.PIPE, text=True,
    )
    try:
        assert first.stdout.readline().strip() == "started"
        # lock file now records the wrapper pid and the holder is live
        deadline = time.time() + 5
        while time.time() < deadline and not lock.exists():
            time.sleep(0.05)
        held = int(lock.read_text().strip())
        os.kill(held, 0)  # raises if not live

        second = subprocess.run(
            ["bash", SESSION_SH, "true"], env=env,
            capture_output=True, text=True, timeout=30,
        )
        assert second.returncode == 97, (second.returncode, second.stderr)
        assert "already holds" in second.stderr

        # and a framework import during the session is CPU-pinned
        platforms, stderr = _platform_after_import(lock)
        assert platforms == "'cpu'", (platforms, stderr)
    finally:
        first.kill()
        first.wait()


def test_pin_stamp_records_pid_and_timestamp(tmp_path, monkeypatch):
    """pin_cpu_if_locked must stamp WHO decided and WHEN alongside
    DTF_CHIP_PINNED, so descendants can bound the stamp's validity
    (ADVICE r5 — the env var itself is inherited forever)."""
    lock = tmp_path / "chip.lock"
    holder = _spawn_sleeper()
    try:
        lock.write_text(str(holder.pid))
        monkeypatch.setenv("DTF_CHIP_LOCK", str(lock))
        monkeypatch.delenv("DTF_CHIP_SESSION", raising=False)
        for var in ("DTF_CHIP_PINNED", "DTF_CHIP_PINNED_PID",
                    "DTF_CHIP_PINNED_AT"):
            monkeypatch.delenv(var, raising=False)
        before = time.time()
        assert chip_lock.pin_cpu_if_locked(log=lambda s: None)
        assert os.environ["DTF_CHIP_PINNED"] == "1"
        assert os.environ["DTF_CHIP_PINNED_PID"] == str(os.getpid())
        assert before <= float(os.environ["DTF_CHIP_PINNED_AT"]) <= time.time()
        assert chip_lock.pin_is_current()  # we pinned ourselves
    finally:
        holder.kill()
        holder.wait()
        # this test mutates the global jax platform pin; restore the rig
        import jax

        jax.config.update("jax_platforms", "cpu")


def test_pin_is_current_bounds_inherited_stamps(monkeypatch):
    """An ancestor's pin stamp is believed only while fresh: a bench
    child spawned after the session ended must not inherit the
    chip_session_live claim indefinitely."""
    monkeypatch.delenv("DTF_CHIP_PINNED", raising=False)
    assert not chip_lock.pin_is_current()  # never pinned

    monkeypatch.setenv("DTF_CHIP_PINNED", "1")
    monkeypatch.setenv("DTF_CHIP_PINNED_PID", str(os.getpid()))
    monkeypatch.delenv("DTF_CHIP_PINNED_AT", raising=False)
    assert chip_lock.pin_is_current()  # own-pid stamp: always current

    other_pid = str(os.getpid() + 1)
    monkeypatch.setenv("DTF_CHIP_PINNED_PID", other_pid)
    monkeypatch.setenv("DTF_CHIP_PINNED_AT", repr(time.time()))
    assert chip_lock.pin_is_current()  # fresh ancestor stamp

    monkeypatch.setenv(
        "DTF_CHIP_PINNED_AT",
        repr(time.time() - chip_lock.PIN_MAX_AGE_S - 60),
    )
    assert not chip_lock.pin_is_current()  # stale ancestor stamp

    monkeypatch.setenv("DTF_CHIP_PINNED_AT",
                       repr(time.time() + 7200))  # clock skew: future
    assert not chip_lock.pin_is_current()

    # legacy stamp (no timestamp) from another process: treated stale
    monkeypatch.delenv("DTF_CHIP_PINNED_AT", raising=False)
    assert not chip_lock.pin_is_current()
    monkeypatch.setenv("DTF_CHIP_PINNED_AT", "yesterday-ish")
    assert not chip_lock.pin_is_current()


def test_unheld_flock_sidecar_means_stale(tmp_path, monkeypatch):
    # SIGKILL'd session (or pid recycled to an unrelated live process):
    # the flock sidecar exists but nobody holds the kernel lock, so the
    # pid file must read as stale even though the recorded pid is alive.
    lock = tmp_path / "chip.lock"
    holder = _spawn_sleeper()  # live pid, but does NOT hold the flock
    try:
        lock.write_text(str(holder.pid))
        (tmp_path / "chip.lock.flock").touch()
        monkeypatch.setenv("DTF_CHIP_LOCK", str(lock))
        monkeypatch.delenv("DTF_CHIP_SESSION", raising=False)
        assert chip_lock.lock_holder() is None
        assert not lock.exists()  # leftover pid file cleaned
        # orphaned sidecar cleaned too (ADVICE r4): a later hand-written
        # pid file must not be judged by a dead session's flock forever
        assert not (tmp_path / "chip.lock.flock").exists()
    finally:
        holder.kill()
        holder.wait()
