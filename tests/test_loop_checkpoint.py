"""Trainer loop + callbacks + checkpoint/resume tests (SURVEY.md §4.3/§5.4:
save, kill, resume must reproduce the uninterrupted run)."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu.train import (
    CheckpointConfig,
    Checkpointer,
    OptimizerConfig,
    Trainer,
    init_or_restore,
    init_train_state,
    make_optimizer,
    make_train_step,
    callbacks as cb,
)

from test_step import linear_init, linear_loss, make_batch


def batches(n, size=16):
    for i in range(n):
        yield make_batch(size, seed=i)


def build_trainer(mesh, tx=None, callbacks=(), state=None, specs=None):
    tx = tx or optax.sgd(0.1)
    if state is None:
        state, specs = init_train_state(
            linear_init, tx, mesh, jax.random.PRNGKey(0)
        )
    step = make_train_step(linear_loss, tx)
    return Trainer(step, state, mesh, specs, callbacks=callbacks)


def test_fit_runs_and_stops_at_num_steps(mesh8):
    trainer = build_trainer(mesh8)
    state = trainer.fit(batches(100), num_steps=5)
    assert int(state.step) == 5


def test_stop_at_step_callback(mesh8):
    trainer = build_trainer(mesh8, callbacks=[cb.StopAtStep(3)])
    state = trainer.fit(batches(100))
    assert int(state.step) == 3


def test_metrics_logger(mesh8, caplog):
    logger_cb = cb.MetricsLogger(every_n=2, batch_size=16, history=True)
    trainer = build_trainer(mesh8, callbacks=[logger_cb, cb.StopAtStep(6)])
    with caplog.at_level(logging.INFO):
        trainer.fit(batches(100))
    assert logger_cb.history, "logger recorded nothing"
    assert "loss" in logger_cb.history[-1]
    assert "steps_per_sec" in logger_cb.history[-1]


def test_nan_guard_raises(mesh8):
    def nan_loss(params, model_state, batch, rng):
        loss = jnp.sum(params["w"]) * jnp.nan
        return loss, (model_state, {})

    tx = optax.sgd(0.1)
    state, specs = init_train_state(linear_init, tx, mesh8, jax.random.PRNGKey(0))
    trainer = Trainer(
        make_train_step(nan_loss, tx), state, mesh8, specs,
        callbacks=[cb.NaNGuard(every_n=1)],
    )
    with pytest.raises(FloatingPointError):
        trainer.fit(batches(10), num_steps=5)


@pytest.mark.slow
def test_optimizer_zoo_smoke(mesh8):
    for name in ["sgd", "momentum", "adam", "adamw", "adagrad", "rmsprop",
                 "lamb", "ftrl", "adafactor"]:
        tx = make_optimizer(OptimizerConfig(name=name, learning_rate=1e-2))
        trainer = build_trainer(mesh8, tx=tx)
        state = trainer.fit(batches(3), num_steps=2)
        assert int(state.step) == 2, name


def test_schedules_smoke():
    from distributed_tensorflow_tpu.train import make_schedule

    for sched in ["constant", "cosine", "warmup_cosine", "exponential", "linear"]:
        fn = make_schedule(OptimizerConfig(
            schedule=sched, learning_rate=0.1, warmup_steps=5, total_steps=50
        ))
        vals = [float(fn(i)) for i in [0, 10, 49]]
        assert all(np.isfinite(vals)), sched


def test_schedule_shapes_analytic():
    """Analytic checkpoints of the LR curves, not just finiteness:
    warmup ramps linearly 0 -> peak, cosine lands on end_lr_factor at
    total_steps, linear interpolates exactly halfway at midpoint."""
    from distributed_tensorflow_tpu.train import make_schedule

    lr, W, T = 0.1, 10, 110
    cos = make_schedule(OptimizerConfig(
        schedule="cosine", learning_rate=lr, warmup_steps=W, total_steps=T,
        end_lr_factor=0.01))
    # linear warmup: exact fractions of peak
    for i in (0, 5, 10):
        np.testing.assert_allclose(float(cos(i)), lr * i / W, rtol=1e-6)
    # peak right after warmup (f32 schedule arithmetic), floor at the end
    np.testing.assert_allclose(float(cos(W)), lr, rtol=1e-6)
    np.testing.assert_allclose(float(cos(T)), lr * 0.01, rtol=1e-5)
    # cosine midpoint: halfway between peak and floor
    np.testing.assert_allclose(
        float(cos(W + (T - W) // 2)), lr * (1 + 0.01) / 2, rtol=1e-5)
    # monotone decay after warmup
    pts = [float(cos(i)) for i in range(W, T, 10)]
    assert all(a >= b for a, b in zip(pts, pts[1:])), pts

    lin = make_schedule(OptimizerConfig(
        schedule="linear", learning_rate=lr, warmup_steps=0, total_steps=100,
        end_lr_factor=0.0))
    np.testing.assert_allclose(float(lin(50)), lr / 2, rtol=1e-6)
    np.testing.assert_allclose(float(lin(100)), 0.0, atol=1e-9)


def test_checkpoint_save_restore_resume(mesh8, tmp_path):
    """The §5.4 oracle: train 6 steps straight == train 3, 'crash', resume 3."""
    tx = optax.adam(1e-2)

    # straight run, 6 steps
    state, specs = init_train_state(linear_init, tx, mesh8, jax.random.PRNGKey(0))
    trainer = Trainer(make_train_step(linear_loss, tx), state, mesh8, specs)
    straight = trainer.fit(batches(6), num_steps=6)

    # interrupted run: 3 steps, save, fresh process simulation, resume 3
    ckpt_dir = str(tmp_path / "ckpt")
    ckpt = Checkpointer(
        CheckpointConfig(directory=ckpt_dir, save_interval_steps=1,
                         async_save=False, save_on_preemption=False),
        mesh8,
    )
    state, specs, restored = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert not restored
    trainer = Trainer(
        make_train_step(linear_loss, tx), state, mesh8, specs,
        callbacks=[cb.CheckpointCallback(ckpt)],
    )
    trainer.fit(batches(3), num_steps=3)
    ckpt.wait()
    assert ckpt.latest_step() == 3
    ckpt.close()

    ckpt2 = Checkpointer(
        CheckpointConfig(directory=ckpt_dir, save_interval_steps=1,
                         async_save=False, save_on_preemption=False),
        mesh8,
    )
    state2, specs2, restored2 = init_or_restore(
        ckpt2, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert restored2
    assert int(state2.step) == 3
    trainer2 = Trainer(make_train_step(linear_loss, tx), state2, mesh8, specs2)
    # feed the same batches 4..6 the straight run saw
    resumed = trainer2.fit(
        (make_batch(16, seed=i) for i in range(3, 6)), num_steps=6
    )
    assert int(resumed.step) == 6
    for a, b in zip(jax.tree.leaves(straight.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)
    ckpt2.close()


def test_failed_run_never_checkpoints_poisoned_state(mesh8, tmp_path):
    """NaN abort must not overwrite the latest checkpoint with bad state."""
    def nan_loss(params, model_state, batch, rng):
        return jnp.sum(params["w"]) * jnp.nan, (model_state, {})

    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "nan"), save_interval_steps=100,
                         async_save=False, save_on_preemption=False),
        mesh8,
    )
    state, specs, _ = init_or_restore(ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    trainer = Trainer(
        make_train_step(nan_loss, tx), state, mesh8, specs,
        callbacks=[cb.NaNGuard(every_n=1), cb.CheckpointCallback(ckpt)],
    )
    with pytest.raises(FloatingPointError):
        trainer.fit(batches(10), num_steps=5)
    assert trainer.failed
    assert ckpt.latest_step() is None  # nothing poisoned was written
    ckpt.close()


def test_save_refuses_nonfinite_params(mesh8, tmp_path):
    """validate_before_save: a direct save() of NaN params is refused — the
    guard that holds even when debug metrics (grads_finite) are off and the
    loss hasn't gone non-finite yet."""
    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "v"), async_save=False,
                         save_on_preemption=False),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    poisoned = state.replace(
        params=jax.tree.map(lambda p: p * jnp.nan, state.params)
    )
    assert ckpt.save(0, poisoned, force=True) is False
    assert ckpt.latest_step() is None
    # and a clean state still saves
    assert ckpt.save(0, state, force=True) is True
    assert ckpt.latest_step() == 0
    ckpt.close()


def test_preemption_with_poisoned_state_fails_not_saves(mesh8, tmp_path):
    """A preemption save refused by validate_before_save must raise
    FloatingPointError (run exits FAILED), not PreemptionSaved — the latter
    would tell the scheduler a checkpoint exists when nothing was written."""
    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "p"), async_save=False,
                         save_on_preemption=True),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    poisoned = state.replace(
        params=jax.tree.map(lambda p: p * jnp.nan, state.params)
    )
    ckpt.watcher._event.set()  # simulate SIGTERM observed
    with pytest.raises(FloatingPointError, match="non-finite"):
        ckpt.maybe_save(3, poisoned)
    assert ckpt.latest_step() is None
    # healthy state at preemption still takes the clean-exit path
    from distributed_tensorflow_tpu.train.checkpoint import PreemptionSaved
    with pytest.raises(PreemptionSaved):
        ckpt.maybe_save(3, state)
    assert ckpt.latest_step() == 3
    ckpt.close()


def test_preemption_poisoned_with_earlier_checkpoint_still_fails(mesh8, tmp_path):
    """The maybe_save refusal branch with an EARLIER checkpoint on disk:
    latest < step still means the preemption save wrote nothing for this
    step, so the run must exit FAILED (FloatingPointError naming the
    stale latest) — and the earlier healthy checkpoint must survive."""
    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "pe"), async_save=False,
                         save_on_preemption=True),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert ckpt.save(2, state, force=True)  # healthy save at step 2
    poisoned = state.replace(
        params=jax.tree.map(lambda p: p * jnp.nan, state.params)
    )
    ckpt.watcher._event.set()
    with pytest.raises(FloatingPointError, match="latest on disk: 2"):
        ckpt.maybe_save(5, poisoned)
    assert ckpt.latest_step() == 2  # the stale-but-healthy save is intact
    ckpt.close()


def test_preemption_poisoned_but_step_already_saved_is_clean(mesh8, tmp_path):
    """If the preempted step is ALREADY covered on disk (save() dedups,
    latest == step), the refusal of the poisoned in-memory state doesn't
    matter — the PreemptionSaved contract holds and the run exits
    cleanly, resuming from the healthy copy of the same step."""
    from distributed_tensorflow_tpu.train.checkpoint import PreemptionSaved

    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "pc"), async_save=False,
                         save_on_preemption=True),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert ckpt.save(5, state, force=True)  # step 5 is on disk, healthy
    poisoned = state.replace(
        params=jax.tree.map(lambda p: p * jnp.nan, state.params)
    )
    ckpt.watcher._event.set()
    with pytest.raises(PreemptionSaved):
        ckpt.maybe_save(5, poisoned)
    assert ckpt.latest_step() == 5
    ckpt.close()


def test_emergency_checkpoint_on_callback_exception(mesh8, tmp_path):
    """An exception out of ANY callback aborts fit() — but the Trainer's
    emergency save keeps the last completed step (crash-safe exits,
    docs/resilience.md). Discovery is implicit: wiring a
    CheckpointCallback is enough, no extra argument."""
    class Boom(cb.Callback):
        def on_step_end(self, trainer, step, metrics):
            if step == 3:
                raise RuntimeError("callback exploded")

    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "em"),
                         save_interval_steps=10**6, async_save=False,
                         save_on_preemption=False),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    trainer = Trainer(
        make_train_step(linear_loss, tx), state, mesh8, specs,
        callbacks=[cb.CheckpointCallback(ckpt), Boom()],
    )
    assert trainer.emergency_checkpoint is ckpt
    with pytest.raises(RuntimeError, match="callback exploded"):
        trainer.fit(batches(10), num_steps=10)
    assert trainer.failed
    assert ckpt.latest_step() == 3  # the emergency save
    ckpt.close()


def test_no_emergency_checkpoint_without_checkpointer(mesh8):
    """No CheckpointCallback and no explicit emergency_checkpoint: the
    failure path must still re-raise cleanly (no AttributeError from the
    best-effort save)."""
    tx = optax.sgd(0.1)
    state, specs = init_train_state(linear_init, tx, mesh8, jax.random.PRNGKey(0))
    trainer = Trainer(make_train_step(linear_loss, tx), state, mesh8, specs)
    assert trainer.emergency_checkpoint is None
    with pytest.raises(IOError):
        def dies():
            yield make_batch(16, seed=0)
            raise IOError("dead feed")
        trainer.fit(dies(), num_steps=10)
    assert trainer.failed


def test_optimizer_clip_grad_norm_wired(mesh8):
    """clip_grad_norm on OptimizerConfig must actually clip."""
    big = make_batch(16)
    big["y"] = big["y"] * 1e6  # huge grads
    tx = make_optimizer(OptimizerConfig(name="sgd", learning_rate=1.0,
                                        clip_grad_norm=1e-3))
    state, specs = init_train_state(linear_init, tx, mesh8, jax.random.PRNGKey(0))
    trainer = Trainer(make_train_step(linear_loss, tx), state, mesh8, specs)
    before = np.asarray(jax.tree.leaves(state.params)[0]).copy()
    state2 = trainer.fit([big], num_steps=1)
    after = np.asarray(jax.tree.leaves(state2.params)[0])
    # update magnitude bounded by lr * clip_norm
    assert np.abs(after - before).max() <= 1e-3 + 1e-6


@pytest.mark.parametrize("name", ["sgd", "momentum", "adam", "rmsprop",
                                  "adagrad"])
def test_weight_decay_honored_everywhere(name):
    """OptimizerConfig.weight_decay must shrink kernels (ndim>1) for every
    non-decoupled optimizer, not silently no-op."""
    tx = make_optimizer(OptimizerConfig(name=name, learning_rate=0.1,
                                        weight_decay=0.5))
    params = {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))}
    grads = jax.tree.map(jnp.zeros_like, params)
    updates, _ = tx.update(grads, tx.init(params), params)
    assert float(jnp.max(updates["kernel"])) < 0  # decay pulls down
    assert float(jnp.abs(updates["bias"]).max()) < 1e-5  # biases exempt


def test_ftrl_l1_applies():
    tx = make_optimizer(OptimizerConfig(name="ftrl", learning_rate=0.1, l1=0.5))
    params = {"w": jnp.ones((4,))}
    grads = {"w": jnp.zeros((4,))}
    opt_state = tx.init(params)
    updates, _ = tx.update(grads, opt_state, params)
    # zero grads + positive weights + l1 → negative (shrinking) update
    assert float(jnp.max(updates["w"])) < 0


def test_preemption_saved_is_clean_stop(mesh8, tmp_path):
    """PreemptionSaved must stop the loop cleanly (failed=False) with the
    state on disk — the restart-and-resume contract (SURVEY.md §5.3)."""
    from distributed_tensorflow_tpu.train.checkpoint import PreemptionSaved

    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "pre"), save_interval_steps=100,
                         async_save=False, save_on_preemption=False),
        mesh8,
    )
    state, specs, _ = init_or_restore(ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))

    class FakePreempt(cb.Callback):
        def on_step_end(self, trainer, step, metrics):
            if step == 2:
                ckpt.save(step, trainer.state, force=True)
                ckpt.wait()
                raise PreemptionSaved(step)

    trainer = Trainer(
        make_train_step(linear_loss, tx), state, mesh8, specs,
        callbacks=[FakePreempt(), cb.CheckpointCallback(ckpt)],
    )
    final = trainer.fit(batches(10), num_steps=10)  # must not raise
    assert not trainer.failed
    assert int(final.step) == 2
    assert ckpt.latest_step() == 2
    ckpt.close()


def test_restore_none_when_empty(mesh8, tmp_path):
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "empty"), async_save=False),
        mesh8,
    )
    assert ckpt.latest_step() is None
    ckpt.close()


def test_manifest_written_and_verified(mesh8, tmp_path):
    """Production saves stamp each step dir with a CRC-trailered
    MANIFEST.dtf via the native IO path (VERDICT round-1 item 8), and
    restore refuses a checkpoint whose shards don't match it."""
    import os

    from distributed_tensorflow_tpu.runtime import io as io_lib

    tx = optax.sgd(0.1)
    ckdir = tmp_path / "m"
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(ckdir), async_save=False,
                         save_on_preemption=False),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert ckpt.save(0, state, force=True)
    man = ckdir / "0" / "MANIFEST.dtf"
    assert man.exists()
    payload = io_lib.read_payload(str(man))  # CRC round-trips
    import json as json_lib

    manifest = json_lib.loads(payload)
    assert manifest["step"] == 0 and manifest["files"]
    assert ckpt.verify_manifest(0) is True

    # restore succeeds with intact shards
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
    )
    assert ckpt.restore(abstract, step=0) is not None

    # truncate a listed shard -> restore refuses
    biggest = max(manifest["files"], key=lambda e: e["bytes"])
    victim = ckdir / "0" / biggest["path"]
    victim.write_bytes(victim.read_bytes()[:-1])
    with pytest.raises(OSError, match="manifest says|missing shard"):
        ckpt.restore(abstract, step=0)
    ckpt.close()


def test_manifest_async_save(mesh8, tmp_path):
    """Async saves stamp the manifest after the background commit."""
    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "a"), async_save=True,
                         save_on_preemption=False),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert ckpt.save(0, state, force=True)
    ckpt.wait()
    assert (tmp_path / "a" / "0" / "MANIFEST.dtf").exists()
    assert ckpt.verify_manifest(0) is True
    ckpt.close()


def test_close_joins_last_manifest_stamper(mesh8, tmp_path):
    """Regression (ISSUE 12 satellite): saves only PRUNE dead entries
    from _manifest_threads, so the LAST save's async stamper has nobody
    behind it — close() (via wait()) must join it, or the final
    checkpoint silently lacks MANIFEST.dtf."""
    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "j"), async_save=True,
                         save_on_preemption=False),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert ckpt.save(0, state, force=True)
    # close() WITHOUT an explicit wait(): the stamper must still be
    # drained and the manifest on disk
    ckpt.close()
    assert ckpt._manifest_threads == []
    assert (tmp_path / "j" / "0" / "MANIFEST.dtf").exists()
    assert ckpt.verify_manifest(0) is True


class _FakeHeartbeatWriter:
    """The HeartbeatWriter duck-type Checkpointer(heartbeat=) consumes:
    ``beat`` + ``phase``, with every beat recorded for assertions."""

    def __init__(self):
        self._phase = "train"
        self.beats = []

    @property
    def phase(self):
        return self._phase

    def beat(self, step=None, attempt=None, phase=None):
        if phase is not None:
            self._phase = phase
        self.beats.append((step, phase))


def test_save_brackets_fleet_heartbeat_phase(mesh8, tmp_path):
    """With a fleet heartbeat wired, every save beats phase ``save`` for
    the write's duration and then restores the previous phase — the
    signal the elastic fleet reads to gang-stop (not shrink) around a
    death that landed mid-checkpoint."""
    w = _FakeHeartbeatWriter()
    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "hb"), async_save=False,
                         save_on_preemption=False),
        mesh8, heartbeat=w,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert ckpt.save(0, state, force=True)
    phases = [p for _, p in w.beats if p is not None]
    assert phases == ["save", "train"]  # bracketed, previous restored
    assert w.phase == "train"
    # a refused/duplicate save never beats (no write happened)
    n = len(w.beats)
    assert not ckpt.save(0, state, force=True)
    assert len(w.beats) == n
    ckpt.close()


def test_async_save_holds_save_phase_until_commit(mesh8, tmp_path):
    """With async_save the heavy shard writes happen on orbax's threads
    AFTER save() returns — the heartbeat must keep phase ``save`` for
    that whole window (a death during the background writes can tear
    the step dir, and the elastic fleet reads the phase to gang-stop
    instead of shrinking around it), restoring the previous phase only
    once the commit lands."""
    import time

    w = _FakeHeartbeatWriter()
    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "ah"), async_save=True,
                         save_on_preemption=False),
        mesh8, heartbeat=w,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert ckpt.save(0, state, force=True)
    ckpt.wait()  # commit landed; the phase-restore thread races us only
    deadline = time.monotonic() + 10.0
    while w.phase != "train" and time.monotonic() < deadline:
        time.sleep(0.01)
    phases = [p for _, p in w.beats if p is not None]
    assert phases == ["save", "train"], phases
    ckpt.close()


def test_stale_phase_restore_cannot_clear_a_newer_save_window(mesh8,
                                                              tmp_path):
    """Back-to-back async saves: the FIRST save's phase-restore thread
    waking after a NEWER save began must not beat the phase back to
    'train' while the newer save's shard writes are in flight — the
    save-sequence guard drops the stale restore."""
    tx = optax.sgd(0.1)
    w = _FakeHeartbeatWriter()
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "sq"), async_save=False,
                         save_on_preemption=False),
        mesh8, heartbeat=w,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    assert ckpt.save(0, state, force=True)  # seq 1, bracketed normally
    # simulate a newer save owning the window while save 1's restore
    # thread wakes late
    with ckpt._hb_lock:
        ckpt._hb_save_seq += 1  # "save 2" started
    w.beat(phase="save")        # ...and beat its save window
    ckpt._restore_phase("train", seq=1)  # save 1's stale restore
    assert w.phase == "save"    # the newer window survives
    # and a restore landing while something ELSE owns the phase (a
    # resize barrier) must never clobber it either
    w.beat(phase="barrier")
    ckpt._restore_phase("train", seq=ckpt._hb_save_seq)
    assert w.phase == "barrier"
    w.beat(phase="save")
    ckpt._restore_phase("train", seq=ckpt._hb_save_seq)  # the owner's
    assert w.phase == "train"
    ckpt.close()


def test_wait_bounds_straggler_join_and_logs_step(mesh8, tmp_path, caplog):
    """A stamper that outlives the bounded join must not hang wait()
    forever: it is logged BY STEP (naming the checkpoint that may lack
    its manifest) and retained so a later wait() retries the join."""
    import logging
    import threading

    tx = optax.sgd(0.1)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "s"), async_save=False,
                         save_on_preemption=False),
        mesh8,
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0)
    )
    release = threading.Event()
    t = threading.Thread(target=release.wait, daemon=True)
    t.start()
    ckpt._manifest_threads = [(7, t)]
    with caplog.at_level(logging.ERROR,
                         logger="distributed_tensorflow_tpu.train.checkpoint"):
        ckpt.wait(manifest_join_s=0.05)  # bounded: returns, never hangs
    assert "manifest thread for step 7" in caplog.text
    assert [s for s, _ in ckpt._manifest_threads] == [7]  # retained
    release.set()
    ckpt.wait(manifest_join_s=5.0)  # the retry drains it
    assert ckpt._manifest_threads == []
    ckpt.close()


def test_ftrl_matches_tf_reference():
    """Exact-FTRL parity oracle: our optax ftrl() tracks
    tf.compat.v1.train.FtrlOptimizer ($TF/python/training/ftrl.py) step
    for step on the same gradient sequence, including L1 sparsification
    and L2 shrinkage."""
    import optax
    tf = pytest.importorskip("tensorflow")

    from distributed_tensorflow_tpu.train.optimizers import ftrl

    rng = np.random.RandomState(0)
    w0 = rng.randn(12).astype(np.float32)
    grads = [rng.randn(12).astype(np.float32) * 0.5 for _ in range(6)]
    lr, l1, l2 = 0.1, 0.5, 0.02

    # TF reference trajectory
    var = tf.Variable(w0)
    opt = tf.compat.v1.train.FtrlOptimizer(
        learning_rate=lr, learning_rate_power=-0.5,
        l1_regularization_strength=l1, l2_regularization_strength=l2,
    )
    tf_traj = []
    for g in grads:
        opt.apply_gradients([(tf.constant(g), var)])
        tf_traj.append(var.numpy().copy())

    # ours
    tx = ftrl(lr, lr_power=-0.5, l1=l1, l2=l2)
    params = jnp.asarray(w0)
    state = tx.init(params)
    for g, want in zip(grads, tf_traj):
        upd, state = tx.update(jnp.asarray(g), state, params)
        params = optax.apply_updates(params, upd)
        np.testing.assert_allclose(np.asarray(params), want,
                                   rtol=1e-5, atol=1e-6)
    # L1 actually sparsifies
    assert (np.asarray(params) == 0).sum() > 0


def test_ftrl_warmup_and_bf16_and_tuple_trees():
    """Regressions: lr=0 warmup step is a no-op (not NaN); accumulator
    dtypes are stable f32 for bf16 params; tuple-containing pytrees work."""
    import optax

    from distributed_tensorflow_tpu.train.optimizers import ftrl

    # warmup: step 0 has lr=0
    sched = optax.linear_schedule(0.0, 0.1, 3)
    tx = ftrl(sched)
    params = jnp.ones((4,), jnp.bfloat16)
    state = tx.init(params)
    assert state["z"].dtype == jnp.float32
    assert state["n"].dtype == jnp.float32
    upd, state = tx.update(jnp.ones((4,), jnp.bfloat16), state, params)
    assert np.all(np.asarray(upd, np.float32) == 0), "lr=0 must be a no-op"
    assert state["z"].dtype == jnp.float32  # unchanged across steps
    params = optax.apply_updates(params, upd)
    for _ in range(3):
        upd, state = tx.update(jnp.ones((4,), jnp.bfloat16), state, params)
        params = optax.apply_updates(params, upd)
    assert np.all(np.isfinite(np.asarray(params, np.float32)))

    # tuple-structured param tree
    tx2 = ftrl(0.1)
    pt = ({"w": jnp.ones((2,))}, {"b": jnp.zeros((3,))})
    st = tx2.init(pt)
    g = ({"w": jnp.ones((2,))}, {"b": jnp.ones((3,))})
    upd, st = tx2.update(g, st, pt)
    assert upd[0]["w"].shape == (2,) and upd[1]["b"].shape == (3,)


def test_multi_optimizer_path_rules():
    """make_multi_optimizer routes params to per-group transforms by path
    regex (first match wins) and falls through to the default."""
    import optax

    from distributed_tensorflow_tpu.train import make_multi_optimizer

    tx = make_multi_optimizer(
        rules=((r"(^|/)wide_", OptimizerConfig(name="sgd", learning_rate=1.0)),),
        default=OptimizerConfig(name="sgd", learning_rate=0.1),
    )
    params = {"wide_dense": jnp.ones((3,)), "deep_0": jnp.ones((3,))}
    state = tx.init(params)
    grads = {"wide_dense": jnp.ones((3,)), "deep_0": jnp.ones((3,))}
    upd, _ = tx.update(grads, state, params)
    # wide gets lr 1.0, deep gets lr 0.1
    np.testing.assert_allclose(np.asarray(upd["wide_dense"]), -np.ones(3),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(upd["deep_0"]), -0.1 * np.ones(3),
                               rtol=1e-6)
