"""Sequence-parallel attention == unsharded oracle, on the 8-fake-device
rig (SURVEY.md §4.2/§4.4): ring, ulysses, allgather × causal × masked,
plus gradient flow through the ring (ppermute AD transpose)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops import attention_reference
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.parallel.ring_attention import (
    sequence_parallel_attention,
)


@pytest.fixture()
def mesh_seq4(devices):
    # data=2 × seq=4: batch and sequence sharding compose
    return build_mesh(MeshSpec(data=2, seq=4), devices[:8])


def make_qkv(key, B=2, H=4, S=128, D=32, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(
        jax.random.normal(k, (B, H, S, D), dtype) for k in ks
    )


@pytest.mark.parametrize("impl", ["ring", "ulysses", "allgather"])
@pytest.mark.parametrize("causal", [False, True])
def test_sp_attention_matches_oracle(mesh_seq4, impl, causal):
    q, k, v = make_qkv(jax.random.PRNGKey(0))
    lens = np.array([128, 77])
    kv_mask = jnp.asarray(np.arange(128)[None, :] < lens[:, None])
    ref = attention_reference(q, k, v, causal=causal, kv_mask=kv_mask)
    out = jax.jit(
        lambda q, k, v: sequence_parallel_attention(
            q, k, v, mesh_seq4, impl=impl, causal=causal, kv_mask=kv_mask
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses", "allgather"])
def test_sp_attention_no_mask(mesh_seq4, impl):
    q, k, v = make_qkv(jax.random.PRNGKey(1), B=2, H=4, S=64)
    ref = attention_reference(q, k, v)
    out = sequence_parallel_attention(q, k, v, mesh_seq4, impl=impl)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_gradients_match_oracle(mesh_seq4):
    q, k, v = make_qkv(jax.random.PRNGKey(2), B=2, H=2, S=64, D=16)

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        out = sequence_parallel_attention(
            q, k, v, mesh_seq4, impl="ring", causal=True
        )
        return (out ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)


@pytest.mark.slow
def test_seq_axis_1_degenerates(devices):
    mesh = build_mesh(MeshSpec(data=8), devices[:8])
    q, k, v = make_qkv(jax.random.PRNGKey(3), B=8, S=32)
    ref = attention_reference(q, k, v, causal=True)
    for impl in ("ring", "ulysses", "allgather"):
        out = sequence_parallel_attention(q, k, v, mesh, impl=impl, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_sp_rejects_indivisible(mesh_seq4):
    q, k, v = make_qkv(jax.random.PRNGKey(4), S=90)
    with pytest.raises(ValueError, match="divisible"):
        sequence_parallel_attention(q, k, v, mesh_seq4)
    q, k, v = make_qkv(jax.random.PRNGKey(5), H=3, S=128)
    with pytest.raises(ValueError, match="ulysses"):
        sequence_parallel_attention(q, k, v, mesh_seq4, impl="ulysses")


def test_ulysses_guard_accounts_for_model_sharding(devices):
    # heads are sharded over model too: H=4 on model=2 leaves 2 local
    # heads, not divisible by seq=4 — must be caught at validation, not
    # inside shard_map (regression: guard used the global head count)
    mesh = build_mesh(MeshSpec(data=1, seq=4, model=2), devices[:8])
    q, k, v = make_qkv(jax.random.PRNGKey(6), B=2, H=4, S=128)
    with pytest.raises(ValueError, match="local heads"):
        sequence_parallel_attention(q, k, v, mesh, impl="ulysses")
