"""Sharded-embedding lookup vs dense-take oracle (SURVEY.md §4.4 pattern:
k-shard result == unsharded result on the same data)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from distributed_tensorflow_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.ops import embedding as emb
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.parallel import mesh as mesh_lib

V, D = 96, 16


@pytest.fixture()
def mesh_tp4(devices):
    return build_mesh(MeshSpec(data=2, model=4), devices[:8])


def _table_and_ids(seed=0, n_ids=32):
    rng = np.random.RandomState(seed)
    table = jnp.asarray(rng.randn(V, D).astype(np.float32))
    ids = jnp.asarray(rng.randint(0, V, size=n_ids).astype(np.int32))
    return table, ids


def test_mod_sharded_lookup_matches_take(mesh_tp4):
    table, ids = _table_and_ids()
    fn = emb.make_sharded_lookup(mesh_tp4)
    got = fn(ids, emb.to_mod_sharded(table, mesh_tp4))
    np.testing.assert_allclose(got, jnp.take(table, ids, axis=0), rtol=1e-6)


def test_mod_sharded_lookup_grad_matches_take(mesh_tp4):
    table, ids = _table_and_ids(1)
    mod = emb.to_mod_sharded(table, mesh_tp4)
    fn = emb.make_sharded_lookup(mesh_tp4)

    g_sharded = jax.grad(lambda t: fn(ids, t).sum())(mod)
    g_dense = jax.grad(lambda t: jnp.take(t, ids, axis=0).sum())(table)
    # map the mod-sharded grad back to vocab order and compare
    n = mesh_tp4.shape[mesh_lib.MODEL]
    rows = emb.shard_vocab(V, n)
    back = np.zeros((V, D), np.float32)
    g_np = np.asarray(g_sharded)
    for s in range(n):
        for r in range(rows):
            gid = s + n * r
            if gid < V:
                back[gid] = g_np[s * rows + r]
    np.testing.assert_allclose(back, g_dense, rtol=1e-6)


def test_range_sharded_lookup_matches_take(mesh_tp4):
    table, ids = _table_and_ids(2)
    got = shard_map(
        lambda i, t: emb.range_sharded_lookup(i, t, mesh_lib.MODEL),
        mesh=mesh_tp4,
        in_specs=(P(mesh_lib.BATCH_AXES), P(mesh_lib.MODEL, None)),
        out_specs=P(mesh_lib.BATCH_AXES, None),
        check_vma=False,
    )(ids, table)
    np.testing.assert_allclose(got, jnp.take(table, ids, axis=0), rtol=1e-6)


def test_batch_sharded_lookup_matches_take(mesh_tp4):
    # batch sharded over the SAME axis as the table (all_to_all-style path)
    table, ids = _table_and_ids(3, n_ids=32)
    mod = emb.to_mod_sharded(table, mesh_tp4)
    got = shard_map(
        lambda i, t: emb.batch_sharded_lookup(i, t, mesh_lib.MODEL),
        mesh=mesh_tp4,
        in_specs=(P(mesh_lib.MODEL), P(mesh_lib.MODEL, None)),
        out_specs=P(mesh_lib.MODEL, None),
        check_vma=False,
    )(ids, mod)
    np.testing.assert_allclose(got, jnp.take(table, ids, axis=0), rtol=1e-6)


def test_single_axis_degrades_to_take(devices):
    mesh1 = build_mesh(MeshSpec(data=8), devices[:8])
    table, ids = _table_and_ids(4)
    fn = emb.make_sharded_lookup(mesh1)
    got = fn(ids, emb.to_mod_sharded(table, mesh1))
    np.testing.assert_allclose(got, jnp.take(table, ids, axis=0), rtol=1e-6)
