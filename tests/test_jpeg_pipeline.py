"""JPEG record container + decode/augment pipeline (data/jpeg_records.py,
data/augment.py) — the real-ImageNet input path (SURVEY.md §7 hard part
#1; reference analog: per-worker tf.data JPEG decode, SURVEY.md §2a).

Covers: container roundtrip, eval-mode determinism, the train-mode
resume contract (index_offset reproduces the exact augmented stream),
epoch reshuffling, augment-op oracles, the `jpeg:` wiring through
make_dataset, and a host-only decode-throughput probe (slow)."""

import time

import numpy as np
import pytest

from distributed_tensorflow_tpu.data import DataConfig, augment, make_dataset
from distributed_tensorflow_tpu.data.jpeg_records import (
    JpegClassificationDataset, make_jpeg_record_file,
)


def _images(n, h=48, w=40, seed=0):
    rng = np.random.RandomState(seed)
    # smooth gradients survive JPEG quality=90 nearly losslessly
    base = np.linspace(0, 200, h * w * 3).reshape(h, w, 3)
    return np.stack([
        np.clip(base + rng.randint(0, 40), 0, 255).astype(np.uint8)
        for _ in range(n)
    ])


@pytest.fixture()
def jpeg_pair(tmp_path):
    path = str(tmp_path / "train")
    imgs = _images(24)
    labels = np.arange(24) % 7
    n = make_jpeg_record_file(path, imgs, labels)
    assert n == 24
    return path, imgs, labels


def test_eval_batches_deterministic_and_decoded(jpeg_pair):
    path, imgs, labels = jpeg_pair
    ds = JpegClassificationDataset(path, 32, 8, train=False, num_batches=3)
    batches = list(ds)
    assert len(batches) == 3
    b = batches[0]
    assert b["image"].shape == (8, 32, 32, 3)
    assert b["image"].dtype == np.float32
    assert 0.0 <= b["image"].min() and b["image"].max() <= 1.0
    # eval mode: no shuffle — labels stream in file order
    np.testing.assert_array_equal(b["label"], labels[:8])
    # deterministic: decoding the same batch twice is identical
    np.testing.assert_array_equal(ds.batch(1)["image"], ds.batch(1)["image"])
    # decode really round-trips the pixels (quality 90, smooth content)
    dec = augment.resize_center_crop(imgs[0], 32) / 255.0
    np.testing.assert_allclose(b["image"][0], dec, atol=0.05)


def test_train_resume_contract_and_reshuffle(jpeg_pair):
    path, _, _ = jpeg_pair
    ds = JpegClassificationDataset(path, 32, 8, train=True, seed=3)
    # resume contract: a fresh instance at index_offset=k reproduces
    # batch k of the uninterrupted stream — images AND augmentations
    resumed = JpegClassificationDataset(path, 32, 8, train=True, seed=3,
                                        index_offset=2)
    want = ds.batch(2)
    got = resumed.batch(0)
    np.testing.assert_array_equal(want["image"], got["image"])
    np.testing.assert_array_equal(want["label"], got["label"])
    # different global indices give different augmented batches
    assert np.any(ds.batch(0)["image"] != ds.batch(1)["image"])
    # epochs reshuffle: 24 imgs / batch 8 = 3 batches/epoch; epoch 0 vs 1
    # see different label order almost surely
    e0 = np.concatenate([ds.batch(i)["label"] for i in range(3)])
    e1 = np.concatenate([ds.batch(i)["label"] for i in range(3, 6)])
    assert sorted(e0.tolist()) == sorted(e1.tolist())  # same epoch content
    assert np.any(e0 != e1)


def test_make_dataset_jpeg_wiring(jpeg_pair):
    path, _, _ = jpeg_pair
    cfg = DataConfig(dataset=f"jpeg:{path}", global_batch_size=8,
                     image_size=32, num_classes=7)
    it = iter(make_dataset(cfg, num_batches=2))
    b = next(it)
    assert b["image"].shape == (8, 32, 32, 3)
    assert set(np.unique(b["label"])) <= set(range(7))


def test_augment_ops_oracles():
    rng = np.random.RandomState(0)
    img = _images(1, h=60, w=80)[0]
    # random_resized_crop: exact output shape, uint8, content from source
    out = augment.random_resized_crop(img, rng, 32)
    assert out.shape == (32, 32, 3) and out.dtype == np.uint8
    # resize_center_crop: shape + the 0.875 short-side recipe
    out = augment.resize_center_crop(img, 32)
    assert out.shape == (32, 32, 3)
    # hflip: flips exactly half the time, exact mirror when it does
    flipped = augment.hflip(img, np.random.RandomState(1))
    either = (np.array_equal(flipped, img)
              or np.array_equal(flipped, img[:, ::-1]))
    assert either
    # random_crop_flip (CIFAR batch recipe) matches a per-image oracle
    batch = _images(6, h=32, w=32, seed=2).astype(np.float32)
    rng1, rng2 = np.random.RandomState(5), np.random.RandomState(5)
    got = augment.random_crop_flip(batch, rng1, padding=4)
    ys = rng2.randint(0, 9, 6)
    xs = rng2.randint(0, 9, 6)
    padded = np.pad(batch, ((0, 0), (4, 4), (4, 4), (0, 0)))
    want = np.stack([
        padded[i, ys[i]:ys[i] + 32, xs[i]:xs[i] + 32] for i in range(6)
    ])
    flips = rng2.rand(6) < 0.5
    want[flips] = want[flips, :, ::-1]
    np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_decode_throughput_host_only(tmp_path):
    """VERDICT round-1 item 6 'done' probe: the threaded decode+augment
    path must sustain a real per-core rate (measured ~500 img/s/core at
    256->224 on this container's single core — a 16-core TPU-VM host
    extrapolates to ~8k img/s, past the ~2.5k img/s bench step rate).
    Thread-pool scaling is asserted only where the host has cores to
    scale onto; PIL releases the GIL during decode."""
    import os

    path = str(tmp_path / "tp")
    n = 128
    imgs = _images(n, h=256, w=256, seed=1)
    make_jpeg_record_file(path, imgs, np.zeros(n, np.int64))

    def rate_of(decoder):
        try:
            ds = JpegClassificationDataset(path, 224, 64, train=True,
                                           decoder=decoder)
        except RuntimeError:  # native lib unavailable on this host
            return None
        ds.batch(0)  # warm the pool + caches
        t0 = time.perf_counter()
        for i in range(1, 5):
            ds.batch(i)
        return 4 * 64 / (time.perf_counter() - t0)

    rate = rate_of("pil")
    native_rate = rate_of("native")
    print(f"decode+augment: pil {rate:.0f} img/s, native "
          f"{native_rate and round(native_rate)} img/s "
          f"({os.cpu_count()} cores)")
    # well under the idle single-core measurement (~500 img/s) — the CI
    # box may be sharing its core with concurrent jobs
    assert rate > 60, rate
    if native_rate is not None:
        # the C++ stage must not be slower than PIL (measured ~1.9x)
        assert native_rate > rate * 0.9, (native_rate, rate)
    if (os.cpu_count() or 1) >= 4:
        ds1 = JpegClassificationDataset(path, 224, 64, train=True,
                                        n_threads=1, decoder="pil")
        ds1.batch(0)
        t0 = time.perf_counter()
        ds1.batch(1)
        serial = 64 / (time.perf_counter() - t0)
        print(f"single-thread: {serial:.0f} images/sec")
        assert rate > 2 * serial, (rate, serial)


def test_imagefolder_converter_roundtrip(tmp_path):
    """tools/make_jpeg_records.py: ImageFolder tree -> record pair by raw
    byte copy (lossless — decoded pixels identical to the source files),
    labels from sorted class dirs, readable by JpegClassificationDataset."""
    import io
    import json
    import sys

    from PIL import Image

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parents[1]))
    from tools.make_jpeg_records import convert

    src = tmp_path / "imagefolder"
    imgs = _images(6, h=40, w=40)
    for i, cls in enumerate(["cat", "dog", "ant"] * 2):
        d = src / cls
        d.mkdir(exist_ok=True, parents=True)
        Image.fromarray(imgs[i]).save(d / f"img{i}.jpg", "JPEG", quality=92)

    out = str(tmp_path / "rec")
    n = convert(str(src), out, shuffle_seed=None)
    assert n == 6
    classes = json.load(open(out + ".classes.json"))
    assert classes == ["ant", "cat", "dog"]

    ds = JpegClassificationDataset(out, 32, 6, train=False, num_batches=1)
    b = next(iter(ds))
    assert b["image"].shape == (6, 32, 32, 3)
    # labels follow sorted-class convention: ant=0, cat=1, dog=2
    assert sorted(b["label"].tolist()) == [0, 0, 1, 1, 2, 2]
    # raw-copy losslessness AND offset/label correspondence: with no
    # shuffle the stream is label-major, so entry i's bytes must equal
    # the i-th file of the sorted walk of its OWN class
    per_class_files = {
        c: sorted(p for p in (src / c).rglob("*") if p.suffix == ".jpg")
        for c in classes
    }
    cursor = {c: 0 for c in classes}
    for i in range(6):
        entry = ds.entries[i]
        raw = bytes(
            ds._data[entry["offset"]: entry["offset"] + entry["length"]]
        )
        cls = classes[int(entry["label"])]
        expect = per_class_files[cls][cursor[cls]]
        cursor[cls] += 1
        assert raw == expect.read_bytes(), (i, cls, expect)


def test_converter_limit_without_shuffle_keeps_all_classes(tmp_path):
    """--limit + --no-shuffle must not truncate the label-major list to
    the first class(es): the subset is interleaved round-robin so every
    class stays represented (ADVICE r2)."""
    import json
    import sys

    import numpy as np
    from PIL import Image

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parents[1]))
    from tools.make_jpeg_records import convert
    from distributed_tensorflow_tpu.data.jpeg_records import _ENTRY

    src = tmp_path / "imagefolder"
    imgs = _images(9, h=24, w=24)
    for i in range(9):
        d = src / f"class{i // 3}"  # 3 classes x 3 images
        d.mkdir(exist_ok=True, parents=True)
        Image.fromarray(imgs[i]).save(d / f"img{i}.jpg", "JPEG")

    out = str(tmp_path / "rec")
    n = convert(str(src), out, shuffle_seed=None, limit=3)
    assert n == 3
    entries = np.fromfile(out + ".idx", _ENTRY)
    assert sorted(entries["label"].tolist()) == [0, 1, 2]
    assert len(json.load(open(out + ".classes.json"))) == 3


def test_native_decoder_matches_pil_policy(tmp_path):
    """Native (C++/libjpeg) and PIL decoders draw IDENTICAL crop/flip
    decisions (augment.sample_crop_rect is the single policy definition)
    and resample within a small tolerance; both are deterministic."""
    from distributed_tensorflow_tpu.data import native_jpeg

    if not native_jpeg.available():
        pytest.skip("native jpeg library unavailable (no g++/libjpeg)")

    path = str(tmp_path / "rec")
    imgs = _images(16, h=64, w=56)
    make_jpeg_record_file(path, imgs, np.arange(16) % 4)

    for train in (False, True):
        dn = JpegClassificationDataset(path, 32, 8, train=train,
                                       decoder="native")
        dp = JpegClassificationDataset(path, 32, 8, train=train,
                                       decoder="pil")
        bn, bp = dn.batch(0), dp.batch(0)
        np.testing.assert_array_equal(bn["label"], bp["label"])
        # same crops/flips, different resampling filter: close, not equal
        assert np.abs(bn["image"] - bp["image"]).max() < 0.08, train
        np.testing.assert_array_equal(
            dn.batch(1)["image"], dn.batch(1)["image"])

    with pytest.raises(ValueError, match="decoder"):
        JpegClassificationDataset(path, 32, 8, decoder="webp")


def test_native_decoder_zero_fills_corrupt_stream(tmp_path):
    from distributed_tensorflow_tpu.data import native_jpeg

    if not native_jpeg.available():
        pytest.skip("native jpeg library unavailable")

    path = str(tmp_path / "rec")
    imgs = _images(8, h=40, w=40)
    make_jpeg_record_file(path, imgs, np.arange(8))
    # truncate record 3's stream in the index (simulates corruption)
    from distributed_tensorflow_tpu.data.jpeg_records import _ENTRY

    entries = np.fromfile(path + ".idx", _ENTRY)
    entries[3]["length"] = 10
    entries.tofile(path + ".idx")
    ds = JpegClassificationDataset(path, 32, 8, train=False,
                                   decoder="native")
    b = ds.batch(0)
    assert b["image"][3].max() == 0.0  # zero-filled, not crashed
    assert b["image"][0].max() > 0.0


def test_jpeg_per_host_sharding_disjoint(tmp_path, monkeypatch):
    """Multi-host contract: each process decodes a DISJOINT strided slice
    of the epoch order and the union covers the epoch exactly —
    simulated by pinning _shard/_n_shards (the tf.data.shard analog)."""
    path = str(tmp_path / "rec")
    imgs = _images(24, h=32, w=32)
    make_jpeg_record_file(path, imgs, np.arange(24))

    seen = []
    for shard in range(2):
        ds = JpegClassificationDataset(path, 32, 8, train=True, seed=1)
        ds._shard, ds._n_shards = shard, 2
        # local batch must be global/2 per host; recompute as the
        # constructor would under process_count=2
        ds.local_bs = 4
        labels = np.concatenate(
            [ds.batch(i)["label"] for i in range(ds._batches_per_epoch())]
        )
        seen.append(labels)
    a, b = seen
    assert len(set(a.tolist()) & set(b.tolist())) == 0  # disjoint
    assert sorted(set(a.tolist()) | set(b.tolist())) == sorted(
        np.arange(24).tolist())  # epoch covered


def test_native_decoder_grayscale_source(tmp_path):
    """Grayscale JPEGs (1-channel sources exist in real ImageNet) must
    decode to RGB in both tiers — libjpeg's out_color_space=JCS_RGB
    upsamples gray, PIL's convert('RGB') likewise."""
    import io

    from PIL import Image

    from distributed_tensorflow_tpu.data import native_jpeg
    from distributed_tensorflow_tpu.data.jpeg_records import _ENTRY

    if not native_jpeg.available():
        pytest.skip("native jpeg library unavailable")

    path = str(tmp_path / "rec")
    gray = _images(4, h=40, w=40)[..., 0]  # [N, H, W] single channel
    entries = np.empty(4, _ENTRY)
    with open(path + ".dat", "wb") as f:
        off = 0
        for i in range(4):
            buf = io.BytesIO()
            Image.fromarray(gray[i], "L").save(buf, "JPEG", quality=92)
            raw = buf.getvalue()
            f.write(raw)
            entries[i] = (off, len(raw), i)
            off += len(raw)
    entries.tofile(path + ".idx")

    bn = JpegClassificationDataset(path, 32, 4, train=False,
                                   decoder="native").batch(0)
    bp = JpegClassificationDataset(path, 32, 4, train=False,
                                   decoder="pil").batch(0)
    assert bn["image"].shape == (4, 32, 32, 3)
    # gray upsampled: all three channels equal
    np.testing.assert_array_equal(bn["image"][..., 0], bn["image"][..., 1])
    assert np.abs(bn["image"] - bp["image"]).max() < 0.08
