"""Wide&Deep (M9): forward parity take-vs-explicit, sharded training
convergence, workload end-to-end (SURVEY.md §7 M9, BASELINE.json:11)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.data.recsys import RecsysConfig, SyntheticCTR
from distributed_tensorflow_tpu.models import wide_deep as wd
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh

CFG = wd.WideDeepConfig(
    vocab_sizes=(64, 32, 16),
    embed_dim=8,
    dense_features=4,
    hidden_sizes=(32, 16),
    dtype="float32",
)


@pytest.fixture()
def mesh_tp4(devices):
    return build_mesh(MeshSpec(data=2, model=4), devices[:8])


def _batch(seed=0, b=16, cfg=CFG):
    rng = np.random.RandomState(seed)
    return {
        "cat": np.stack(
            [rng.randint(0, v, b) for v in cfg.vocab_sizes], -1
        ).astype(np.int32),
        "dense": rng.randn(b, cfg.dense_features).astype(np.float32),
        "label": rng.randint(0, 2, b).astype(np.float32),
    }


def test_forward_shape_and_finite():
    model = wd.WideDeep(CFG)
    params, _ = wd.make_init_fn(CFG)(jax.random.PRNGKey(0))
    b = _batch()
    logits = model.apply({"params": params}, b["cat"], b["dense"])
    assert logits.shape == (16,)
    assert np.isfinite(np.asarray(logits)).all()


def test_explicit_lookup_matches_take(mesh_tp4):
    b = _batch(1)
    params, _ = wd.make_init_fn(CFG)(jax.random.PRNGKey(0))
    dense_model = wd.WideDeep(CFG)
    expl_cfg = wd.WideDeepConfig(**{
        **CFG.__dict__, "embed_impl": "explicit"
    })
    expl_model = wd.WideDeep(expl_cfg, mesh_tp4)

    want = dense_model.apply({"params": params}, b["cat"], b["dense"])
    got = jax.jit(
        lambda p, c, d: expl_model.apply({"params": p}, c, d)
    )(params, b["cat"], b["dense"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    # backward parity: table gradients through the explicit exchange
    def loss(model):
        return lambda p: model.apply(
            {"params": p}, b["cat"], b["dense"]
        ).sum()

    g_take = jax.grad(loss(dense_model))(params)
    g_expl = jax.jit(jax.grad(loss(expl_model)))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_expl, g_take,
    )


@pytest.mark.parametrize("impl", ["take", "explicit"])
def test_workload_trains_and_evals(mesh_tp4, tmp_path, impl):
    from distributed_tensorflow_tpu.workloads import run_workload

    res = run_workload(
        "wide_deep",
        overrides=[
            f"model.embed_impl={impl}",
            "model.vocab_sizes=[64,32,16]",
            "model.embed_dim=8",
            "model.dense_features=4",
            "model.hidden_sizes=[32,16]",
            "model.dtype=float32",
            "mesh.data=2",
            "mesh.model=4",
            "data.global_batch_size=64",
            "train.num_steps=60",
            "train.log_every=20",
            "optimizer.learning_rate=0.01",
        ],
    )
    first = res.history[0]["loss"]
    last = res.history[-1]["loss"]
    assert last < first, (first, last)
    assert res.eval_metrics["accuracy"] > 0.6, res.eval_metrics
    # streaming AUC (utils/metrics.py histograms, finalized in the
    # runner): a trained CTR model must rank clicks above non-clicks
    assert 0.6 < res.eval_metrics["auc"] <= 1.0, res.eval_metrics


def test_ctr_dataset_deterministic_and_skewed():
    cfg = RecsysConfig(vocab_sizes=(64, 32), dense_features=4,
                       global_batch_size=32)
    a = SyntheticCTR(cfg).batch(3)
    b = SyntheticCTR(cfg).batch(3)
    np.testing.assert_array_equal(a["cat"], b["cat"])
    # zipf skew: hot ids are 0 (head) and v-1 (clipped tail)
    big = np.concatenate([SyntheticCTR(cfg).batch(i)["cat"][:, 0]
                          for i in range(20)])
    assert np.bincount(big).argmax() in (0, cfg.vocab_sizes[0] - 1)
    assert set(np.unique(a["label"])) <= {0.0, 1.0}


def test_multi_optimizer_state_inherits_table_sharding():
    """The FTRL/AdaGrad split must not cost the tables their sharding:
    optimizer slot variables inside optax.masked/multi_transform states
    inherit the P('model', None) table specs (round-2 review finding —
    the structure match must see through MaskedNode containers)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train.step import opt_state_specs
    from distributed_tensorflow_tpu.workloads import wide_deep as wl

    cfg = wl.default_config()
    params, _ = wd.make_init_fn(cfg.model)(jax.random.PRNGKey(0))
    param_specs = sh.specs_from_path_rules(params, wd.embedding_rules())
    tx = wl._canonical_tx(cfg)
    assert tx is not None
    opt_shape = jax.eval_shape(tx.init, params)
    specs = opt_state_specs(opt_shape, params, param_specs)
    # treedefs must match exactly (MaskedNode mirrored into the spec tree)
    assert (jax.tree.structure(specs, is_leaf=lambda x: isinstance(x, P))
            .num_leaves > 0)
    flat = [
        s for s in jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        if isinstance(s, P)
    ]
    model_sharded = [s for s in flat if any(ax == "model" for ax in s)]
    # deep tables (adagrad sum-of-squares) AND wide tables (ftrl z + n)
    n_feat = len(cfg.model.vocab_sizes)
    assert len(model_sharded) >= 3 * n_feat, (len(model_sharded), n_feat)


def test_auc_histogram_metric():
    """Unit oracle for utils/metrics.py: exact rank-sum AUC vs a direct
    pairwise computation, plus the degenerate edges."""
    from distributed_tensorflow_tpu.utils import metrics as m

    r = np.random.RandomState(3)
    logits = jnp.asarray(r.randn(400) * 2)
    labels = jnp.asarray((r.rand(400) < 0.3).astype(np.float32))
    h = m.auc_histograms(logits, labels)
    got = m.auc_from_histograms(h["auc_pos_hist"], h["auc_neg_hist"])
    # direct Mann-Whitney on the raw scores
    s = np.asarray(logits)
    pos, neg = s[np.asarray(labels) == 1], s[np.asarray(labels) == 0]
    direct = float(
        ((pos[:, None] > neg[None, :]).sum()
         + 0.5 * (pos[:, None] == neg[None, :]).sum())
        / (len(pos) * len(neg))
    )
    assert abs(got - direct) < 5e-3, (got, direct)  # O(1/bins) bucketing

    # perfect separation -> 1.0; identical distributions -> ~0.5
    h2 = m.auc_histograms(
        jnp.asarray([-5.0, -4.0, 4.0, 5.0]), jnp.asarray([0.0, 0.0, 1.0, 1.0]))
    assert m.auc_from_histograms(h2["auc_pos_hist"], h2["auc_neg_hist"]) == 1.0
    # identical score multisets for both classes: exactly 0.5 (tie credit)
    x = r.randn(500)
    same = jnp.asarray(np.concatenate([x, x]))
    lab = jnp.asarray(np.concatenate([np.ones(500), np.zeros(500)])
                      .astype(np.float32))
    h3 = m.auc_histograms(same, lab)
    assert m.auc_from_histograms(
        h3["auc_pos_hist"], h3["auc_neg_hist"]) == 0.5
    # saturation regression: confidently-scored but separable pairs must
    # NOT collapse to 0.5 (logit-space bucketing; sigmoid-space would)
    h5 = m.auc_histograms(
        jnp.asarray([7.5, 7.6, 9.0, 9.1]), jnp.asarray([0.0, 0.0, 1.0, 1.0]))
    assert m.auc_from_histograms(h5["auc_pos_hist"], h5["auc_neg_hist"]) == 1.0
    # one-class batch: undefined -> NaN
    h4 = m.auc_histograms(jnp.asarray([1.0, 2.0]), jnp.asarray([1.0, 1.0]))
    assert np.isnan(m.auc_from_histograms(h4["auc_pos_hist"], h4["auc_neg_hist"]))
    # histograms merge by addition: two halves == whole
    ha = m.auc_histograms(logits[:200], labels[:200])
    hb = m.auc_histograms(logits[200:], labels[200:])
    merged = m.auc_from_histograms(
        ha["auc_pos_hist"] + hb["auc_pos_hist"],
        ha["auc_neg_hist"] + hb["auc_neg_hist"])
    assert abs(merged - got) < 1e-9, (merged, got)


class TestCTRRecords:
    def _record_file(self, tmp_path, n=600, vocabs=(50, 30), dense=4):
        import numpy as np

        from distributed_tensorflow_tpu.data.recsys import (
            make_ctr_record_file,
        )

        r = np.random.RandomState(0)
        label = (r.rand(n) > 0.5).astype(np.float32)
        dn = r.randn(n, dense).astype(np.float32)
        cat = np.stack([r.randint(0, v, n) for v in vocabs], -1)
        path = str(tmp_path / "ctr.dat")
        make_ctr_record_file(path, label, dn, cat)
        return path, label, dn, cat

    def test_roundtrip_and_shuffle(self, tmp_path):
        import numpy as np

        from distributed_tensorflow_tpu.data.recsys import (
            CTRRecordDataset, RecsysConfig,
        )

        path, label, dn, cat = self._record_file(tmp_path)
        cfg = RecsysConfig(vocab_sizes=(50, 30), dense_features=4,
                           global_batch_size=100, seed=3)
        batches = list(CTRRecordDataset(path, cfg, num_batches=6))
        assert len(batches) == 6
        b = batches[0]
        assert b["cat"].shape == (100, 2) and b["dense"].shape == (100, 4)
        assert b["label"].shape == (100,)
        # epoch 0 = a permutation of the file: the 6 batches cover all
        # 600 records exactly once (match rows via dense fingerprint)
        seen = np.concatenate([bb["dense"][:, 0] for bb in batches])
        np.testing.assert_allclose(np.sort(seen), np.sort(dn[:, 0]),
                                   rtol=1e-6)
        # resume contract: index_offset k reproduces batch k exactly
        again = next(iter(CTRRecordDataset(path, cfg, num_batches=1,
                                           index_offset=3)))
        for k in ("cat", "dense", "label"):
            np.testing.assert_array_equal(again[k], batches[3][k])

    def test_workload_trains_on_ctr_records(self, tmp_path):
        from distributed_tensorflow_tpu import workloads

        path, *_ = self._record_file(tmp_path, n=512, vocabs=(50, 30),
                                     dense=4)
        result = workloads.run_workload(
            "wide_deep",
            [
                f"--data.dataset=ctr:{path}",
                "--data.global_batch_size=64",
                "--model.vocab_sizes=[50,30]",
                "--model.dense_features=4",
                "--model.embed_dim=4",
                "--model.hidden_sizes=[16,8]",
                "--train.num_steps=4",
                "--train.log_every=2",
                "--train.eval_batches=2",
                "--checkpoint.directory=",
            ],
        )
        assert result.history and all(
            h["loss"] == h["loss"] for h in result.history
        )
        import numpy as np

        # no eval_dataset given: eval drew from the training file, so the
        # metric is tagged train_auc (ADVICE r3) — the honest label
        assert np.isfinite(result.eval_metrics["train_auc"])
        assert "auc" not in result.eval_metrics

    def test_explicit_eval_dataset_gets_untagged_auc(self, tmp_path):
        from distributed_tensorflow_tpu import workloads

        path, *_ = self._record_file(tmp_path, n=512, vocabs=(50, 30),
                                     dense=4)
        (tmp_path / "ev").mkdir()
        epath, *_ = self._record_file(
            tmp_path / "ev", n=256, vocabs=(50, 30), dense=4)
        result = workloads.run_workload(
            "wide_deep",
            [
                f"--data.dataset=ctr:{path}",
                f"--data.eval_dataset=ctr:{epath}",
                "--data.global_batch_size=64",
                "--model.vocab_sizes=[50,30]",
                "--model.dense_features=4",
                "--model.embed_dim=4",
                "--model.hidden_sizes=[16,8]",
                "--train.num_steps=2",
                "--train.log_every=2",
                "--train.eval_batches=2",
                "--checkpoint.directory=",
            ],
        )
        import numpy as np

        assert np.isfinite(result.eval_metrics["auc"])
        assert "train_auc" not in result.eval_metrics


def test_unrecognized_eval_dataset_raises():
    # an explicit-but-unsupported eval source must error loudly, not
    # silently fall back to a train-set metric (code-review r4)
    import pytest as _pytest

    from distributed_tensorflow_tpu import workloads

    with _pytest.raises(ValueError, match="eval_dataset"):
        workloads.run_workload("wide_deep", [
            "--data.eval_dataset=npz:/nonexistent.npz",
            "--train.num_steps=1", "--checkpoint.directory=",
        ])
