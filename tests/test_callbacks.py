"""train/callbacks.py unit tests — pure host, no mesh, no device step:
a stub trainer + fake clocks drive every callback path (ISSUE 2
satellite: this module previously had zero coverage).

Covers: StopAtStep, NaNGuard fail-fast vs request-stop, MetricsLogger
throughput math under a deterministic clock, the SummaryWriter
stale-scalar fix (cadence mismatch with its paired logger), and
TelemetryCallback's registry mirroring."""

import numpy as np
import pytest

from distributed_tensorflow_tpu import obs
from distributed_tensorflow_tpu.train import callbacks as cb


class StubTrainer:
    """Just the surface callbacks touch: request_stop/should_stop."""

    def __init__(self):
        self.stop_reason = None
        self.failed = False

    def request_stop(self, reason=""):
        if self.stop_reason is None:
            self.stop_reason = reason or "requested"

    @property
    def should_stop(self):
        return self.stop_reason is not None


class FakeClock:
    """Deterministic perf_counter: advances ``dt`` per call."""

    def __init__(self, dt=1.0, t0=100.0):
        self.t = t0
        self.dt = dt

    def __call__(self):
        self.t += self.dt
        return self.t


class FakeTBWriter:
    def __init__(self):
        self.scalars = []  # (tag, value, step)
        self.closed = False

    def add_scalar(self, tag, value, global_step):
        self.scalars.append((tag, value, global_step))

    def flush(self):
        pass

    def close(self):
        self.closed = True


# ---------------------------------------------------------------------------
# StopAtStep / NaNGuard
# ---------------------------------------------------------------------------


def test_stop_at_step():
    t = StubTrainer()
    hook = cb.StopAtStep(last_step=3)
    for step in (1, 2):
        hook.on_step_end(t, step, {})
        assert not t.should_stop
    hook.on_step_end(t, 3, {})
    assert t.should_stop and "last_step=3" in t.stop_reason


def test_nan_guard_fail_fast_raises():
    t = StubTrainer()
    guard = cb.NaNGuard(every_n=1, fail_fast=True)
    guard.on_step_end(t, 1, {"loss": np.float32(1.0),
                             "grads_finite": np.float32(1.0)})
    with pytest.raises(FloatingPointError, match="step 2"):
        guard.on_step_end(t, 2, {"loss": np.float32(np.nan)})
    with pytest.raises(FloatingPointError):
        guard.on_step_end(t, 3, {"grads_finite": np.float32(0.0)})
    assert not t.should_stop  # fail-fast never uses the stop path


def test_nan_guard_request_stop_path():
    t = StubTrainer()
    guard = cb.NaNGuard(every_n=1, fail_fast=False)
    guard.on_step_end(t, 1, {"loss": np.float32(np.inf)})
    assert t.should_stop and "non-finite" in t.stop_reason


def test_nan_guard_cadence_gating():
    """Off-cadence steps are never inspected — the async contract."""
    t = StubTrainer()
    guard = cb.NaNGuard(every_n=10, fail_fast=True)
    guard.on_step_end(t, 5, {"loss": np.float32(np.nan)})  # not step % 10
    assert not t.should_stop
    with pytest.raises(FloatingPointError):
        guard.on_step_end(t, 10, {"loss": np.float32(np.nan)})


# ---------------------------------------------------------------------------
# MetricsLogger
# ---------------------------------------------------------------------------


def test_metrics_logger_throughput_math():
    """Fake clock: one tick per fetch → steps_per_sec is exactly
    every_n / dt, examples/sec scales by batch size."""
    clock = FakeClock(dt=2.5)
    ml = cb.MetricsLogger(every_n=5, batch_size=8, clock=clock)
    t = StubTrainer()
    ml.on_train_start(t)
    for step in range(1, 11):
        ml.on_step_end(t, step, {"loss": np.float32(1.0 / step)})
    # first fetch (step 5) has no baseline → no throughput keys
    # second fetch (step 10): 5 steps in one 2.5s clock tick
    assert ml.last_step == 10
    assert ml.last["steps_per_sec"] == pytest.approx(5 / 2.5)
    assert ml.last["examples_per_sec"] == pytest.approx(8 * 5 / 2.5)
    assert ml.last["loss"] == pytest.approx(0.1)
    assert "mfu" not in ml.last  # no model_flops given


def test_metrics_logger_cadence_and_history():
    ml = cb.MetricsLogger(every_n=3, history=True, clock=FakeClock())
    t = StubTrainer()
    ml.on_train_start(t)
    for step in range(1, 8):
        ml.on_step_end(t, step, {"loss": np.float32(step)})
    assert [h["step"] for h in ml.history] == [3, 6]
    assert ml.last_step == 6 and ml.last["loss"] == 6.0
    ml.on_train_start(t)  # restart clears staleness
    assert ml.last == {} and ml.last_step is None


# ---------------------------------------------------------------------------
# SummaryWriter stale-scalar fix
# ---------------------------------------------------------------------------


def test_summary_writer_skips_stale_logger_scalars():
    """Writer every 2, logger every 4: at steps where the logger did NOT
    fetch, the writer must read the live metrics dict, not the logger's
    old `last` (the stale-scalar bug)."""
    ml = cb.MetricsLogger(every_n=4, clock=FakeClock())
    sw = cb.SummaryWriter("unused", every_n=2, metrics_logger=ml)
    sw._writer = FakeTBWriter()  # bypass tensorboardX + chief gating
    t = StubTrainer()
    ml.on_train_start(t)
    for step in range(1, 7):
        m = {"loss": np.float32(10.0 * step)}
        ml.on_step_end(t, step, m)  # logger runs first, like in a real list
        sw.on_step_end(t, step, m)
    by_step = {s: v for (tag, v, s) in sw._writer.scalars if tag == "train/loss"}
    # steps 2 and 6: logger stale (fetched at nothing / step 4) → live value
    assert by_step[2] == pytest.approx(20.0)
    assert by_step[6] == pytest.approx(60.0)
    # step 4: cadences align → reuses the logger's freshly fetched dict
    assert by_step[4] == pytest.approx(40.0)


def test_summary_writer_reuses_aligned_logger_and_closes():
    ml = cb.MetricsLogger(every_n=2, batch_size=4, clock=FakeClock())
    sw = cb.SummaryWriter("unused", every_n=2, metrics_logger=ml)
    fake = FakeTBWriter()
    sw._writer = fake
    t = StubTrainer()
    ml.on_train_start(t)
    for step in (1, 2, 3, 4):
        m = {"loss": np.float32(step)}
        ml.on_step_end(t, step, m)
        sw.on_step_end(t, step, m)
    # aligned: the logger's derived throughput scalars get written too
    tags = {tag for (tag, _, s) in fake.scalars if s == 4}
    assert {"train/loss", "train/steps_per_sec",
            "train/examples_per_sec"} <= tags
    sw.on_train_end(t)
    assert fake.closed and sw._writer is None


# ---------------------------------------------------------------------------
# TelemetryCallback
# ---------------------------------------------------------------------------


def test_telemetry_callback_step_histogram_and_gauges():
    reg = obs.Registry()
    clock = FakeClock(dt=0.5)
    tc = cb.TelemetryCallback(registry=reg, every_n=2, clock=clock)
    t = StubTrainer()
    tc.on_train_start(t)
    for step in range(1, 6):
        tc.on_step_end(t, step, {"loss": np.float32(1.0 / step)})
    h = reg.get("train_step_seconds")
    assert h.count == 4  # first step has no baseline
    assert h.sum == pytest.approx(4 * 0.5)  # one clock tick per step
    assert reg.get("train_steps_total").value == 5
    assert reg.get("train_global_step").value == 5
    # gauges sampled at the cadence steps only — last write was step 4
    assert reg.get("train_loss").value == pytest.approx(0.25)


def test_telemetry_callback_reuses_aligned_logger_fetch():
    reg = obs.Registry()
    clock = FakeClock()
    ml = cb.MetricsLogger(every_n=2, batch_size=4, clock=clock)
    tc = cb.TelemetryCallback(registry=reg, every_n=2, metrics_logger=ml,
                              clock=clock)
    t = StubTrainer()
    ml.on_train_start(t)
    tc.on_train_start(t)
    for step in range(1, 5):
        m = {"loss": np.float32(step)}
        ml.on_step_end(t, step, m)
        tc.on_step_end(t, step, m)
    # derived scalars (steps_per_sec) only exist via the logger's dict —
    # their presence proves the aligned reuse path ran
    assert reg.get("train_steps_per_sec") is not None
    assert reg.get("train_loss").value == pytest.approx(4.0)


def test_telemetry_callback_sanitizes_metric_names():
    reg = obs.Registry()
    tc = cb.TelemetryCallback(registry=reg, every_n=1, clock=FakeClock())
    t = StubTrainer()
    tc.on_train_start(t)
    tc.on_step_end(t, 1, {"top-1/acc": np.float32(0.5)})
    assert reg.get("train_top_1_acc").value == 0.5
    # the sanitized name renders as a valid exposition line
    assert "train_top_1_acc 0.5" in obs.render(reg)


def test_telemetry_callback_defaults_to_process_registry():
    tc = cb.TelemetryCallback(every_n=1, clock=FakeClock())
    assert tc.registry is obs.default_registry()


# ---------------------------------------------------------------------------
# Watchdog abort_on_stall + fleet heartbeat seam (ISSUE 8 satellites)
# ---------------------------------------------------------------------------


class ManualClock:
    """Clock that moves only when the test moves it."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_watchdog_abort_on_stall_raises_stalled_error():
    """abort mode: the stall edge delivers a StalledError asynchronously
    into the thread that entered on_train_start, so a hung attempt dies
    classified (resilience maps it to 'stalled') instead of only
    flagging a gauge."""
    import time

    reg = obs.Registry()
    clk = ManualClock()
    wd = cb.Watchdog(budget_s=5.0, registry=reg, poll_s=0.005, clock=clk,
                     abort_on_stall=True)
    t = StubTrainer()
    wd.on_train_start(t)
    try:
        clk.t = 100.0  # hung step: way over budget, no on_step_end
        with pytest.raises(cb.StalledError):
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:  # the "hung" Python loop
                time.sleep(0.001)
            raise AssertionError("watchdog never aborted the loop")
    finally:
        wd.on_train_end(t)
    assert reg.get("train_watchdog_stalls_total").value == 1
    assert reg.get("train_watchdog_stalled").value == 1.0


def test_watchdog_default_never_aborts():
    """Detection-only default: same stall, no exception — the gauge and
    counter remain the only record."""
    import time

    reg = obs.Registry()
    clk = ManualClock()
    wd = cb.Watchdog(budget_s=5.0, registry=reg, poll_s=0.005, clock=clk)
    t = StubTrainer()
    wd.on_train_start(t)
    try:
        clk.t = 100.0
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if reg.get("train_watchdog_stalls_total").value:
                break
            time.sleep(0.001)
        time.sleep(0.05)  # would-be delivery window: nothing may raise
    finally:
        wd.on_train_end(t)
    assert reg.get("train_watchdog_stalls_total").value == 1


def test_heartbeat_callback_beats_from_step_seam(tmp_path):
    from distributed_tensorflow_tpu.resilience import fleet as fl

    w = fl.HeartbeatWriter(str(tmp_path / "hb.json"), incarnation=1)
    hb_cb = cb.HeartbeatCallback(w, every_n=2)
    t = StubTrainer()
    hb_cb.on_train_start(t)
    hb = fl.read_heartbeat(str(tmp_path / "hb.json"))
    assert hb.phase == "train"
    seq0 = hb.seq
    hb_cb.on_step_end(t, 1, {})  # off-cadence: no write
    assert fl.read_heartbeat(str(tmp_path / "hb.json")).seq == seq0
    hb_cb.on_step_end(t, 2, {})
    hb = fl.read_heartbeat(str(tmp_path / "hb.json"))
    assert hb.seq == seq0 + 1 and hb.step == 2


def test_elastic_callback_reports_hold_as_pause():
    """A resize barrier hold is a sanctioned pause: its wall time is
    broadcast to every note_pause-aware peer (cadence meters keep
    measuring the train loop; an armed Watchdog re-arms at the
    boundary) and never booked as a step."""

    class FakeClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

    clk = FakeClock()

    class HoldingClient:
        def __init__(self):
            self.polled = []

        def poll(self, step):
            self.polled.append(step)
            clk.t += 7.5  # the fleet held us for 7.5s

    class Peer(cb.Callback):
        def __init__(self):
            self.pauses = []

        def note_pause(self, seconds):
            self.pauses.append(seconds)

    client, peer = HoldingClient(), Peer()
    ecb = cb.ElasticCallback(client, clock=clk)
    t = StubTrainer()
    t.callbacks = [ecb, peer]
    ecb.on_step_end(t, 3, {})
    assert client.polled == [3]
    assert peer.pauses == [7.5]
