"""MoE layer: routing oracle, capacity drops, expert-parallel sharding
parity, and a transformer-with-MoE train smoke (SURVEY.md §2c EP row)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops import moe as moe_lib
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.parallel import sharding as sh

CFG = moe_lib.MoEConfig(
    num_experts=4, d_model=16, d_ff=32, top_k=2,
    capacity_factor=8.0,  # big enough that nothing drops
    dtype="float32",
)


def _x(seed=0, b=2, s=8):
    return jnp.asarray(
        np.random.RandomState(seed).randn(b, s, CFG.d_model).astype(np.float32)
    )


def _init(cfg=CFG, seed=0):
    model = moe_lib.MoEMLP(cfg)
    vars_ = model.init(jax.random.PRNGKey(seed), _x(), train=False)
    return model, vars_["params"]


def _dense_oracle(params, x, cfg):
    """Per-token direct computation of the same top-k expert mix."""
    T = x.shape[0] * x.shape[1]
    tokens = np.asarray(x).reshape(T, cfg.d_model)
    k = np.asarray(params["router"]["kernel"])
    b = np.asarray(params["router"]["bias"])
    probs = np.asarray(jax.nn.softmax(tokens @ k + b, axis=-1))
    w_in, b_in = np.asarray(params["w_in"]), np.asarray(params["b_in"])
    w_out, b_out = np.asarray(params["w_out"]), np.asarray(params["b_out"])
    out = np.zeros_like(tokens)
    for t in range(T):
        order = np.argsort(-probs[t])[: cfg.top_k]
        gates = probs[t][order]
        if cfg.top_k > 1:
            gates = gates / gates.sum()
        # top_k == 1: raw gate (Switch) — keeps the router differentiable
        for e, g in zip(order, gates):
            h = np.asarray(jax.nn.gelu(tokens[t] @ w_in[e] + b_in[e]))
            out[t] += g * (h @ w_out[e] + b_out[e])
    return out.reshape(x.shape)


def test_matches_dense_oracle_when_no_drops():
    model, params = _init()
    x = _x(1)
    y, _ = model.apply({"params": params}, x, train=True, mutable=["losses"])
    np.testing.assert_allclose(
        np.asarray(y), _dense_oracle(params, x, CFG), atol=1e-4
    )


def test_grouped_routing_matches_oracle():
    # several routing groups (T=16, group=4): same per-token result as the
    # ungrouped oracle when capacity is ample
    cfg = moe_lib.MoEConfig(**{**CFG.__dict__, "group_size": 4})
    model, params = _init(cfg)
    x = _x(7)
    y, _ = model.apply({"params": params}, x, train=True, mutable=["losses"])
    np.testing.assert_allclose(
        np.asarray(y), _dense_oracle(params, x, cfg), atol=1e-4
    )
    with pytest.raises(ValueError, match="divide"):
        bad = moe_lib.MoEConfig(**{**CFG.__dict__, "group_size": 5})
        moe_lib.MoEMLP(bad).init(jax.random.PRNGKey(0), _x(), train=False)


def test_aux_loss_positive_and_bounded():
    model, params = _init()
    _, mut = model.apply({"params": params}, _x(2), train=True,
                         mutable=["losses"])
    aux = float(moe_lib.collect_aux_loss(mut))
    # perfectly balanced router gives aux_weight * 1.0; imbalance gives more
    assert 0 < aux < CFG.router_aux_weight * CFG.num_experts


@pytest.mark.slow
def test_top1_router_gets_main_loss_gradient():
    """Switch-style top_k=1 must scale outputs by the raw gate probability —
    renormalizing would pin the gate at 1.0 and starve the router of
    main-loss gradient (round-1 advisor finding)."""
    cfg = moe_lib.MoEConfig(**{**CFG.__dict__, "top_k": 1,
                               "router_aux_weight": 0.0})
    model, params = _init(cfg)
    x = _x(5)

    def main_loss(p):
        y, _ = model.apply({"params": p}, x, train=True, mutable=["losses"])
        return jnp.sum(y ** 2)

    g = jax.grad(main_loss)(params)
    router_g = np.abs(np.asarray(g["router"]["kernel"])).max()
    assert router_g > 0, "router received no gradient from the main loss"


def test_capacity_drops_produce_zeros():
    # capacity 1 per expert, 16 tokens over 4 experts → most tokens dropped
    cfg = moe_lib.MoEConfig(**{**CFG.__dict__, "capacity_factor": 1e-6,
                               "top_k": 1})
    model, params = _init(cfg)
    x = _x(3)
    y, _ = model.apply({"params": params}, x, train=True, mutable=["losses"])
    T = x.shape[0] * x.shape[1]
    flat = np.asarray(y).reshape(T, -1)
    zero_rows = (np.abs(flat).max(axis=-1) == 0).sum()
    assert zero_rows >= T - cfg.num_experts  # ≤1 survivor per expert


def test_sharded_matches_unsharded(devices):
    mesh = build_mesh(MeshSpec(data=2, expert=4), devices[:8])
    model, params = _init()
    x = _x(4, b=4)
    want, _ = model.apply({"params": params}, x, train=True,
                          mutable=["losses"])
    specs = sh.specs_from_path_rules(params, moe_lib.moe_rules())
    # Guard against rule/naming drift making this test vacuous (round-1
    # advisor finding: the old moe/-prefixed rules matched nothing on a
    # bare MoEMLP tree, so it compared replicated vs replicated): the
    # expert weights must actually carry the expert axis.
    from jax.sharding import PartitionSpec as P

    expert_specs = [
        s for s in jax.tree.leaves(specs, is_leaf=lambda v: isinstance(v, P))
        if any(ax == "expert" for ax in s if ax is not None)
    ]
    assert len(expert_specs) >= 4, specs
    sharded = sh.shard_tree(params, mesh, specs)
    xs = jax.device_put(
        x, jax.sharding.NamedSharding(mesh, sh.batch_spec(x.ndim))
    )
    got, _ = jax.jit(
        lambda p, v: model.apply({"params": p}, v, train=True,
                                 mutable=["losses"])
    )(sharded, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


@pytest.mark.slow
def test_transformer_moe_trains(devices):
    import optax

    from distributed_tensorflow_tpu.models.transformer import (
        Transformer, TransformerConfig, lm_loss_fn, make_init_fn, tp_rules,
    )
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )

    cfg = TransformerConfig(
        vocab_size=128, max_len=32, num_layers=2, d_model=32, num_heads=4,
        d_ff=64, causal=True, pre_ln=True, dtype="float32",
        num_experts=4, moe_every=2, dropout=0.0,
    )
    mesh = build_mesh(MeshSpec(data=2, expert=2, model=2), devices[:8])
    model = Transformer(cfg, mesh)
    tx = optax.adam(1e-3)
    state, specs = init_train_state(
        make_init_fn(model, 32), tx, mesh, jax.random.PRNGKey(0),
        param_rules=tp_rules(),
    )
    step = jit_train_step(
        make_train_step(lm_loss_fn(model), tx,
                        StepOptions(check_grads_finite=True)), mesh, specs
    )
    rng = np.random.RandomState(0)
    losses = []
    for i in range(10):
        batch = {
            "input_ids": jax.device_put(
                rng.randint(0, 16, (8, 32)).astype(np.int32),
                jax.sharding.NamedSharding(mesh, sh.batch_spec(2)),
            )
        }
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
        assert float(metrics["grads_finite"]) == 1.0
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_scatter_dispatch_matches_einsum():
    """The linear-memory scatter dispatch makes identical routing
    decisions and produces the same outputs/aux as the einsum dispatch —
    including under capacity pressure (drops) and for top_k=1."""
    import dataclasses

    for top_k, cf in [(2, 8.0), (2, 0.6), (1, 0.6)]:
        cfg_e = dataclasses.replace(CFG, top_k=top_k, capacity_factor=cf)
        cfg_s = dataclasses.replace(cfg_e, dispatch_impl="scatter")
        model_e, params = _init(cfg_e, seed=3)
        model_s = moe_lib.MoEMLP(cfg_s)
        x = _x(seed=4)
        y_e, mut_e = model_e.apply(
            {"params": params}, x, train=True, mutable=["losses"]
        )
        y_s, mut_s = model_s.apply(
            {"params": params}, x, train=True, mutable=["losses"]
        )
        np.testing.assert_allclose(
            np.asarray(y_s), np.asarray(y_e), rtol=1e-5, atol=1e-5,
            err_msg=f"top_k={top_k} cf={cf}",
        )
        np.testing.assert_allclose(
            float(moe_lib.collect_aux_loss(mut_s)),
            float(moe_lib.collect_aux_loss(mut_e)), rtol=1e-6,
        )


@pytest.mark.slow
def test_scatter_dispatch_gradients_match_einsum():
    import dataclasses

    cfg_e = dataclasses.replace(CFG, capacity_factor=0.8)
    cfg_s = dataclasses.replace(cfg_e, dispatch_impl="scatter")
    _, params = _init(cfg_e, seed=5)
    x = _x(seed=6)

    def loss(cfg):
        model = moe_lib.MoEMLP(cfg)

        def go(p):
            y, mut = model.apply(
                {"params": p}, x, train=True, mutable=["losses"]
            )
            return (y * y).mean() + moe_lib.collect_aux_loss(mut)

        return go

    g_e = jax.grad(loss(cfg_e))(params)
    g_s = jax.grad(loss(cfg_s))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        ),
        g_s, g_e,
    )
