"""Perf-regression sentinel (ISSUE 17): tools/bench_trend.py detects
regressions in both metric directions over provenance-stamped bench
JSONs and REFUSES (exit 2) to compare runs whose provenance is missing
or disagrees on platform/device_kind — a cross-platform delta is a
category error, not a regression."""

import json

import pytest

from tools import bench_trend as bt


def bench_json(tmp_path, name, metrics, *, platform="cpu",
               device_kind="cpu", git_sha="abc123", provenance=True):
    payload = dict(metrics)
    if provenance:
        payload["provenance"] = {
            "platform": platform, "device_kind": device_kind,
            "git_sha": git_sha, "backend": platform,
        }
        if git_sha is None:
            del payload["provenance"]["git_sha"]
    path = tmp_path / name
    path.write_text(json.dumps(payload))
    return str(path)


# ---------------------------------------------------------------------------
# direction inference
# ---------------------------------------------------------------------------


def test_direction_inferred_from_metric_leaf():
    assert bt.lower_is_better("ttft_p99_ms")
    assert bt.lower_is_better("routed.lanes.interactive.ttft_p99_ms")
    assert bt.lower_is_better("wall_s")
    assert bt.lower_is_better("queue_wait_seconds")
    assert not bt.lower_is_better("tokens_per_sec")
    assert not bt.lower_is_better("prefix_hits")


def test_parse_metric_override_and_bad_direction():
    assert bt.parse_metric("x.tokens_per_sec") == ("x.tokens_per_sec", False)
    assert bt.parse_metric("score_ms:higher") == ("score_ms", False)
    assert bt.parse_metric("throughput:lower") == ("throughput", True)
    with pytest.raises(ValueError):
        bt.parse_metric("x:sideways")


def test_lookup_dotted_paths():
    d = {"routed": {"lanes": {"interactive": {"ttft_p99_ms": 7.0}}}}
    assert bt.lookup(d, "routed.lanes.interactive.ttft_p99_ms") == 7.0
    assert bt.lookup(d, "routed.lanes.batch.ttft_p99_ms") is None
    assert bt.lookup(d, "nope") is None


def test_lookup_list_indices():
    """Numeric parts index into lists — the sweep-report shape
    (``cells.0.steps_per_sec``) ci_fast's regression sentinel reads."""
    d = {"cells": [{"steps_per_sec": 101.0}, {"steps_per_sec": 55.0}]}
    assert bt.lookup(d, "cells.0.steps_per_sec") == 101.0
    assert bt.lookup(d, "cells.1.steps_per_sec") == 55.0
    assert bt.lookup(d, "cells.2.steps_per_sec") is None  # out of range
    assert bt.lookup(d, "cells.x.steps_per_sec") is None  # not an index


# ---------------------------------------------------------------------------
# trend verdicts
# ---------------------------------------------------------------------------


def test_latency_regression_fails(tmp_path):
    old = bench_json(tmp_path, "old.json", {"ttft_p99_ms": 10.0},
                     git_sha="aaa")
    new = bench_json(tmp_path, "new.json", {"ttft_p99_ms": 15.0},
                     git_sha="bbb")
    assert bt.main([old, new, "--metric", "ttft_p99_ms",
                    "--max-regress-pct", "10"]) == 1


def test_throughput_regression_fails_and_latency_drop_passes(tmp_path):
    old = bench_json(tmp_path, "old.json",
                     {"tokens_per_sec": 100.0, "ttft_p99_ms": 10.0},
                     git_sha="aaa")
    new = bench_json(tmp_path, "new.json",
                     {"tokens_per_sec": 80.0, "ttft_p99_ms": 8.0},
                     git_sha="bbb")
    # throughput fell 20%: regression
    assert bt.main([old, new, "--metric", "tokens_per_sec"]) == 1
    # latency fell 20%: improvement
    assert bt.main([old, new, "--metric", "ttft_p99_ms"]) == 0
    # both, within a huge budget: ok
    assert bt.main([old, new, "--metric", "tokens_per_sec",
                    "--metric", "ttft_p99_ms",
                    "--max-regress-pct", "50"]) == 0


def test_direction_override_flips_verdict(tmp_path):
    old = bench_json(tmp_path, "old.json", {"score_ms": 10.0}, git_sha="a")
    new = bench_json(tmp_path, "new.json", {"score_ms": 20.0}, git_sha="b")
    assert bt.main([old, new, "--metric", "score_ms"]) == 1  # _ms: lower
    assert bt.main([old, new, "--metric", "score_ms:higher"]) == 0


def test_three_run_trend_compares_first_to_last(tmp_path):
    runs = [bench_json(tmp_path, f"r{i}.json", {"ttft_p99_ms": v},
                       git_sha=f"sha{i}")
            for i, v in enumerate([10.0, 30.0, 10.5])]
    # the middle spike does not matter; first->last is +5%
    assert bt.main(runs + ["--metric", "ttft_p99_ms"]) == 0


def test_missing_metric_and_zero_baseline_fail(tmp_path):
    old = bench_json(tmp_path, "old.json", {"a_ms": 0.0}, git_sha="a")
    new = bench_json(tmp_path, "new.json", {"a_ms": 5.0}, git_sha="b")
    assert bt.main([old, new, "--metric", "b_ms"]) == 1
    assert bt.main([old, new, "--metric", "a_ms"]) == 1  # no trend from 0


# ---------------------------------------------------------------------------
# the provenance refusal gate (exit 2, BEFORE any metric math)
# ---------------------------------------------------------------------------


def test_refuses_unstamped_run(tmp_path):
    old = bench_json(tmp_path, "old.json", {"ttft_p99_ms": 10.0},
                     git_sha="aaa")
    new = bench_json(tmp_path, "new.json", {"ttft_p99_ms": 1.0},
                     provenance=False)
    # the candidate IMPROVED — refused anyway: unstamped is uncomparable
    assert bt.main([old, new, "--metric", "ttft_p99_ms"]) == 2


def test_refuses_run_without_git_sha(tmp_path):
    old = bench_json(tmp_path, "old.json", {"ttft_p99_ms": 10.0},
                     git_sha="aaa")
    new = bench_json(tmp_path, "new.json", {"ttft_p99_ms": 10.0},
                     git_sha=None)
    assert bt.main([old, new, "--metric", "ttft_p99_ms"]) == 2


def test_refuses_cross_platform_comparison(tmp_path):
    old = bench_json(tmp_path, "old.json", {"tokens_per_sec": 100.0},
                     platform="tpu", device_kind="TPU v5", git_sha="aaa")
    new = bench_json(tmp_path, "new.json", {"tokens_per_sec": 10.0},
                     platform="cpu", device_kind="cpu", git_sha="bbb")
    assert bt.main([old, new, "--metric", "tokens_per_sec"]) == 2


def test_refuses_device_kind_disagreement(tmp_path):
    old = bench_json(tmp_path, "old.json", {"tokens_per_sec": 100.0},
                     platform="tpu", device_kind="TPU v4", git_sha="aaa")
    new = bench_json(tmp_path, "new.json", {"tokens_per_sec": 100.0},
                     platform="tpu", device_kind="TPU v5", git_sha="bbb")
    assert bt.main([old, new, "--metric", "tokens_per_sec"]) == 2


def test_differing_git_sha_is_the_comparison_axis_not_a_refusal(tmp_path):
    old = bench_json(tmp_path, "old.json", {"tokens_per_sec": 100.0},
                     git_sha="aaa")
    new = bench_json(tmp_path, "new.json", {"tokens_per_sec": 101.0},
                     git_sha="bbb")
    assert bt.main([old, new, "--metric", "tokens_per_sec"]) == 0


def test_refuses_unreadable_json(tmp_path):
    old = bench_json(tmp_path, "old.json", {"a_ms": 1.0}, git_sha="a")
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert bt.main([old, str(bad), "--metric", "a_ms"]) == 2
    assert bt.main([old, str(tmp_path / "absent.json"),
                    "--metric", "a_ms"]) == 2


def test_refuses_bad_direction_suffix(tmp_path):
    old = bench_json(tmp_path, "old.json", {"a_ms": 1.0}, git_sha="a")
    new = bench_json(tmp_path, "new.json", {"a_ms": 1.0}, git_sha="b")
    assert bt.main([old, new, "--metric", "a_ms:sideways"]) == 2


# ---------------------------------------------------------------------------
# end-to-end against real bench_serve --fleet --json output shape
# ---------------------------------------------------------------------------


def test_provenance_check_matches_stamp_provenance_shape(tmp_path):
    """The gate accepts what obs.scaling.stamp_provenance actually
    writes (same keys bench_serve/bench stamp with)."""
    from distributed_tensorflow_tpu.obs.scaling import stamp_provenance

    payload = {"tokens_per_sec": 100.0}
    stamp_provenance(payload)
    p1 = tmp_path / "r1.json"
    p1.write_text(json.dumps(payload))
    payload2 = {"tokens_per_sec": 99.0}
    stamp_provenance(payload2)
    p2 = tmp_path / "r2.json"
    p2.write_text(json.dumps(payload2))
    rc = bt.main([str(p1), str(p2), "--metric", "tokens_per_sec"])
    # same-tree stamps always carry a git_sha here (repo checkout), so
    # the comparison must proceed and pass (−1% within the 10% budget)
    assert rc == 0
