"""Request ledger (ISSUE 17): transition-as-span partition invariants,
dump/validate round-trips, the cross-process clock-anchor merge, and
the trace-continuity acceptance — a replica SIGKILL-equivalent death
mid-decode yields ONE merged trace with spans from both replica
processes, a ``requeue_reprefill`` phase, no gaps or overlaps, and a
TTFT attribution whose parts sum to the measurement on a fake clock."""

import json

import pytest

from distributed_tensorflow_tpu import serve
from distributed_tensorflow_tpu.models import transformer as tfm
from distributed_tensorflow_tpu.obs import reqtrace as rq
from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.serve import fleet as sf
from distributed_tensorflow_tpu.serve import router as rt


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# Ledger unit invariants (jax-free)
# ---------------------------------------------------------------------------


def test_transitions_partition_wall_time_by_construction():
    """Each transition closes the open span at the same clock read, so
    phase durations sum to measured wall time EXACTLY — no
    'unattributed' bucket for the tail report to hide in."""
    clk = FakeClock(10.0)
    tr = rq.ReqTrace(src="router", clock=clk)
    tr.transition(1, "queue_wait")
    clk.advance(0.5)
    tr.transition(1, "route")
    clk.advance(0.25)
    tr.transition(1, "decode_gap")
    clk.advance(1.25)
    tr.finish(1, "eos")
    (rec,) = tr.records()
    parts = rq.phase_partition(rec)
    assert [p for p, _, _ in parts] == ["queue_wait", "route", "decode_gap"]
    assert parts[0][1] == 10.0 and parts[-1][2] == 12.0
    att = rq.attribute_window(rec, 10.0, 12.0)
    assert att == {"queue_wait": 0.5, "route": 0.25, "decode_gap": 1.25}
    assert sum(att.values()) == 2.0
    assert rec["finish_reason"] == "eos"


def test_unknown_phase_and_reserved_attrs_rejected():
    tr = rq.ReqTrace(clock=FakeClock())
    with pytest.raises(ValueError, match="unknown request-trace phase"):
        tr.transition(1, "warp_speed")
    with pytest.raises(ValueError, match="reserved"):
        tr.transition(1, "route", spans=[])


def test_capacity_evicts_oldest_and_counts():
    tr = rq.ReqTrace(capacity=2, clock=FakeClock())
    for rid in (1, 2, 3):
        tr.transition(rid, "queue_wait")
    assert len(tr) == 2
    assert tr.dropped == 1
    assert [r["rid"] for r in tr.records()] == [2, 3]


def test_finish_unknown_rid_is_ignored():
    tr = rq.ReqTrace(clock=FakeClock())
    tr.finish(404, "eos")  # evicted rid: must not raise on the serve path
    assert len(tr) == 0


def test_seq_tracks_mutations_for_dirty_dumping():
    tr = rq.ReqTrace(clock=FakeClock())
    s0 = tr.seq
    tr.transition(1, "queue_wait")
    assert tr.seq == s0 + 1
    tr.finish(1)
    assert tr.seq == s0 + 2
    tr.records()
    assert tr.seq == s0 + 2  # reads are not mutations


def test_dump_validate_load_roundtrip(tmp_path):
    clk = FakeClock(5.0)
    tr = rq.ReqTrace(src="w0i0", clock=clk)
    tr.transition(7, "admission_block", requeue=0)
    clk.advance(1.0)
    tr.transition(7, "prefill_chunks")
    clk.advance(1.0)
    tr.finish(7, "eos")
    path = tr.dump(str(tmp_path / "t.jsonl"), reason="unit",
                   extra={"worker": 0})
    assert rq.validate_dump(path) == []
    header, records = rq.load_dump(path)
    assert header["schema"] == rq.SCHEMA
    assert header["src"] == "w0i0" and header["worker"] == 0
    assert header["records"] == len(records) == 1
    assert records[0]["spans"][0]["requeue"] == 0


def test_validator_catches_torn_dump(tmp_path):
    clk = FakeClock()
    tr = rq.ReqTrace(clock=clk)
    tr.transition(1, "queue_wait")
    tr.finish(1)
    path = tr.dump(str(tmp_path / "t.jsonl"), reason="unit")
    lines = open(path).read().splitlines()
    torn = tmp_path / "torn.jsonl"
    # dtflint: disable=atomic-durable-write — reviewed: corrupting a
    # test corpus on purpose, torn-ness is the point
    torn.write_text(lines[0] + "\n")
    assert any("torn" in f for f in rq.validate_dump(str(torn)))


def test_merge_recovers_constant_clock_skew_exactly(tmp_path):
    """Router at t=100, replica clock 800 s ahead; the dispatch→ingest
    anchor recovers off = -800 exactly and the merged record partitions
    the request's life on the ROUTER clock."""
    rclk, wclk = FakeClock(100.0), FakeClock(900.0)
    router = rq.ReqTrace(src="router", clock=rclk)
    rep = rq.ReqTrace(src="w0i0", clock=wclk)
    router.transition(1, "queue_wait")
    rclk.t = 101.0
    router.transition(1, "route", requeue=0)
    wclk.t = 901.0  # same instant on the replica's skewed clock
    rep.transition(1, "admission_block", requeue=0)
    wclk.t = 902.0
    rep.transition(1, "prefill_chunks")
    wclk.t = 903.0
    rep.transition(1, "decode_gap")
    rclk.t = 103.5
    router.transition(1, "decode_gap", n=1)
    rclk.t = 104.0
    router.finish(1, "eos")
    wclk.t = 904.0
    rep.finish(1, "eos")
    rp = router.dump(str(tmp_path / "router.jsonl"), reason="unit")
    wp = rep.dump(str(tmp_path / "w0.jsonl"), reason="unit")
    header, merged, failures = rq.merge_traces(rp, [wp], reason="unit")
    assert failures == []
    assert header["offsets"] == {"w0i0": -800.0}
    (rec,) = merged
    assert rec["sources"] == ["router", "w0i0"]
    parts = rq.phase_partition(rec)  # gap/overlap-free or raises
    assert parts[0][1] == 100.0 and parts[-1][2] == 104.0
    assert rq.first_token_t(rec) == 103.0  # replica sample, aligned


def test_merge_fails_without_anchors_and_on_src_collision(tmp_path):
    clk = FakeClock()
    router = rq.ReqTrace(src="router", clock=clk)
    router.transition(1, "queue_wait")
    router.finish(1)
    rp = router.dump(str(tmp_path / "router.jsonl"), reason="unit")

    orphan = rq.ReqTrace(src="w0i0", clock=clk)
    orphan.transition(99, "admission_block", requeue=0)  # router never routed
    orphan.finish(99)
    op = orphan.dump(str(tmp_path / "orphan.jsonl"), reason="unit")
    _, _, failures = rq.merge_traces(rp, [op])
    assert any("no dispatch" in f for f in failures)

    dup = rq.ReqTrace(src="router", clock=clk)  # collides with the router
    dup.transition(1, "admission_block", requeue=0)
    dp = dup.dump(str(tmp_path / "dup.jsonl"), reason="unit")
    _, _, failures = rq.merge_traces(rp, [dp])
    assert any("collides" in f for f in failures)


def test_merge_fails_on_inconsistent_anchors(tmp_path):
    """A replica whose ingest stamp implies an offset ABOVE what its
    token delivery allows is lying about its clock — merge refusal, not
    a guess."""
    rclk, wclk = FakeClock(100.0), FakeClock(100.0)
    router = rq.ReqTrace(src="router", clock=rclk)
    rep = rq.ReqTrace(src="w0i0", clock=wclk)
    router.transition(1, "queue_wait")
    rclk.t = 110.0
    router.transition(1, "route", requeue=0)
    wclk.t = 105.0  # ingest BEFORE dispatch on the shared scale: lo = +5
    rep.transition(1, "admission_block", requeue=0)
    wclk.t = 106.0
    rep.transition(1, "decode_gap")
    rclk.t = 107.0  # delivery before sample+lo: hi = 1 < lo
    router.transition(1, "decode_gap", n=1)
    router.finish(1, "eos")
    rep.finish(1, "eos")
    rp = router.dump(str(tmp_path / "router.jsonl"), reason="unit")
    wp = rep.dump(str(tmp_path / "w0.jsonl"), reason="unit")
    _, _, failures = rq.merge_traces(rp, [wp])
    assert any("inconsistent clock anchors" in f for f in failures)


def test_span_chain_matches_subsequence_and_attrs():
    clk = FakeClock()
    tr = rq.ReqTrace(clock=clk)
    tr.transition(1, "queue_wait", lane="interactive")
    clk.advance(1)
    tr.transition(1, "route", requeue=0)
    clk.advance(1)
    tr.transition(1, "decode_gap")
    clk.advance(1)
    tr.finish(1, "eos")
    (rec,) = tr.records()
    assert rq.span_chain_matches(rec, ["queue_wait", "decode_gap"])
    assert rq.span_chain_matches(
        rec, [("queue_wait", {"lane": "interactive"}), "route",
              ("finish", {"reason": "eos"})])
    assert not rq.span_chain_matches(rec, ["route", "queue_wait"])
    assert not rq.span_chain_matches(rec, [("route", {"requeue": 1})])


# ---------------------------------------------------------------------------
# Trace continuity across a replica death (LocalReplica fleet, fake clock)
# ---------------------------------------------------------------------------


def fleet_decoder():
    return tfm.TransformerConfig(
        vocab_size=128, max_len=96, num_layers=1, d_model=32, num_heads=4,
        d_ff=64, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )


#: deliberate per-replica clock skew (seconds) the merge must undo
SKEWS = (1000.0, 5000.0, 9000.0)


def run_traced_fleet(kill_after_tokens=None, n=6):
    """The serve-fleet failover harness with request ledgers attached:
    the router ledger on the fleet clock, each engine's ledger on its
    own SKEWED clock — per-process monotonic clocks do not compare, and
    the test makes that maximally true in-process."""
    cfg = fleet_decoder()
    clk = FakeClock()
    reg, rec = Registry(), FlightRecorder()
    router_trace = rq.ReqTrace(src="router", clock=clk)
    traces = {"reqtrace-router.jsonl": router_trace}

    def launch(index, incarnation):
        skew = SKEWS[index % len(SKEWS)]
        eng_trace = rq.ReqTrace(src=f"w{index}i{incarnation}",
                                clock=lambda s=skew: clk.t + s)
        traces[f"reqtrace-w{index}i{incarnation}.jsonl"] = eng_trace
        eng = serve.ServeEngine.with_random_params(
            cfg, seed=0, num_slots=2, paged=True, block_size=8,
            prefill_chunk=16, reqtrace=eng_trace)
        return sf.LocalReplica(eng)

    router = rt.Router(max_outstanding=2, seed=0, registry=reg,
                       flightrec=rec, clock=clk, reqtrace=router_trace)
    sup = sf.ServeFleetSupervisor(
        launch, 2, router=router, registry=reg, flightrec=rec,
        clock=clk, sleep=lambda s: clk.advance(s or 0.01))
    sup.start()
    pfx = [[(7 * g + k) % 128 for k in range(16)] for g in range(2)]
    for i in range(n):
        lane = rt.LANE_INTERACTIVE if i % 2 == 0 else rt.LANE_BATCH
        router.submit(pfx[i % 2] + [(3 * i + 1) % 128], max_new_tokens=6,
                      lane=lane, prefix_len=16)
    killed = kill_after_tokens is None
    for _ in range(10_000):
        if router.idle:
            break
        sup.pump()
        clk.advance(1.0)
        if not killed:
            busy = [w for w in sorted(sup.replicas)
                    if any(router.requests[rid].delivered
                           for rid in router.outstanding.get(w, ()))]
            delivered = sum(len(r.delivered)
                            for r in router.requests.values())
            if busy and delivered >= kill_after_tokens:
                sup.replicas[busy[0]].handle.hard_kill()
                killed = True
    else:
        raise AssertionError("fleet did not go idle in 10k pumps")
    sup.stop()
    return router, traces


def dump_and_merge(traces, tmp_path, reason="test"):
    paths = {name: tr.dump(str(tmp_path / name), reason=reason)
             for name, tr in traces.items()}
    router_path = paths.pop("reqtrace-router.jsonl")
    for p in paths.values():
        assert rq.validate_dump(p) == []
    return rq.merge_traces(router_path, sorted(paths.values()),
                           reason=reason)


def test_killed_request_yields_one_merged_trace_across_replicas(tmp_path):
    """ISSUE 17 acceptance: a request killed mid-decode re-prefills on
    the survivor and its MERGED trace is one gap-free timeline with
    spans from BOTH replica processes, the death visible as a
    ``requeue_reprefill`` phase between the two lives."""
    router, traces = run_traced_fleet(kill_after_tokens=3)
    header, merged, failures = dump_and_merge(traces, tmp_path)
    assert failures == []
    # the anchors recovered each engine's deliberate skew exactly: the
    # dispatch and the ingest happen in the same pump on the fake clock
    for src, off in header["offsets"].items():
        idx = int(src[1:src.index("i")])
        assert off == -SKEWS[idx % len(SKEWS)], (src, off)

    killed = [rid for rid, req in router.finished.items() if req.requeues]
    assert killed, "no request crossed the kill"
    by_rid = {rec["rid"]: rec for rec in merged}
    for rid in killed:
        rec = by_rid[rid]
        replicas = [s for s in rec["sources"] if s != "router"]
        assert len(replicas) >= 2, rec["sources"]
        assert rq.span_chain_matches(rec, [
            "queue_wait", ("route", {"requeue": 0}),
            ("admission_block", {"requeue": 0}), "prefill_chunks",
            "decode_gap", "requeue_reprefill", ("route", {"requeue": 1}),
            ("admission_block", {"requeue": 1}), "prefill_chunks",
            "decode_gap",
            ("finish", {"reason": router.finished[rid].finish_reason}),
        ])
        parts = rq.phase_partition(rec)  # raises on any gap/overlap
        assert parts[0][1] == router.finished[rid].t_submit
        assert "requeue_reprefill" in {p for p, _, _ in parts}
    # every record (killed or not) partitions cleanly
    for rec in merged:
        rq.phase_partition(rec)


def test_tail_attribution_sums_to_measured_ttft(tmp_path):
    """The attribution soundness gate: each request's TTFT decomposes
    into named phases summing to the ROUTER-measured TTFT (fake clock:
    exact, far inside the 1% acceptance tolerance)."""
    router, traces = run_traced_fleet(kill_after_tokens=3)
    _, merged, failures = dump_and_merge(traces, tmp_path)
    assert failures == []
    checked = 0
    for rec in merged:
        req = router.finished[rec["rid"]]
        if req.t_first_token is None:
            continue
        tok = rq.first_token_t(rec)
        assert tok is not None
        att = rq.attribute_window(rec, req.t_submit, req.t_first_token)
        want = req.t_first_token - req.t_submit
        got = sum(att.values())
        assert abs(got - want) <= max(1e-9, 0.01 * want), (att, want)
        # on the engine side the first decode_gap opens at SAMPLE time,
        # at-or-before the router observes the token
        assert tok <= req.t_first_token
        checked += 1
    assert checked == len(merged) == len(router.finished)


def test_trace_view_cli_gates_merged_story(tmp_path):
    """tools/trace_view.py end-to-end on real fleet dumps: merge, causal
    chain --expect, --require-replicas, the tail report, and the chrome
    export — the exact invocation ci_fast gates the chaos round with."""
    from tools import trace_view

    router, traces = run_traced_fleet(kill_after_tokens=3)
    paths = {name: tr.dump(str(tmp_path / name), reason="test")
             for name, tr in traces.items()}
    argv = sorted(paths.values()) + [
        "--out", str(tmp_path / "merged.jsonl"),
        "--chrome", str(tmp_path / "trace.json"),
        "--slowest", "3",
        "--expect",
        "queue_wait,route,admission_block,prefill_chunks,decode_gap,"
        "requeue_reprefill,route,admission_block,prefill_chunks,"
        "decode_gap,finish",
        "--require-replicas", "2",
    ]
    assert trace_view.main(argv) == 0
    header, records = rq.load_dump(str(tmp_path / "merged.jsonl"))
    assert header["schema"] == rq.MERGED_SCHEMA
    assert len(records) == len(router.finished)
    chrome = json.load(open(tmp_path / "trace.json"))
    assert chrome["traceEvents"], "empty chrome export"
    assert {e["ph"] for e in chrome["traceEvents"]} == {"X"}
    # an impossible chain must FAIL the gate
    bad = sorted(paths.values()) + [
        "--expect", "decode_gap,queue_wait"]
    assert trace_view.main(bad) == 1
