"""Distributed eval (train/evaluation.py, ISSUE 11): the sharded eval
path must be BIT-identical to a serial evaluator on the 8-device CPU
mesh, tick its obs surface, and leave the train loop's step cadence
unperturbed (the note_pause seam)."""

import numpy as np
import optax
import pytest

import jax

from distributed_tensorflow_tpu import obs
from distributed_tensorflow_tpu.models import MLP, MLPConfig, common
from distributed_tensorflow_tpu.obs import flightrec as fr
from distributed_tensorflow_tpu.parallel import (
    MeshSpec, build_mesh, single_device_mesh,
)
from distributed_tensorflow_tpu.train import (
    ShardedEvaluator, callbacks as cb, derive_metrics, init_train_state,
)
from distributed_tensorflow_tpu.train.evaluation import batch_shards


def _mlp_fixture(mesh, hidden=(512, 512), classes=100, dim=64):
    cfg = MLPConfig(hidden_sizes=hidden, num_classes=classes)
    model = MLP(cfg)
    eval_fn = common.classification_eval_fn(model)
    state, _ = init_train_state(
        common.make_init_fn(model, (dim,)), optax.sgd(0.1), mesh,
        jax.random.PRNGKey(0),
    )
    return eval_fn, state


def _batches(n, batch, dim=64, classes=100, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {"image": rng.randn(batch, dim).astype(np.float32),
         "label": rng.randint(0, classes, batch).astype(np.int32)}
        for _ in range(n)
    ]


def test_sharded_eval_bit_identical_to_serial(devices):
    """THE acceptance gate: eval loss (and every summed statistic) from
    the dp8-sharded evaluator equals a serial single-device evaluator
    bit for bit — at a shape where naive GSPMD partitioning provably
    differs in the last ulp (512-wide MLP, batch 256; measured)."""
    mesh8 = build_mesh(MeshSpec(data=8), devices[:8])
    mesh1 = single_device_mesh(devices[0])
    batches = _batches(3, 256)

    eval_fn8, state8 = _mlp_fixture(mesh8)
    evaluator = ShardedEvaluator(eval_fn8, mesh8, registry=obs.Registry())
    sharded = evaluator.run(state8, iter(batches), 3)

    # serial path: same weights on ONE device, same chunks in the same
    # order through the plain per-chunk jit, float64 host accumulation
    eval_fn1, state1 = _mlp_fixture(mesh1)
    chunk_step = jax.jit(
        lambda state, b: eval_fn1(state.params, state.model_state, b))
    shards = batch_shards(mesh8)
    serial: dict = {}
    for batch in batches:
        per = batch["label"].shape[0] // shards
        for s in range(shards):
            chunk = jax.tree.map(
                lambda x: x[s * per:(s + 1) * per], batch)
            chunk = jax.device_put(chunk, devices[0])
            out = chunk_step(state1, chunk)
            for k, v in out.items():
                serial[k] = serial.get(k, 0.0) + np.asarray(v, np.float64)

    assert set(sharded) == set(serial)
    for k in sharded:
        a = np.asarray(sharded[k], np.float64)
        b = np.asarray(serial[k], np.float64)
        assert a.tobytes() == b.tobytes(), (
            f"{k}: sharded {a!r} != serial {b!r} (bitwise)")
    m = derive_metrics(sharded)
    assert m["loss"] == pytest.approx(
        float(serial["loss_sum"] / serial["count"]))


def test_sharded_eval_same_result_across_meshes(devices):
    """The reduction tree is pinned by the program, not the mesh: dp8
    and dp4×tp2 evaluate to the same bits for the same weights."""
    meshes = [build_mesh(MeshSpec(data=8), devices[:8]),
              build_mesh(MeshSpec(data=4, model=2), devices[:8])]
    batches = _batches(2, 128)
    results = []
    for mesh in meshes:
        eval_fn, state = _mlp_fixture(mesh, hidden=(64, 64))
        ev = ShardedEvaluator(eval_fn, mesh, registry=obs.Registry())
        results.append(ev.run(state, iter(batches), 2))
    for k in results[0]:
        a = np.asarray(results[0][k], np.float64)
        b = np.asarray(results[1][k], np.float64)
        assert a.tobytes() == b.tobytes(), f"{k} differs across meshes"


def test_eval_obs_surface(devices):
    """Each eval batch ticks eval_steps_total; each pass brackets its
    batches with eval_start/eval_end in the flight recorder."""
    mesh = build_mesh(MeshSpec(data=8), devices[:8])
    reg = obs.Registry()
    rec = fr.FlightRecorder(capacity=64)
    eval_fn, state = _mlp_fixture(mesh, hidden=(64, 64))
    ev = ShardedEvaluator(eval_fn, mesh, registry=reg, flightrec=rec)
    ev.run(state, iter(_batches(3, 64)), 3, step=7)
    assert reg.get("eval_steps_total").value == 3
    assert fr.contains_in_order(
        rec.events(),
        [("eval_start", {"step": 7, "shards": 8}),
         ("eval_end", {"step": 7, "batches": 3})])
    ev.run(state, iter(_batches(2, 64)), 2)
    assert reg.get("eval_steps_total").value == 5


def test_indivisible_batch_falls_back_flat(devices, caplog):
    """A batch that doesn't divide by the shard count still evaluates
    (flat fallback), with a one-time warning — correct, just outside
    the bit-exact contract."""
    import logging

    mesh = build_mesh(MeshSpec(data=8), devices[:8])
    eval_fn, state = _mlp_fixture(mesh, hidden=(64, 64))
    ev = ShardedEvaluator(eval_fn, mesh, registry=obs.Registry())
    batches = _batches(2, 60)  # 60 % 8 != 0
    with caplog.at_level(logging.WARNING,
                         logger="distributed_tensorflow_tpu.train.evaluation"):
        totals = ev.run(state, iter(batches), 2)
    assert totals["count"] == pytest.approx(120.0)
    assert sum("does not divide" in r.message for r in caplog.records) == 1
    m = derive_metrics(totals)
    assert 0.0 <= m["accuracy"] <= 1.0 and np.isfinite(m["loss"])


def test_note_pause_keeps_cadence_clean():
    """A mid-train eval pause reported through note_pause must not leak
    into train_step_seconds, the productive-seconds ledger, or
    MetricsLogger's steps/sec — the 'eval does not perturb the step
    cadence' half of the distributed-eval contract."""
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    reg = obs.Registry()
    tc = cb.TelemetryCallback(registry=reg, every_n=10**9, clock=clock)
    ml = cb.MetricsLogger(every_n=2, batch_size=10, clock=clock)

    for c in (tc, ml):
        c.on_train_start(None)
    t[0] += 5.0  # compile window
    for c in (tc, ml):
        c.on_step_end(None, 1, {})
    t[0] += 1.0
    for c in (tc, ml):
        c.on_step_end(None, 2, {})
    # eval pause: 3s off the train path between steps 2 and 3
    t[0] += 3.0
    for c in (tc, ml):
        c.note_pause(3.0)
    t[0] += 1.0
    for c in (tc, ml):
        c.on_step_end(None, 3, {})
    t[0] += 1.0
    for c in (tc, ml):
        c.on_step_end(None, 4, {})

    h = reg.get("train_step_seconds")
    assert h.count == 3 and h.sum == pytest.approx(3.0)  # 3 × 1s steps
    assert reg.get("goodput_productive_seconds_total").value == \
        pytest.approx(3.0)
    assert reg.get("wasted_seconds_total",
                   cause="compile_warmup").value == pytest.approx(5.0)
    # MetricsLogger cadence window steps 2→4 spans 5s wall incl. the 3s
    # pause; steps/sec must read 2 steps / 2s of train time
    assert ml.last["steps_per_sec"] == pytest.approx(1.0)


def test_note_pause_rearms_watchdog_and_heartbeat():
    """The pause protocol reaches the liveness observers too: a
    finished eval re-arms the Watchdog beat (no stall abort right after
    a long eval) and writes a heartbeat (the fleet monitor's silent
    window ends at the pause boundary)."""
    t = [0.0]
    w = cb.Watchdog(budget_s=5.0, clock=lambda: t[0],
                    registry=obs.Registry(), poll_s=1000.0)
    w.on_train_start(None)
    try:
        t[0] = 10.0  # eval pause longer than the budget just ended
        w.note_pause(10.0)
        with w._lock:
            assert w._beat == 10.0  # budget clock restarted at pause end
    finally:
        w.on_train_end(None)

    class FakeWriter:
        calls = 0

        def beat(self, **kw):
            FakeWriter.calls += 1

    hb = cb.HeartbeatCallback(FakeWriter())
    hb.note_pause(3.0)
    assert FakeWriter.calls == 1


def test_note_pause_inside_warmup_window():
    """A pause before the first completed step must stay out of the
    compile_warmup waste bucket too."""
    t = [0.0]
    reg = obs.Registry()
    tc = cb.TelemetryCallback(registry=reg, every_n=10**9,
                              clock=lambda: t[0])
    tc.on_train_start(None)
    t[0] += 4.0
    tc.note_pause(3.0)
    t[0] += 1.0
    tc.on_step_end(None, 1, {})
    assert reg.get("wasted_seconds_total",
                   cause="compile_warmup").value == pytest.approx(2.0)


def test_runner_eval_paths_use_sharded_evaluator(devices, tmp_path):
    """The runner's standalone eval-from-checkpoint flows through the
    distributed evaluator and agrees with the live-trainer eval it
    checkpointed from (both sharded, same reduction)."""
    from distributed_tensorflow_tpu import workloads

    overrides = [
        "--train.num_steps=6", "--train.log_every=3",
        "--train.eval_batches=2", "--data.global_batch_size=64",
        f"--checkpoint.directory={tmp_path}/ck",
        "--checkpoint.save_interval_steps=5",
        "--checkpoint.async_save=false",
        "--checkpoint.save_on_preemption=false",
    ]
    result = workloads.run_workload("mnist_mlp", overrides)
    mod = workloads.get("mnist_mlp")
    cfg = mod.default_config()
    from distributed_tensorflow_tpu.utils import config as config_lib

    cfg = config_lib.apply_overrides(cfg, overrides)
    again = workloads.evaluate_from_checkpoint(cfg, mod.build)
    assert again["step"] == 6
    assert again["loss"] == pytest.approx(result.eval_metrics["loss"])
