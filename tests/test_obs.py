"""obs/ subsystem tests: registry merge semantics, histogram percentile
accuracy against numpy quantiles, Prometheus render invariants, span
tracing, JSONL export, and the serve-engine telemetry acceptance gate
(ISSUE 2: sum of latency-histogram counts == finished requests)."""

import json
import re
import urllib.request

import numpy as np
import pytest

from distributed_tensorflow_tpu import obs
from distributed_tensorflow_tpu.obs import registry as reg_lib


# ---------------------------------------------------------------------------
# Registry primitives
# ---------------------------------------------------------------------------


def test_get_or_create_identity_and_type_conflict():
    r = obs.Registry()
    c1 = r.counter("requests_total", "help text")
    c2 = r.counter("requests_total")
    assert c1 is c2
    # distinct label sets are distinct children
    a = r.counter("finished_total", reason="eos")
    b = r.counter("finished_total", reason="max_len")
    assert a is not b
    with pytest.raises(ValueError):
        r.gauge("requests_total")  # name already a counter
    with pytest.raises(ValueError):
        r.counter("0bad name")


def test_counter_and_gauge_semantics():
    r = obs.Registry()
    c = r.counter("c_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("g")
    g.set(7)
    g.set(3)
    assert g.value == 3.0


def test_histogram_observe_and_bucket_edges():
    r = obs.Registry()
    h = r.histogram("h_seconds", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 1.0, 5.0, 100.0, 1e6):  # 1.0 and 100.0 land ON bounds
        h.observe(v)
    assert h.counts.tolist() == [2, 1, 1, 1]  # le semantics + overflow
    assert h.count == 5
    assert h.sum == pytest.approx(0.5 + 1 + 5 + 100 + 1e6)
    with pytest.raises(ValueError):
        r.histogram("h_seconds", buckets=(1.0, 2.0))  # bucket mismatch
    with pytest.raises(ValueError):
        obs.Histogram("bad", buckets=())


def test_histogram_percentiles_match_numpy_quantiles():
    """Log-bucket read-back must sit within one bucket ratio of the true
    quantile, for distributions spanning several decades."""
    rng = np.random.RandomState(7)
    per_decade = 20
    ratio = 10 ** (1 / per_decade)
    buckets = obs.log_buckets(1e-5, 10.0, per_decade=per_decade)
    for vals in (
        rng.lognormal(-5.0, 1.5, 20_000),
        rng.exponential(0.01, 20_000),
        np.abs(rng.normal(0.001, 0.0005, 20_000)) + 1e-5,
    ):
        h = obs.Histogram("lat_seconds", buckets=buckets)
        for v in vals:
            h.observe(v)
        for q in (0.1, 0.5, 0.9, 0.99):
            est = h.percentile(q)
            true = float(np.quantile(vals, q))
            assert est == pytest.approx(true, rel=ratio - 1 + 0.01), (
                f"q={q}: est {est} vs numpy {true}"
            )


def test_histogram_percentile_edges():
    h = obs.Histogram("h", buckets=(1.0, 2.0))
    assert np.isnan(h.percentile(0.5))  # empty
    h.observe(100.0)  # overflow-only
    assert h.percentile(0.5) == 2.0  # floor: last finite bound
    with pytest.raises(ValueError):
        h.percentile(1.5)


def test_registry_merge_semantics():
    """Counters/histograms add; gauges take the freshest write; missing
    metrics are adopted as independent copies."""
    a, b = obs.Registry(), obs.Registry()
    a.counter("c_total").inc(2)
    b.counter("c_total").inc(5)
    ha = a.histogram("h", buckets=(1.0, 2.0))
    hb = b.histogram("h", buckets=(1.0, 2.0))
    ha.observe(0.5)
    hb.observe(1.5)
    hb.observe(10.0)
    a.gauge("g").set(1.0)
    gb = b.gauge("g")
    gb.set(9.0)
    gb.set(2.0)  # b wrote twice → fresher than a's single write
    b.counter("only_in_b_total").inc(3)

    a.merge(b)
    assert a.get("c_total").value == 7
    assert a.get("h").counts.tolist() == [1, 1, 1]
    assert a.get("h").sum == pytest.approx(12.0)
    assert a.get("g").value == 2.0
    assert a.get("only_in_b_total").value == 3
    # adoption copies — mutating the source must not alias
    b.counter("only_in_b_total").inc()
    assert a.get("only_in_b_total").value == 3

    # merged-in-both == observed-in-one: sufficient-statistic exactness
    c = obs.Registry()
    hc = c.histogram("h", buckets=(1.0, 2.0))
    for v in (0.5, 1.5, 10.0):
        hc.observe(v)
    assert hc.counts.tolist() == a.get("h").counts.tolist()

    with pytest.raises(ValueError):
        ha.merge_from(obs.Histogram("h", buckets=(1.0, 3.0)))


def test_gauge_repeated_merge_from_live_source():
    """Scrape-aggregator pattern: merging the SAME live registry
    repeatedly must keep tracking fresh gauge writes (seq must not
    inflate past the source's)."""
    host, agg = obs.Registry(), obs.Registry()
    g = host.gauge("occ")
    for v in (1.0, 2.0, 3.0):
        g.set(v)
        g.set(v * 10)  # two writes per cycle: seq grows faster than 1
        agg.merge(host)
        assert agg.get("occ").value == v * 10


def test_render_survives_non_finite_values():
    """A diverged-loss gauge must not kill the scrape endpoint."""
    r = obs.Registry()
    r.gauge("train_loss").set(float("nan"))
    r.gauge("g_inf").set(float("inf"))
    r.gauge("g_ninf").set(float("-inf"))
    text = obs.render(r)
    assert "train_loss NaN" in text
    assert "g_inf +Inf" in text and "g_ninf -Inf" in text


def test_snapshot_consistent_under_concurrent_merge():
    """Regression (ISSUE 6 satellite): a snapshot taken while a merge is
    in flight must be a consistent cut. ``merge`` mutates a histogram's
    ``counts`` then ``sum`` under the registry lock; ``snapshot`` now
    reads under the same lock, so it can never capture the counts of
    merge k and the sum of merge k-1. Every source observation is 1.0,
    so consistency is exactly ``sum == count`` in every snapshot. The
    bucket array is wide enough that the numpy ``counts +=`` releases
    the GIL — the lock-free-snapshot tear reproduces within ~1000
    merges on this shape, so this test genuinely detects a revert."""
    import threading

    src = obs.Registry()
    wide = tuple(float(x) for x in np.linspace(1e-3, 1e3, 100_000))
    hs = src.histogram("h_seconds", buckets=wide)
    hs.observe(1.0)
    src.counter("c_total").inc(1)

    dst = obs.Registry()
    stop = threading.Event()
    torn: list[dict] = []

    def snapshotter():
        while not stop.is_set():
            snap = dst.snapshot()
            h = snap.get("h_seconds")
            if h is not None and h["sum"] != float(h["count"]):
                torn.append(h)
                return

    t = threading.Thread(target=snapshotter)
    t.start()
    for _ in range(1500):
        dst.merge(src)
    stop.set()
    t.join()
    assert not torn, f"torn histogram snapshot: {torn[:1]}"
    assert dst.get("h_seconds").count == 1500
    assert dst.get("c_total").value == 1500.0


def test_registry_delta_semantics():
    """delta(snapshot) isolates an interval without reset():
    counters/histograms diff, gauges report current value, unchanged
    metrics are omitted, unseen metrics diff against zero."""
    r = obs.Registry()
    c = r.counter("c_total")
    c.inc(2)
    h = r.histogram("h_seconds", buckets=(1.0, 2.0))
    h.observe(0.5)
    g = r.gauge("g")
    g.set(1.0)
    r.counter("quiet_total").inc(7)  # untouched after the baseline

    snap = r.snapshot()
    c.inc(3)
    h.observe(1.5)
    h.observe(10.0)
    g.set(4.0)
    r.counter("born_later_total", x="1").inc()

    d = r.delta(snap)
    assert d["c_total"] == {"kind": "counter", "value": 3.0}
    assert d["h_seconds"]["counts"] == [0, 1, 1]
    assert d["h_seconds"]["count"] == 2
    assert d["h_seconds"]["sum"] == pytest.approx(11.5)
    assert d["g"] == {"kind": "gauge", "value": 4.0}
    assert d["born_later_total{x=1}"] == {"kind": "counter", "value": 1.0}
    assert "quiet_total" not in d
    # a quiet interval yields an empty delta
    assert r.delta(r.snapshot()) == {}
    # the live registry is untouched: no reset happened
    assert r.get("c_total").value == 5.0
    assert r.get("quiet_total").value == 7.0


def test_registry_delta_rejects_unrelated_baseline():
    """A baseline the live registry is BEHIND (reset() intervened, or it
    came from another registry) must raise, not emit negative rates."""
    r = obs.Registry()
    r.counter("c_total").inc(5)
    snap = r.snapshot()
    r.reset()
    r.counter("c_total").inc(1)
    with pytest.raises(ValueError, match="went down"):
        r.delta(snap)

    r2 = obs.Registry()
    r2.histogram("h_seconds", buckets=(1.0, 2.0)).observe(0.5)
    snap2 = r2.snapshot()
    r2.reset()
    with pytest.raises(ValueError, match="shrank"):
        r2.delta(snap2)

    r3 = obs.Registry()
    r3.gauge("x")
    with pytest.raises(ValueError, match="kind mismatch"):
        r3.delta({"x": {"kind": "counter", "value": 0.0}})


def test_delta_consistent_under_concurrent_merge():
    """Companion to the snapshot-tear regression above: ``delta`` reads
    the live table under the registry lock, so a delta taken while
    merges are in flight must also be a consistent cut — every source
    observation is 1.0, so consistency is exactly ``sum == count`` in
    every delta the reader computes."""
    import threading

    src = obs.Registry()
    wide = tuple(float(x) for x in np.linspace(1e-3, 1e3, 100_000))
    hs = src.histogram("h_seconds", buckets=wide)
    hs.observe(1.0)

    dst = obs.Registry()
    for _ in range(100):
        dst.merge(src)
    baseline = dst.snapshot()

    stop = threading.Event()
    torn: list[dict] = []

    def reader():
        while not stop.is_set():
            d = dst.delta(baseline)
            h = d.get("h_seconds")
            if h is not None and h["sum"] != float(h["count"]):
                torn.append(h)
                return

    t = threading.Thread(target=reader)
    t.start()
    for _ in range(1000):
        dst.merge(src)
    stop.set()
    t.join()
    assert not torn, f"torn histogram delta: {torn[:1]}"
    assert dst.delta(baseline)["h_seconds"]["count"] == 1000


def test_registry_reset_keeps_handles():
    r = obs.Registry()
    c, h, g = r.counter("c_total"), r.histogram("h"), r.gauge("g")
    c.inc(5)
    h.observe(0.1)
    g.set(4)
    r.reset()
    assert c.value == 0 and h.count == 0 and h.sum == 0 and g.value == 0
    c.inc()  # same handle still registered
    assert r.get("c_total").value == 1


# ---------------------------------------------------------------------------
# Prometheus render
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [-+0-9.eE]+$|^# (HELP|TYPE) .+$"
)


def test_render_format_and_invariants():
    r = obs.Registry()
    r.counter("req_total", "requests").inc(3)
    r.gauge("occ", "occupancy").set(0.5)
    h = r.histogram("lat_seconds", "latency", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.05, 0.5):
        h.observe(v)
    r.counter("fin_total", reason='we"ird\\label').inc()

    text = obs.render(r)
    lines = text.splitlines()
    assert text.endswith("\n")
    for line in lines:
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
    assert "# TYPE req_total counter" in lines
    assert "# TYPE lat_seconds histogram" in lines
    assert "req_total 3" in lines
    # buckets are CUMULATIVE and end at +Inf == count
    cums = [
        int(m.group(1))
        for m in re.finditer(r'lat_seconds_bucket\{le="[^"]+"\} (\d+)', text)
    ]
    assert cums == sorted(cums) and cums[-1] == 4
    assert "lat_seconds_count 4" in lines
    # label escaping survives
    assert 'reason="we\\"ird\\\\label"' in text
    # HELP/TYPE emitted once per name even with label children
    assert text.count("# TYPE fin_total") == 1


def test_http_scrape_endpoint():
    r = obs.Registry()
    r.counter("hits_total").inc(2)
    server = obs.serve_http(r, port=0)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert "hits_total 2" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5
            )
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------


def test_tracer_nesting_and_registry_feed():
    fake_t = [0.0]

    def clock():
        fake_t[0] += 1.0
        return fake_t[0]

    r = obs.Registry()
    tr = obs.Tracer(registry=r, annotate=False, clock=clock)
    with tr.span("step"):
        assert tr.current_path == "step"
        with tr.span("prefill"):
            assert tr.current_path == "step.prefill"
    assert tr.current_path == ""
    # inner closes first; durations from the fake clock are exact
    assert [(s.path, s.depth, s.duration) for s in tr.events] == [
        ("step.prefill", 1, 1.0),
        ("step", 0, 3.0),
    ]
    from distributed_tensorflow_tpu.obs.trace import SPAN_HISTOGRAM

    assert r.get(SPAN_HISTOGRAM, span="step.prefill").count == 1
    assert r.get(SPAN_HISTOGRAM, span="step").count == 1


def test_tracer_records_on_exception_and_bounds_events():
    tr = obs.Tracer(annotate=False, max_events=2)
    with pytest.raises(RuntimeError):
        with tr.span("dies"):
            raise RuntimeError("boom")
    assert tr.events[-1].name == "dies"
    assert tr.current_path == ""  # stack unwound
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.events) == 2 and tr.dropped == 4


def test_tracer_annotate_passthrough_smoke():
    """annotate=True must work whether or not a jax profiler trace is
    active (TraceAnnotation is a no-op outside an active trace)."""
    tr = obs.Tracer(annotate=True)
    with tr.span("annotated"):
        pass
    assert tr.events[-1].path == "annotated"


# ---------------------------------------------------------------------------
# JSONL export
# ---------------------------------------------------------------------------


def test_jsonl_logger_events_and_snapshot(tmp_path):
    r = obs.Registry()
    r.counter("c_total").inc(4)
    r.histogram("h", buckets=(1.0,)).observe(0.5)
    path = str(tmp_path / "events.jsonl")
    # single-process test rig: this process IS the chief, so chief_only
    # stays enabled — the gating path itself is exercised either way
    with obs.JsonlLogger(path, r, clock=lambda: 123.0) as jl:
        assert jl.enabled
        jl.event("admitted", uid=7)
        jl.write_snapshot(step=10)
    recs = [json.loads(line) for line in open(path)]
    assert [rec["event"] for rec in recs] == ["admitted", "snapshot"]
    assert recs[0] == {"t": 123.0, "event": "admitted", "uid": 7}
    snap = recs[1]["metrics"]
    assert snap["c_total"] == {"kind": "counter", "value": 4.0}
    assert snap["h"]["counts"] == [1, 0] and recs[1]["step"] == 10


def test_jsonl_logger_disabled_noop(tmp_path, monkeypatch):
    from distributed_tensorflow_tpu.parallel import cluster

    monkeypatch.setattr(cluster, "is_chief", lambda: False)
    path = str(tmp_path / "nothing.jsonl")
    with obs.JsonlLogger(path, obs.Registry()) as jl:
        assert not jl.enabled
        jl.event("dropped")
        jl.write_snapshot()
    import os

    assert not os.path.exists(path)


# ---------------------------------------------------------------------------
# Serve-engine telemetry (the ISSUE 2 acceptance gate)
# ---------------------------------------------------------------------------


def test_serve_engine_telemetry_counts_and_render():
    """A drained ServeEngine run yields non-empty TTFT / per-token
    histograms whose counts equal the finished-request count, and the
    registry renders valid Prometheus exposition."""
    from distributed_tensorflow_tpu import serve
    from distributed_tensorflow_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64, max_len=48, num_layers=1, d_model=16, num_heads=2,
        d_ff=32, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )
    reg = obs.Registry()
    eng = serve.ServeEngine.with_random_params(cfg, num_slots=2, registry=reg)
    assert eng.registry is reg
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]]  # forces queueing
    for p in prompts:
        eng.submit(p, max_new_tokens=5)
    done = eng.run()
    assert len(done) == len(prompts)

    ttft = reg.get("serve_ttft_seconds")
    tpot = reg.get("serve_tpot_seconds")
    qwait = reg.get("serve_queue_wait_seconds")
    finished = sum(
        m.value for m in reg.collect() if m.name == "serve_finished_total"
    )
    assert ttft.count == len(prompts) and ttft.sum > 0
    assert tpot.count == len(prompts)
    assert qwait.count == len(prompts)
    assert int(finished) == len(prompts)
    assert reg.get("serve_finished_total",
                   reason="max_new_tokens").value == len(prompts)
    assert reg.get("serve_admitted_total").value == len(prompts)
    # every generated token was counted
    total_toks = sum(len(r.generated) for r in done.values())
    assert reg.get("serve_tokens_total").value == total_toks
    assert reg.get("serve_step_seconds").count > 0
    assert 0 < reg.get("serve_occupancy").value <= 1.0
    # TTFT >= queue wait for every request → also true of the sums
    assert ttft.sum >= qwait.sum

    text = obs.render(reg)
    assert "# TYPE serve_ttft_seconds histogram" in text
    assert 'serve_finished_total{reason="max_new_tokens"} 4' in text
    for line in text.splitlines():
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"


def test_serve_step_stats_timing_split():
    """StepStats carries the prefill/decode wall split; registry reset
    drops warmup observations but keeps recording (the bench contract)."""
    from distributed_tensorflow_tpu import serve
    from distributed_tensorflow_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64, max_len=48, num_layers=1, d_model=16, num_heads=2,
        d_ff=32, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )
    eng = serve.ServeEngine.with_random_params(cfg, num_slots=2)
    eng.submit([1, 2, 3], max_new_tokens=3)
    first = eng.step()
    assert first.admitted == 1
    assert first.wall_s >= first.prefill_s + first.decode_s - 1e-6
    assert first.prefill_s > 0 and first.decode_s > 0

    eng.run()
    eng.registry.reset()
    assert eng.registry.get("serve_ttft_seconds").count == 0
    eng.submit([4, 5], max_new_tokens=2)
    eng.run()
    assert eng.registry.get("serve_ttft_seconds").count == 1
