"""Independent cross-framework oracle: a from-scratch torch (CPU)
reimplementation of the transformer block math, fed the SAME weights as
the flax model. The in-repo parity tests compare flax twins that share
module code, so a systematic error in the shared code (masking sign,
softmax axis, GELU flavor, residual order) would cancel out; torch's
independent kernels cannot share such a bug.

Matching contract: flax nn.gelu defaults to the tanh approximation;
LayerNorm eps follows flax's 1e-6 default; attention uses 1/sqrt(D)
scaling with pre-softmax additive masking. Post-LN (BERT) arrangement.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from distributed_tensorflow_tpu.models import transformer as tfm


def _t(x):
    return torch.from_numpy(np.asarray(x, np.float32))


def _torch_attention(a, xn, B, S, H, D, logit_mask):
    """q/k/v projection + scaled softmax attention + merge + out-proj,
    from the flax ``attn`` param subtree. ``logit_mask`` is an additive
    [.., S, S]-broadcastable tensor (0 = keep, -1e9 = drop) — the single
    spot where the encoder-padding and causal variants differ."""
    split = lambda t: t.reshape(B, S, H, D).permute(0, 2, 1, 3)
    q = split(xn @ _t(a["query"]["kernel"]) + _t(a["query"]["bias"]))
    k = split(xn @ _t(a["key"]["kernel"]) + _t(a["key"]["bias"]))
    v = split(xn @ _t(a["value"]["kernel"]) + _t(a["value"]["bias"]))
    logits = (q @ k.transpose(-1, -2)) / (D ** 0.5)
    if logit_mask is not None:
        logits = logits + logit_mask
    out = torch.softmax(logits, dim=-1) @ v
    out = out.permute(0, 2, 1, 3).reshape(B, S, H * D)
    return out @ _t(a["attn_out"]["kernel"]) + _t(a["attn_out"]["bias"])


def _perturb(params, seed):
    """Move params off their init values so LN scales/biases and the
    zero-init heads carry signal in the comparison."""
    leaves, tree = jax.tree.flatten(params)
    keys = jax.random.split(jax.random.PRNGKey(seed), len(leaves))
    return jax.tree.unflatten(tree, [
        l + 0.05 * jax.random.normal(k, l.shape, l.dtype)
        for l, k in zip(leaves, keys)
    ])


def torch_block(p, x, cfg, mask=None):
    """One post-LN encoder block in pure torch, weights from the flax
    param subtree ``p`` (layer_i)."""
    F = torch.nn.functional
    B, S, d = x.shape
    H, D = cfg.num_heads, cfg.d_model // cfg.num_heads

    logit_mask = None
    if mask is not None:
        logit_mask = torch.where(_t(mask)[:, None, None, :] > 0, 0.0, -1e9)
    out = _torch_attention(p["attn"], x, B, S, H, D, logit_mask)
    x = F.layer_norm(
        x + out, (d,), _t(p["ln1"]["scale"]), _t(p["ln1"]["bias"]),
        eps=1e-6,
    )
    h = x @ _t(p["mlp_in"]["kernel"]) + _t(p["mlp_in"]["bias"])
    h = F.gelu(h, approximate="tanh")  # flax nn.gelu default flavor
    h = h @ _t(p["mlp_out"]["kernel"]) + _t(p["mlp_out"]["bias"])
    return F.layer_norm(
        x + h, (d,), _t(p["ln2"]["scale"]), _t(p["ln2"]["bias"]),
        eps=1e-6,
    )


def torch_bert_forward(params, ids, cfg, mask=None):
    emb = _t(params["tok_embed"]["embedding"])
    x = emb[torch.from_numpy(np.asarray(ids))]
    x = x + _t(params["pos_embed"])[None, : ids.shape[1]]
    x = torch.nn.functional.layer_norm(
        x, (cfg.d_model,), _t(params["embed_ln"]["scale"]),
        _t(params["embed_ln"]["bias"]), eps=1e-6,
    )
    for i in range(cfg.num_layers):
        x = torch_block(params[f"layer_{i}"], x, cfg, mask)
    x = x @ _t(params["mlm_transform"]["kernel"]) + _t(
        params["mlm_transform"]["bias"])
    x = torch.nn.functional.gelu(x, approximate="tanh")
    x = torch.nn.functional.layer_norm(
        x, (cfg.d_model,), _t(params["mlm_ln"]["scale"]),
        _t(params["mlm_ln"]["bias"]), eps=1e-6,
    )
    return x @ emb.T + _t(params["mlm_bias"])


@pytest.mark.slow
@pytest.mark.parametrize("masked", [False, True])
def test_flax_bert_matches_independent_torch(masked):
    cfg = tfm.TransformerConfig(
        vocab_size=96, max_len=24, num_layers=2, d_model=32, num_heads=4,
        d_ff=64, dropout=0.0, causal=False, pre_ln=False, dtype="float32",
        attention_impl="dense",
    )
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 24)(jax.random.PRNGKey(2))
    params = _perturb(params, 5)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (3, 24)).astype(np.int32)
    mask = None
    if masked:
        mask = np.ones((3, 24), np.int32)
        mask[:, -5:] = 0
    want = torch_bert_forward(
        jax.device_get(params), ids, cfg, mask
    ).detach().numpy()
    got = np.asarray(model.apply(
        {"params": params}, jnp.asarray(ids),
        jnp.asarray(mask) if mask is not None else None, train=False,
    ))
    if masked:
        # masked-out positions' logits may differ (both arbitrary);
        # compare real positions only
        got, want = got[:, :-5], want[:, :-5]
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def torch_gpt_forward(params, ids, cfg):
    """Pre-LN causal decoder in pure torch (gpt_small arrangement):
    x + attn(ln1(x)), x + mlp(ln2(x)), final_ln, tied head."""
    F = torch.nn.functional
    emb = _t(params["tok_embed"]["embedding"])
    x = emb[torch.from_numpy(np.asarray(ids))]
    x = x + _t(params["pos_embed"])[None, : ids.shape[1]]
    B, S, d = x.shape
    H, D = cfg.num_heads, cfg.d_model // cfg.num_heads
    causal = torch.tril(torch.ones(S, S, dtype=torch.bool))
    causal_mask = torch.where(causal, 0.0, -1e9)
    for i in range(cfg.num_layers):
        p = params[f"layer_{i}"]
        xn = F.layer_norm(x, (d,), _t(p["ln1"]["scale"]),
                          _t(p["ln1"]["bias"]), eps=1e-6)
        x = x + _torch_attention(p["attn"], xn, B, S, H, D, causal_mask)
        hn = F.layer_norm(x, (d,), _t(p["ln2"]["scale"]),
                          _t(p["ln2"]["bias"]), eps=1e-6)
        h = hn @ _t(p["mlp_in"]["kernel"]) + _t(p["mlp_in"]["bias"])
        h = F.gelu(h, approximate="tanh")
        x = x + (h @ _t(p["mlp_out"]["kernel"]) + _t(p["mlp_out"]["bias"]))
    x = F.layer_norm(x, (d,), _t(params["final_ln"]["scale"]),
                     _t(params["final_ln"]["bias"]), eps=1e-6)
    return x @ emb.T + _t(params["mlm_bias"])


@pytest.mark.slow
def test_flax_gpt_matches_independent_torch():
    """Pre-LN CAUSAL decoder vs the independent torch oracle — catches
    causal-mask offset/sign errors the flax twins share by construction."""
    cfg = tfm.TransformerConfig(
        vocab_size=96, max_len=24, num_layers=2, d_model=32, num_heads=4,
        d_ff=64, dropout=0.0, causal=True, pre_ln=True, dtype="float32",
        attention_impl="dense",
    )
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 24)(jax.random.PRNGKey(3))
    params = _perturb(params, 7)
    ids = np.random.RandomState(1).randint(
        0, cfg.vocab_size, (3, 24)).astype(np.int32)
    want = torch_gpt_forward(jax.device_get(params), ids, cfg
                             ).detach().numpy()
    got = np.asarray(model.apply(
        {"params": params}, jnp.asarray(ids), None, train=False))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def _same_pad(x, k, s):
    """TPU/flax 'SAME' padding for a [N,C,H,W] torch tensor."""
    H = x.shape[-1]
    total = max((-(H // -s) - 1) * s + k - H, 0)
    lo, hi = total // 2, total - total // 2
    return torch.nn.functional.pad(x, (lo, hi, lo, hi))


def _torch_conv(x, kernel, s):
    """flax nn.Conv(use_bias=False, padding='SAME') in torch NCHW:
    kernel comes in flax [H, W, I, O] layout."""
    w = _t(kernel).permute(3, 2, 0, 1)
    return torch.nn.functional.conv2d(_same_pad(x, w.shape[-1], s), w,
                                      stride=s)


def _torch_bn(x, p, eps):
    """Train-mode BatchNorm: normalize with the batch's biased stats —
    same as flax nn.BatchNorm(use_running_average=False)."""
    return torch.nn.functional.batch_norm(
        x, None, None, weight=_t(p["scale"]), bias=_t(p["bias"]),
        training=True, eps=eps)


def test_flax_resnet_bottleneck_matches_independent_torch():
    """The flagship's bottleneck block vs a from-scratch torch NCHW
    reimplementation fed the same weights: conv kernel layout (HWIO vs
    OIHW), SAME padding under the v1.5 strided 3x3, train-mode BN
    normalization, projection shortcut, residual+relu order. The in-repo
    fused-vs-standard twins share flax module code; torch's independent
    conv/BN kernels cannot share a systematic bug with them."""
    from distributed_tensorflow_tpu.models.resnet import (
        BottleneckBlock, ResNetConfig,
    )

    cfg = ResNetConfig(dtype="float32")
    block = BottleneckBlock(filters=8, strides=2, cfg=cfg)
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(2, 8, 8, 16), jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x, train=True)
    # perturb away from init: bn3's zero-init scale would silence the
    # whole residual branch and make the comparison vacuous
    leaves, treedef = jax.tree.flatten(variables["params"])
    keys = jax.random.split(jax.random.PRNGKey(7), len(leaves))
    params = jax.tree.unflatten(treedef, [
        l + 0.05 * jax.random.normal(k, l.shape, l.dtype)
        for l, k in zip(leaves, keys)
    ])

    got, _ = block.apply(
        {"params": params, "batch_stats": variables["batch_stats"]},
        x, train=True, mutable=["batch_stats"],
    )

    p = jax.device_get(params)
    eps = cfg.bn_epsilon
    xt = _t(np.asarray(x)).permute(0, 3, 1, 2)
    y = torch.relu(_torch_bn(_torch_conv(xt, p["conv1"]["kernel"], 1),
                             p["bn1"], eps))
    y = torch.relu(_torch_bn(_torch_conv(y, p["conv2"]["kernel"], 2),
                             p["bn2"], eps))
    y = _torch_bn(_torch_conv(y, p["conv3"]["kernel"], 1), p["bn3"], eps)
    res = _torch_bn(_torch_conv(xt, p["proj_conv"]["kernel"], 2),
                    p["proj_bn"], eps)
    want = torch.relu(res + y).permute(0, 2, 3, 1).numpy()

    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)
