"""Fleet observatory (obs/fleetview.py): snapshot export crash-safety,
Registry.from_snapshot reconstruction, merge-not-average aggregation —
including the proof that fleet-merged histogram p99 equals the p99 of
the union stream (to bucket resolution) while averaging per-worker p99s
provably does not — own-clock staleness, and the causally merged
cross-worker timeline with its anchor must-fail cases."""

import json
import os

import numpy as np
import pytest

from distributed_tensorflow_tpu.obs import fleetview as fv
from distributed_tensorflow_tpu.obs import flightrec as fr
from distributed_tensorflow_tpu.obs import goodput
from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.resilience import FaultClock


# ---------------------------------------------------------------------------
# Registry.from_snapshot — the cross-process half of the merge contract
# ---------------------------------------------------------------------------


def _sample_registry() -> Registry:
    r = Registry()
    r.counter("c_total", "plain").inc(3)
    r.counter("family_total", "labeled", cause="x").inc(2)
    r.counter("family_total", "labeled", cause="y").inc(5)
    r.gauge("g", "gauge").set(0.5)
    h = r.histogram("h_seconds", "seconds")
    for v in (1e-3, 2e-3, 5.0):
        h.observe(v)
    return r


def test_from_snapshot_roundtrips_exactly():
    r = _sample_registry()
    snap = r.snapshot()
    # through JSON, as the fleet actually receives it
    back = Registry.from_snapshot(json.loads(json.dumps(snap)))
    assert back.snapshot() == snap


def test_from_snapshot_adds_labels_and_filters_kinds():
    r = _sample_registry()
    snap = r.snapshot()
    back = Registry.from_snapshot(snap, labels={"worker": "3"})
    assert back.get("c_total", worker="3").value == 3
    assert back.get("family_total", cause="x", worker="3").value == 2
    assert back.get("h_seconds", worker="3").count == 3
    only = Registry.from_snapshot(snap, kinds=("counter", "histogram"))
    assert only.get("g") is None
    assert only.get("c_total").value == 3


def test_from_snapshot_rejects_malformed():
    with pytest.raises(ValueError, match="malformed snapshot entry"):
        Registry.from_snapshot({"x": {"kind": "counter"}})  # no value
    with pytest.raises(ValueError, match="malformed snapshot entry"):
        Registry.from_snapshot(
            {"h": {"kind": "histogram", "bounds": [1.0, 2.0],
                   "counts": [1, 2], "sum": 3.0}})  # counts != bounds+1
    with pytest.raises(ValueError, match="unknown metric kind"):
        Registry.from_snapshot({"x": {"kind": "summary", "value": 1}})


# ---------------------------------------------------------------------------
# THE aggregation-soundness claim: merged p99 == union p99, != avg of p99s
# ---------------------------------------------------------------------------


def test_merged_histogram_p99_is_union_p99_not_average_of_p99s():
    """docs/observability.md promises: "a fleet aggregator that merges
    per-host snapshots and takes p99 gets the true fleet p99 (to bucket
    resolution), which averaging per-host p99s can never give". Prove
    both halves: (a) the merged histogram's percentile is EXACTLY the
    percentile of a histogram fed the union stream (same buckets →
    identical counts → identical read-back, no extra resolution loss),
    and (b) it is within one bucket ratio of the true union quantile,
    while the average of per-worker p99s is off by far more than one
    bucket ratio on a skewed fleet."""
    fast = [1e-3] * 99 + [10.0]          # worker 0: fast, one straggler
    slow = [10.0] * 99 + [1e-3]          # worker 1: slow, one fast
    regs = []
    for values in (fast, slow):
        r = Registry()
        h = r.histogram("train_step_seconds", "seconds")
        for v in values:
            h.observe(v)
        regs.append(r)

    merged = Registry()
    for r in regs:
        merged.merge(Registry.from_snapshot(
            json.loads(json.dumps(r.snapshot()))))
    union = Registry()
    hu = union.histogram("train_step_seconds", "seconds")
    for v in fast + slow:
        hu.observe(v)

    hm = merged.get("train_step_seconds")
    assert hm.counts.tolist() == hu.counts.tolist()
    assert hm.percentile(0.99) == hu.percentile(0.99)  # exact, not approx

    bucket_ratio = 10 ** (1 / 8)  # LATENCY_BUCKETS: 8 buckets/decade
    true_p99 = float(np.quantile(np.asarray(fast + slow), 0.99))
    assert true_p99 / bucket_ratio <= hm.percentile(0.99) \
        <= true_p99 * bucket_ratio

    avg_of_p99s = float(np.mean(
        [r.get("train_step_seconds").percentile(0.99) for r in regs]))
    # ~ (0.001 + 10) / 2 ≈ 5 vs a true p99 of 10: off by ~2x, far past
    # one bucket ratio (~1.33) — averaging percentiles is not a quantile
    assert avg_of_p99s < true_p99 / (bucket_ratio ** 2)


# ---------------------------------------------------------------------------
# Snapshot exporter: schema, rate limit, crash safety
# ---------------------------------------------------------------------------


def _exporter(tmp_path, clk, **kw):
    reg = Registry()
    rec = FlightRecorder(clock=clk)
    exp = fv.SnapshotExporter(
        fv.fleetsnap_path(str(tmp_path), 0), worker=0, incarnation=2,
        registry=reg, flightrec=rec, clock=clk, **kw)
    return exp, reg, rec


def test_exporter_writes_valid_schema_and_counts(tmp_path):
    clk = FaultClock(7.0)
    exp, reg, rec = _exporter(tmp_path, clk)
    rec.emit("train_start", step=0)
    path = exp.export(step=4, phase="train")
    snap = fv.read_snapshot(path)
    assert fv.validate_snapshot(snap, expect_worker=0) == []
    assert (snap["worker"], snap["incarnation"], snap["seq"]) == (0, 2, 1)
    assert snap["step"] == 4 and snap["t"] == 7.0
    assert snap["registry"][
        'fleetsnap_exports_total{worker=0}']["value"] == 1
    kinds = [e["kind"] for e in snap["flightrec_tail"]]
    assert kinds == ["train_start", "fleetsnap_export"]
    assert not os.path.exists(path + ".tmp")  # atomic: tmp never lingers


def test_exporter_rate_limit_on_injected_clock(tmp_path):
    clk = FaultClock()
    exp, _, _ = _exporter(tmp_path, clk, min_interval_s=10.0)
    assert exp.export(step=1) is not None
    assert exp.export(step=2) is None          # inside the window
    assert exp.export(step=2, force=True) is not None  # bypass
    clk.advance(11.0)
    assert exp.export(step=3) is not None
    snap = fv.read_snapshot(fv.fleetsnap_path(str(tmp_path), 0))
    assert snap["seq"] == 3 and snap["step"] == 3


def test_kill_mid_export_leaves_previous_snapshot_readable(
        tmp_path, monkeypatch):
    """Regression for the crash-safety contract: a worker killed between
    writing the tmp sibling and the rename must leave the PREVIOUS
    snapshot intact and readable — simulated by making os.replace die
    exactly once, after the tmp file is fully written."""
    clk = FaultClock()
    exp, _, _ = _exporter(tmp_path, clk)
    path = exp.export(step=1)
    real_replace = os.replace

    def killed(src, dst):
        raise OSError("killed mid-export")

    monkeypatch.setattr(os, "replace", killed)
    with pytest.raises(OSError, match="killed mid-export"):
        exp.export(step=2, force=True)
    monkeypatch.setattr(os, "replace", real_replace)
    # the torn attempt left a .tmp; the published snapshot is still v1
    snap = fv.read_snapshot(path)
    assert fv.validate_snapshot(snap, expect_worker=0) == []
    assert snap["seq"] == 1 and snap["step"] == 1
    # and the next export recovers, replacing atomically over the corpse
    exp.export(step=3, force=True)
    assert fv.read_snapshot(path)["step"] == 3


# ---------------------------------------------------------------------------
# Fleet aggregator: merge-not-average, rebuild-not-accumulate, staleness
# ---------------------------------------------------------------------------


def _worker_snapshot(fleet_dir, worker, clk, productive, wasted,
                     incarnation=1):
    reg = Registry()
    goodput.note_productive(productive, registry=reg)
    goodput.note_wasted(goodput.WASTE_COMPILE_WARMUP, wasted, registry=reg)
    rec = FlightRecorder(clock=clk)
    exp = fv.SnapshotExporter(
        fv.fleetsnap_path(fleet_dir, worker), worker=worker,
        incarnation=incarnation, registry=reg, flightrec=rec, clock=clk)
    exp.export(step=1, phase="train")
    return exp


def test_aggregator_goodput_is_merged_not_averaged(tmp_path):
    """worker 0: 9s productive / 1s wasted (0.9); worker 1: 1s / 3s
    (0.25). The merged fraction is 10/14 ≈ 0.714 — the average of
    fractions (0.575) would weight a 4-second trajectory like a
    10-second one."""
    d = str(tmp_path)
    clk = FaultClock()
    _worker_snapshot(d, 0, clk, productive=9.0, wasted=1.0)
    _worker_snapshot(d, 1, clk, productive=1.0, wasted=3.0)
    freg, frec = Registry(), FlightRecorder(clock=clk)
    agg = fv.FleetAggregator(d, [0, 1], registry=freg, flightrec=frec,
                             clock=clk)
    view = agg.poll()
    frac = freg.get(fv.FLEET_GOODPUT_FRACTION)
    assert frac is not None
    assert abs(frac.value - 10.0 / 14.0) < 1e-9
    assert abs(view.get(fv.FLEET_GOODPUT_FRACTION).value
               - 10.0 / 14.0) < 1e-9
    # per-worker labeled copies AND the unlabeled union coexist
    assert view.get(goodput.PRODUCTIVE_SECONDS, worker="0").value == 9.0
    assert view.get(goodput.PRODUCTIVE_SECONDS).value == 10.0
    # gauges never union: worker-labeled only
    assert view.get(goodput.GOODPUT_FRACTION, worker="0") is not None
    assert view.get(goodput.GOODPUT_FRACTION) is None
    # regression: a metric ALREADY worker-labeled in the worker's own
    # registry (the exporter's export counter) must appear in the view
    # exactly once — its relabeled copy and the union land on the same
    # key, so naive double-merging would report 2x
    assert view.get(fv.FLEETSNAP_EXPORTS_TOTAL, worker="0").value == 1.0


def test_aggregator_rebuilds_instead_of_accumulating(tmp_path):
    """Polling the SAME snapshot twice must not double the union
    counters — the view is rebuilt from the current files, never folded
    into an accumulating registry."""
    d = str(tmp_path)
    clk = FaultClock()
    _worker_snapshot(d, 0, clk, productive=5.0, wasted=0.0)
    agg = fv.FleetAggregator(d, [0], registry=Registry(),
                             flightrec=FlightRecorder(clock=clk), clock=clk)
    v1 = agg.poll()
    clk.advance(1.0)
    v2 = agg.poll()
    assert v1.get(goodput.PRODUCTIVE_SECONDS).value == 5.0
    assert v2.get(goodput.PRODUCTIVE_SECONDS).value == 5.0


def test_aggregator_staleness_on_own_clock_and_merge_events(tmp_path):
    d = str(tmp_path)
    wclk = FaultClock(100.0)  # worker clock: unrelated to the fleet's
    exp = _worker_snapshot(d, 0, wclk, productive=1.0, wasted=0.0)
    fclk = FaultClock()
    freg, frec = Registry(), FlightRecorder(clock=fclk)
    agg = fv.FleetAggregator(d, [0], registry=freg, flightrec=frec,
                             clock=fclk)
    agg.poll()
    assert freg.get(fv.FLEET_WORKER_STALENESS, worker="0").value == 0.0
    assert freg.get(fv.FLEETSNAP_MERGES_TOTAL, worker="0").value == 1
    # no new export: staleness grows on the AGGREGATOR's clock, and no
    # new fleetsnap_merge is emitted for a seq already observed
    fclk.advance(30.0)
    agg.poll()
    assert freg.get(fv.FLEET_WORKER_STALENESS, worker="0").value == 30.0
    assert freg.get(fv.FLEETSNAP_MERGES_TOTAL, worker="0").value == 1
    # a fresh export resets staleness and emits the next anchor
    exp.export(step=2, force=True)
    fclk.advance(5.0)
    agg.poll()
    assert freg.get(fv.FLEET_WORKER_STALENESS, worker="0").value == 0.0
    assert freg.get(fv.FLEETSNAP_MERGES_TOTAL, worker="0").value == 2
    merges = [e for e in frec.events() if e["kind"] == "fleetsnap_merge"]
    assert [e["seq"] for e in merges] == [1, 2]
    assert all(e["worker"] == 0 and e["pid"] == os.getpid()
               for e in merges)


def test_aggregator_rejects_label_collision_snapshot(tmp_path):
    """A snapshot claiming another worker's index under this worker's
    path is a label collision and must not enter the merged view."""
    d = str(tmp_path)
    clk = FaultClock()
    _worker_snapshot(d, 0, clk, productive=1.0, wasted=0.0)
    # worker 1's slot holds a snapshot claiming worker 0
    os.replace(fv.fleetsnap_path(d, 0), fv.fleetsnap_path(d, 1))
    agg = fv.FleetAggregator(d, [1], registry=Registry(),
                             flightrec=FlightRecorder(clock=clk), clock=clk)
    view = agg.poll()
    assert view.get(goodput.PRODUCTIVE_SECONDS) is None
    assert agg.status == {}


# ---------------------------------------------------------------------------
# FleetSnapshotCallback (train/callbacks.py) — step-seam driver
# ---------------------------------------------------------------------------


class _FakeExporter:
    def __init__(self, fail=False):
        self.calls = []
        self.fail = fail

    def export(self, step=None, phase=None, force=False):
        self.calls.append((step, force))
        if self.fail:
            raise OSError("disk full")
        return "path"


class _FakeTrainer:
    class state:
        step = 7


def test_fleet_snapshot_callback_cadence_and_best_effort():
    from distributed_tensorflow_tpu.train import callbacks as cb

    exp = _FakeExporter()
    c = cb.FleetSnapshotCallback(exp, every_n=2)
    c.on_train_start(_FakeTrainer())
    for step in (1, 2, 3, 4):
        c.on_step_end(_FakeTrainer(), step, {})
    c.on_train_end(_FakeTrainer())
    assert exp.calls == [(7, False), (2, False), (4, False), (7, True)]
    # an export failure is logged, never raised into the step
    failing = cb.FleetSnapshotCallback(_FakeExporter(fail=True))
    failing.on_step_end(_FakeTrainer(), 1, {})
    with pytest.raises(ValueError):
        cb.FleetSnapshotCallback(exp, every_n=0)


# ---------------------------------------------------------------------------
# Merged cross-worker timelines: anchors, shifts, must-fails
# ---------------------------------------------------------------------------


def _dump_recorder(path, rec, **extra):
    return rec.dump(path, reason="test", extra=extra or None)


def test_merge_shifts_worker_events_onto_fleet_clock(tmp_path):
    """Worker events anchored by launch land AT the launch and keep
    their relative spacing; the cross-process causal expectations hold
    on the merged sequence even though the raw clocks are wildly
    offset."""
    pid = os.getpid()
    fclk = FaultClock(1000.0)
    frec = FlightRecorder(clock=fclk)
    frec.emit("fleet_start", workers=1, incarnation=1)
    fclk.advance(1.0)   # 1001
    frec.emit("fleet_gang_stop", cause="transient", survivors=1, killed=0)
    fclk.advance(1.0)   # 1002
    frec.emit("fleet_launch", worker=0, incarnation=2, pid=pid)
    fclk.advance(5.0)   # 1007
    frec.emit("ckpt_restore", step=4, fallback=True, worker=0,
              relayed=True, incarnation=2)
    fclk.advance(1.0)   # 1008
    frec.emit("fleet_restart", restart=1, cause="transient", incarnation=2)
    fclk.advance(10.0)  # 1018
    frec.emit("fleet_done", incarnation=2)

    wclk = FaultClock(50.0)  # a clock that shares nothing with the fleet's
    wrec = FlightRecorder(clock=wclk)
    wrec.emit("train_start", step=4)
    wclk.advance(2.0)   # 52
    wrec.emit("ckpt_restore", step=4, fallback=True)
    wclk.advance(2.0)   # 54
    wrec.emit("train_stop", step=8, reason="done")

    fp = _dump_recorder(str(tmp_path / "fleet.jsonl"), frec)
    wp = _dump_recorder(str(tmp_path / "w0.jsonl"), wrec,
                        worker=0, incarnation=2)
    header, events, failures = fv.merge_timelines(fp, [wp])
    assert failures == []
    src = {s["src"]: s for s in header["sources"]}
    # offset = t(launch) - t(first worker event) = 1002 - 50
    assert src["w0i2"]["offset"] == pytest.approx(952.0)
    order = [(e["src"], e["kind"]) for e in events]
    assert order.index(("fleet", "fleet_gang_stop")) \
        < order.index(("w0i2", "ckpt_restore")) \
        < order.index(("fleet", "fleet_restart"))
    # the merged sequence passes the cross-process causal gate
    assert fr.contains_in_order(events, [
        ("fleet_gang_stop", {}),
        ("ckpt_restore", {"src": "w0i2", "fallback": True}),
        ("fleet_restart", {}), ("fleet_done", {})])
    out = str(tmp_path / "merged.jsonl")
    fv.write_merged(out, header, events)
    assert fv.validate_merged_dump(out) == []


def test_merge_elastic_handshake_anchors_force_resize_order(tmp_path):
    """The hold/release handshake must read causally in the merged
    timeline: fleet_hold < elastic_hold < fleet_shrink <
    elastic_release — even when the worker's raw clock would place its
    events long before the fleet's."""
    pid = os.getpid()
    fclk = FaultClock(2000.0)
    frec = FlightRecorder(clock=fclk)
    frec.emit("fleet_launch", worker=0, incarnation=1, pid=pid)
    fclk.advance(4.0)
    frec.emit("fleet_hold", version=2, hold=[0], resize="shrink")
    fclk.advance(2.0)
    frec.emit("fleet_shrink", worker=1, world=1, barrier=3,
              cause="transient", version=3)
    fclk.advance(10.0)
    frec.emit("fleet_done", incarnation=1)

    wclk = FaultClock(10.0)
    wrec = FlightRecorder(clock=wclk)
    wrec.emit("train_start", step=0)
    wclk.advance(1.0)
    wrec.emit("elastic_release", version=1, world=2, barrier=0, rank=0)
    wclk.advance(4.0)
    wrec.emit("elastic_hold", step=3, version=2)
    wclk.advance(3.0)
    wrec.emit("elastic_release", version=3, world=1, barrier=3, rank=0)
    wclk.advance(1.0)
    wrec.emit("train_stop", step=8, reason="done")

    fp = _dump_recorder(str(tmp_path / "fleet.jsonl"), frec)
    wp = _dump_recorder(str(tmp_path / "w0.jsonl"), wrec,
                        worker=0, incarnation=1)
    header, events, failures = fv.merge_timelines(fp, [wp])
    assert failures == []
    assert fr.contains_in_order(events, [
        ("fleet_hold", {}), ("elastic_hold", {"src": "w0i1"}),
        ("fleet_shrink", {}),
        ("elastic_release", {"src": "w0i1", "version": 3})])


def test_merge_failure_corpus(tmp_path):
    """Every unusable-input class is a loud merge failure: missing
    identity, missing launch anchor, label collision, causally
    impossible anchors."""
    pid = os.getpid()
    fclk = FaultClock(100.0)
    frec = FlightRecorder(clock=fclk)
    frec.emit("fleet_launch", worker=0, incarnation=1, pid=pid)
    fclk.advance(1.0)
    frec.emit("fleet_done", incarnation=1)
    fp = _dump_recorder(str(tmp_path / "fleet.jsonl"), frec)

    wclk = FaultClock(10.0)
    wrec = FlightRecorder(clock=wclk)
    wrec.emit("train_start", step=0)
    wclk.advance(30.0)  # 30s of life vs a 1s launch->done window
    wrec.emit("train_stop", step=8, reason="done")
    wp = _dump_recorder(str(tmp_path / "w0.jsonl"), wrec,
                        worker=0, incarnation=1)

    _, _, failures = fv.merge_timelines(fp, [wp])
    assert any("inconsistent" in f for f in failures), failures

    anon = _dump_recorder(str(tmp_path / "anon.jsonl"), wrec)
    _, _, failures = fv.merge_timelines(fp, [anon])
    assert any("identity" in f for f in failures), failures

    other = _dump_recorder(str(tmp_path / "w9.jsonl"), wrec,
                           worker=9, incarnation=1)
    _, _, failures = fv.merge_timelines(fp, [other])
    assert any("anchor missing" in f for f in failures), failures

    _, _, failures = fv.merge_timelines(fp, [wp, wp])
    assert any("collision" in f for f in failures), failures


def test_merge_disambiguates_relaunched_slot_by_pid(tmp_path):
    """An elastic replacement reuses (worker, incarnation); two
    fleet_launch events exist for the slot and the dump must anchor on
    ITS OWN (pid-matched) launch, not the corpse's."""
    pid = os.getpid()
    fclk = FaultClock(100.0)
    frec = FlightRecorder(clock=fclk)
    frec.emit("fleet_launch", worker=1, incarnation=1, pid=pid + 1)
    fclk.advance(50.0)  # 150: the replacement launch
    frec.emit("fleet_launch", worker=1, incarnation=1, pid=pid,
              rejoin=True)
    fclk.advance(20.0)
    frec.emit("fleet_done", incarnation=1)
    fp = _dump_recorder(str(tmp_path / "fleet.jsonl"), frec)

    wclk = FaultClock(7.0)
    wrec = FlightRecorder(clock=wclk)
    wrec.emit("train_start", step=2)
    wclk.advance(1.0)
    wrec.emit("train_stop", step=8, reason="done")
    wp = _dump_recorder(str(tmp_path / "w1.jsonl"), wrec,
                        worker=1, incarnation=1)
    header, events, failures = fv.merge_timelines(fp, [wp])
    assert failures == []
    src = {s["src"]: s for s in header["sources"]}
    assert src["w1i1"]["offset"] == pytest.approx(150.0 - 7.0)


def test_validate_merged_dump_catches_corruption(tmp_path):
    pid = os.getpid()
    fclk = FaultClock(1.0)
    frec = FlightRecorder(clock=fclk)
    frec.emit("fleet_launch", worker=0, incarnation=1, pid=pid)
    fclk.advance(5.0)
    frec.emit("fleet_done", incarnation=1)
    fp = _dump_recorder(str(tmp_path / "fleet.jsonl"), frec)
    wclk = FaultClock(2.0)
    wrec = FlightRecorder(clock=wclk)
    wrec.emit("train_start", step=0)
    wp = _dump_recorder(str(tmp_path / "w0.jsonl"), wrec,
                        worker=0, incarnation=1)
    header, events, failures = fv.merge_timelines(fp, [wp])
    assert failures == []
    out = str(tmp_path / "merged.jsonl")
    fv.write_merged(out, header, events)
    assert fv.validate_merged_dump(out) == []

    def corrupt(mutate, needle):
        h = json.loads(json.dumps(header))
        evs = json.loads(json.dumps(events))
        mutate(h, evs)
        bad = str(tmp_path / "bad.jsonl")
        fv.write_merged(bad, h, evs)
        got = fv.validate_merged_dump(bad)
        assert any(needle in f for f in got), (needle, got)

    corrupt(lambda h, e: h.update(schema="dtf-fleetmerge-0"), "schema")
    corrupt(lambda h, e: h.update(events=99), "dump has")
    corrupt(lambda h, e: e[0].update(t=1e9), "decreases")
    corrupt(lambda h, e: e[0].update(kind="meteor_strike"), "unknown")
    corrupt(lambda h, e: e[0].pop("src"), "not declared")
    corrupt(lambda h, e: h["sources"].append(dict(h["sources"][1])),
            "collision")


# ---------------------------------------------------------------------------
# FleetSupervisor wiring: the aggregator runs on the fleet's poll loop
# ---------------------------------------------------------------------------


def test_fleet_supervisor_aggregates_snapshots(tmp_path):
    """Scripted fleet (FakeProc/Scenario idiom from test_fleet.py):
    with snapshot_poll_s set, the supervisor folds the workers'
    snapshots mid-run — fleet_goodput_fraction and staleness gauges
    appear on ITS registry and fleetsnap_merge anchors in ITS ring,
    all before fleet_done."""
    from distributed_tensorflow_tpu.resilience import RetryPolicy
    from distributed_tensorflow_tpu.resilience import fleet as fl

    clk = FaultClock()
    fleet_dir = str(tmp_path / "fleet")
    os.makedirs(fleet_dir)

    class FakeProc:
        pid = 4242

        def __init__(self):
            self.rc = None

        def poll(self):
            return self.rc

        def terminate(self):
            self.rc = fl.EXIT_PREEMPTED

        def kill(self):
            self.rc = -9

        def wait(self, timeout=None):
            return self.rc

    def launch(i, incarnation):
        p = FakeProc()
        w = fl.HeartbeatWriter(fl.heartbeat_path(fleet_dir, i),
                               incarnation=incarnation, clock=clk)
        w.beat(step=8, phase="done")
        _worker_snapshot(fleet_dir, i, clk, productive=4.0, wasted=1.0,
                         incarnation=incarnation)
        p.rc = 0
        return p

    rec = FlightRecorder(clock=clk)
    reg = Registry()
    cfg = fl.FleetConfig(
        max_restarts=0, backoff=RetryPolicy(base_s=0.0, jitter=0.0),
        poll_s=1.0, heartbeat_timeout_s=5.0, stall_timeout_s=10.0,
        launch_grace_s=20.0, term_grace_s=4.0, snapshot_poll_s=1.0)
    fleet = fl.FleetSupervisor(
        launch, 2, fleet_dir, cfg, registry=reg, flightrec=rec,
        clock=clk, sleep=clk.advance)
    out = fleet.run()
    assert out["restarts"] == 0
    frac = reg.get(fv.FLEET_GOODPUT_FRACTION)
    assert frac is not None and abs(frac.value - 8.0 / 10.0) < 1e-9
    for i in (0, 1):
        assert reg.get(fv.FLEET_WORKER_STALENESS, worker=str(i)) is not None
    kinds = [e["kind"] for e in rec.events()]
    merge_idx = kinds.index("fleetsnap_merge")
    assert merge_idx < kinds.index("fleet_done")
    view = fleet.aggregator.view()
    assert view.get(goodput.PRODUCTIVE_SECONDS).value == 8.0
