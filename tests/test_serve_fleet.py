"""Serve fleet (ISSUE 16): router lane/requeue invariants, prefix-aware
placement against the random baseline, and the failover acceptance —
one replica killed mid-stream, every in-flight stream re-prefilled and
finished on survivors BIT-IDENTICAL to an uncontended run, interactive
p99 TTFT bounded through the kill (deterministic fake clock)."""

import pytest

from distributed_tensorflow_tpu import serve
from distributed_tensorflow_tpu.models import transformer as tfm
from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.serve import fleet as sf
from distributed_tensorflow_tpu.serve import router as rt


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_router(**kw):
    kw.setdefault("registry", Registry())
    kw.setdefault("flightrec", FlightRecorder())
    return rt.Router(**kw)


# ---------------------------------------------------------------------------
# Router invariants (jax-free)
# ---------------------------------------------------------------------------


def test_unknown_lane_rejected():
    r = make_router()
    with pytest.raises(rt.UnknownLane):
        r.submit([1, 2], lane="bulk")


def test_lane_queues_disjoint():
    r = make_router()
    b = r.submit([1, 2], lane=rt.LANE_BATCH)
    a = r.submit([3, 4], lane=rt.LANE_INTERACTIVE)
    assert r.queued(rt.LANE_BATCH) == 1
    assert r.queued(rt.LANE_INTERACTIVE) == 1
    assert [q.rid for q in r.lanes[rt.LANE_BATCH]] == [b]
    assert [q.rid for q in r.lanes[rt.LANE_INTERACTIVE]] == [a]


def test_interactive_dispatches_before_batch():
    """ALL of interactive drains before ANY of batch, whatever the
    submission interleaving — the SLO tier order."""
    r = make_router(max_outstanding=4)
    r.add_replica(0)
    b1 = r.submit([1], lane=rt.LANE_BATCH)
    a1 = r.submit([2], lane=rt.LANE_INTERACTIVE)
    b2 = r.submit([3], lane=rt.LANE_BATCH)
    a2 = r.submit([4], lane=rt.LANE_INTERACTIVE)
    order = [req.rid for _, req in r.dispatch()]
    assert order == [a1, a2, b1, b2]


def test_dispatch_fifo_within_lane_and_head_of_line():
    """Within a lane dispatch is FIFO, and a head that cannot be placed
    blocks everything behind it (no skipping ahead)."""
    r = make_router(max_outstanding=2)
    r.add_replica(0)
    rids = [r.submit([i + 1], lane=rt.LANE_INTERACTIVE) for i in range(4)]
    first = [req.rid for _, req in r.dispatch()]
    assert first == rids[:2]  # capacity 2: the head pair, in order
    assert r.queued(rt.LANE_INTERACTIVE) == 2
    r.on_token(rids[0], 7)
    r.on_finish(rids[0], "eos")
    assert [req.rid for _, req in r.dispatch()] == [rids[2]]


def test_requeue_preserves_fifo_within_lane():
    """Death path: in-flight requests return to the HEAD of their lane
    in original dispatch order, ahead of anything still queued."""
    r = make_router(max_outstanding=4)
    r.add_replica(0)
    i0 = r.submit([1], lane=rt.LANE_INTERACTIVE)
    i1 = r.submit([2], lane=rt.LANE_INTERACTIVE)
    b0 = r.submit([3], lane=rt.LANE_BATCH)
    b1 = r.submit([4], lane=rt.LANE_BATCH)
    assert len(r.dispatch()) == 4  # all in flight on replica 0
    i2 = r.submit([5], lane=rt.LANE_INTERACTIVE)  # queued behind
    b2 = r.submit([6], lane=rt.LANE_BATCH)
    requeued = r.requeue_replica(0)
    assert sorted(requeued) == [i0, i1, b0, b1]
    assert [q.rid for q in r.lanes[rt.LANE_INTERACTIVE]] == [i0, i1, i2]
    assert [q.rid for q in r.lanes[rt.LANE_BATCH]] == [b0, b1, b2]
    for rid in requeued:
        assert r.requests[rid].requeues == 1
        assert r.requests[rid].replica is None


def test_remove_replica_with_inflight_raises():
    r = make_router()
    r.add_replica(0)
    r.submit([1], lane=rt.LANE_INTERACTIVE)
    r.dispatch()
    with pytest.raises(RuntimeError):
        r.remove_replica(0)


def test_prefix_placement_follows_home_and_counts_hits():
    reg = Registry()
    r = make_router(registry=reg, max_outstanding=4)
    r.add_replica(0)
    r.add_replica(1)
    pfx = list(range(8))
    a = r.submit(pfx + [50], lane=rt.LANE_INTERACTIVE, prefix_len=8)
    b = r.submit(pfx + [51], lane=rt.LANE_INTERACTIVE, prefix_len=8)
    orders = r.dispatch()
    assert orders[0][0] == orders[1][0]  # same home replica
    # the first placement pinned (no hit); the second followed the pin
    assert int(reg.get("router_prefix_hits_total").value) == 1
    home = orders[0][0]
    r.requeue_replica(home)  # the home dies: pins dropped
    assert r.dispatch()  # repins on the survivor without error
    assert all(req.replica != home for req in r.requests.values())


def test_requeued_payload_resumes_past_delivered_tokens():
    """The re-dispatch payload is prompt + delivered tokens with the
    budget reduced to match — the re-prefill contract."""
    r = make_router(max_outstanding=2)
    r.add_replica(0)
    rid = r.submit([1, 2, 3], max_new_tokens=8, lane=rt.LANE_BATCH)
    r.dispatch()
    r.on_token(rid, 40)
    r.on_token(rid, 41)
    r.requeue_replica(0)
    req = r.requests[rid]
    payload = req.payload()
    assert payload["prompt"] == [1, 2, 3, 40, 41]
    assert payload["max_new_tokens"] == 6
    assert payload["priority"] == rt.LANE_PRIORITY[rt.LANE_BATCH]


def test_batch_lane_maps_to_lower_engine_priority():
    assert rt.LANE_PRIORITY[rt.LANE_BATCH] \
        < rt.LANE_PRIORITY[rt.LANE_INTERACTIVE]


# ---------------------------------------------------------------------------
# Fleet failover (LocalReplica engines, deterministic fake clock)
# ---------------------------------------------------------------------------


def fleet_decoder():
    return tfm.TransformerConfig(
        vocab_size=128, max_len=96, num_layers=1, d_model=32, num_heads=4,
        d_ff=64, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )


def _make_engine(cfg, spec_k=0):
    return serve.ServeEngine.with_random_params(
        cfg, seed=0, num_slots=2, paged=True, block_size=8,
        prefill_chunk=16, spec_k=spec_k)


def shared_prefix_trace(n=6, groups=2, max_new=6):
    """n requests over `groups` shared 16-token system prompts,
    alternating lanes: (prompt, lane, prefix_len, max_new) rows."""
    pfx = [[(7 * g + k) % 128 for k in range(16)] for g in range(groups)]
    trace = []
    for i in range(n):
        lane = rt.LANE_INTERACTIVE if i % 2 == 0 else rt.LANE_BATCH
        prompt = pfx[i % groups] + [(3 * i + 1) % 128, (5 * i + 2) % 128]
        trace.append((prompt, lane, 16, max_new))
    return trace


def baseline_streams(cfg, trace):
    """Uncontended ground truth: each prompt decoded alone on one
    engine with the same seed-deterministic weights."""
    eng = _make_engine(cfg)
    out = {i: list(eng.stream(p, max_new_tokens=m))
           for i, (p, _lane, _plen, m) in enumerate(trace)}
    eng.drain()
    return out


def run_fleet(cfg, trace, *, policy="prefix", num_replicas=2,
              kill_after_tokens=None, spec_k=0):
    """Drive a LocalReplica fleet over the trace on a fake clock
    (1 pump = 1 s); optionally hard-kill a mid-stream replica once
    `kill_after_tokens` tokens are in flight."""
    clk = FakeClock()
    reg, rec = Registry(), FlightRecorder()
    engines = []

    def launch(index, incarnation):
        eng = _make_engine(cfg, spec_k=spec_k)
        engines.append(eng)
        return sf.LocalReplica(eng)

    router = rt.Router(policy=policy, max_outstanding=2, seed=0,
                       registry=reg, flightrec=rec, clock=clk)
    sup = sf.ServeFleetSupervisor(
        launch, num_replicas, router=router, registry=reg, flightrec=rec,
        clock=clk, sleep=lambda s: clk.advance(s or 0.01))
    sup.start()
    for prompt, lane, plen, max_new in trace:
        router.submit(prompt, max_new_tokens=max_new, lane=lane,
                      prefix_len=plen)
    killed = kill_after_tokens is None
    for _ in range(10_000):
        if router.idle:
            break
        sup.pump()
        clk.advance(1.0)
        if not killed:
            busy = [w for w in sorted(sup.replicas)
                    if any(router.requests[rid].delivered
                           for rid in router.outstanding.get(w, ()))]
            delivered = sum(len(r.delivered)
                            for r in router.requests.values())
            if busy and delivered >= kill_after_tokens:
                sup.replicas[busy[0]].handle.hard_kill()
                killed = True
    else:
        raise AssertionError("fleet did not go idle in 10k pumps")
    survivors = sorted(sup.replicas)
    sup.stop()
    return router, reg, rec, engines, sup, survivors


def test_kill_midstream_no_request_lost_streams_bit_identical():
    """ISSUE 16 acceptance: a replica dies mid-stream, nothing is lost,
    every stream completes on survivors, and each full token stream is
    bit-identical to the uncontended single-engine run (re-prefill with
    identical weights is deterministic)."""
    cfg = fleet_decoder()
    trace = shared_prefix_trace(n=6)
    want = baseline_streams(cfg, trace)
    router, reg, rec, engines, sup, survivors = run_fleet(
        cfg, trace, kill_after_tokens=3)

    assert sup.deaths == 1
    assert int(reg.get("router_requeues_total").value) >= 1
    assert len(router.finished) == len(trace)  # no request lost
    for rid, req in router.finished.items():
        assert req.delivered == want[rid], (
            f"rid {rid} diverged after requeue: {req.delivered} != "
            f"{want[rid]}")
    # at least one finished stream actually crossed the kill
    assert any(req.requeues for req in router.finished.values())
    # survivors drained leak-free; the corpse never writes its audit
    assert survivors and set(sup.drained) == set(survivors)
    assert all(d["leak_free"] for d in sup.drained.values())
    kinds = [e["kind"] for e in rec.events()]
    for kind in ("serve_replica_dead", "serve_requeue", "fleet_done"):
        assert kind in kinds


def test_interactive_p99_ttft_bounded_through_kill():
    """The kill costs the interactive lane a bounded constant factor
    over the kill-free run — not an unbounded stall (fake clock: 1 pump
    = 1 s, so the percentiles are exact pump counts)."""
    cfg = fleet_decoder()
    trace = shared_prefix_trace(n=8, max_new=6)
    _, reg0, *_ = run_fleet(cfg, trace)
    base_p99 = reg0.get("router_ttft_seconds",
                        lane=rt.LANE_INTERACTIVE).percentile(0.99)
    router, reg, *_ = run_fleet(cfg, trace, kill_after_tokens=3)
    assert len(router.finished) == len(trace)
    kill_p99 = reg.get("router_ttft_seconds",
                       lane=rt.LANE_INTERACTIVE).percentile(0.99)
    assert kill_p99 <= 3 * base_p99 + 10.0, (kill_p99, base_p99)


def test_kill_midstream_cannot_reset_tpot_clock():
    """ISSUE 17 regression pin: router-side TPOT is measured from the
    ORIGINAL first delivered token across replica deaths — a mid-stream
    kill must not reset a request's TPOT clock on the survivor.
    ``on_token`` stamps ``t_first_token`` only while it is None, and the
    requeue path must leave it (and ``t_submit``) alone."""
    cfg = fleet_decoder()
    trace = shared_prefix_trace(n=6)
    router, reg, rec, *_ = run_fleet(cfg, trace, kill_after_tokens=3)

    t_requeue = min(e["t"] for e in rec.events()
                    if e["kind"] == "serve_requeue")
    crossed = [req for req in router.finished.values()
               if req.requeues and req.t_first_token is not None]
    assert crossed, "no killed request had delivered a token"
    for req in crossed:
        # the pre-kill stamp survived the survivor's re-prefill...
        assert req.t_first_token <= t_requeue, (
            f"rid {req.rid}: t_first_token {req.t_first_token} is AFTER "
            f"the requeue at {t_requeue} — the TPOT clock was reset")
        assert req.t_submit < req.t_first_token < req.t_finish
        # ...and the finish-side observation used it: the per-token
        # cadence the client saw INCLUDES the re-prefill detour
        tpot = (req.t_finish - req.t_first_token) / (len(req.delivered) - 1)
        assert tpot > 0
    # one TPOT observation per finished multi-token request, none lost
    finished_multi = sum(1 for req in router.finished.values()
                         if req.t_first_token is not None
                         and len(req.delivered) > 1)
    assert reg.total(rt.ROUTER_TPOT_SECONDS) == finished_multi


def test_spec_multi_token_pumps_keep_tpot_per_token():
    """PR 20 regression pin (alongside the TPOT-clock pin above): with
    speculative engines one pump can deliver SEVERAL tokens per request,
    and the router's TPOT accounting must stay per-TOKEN — exactly one
    observation per finished multi-token request, never one per pump —
    while the streams stay bit-identical to the non-spec baseline
    (greedy-exact acceptance)."""
    cfg = fleet_decoder()
    trace = shared_prefix_trace(n=6)
    want = baseline_streams(cfg, trace)
    router, reg, rec, engines, sup, survivors = run_fleet(
        cfg, trace, spec_k=4)

    assert len(router.finished) == len(trace)
    for rid, req in router.finished.items():
        assert req.delivered == want[rid], (
            f"rid {rid} diverged under speculation: {req.delivered} != "
            f"{want[rid]}")
    # speculation actually landed multi-token steps somewhere
    accepted = sum(
        int(e.registry.get("spec_tokens_accepted_total").value)
        for e in engines)
    assert accepted > 0
    finished_multi = sum(1 for req in router.finished.values()
                         if req.t_first_token is not None
                         and len(req.delivered) > 1)
    assert reg.total(rt.ROUTER_TPOT_SECONDS) == finished_multi
    for req in router.finished.values():
        if req.t_first_token is not None:
            assert req.t_submit <= req.t_first_token <= req.t_finish
    assert all(d["leak_free"] for d in sup.drained.values())


def test_prefix_routing_beats_random_on_shared_prefix_trace():
    """ISSUE 16 acceptance: routed prefix-hit rate strictly beats the
    seeded random baseline on a shared-system-prompt trace, measured as
    `prefix_reuse_hits_total` ON THE ENGINES — blocks actually reused
    instead of re-prefilled."""
    cfg = fleet_decoder()
    trace = shared_prefix_trace(n=10, groups=2, max_new=4)

    def engine_hits(policy):
        *_, engines, _sup, _surv = run_fleet(cfg, trace, policy=policy)
        return sum(int(e.registry.get("prefix_reuse_hits_total").value)
                   for e in engines)

    routed, rand = engine_hits("prefix"), engine_hits("random")
    assert routed > rand, (routed, rand)


def test_elastic_add_replica_absorbs_without_drain():
    """Scale-up mid-run: the joining replica takes new work on the very
    next dispatch; nothing drains, everything finishes."""
    cfg = fleet_decoder()
    trace = shared_prefix_trace(n=6, groups=3, max_new=4)
    clk = FakeClock()
    reg, rec = Registry(), FlightRecorder()

    def launch(index, incarnation):
        return sf.LocalReplica(_make_engine(cfg))

    router = rt.Router(max_outstanding=2, registry=reg, flightrec=rec,
                       clock=clk)
    sup = sf.ServeFleetSupervisor(
        launch, 1, router=router, registry=reg, flightrec=rec,
        clock=clk, sleep=lambda s: clk.advance(s or 0.01))
    sup.start()
    for prompt, lane, plen, max_new in trace:
        router.submit(prompt, max_new_tokens=max_new, lane=lane,
                      prefix_len=plen)
    sup.pump()
    new = sup.add_replica()
    assert new == 1
    for _ in range(10_000):
        if router.idle:
            break
        sup.pump()
        clk.advance(1.0)
    sup.stop()
    assert len(router.finished) == len(trace)
    assert sup.deaths == 0  # absorbed, not recovered
    routed_to_new = [e for e in rec.events()
                     if e["kind"] == "serve_route" and e.get("replica") == new]
    assert routed_to_new  # the joiner became a placement target
    assert set(sup.drained) == {0, 1}
    assert all(d["leak_free"] for d in sup.drained.values())


def test_scheduler_priority_victim_selection():
    """The engine's preemption victim is the LOWEST-priority resident
    (batch before interactive), youngest among equals — the seam the
    router's lanes map onto."""
    cfg = fleet_decoder()
    eng = _make_engine(cfg)
    hi = eng.submit([1, 2, 3], max_new_tokens=4,
                    priority=rt.LANE_PRIORITY[rt.LANE_INTERACTIVE])
    lo = eng.submit([4, 5, 6], max_new_tokens=4,
                    priority=rt.LANE_PRIORITY[rt.LANE_BATCH])
    eng.sched.admit()
    slots = {req.uid: s for s, req in enumerate(eng.sched.slots)
             if req is not None}
    victim = eng._youngest_resident(exclude=-1)
    assert victim == slots[lo]  # batch absorbs preemption first
    # all-equal priorities: the original youngest-uid rule
    eng2 = _make_engine(cfg)
    a = eng2.submit([1, 2], max_new_tokens=4)
    b = eng2.submit([3, 4], max_new_tokens=4)
    eng2.sched.admit()
    slots2 = {req.uid: s for s, req in enumerate(eng2.sched.slots)
              if req is not None}
    assert eng2._youngest_resident(exclude=-1) == slots2[max(a, b)]
    eng.drain()
    eng2.drain()
