"""Unit tests for the shared VMEM tile-selection model (ops/_tiling.py).

The round-3 on-chip OOM (conv1x1_bn_bwd_dw at [12544, 512] x [12544,
2048], 17.86 MB scoped stack vs the 16 MB core limit) is the regression
these pin: the joint picker must keep its own accounting under budget
for every shape the batch-256 ResNet-50 / bench transformer paths emit.
"""

import pytest

from distributed_tensorflow_tpu.ops import _tiling

# every 1x1-conv dw shape a batch-256 ResNet-50 emits + bench ln_matmul
BENCH_SHAPES = [
    (200704, 64, 256), (200704, 256, 64), (200704, 256, 128),
    (50176, 128, 512), (50176, 512, 128), (50176, 512, 256),
    (12544, 256, 1024), (12544, 1024, 256), (12544, 1024, 512),
    (3136, 512, 2048), (3136, 2048, 512),
    (12544, 512, 2048), (12544, 2048, 512),
    (16384, 768, 2304), (16384, 768, 3072), (16384, 3072, 768),
    (32768, 1024, 4096),
]


@pytest.mark.parametrize("M,cin,cout", BENCH_SHAPES)
@pytest.mark.parametrize("emit_stats", [False, True])
def test_bench_shapes_fit_and_divide(M, cin, cout, emit_stats):
    bm, bn = _tiling.pick_dw_tiles(
        M, cin, cout, in_bytes=2, emit_stats=emit_stats, name="t"
    )
    assert M % bm == 0 and cout % bn == 0
    assert bm % 8 == 0 or bm == M
    assert bn % 128 == 0 or bn == cout
    # re-apply the picker's own accounting: chosen tile must be in budget
    stream = 2 * (bm * cin * 2 + 2 * bm * bn * 2)
    acc = 3 * cin * bn * 4
    scratch = (2 if emit_stats else 1) * bm * bn * 4 + bm * cin * 4 + bm * cin * 2
    assert stream + acc + scratch <= 13 * 1024 * 1024


def test_r3_oom_shape_stays_under_scoped_limit():
    """The exact shape that blew the 16 MB scoped limit on-chip: the
    model's own upper bound for the chosen tile must leave real slack."""
    bm, bn = _tiling.pick_dw_tiles(
        12544, 512, 2048, in_bytes=2, emit_stats=True, name="t"
    )
    # the old independent-term picker chose (448, 2048) here -> 17.86 MB
    assert (bm, bn) != (448, 2048)
    assert bm * bn < 448 * 2048


def test_prefers_wide_bm_then_wide_bn():
    # comfortable shape: both dims should stay whole
    bm, bn = _tiling.pick_dw_tiles(
        1024, 128, 256, in_bytes=2, emit_stats=True, name="t"
    )
    assert bn == 256
    assert bm >= 128


def test_error_names_the_failing_dimension():
    with pytest.raises(ValueError, match="M=12545"):
        _tiling.pick_dw_tiles(12545, 4096, 8192, in_bytes=4,
                              emit_stats=True, name="t")
    with pytest.raises(ValueError, match="cin=2000000"):
        _tiling.pick_dw_tiles(4096, 2000000, 128, in_bytes=2,
                              emit_stats=True, name="t")


def test_resolve_bwd_impl_policy(monkeypatch):
    monkeypatch.delenv("DTF_FUSED_BWD", raising=False)
    assert _tiling.resolve_bwd_impl(None) == "xla"
    monkeypatch.setenv("DTF_FUSED_BWD", "pallas")
    assert _tiling.resolve_bwd_impl(None) == "pallas"
    assert _tiling.resolve_bwd_impl("xla") == "xla"  # explicit arg wins
    with pytest.raises(ValueError, match="bwd_impl"):
        _tiling.resolve_bwd_impl("cuda")
