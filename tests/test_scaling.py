"""Scaling observatory (ISSUE 11): provenance stamping, the
dtf-scaling-1 report schema, and the tools/sweep.py mesh×workload
harness on the 8-device CPU rig."""

import copy
import json

import pytest

from distributed_tensorflow_tpu import obs
from distributed_tensorflow_tpu.obs import scaling


def _fake_prov(**over):
    prov = {"backend": "cpu", "platform": "cpu", "device_kind": "cpu",
            "device_count": 8, "hostname": "t", "git_sha": "cafe"}
    prov.update(over)
    return prov


def _fake_cell(name="dp8", n=8, data=8, model=1, eps=40960.0, **over):
    cell = {
        "cell": name, "workload": "mlp", "axis": "dp", "n_devices": n,
        "mesh": {"pipe": 1, "data": data, "fsdp": 1, "seq": 1,
                 "expert": 1, "model": model},
        "global_batch": 128 * data, "steps": 8, "steps_per_sec": 40.0,
        "examples_per_sec": eps, "provenance": _fake_prov(),
    }
    cell.update(over)
    return cell


# ---------------------------------------------------------------------------
# provenance
# ---------------------------------------------------------------------------


def test_provenance_reads_live_backend(devices):
    prov = scaling.provenance()
    for key in scaling.PROVENANCE_KEYS:
        assert key in prov, key
    assert prov["backend"] == "cpu" and prov["platform"] == "cpu"
    assert prov["device_count"] >= 8
    assert isinstance(prov["git_sha"], str) and prov["git_sha"]
    assert prov["hostname"]


def test_provenance_with_mesh_describes_the_subset(devices):
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=4, model=2), devices[:8])
    prov = scaling.provenance(mesh)
    assert prov["device_count"] == 8
    assert prov["mesh"] == {"pipe": 1, "data": 4, "fsdp": 1, "seq": 1,
                            "expert": 1, "model": 2}
    one = build_mesh(MeshSpec(data=1), devices[:1])
    assert scaling.provenance(one)["device_count"] == 1


def test_stamp_provenance_in_place(devices):
    row = {"metric": "x", "value": 1.0}
    out = scaling.stamp_provenance(row)
    assert out is row and row["provenance"]["platform"] == "cpu"


def test_git_sha_unknown_outside_repo(tmp_path):
    assert scaling.git_sha(str(tmp_path)) == "unknown"


# ---------------------------------------------------------------------------
# report schema + efficiency
# ---------------------------------------------------------------------------


def test_validator_roundtrip_and_masquerade(tmp_path):
    base = _fake_cell("1dev", n=1, data=1, eps=15360.0)
    cell = _fake_cell()
    report = {"schema": scaling.SCHEMA, "provenance": _fake_prov(),
              "cells": [base, cell],
              "efficiency": scaling.scaling_efficiency([base, cell]),
              "gates": []}
    assert scaling.validate_scaling_report(report) == []

    # write_report validates, writes atomically, and round-trips
    path = str(tmp_path / "r.json")
    scaling.write_report(path, report)
    assert scaling.validate_scaling_report(path) == []
    assert json.load(open(path))["schema"] == scaling.SCHEMA

    # the masquerade: a TPU-claiming cell under a CPU header is invalid
    bad = copy.deepcopy(report)
    bad["cells"][1]["provenance"]["platform"] = "tpu"
    failures = scaling.validate_scaling_report(bad)
    assert any("masquerade" in f for f in failures)
    with pytest.raises(ValueError, match="refusing to write"):
        scaling.write_report(str(tmp_path / "bad.json"), bad)


def test_validator_negative_cases():
    base = _fake_cell("1dev", n=1, data=1, eps=15360.0)
    good = {"schema": scaling.SCHEMA, "provenance": _fake_prov(),
            "cells": [base, _fake_cell()], "efficiency": [], "gates": []}

    def failures_after(mutate):
        bad = copy.deepcopy(good)
        mutate(bad)
        return scaling.validate_scaling_report(bad)

    assert any("schema" in f for f in
               failures_after(lambda r: r.update(schema="nope")))
    assert any("missing 'provenance'" in f for f in
               failures_after(lambda r: r["cells"][0].pop("provenance")))
    assert any("finite positive" in f for f in failures_after(
        lambda r: r["cells"][0].update(steps_per_sec=float("nan"))))
    assert any("does not multiply" in f for f in failures_after(
        lambda r: r["cells"][1]["mesh"].update(data=2)))
    assert any("no cells" in f for f in
               failures_after(lambda r: r.update(cells=[])))
    assert any("inconsistent" in f for f in failures_after(
        lambda r: r.update(gates=[{"threshold": 0.8, "value": 0.5,
                                   "passed": True}])))


def test_scaling_efficiency_bases():
    """shared_host basis (CPU rig): ideal is flat throughput;
    per_device basis (real accelerators): ideal is N × 1-dev."""
    base = _fake_cell("1dev", n=1, data=1, eps=1000.0)
    dp8 = _fake_cell("dp8", eps=800.0)
    reg = obs.Registry()
    eff = scaling.scaling_efficiency([base, dp8], registry=reg)
    assert eff == [{"cell": "dp8", "workload": "mlp", "axis": "dp",
                    "n_devices": 8, "basis": "shared_host",
                    "value": 0.8}]
    assert reg.get(scaling.SCALING_EFFICIENCY, cell="dp8",
                   workload="mlp").value == pytest.approx(0.8)

    # a TPU run computes against the N× ideal
    tpu = {"platform": "tpu", "device_kind": "TPU v5 lite"}
    base_t = _fake_cell("1dev", n=1, data=1, eps=1000.0,
                        provenance=_fake_prov(**tpu))
    dp8_t = _fake_cell("dp8", eps=6400.0, provenance=_fake_prov(**tpu))
    eff_t = scaling.scaling_efficiency([base_t, dp8_t])
    assert eff_t[0]["basis"] == "per_device"
    assert eff_t[0]["value"] == pytest.approx(6400.0 / (8 * 1000.0))

    # no 1-dev baseline → no entry (not a crash)
    assert scaling.scaling_efficiency([dp8]) == []


def test_sweep_cells_counter():
    reg = obs.Registry()
    scaling.note_cell(reg)
    scaling.note_cell(reg)
    assert reg.get(scaling.SWEEP_CELLS).value == 2


# ---------------------------------------------------------------------------
# the sweep harness end-to-end (the acceptance surface)
# ---------------------------------------------------------------------------


def test_sweep_dryrun_report_and_gate(tmp_path, capsys, devices):
    """2-cell CI shape: schema-valid report, every cell provenance
    stamped with the honest platform, dp gate evaluated, metrics
    isolated per cell via Registry.delta (counted in the process
    registry without any reset)."""
    from distributed_tensorflow_tpu.obs.registry import default_registry
    from tools import sweep

    reg = default_registry()
    before = reg.snapshot()
    out = str(tmp_path / "scaling.json")
    rc = sweep.main(["--dryrun", "--out", out, "--expect-platform", "cpu",
                     "--steps", "6"])
    capsys.readouterr()
    assert rc == 0
    assert scaling.validate_scaling_report(out) == []
    report = json.load(open(out))
    assert [c["cell"] for c in report["cells"]] == \
        ["1dev", "dp8", "pod2_dp2"]
    for cell in report["cells"]:
        assert cell["provenance"]["platform"] == "cpu"
        assert cell["provenance"]["git_sha"] == \
            report["provenance"]["git_sha"]
        assert cell["steps_per_sec"] > 0
        assert cell["eval_batches"] == 2  # distributed eval ran per cell
        assert "mfu" in cell  # flowed through goodput.train_mfu
    # the two-level cell is stamped with its fault-domain shape
    pod_cell = report["cells"][2]
    assert pod_cell["pods"] == 2 and pod_cell["devices_per_pod"] == 2
    assert report["gates"] and report["gates"][0]["axis"] == "dp"
    assert report["gates"][0]["passed"]

    d = reg.delta(before)
    assert d[scaling.SWEEP_CELLS]["value"] == 3
    assert d["eval_steps_total"]["value"] == 6


def test_sweep_dryrun_rejects_explicit_matrix(capsys):
    """--dryrun fixes the matrix; a silently-ignored --cells/--workloads
    would measure the wrong cells and be trusted anyway."""
    from tools import sweep

    with pytest.raises(SystemExit) as e:
        sweep.main(["--dryrun", "--cells", "dp4_tp2"])
    assert e.value.code == 2
    assert "drop --cells" in capsys.readouterr().err


def test_sweep_expect_platform_mismatch_fails(tmp_path, capsys, devices):
    from tools import sweep

    rc = sweep.main(["--cells", "1dev", "--workloads", "mlp",
                     "--steps", "4", "--eval-batches", "0",
                     "--expect-platform", "tpu",
                     "--out", str(tmp_path / "r.json")])
    capsys.readouterr()
    assert rc == 4  # an honest cpu report can't satisfy a tpu expectation


def test_sweep_full_mesh_matrix(tmp_path, capsys, devices):
    """The full 8-mesh matrix (the MULTICHIP dryrun shapes plus the
    two-level pod cells) over the mlp workload: ≥ 8 provenance-stamped
    cells in one report."""
    from tools import sweep

    out = str(tmp_path / "full.json")
    rc = sweep.main(["--workloads", "mlp", "--steps", "6", "--out", out,
                     "--eval-batches", "1"])
    capsys.readouterr()
    assert rc == 0
    report = json.load(open(out))
    assert scaling.validate_scaling_report(report) == []
    assert len(report["cells"]) == 8
    axes = {c["axis"] for c in report["cells"]}
    assert {"dp", "tp", "fsdp", "hybrid", "pod"} <= axes
    assert {e["cell"] for e in report["efficiency"]} >= \
        {"dp2", "dp8", "dp4_tp2", "dp2_fsdp2_tp2", "dp8_hybrid2"}
