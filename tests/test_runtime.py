"""Native runtime (C++ loader + checksummed IO): build, native↔fallback
parity, shard disjointness, resume, corruption detection."""

import ctypes
import os
import random

import numpy as np
import pytest

from distributed_tensorflow_tpu.runtime import (
    RecordFileLoader, available, epoch_permutation, load_library,
    read_payload, write_payload,
)
from distributed_tensorflow_tpu.runtime import io as io_lib


@pytest.fixture(scope="module")
def lib():
    lib = load_library()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


@pytest.fixture()
def record_file(tmp_path):
    rng = np.random.RandomState(0)
    n, rec = 64, 20
    data = rng.randint(0, 256, (n, rec)).astype(np.uint8)
    path = str(tmp_path / "data.bin")
    data.tofile(path)
    return path, data


def test_native_builds(lib):
    assert available()


def test_permutation_parity(lib):
    for n, seed in [(1, 0), (17, 3), (256, 12345)]:
        out = (ctypes.c_int64 * n)()
        lib.dtf_epoch_permutation(n, seed, out)
        np.testing.assert_array_equal(
            np.asarray(out), epoch_permutation(n, seed)
        )
        assert sorted(out) == list(range(n))


def test_native_matches_fallback(record_file, lib):
    path, _ = record_file
    kw = dict(seed=7, n_shards=2, shard=1, num_batches=10)
    nat = list(RecordFileLoader(path, 20, 8, use_native=True, **kw))
    py = list(RecordFileLoader(path, 20, 8, use_native=False, **kw))
    assert len(nat) == len(py) == 10
    for a, b in zip(nat, py):
        np.testing.assert_array_equal(a, b)


def test_batches_match_oracle(record_file, lib):
    path, data = record_file
    ldr = RecordFileLoader(path, 20, 8, seed=3, num_batches=6,
                           use_native=True)
    oracle = RecordFileLoader(path, 20, 8, seed=3, use_native=False)
    for bi, batch in enumerate(ldr):
        np.testing.assert_array_equal(batch, data[oracle.batch_indices(bi)])


def test_shards_disjoint_and_cover_epoch(record_file):
    path, _ = record_file
    seen = []
    for shard in range(2):
        ldr = RecordFileLoader(path, 20, 8, seed=1, shard=shard, n_shards=2,
                               use_native=False)
        for bi in range(ldr.batches_per_epoch):
            seen.append(ldr.batch_indices(bi))
    flat = np.concatenate(seen)
    # one epoch over both shards touches every record exactly once
    assert sorted(flat.tolist()) == list(range(64))


def test_resume_continues_stream(record_file, lib):
    path, _ = record_file
    full = list(RecordFileLoader(path, 20, 8, seed=2, num_batches=8,
                                 use_native=True))
    resumed = list(RecordFileLoader(path, 20, 8, seed=2, num_batches=5,
                                    start_batch=3, use_native=True))
    for a, b in zip(full[3:], resumed):
        np.testing.assert_array_equal(a, b)


def test_next_without_release_does_not_deadlock(record_file, lib):
    """Holding several batches before releasing any must not starve the
    producers (next() must wake a worker when it lowers in-flight)."""
    import threading

    path, _ = record_file
    h = lib.dtf_loader_create(path.encode(), 20, 8, 2, 2, 0, 0, 1, 0)
    assert h
    held = []

    def consume():
        for _ in range(3):  # depth=2: the 3rd next needs a producer wakeup
            held.append(lib.dtf_loader_next(h))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    t.join(timeout=30)
    ok = not t.is_alive()
    if ok:
        for b in held:
            lib.dtf_loader_release(h, b)
        lib.dtf_loader_destroy(h)  # leak on failure: destroy would race
    assert ok, "loader deadlocked when batches were held across next() calls"


def test_decode_hook(record_file):
    path, data = record_file
    ldr = RecordFileLoader(
        path, 20, 8, num_batches=2,
        decode=lambda raw: {"sum": raw.sum(axis=1)},
    )
    out = list(ldr)
    assert set(out[0]) == {"sum"} and out[0]["sum"].shape == (8,)


def test_io_roundtrip(tmp_path):
    path = str(tmp_path / "shard-0")
    payload = random.Random(7).randbytes(10_000)  # seeded: reproducible
    write_payload(path, payload)
    assert read_payload(path) == payload
    # overwrite is atomic: old file stays valid if we re-write
    write_payload(path, b"second")
    assert read_payload(path) == b"second"
    assert not os.path.exists(path + ".tmp")


def test_io_detects_corruption(tmp_path):
    path = str(tmp_path / "shard-1")
    write_payload(path, b"x" * 1000)
    raw = bytearray(open(path, "rb").read())
    raw[500] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises(OSError, match="CRC"):
        read_payload(path)


def test_io_python_fallback_format_compatible(tmp_path, monkeypatch):
    """Bytes written natively must read through the Python fallback and
    vice versa (same trailer format)."""
    p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
    write_payload(p1, b"native-written")  # native (if available)
    monkeypatch.setattr(io_lib.native, "load_library", lambda: None)
    assert read_payload(p1) == b"native-written"
    write_payload(p2, b"python-written")
    monkeypatch.undo()
    assert read_payload(p2) == b"python-written"


def test_record_classification_dataset(tmp_path):
    from distributed_tensorflow_tpu.data.records import (
        RecordClassificationDataset, make_record_file,
    )

    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (32, 4, 4, 1)).astype(np.uint8)
    labels = rng.randint(0, 10, 32).astype(np.int32)
    path = str(tmp_path / "imgs.bin")
    rb = make_record_file(path, images, labels)
    assert rb == 4 * 4 * 1 + 4
    ds = RecordClassificationDataset(path, (4, 4, 1), 8, num_batches=4)
    batches = list(ds)
    assert len(batches) == 4
    b = batches[0]
    assert b["image"].shape == (8, 4, 4, 1) and b["image"].dtype == np.float32
    assert b["label"].shape == (8,) and b["label"].dtype == np.int32
    assert 0.0 <= b["image"].min() and b["image"].max() <= 1.0
    # labels travel with their images through the shuffle
    ds2 = RecordClassificationDataset(path, (4, 4, 1), 8, num_batches=1,
                                      use_native=False)
    b2 = next(iter(ds2))
    np.testing.assert_array_equal(b["label"], b2["label"])
    np.testing.assert_allclose(b["image"], b2["image"])
