"""Multi-process DCN tests (SURVEY.md §4.3): real forked processes with
jax.distributed over localhost — init/psum, divergence detection,
multi-host checkpoint + resume, and coordinated preemption save. The
MultiProcessRunner analog ($TF multi_process_runner.py:107)."""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")
N = 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _clean_env() -> dict:
    env = dict(os.environ)
    # the workers set their own platform/device env before importing jax
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def run_cluster(scenario: str, workdir: str = "", extra=(), timeout=180,
                after_ready=None):
    coord = f"localhost:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, scenario, coord, str(N), str(pid),
             workdir or "-", *extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_clean_env(),
        )
        for pid in range(N)
    ]
    outs = []
    try:
        if after_ready is not None:
            # wait for every worker to print READY, then act (e.g. SIGTERM)
            deadline = time.time() + timeout
            ready = 0
            import select

            streams = {p.stdout: p for p in procs}
            buffers = {p: [] for p in procs}
            while ready < N and streams and time.time() < deadline:
                r, _, _ = select.select(list(streams), [], [], 1.0)
                for st in r:
                    line = st.readline()
                    if not line:  # EOF: worker died before READY
                        del streams[st]
                        continue
                    buffers[streams[st]].append(line)
                    if line.startswith("READY"):
                        ready += 1
            assert ready == N, (
                "workers never became READY:\n"
                + "\n---\n".join("".join(b) for b in buffers.values())
            )
            after_ready(procs)
            for p in procs:
                rest, _ = p.communicate(timeout=timeout)
                outs.append("".join(buffers[p]) + rest)
        else:
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} rc={p.returncode}:\n{out}"
    return outs


@pytest.mark.slow
def test_distributed_psum():
    outs = run_cluster("psum")
    for pid, out in enumerate(outs):
        assert f"PSUM-OK {pid}" in out, out


@pytest.mark.slow
def test_hybrid_mesh_two_process_step():
    """2-process ICI×DCN hybrid mesh trains one step with agreeing loss
    (VERDICT round-1 item 5)."""
    outs = run_cluster("hybrid")
    for pid, out in enumerate(outs):
        assert f"HYBRID-OK {pid}" in out, out


@pytest.mark.slow
def test_pipeline_stages_across_hosts():
    """dcn_pipe=2: pipeline stages live on DIFFERENT processes — every
    schedule hop (fwd ppermute and its backward transpose) crosses the
    host boundary, with dropout active through the tick."""
    outs = run_cluster("pipeline", timeout=300)
    for pid, out in enumerate(outs):
        assert f"PIPELINE-OK {pid}" in out, out


@pytest.mark.slow
def test_cross_host_divergence_detection():
    outs = run_cluster("divergence")
    for pid, out in enumerate(outs):
        assert f"AGREE-OK {pid}" in out, out
        assert f"DIVERGE-CAUGHT {pid}" in out, out


@pytest.mark.slow
def test_multihost_checkpoint_and_resume(tmp_path):
    d = str(tmp_path / "ckpt")
    outs = run_cluster("checkpoint", d)
    for pid, out in enumerate(outs):
        assert f"CKPT-OK {pid} step=10" in out, out
    # second cluster resumes from step 10 and reaches 20
    outs = run_cluster("checkpoint", d, extra=("--resume",))
    for pid, out in enumerate(outs):
        assert f"CKPT-OK {pid} step=20" in out, out


@pytest.mark.slow
def test_preemption_coordinated_save(tmp_path):
    d = str(tmp_path / "ckpt")

    def sigterm_host0(procs):
        time.sleep(1.0)  # let a few steps run
        procs[0].send_signal(signal.SIGTERM)

    outs = run_cluster("preempt", d, after_ready=sigterm_host0)
    for pid, out in enumerate(outs):
        assert f"PREEMPT-SAVED {pid}" in out, out
