"""Fused LayerNorm+matmul kernel tests (interpret mode on CPU): forward
and full gradient parity against the pure-jnp oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops.fused_ln_matmul import (
    ln_matmul,
    ln_matmul_reference,
)


def _mk(M=64, d=32, n=48, dtype=jnp.float32, seed=0):
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(M, d), dtype)
    gamma = jnp.asarray(r.rand(d) + 0.5, jnp.float32)
    beta = jnp.asarray(r.randn(d) * 0.1, jnp.float32)
    w = jnp.asarray(r.randn(d, n) * 0.1, dtype)
    bias = jnp.asarray(r.randn(n) * 0.1, jnp.float32)
    return x, gamma, beta, w, bias


@pytest.mark.parametrize("with_bias", [False, True])
def test_forward_matches_reference(with_bias):
    x, gamma, beta, w, bias = _mk()
    b = bias if with_bias else None
    got = ln_matmul(x, gamma, beta, w, b)
    want = ln_matmul_reference(x, gamma, beta, w, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("bwd_impl", ["xla", "pallas"])
def test_gradients_match_reference(bwd_impl):
    x, gamma, beta, w, bias = _mk(M=48, d=24, n=40)

    def loss(fn):
        def go(x, gamma, beta, w, bias):
            kw = {"bwd_impl": bwd_impl} if fn is ln_matmul else {}
            y = fn(x, gamma, beta, w, bias, **kw)
            return (y * jnp.cos(y)).mean()

        return go

    got = jax.grad(loss(ln_matmul), argnums=(0, 1, 2, 3, 4))(
        x, gamma, beta, w, bias
    )
    want = jax.grad(loss(ln_matmul_reference), argnums=(0, 1, 2, 3, 4))(
        x, gamma, beta, w, bias
    )
    for name, g, wn in zip(("dx", "dgamma", "dbeta", "dw", "dbias"),
                           got, want):
        assert g.shape == wn.shape, name
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(wn), rtol=2e-4, atol=2e-4,
            err_msg=name,
        )


def test_bf16_io_and_flax_ln_parity():
    """bf16 IO with f32 stats; and the kernel's LN matches flax
    nn.LayerNorm numerics (eps inside rsqrt) so the transformer
    integration is drop-in."""
    import flax.linen as nn

    x, gamma, beta, w, bias = _mk(M=128, d=64, n=64, dtype=jnp.bfloat16)
    got = ln_matmul(x, gamma, beta, w, bias)
    assert got.dtype == jnp.bfloat16

    ln = nn.LayerNorm(epsilon=1e-6, dtype=jnp.float32)
    h = ln.apply({"params": {"scale": gamma, "bias": beta}},
                 x.astype(jnp.float32)).astype(jnp.bfloat16)
    want = (jnp.dot(h, w, preferred_element_type=jnp.float32)
            + bias).astype(jnp.bfloat16)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2,
    )


@pytest.mark.slow
def test_transformer_fused_ln_matches_unfused():
    """TransformerConfig(fused_ln_matmul=True) produces the same logits
    and gradients as the unfused pre-LN path on the SAME params (the
    param trees are identical by construction), and rejects post-LN."""
    from distributed_tensorflow_tpu.models import transformer as tfm

    kw = dict(vocab_size=64, max_len=16, num_layers=2, d_model=32,
              num_heads=4, d_ff=64, causal=True, pre_ln=True,
              dropout=0.0, dtype="float32")
    m_plain = tfm.Transformer(tfm.TransformerConfig(**kw))
    m_fused = tfm.Transformer(
        tfm.TransformerConfig(fused_ln_matmul=True, **kw)
    )
    params, _ = tfm.make_init_fn(m_plain, 16)(jax.random.PRNGKey(0))
    params_f, _ = tfm.make_init_fn(m_fused, 16)(jax.random.PRNGKey(0))
    assert (jax.tree.structure(params) == jax.tree.structure(params_f))

    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (4, 16)), jnp.int32
    )
    want = m_plain.apply({"params": params}, ids, None, train=False)
    got = m_fused.apply({"params": params}, ids, None, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)

    def loss(model):
        def go(p):
            out = model.apply({"params": p}, ids, None, train=False)
            return (out ** 2).mean()
        return go

    g_plain = jax.grad(loss(m_plain))(params)
    g_fused = jax.grad(loss(m_fused))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4
        ),
        g_fused, g_plain,
    )

    # post-LN is structurally ineligible
    bad = tfm.Transformer(tfm.TransformerConfig(
        **{**kw, "pre_ln": False, "causal": False}, fused_ln_matmul=True
    ))
    with pytest.raises(ValueError, match="pre_ln"):
        tfm.make_init_fn(bad, 16)(jax.random.PRNGKey(1))
