import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.models import common
from distributed_tensorflow_tpu.models.resnet import (
    ResNet50,
    ResNetConfig,
    flops_per_example,
)


def tiny_cfg(**kw):
    defaults = dict(stage_sizes=(1, 1, 1, 1), width=8, num_classes=10,
                    dtype="float32")
    defaults.update(kw)
    return ResNetConfig(**defaults)


@pytest.mark.slow
def test_resnet_forward_shape_and_params():
    model = ResNet50(tiny_cfg())
    init_fn = common.make_init_fn(model, (32, 32, 3))
    params, mstate = init_fn(jax.random.PRNGKey(0))
    assert "batch_stats" in mstate
    logits = model.apply(
        {"params": params, **mstate}, jnp.zeros((2, 32, 32, 3)), train=False
    )
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_space_to_depth_stem():
    from distributed_tensorflow_tpu.models.resnet import space_to_depth

    # fold/unfold bookkeeping: channels carry the 2x2 patch
    x = jnp.arange(2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 3)
    y = space_to_depth(x, 2)
    assert y.shape == (2, 2, 2, 12)
    np.testing.assert_array_equal(y[0, 0, 0, :3], x[0, 0, 0])
    np.testing.assert_array_equal(y[0, 0, 0, 3:6], x[0, 0, 1])

    # stem kernel is the folded 4x4x(C*4) layout — shape-level only
    # (eval_shape: no compile; the compiled end-to-end twin is the slow
    # test below, so the fast tier stays under the 200s budget)
    cfg = tiny_cfg(stem="space_to_depth")
    model = ResNet50(cfg)
    init_fn = common.make_init_fn(model, (32, 32, 3))
    params, _ = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    assert params["stem_conv_s2d"]["kernel"].shape == (4, 4, 12, 8)
    assert flops_per_example(cfg, 32) != flops_per_example(tiny_cfg(), 32)


@pytest.mark.slow
def test_space_to_depth_stem_forward_compiles():
    cfg = tiny_cfg(stem="space_to_depth")
    model = ResNet50(cfg)
    params, mstate = common.make_init_fn(model, (32, 32, 3))(
        jax.random.PRNGKey(0)
    )
    logits = model.apply(
        {"params": params, **mstate}, jnp.zeros((2, 32, 32, 3)), train=False
    )
    assert logits.shape == (2, 10)


@pytest.mark.slow
def test_resnet_train_step_updates_bn_stats(mesh8):
    import optax

    from distributed_tensorflow_tpu.train import (
        init_train_state, jit_train_step, make_train_step,
    )

    model = ResNet50(tiny_cfg())
    loss_fn = common.classification_loss_fn(model)
    tx = optax.sgd(0.1)
    state, specs = init_train_state(
        common.make_init_fn(model, (16, 16, 3)), tx, mesh8, jax.random.PRNGKey(0)
    )
    before = np.asarray(
        jax.tree.leaves(state.model_state["batch_stats"])[0]
    ).copy()
    step = jit_train_step(make_train_step(loss_fn, tx), mesh8, specs)
    batch = {
        "image": jnp.asarray(np.random.RandomState(0).randn(8, 16, 16, 3),
                             jnp.float32),
        "label": jnp.zeros((8,), jnp.int32),
    }
    from jax.sharding import NamedSharding
    from distributed_tensorflow_tpu.parallel import sharding as sh

    batch = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh8, sh.batch_spec(x.ndim))),
        batch,
    )
    state, metrics = step(state, batch)
    after = np.asarray(jax.tree.leaves(state.model_state["batch_stats"])[0])
    assert not np.array_equal(before, after), "BN stats did not update"
    assert np.isfinite(float(metrics["loss"]))


def test_resnet50_flops_sane():
    # ResNet-50 ≈ 4.1 GMACs = 8.2 GFLOPs fwd @224 (fwd-only contract,
    # utils/flops.py; the ×3 train multiplier is the consumer's job)
    f = flops_per_example(ResNetConfig(), 224)
    assert 6.5e9 < f < 9.5e9, f


@pytest.mark.slow
def test_resnet_bf16_params_stay_f32():
    model = ResNet50(tiny_cfg(dtype="bfloat16"))
    params, _ = common.make_init_fn(model, (16, 16, 3))(jax.random.PRNGKey(0))
    kinds = {p.dtype for p in jax.tree.leaves(params)}
    assert kinds == {jnp.dtype("float32")}, kinds


@pytest.mark.slow
def test_fused_block_impl_matches_standard():
    """Same params through the fused-kernel blocks == the standard flax
    blocks, forward (train + eval) and gradients, and the batch_stats
    updates agree — the param trees are identical by construction."""
    cfg_std = tiny_cfg()
    cfg_fused = tiny_cfg(block_impl="fused")
    m_std = ResNet50(cfg_std)
    m_fused = ResNet50(cfg_fused)
    params, mstate = common.make_init_fn(m_std, (32, 32, 3))(
        jax.random.PRNGKey(0)
    )
    params_f, mstate_f = common.make_init_fn(m_fused, (32, 32, 3))(
        jax.random.PRNGKey(0)
    )
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a.shape, b.shape),
                 params, params_f)

    x = jnp.asarray(np.random.RandomState(0).randn(8, 32, 32, 3), jnp.float32)

    # eval forward
    e_std = m_std.apply({"params": params, **mstate}, x, train=False)
    e_fused = m_fused.apply({"params": params, **mstate}, x, train=False)
    np.testing.assert_allclose(np.asarray(e_fused), np.asarray(e_std),
                               rtol=1e-4, atol=1e-4)

    # train forward + batch_stats updates
    def fwd(model, p):
        out, mut = model.apply(
            {"params": p, **mstate}, x, train=True, mutable=["batch_stats"]
        )
        return out, mut["batch_stats"]

    t_std, bs_std = fwd(m_std, params)
    t_fused, bs_fused = fwd(m_fused, params)
    np.testing.assert_allclose(np.asarray(t_fused), np.asarray(t_std),
                               rtol=2e-3, atol=2e-3)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3
        ),
        bs_fused, bs_std,
    )

    # gradients
    def loss(model):
        def go(p):
            out, _ = model.apply(
                {"params": p, **mstate}, x, train=True,
                mutable=["batch_stats"],
            )
            return (out.astype(jnp.float32) ** 2).mean()
        return go

    g_std = jax.grad(loss(m_std))(params)
    g_fused = jax.grad(loss(m_fused))(params)
    flat_s, _ = jax.flatten_util.ravel_pytree(g_std)
    flat_f, _ = jax.flatten_util.ravel_pytree(g_fused)
    np.testing.assert_allclose(np.asarray(flat_f), np.asarray(flat_s),
                               rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_fused_block_impl_through_dp_mesh(devices):
    """Fused blocks under a data=8 mesh (shard_map psum stats) match the
    standard model under plain GSPMD on the same global batch."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=8), devices[:8])
    cfg_fused = tiny_cfg(block_impl="fused")
    m_std = ResNet50(tiny_cfg())
    m_fused = ResNet50(cfg_fused, mesh)
    params, mstate = common.make_init_fn(m_std, (32, 32, 3))(
        jax.random.PRNGKey(0)
    )
    x = jnp.asarray(np.random.RandomState(1).randn(16, 32, 32, 3), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("data",))))

    def fwd(model, p, xin):
        out, mut = model.apply(
            {"params": p, **mstate}, xin, train=True, mutable=["batch_stats"]
        )
        return out, mut["batch_stats"]

    want, bs_want = jax.jit(lambda p: fwd(m_std, p, x))(params)
    got, bs_got = jax.jit(lambda p: fwd(m_fused, p, xs))(params)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3
        ),
        bs_got, bs_want,
    )

    # gradients through shard_map + psum'd BN stats + the Pallas
    # custom_vjp — the exact path bench.py defaults to on TPU
    def loss(model, xin):
        def go(p):
            out, _ = model.apply(
                {"params": p, **mstate}, xin, train=True,
                mutable=["batch_stats"],
            )
            return (out.astype(jnp.float32) ** 2).mean()
        return go

    g_std = jax.jit(jax.grad(loss(m_std, x)))(params)
    g_fused = jax.jit(jax.grad(loss(m_fused, xs)))(params)
    flat_s, _ = jax.flatten_util.ravel_pytree(jax.device_get(g_std))
    flat_f, _ = jax.flatten_util.ravel_pytree(jax.device_get(g_fused))
    np.testing.assert_allclose(np.asarray(flat_f), np.asarray(flat_s),
                               rtol=5e-3, atol=5e-3)
