"""The driver entry points stay runnable, including the 16-way pod-shape
mesh point (VERDICT r3 item 7: pp=2 x model=2 x data=4).

dryrun_multichip(n) scales every case with n; at n=16 case 3 becomes the
pp2 x tp2 x dp4 pipeline mesh and case 1 becomes dp4 x fsdp2 x tp2.
These run in subprocesses because the virtual device count is fixed at
backend init (the test rig pins 8)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_dryrun(n):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    env.pop("DTF_CHIP_SESSION", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py"), str(n)],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    return out.stdout


@pytest.mark.slow
def test_dryrun_multichip_16way_pod_shape():
    stdout = _run_dryrun(16)
    assert "dryrun[pp/tp/dp] ok" in stdout, stdout
    assert "pipe=2" in stdout and "model=2" in stdout, stdout
    assert "dryrun_multichip ok" in stdout, stdout
