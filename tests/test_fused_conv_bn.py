"""Fused 1x1-conv + BN kernel tests (interpret mode on CPU): forward and
gradient parity against the pure-jnp oracle, for every prologue/stats
combination the ResNet integration uses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops.fused_conv_bn import (
    bn_scale_shift,
    conv1x1_bn_act,
    conv1x1_bn_act_reference,
    moments_from_sums,
)


def _mk(M=64, cin=32, cout=48, dtype=jnp.float32, seed=0):
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(M, cin), dtype)
    w = jnp.asarray(r.randn(cin, cout) * 0.1, dtype)
    gamma = jnp.asarray(r.rand(cin) + 0.5, jnp.float32)
    beta = jnp.asarray(r.randn(cin) * 0.1, jnp.float32)
    mean = jnp.asarray(r.randn(cin) * 0.2, jnp.float32)
    var = jnp.asarray(r.rand(cin) + 0.3, jnp.float32)
    scale, shift = bn_scale_shift(mean, var, gamma, beta, 1e-5)
    return x, w, scale, shift


@pytest.mark.parametrize("prologue", [False, True])
@pytest.mark.parametrize("relu", [False, True])
@pytest.mark.parametrize("emit_stats", [False, True])
def test_forward_matches_reference(prologue, relu, emit_stats):
    x, w, scale, shift = _mk()
    kw = dict(relu=relu, emit_stats=emit_stats)
    args = (x, w, scale, shift) if prologue else (x, w)
    got = conv1x1_bn_act(*args, **kw)
    want = conv1x1_bn_act_reference(*args, **kw)
    if emit_stats:
        for g, wnt in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(wnt), rtol=1e-5, atol=1e-4
            )
    else:
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-4
        )


@pytest.mark.parametrize("prologue", [False, True])
@pytest.mark.parametrize("bwd_impl,shape", [
    ("xla", (48, 24, 40)),
    ("pallas", (48, 24, 40)),    # tiny: two-pass fallback (bm < 64)
    ("pallas", (256, 32, 48)),   # larger: the single-pass kernel
], ids=["xla", "pallas-two-pass", "pallas-single-pass"])
def test_gradients_match_reference(prologue, bwd_impl, shape):
    """Full-pathway gradient check: the loss consumes y AND the emitted
    stats (through moments, like the next BN does), so the stats-output
    cotangent path into dy is exercised. The two pallas shapes route to
    the two-pass pair vs the single-pass kernel respectively — asserted
    against the picker so the ids stay honest."""
    from distributed_tensorflow_tpu.ops import _tiling

    M, cin, cout = shape
    single = _tiling.pick_single_pass_bm(
        M, cin, cout, in_bytes=4, emit_stats=True) is not None
    assert single == (shape == (256, 32, 48))

    x, w, scale, shift = _mk(M=M, cin=cin, cout=cout)

    def loss(fn):
        def go(x, w, scale, shift):
            args = (x, w, scale, shift) if prologue else (x, w)
            kw = {"bwd_impl": bwd_impl} if fn is conv1x1_bn_act else {}
            y, s, ssq = fn(*args, relu=True, emit_stats=True, **kw)
            mean, var = moments_from_sums(s, ssq, y.shape[0])
            return (
                (y * y).mean()
                + (mean * mean).sum()
                + jnp.sqrt(var + 1e-3).sum()
            )

        return go

    got = jax.grad(loss(conv1x1_bn_act), argnums=(0, 1, 2, 3))(
        x, w, scale, shift
    )
    want = jax.grad(loss(conv1x1_bn_act_reference), argnums=(0, 1, 2, 3))(
        x, w, scale, shift
    )
    names = ["dx", "dw", "dscale", "dshift"]
    n_checked = 4 if prologue else 2
    for name, g, wnt in list(zip(names, got, want))[:n_checked]:
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(wnt), rtol=2e-4, atol=2e-4,
            err_msg=name,
        )


def test_bf16_io_f32_accumulation():
    x, w, scale, shift = _mk(M=128, cin=64, cout=64, dtype=jnp.bfloat16)
    y, s, ssq = conv1x1_bn_act(x, w, scale, shift)
    assert y.dtype == jnp.bfloat16
    assert s.dtype == jnp.float32 and ssq.dtype == jnp.float32
    yr, sr, ssqr = conv1x1_bn_act_reference(x, w, scale, shift)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=2e-2, atol=2e-2,
    )
    # stats are computed on the quantized output -> exact match vs oracle
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ssq), np.asarray(ssqr), rtol=1e-5)


def test_moments_and_affine_helpers_match_batchnorm():
    r = np.random.RandomState(0)
    y = jnp.asarray(r.randn(256, 16), jnp.float32)
    s, ssq = y.sum(0), (y * y).sum(0)
    mean, var = moments_from_sums(s, ssq, y.shape[0])
    np.testing.assert_allclose(np.asarray(mean), np.asarray(y.mean(0)),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(y.var(0)),
                               rtol=1e-4, atol=1e-5)
    gamma = jnp.asarray(r.rand(16) + 0.5, jnp.float32)
    beta = jnp.asarray(r.randn(16), jnp.float32)
    scale, shift = bn_scale_shift(mean, var, gamma, beta, 1e-5)
    want = (y - mean) * gamma * jax.lax.rsqrt(var + 1e-5) + beta
    np.testing.assert_allclose(np.asarray(y * scale + shift),
                               np.asarray(want), rtol=1e-4, atol=1e-4)




def test_sharded_batch_partitions_without_gather(devices):
    """The fused op under GSPMD with a batch-sharded input must partition
    along M (zero all-gathers in the compiled HLO) and keep the output
    batch-sharded — the multi-chip data-parallel contract. (Interpret
    mode proves the CPU/virtual-mesh path; single-chip hardware cannot
    exercise the Mosaic partitioner — docs/kernels.md notes the gap.)"""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(devices[:8]).reshape(8), ("data",))
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(64, 32), jnp.float32)
    w = jnp.asarray(r.randn(32, 48) * 0.1, jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    ws = jax.device_put(w, NamedSharding(mesh, P(None, None)))

    def f(x, w):
        return conv1x1_bn_act(x, w, emit_stats=True)

    hlo = jax.jit(f).lower(xs, ws).compile().as_text()
    assert hlo.count("all-gather") == 0
    y, s, q = jax.jit(f)(xs, ws)
    yr, sr, qr = conv1x1_bn_act_reference(x, w)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr),
                               rtol=1e-5, atol=1e-4)
    assert "data" in str(y.sharding)


def test_pallas_bwd_known_slow_guard(monkeypatch):
    """VERDICT r3 weak #4: DTF_FUSED_BWD=pallas must refuse shapes whose
    Mosaic compile is known-pathological — warn, fall back to the XLA
    backward (same math), and still produce correct gradients.
    DTF_FUSED_BWD_FORCE=1 bypasses the guard (measurement runs)."""
    from distributed_tensorflow_tpu.ops import _tiling

    M, cin, cout = 48, 24, 40
    monkeypatch.setattr(
        _tiling, "PALLAS_BWD_KNOWN_SLOW", {(M, cin, cout)})
    monkeypatch.delenv("DTF_FUSED_BWD_FORCE", raising=False)
    # fresh custom_vjp closures: the op cache is keyed on bwd_impl only,
    # and the guard runs inside bwd at trace time, so no cache clear is
    # needed — but guard against a stale jit cache anyway
    jax.clear_caches()
    x, w, scale, shift = _mk(M=M, cin=cin, cout=cout)

    def loss(x, w, scale, shift):
        y, s, ssq = conv1x1_bn_act(
            x, w, scale, shift, relu=True, emit_stats=True,
            bwd_impl="pallas")
        mean, var = moments_from_sums(s, ssq, y.shape[0])
        return (y * y).mean() + (mean * mean).sum() + var.sum()

    def ref_loss(x, w, scale, shift):
        y, s, ssq = conv1x1_bn_act_reference(
            x, w, scale, shift, relu=True, emit_stats=True)
        mean, var = moments_from_sums(s, ssq, y.shape[0])
        return (y * y).mean() + (mean * mean).sum() + var.sum()

    with pytest.warns(UserWarning, match="known to stall"):
        got = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, scale, shift)
    want = jax.grad(ref_loss, argnums=(0, 1, 2, 3))(x, w, scale, shift)
    for g, wnt in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(wnt), rtol=2e-4, atol=2e-4)

    # FORCE bypass: no warning, pallas path taken (still correct)
    monkeypatch.setenv("DTF_FUSED_BWD_FORCE", "1")
    jax.clear_caches()
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("error", UserWarning)
        got2 = jax.grad(loss, argnums=(0, 1, 2, 3))(x, w, scale, shift)
    for g, wnt in zip(got2, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(wnt), rtol=2e-4, atol=2e-4)
