import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel import sharding as sh
from distributed_tensorflow_tpu.parallel import DATA, FSDP, MODEL


def test_spec_from_logical():
    spec = sh.spec_from_logical(["batch", "embed"], sh.TP_RULES)
    assert spec == P(("data", "fsdp"), None)
    spec = sh.spec_from_logical(["embed", "mlp"], sh.TP_RULES)
    assert spec == P(None, "model")


def test_path_rules_first_match_wins():
    tree = {"dense1": {"kernel": jnp.zeros((4, 4)), "bias": jnp.zeros((4,))}}
    rules = [
        (r"kernel", P(None, MODEL)),
        (r".*", P()),
    ]
    specs = sh.specs_from_path_rules(tree, rules)
    assert specs["dense1"]["kernel"] == P(None, MODEL)
    assert specs["dense1"]["bias"] == P()


def test_shard_tree_and_batch(mesh_dp4_tp2):
    x = jnp.zeros((8, 16))
    sharded = jax.device_put(
        x, sh.named_sharding(mesh_dp4_tp2, sh.batch_spec(2))
    )
    # batch dim split over data*fsdp = 4 shards
    assert sharded.sharding.spec == P(("data", "fsdp"), None)
    shard_shapes = {s.data.shape for s in sharded.addressable_shards}
    assert shard_shapes == {(2, 16)}


def test_auto_fsdp_specs():
    devices = jax.devices()[:8]
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=2, fsdp=4), devices)
    params = {
        "big": jnp.zeros((128, 256)),
        "small": jnp.zeros((4,)),
        "odd": jnp.zeros((33333,)),  # not divisible by 4
    }
    specs = sh.auto_fsdp_specs(params, mesh, min_size=16)
    assert specs["big"] == P(None, FSDP)
    assert specs["small"] == P()
    assert specs["odd"] == P()


def test_replicate(mesh8):
    tree = {"w": jnp.ones((4, 4))}
    rep = sh.replicate(tree, mesh8)
    assert rep["w"].sharding.spec == P()


# ---------------------------------------------------------------------------
# Partition-rules engine (PR 14): tables, coverage contract, attribution
# ---------------------------------------------------------------------------

import pytest

from distributed_tensorflow_tpu.models import resnet as resnet_lib
from distributed_tensorflow_tpu.models import transformer as tfm
from distributed_tensorflow_tpu.models import wide_deep as wd
from distributed_tensorflow_tpu.models import common as models_common
from distributed_tensorflow_tpu.ops.moe import moe_rules


def _leaf(*shape):
    return jnp.zeros(shape or (2,))


def test_partition_rules_first_match_precedence():
    """Overlapping regexes: the earlier row wins the contested path,
    the later row stays alive on the paths the earlier one misses."""
    table = sh.partition_rules(
        "t-precedence",
        (
            (r"a/kernel", P(None, MODEL)),
            (r"kernel", P(MODEL, None)),   # also matches a/kernel
            (sh.CATCH_ALL, sh.REPLICATED),
        ),
    )
    tree = {"a": {"kernel": _leaf(4, 4)}, "b": {"kernel": _leaf(4, 4)},
            "c": {"bias": _leaf()}}
    specs = sh.match_partition_rules(table, tree)
    assert specs["a"]["kernel"] == P(None, MODEL)   # rule 0, not rule 1
    assert specs["b"]["kernel"] == P(MODEL, None)
    assert specs["c"]["bias"] == P()


def test_partition_rules_unmatched_param_is_hard_error():
    table = sh.partition_rules(
        "t-unmatched", ((r"kernel$", P(None, MODEL)),))
    tree = {"a": {"kernel": _leaf(4, 4)}, "b": {"bias": _leaf()}}
    with pytest.raises(sh.PartitionCoverageError) as ei:
        sh.match_partition_rules(table, tree)
    msg = str(ei.value)
    # the full attribution listing names the orphan and the winner
    assert "b/bias  <-  UNMATCHED" in msg
    assert "a/kernel" in msg and "rule[0]" in msg
    assert "1 unmatched param(s)" in msg


def test_partition_rules_dead_rule_is_hard_error():
    table = sh.partition_rules(
        "t-dead",
        ((r"kernel$", P(None, MODEL)),
         (r"kernle$", P(MODEL, None)),    # typo: matches nothing
         (sh.CATCH_ALL, sh.REPLICATED)),
    )
    tree = {"a": {"kernel": _leaf(4, 4)}, "b": {"bias": _leaf()}}
    with pytest.raises(sh.PartitionCoverageError) as ei:
        sh.match_partition_rules(table, tree)
    msg = str(ei.value)
    assert "1 dead rule(s)" in msg
    assert "'kernle$'" in msg and "DEAD" in msg


def test_partition_rules_construction_validation():
    with pytest.raises(ValueError, match="does not compile"):
        sh.partition_rules("t-bad-rx", ((r"kernel[", P()),))
    with pytest.raises(ValueError, match="must be a PartitionSpec"):
        sh.partition_rules("t-bad-spec", ((r"kernel", "model"),))
    with pytest.raises(ValueError, match="must be"):
        sh.partition_rules("t-bad-arity", ((r"kernel",),))


def test_partition_rules_coverage_contract_checked_at_construction():
    """A table that cannot cover its own static fixture fails at
    authoring time, not at the first training run."""
    with pytest.raises(sh.PartitionCoverageError, match="coverage contract"):
        sh.partition_rules(
            "t-cov", ((r"kernel$", P(None, MODEL)),),
            coverage=("a/kernel", "a/bias"))
    # total + live: constructs fine
    t = sh.partition_rules(
        "t-cov-ok",
        ((r"kernel$", P(None, MODEL)), (sh.CATCH_ALL, sh.REPLICATED)),
        coverage=("a/kernel", "a/bias"))
    assert t.coverage == ("a/kernel", "a/bias")


def test_partition_rules_select_variants():
    table = sh.partition_rules(
        "t-var",
        ((r"qkv/kernel", P(None, MODEL), "fused"),
         (r"(query|key|value)/kernel", P(None, MODEL), "split"),
         (sh.CATCH_ALL, sh.REPLICATED)),
    )
    fused = table.select("fused")
    assert [r.pattern for r in fused.rows] == [r"qkv/kernel", sh.CATCH_ALL]
    assert fused.name == "t-var[fused]"
    # the un-selected variant row would be dead on a fused tree — and
    # with select() it is gone instead
    tree = {"attn": {"qkv": {"kernel": _leaf(4, 12)}}, "ln": {"b": _leaf()}}
    specs = sh.match_partition_rules(fused, tree)
    assert specs["attn"]["qkv"]["kernel"] == P(None, MODEL)
    with pytest.raises(sh.PartitionCoverageError):
        sh.match_partition_rules(table, tree)  # unselected: dead rows


def test_attribution_listing_and_soft_dispatch():
    table = sh.partition_rules(
        "t-attr", ((r"kernel$", P(None, MODEL)),))
    tree = {"a": {"kernel": _leaf(4, 4)}, "b": {"bias": _leaf()}}
    matches = sh.attribute_partition_rules(table, tree)
    assert [(m.path, m.rule_index) for m in matches] == [
        ("a/kernel", 0), ("b/bias", -1)]
    listing = sh.format_attribution(table, matches)
    assert "a/kernel  <-  rule[0] 'kernel$'" in listing
    assert "b/bias  <-  UNMATCHED" in listing
    # specs_from_rules: tables are strict, legacy sequences stay soft
    with pytest.raises(sh.PartitionCoverageError):
        sh.specs_from_rules(tree, table)
    soft = sh.specs_from_rules(tree, table.as_path_rules())
    assert soft["b"]["bias"] == P()  # replicate-on-miss


# ---------------------------------------------------------------------------
# Migration parity: rules-table specs == the pre-engine hand-authored ones
# ---------------------------------------------------------------------------

#: The pre-PR-14 hand-authored megatron rules (models/transformer.py
#: TP_PATH_RULES at PR 13), frozen here verbatim as the parity oracle.
_LEGACY_TP_PATH_RULES = (
    (r"(query|key|value)/kernel", P(None, "model")),
    (r"(query|key|value)/bias", P("model")),
    (r"qkv/kernel", P(None, "model")),
    (r"qkv/bias", P("model")),
    (r"attn_out/kernel", P("model", None)),
    (r"mlp_in/kernel", P(None, "model")),
    (r"mlp_in/bias", P("model")),
    (r"mlp_out/kernel", P("model", None)),
    (r"tok_embed/embedding", P("model", None)),
    (r"mlm_bias", P("model")),
)


def _tiny_tfm_cfg(**kw):
    base = dict(vocab_size=64, max_len=32, num_layers=2, d_model=32,
                num_heads=4, d_ff=64, dropout=0.0, dtype="float32")
    base.update(kw)
    return tfm.TransformerConfig(**base)


_TFM_VARIANTS = {
    "bert": {},
    "causal_fused": dict(causal=True, pre_ln=True, fused_qkv=True),
    "moe": dict(num_experts=4, moe_every=2),
}


def _tfm_abstract_params(cfg):
    init_fn = tfm.make_init_fn(tfm.Transformer(cfg), 16)
    return jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0]


@pytest.mark.parametrize("variant", sorted(_TFM_VARIANTS))
def test_transformer_rules_match_legacy_hand_authored_specs(variant):
    """match_partition_rules(transformer_rules(cfg)) is bit-identical to
    the PR 13 soft path-rules resolution for every shipped variant."""
    cfg = _tiny_tfm_cfg(**_TFM_VARIANTS[variant])
    params = _tfm_abstract_params(cfg)
    got = sh.match_partition_rules(tfm.transformer_rules(cfg), params)
    want = sh.specs_from_path_rules(
        params, tuple(moe_rules()) + _LEGACY_TP_PATH_RULES)
    assert jax.tree.structure(got, is_leaf=lambda x: isinstance(x, P)) \
        == jax.tree.structure(want, is_leaf=lambda x: isinstance(x, P))
    mismatches = [
        (sh._path_str(p), a, b)
        for (p, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(got),
            jax.tree_util.tree_leaves_with_path(want))
        if a != b
    ]
    assert mismatches == []


def test_resnet_rules_match_legacy_replicated_specs():
    """ResNet previously shipped NO param rules (everything replicated);
    the one-row catch-all table must derive the identical spec tree."""
    cfg = resnet_lib.ResNetConfig(stage_sizes=(1, 1), width=8,
                                  num_classes=10, dtype="float32")
    model = resnet_lib.ResNet50(cfg)
    params = jax.eval_shape(
        models_common.make_init_fn(model, (16, 16, 3)),
        jax.random.PRNGKey(0))[0]
    got = sh.match_partition_rules(resnet_lib.RESNET_RULES, params)
    want = sh.replicated_specs(params)
    assert all(jax.tree.leaves(jax.tree.map(
        lambda a, b: a == b, got, want,
        is_leaf=lambda x: isinstance(x, P))))


def test_wide_deep_rules_match_legacy_embedding_rules():
    """The pre-PR-14 wide&deep path rules, frozen verbatim: unanchored
    table_\\d+ (which also swallowed wide_table_*, same spec) + the soft
    replicate-on-miss default."""
    legacy = (
        (r"table_\d+", P("model", None)),
        (r"wide_table_\d+", P("model", None)),
    )
    params = jax.eval_shape(
        wd.make_init_fn(wd.WideDeepConfig()), jax.random.PRNGKey(0))[0]
    got = sh.match_partition_rules(wd.WIDE_DEEP_RULES, params)
    want = sh.specs_from_path_rules(params, legacy)
    mismatches = [
        (sh._path_str(p), a, b)
        for (p, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(got),
            jax.tree_util.tree_leaves_with_path(want))
        if a != b
    ]
    assert mismatches == []


def test_transformer_moe_rows_mirror_moe_rules():
    """The four 'moe'-tagged table rows are exactly ops.moe.moe_rules()
    (pattern AND spec) — the table cannot drift from the op's layout."""
    tagged = [(r.pattern, r.spec) for r in tfm.TRANSFORMER_RULES.rows
              if r.tag == "moe"]
    assert tagged == list(map(tuple, moe_rules()))


# ---------------------------------------------------------------------------
# Coverage fixtures are live: the frozen path lists == the real models
# ---------------------------------------------------------------------------


def _paths(tree):
    return sorted(
        sh._path_str(p)
        for p, _ in jax.tree_util.tree_leaves_with_path(tree))


def test_transformer_coverage_fixture_is_live():
    union = set()
    for kw in _TFM_VARIANTS.values():
        union.update(_paths(_tfm_abstract_params(_tiny_tfm_cfg(**kw))))
    assert sorted(union) == sorted(tfm.TRANSFORMER_RULES.coverage)


def test_resnet_coverage_fixture_is_live():
    cfg = resnet_lib.ResNetConfig(stage_sizes=(1, 1), width=8,
                                  num_classes=10, dtype="float32")
    params = jax.eval_shape(
        models_common.make_init_fn(resnet_lib.ResNet50(cfg), (16, 16, 3)),
        jax.random.PRNGKey(0))[0]
    assert _paths(params) == sorted(resnet_lib.RESNET_RULES.coverage)


def test_wide_deep_coverage_fixture_is_live():
    params = jax.eval_shape(
        wd.make_init_fn(wd.WideDeepConfig()), jax.random.PRNGKey(0))[0]
    assert _paths(params) == sorted(wd.WIDE_DEEP_RULES.coverage)
