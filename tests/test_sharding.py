import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel import sharding as sh
from distributed_tensorflow_tpu.parallel import DATA, FSDP, MODEL


def test_spec_from_logical():
    spec = sh.spec_from_logical(["batch", "embed"], sh.TP_RULES)
    assert spec == P(("data", "fsdp"), None)
    spec = sh.spec_from_logical(["embed", "mlp"], sh.TP_RULES)
    assert spec == P(None, "model")


def test_path_rules_first_match_wins():
    tree = {"dense1": {"kernel": jnp.zeros((4, 4)), "bias": jnp.zeros((4,))}}
    rules = [
        (r"kernel", P(None, MODEL)),
        (r".*", P()),
    ]
    specs = sh.specs_from_path_rules(tree, rules)
    assert specs["dense1"]["kernel"] == P(None, MODEL)
    assert specs["dense1"]["bias"] == P()


def test_shard_tree_and_batch(mesh_dp4_tp2):
    x = jnp.zeros((8, 16))
    sharded = jax.device_put(
        x, sh.named_sharding(mesh_dp4_tp2, sh.batch_spec(2))
    )
    # batch dim split over data*fsdp = 4 shards
    assert sharded.sharding.spec == P(("data", "fsdp"), None)
    shard_shapes = {s.data.shape for s in sharded.addressable_shards}
    assert shard_shapes == {(2, 16)}


def test_auto_fsdp_specs():
    devices = jax.devices()[:8]
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=2, fsdp=4), devices)
    params = {
        "big": jnp.zeros((128, 256)),
        "small": jnp.zeros((4,)),
        "odd": jnp.zeros((33333,)),  # not divisible by 4
    }
    specs = sh.auto_fsdp_specs(params, mesh, min_size=16)
    assert specs["big"] == P(None, FSDP)
    assert specs["small"] == P()
    assert specs["odd"] == P()


def test_replicate(mesh8):
    tree = {"w": jnp.ones((4, 4))}
    rep = sh.replicate(tree, mesh8)
    assert rep["w"].sharding.spec == P()
