"""Pipeline-parallel tests: schedule parity vs sequential oracle (forward
and gradients), pp×dp composition through the train engine — the
strategy_test_lib-style distributed-correctness oracles of SURVEY.md §4.4."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.models import transformer as tfm
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.parallel import sharding as sh
from distributed_tensorflow_tpu.parallel.pipeline import (
    microbatch,
    pipeline_apply,
    stack_stages,
    unmicrobatch,
)
from distributed_tensorflow_tpu.train import (
    StepOptions, init_train_state, jit_train_step, make_train_step,
)


def test_microbatch_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    mb = microbatch(x, 4)
    assert mb.shape == (4, 3, 2)
    np.testing.assert_array_equal(unmicrobatch(mb), x)
    with pytest.raises(ValueError, match="not divisible"):
        microbatch(x, 5)


def _toy_stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _toy_params(key, n_stages, d):
    keys = jax.random.split(key, n_stages)
    return stack_stages([
        {"w": jax.random.normal(k, (d, d)) * 0.5, "b": jnp.zeros((d,))}
        for k in keys
    ])


def _toy_sequential(params, x_mb):
    def per_mb(x):
        def body(x, p):
            return _toy_stage_fn(p, x), None

        y, _ = jax.lax.scan(body, x, params)
        return y

    return jax.vmap(per_mb)(x_mb)


def test_pipeline_matches_sequential(devices):
    mesh = build_mesh(MeshSpec(pipe=4, data=2), devices[:8])
    params = _toy_params(jax.random.PRNGKey(0), 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, 8))  # [M, mb, d]
    want = _toy_sequential(params, x)
    got = jax.jit(
        lambda p, x: pipeline_apply(_toy_stage_fn, p, x, mesh)
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_pipeline_gradients_match(devices):
    mesh = build_mesh(MeshSpec(pipe=4), devices[:4])
    params = _toy_params(jax.random.PRNGKey(0), 4, 4)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 4))

    def loss_pipe(p):
        return (pipeline_apply(_toy_stage_fn, p, x, mesh) ** 2).sum()

    def loss_seq(p):
        return (_toy_sequential(p, x) ** 2).sum()

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.jit(jax.grad(loss_seq))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_pipe, g_seq,
    )


def test_pipeline_rejects_too_few_microbatches(devices):
    mesh = build_mesh(MeshSpec(pipe=4), devices[:4])
    params = _toy_params(jax.random.PRNGKey(0), 4, 4)
    x = jnp.zeros((2, 2, 4))  # M=2 < S=4
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(_toy_stage_fn, params, x, mesh)


def _toy_chunks(key, n_chunks, d):
    keys = jax.random.split(key, n_chunks)
    return stack_stages([
        {"w": jax.random.normal(k, (d, d)) * 0.5, "b": jnp.zeros((d,))}
        for k in keys
    ])


def test_interleaved_matches_sequential(devices):
    """V=2 circular schedule == scanning all S*V chunks in order."""
    S, V, d = 4, 2, 8
    mesh = build_mesh(MeshSpec(pipe=S, data=2), devices[:8])
    flat = _toy_chunks(jax.random.PRNGKey(0), S * V, d)  # [S*V, ...]
    # device layout [S, V, ...]: chunk c = v*S + stage
    dev = jax.tree.map(
        lambda p: p.reshape(V, S, *p.shape[1:]).swapaxes(0, 1), flat
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, d))  # [M, mb, d]
    want = _toy_sequential(flat, x)
    got = jax.jit(
        lambda p, x: pipeline_apply(_toy_stage_fn, p, x, mesh, n_virtual=V)
    )(dev, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_interleaved_gradients_match(devices):
    S, V, d = 2, 2, 4
    mesh = build_mesh(MeshSpec(pipe=S), devices[:2])
    flat = _toy_chunks(jax.random.PRNGKey(0), S * V, d)
    dev = jax.tree.map(
        lambda p: p.reshape(V, S, *p.shape[1:]).swapaxes(0, 1), flat
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, d))

    def loss_pipe(p):
        return (pipeline_apply(_toy_stage_fn, p, x, mesh,
                               n_virtual=V) ** 2).sum()

    def loss_seq(p):
        return (_toy_sequential(p, x) ** 2).sum()

    g_pipe = jax.jit(jax.grad(loss_pipe))(dev)
    g_seq = jax.jit(jax.grad(loss_seq))(flat)
    g_pipe_flat = jax.tree.map(
        lambda p: p.swapaxes(0, 1).reshape(S * V, *p.shape[2:]), g_pipe
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_pipe_flat, g_seq,
    )


def test_interleaved_rejects_misaligned_microbatches(devices):
    mesh = build_mesh(MeshSpec(pipe=4), devices[:4])
    params = jax.tree.map(
        lambda p: p.reshape(2, 4, *p.shape[1:]).swapaxes(0, 1),
        _toy_chunks(jax.random.PRNGKey(0), 8, 4),
    )
    x = jnp.zeros((6, 2, 4))  # M=6 not divisible by S=4
    with pytest.raises(ValueError, match="divisible"):
        pipeline_apply(_toy_stage_fn, params, x, mesh, n_virtual=2)


def _tiny_cfg(**kw):
    base = dict(vocab_size=64, max_len=16, num_layers=4, d_model=32,
                num_heads=4, d_ff=64, causal=True, pre_ln=True,
                dtype="float32", dropout=0.0)
    base.update(kw)
    return tfm.TransformerConfig(**base)


def test_pipeline_params_roundtrip():
    cfg = _tiny_cfg()
    params, _ = tfm.make_init_fn(tfm.Transformer(cfg), 16)(
        jax.random.PRNGKey(0)
    )
    pparams = tfm.to_pipeline_params(params, cfg, n_stages=2)
    assert pparams["blocks"]["attn"]["query"]["kernel"].shape[:2] == (2, 2)
    back = tfm.from_pipeline_params(pparams, cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        params, back,
    )


def test_pipelined_transformer_rejects_moe():
    cfg = _tiny_cfg(num_experts=4)
    with pytest.raises(ValueError, match="homogeneous"):
        tfm.make_pipelined_init_fn(cfg, n_stages=2, seq_len=16)


@pytest.mark.parametrize("family", [
    pytest.param("gpt", marks=pytest.mark.slow), "bert"])
def test_pipelined_transformer_matches_dense(devices, family):
    """Same weights through the pipeline schedule == the dense flax
    forward (the family shares the Block module, so this is an exact
    schedule-correctness oracle — including the masked/aux path for
    BERT)."""
    cfg = (
        _tiny_cfg()
        if family == "gpt"
        else _tiny_cfg(causal=False, pre_ln=False)
    )
    mesh = build_mesh(MeshSpec(pipe=4, data=2), devices[:8])
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)), jnp.int32)
    mask = None
    if family == "bert":
        mask = jnp.asarray(rng.rand(8, 16) < 0.9, jnp.int32)
    want = model.apply({"params": params}, ids, mask, train=False)
    pparams = tfm.to_pipeline_params(params, cfg, n_stages=4)
    got = jax.jit(
        lambda p, i: tfm.pipelined_apply(p, i, mask, cfg, mesh,
                                         n_microbatches=4)
    )(pparams, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_pipelined_transformer_interleaved_matches_dense(devices):
    """num_layers=4 over pipe=2 with n_virtual=2 (4 chunks of 1 layer,
    each device owning chunks {d, d+2}) == the dense forward; round-trip
    back to the dense layout is exact."""
    cfg = _tiny_cfg()
    mesh = build_mesh(MeshSpec(pipe=2, data=2), devices[:4])
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)), jnp.int32)
    want = model.apply({"params": params}, ids, None, train=False)
    pparams = tfm.to_pipeline_params(params, cfg, n_stages=2, n_virtual=2)
    assert pparams["blocks"]["attn"]["query"]["kernel"].shape[:3] == (2, 2, 1)
    got = jax.jit(
        lambda p, i: tfm.pipelined_apply(p, i, None, cfg, mesh,
                                         n_microbatches=4, n_virtual=2)
    )(pparams, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)
    back = tfm.from_pipeline_params(pparams, cfg, n_virtual=2)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        params, back,
    )


@pytest.mark.slow
def test_pipelined_transformer_trains(devices):
    """Full train-engine integration on a pipe=2 × data=2 × fsdp=2 mesh:
    loss decreases on the deterministic-walk corpus."""
    cfg = _tiny_cfg()
    mesh = build_mesh(MeshSpec(pipe=2, data=2, fsdp=2), devices[:8])
    tx = optax.adam(3e-3)
    init_fn = tfm.make_pipelined_init_fn(cfg, n_stages=2, seq_len=16)
    state, specs = init_train_state(
        init_fn, tx, mesh, jax.random.PRNGKey(0),
        param_specs=tfm.pipeline_param_specs(
            jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0]
        ),
    )
    assert (
        state.params["blocks"]["attn"]["query"]["kernel"].sharding.spec[0]
        == "pipe"
    )
    step = jit_train_step(
        make_train_step(tfm.pipelined_lm_loss_fn(cfg, mesh, 4), tx,
                        StepOptions(check_grads_finite=True)),
        mesh, specs,
    )
    rng = np.random.RandomState(0)
    losses = []
    for i in range(25):
        start = rng.randint(0, cfg.vocab_size, (16, 1))
        ids = (start + np.arange(16)[None]) % cfg.vocab_size
        batch = {"input_ids": jnp.asarray(ids, jnp.int32)}
        batch = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, sh.batch_spec(x.ndim))
            ),
            batch,
        )
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
        assert float(metrics["grads_finite"]) == 1.0
    assert losses[-1] < losses[0] * 0.8, losses


@pytest.mark.slow
def test_pipelined_transformer_pp_tp_matches_dense(devices):
    """PP×TP: pipe=2 × model=2 × data=2 — manual megatron TP inside the
    pipeline island (column/row slices + psum, Block.tp_shards) must
    reproduce the dense flax forward exactly, and the gradients must
    match the dense model's gradients transposed into the pipe layout."""
    cfg = _tiny_cfg()
    mesh = build_mesh(MeshSpec(pipe=2, model=2, data=2), devices[:8])
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)), jnp.int32)
    want = model.apply({"params": params}, ids, None, train=False)
    pparams = tfm.to_pipeline_params(params, cfg, n_stages=2)
    got = jax.jit(
        lambda p, i: tfm.pipelined_apply(p, i, None, cfg, mesh,
                                         n_microbatches=4)
    )(pparams, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    # gradient parity: d mean(logits^2) — dense grads transposed to the
    # pipe layout == grads through the PP×TP schedule
    def dense_loss(p):
        lg = model.apply({"params": p}, ids, None, train=False)
        return (lg ** 2).mean()

    def piped_loss(pp):
        lg = tfm.pipelined_apply(pp, ids, None, cfg, mesh,
                                 n_microbatches=4)
        return (lg ** 2).mean()

    g_dense = jax.jit(jax.grad(dense_loss))(params)
    want_g = tfm.to_pipeline_params(g_dense, cfg, n_stages=2)
    got_g = jax.jit(jax.grad(piped_loss))(pparams)
    flat_w = jax.tree_util.tree_leaves_with_path(want_g)
    flat_g = jax.tree_util.tree_leaves_with_path(got_g)
    for (path, w), (_, g) in zip(flat_w, flat_g):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-4,
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.slow
def test_pipelined_transformer_pp_tp_trains(devices):
    """Train-engine integration on pipe=2 × model=2 × data=2: the stacked
    leaves shard over BOTH pipe and model (pipeline_param_specs(tp=True))
    and the loss decreases."""
    cfg = _tiny_cfg()
    mesh = build_mesh(MeshSpec(pipe=2, model=2, data=2), devices[:8])
    tx = optax.adam(3e-3)
    init_fn = tfm.make_pipelined_init_fn(cfg, n_stages=2, seq_len=16)
    specs = tfm.pipeline_param_specs(
        jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0], tp=True
    )
    # kernels must actually carry the model axis (vacuity guard)
    qk = specs["blocks"]["attn"]["query"]["kernel"]
    ok_ = specs["blocks"]["attn"]["attn_out"]["kernel"]
    assert qk[-1] == "model" and ok_[-2] == "model", (qk, ok_)
    state, sspecs = init_train_state(
        init_fn, tx, mesh, jax.random.PRNGKey(0), param_specs=specs,
    )
    step = jit_train_step(
        make_train_step(
            tfm.pipelined_lm_loss_fn(cfg, mesh, n_microbatches=4), tx,
            StepOptions(check_grads_finite=True)), mesh, sspecs,
    )
    rng = np.random.RandomState(1)
    ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    batch = {"input_ids": jax.device_put(
        jnp.asarray(ids), NamedSharding(mesh, sh.batch_spec(2)))}
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)
        assert float(metrics["grads_finite"]) == 1.0
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_block_tp_guards():
    """Manual-TP misuse fails loudly: indivisible heads/d_ff, MoE, and
    fused-LN are all rejected."""
    x = jnp.zeros((2, 8, 32), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        tfm.Block(_tiny_cfg(num_heads=3), tp_shards=2).init(
            jax.random.PRNGKey(0), x, None, False)
    with pytest.raises(ValueError, match="d_ff"):
        tfm.Block(_tiny_cfg(d_ff=66), tp_shards=4).init(
            jax.random.PRNGKey(0), x, None, False)
    with pytest.raises(ValueError, match="MoE"):
        tfm.Block(_tiny_cfg(num_experts=2), None, True, tp_shards=2).init(
            jax.random.PRNGKey(0), x, None, False)
    with pytest.raises(ValueError, match="fused_ln_matmul"):
        tfm.Block(_tiny_cfg(fused_ln_matmul=True), tp_shards=2).init(
            jax.random.PRNGKey(0), x, None, False)


def test_pipelined_transformer_pp_tp_interleaved_matches_dense(devices):
    """PP×TP × interleaved: the [S, V, lc, ...] stacking must place the
    `model` axis on the same trailing kernel dims (a wrong-but-square
    placement on the d_model×d_model qkv kernels would still be
    shape-compatible — only numerical parity catches it)."""
    cfg = _tiny_cfg()  # 4 layers
    mesh = build_mesh(MeshSpec(pipe=2, model=2, data=2), devices[:8])
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)), jnp.int32)
    want = model.apply({"params": params}, ids, None, train=False)
    pparams = tfm.to_pipeline_params(params, cfg, n_stages=2, n_virtual=2)
    got = jax.jit(
        lambda p, i: tfm.pipelined_apply(p, i, None, cfg, mesh,
                                         n_microbatches=4, n_virtual=2)
    )(pparams, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_pipeline_apply_rejects_param_specs_on_degenerate_mesh(devices):
    """pipe=1 runs outside shard_map: TP param_specs must be rejected,
    not silently dropped (a TP stage_fn's psum would hit unbound axes)."""
    mesh = build_mesh(MeshSpec(data=2), devices[:2])
    params = _toy_params(jax.random.PRNGKey(0), 1, 8)
    x_mb = jnp.ones((2, 4, 8))
    with pytest.raises(ValueError, match="degenerate"):
        pipeline_apply(_toy_stage_fn, params, x_mb, mesh,
                       param_specs=jax.tree.map(
                           lambda _: P("pipe"), params,
                       ))


@pytest.mark.slow
def test_pipelined_dropout_schedule_independent(devices):
    """Dropout through the pipeline (VERDICT r2 item 7): the per-
    (microbatch, global-layer, batch-shard) key derivation must be
    independent of the S>1 (S, V) schedule decomposition — pipe=2/V=1,
    pipe=2/V=2 and pipe=4/V=1 draw the SAME masks at a fixed batch
    sharding — and must actually drop (differs from train=False)."""
    cfg = _tiny_cfg(dropout=0.5)
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (8, 16)), jnp.int32)
    key = jax.random.PRNGKey(7)

    outs = []
    for spec, n_devs, n_stages, n_virtual in (
        (MeshSpec(pipe=2, data=2), 4, 2, 1),
        (MeshSpec(pipe=2, data=2), 4, 2, 2),
        (MeshSpec(pipe=4, data=2), 8, 4, 1),
    ):
        mesh = build_mesh(spec, devices[:n_devs])
        pp = tfm.to_pipeline_params(params, cfg, n_stages=n_stages,
                                    n_virtual=n_virtual)
        outs.append(jax.jit(
            lambda p, i, k, mesh=mesh, nv=n_virtual: tfm.pipelined_apply(
                p, i, None, cfg, mesh, n_microbatches=4, n_virtual=nv,
                train=True, rng=k,
            )
        )(pp, ids, key))

    np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs[1]),
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs[2]),
                               atol=2e-4)

    mesh = build_mesh(MeshSpec(pipe=2, data=2), devices[:4])
    pp = tfm.to_pipeline_params(params, cfg, n_stages=2)
    eval_out = jax.jit(
        lambda p, i: tfm.pipelined_apply(p, i, None, cfg, mesh,
                                         n_microbatches=4)
    )(pp, ids)
    assert not np.allclose(np.asarray(outs[0]), np.asarray(eval_out)), (
        "dropout had no effect")
    # a different key draws different masks (keys really reach the blocks)
    other = jax.jit(
        lambda p, i, k: tfm.pipelined_apply(
            p, i, None, cfg, mesh, n_microbatches=4, train=True, rng=k)
    )(pp, ids, jax.random.PRNGKey(8))
    assert not np.allclose(np.asarray(outs[0]), np.asarray(other))
    # pipe=1 degenerate: a different (global-shape) stream, but dropout
    # is active, deterministic, and decorrelated across layers/keys
    mesh1 = build_mesh(MeshSpec(data=2), devices[:2])
    pp1 = tfm.to_pipeline_params(params, cfg, n_stages=1)
    f1 = jax.jit(lambda p, i, k: tfm.pipelined_apply(
        p, i, None, cfg, mesh1, n_microbatches=4, train=True, rng=k))
    a, b = f1(pp1, ids, key), f1(pp1, ids, key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.allclose(np.asarray(a), np.asarray(
        f1(pp1, ids, jax.random.PRNGKey(8))))


@pytest.mark.slow
def test_pipelined_dropout_trains_and_grads_flow(devices):
    """Grad through the stochastic schedule: masks replay identically in
    the backward (jax.checkpoint) and the train engine runs."""
    import optax

    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )

    cfg = _tiny_cfg(dropout=0.1)
    mesh = build_mesh(MeshSpec(pipe=2, data=2), devices[:4])
    init_fn = tfm.make_pipelined_init_fn(cfg, n_stages=2, seq_len=16)
    state, specs = init_train_state(
        init_fn, optax.adam(3e-3), mesh, jax.random.PRNGKey(0),
        param_specs=tfm.pipeline_param_specs(
            jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0]
        ),
    )
    step = jit_train_step(
        make_train_step(tfm.pipelined_lm_loss_fn(cfg, mesh, 4),
                        optax.adam(3e-3),
                        StepOptions(check_grads_finite=True)),
        mesh, specs,
    )
    rng = np.random.RandomState(0)
    losses = []
    for i in range(20):
        start = rng.randint(0, cfg.vocab_size, (16, 1))
        ids = (start + np.arange(16)[None]) % cfg.vocab_size
        batch = {"input_ids": jax.device_put(
            jnp.asarray(ids, jnp.int32),
            NamedSharding(mesh, sh.batch_spec(2)))}
        state, metrics = step(state, batch)
        assert float(metrics["grads_finite"]) == 1.0
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses


@pytest.mark.slow
def test_pipelined_composes_with_grad_accum(devices):
    """PP × ConditionalAccumulator-descendant: grad_accum_steps=2 through
    the pipelined loss must equal the accum=1 step on the same batch
    (causal LM: every chunk has identical valid-token counts, so
    mean-of-means == full-batch mean exactly; dropout off)."""
    import optax

    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )

    cfg = _tiny_cfg()  # causal, dropout=0.0
    mesh = build_mesh(MeshSpec(pipe=2, data=2), devices[:4])
    init_fn = tfm.make_pipelined_init_fn(cfg, n_stages=2, seq_len=16)
    specs = tfm.pipeline_param_specs(
        jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0])
    tx = optax.sgd(0.1)
    loss_fn = tfm.pipelined_lm_loss_fn(cfg, mesh, n_microbatches=4)

    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (16, 16))
    batch = {"input_ids": jax.device_put(
        jnp.asarray(ids, jnp.int32),
        NamedSharding(mesh, sh.batch_spec(2)))}

    results = []
    for accum in (1, 2):
        state, sspecs = init_train_state(
            init_fn, tx, mesh, jax.random.PRNGKey(0), param_specs=specs)
        step = jit_train_step(
            make_train_step(loss_fn, tx,
                            StepOptions(grad_accum_steps=accum)),
            mesh, sspecs,
        )
        state, metrics = step(state, batch)
        results.append((state.params, float(metrics["loss"])))

    (p1, l1), (p2, l2) = results
    assert abs(l1 - l2) < 1e-5, (l1, l2)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5),
        p1, p2,
    )


@pytest.mark.slow
def test_pipelined_dropout_consistent_under_tp(devices):
    """PP×TP × dropout: model-axis devices must draw IDENTICAL masks
    (the shard fold uses only the data/fsdp index), so the TP=2 forward
    equals the TP=1 forward exactly — a wrong per-device key would break
    the row-parallel psum math, which only numerical parity catches."""
    cfg = _tiny_cfg(dropout=0.5)
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 16)),
        jnp.int32)
    key = jax.random.PRNGKey(3)
    pp = tfm.to_pipeline_params(params, cfg, n_stages=2)

    outs = []
    for spec, nd in ((MeshSpec(pipe=2, data=2), 4),
                     (MeshSpec(pipe=2, model=2, data=2), 8)):
        mesh = build_mesh(spec, devices[:nd])
        outs.append(np.asarray(jax.jit(
            lambda p, i, k, mesh=mesh: tfm.pipelined_apply(
                p, i, None, cfg, mesh, n_microbatches=4,
                train=True, rng=k)
        )(pp, ids, key)))
    np.testing.assert_allclose(outs[0], outs[1], atol=2e-4)
