"""Pipeline-parallel tests: schedule parity vs sequential oracle (forward
and gradients), pp×dp composition through the train engine — the
strategy_test_lib-style distributed-correctness oracles of SURVEY.md §4.4."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.models import pipelined_lm as plm
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.parallel import sharding as sh
from distributed_tensorflow_tpu.parallel.pipeline import (
    microbatch,
    pipeline_apply,
    stack_stages,
    unmicrobatch,
)
from distributed_tensorflow_tpu.train import (
    StepOptions, init_train_state, jit_train_step, make_train_step,
)


def test_microbatch_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    mb = microbatch(x, 4)
    assert mb.shape == (4, 3, 2)
    np.testing.assert_array_equal(unmicrobatch(mb), x)
    with pytest.raises(ValueError, match="not divisible"):
        microbatch(x, 5)


def _toy_stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _toy_params(key, n_stages, d):
    keys = jax.random.split(key, n_stages)
    return stack_stages([
        {"w": jax.random.normal(k, (d, d)) * 0.5, "b": jnp.zeros((d,))}
        for k in keys
    ])


def _toy_sequential(params, x_mb):
    def per_mb(x):
        def body(x, p):
            return _toy_stage_fn(p, x), None

        y, _ = jax.lax.scan(body, x, params)
        return y

    return jax.vmap(per_mb)(x_mb)


def test_pipeline_matches_sequential(devices):
    mesh = build_mesh(MeshSpec(pipe=4, data=2), devices[:8])
    params = _toy_params(jax.random.PRNGKey(0), 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 6, 8))  # [M, mb, d]
    want = _toy_sequential(params, x)
    got = jax.jit(
        lambda p, x: pipeline_apply(_toy_stage_fn, p, x, mesh)
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_pipeline_gradients_match(devices):
    mesh = build_mesh(MeshSpec(pipe=4), devices[:4])
    params = _toy_params(jax.random.PRNGKey(0), 4, 4)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, 4))

    def loss_pipe(p):
        return (pipeline_apply(_toy_stage_fn, p, x, mesh) ** 2).sum()

    def loss_seq(p):
        return (_toy_sequential(p, x) ** 2).sum()

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.jit(jax.grad(loss_seq))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_pipe, g_seq,
    )


def test_pipeline_rejects_too_few_microbatches(devices):
    mesh = build_mesh(MeshSpec(pipe=4), devices[:4])
    params = _toy_params(jax.random.PRNGKey(0), 4, 4)
    x = jnp.zeros((2, 2, 4))  # M=2 < S=4
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(_toy_stage_fn, params, x, mesh)


def _tiny_lm_cfg(**kw):
    base = dict(vocab_size=64, max_len=16, num_layers=4, d_model=32,
                num_heads=4, d_ff=64, n_stages=2, n_microbatches=4,
                dtype="float32")
    base.update(kw)
    return plm.PipelinedLMConfig(**base)


def test_pipelined_lm_matches_reference(devices):
    cfg = _tiny_lm_cfg(n_stages=4)
    mesh = build_mesh(MeshSpec(pipe=4, data=2), devices[:8])
    params = plm.init_params(jax.random.PRNGKey(0), cfg)
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (8, 16))
    ids = jnp.asarray(ids, jnp.int32)
    want = plm.reference_apply(params, ids, cfg)
    got = jax.jit(lambda p, i: plm.apply(p, i, cfg, mesh))(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_pipelined_lm_trains(devices):
    """Full train-engine integration on a pipe=2 × data=2 × fsdp=2 mesh:
    loss decreases on the deterministic-walk corpus."""
    cfg = _tiny_lm_cfg()
    mesh = build_mesh(MeshSpec(pipe=2, data=2, fsdp=2), devices[:8])
    tx = optax.adam(3e-3)
    state, specs = init_train_state(
        plm.make_init_fn(cfg), tx, mesh, jax.random.PRNGKey(0),
        param_specs=plm.param_specs(
            jax.eval_shape(plm.make_init_fn(cfg), jax.random.PRNGKey(0))[0]
        ),
    )
    assert state.params["blocks"]["wqkv"].sharding.spec[0] == "pipe"
    step = jit_train_step(
        make_train_step(plm.lm_loss_fn(cfg, mesh), tx,
                        StepOptions(check_grads_finite=True)),
        mesh, specs,
    )
    rng = np.random.RandomState(0)
    losses = []
    for i in range(25):
        start = rng.randint(0, cfg.vocab_size, (16, 1))
        ids = (start + np.arange(16)[None]) % cfg.vocab_size
        batch = {"input_ids": jnp.asarray(ids, jnp.int32)}
        batch = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, sh.batch_spec(x.ndim))
            ),
            batch,
        )
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
        assert float(metrics["grads_finite"]) == 1.0
    assert losses[-1] < losses[0] * 0.8, losses
