"""Test rig: 8 fake CPU devices in one process (SURVEY.md §4.2).

The analog of TF's `create_in_process_cluster` ($TF/python/distribute/
multi_worker_test_base.py:123): every collective/sharding test runs on CI
hardware with no TPU. The environment may pre-import jax and pre-set
JAX_PLATFORMS (e.g. a TPU tunnel platform), so we force the CPU backend via
jax.config before any device is touched — backends initialize lazily, so
this is safe as long as conftest runs before the first jax.devices() call.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, (
        f"test rig expects >=8 fake devices, got {len(devs)}; "
        "was a jax backend initialized before conftest?"
    )
    return devs


@pytest.fixture()
def mesh8(devices):
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=8), devices[:8])


@pytest.fixture()
def mesh_dp4_tp2(devices):
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=4, model=2), devices[:8])


# Persistent XLA compilation cache — OPT-IN via DTF_TEST_CACHE=<dir>,
# default OFF. On this jaxlib/CPU combination, executables DESERIALIZED
# from the persistent cache mishandle buffer donation: donated inputs
# (the train step's state, the serve engine's KV cache) go through stale
# aliasing info, which manifests as glibc heap-corruption aborts
# ("corrupted double-linked list") or — worse — silently NaN'd params on
# restore-and-resume. Found by the resilience chaos suite: with a warm
# cache even the SEED test_loop_checkpoint.py crashed when run in
# isolation, and tests/chaos_worker.py resumes produced NaN params while
# exiting 0. Cold compiles cost seconds per program but are correct; do
# not re-enable by default without re-running
# tests/test_resilience.py::test_kill_resume_bit_identical twice
# back-to-back (cold then warm) under the cache dir.
_cache_dir = os.environ.get("DTF_TEST_CACHE", "0")
if _cache_dir != "0":
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)
