"""Fault-injection tests: every recovery path in the durability story
driven end-to-end by the deterministic harness (resilience/faults.py) —
preemption agreement, emergency checkpoints, validate-before-save
refusal, manifest rejection of corrupt shards, watchdog stall flagging,
and the subprocess kill→restart→resume bit-identity oracle."""

import os
import signal
import subprocess
import sys

import jax
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu import resilience as rz
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.train import (
    CheckpointConfig,
    Checkpointer,
    Trainer,
    callbacks as cb,
    init_or_restore,
    make_train_step,
)
from distributed_tensorflow_tpu.train.checkpoint import (
    PreemptionWatcher,
)

from test_step import linear_init, linear_loss, make_batch

WORKER = os.path.join(os.path.dirname(__file__), "chaos_worker.py")


def batches(n, size=16):
    for i in range(n):
        yield make_batch(size, seed=i)


# ---------------------------------------------------------------------------
# Harness unit behavior (no device)
# ---------------------------------------------------------------------------


def test_fault_plan_seeded_deterministic():
    kinds = ("sigterm", "data_error", "nan_batch", "clock_stall")
    a = rz.FaultPlan.seeded(7, 100, kinds=kinds)
    b = rz.FaultPlan.seeded(7, 100, kinds=kinds)
    assert a == b  # same seed → identical plan
    assert a != rz.FaultPlan.seeded(8, 100, kinds=kinds)
    for f in a.faults:
        at = f.step if hasattr(f, "step") else f.batch
        assert 2 <= at <= 99  # never the first or final step
    with pytest.raises(ValueError):
        rz.FaultPlan.seeded(0, 2)
    with pytest.raises(ValueError):
        rz.FaultPlan.seeded(0, 10, kinds=("meteor_strike",))


def test_fault_clock():
    clk = rz.FaultClock(start=5.0)
    assert clk() == 5.0
    assert clk.advance(2.5) == 7.5 == clk()
    with pytest.raises(ValueError):
        clk.advance(-1.0)


def test_faulty_iterator_data_error_and_nan_poison():
    def src():
        i = 0
        while True:
            i += 1
            yield {"x": np.ones(4, np.float32), "label": np.zeros(4, np.int32),
                   "i": i}

    it = rz.FaultPlan((rz.NaNBatch(2), rz.DataError(4),)).wrap(src())
    b1 = next(it)
    assert np.isfinite(b1["x"]).all()
    b2 = next(it)  # poisoned: one NaN in the first float array
    assert np.isnan(b2["x"]).any() and not np.isnan(b2["x"]).all()
    assert np.isfinite(b1["x"]).all()  # original batch dict untouched
    assert b2["label"].dtype == np.int32  # ints never poisoned
    b3 = next(it)
    assert np.isfinite(b3["x"]).all()  # NaN fault fires exactly once
    with pytest.raises(IOError, match="injected data fault"):
        next(it)
    # fires exactly once, and the faulted fetch consumed NO source batch
    # (a real IO error loses the read, not the data)
    assert next(it)["i"] == 4


def test_transient_io_fires_n_times_total_across_rewraps():
    """TransientIOError decays PER PLAN: the remaining-fires count
    survives re-wrapping the stream (the RetryingIterator re-seek), and
    a faulted fetch consumes no source batch."""

    def src(start):
        i = start
        while True:
            i += 1
            yield {"i": i}

    plan = rz.FaultPlan((rz.TransientIOError(batch=2, times=2),))
    it = plan.wrap(src(0))
    assert next(it)["i"] == 1  # batch 1: before the fault index
    with pytest.raises(IOError, match="transient"):
        next(it)  # fire 1 of 2
    it2 = plan.wrap(src(1), start=1)  # fresh wrap = the re-seek case
    with pytest.raises(IOError, match="transient"):
        next(it2)  # fire 2 of 2
    it3 = plan.wrap(src(1), start=1)
    assert next(it3)["i"] == 2  # decayed: the owed batch, nothing lost
    assert next(it3)["i"] == 3


def test_one_shot_faults_fire_once_per_plan_across_seams():
    """Sigterm/DataError fired-state lives on the plan, so a Supervisor
    rebuilding the callback list / re-wrapping the data on restart never
    re-fires a fault that already happened."""
    plan = rz.FaultPlan((rz.DataError(2),))
    with pytest.raises(IOError):
        next(plan.wrap(iter([{"a": 1}, {"a": 2}]), start=1))
    # a fresh wrap of the same plan does NOT re-fire
    assert next(plan.wrap(iter([{"a": 2}]), start=1))["a"] == 2


def test_clock_stall_fault_via_callback():
    clk = rz.FaultClock()
    fcb = rz.FaultPlan((rz.ClockStall(step=3, dt=120.0),)).callback(clock=clk)
    for step in range(1, 6):
        fcb.on_step_end(None, step, {})
    assert clk() == 120.0  # fired once at step 3, never again
    with pytest.raises(ValueError, match="clock"):
        rz.FaultPlan((rz.ClockStall(1, 1.0),)).callback().on_step_end(
            None, 1, {})


# ---------------------------------------------------------------------------
# Signal-handler hygiene (satellite: PreemptionWatcher.close)
# ---------------------------------------------------------------------------


def test_preemption_watcher_close_restores_handlers():
    orig = signal.getsignal(signal.SIGTERM)
    try:
        w1 = PreemptionWatcher()
        assert signal.getsignal(signal.SIGTERM) == w1._handler
        w2 = PreemptionWatcher()  # captures w1's handler as its _prev
        w2.close()  # LIFO close: w1 handler back in place
        assert signal.getsignal(signal.SIGTERM) == w1._handler
        w1.close()
        assert signal.getsignal(signal.SIGTERM) == orig
        # out-of-order close must not clobber a newer watcher's handler
        w3 = PreemptionWatcher()
        w4 = PreemptionWatcher()
        w3.close()
        assert signal.getsignal(signal.SIGTERM) == w4._handler
        w4.close()
    finally:
        signal.signal(signal.SIGTERM, orig)


def test_checkpointer_close_restores_signal_handler(mesh8, tmp_path):
    orig = signal.getsignal(signal.SIGTERM)
    try:
        ckpt = Checkpointer(
            CheckpointConfig(directory=str(tmp_path / "w"), async_save=False,
                             save_on_preemption=True),
            mesh8,
        )
        assert signal.getsignal(signal.SIGTERM) != orig
        ckpt.close()
        assert signal.getsignal(signal.SIGTERM) == orig
    finally:
        signal.signal(signal.SIGTERM, orig)


# ---------------------------------------------------------------------------
# In-process fault → recovery paths
# ---------------------------------------------------------------------------


def _checkpointer(mesh, d, **kw):
    base = dict(directory=str(d), save_interval_steps=10**6,
                async_save=False, save_on_preemption=False,
                preemption_check_every=1)
    base.update(kw)
    return Checkpointer(CheckpointConfig(**base), mesh)


def test_sigterm_fault_coordinated_save_clean_exit(mesh8, tmp_path):
    """Sigterm fault → PreemptionWatcher flag → coordinated final save →
    PreemptionSaved → clean Trainer stop, all through production seams."""
    orig = signal.getsignal(signal.SIGTERM)
    tx = optax.sgd(0.1)
    ckpt = _checkpointer(mesh8, tmp_path / "pre", save_on_preemption=True)
    try:
        state, specs, _ = init_or_restore(
            ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
        plan = rz.FaultPlan((rz.Sigterm(3),))
        trainer = Trainer(
            make_train_step(linear_loss, tx), state, mesh8, specs,
            callbacks=[cb.CheckpointCallback(ckpt), plan.callback()],
        )
        trainer.fit(batches(50), num_steps=50)
        assert not trainer.failed
        assert "preempted" in trainer._stop_reason
        # SIGTERM fires after step 3; the next step's maybe_save coordinates
        assert ckpt.latest_step() == 4
        ckpt.close()
    finally:
        signal.signal(signal.SIGTERM, orig)


def test_data_fault_emergency_checkpoint_then_resume_matches(mesh8, tmp_path):
    """An IOError out of the data iterator aborts the run — but the
    Trainer's emergency save means restart-and-resume loses nothing and
    reproduces the uninterrupted run's params exactly."""
    tx = optax.adam(1e-2)

    # uninterrupted reference: 6 steps
    from distributed_tensorflow_tpu.train import init_train_state
    state, specs = init_train_state(linear_init, tx, mesh8, jax.random.PRNGKey(0))
    trainer = Trainer(make_train_step(linear_loss, tx), state, mesh8, specs)
    straight = trainer.fit(batches(6), num_steps=6)

    # faulted run: the iterator dies feeding step 4 (3 steps complete)
    ckpt = _checkpointer(mesh8, tmp_path / "em")
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    plan = rz.FaultPlan((rz.DataError(4),))
    trainer = Trainer(
        make_train_step(linear_loss, tx), state, mesh8, specs,
        callbacks=[cb.CheckpointCallback(ckpt)],
    )
    with pytest.raises(IOError, match="injected data fault"):
        trainer.fit(plan.wrap(batches(50)), num_steps=50)
    assert trainer.failed
    assert ckpt.latest_step() == 3  # the emergency save, not a cadence one
    ckpt.close()

    # fresh "process": restore and run the remaining steps on the same data
    ckpt2 = _checkpointer(mesh8, tmp_path / "em")
    state2, specs2, restored = init_or_restore(
        ckpt2, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    assert restored and int(state2.step) == 3
    trainer2 = Trainer(make_train_step(linear_loss, tx), state2, mesh8, specs2)
    resumed = trainer2.fit(
        (make_batch(16, seed=i) for i in range(3, 6)), num_steps=6)
    assert int(resumed.step) == 6
    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ckpt2.close()


def test_nan_fault_refused_by_validate_before_save(mesh8, tmp_path):
    """NaNBatch fault → non-finite grads poison the params → NaNGuard
    aborts AND both the cadence save and the emergency save refuse the
    poisoned state: the latest checkpoint stays the last healthy step."""
    tx = optax.sgd(0.1)
    ckpt = _checkpointer(mesh8, tmp_path / "nan", save_interval_steps=1)
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    plan = rz.FaultPlan((rz.NaNBatch(3),))
    trainer = Trainer(
        make_train_step(linear_loss, tx), state, mesh8, specs,
        callbacks=[cb.NaNGuard(every_n=1), cb.CheckpointCallback(ckpt)],
    )
    with pytest.raises(FloatingPointError):
        trainer.fit(plan.wrap(batches(50)), num_steps=50)
    assert trainer.failed
    assert ckpt.latest_step() == 2  # healthy cadence saves survive, NaN never lands
    ckpt.close()


def test_truncated_shard_rejected_at_restore(mesh8, tmp_path):
    """Acceptance gate: a shard truncated by the fault harness must be
    rejected by verify_manifest (OSError), never silently loaded."""
    tx = optax.sgd(0.1)
    ckpt = _checkpointer(mesh8, tmp_path / "tr")
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    assert ckpt.save(0, state, force=True)
    assert ckpt.verify_manifest(0) is True
    victim = rz.truncate_shard(str(tmp_path / "tr"), 0)
    assert os.path.exists(victim)
    with pytest.raises(OSError, match="manifest says|missing shard"):
        ckpt.verify_manifest(0)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    with pytest.raises(OSError):
        ckpt.restore(abstract, step=0)
    ckpt.close()


def test_restore_error_names_shard_and_sizes(mesh8, tmp_path):
    """Satellite: a manifest mismatch must name the offending shard file
    and its expected-vs-actual size — 'step rejected' alone is
    undebuggable."""
    tx = optax.sgd(0.1)
    ckpt = _checkpointer(mesh8, tmp_path / "msg")
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    assert ckpt.save(0, state, force=True)
    victim = rz.truncate_shard(str(tmp_path / "msg"), 0, nbytes=3)
    with pytest.raises(OSError,
                       match=r"shard .+ is \d+ bytes, manifest says \d+"):
        ckpt.verify_manifest(0)
    try:
        ckpt.verify_manifest(0)
    except OSError as e:
        assert os.path.basename(victim) in str(e)  # names THE file
        assert "+3" in str(e)  # and the byte delta
    ckpt.close()


def test_fallback_restore_chain(mesh8, tmp_path):
    """Satellite: newest corrupt → fallback restore quarantines it to
    .corrupt/ and lands on the previous valid step; a subsequent save at
    the quarantined step number succeeds cleanly."""
    tx = optax.sgd(0.1)
    d = tmp_path / "fb"
    ckpt = _checkpointer(mesh8, d, save_interval_steps=1)
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    trainer = Trainer(
        make_train_step(linear_loss, tx), state, mesh8, specs,
        callbacks=[cb.CheckpointCallback(ckpt)],
    )
    trainer.fit(batches(3), num_steps=3)  # saves at steps 1, 2, 3
    ckpt.close()
    rz.truncate_shard(str(d), 3)

    ckpt2 = _checkpointer(mesh8, d, save_interval_steps=1)
    state2, specs2, restored = init_or_restore(
        ckpt2, linear_init, tx, mesh8, jax.random.PRNGKey(0), fallback=True)
    assert restored and int(state2.step) == 2  # previous valid step wins
    assert ckpt2.latest_step() == 2
    qdir = d / ".corrupt" / "3"
    assert qdir.is_dir()  # quarantined, not deleted, not reused
    note = (qdir / "QUARANTINE").read_text()
    assert "shard" in note and "manifest says" in note
    # re-saving at the quarantined step number starts clean
    trainer2 = Trainer(
        make_train_step(linear_loss, tx), state2, mesh8, specs2,
        callbacks=[cb.CheckpointCallback(ckpt2)],
    )
    resumed = trainer2.fit(
        (make_batch(16, seed=i) for i in range(2, 4)), num_steps=4)
    assert int(resumed.step) == 4
    assert ckpt2.verify_manifest(3) is True  # the re-save is intact
    assert ckpt2.verify_manifest(4) is True
    ckpt2.close()


def test_fallback_restore_all_corrupt_returns_none(mesh8, tmp_path):
    tx = optax.sgd(0.1)
    d = tmp_path / "fb2"
    ckpt = _checkpointer(mesh8, d)
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    for s in (0, 1):
        assert ckpt.save(s, state if s == 0 else state.replace(step=s),
                         force=True)
    rz.truncate_shard(str(d), 0)
    rz.truncate_shard(str(d), 1)
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
    assert ckpt.restore(abstract, fallback=True) is None
    assert ckpt.latest_step() is None
    assert sorted(p.name for p in (d / ".corrupt").iterdir()) == ["0", "1"]
    # non-fallback restore of a corrupt step still raises loudly
    ckpt.close()


def test_fallback_transient_verify_blip_does_not_quarantine(mesh8, tmp_path,
                                                            monkeypatch):
    """Quarantine is destructive, so a transient FS error during the
    integrity check must be retried away — never condemn a good newest
    step over a blip."""
    from distributed_tensorflow_tpu.runtime import io as io_lib

    tx = optax.sgd(0.1)
    d = tmp_path / "blip"
    ckpt = _checkpointer(mesh8, d)
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    assert ckpt.save(0, state, force=True)
    ckpt.close()

    real = io_lib.read_payload
    fails = {"n": 1}

    def flaky(path):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("transient stale handle")
        return real(path)

    monkeypatch.setattr(io_lib, "read_payload", flaky)
    reg = Registry()
    ckpt2 = Checkpointer(
        CheckpointConfig(directory=str(d), async_save=False,
                         save_on_preemption=False),
        mesh8, registry=reg,
        io_retry=rz.RetryPolicy(max_attempts=3, base_s=0.0, jitter=0.0))
    state2, specs2, restored = init_or_restore(
        ckpt2, linear_init, tx, mesh8, jax.random.PRNGKey(0), fallback=True)
    assert restored  # the good step survived the blip
    assert not (d / ".corrupt").exists()
    assert reg.get("retry_attempts_total", site="ckpt_verify").value == 1.0
    ckpt2.close()


def test_fallback_walks_past_restore_time_failure(mesh8, tmp_path,
                                                  monkeypatch):
    """A step that verifies but fails at READ time (e.g. shards committed,
    manifest never stamped, bytes unreadable) must be quarantined and the
    walk continue to an older valid step — not escape fallback raw."""
    tx = optax.sgd(0.1)
    d = tmp_path / "rt"
    ckpt = _checkpointer(mesh8, d, save_interval_steps=1)
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    trainer = Trainer(
        make_train_step(linear_loss, tx), state, mesh8, specs,
        callbacks=[cb.CheckpointCallback(ckpt)])
    trainer.fit(batches(2), num_steps=2)  # saves steps 1, 2
    ckpt.close()

    ckpt2 = _checkpointer(mesh8, d, save_interval_steps=1)
    ckpt2.io_retry = rz.RetryPolicy(max_attempts=2, base_s=0.0, jitter=0.0)
    real = ckpt2.manager.restore

    def flaky(step, args=None):
        if step == 2:
            raise OSError("unreadable shard bytes")
        return real(step, args=args)

    monkeypatch.setattr(ckpt2.manager, "restore", flaky)
    state2, specs2, restored = init_or_restore(
        ckpt2, linear_init, tx, mesh8, jax.random.PRNGKey(0), fallback=True)
    assert restored and int(state2.step) == 1  # fell back past step 2
    assert (d / ".corrupt" / "2").is_dir()
    ckpt2.close()


def test_checkpoint_manifest_write_retries_transient(mesh8, tmp_path,
                                                     monkeypatch):
    """The ckpt_manifest_write retry seam: a write that fails twice with
    OSError still produces an intact manifest, and the obs counters
    account for the re-attempts."""
    from distributed_tensorflow_tpu.runtime import io as io_lib

    reg = Registry()
    real = io_lib.write_payload
    fails = {"n": 2}

    def flaky(path, data):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise OSError("injected transient write fault")
        return real(path, data)

    monkeypatch.setattr(io_lib, "write_payload", flaky)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "rw"), async_save=False,
                         save_on_preemption=False),
        mesh8, registry=reg,
        io_retry=rz.RetryPolicy(max_attempts=4, base_s=0.0, jitter=0.0),
    )
    tx = optax.sgd(0.1)
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    assert ckpt.save(0, state, force=True)
    assert ckpt.verify_manifest(0) is True
    assert reg.get("retry_attempts_total",
                   site="ckpt_manifest_write").value == 2.0
    assert reg.total("retry_exhausted_total") == 0.0
    ckpt.close()


def test_corrupt_shard_is_size_preserving(mesh8, tmp_path):
    """corrupt_shard flips content without changing sizes — the fault
    the size-checking manifest intentionally does NOT catch (that tier
    is orbax's own digests / the manifest's CRC on itself); the harness
    keeps the two fault classes distinct."""
    tx = optax.sgd(0.1)
    ckpt = _checkpointer(mesh8, tmp_path / "co")
    state, specs, _ = init_or_restore(
        ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0))
    assert ckpt.save(0, state, force=True)
    victim = rz.corrupt_shard(str(tmp_path / "co"), 0)
    assert ckpt.verify_manifest(0) is True  # sizes intact by design
    assert os.path.getsize(victim) > 0
    ckpt.close()


# ---------------------------------------------------------------------------
# Watchdog
# ---------------------------------------------------------------------------


def test_watchdog_flags_hung_step_and_recovers():
    import time

    reg = Registry()
    wd = cb.Watchdog(budget_s=0.05, poll_s=0.01, registry=reg)
    wd.on_train_start(None)
    try:
        deadline = time.monotonic() + 2.0
        while (reg.get("train_watchdog_stalled").value == 0.0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert reg.get("train_watchdog_stalled").value == 1.0
        assert reg.get("train_watchdog_stalls_total").value == 1.0
        wd.on_step_end(None, 1, {})  # a step lands: stall clears
        assert reg.get("train_watchdog_stalled").value == 0.0
    finally:
        wd.on_train_end(None)
    assert wd._thread is None


def test_watchdog_with_injected_clock_stall():
    import time

    reg = Registry()
    clk = rz.FaultClock()
    wd = cb.Watchdog(budget_s=60.0, poll_s=0.01, registry=reg, clock=clk)
    wd.on_train_start(None)
    try:
        fcb = rz.FaultPlan((rz.ClockStall(step=1, dt=61.0),)).callback(clk)
        fcb.on_step_end(None, 1, {})  # the "hang": one minute vanishes
        deadline = time.monotonic() + 2.0
        while (reg.get("train_watchdog_stalled").value == 0.0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert reg.get("train_watchdog_stalled").value == 1.0
    finally:
        wd.on_train_end(None)


# ---------------------------------------------------------------------------
# Subprocess end-to-end: kill → restart → resume, bit-identical
# ---------------------------------------------------------------------------


def _run_worker(workdir, *extra, timeout=240):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the worker sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, WORKER, str(workdir), *extra],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    assert p.returncode == 0, f"worker rc={p.returncode}:\n{p.stdout}\n{p.stderr}"
    return p.stdout


def test_kill_resume_bit_identical(tmp_path):
    """THE acceptance criterion: SIGTERM mid-run → PreemptionSaved →
    fresh process restores and finishes → params bit-identical to an
    uninterrupted run of the same seed."""
    a_dir, b_dir = tmp_path / "straight", tmp_path / "killed"
    a_out, b_out = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")

    out = _run_worker(a_dir, "--steps", "8", "--out", a_out)
    assert "CHAOS-DONE step=8" in out, out

    out = _run_worker(b_dir, "--steps", "8", "--sigterm-at", "3")
    assert "CHAOS-PREEMPTED step=4" in out, out  # saved the step after the signal

    out = _run_worker(b_dir, "--steps", "8", "--out", b_out)
    assert "CHAOS-DONE step=8" in out, out

    a, b = np.load(a_out), np.load(b_out)
    assert sorted(a.files) == sorted(b.files) and a.files
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])  # BIT-identical
