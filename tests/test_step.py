"""Train-step engine tests, incl. the k-replica == 1-replica numerical
parity oracle (SURVEY.md §4.4, the strategy_test_lib pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel import (
    MeshSpec,
    build_mesh,
    sharding as sh,
    single_device_mesh,
)
from distributed_tensorflow_tpu.train import (
    StepOptions,
    init_train_state,
    jit_train_step,
    make_train_step,
)


def linear_init(key):
    k1, k2 = jax.random.split(key)
    params = {
        "w": jax.random.normal(k1, (8, 4)) * 0.1,
        "b": jnp.zeros((4,)),
    }
    return params, {}


def linear_loss(params, model_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    loss = jnp.mean((pred - batch["y"]) ** 2)
    return loss, (model_state, {"mse": loss})


def make_batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "x": rng.randn(n, 8).astype(np.float32),
        "y": rng.randn(n, 4).astype(np.float32),
    }


def _put(batch, mesh):
    return jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, sh.batch_spec(x.ndim))
        ),
        batch,
    )


def run_steps(mesh, n_steps=3, accum=1, batch=None):
    tx = optax.sgd(0.1)
    state, specs = init_train_state(
        linear_init, tx, mesh, jax.random.PRNGKey(0)
    )
    step = jit_train_step(
        make_train_step(linear_loss, tx, StepOptions(grad_accum_steps=accum)),
        mesh,
        specs,
    )
    batch = batch or make_batch()
    losses = []
    for _ in range(n_steps):
        state, metrics = step(state, _put(batch, mesh))
        losses.append(float(metrics["loss"]))
    return state, losses


def test_loss_decreases_single_device():
    _, losses = run_steps(single_device_mesh(jax.devices()[0]))
    assert losses[-1] < losses[0]


def test_dp8_matches_single_device(devices):
    """The distributed-correctness oracle: 8-way sync DP on the same global
    batch must produce bit-comparable results to 1 device."""
    mesh1 = single_device_mesh(devices[0])
    mesh8 = build_mesh(MeshSpec(data=8), devices[:8])
    batch = make_batch(n=16)
    s1, l1 = run_steps(mesh1, batch=batch)
    s8, l8 = run_steps(mesh8, batch=batch)
    np.testing.assert_allclose(l1, l8, rtol=1e-5, atol=1e-7)
    # tolerance covers cross-device reduction-order float noise
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)


def test_grad_accum_matches_full_batch(devices):
    """accum=4 over the same global batch == accum=1 (mean-of-means)."""
    mesh = build_mesh(MeshSpec(data=8), devices[:8])
    batch = make_batch(n=32)
    s1, l1 = run_steps(mesh, accum=1, batch=batch)
    s4, l4 = run_steps(mesh, accum=4, batch=batch)
    np.testing.assert_allclose(l1, l4, rtol=1e-4)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4)


def test_metrics_contents(mesh8):
    tx = optax.sgd(0.1)
    state, specs = init_train_state(linear_init, tx, mesh8, jax.random.PRNGKey(0))
    step = jit_train_step(
        make_train_step(linear_loss, tx,
                        StepOptions(clip_grad_norm=1.0, check_grads_finite=True)),
        mesh8, specs,
    )
    state, metrics = step(state, _put(make_batch(), mesh8))
    assert {"loss", "mse", "grad_norm", "grads_finite"} <= set(metrics)
    assert float(metrics["grads_finite"]) == 1.0
    assert int(state.step) == 1


def test_sharded_params_tp(mesh_dp4_tp2):
    """Params sharded over model axis via path rules; step still correct."""
    tx = optax.adam(1e-2)
    state, specs = init_train_state(
        linear_init, tx, mesh_dp4_tp2, jax.random.PRNGKey(0),
        param_rules=[(r"w", P(None, "model"))],
    )
    assert state.params["w"].sharding.spec == P(None, "model")
    # Adam slots inherit the param sharding (weight-update sharding hook).
    mu_w = state.opt_state[0].mu["w"]
    assert mu_w.sharding.spec == P(None, "model")
    step = jit_train_step(make_train_step(linear_loss, tx), mesh_dp4_tp2, specs)
    state, metrics = step(state, _put(make_batch(), mesh_dp4_tp2))
    assert np.isfinite(float(metrics["loss"]))


def test_fsdp_auto_sharding(devices):
    mesh = build_mesh(MeshSpec(data=2, fsdp=4), devices[:8])

    def big_init(key):
        return {"w": jax.random.normal(key, (256, 128))}, {}

    tx = optax.sgd(0.1)
    state, specs = init_train_state(
        big_init, tx, mesh, jax.random.PRNGKey(0), fsdp=True
    )
    spec = state.params["w"].sharding.spec
    assert "fsdp" in str(spec)


def test_grads_finite_free_via_grad_norm(devices):
    """When grad-norm/clipping is already on, grads_finite derives from
    the global norm at zero extra cost — same-step NaN signal without
    the per-leaf isfinite pass (VERDICT r2 Weak #4)."""
    mesh = build_mesh(MeshSpec(data=2), devices[:2])
    tx = optax.sgd(0.1)

    def loss_fn(params, model_state, batch, rng):
        loss = (params["w"] * batch["x"]).sum() * batch["scale"]
        return loss, (model_state, {})

    state, specs = init_train_state(
        lambda rng: ({"w": jnp.ones(4)}, {}), tx, mesh,
        jax.random.PRNGKey(0),
    )
    step = jit_train_step(
        make_train_step(loss_fn, tx, StepOptions(clip_grad_norm=1.0)),
        mesh, specs,
    )
    good = {"x": jnp.ones(4), "scale": jnp.float32(1.0)}
    state, m = step(state, good)
    assert float(m["grads_finite"]) == 1.0 and "grad_norm" in m
    bad = {"x": jnp.ones(4), "scale": jnp.float32(np.nan)}
    _, m = step(state, bad)
    assert float(m["grads_finite"]) == 0.0  # SAME step, not one later
