"""Retry/backoff layer (resilience/retry.py) and the re-seeking
RetryingIterator (data/pipeline.py): budget semantics, deterministic
seeded jitter, the obs counters, and exhaustion classification — all
device-free."""

import time

import pytest

from distributed_tensorflow_tpu import resilience as rz
from distributed_tensorflow_tpu.data.pipeline import RetryingIterator
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.resilience.retry import retry_call


def _noop_sleep(_):  # tests never really wait
    pass


def _fast(**kw):
    base = dict(max_attempts=3, base_s=0.0, jitter=0.0)
    base.update(kw)
    return rz.RetryPolicy(**base)


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def test_policy_validation():
    with pytest.raises(ValueError):
        rz.RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        rz.RetryPolicy(multiplier=0.5)
    with pytest.raises(ValueError):
        rz.RetryPolicy(jitter=1.5)
    with pytest.raises(ValueError):
        rz.RetryPolicy(base_s=-1.0)


def test_backoff_escalates_caps_and_is_deterministic():
    p = rz.RetryPolicy(base_s=1.0, multiplier=2.0, max_backoff_s=4.0,
                       jitter=0.5, seed=3)
    for i in range(6):
        d = p.backoff_s(i)
        raw = min(1.0 * 2.0 ** i, 4.0)
        assert raw * 0.5 <= d <= raw  # jitter only shrinks, within bound
        assert d == p.backoff_s(i)  # same (seed, index) → same delay
    # a different seed jitters differently somewhere in the schedule
    q = rz.RetryPolicy(base_s=1.0, multiplier=2.0, max_backoff_s=4.0,
                       jitter=0.5, seed=4)
    assert any(p.backoff_s(i) != q.backoff_s(i) for i in range(6))
    # jitter=0 → exact exponential schedule
    z = rz.RetryPolicy(base_s=1.0, multiplier=2.0, max_backoff_s=40.0,
                       jitter=0.0)
    assert [z.backoff_s(i) for i in range(3)] == [1.0, 2.0, 4.0]


# ---------------------------------------------------------------------------
# retry_call
# ---------------------------------------------------------------------------


def test_retry_call_absorbs_transient_and_counts():
    reg = Registry()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise IOError("transient")
        return "ok"

    slept = []
    out = retry_call(flaky, policy=_fast(max_attempts=5, base_s=0.25),
                     site="t", registry=reg, sleep=slept.append)
    assert out == "ok" and calls["n"] == 3
    assert reg.get("retry_attempts_total", site="t").value == 2
    assert reg.get("retry_exhausted_total", site="t").value == 0
    assert slept == [0.25, 0.5]  # escalating, jitter=0


def test_retry_call_exhausts_attempt_budget():
    reg = Registry()

    def always():
        raise IOError("permanent")

    with pytest.raises(rz.RetryExhausted) as ei:
        retry_call(always, policy=_fast(max_attempts=3), site="t",
                   registry=reg, sleep=_noop_sleep)
    assert ei.value.site == "t" and ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, IOError)  # what actually failed
    assert "t" in str(ei.value) and "3" in str(ei.value)
    assert reg.get("retry_attempts_total", site="t").value == 2
    assert reg.get("retry_exhausted_total", site="t").value == 1


def test_retry_call_total_deadline():
    reg = Registry()
    clk = rz.FaultClock()

    def always():
        clk.advance(10.0)  # each attempt burns fake wall time
        raise IOError("slow and broken")

    with pytest.raises(rz.RetryExhausted) as ei:
        retry_call(
            always,
            policy=_fast(max_attempts=100, base_s=1.0, deadline_s=25.0),
            site="dl", registry=reg, clock=clk, sleep=clk.advance,
        )
    assert ei.value.reason == "total deadline"
    assert ei.value.attempts < 100  # the clock, not the count, gave up
    assert reg.get("retry_exhausted_total", site="dl").value == 1


def test_retry_call_non_retryable_passes_through():
    reg = Registry()

    def bug():
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry_call(bug, policy=_fast(), site="t", registry=reg,
                   sleep=_noop_sleep)
    assert reg.get("retry_attempts_total", site="t").value == 0
    assert reg.get("retry_exhausted_total", site="t").value == 0


def test_retry_call_attempt_timeout():
    reg = Registry()

    def hangs():
        time.sleep(5.0)

    with pytest.raises(rz.RetryExhausted) as ei:
        retry_call(
            hangs,
            policy=_fast(max_attempts=2, attempt_timeout_s=0.05),
            site="hang", registry=reg, sleep=_noop_sleep,
        )
    assert isinstance(ei.value.__cause__, rz.AttemptTimeout)
    assert reg.get("retry_exhausted_total", site="hang").value == 1


def test_retry_call_on_retry_failure_obeys_budget():
    """A hook (re-seek) that hits the same outage as the attempt counts
    against the budget and surfaces as RetryExhausted — never escapes
    retry_call raw."""
    reg = Registry()

    def always():
        raise IOError("fetch down")

    def broken_reseek(n, e):
        raise IOError("reopen down too")

    with pytest.raises(rz.RetryExhausted) as ei:
        retry_call(always, policy=_fast(max_attempts=3), site="rk",
                   registry=reg, sleep=_noop_sleep, on_retry=broken_reseek)
    assert isinstance(ei.value.__cause__, IOError)
    assert reg.get("retry_exhausted_total", site="rk").value == 1


def test_retry_call_on_retry_hook_runs_between_attempts():
    seen = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise IOError("once")
        return calls["n"]

    out = retry_call(flaky, policy=_fast(), site="h", registry=Registry(),
                     sleep=_noop_sleep,
                     on_retry=lambda n, e: seen.append((n, str(e))))
    assert out == 2 and seen == [(1, "once")]


# ---------------------------------------------------------------------------
# RetryingIterator: re-seek via the deterministic (seed, index) scheme
# ---------------------------------------------------------------------------


def _counting_stream(start):
    i = start
    while True:
        i += 1
        yield {"i": i}


def test_retrying_iterator_absorbs_transient_reseek():
    reg = Registry()
    plan = rz.FaultPlan((rz.TransientIOError(batch=3, times=2),))
    it = RetryingIterator(
        lambda i: plan.wrap(_counting_stream(i), start=i),
        _fast(max_attempts=5), registry=reg, sleep=_noop_sleep,
    )
    # the faulted fetch loses no data: the stream re-seeks to index 3
    assert [next(it)["i"] for _ in range(5)] == [1, 2, 3, 4, 5]
    assert it.index == 5
    assert reg.get("retry_attempts_total", site="data").value == 2
    assert reg.get("retry_exhausted_total", site="data").value == 0


def test_retrying_iterator_exhausts_on_permanent_fault():
    reg = Registry()
    plan = rz.FaultPlan((rz.TransientIOError(batch=2, times=10 ** 9),))
    it = RetryingIterator(
        lambda i: plan.wrap(_counting_stream(i), start=i),
        _fast(max_attempts=3), registry=reg, sleep=_noop_sleep,
    )
    assert next(it)["i"] == 1
    with pytest.raises(rz.RetryExhausted) as ei:
        next(it)
    assert ei.value.site == "data"
    assert isinstance(ei.value.__cause__, IOError)
    assert reg.get("retry_exhausted_total", site="data").value == 1
    # exhaustion classifies as transient for the Supervisor
    assert rz.classify_failure(ei.value) == rz.TRANSIENT


def test_retrying_iterator_finite_stream_ends_cleanly():
    def bounded(i):
        return iter([{"i": j} for j in range(i + 1, 4)])

    it = RetryingIterator(bounded, _fast(), registry=Registry(),
                          sleep=_noop_sleep)
    assert [b["i"] for b in it] == [1, 2, 3]


def test_retrying_iterator_resume_from_offset():
    """start_index positions the stream mid-run (checkpoint resume), and
    batch-indexed faults stay aligned with the GLOBAL index."""
    reg = Registry()
    plan = rz.FaultPlan((rz.TransientIOError(batch=2, times=1),))
    it = RetryingIterator(
        lambda i: plan.wrap(_counting_stream(i), start=i),
        _fast(), start_index=5, registry=reg, sleep=_noop_sleep,
    )
    # batches 6, 7: past the batch-2 fault index, but count>=batch means
    # the pending transient still fires once before decaying
    assert [next(it)["i"] for _ in range(2)] == [6, 7]
    assert reg.get("retry_attempts_total", site="data").value == 1


# ---------------------------------------------------------------------------
# seeded plans with the new kinds
# ---------------------------------------------------------------------------


def test_seeded_plan_new_kinds_deterministic():
    kinds = ("sigterm", "transient_io", "ckpt_corrupt")
    a = rz.FaultPlan.seeded(7, 20, kinds=kinds)
    b = rz.FaultPlan.seeded(7, 20, kinds=kinds)
    assert a == b
    assert a != rz.FaultPlan.seeded(8, 20, kinds=kinds)
    assert isinstance(a.faults[1], rz.TransientIOError)
    assert 1 <= a.faults[1].times <= 2
    assert isinstance(a.faults[2], rz.CorruptCheckpoint)
