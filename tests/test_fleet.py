"""Fleet supervision (resilience/fleet.py): heartbeat protocol,
liveness staleness edge cases on an injected clock (stale-but-ticking vs
absent vs previous-incarnation), exit-code classification, the gang
restart state machine driven by scripted fake workers, restart-budget
exhaustion with a postmortem that passes the ``--expect`` chain — and
the subprocess E2E acceptance gate: a 2-worker gang where one worker
hangs mid-run, is detected by missed heartbeats, and the gang-restarted
fleet finishes with params bit-identical to an uninterrupted run."""

import importlib.util
import os
import random
import subprocess
import sys

import numpy as np
import pytest

from distributed_tensorflow_tpu import resilience as rz
from distributed_tensorflow_tpu.obs import flightrec as fr
from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.resilience import fleet as fl
from distributed_tensorflow_tpu.runtime import io as io_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "chaos_worker.py")


# ---------------------------------------------------------------------------
# Heartbeat writer / reader
# ---------------------------------------------------------------------------


def test_heartbeat_roundtrip_and_persistence(tmp_path):
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock(10.0)
    w = fl.HeartbeatWriter(path, incarnation=3, clock=clk)
    w.beat(step=5, attempt=1, phase="train")
    hb = fl.read_heartbeat(path)
    assert (hb.pid, hb.seq, hb.step, hb.attempt) == (os.getpid(), 1, 5, 1)
    assert (hb.incarnation, hb.phase, hb.t) == (3, "train", 10.0)
    assert hb.restore_step is None
    # fields persist across beats; seq is strictly monotonic
    w.note_restore(4, fallback=True)
    w.beat(step=6)
    hb = fl.read_heartbeat(path)
    assert hb.seq == 3 and hb.step == 6
    assert hb.restore_step == 4 and hb.restore_fallback is True
    w.finish("done")
    hb = fl.read_heartbeat(path)
    assert hb.phase == "done" and hb.restore_step == 4
    assert not os.path.exists(path + ".tmp")  # atomic: tmp never lingers


def test_heartbeat_reader_absent_and_garbage(tmp_path):
    assert fl.read_heartbeat(str(tmp_path / "nope.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert fl.read_heartbeat(str(bad)) is None  # unreadable == absent


def test_heartbeat_pulse_thread_ticks_and_stops(tmp_path):
    path = str(tmp_path / "hb.json")
    w = fl.HeartbeatWriter(path, incarnation=1, pulse_interval_s=0.005)
    import time as time_lib

    deadline = time_lib.monotonic() + 5.0
    while time_lib.monotonic() < deadline:
        hb = fl.read_heartbeat(path)
        if hb is not None and hb.seq >= 3:
            break
        time_lib.sleep(0.005)
    assert fl.read_heartbeat(path).seq >= 3, "pulse thread never beat"
    w.close()
    seq = fl.read_heartbeat(path).seq
    time_lib.sleep(0.05)
    assert fl.read_heartbeat(path).seq == seq  # stopped


# ---------------------------------------------------------------------------
# Liveness monitor: the staleness edge cases, on an injected clock
# ---------------------------------------------------------------------------


def _monitor(path, clk, incarnation=1):
    return fl.HeartbeatMonitor(
        path, incarnation, clock=clk,
        heartbeat_timeout_s=5.0, stall_timeout_s=10.0, launch_grace_s=20.0)


def test_monitor_absent_heartbeat_is_death_after_grace(tmp_path):
    clk = rz.FaultClock()
    m = _monitor(str(tmp_path / "hb.json"), clk)
    assert m.check() == fl.WAITING
    clk.advance(19.0)
    assert m.check() == fl.WAITING  # still inside the launch grace
    clk.advance(2.0)
    assert m.check() == fl.DEAD


def test_monitor_silent_heartbeat_is_death(tmp_path):
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    w = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk)
    w.beat(step=1, phase="train")
    assert m.check() == fl.LIVE
    clk.advance(4.0)
    assert m.check() == fl.LIVE      # within the beat budget
    clk.advance(2.0)
    assert m.check() == fl.DEAD      # absent: seq frozen past budget


def test_monitor_ticking_but_frozen_step_is_stall(tmp_path):
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    w = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk)
    w.beat(step=7, phase="train")
    assert m.check() == fl.LIVE
    for _ in range(4):               # stale-but-ticking: seq up, step frozen
        clk.advance(3.0)
        w.beat()                     # pulse-style beat, same step
        status = m.check()
    assert status == fl.STALLED_HB
    # a step advancing clears the stall judgment
    w.beat(step=8)
    assert m.check() == fl.LIVE


def test_monitor_ignores_previous_incarnation(tmp_path):
    """A heartbeat freshly WRITTEN by a straggler of the previous
    incarnation must read as absent — never as liveness."""
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    old = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk, incarnation=2)
    for _ in range(21):
        old.beat(step=3, phase="train")  # fresh writes, wrong incarnation
        clk.advance(1.0)
    assert m.check() == fl.DEAD
    # the new incarnation checking in flips it to live
    fl.HeartbeatWriter(path, incarnation=2, clock=clk).beat(phase="train")
    assert m.check() == fl.LIVE


def test_monitor_stall_judges_any_phase_progress(tmp_path):
    """Progress = (step, attempt, phase) changing. A pulsed worker hung
    in build/restore (phase init, seq ticking) must stall out like a
    mid-train hang — otherwise the pulse thread makes init-phase hangs
    permanently invisible. Attempt/phase transitions count as progress;
    terminal phases are exempt (the process is exiting)."""
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    w = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk)
    w.beat(phase="init")
    assert m.check() == fl.LIVE      # anchors the progress clock
    for _ in range(4):
        clk.advance(3.0)
        w.beat()                     # pulse: seq up, no progress
        status = m.check()
    assert status == fl.STALLED_HB   # init-phase hang detected
    w.beat(attempt=1)                # a new attempt IS progress
    assert m.check() == fl.LIVE
    # terminal phases hold the step frozen legitimately
    w.beat(phase="done")
    for _ in range(5):
        clk.advance(3.0)
        w.beat()
        assert m.check() == fl.LIVE


# ---------------------------------------------------------------------------
# Control files + common checkpoint step
# ---------------------------------------------------------------------------


def test_incarnation_and_restore_files(tmp_path):
    d = str(tmp_path / "fleet")
    assert fl.read_incarnation(d) == 0
    assert fl.read_restore_step(d) is None
    fl.write_incarnation(d, 4)
    fl.write_restore_step(d, 12)
    assert fl.read_incarnation(d) == 4
    assert fl.read_restore_step(d) == 12


def _fake_ckpt_step(ckpt_dir, step, nbytes=64, manifest=True):
    d = os.path.join(ckpt_dir, str(step))
    os.makedirs(d, exist_ok=True)
    shard = os.path.join(d, "shard.bin")
    with open(shard, "wb") as f:
        # seeded: shard content is arbitrary but must be reproducible —
        # this helper fabricates the evidence the common-ceiling logic
        # verifies, and a replay oracle may not consume OS entropy
        f.write(random.Random(1000 + step).randbytes(nbytes))
    if manifest:
        payload = (
            '{"step": %d, "files": [{"path": "shard.bin", "bytes": %d}]}'
            % (step, nbytes)
        ).encode()
        io_lib.write_payload(os.path.join(d, "MANIFEST.dtf"), payload)
    return shard


def test_newest_common_valid_step(tmp_path):
    w0, w1 = str(tmp_path / "w0"), str(tmp_path / "w1")
    _fake_ckpt_step(w0, 2)
    shard4 = _fake_ckpt_step(w0, 4)
    _fake_ckpt_step(w1, 2)
    assert fl.newest_valid_step(w0) == 4
    assert fl.newest_common_valid_step([w0, w1]) == 2
    # torn newest shard: size check fails, older step wins
    with open(shard4, "r+b") as f:
        f.truncate(10)
    assert fl.newest_valid_step(w0) == 2
    # pre-manifest steps count as valid (restore unchecked, by design)
    _fake_ckpt_step(w1, 6, manifest=False)
    assert fl.newest_valid_step(w1) == 6
    # a worker with nothing restorable pins the gang to a fresh start
    assert fl.newest_common_valid_step([w0, str(tmp_path / "empty")]) == 0
    assert fl.newest_common_valid_step([]) is None
    # retention gap: a worker retaining ONLY steps newer than the
    # others' must not yield a ceiling it cannot restore itself — no
    # shared step means a gang-wide fresh start, never a split gang
    w2 = str(tmp_path / "w2")
    _fake_ckpt_step(w2, 10)
    assert fl.newest_common_valid_step([w0, w2]) == 0
    assert fl.newest_common_valid_step([w1, str(tmp_path / "w3")]) == 0


def test_restore_step_cleared_by_new_fleet_run(tmp_path):
    """A RESTORE_STEP left by a previous fleet run must not cap a new
    run's restores at an old step."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    fl.write_restore_step(fleet_dir, 2)  # stale ceiling from an old run

    def launch(i, incarnation):
        p = FakeProc()
        _beat(fleet_dir, i, incarnation, clk, step=8, phase="done")
        p.rc = 0
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1)
    fleet.run()
    assert fl.read_restore_step(fleet_dir) is None


# ---------------------------------------------------------------------------
# FleetSupervisor state machine, scripted fake workers, injected clock
# ---------------------------------------------------------------------------


class FakeProc:
    """The Popen control surface the fleet drives, fully scripted."""

    _next_pid = 1000

    def __init__(self):
        FakeProc._next_pid += 1
        self.pid = FakeProc._next_pid
        self.rc = None

    def poll(self):
        return self.rc

    def terminate(self):
        # a cooperative worker takes its preemption save and exits
        if self.rc is None:
            self.rc = fl.EXIT_PREEMPTED

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class Scenario:
    """Deterministic world driver: the fleet's injected ``sleep``
    advances the FaultClock and fires scheduled actions, so process
    deaths and heartbeats happen at exact simulated times."""

    def __init__(self, clk):
        self.clk = clk
        self._events = []

    def at(self, t, fn):
        self._events.append([float(t), fn, False])

    def sleep(self, s):
        self.clk.advance(s)
        for ev in sorted(self._events, key=lambda e: e[0]):
            if not ev[2] and self.clk.t >= ev[0]:
                ev[2] = True
                ev[1]()


def _mk_fleet(tmp_path, launch, clk, scenario, *, n=2, max_restarts=2,
              ckpt_dirs=None):
    rec = FlightRecorder(clock=clk)
    reg = Registry()
    cfg = fl.FleetConfig(
        max_restarts=max_restarts,
        backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0),
        poll_s=1.0, heartbeat_timeout_s=5.0, stall_timeout_s=10.0,
        launch_grace_s=20.0, term_grace_s=4.0)
    fleet = fl.FleetSupervisor(
        launch, n, str(tmp_path / "fleet"), cfg, ckpt_dirs=ckpt_dirs,
        registry=reg, flightrec=rec, clock=clk, sleep=scenario.sleep)
    return fleet, rec, reg


def _beat(fleet_dir, worker, incarnation, clk, *, step=None, phase="train",
          restore=None, cause=None):
    w = fl.HeartbeatWriter(fl.heartbeat_path(fleet_dir, worker),
                           incarnation=incarnation, clock=clk)
    if restore is not None:
        w.note_restore(restore, fallback=True)
    if cause is not None:
        w.finish(phase, cause=cause)
    else:
        w.beat(step=step, phase=phase)


def test_fleet_gang_restart_on_worker_death(tmp_path):
    """Exit-code death of one worker → whole-gang SIGTERM, incarnation
    bump, relaunch; the relayed restore note lands BEFORE fleet_restart
    so the timeline reads causally."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append((i, incarnation, p))
        if incarnation == 2:
            # relaunched worker: restores at the common step, finishes
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=4)
            p.rc = 0
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=2))
    sc.at(1.0, lambda: _beat(fleet_dir, 1, 1, clk, step=2))
    # worker 1 dies hard (SIGKILL-shaped rc); worker 0 stays healthy
    sc.at(2.0, lambda: setattr(launches[1][2], "rc", -9))
    sc.at(3.0, lambda: _beat(fleet_dir, 0, 1, clk, step=3))

    out = fleet.run()
    assert out == {"restarts": 1, "incarnation": 2}
    assert fl.read_incarnation(fleet_dir) == 2
    assert [(i, inc) for i, inc, _ in launches] == [
        (0, 1), (1, 1), (0, 2), (1, 2)]
    # the survivor got the gang-stop SIGTERM
    assert launches[0][2].rc == fl.EXIT_PREEMPTED
    assert fr.contains_in_order(rec.events(), [
        ("fleet_start", {"workers": 2}),
        ("fleet_launch", {"worker": 0, "incarnation": 1}),
        ("fleet_worker_dead", {"worker": 1, "cause": rz.TRANSIENT}),
        ("fleet_gang_stop", {"cause": rz.TRANSIENT}),
        ("ckpt_restore", {"fallback": True, "relayed": True}),
        ("fleet_restart", {"restart": 1, "cause": rz.TRANSIENT}),
        ("fleet_done", {"incarnation": 2}),
    ])
    assert reg.get(fl.FLEET_RESTARTS_TOTAL, cause=rz.TRANSIENT).value == 1
    assert reg.get(fl.FLEET_WORKER_DEATHS_TOTAL).value == 1


def test_fleet_detects_missed_heartbeats_and_exhausts(tmp_path):
    """A worker that stays alive but never beats is declared dead by
    liveness; with the budget at 0 the fleet raises FleetExhausted and
    the dumped postmortem passes the tools/postmortem.py --expect
    chain."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    procs = []

    def launch(i, incarnation):
        p = FakeProc()
        procs.append(p)
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, max_restarts=0)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=1))
    # worker 1: alive forever, zero beats → dead after the launch grace
    with pytest.raises(fl.FleetExhausted) as ei:
        fleet.run()
    assert ei.value.cause == rz.TRANSIENT
    assert "heartbeat" in str(ei.value)
    assert all(p.rc is not None for p in procs)  # gang fully stopped
    dump = os.path.join(fleet.workdir, "postmortem.jsonl")
    assert os.path.exists(dump)
    spec = importlib.util.spec_from_file_location(
        "postmortem", os.path.join(REPO, "tools", "postmortem.py"))
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    assert pm.main([dump, "--quiet", "--expect",
                    "fleet_start,fleet_worker_dead[cause=transient],"
                    "fleet_gang_stop,fleet_exhausted[cause=transient]"]) == 0


def test_fleet_stall_is_classified_stalled(tmp_path):
    """Heartbeats ticking but the step frozen → the per-process stall
    judgment, classified through classify_failure(StalledError) =
    'stalled'."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")

    def launch(i, incarnation):
        return FakeProc()

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1,
                                max_restarts=0)
    # ONE writer so seq keeps ticking (pulse-style) while the step never
    # advances — the live-but-frozen process the stall budget exists for
    w = fl.HeartbeatWriter(fl.heartbeat_path(fleet_dir, 0), incarnation=1,
                           clock=clk)
    sc.at(0.5, lambda: w.beat(step=5, phase="train"))
    for t in range(1, 40):
        sc.at(float(t), w.beat)
    with pytest.raises(fl.FleetExhausted) as ei:
        fleet.run()
    assert ei.value.cause == rz.STALLED
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {"cause": rz.STALLED}),
        ("fleet_gang_stop", {}), ("fleet_exhausted", {"cause": rz.STALLED}),
    ])


def test_fleet_nonrestartable_cause_raises_without_restart(tmp_path):
    """EXIT_FAILED with a fatal cause in the final heartbeat must not
    burn a restart — it raises immediately."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    procs = []

    def launch(i, incarnation):
        p = FakeProc()
        procs.append(p)
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1,
                                max_restarts=5)
    def fail():
        _beat(fleet_dir, 0, 1, clk, phase="failed", cause=rz.FATAL)
        procs[0].rc = fl.EXIT_FAILED

    sc.at(1.0, fail)
    with pytest.raises(fl.FleetExhausted) as ei:
        fleet.run()
    assert ei.value.cause == rz.FATAL
    assert fleet.restarts == 0
    assert len(procs) == 1  # never relaunched


def test_fleet_spontaneous_preemption_restarts_gang(tmp_path):
    """A worker exiting via its coordinated preemption save (rc 75, not
    ours) is a restartable gang failure with cause=preemption."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append((incarnation, p))
        if incarnation == 2:
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=2)
            p.rc = 0
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=3))
    sc.at(2.0, lambda: setattr(launches[0][1], "rc", fl.EXIT_PREEMPTED))
    out = fleet.run()
    assert out["restarts"] == 1
    assert reg.get(fl.FLEET_RESTARTS_TOTAL, cause=rz.PREEMPTION).value == 1


def test_fleet_writes_common_restore_ceiling(tmp_path):
    """At a gang restart the fleet computes the newest step EVERY worker
    can restore and writes it as the ceiling the relaunch reads."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    w0, w1 = str(tmp_path / "ck0"), str(tmp_path / "ck1")
    _fake_ckpt_step(w0, 2)
    _fake_ckpt_step(w0, 4)
    _fake_ckpt_step(w0, 6)
    _fake_ckpt_step(w1, 2)
    _fake_ckpt_step(w1, 4)
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append(p)
        if incarnation == 2:
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=4)
            p.rc = 0
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=2,
                                ckpt_dirs=[w0, w1])
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=6))
    sc.at(1.0, lambda: _beat(fleet_dir, 1, 1, clk, step=4))
    sc.at(2.0, lambda: setattr(launches[1], "rc", 1))  # crash
    fleet.run()
    assert fl.read_restore_step(fleet_dir) == 4  # newest shared step
    # abandoned history above the ceiling is moved aside: left in
    # place, w0's step 6 would shadow the re-trained step 6 forever
    # (save() skips existing step numbers)
    assert not os.path.isdir(os.path.join(w0, "6"))
    assert os.path.isdir(os.path.join(w0, ".abandoned", "6"))
    assert fl.valid_steps(w0) == [2, 4]


def test_fleet_flags_restore_divergence(tmp_path):
    """A relaunched worker whose restore landed on a DIFFERENT step
    than the gang ceiling (quarantined copy, fresh init) is a
    gang-consistency failure, not a silent split gang."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    w0 = str(tmp_path / "ck0")
    _fake_ckpt_step(w0, 4)
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append(p)
        if incarnation == 2:
            # worker claims it restored step 2, but the gang ceiling is 4
            _beat(fleet_dir, i, 2, clk, step=8, phase="train", restore=2)
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1,
                                max_restarts=1, ckpt_dirs=[w0])
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=4))
    sc.at(2.0, lambda: setattr(launches[0], "rc", -9))
    with pytest.raises(fl.FleetExhausted) as ei:
        fleet.run()
    assert ei.value.cause == rz.TRANSIENT
    assert "divergence" in str(ei.value)
    assert fr.contains_in_order(rec.events(), [
        ("fleet_restart", {}),  # never emitted for the diverged gang
    ]) is False
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {"cause": rz.TRANSIENT}),
        ("fleet_gang_stop", {}),
        ("fleet_worker_dead", {"cause": rz.TRANSIENT}),
        ("fleet_exhausted", {}),
    ])


def test_fleet_interrupt_wakes_default_wait():
    import time as time_lib

    fleet = fl.FleetSupervisor(lambda i, k: FakeProc(), 1, "/tmp/unused-fleet",
                               flightrec=FlightRecorder(), registry=Registry())
    fleet.interrupt()
    t0 = time_lib.monotonic()
    fleet._wait(30.0)
    assert time_lib.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# Subprocess E2E: missed-heartbeat death → gang restart → bit-identity
# ---------------------------------------------------------------------------


def _env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the worker sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_straight(workdir, out, timeout=240):
    p = subprocess.run(
        [sys.executable, WORKER, str(workdir), "--steps", "8", "--out", out],
        capture_output=True, text=True, timeout=timeout, env=_env(),
    )
    assert p.returncode == 0, f"rc={p.returncode}:\n{p.stdout}\n{p.stderr}"
    assert "CHAOS-DONE step=8" in p.stdout, p.stdout


def test_fleet_e2e_gang_restart_bit_identical(tmp_path):
    """THE fleet acceptance gate: worker 1 hangs mid-run (heartbeats
    stop, process alive), the FleetSupervisor detects the death by
    missed heartbeats, gang-restarts with a bumped incarnation from the
    latest common valid checkpoint, and every worker's final params are
    bit-identical to an uninterrupted same-seed run."""
    straight_out = str(tmp_path / "straight.npz")
    _run_straight(tmp_path / "straight_ckpt", straight_out)

    fleet_dir = str(tmp_path / "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    ckpt_dirs = [str(tmp_path / f"ckpt{i}") for i in range(2)]
    outs = [str(tmp_path / f"out{i}.npz") for i in range(2)]

    def launch(i, incarnation):
        args = [sys.executable, WORKER, ckpt_dirs[i], "--fleet",
                "--fleet-dir", fleet_dir, "--worker-index", str(i),
                "--steps", "8", "--out", outs[i]]
        if i == 1:
            args += ["--hang-at", "3"]  # gated to incarnation 1
        log = open(os.path.join(fleet_dir, f"worker{i}-inc{incarnation}.log"),
                   "w")
        try:
            return subprocess.Popen(args, stdout=log,
                                    stderr=subprocess.STDOUT, env=_env())
        finally:
            log.close()

    rec = FlightRecorder()
    reg = Registry()
    fleet = fl.FleetSupervisor(
        launch, 2, fleet_dir,
        fl.FleetConfig(max_restarts=2,
                       backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0),
                       poll_s=0.2, heartbeat_timeout_s=20.0,
                       stall_timeout_s=600.0, launch_grace_s=180.0,
                       term_grace_s=5.0),
        ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec)
    out = fleet.run()

    assert out["restarts"] == 1, _logs(fleet_dir)
    assert out["incarnation"] == 2
    assert fl.read_incarnation(fleet_dir) == 2
    # the hung worker had saved step 2 (cadence 2, hang at 3): the
    # common valid step the gang restarted from must honor it
    assert fl.read_restore_step(fleet_dir) == 2
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {"worker": 1, "cause": rz.TRANSIENT}),
        ("fleet_gang_stop", {"cause": rz.TRANSIENT}),
        ("ckpt_restore", {"fallback": True, "relayed": True}),
        ("fleet_restart", {"restart": 1, "incarnation": 2}),
        ("fleet_done", {}),
    ]), rec.events()
    assert reg.get(fl.FLEET_WORKER_DEATHS_TOTAL).value == 1

    a = np.load(straight_out)
    for o in outs:
        b = np.load(o)
        assert sorted(a.files) == sorted(b.files) and a.files
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k])  # BIT-identical


def _logs(fleet_dir):
    chunks = []
    for n in sorted(os.listdir(fleet_dir)):
        if n.endswith(".log"):
            with open(os.path.join(fleet_dir, n)) as f:
                chunks.append(f"--- {n} ---\n{f.read()}")
    return "\n".join(chunks)
