"""Fleet supervision (resilience/fleet.py): heartbeat protocol,
liveness staleness edge cases on an injected clock (stale-but-ticking vs
absent vs previous-incarnation), exit-code classification, the gang
restart state machine driven by scripted fake workers, restart-budget
exhaustion with a postmortem that passes the ``--expect`` chain — and
the subprocess E2E acceptance gate: a 2-worker gang where one worker
hangs mid-run, is detected by missed heartbeats, and the gang-restarted
fleet finishes with params bit-identical to an uninterrupted run."""

import importlib.util
import os
import random
import subprocess
import sys

import numpy as np
import pytest

from distributed_tensorflow_tpu import resilience as rz
from distributed_tensorflow_tpu.obs import flightrec as fr
from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.resilience import fleet as fl
from distributed_tensorflow_tpu.runtime import io as io_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "chaos_worker.py")


# ---------------------------------------------------------------------------
# Heartbeat writer / reader
# ---------------------------------------------------------------------------


def test_heartbeat_roundtrip_and_persistence(tmp_path):
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock(10.0)
    w = fl.HeartbeatWriter(path, incarnation=3, clock=clk)
    w.beat(step=5, attempt=1, phase="train")
    hb = fl.read_heartbeat(path)
    assert (hb.pid, hb.seq, hb.step, hb.attempt) == (os.getpid(), 1, 5, 1)
    assert (hb.incarnation, hb.phase, hb.t) == (3, "train", 10.0)
    assert hb.restore_step is None
    # fields persist across beats; seq is strictly monotonic
    w.note_restore(4, fallback=True)
    w.beat(step=6)
    hb = fl.read_heartbeat(path)
    assert hb.seq == 3 and hb.step == 6
    assert hb.restore_step == 4 and hb.restore_fallback is True
    w.finish("done")
    hb = fl.read_heartbeat(path)
    assert hb.phase == "done" and hb.restore_step == 4
    assert not os.path.exists(path + ".tmp")  # atomic: tmp never lingers


def test_heartbeat_reader_absent_and_garbage(tmp_path):
    assert fl.read_heartbeat(str(tmp_path / "nope.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert fl.read_heartbeat(str(bad)) is None  # unreadable == absent


def test_heartbeat_pulse_thread_ticks_and_stops(tmp_path):
    path = str(tmp_path / "hb.json")
    w = fl.HeartbeatWriter(path, incarnation=1, pulse_interval_s=0.005)
    import time as time_lib

    deadline = time_lib.monotonic() + 5.0
    while time_lib.monotonic() < deadline:
        hb = fl.read_heartbeat(path)
        if hb is not None and hb.seq >= 3:
            break
        time_lib.sleep(0.005)
    assert fl.read_heartbeat(path).seq >= 3, "pulse thread never beat"
    w.close()
    seq = fl.read_heartbeat(path).seq
    time_lib.sleep(0.05)
    assert fl.read_heartbeat(path).seq == seq  # stopped


# ---------------------------------------------------------------------------
# Liveness monitor: the staleness edge cases, on an injected clock
# ---------------------------------------------------------------------------


def _monitor(path, clk, incarnation=1):
    return fl.HeartbeatMonitor(
        path, incarnation, clock=clk,
        heartbeat_timeout_s=5.0, stall_timeout_s=10.0, launch_grace_s=20.0)


def test_monitor_absent_heartbeat_is_death_after_grace(tmp_path):
    clk = rz.FaultClock()
    m = _monitor(str(tmp_path / "hb.json"), clk)
    assert m.check() == fl.WAITING
    clk.advance(19.0)
    assert m.check() == fl.WAITING  # still inside the launch grace
    clk.advance(2.0)
    assert m.check() == fl.DEAD


def test_monitor_silent_heartbeat_is_death(tmp_path):
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    w = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk)
    w.beat(step=1, phase="train")
    assert m.check() == fl.LIVE
    clk.advance(4.0)
    assert m.check() == fl.LIVE      # within the beat budget
    clk.advance(2.0)
    assert m.check() == fl.DEAD      # absent: seq frozen past budget


def test_monitor_ticking_but_frozen_step_is_stall(tmp_path):
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    w = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk)
    w.beat(step=7, phase="train")
    assert m.check() == fl.LIVE
    for _ in range(4):               # stale-but-ticking: seq up, step frozen
        clk.advance(3.0)
        w.beat()                     # pulse-style beat, same step
        status = m.check()
    assert status == fl.STALLED_HB
    # a step advancing clears the stall judgment
    w.beat(step=8)
    assert m.check() == fl.LIVE


def test_monitor_ignores_previous_incarnation(tmp_path):
    """A heartbeat freshly WRITTEN by a straggler of the previous
    incarnation must read as absent — never as liveness."""
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    old = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk, incarnation=2)
    for _ in range(21):
        old.beat(step=3, phase="train")  # fresh writes, wrong incarnation
        clk.advance(1.0)
    assert m.check() == fl.DEAD
    # the new incarnation checking in flips it to live
    fl.HeartbeatWriter(path, incarnation=2, clock=clk).beat(phase="train")
    assert m.check() == fl.LIVE


def test_monitor_stall_judges_any_phase_progress(tmp_path):
    """Progress = (step, attempt, phase) changing. A pulsed worker hung
    in build/restore (phase init, seq ticking) must stall out like a
    mid-train hang — otherwise the pulse thread makes init-phase hangs
    permanently invisible. Attempt/phase transitions count as progress;
    terminal phases are exempt (the process is exiting)."""
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    w = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk)
    w.beat(phase="init")
    assert m.check() == fl.LIVE      # anchors the progress clock
    for _ in range(4):
        clk.advance(3.0)
        w.beat()                     # pulse: seq up, no progress
        status = m.check()
    assert status == fl.STALLED_HB   # init-phase hang detected
    w.beat(attempt=1)                # a new attempt IS progress
    assert m.check() == fl.LIVE
    # terminal phases hold the step frozen legitimately
    w.beat(phase="done")
    for _ in range(5):
        clk.advance(3.0)
        w.beat()
        assert m.check() == fl.LIVE


# ---------------------------------------------------------------------------
# Control files + common checkpoint step
# ---------------------------------------------------------------------------


def test_incarnation_and_restore_files(tmp_path):
    d = str(tmp_path / "fleet")
    assert fl.read_incarnation(d) == 0
    assert fl.read_restore_step(d) is None
    fl.write_incarnation(d, 4)
    fl.write_restore_step(d, 12)
    assert fl.read_incarnation(d) == 4
    assert fl.read_restore_step(d) == 12


def _fake_ckpt_step(ckpt_dir, step, nbytes=64, manifest=True):
    d = os.path.join(ckpt_dir, str(step))
    os.makedirs(d, exist_ok=True)
    shard = os.path.join(d, "shard.bin")
    with open(shard, "wb") as f:
        # seeded: shard content is arbitrary but must be reproducible —
        # this helper fabricates the evidence the common-ceiling logic
        # verifies, and a replay oracle may not consume OS entropy
        f.write(random.Random(1000 + step).randbytes(nbytes))
    if manifest:
        payload = (
            '{"step": %d, "files": [{"path": "shard.bin", "bytes": %d}]}'
            % (step, nbytes)
        ).encode()
        io_lib.write_payload(os.path.join(d, "MANIFEST.dtf"), payload)
    return shard


def test_newest_common_valid_step(tmp_path):
    w0, w1 = str(tmp_path / "w0"), str(tmp_path / "w1")
    _fake_ckpt_step(w0, 2)
    shard4 = _fake_ckpt_step(w0, 4)
    _fake_ckpt_step(w1, 2)
    assert fl.newest_valid_step(w0) == 4
    assert fl.newest_common_valid_step([w0, w1]) == 2
    # torn newest shard: size check fails, older step wins
    with open(shard4, "r+b") as f:
        f.truncate(10)
    assert fl.newest_valid_step(w0) == 2
    # pre-manifest steps count as valid (restore unchecked, by design)
    _fake_ckpt_step(w1, 6, manifest=False)
    assert fl.newest_valid_step(w1) == 6
    # a worker with nothing restorable pins the gang to a fresh start
    assert fl.newest_common_valid_step([w0, str(tmp_path / "empty")]) == 0
    assert fl.newest_common_valid_step([]) is None
    # retention gap: a worker retaining ONLY steps newer than the
    # others' must not yield a ceiling it cannot restore itself — no
    # shared step means a gang-wide fresh start, never a split gang
    w2 = str(tmp_path / "w2")
    _fake_ckpt_step(w2, 10)
    assert fl.newest_common_valid_step([w0, w2]) == 0
    assert fl.newest_common_valid_step([w1, str(tmp_path / "w3")]) == 0


def test_restore_step_cleared_by_new_fleet_run(tmp_path):
    """A RESTORE_STEP left by a previous fleet run must not cap a new
    run's restores at an old step."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    fl.write_restore_step(fleet_dir, 2)  # stale ceiling from an old run

    def launch(i, incarnation):
        p = FakeProc()
        _beat(fleet_dir, i, incarnation, clk, step=8, phase="done")
        p.rc = 0
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1)
    fleet.run()
    assert fl.read_restore_step(fleet_dir) is None


# ---------------------------------------------------------------------------
# FleetSupervisor state machine, scripted fake workers, injected clock
# ---------------------------------------------------------------------------


class FakeProc:
    """The Popen control surface the fleet drives, fully scripted."""

    _next_pid = 1000

    def __init__(self):
        FakeProc._next_pid += 1
        self.pid = FakeProc._next_pid
        self.rc = None

    def poll(self):
        return self.rc

    def terminate(self):
        # a cooperative worker takes its preemption save and exits
        if self.rc is None:
            self.rc = fl.EXIT_PREEMPTED

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class Scenario:
    """Deterministic world driver: the fleet's injected ``sleep``
    advances the FaultClock and fires scheduled actions, so process
    deaths and heartbeats happen at exact simulated times."""

    def __init__(self, clk):
        self.clk = clk
        self._events = []

    def at(self, t, fn):
        self._events.append([float(t), fn, False])

    def sleep(self, s):
        self.clk.advance(s)
        for ev in sorted(self._events, key=lambda e: e[0]):
            if not ev[2] and self.clk.t >= ev[0]:
                ev[2] = True
                ev[1]()


def _mk_fleet(tmp_path, launch, clk, scenario, *, n=2, max_restarts=2,
              ckpt_dirs=None, **cfg_kw):
    rec = FlightRecorder(clock=clk)
    reg = Registry()
    cfg = fl.FleetConfig(
        max_restarts=max_restarts,
        backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0),
        poll_s=1.0, heartbeat_timeout_s=5.0, stall_timeout_s=10.0,
        launch_grace_s=20.0, term_grace_s=4.0, **cfg_kw)
    fleet = fl.FleetSupervisor(
        launch, n, str(tmp_path / "fleet"), cfg, ckpt_dirs=ckpt_dirs,
        registry=reg, flightrec=rec, clock=clk, sleep=scenario.sleep)
    return fleet, rec, reg


def _beat(fleet_dir, worker, incarnation, clk, *, step=None, phase="train",
          restore=None, cause=None):
    w = fl.HeartbeatWriter(fl.heartbeat_path(fleet_dir, worker),
                           incarnation=incarnation, clock=clk)
    if restore is not None:
        w.note_restore(restore, fallback=True)
    if cause is not None:
        w.finish(phase, cause=cause)
    else:
        w.beat(step=step, phase=phase)


def test_fleet_gang_restart_on_worker_death(tmp_path):
    """Exit-code death of one worker → whole-gang SIGTERM, incarnation
    bump, relaunch; the relayed restore note lands BEFORE fleet_restart
    so the timeline reads causally."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append((i, incarnation, p))
        if incarnation == 2:
            # relaunched worker: restores at the common step, finishes
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=4)
            p.rc = 0
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=2))
    sc.at(1.0, lambda: _beat(fleet_dir, 1, 1, clk, step=2))
    # worker 1 dies hard (SIGKILL-shaped rc); worker 0 stays healthy
    sc.at(2.0, lambda: setattr(launches[1][2], "rc", -9))
    sc.at(3.0, lambda: _beat(fleet_dir, 0, 1, clk, step=3))

    out = fleet.run()
    assert out == {"restarts": 1, "incarnation": 2, "resizes": 0}
    assert fl.read_incarnation(fleet_dir) == 2
    assert [(i, inc) for i, inc, _ in launches] == [
        (0, 1), (1, 1), (0, 2), (1, 2)]
    # the survivor got the gang-stop SIGTERM
    assert launches[0][2].rc == fl.EXIT_PREEMPTED
    assert fr.contains_in_order(rec.events(), [
        ("fleet_start", {"workers": 2}),
        ("fleet_launch", {"worker": 0, "incarnation": 1}),
        ("fleet_worker_dead", {"worker": 1, "cause": rz.TRANSIENT}),
        ("fleet_gang_stop", {"cause": rz.TRANSIENT}),
        ("ckpt_restore", {"fallback": True, "relayed": True}),
        ("fleet_restart", {"restart": 1, "cause": rz.TRANSIENT}),
        ("fleet_done", {"incarnation": 2}),
    ])
    assert reg.get(fl.FLEET_RESTARTS_TOTAL, cause=rz.TRANSIENT).value == 1
    assert reg.get(fl.FLEET_WORKER_DEATHS_TOTAL).value == 1


def test_fleet_detects_missed_heartbeats_and_exhausts(tmp_path):
    """A worker that stays alive but never beats is declared dead by
    liveness; with the budget at 0 the fleet raises FleetExhausted and
    the dumped postmortem passes the tools/postmortem.py --expect
    chain."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    procs = []

    def launch(i, incarnation):
        p = FakeProc()
        procs.append(p)
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, max_restarts=0)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=1))
    # worker 1: alive forever, zero beats → dead after the launch grace
    with pytest.raises(fl.FleetExhausted) as ei:
        fleet.run()
    assert ei.value.cause == rz.TRANSIENT
    assert "heartbeat" in str(ei.value)
    assert all(p.rc is not None for p in procs)  # gang fully stopped
    dump = os.path.join(fleet.workdir, "postmortem.jsonl")
    assert os.path.exists(dump)
    spec = importlib.util.spec_from_file_location(
        "postmortem", os.path.join(REPO, "tools", "postmortem.py"))
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)
    assert pm.main([dump, "--quiet", "--expect",
                    "fleet_start,fleet_worker_dead[cause=transient],"
                    "fleet_gang_stop,fleet_exhausted[cause=transient]"]) == 0


def test_fleet_stall_is_classified_stalled(tmp_path):
    """Heartbeats ticking but the step frozen → the per-process stall
    judgment, classified through classify_failure(StalledError) =
    'stalled'."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")

    def launch(i, incarnation):
        return FakeProc()

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1,
                                max_restarts=0)
    # ONE writer so seq keeps ticking (pulse-style) while the step never
    # advances — the live-but-frozen process the stall budget exists for
    w = fl.HeartbeatWriter(fl.heartbeat_path(fleet_dir, 0), incarnation=1,
                           clock=clk)
    sc.at(0.5, lambda: w.beat(step=5, phase="train"))
    for t in range(1, 40):
        sc.at(float(t), w.beat)
    with pytest.raises(fl.FleetExhausted) as ei:
        fleet.run()
    assert ei.value.cause == rz.STALLED
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {"cause": rz.STALLED}),
        ("fleet_gang_stop", {}), ("fleet_exhausted", {"cause": rz.STALLED}),
    ])


def test_fleet_nonrestartable_cause_raises_without_restart(tmp_path):
    """EXIT_FAILED with a fatal cause in the final heartbeat must not
    burn a restart — it raises immediately."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    procs = []

    def launch(i, incarnation):
        p = FakeProc()
        procs.append(p)
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1,
                                max_restarts=5)
    def fail():
        _beat(fleet_dir, 0, 1, clk, phase="failed", cause=rz.FATAL)
        procs[0].rc = fl.EXIT_FAILED

    sc.at(1.0, fail)
    with pytest.raises(fl.FleetExhausted) as ei:
        fleet.run()
    assert ei.value.cause == rz.FATAL
    assert fleet.restarts == 0
    assert len(procs) == 1  # never relaunched


def test_fleet_spontaneous_preemption_restarts_gang(tmp_path):
    """A worker exiting via its coordinated preemption save (rc 75, not
    ours) is a restartable gang failure with cause=preemption."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append((incarnation, p))
        if incarnation == 2:
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=2)
            p.rc = 0
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=3))
    sc.at(2.0, lambda: setattr(launches[0][1], "rc", fl.EXIT_PREEMPTED))
    out = fleet.run()
    assert out["restarts"] == 1
    assert reg.get(fl.FLEET_RESTARTS_TOTAL, cause=rz.PREEMPTION).value == 1


def test_fleet_writes_common_restore_ceiling(tmp_path):
    """At a gang restart the fleet computes the newest step EVERY worker
    can restore and writes it as the ceiling the relaunch reads."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    w0, w1 = str(tmp_path / "ck0"), str(tmp_path / "ck1")
    _fake_ckpt_step(w0, 2)
    _fake_ckpt_step(w0, 4)
    _fake_ckpt_step(w0, 6)
    _fake_ckpt_step(w1, 2)
    _fake_ckpt_step(w1, 4)
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append(p)
        if incarnation == 2:
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=4)
            p.rc = 0
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=2,
                                ckpt_dirs=[w0, w1])
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=6))
    sc.at(1.0, lambda: _beat(fleet_dir, 1, 1, clk, step=4))
    sc.at(2.0, lambda: setattr(launches[1], "rc", 1))  # crash
    fleet.run()
    assert fl.read_restore_step(fleet_dir) == 4  # newest shared step
    # abandoned history above the ceiling is moved aside: left in
    # place, w0's step 6 would shadow the re-trained step 6 forever
    # (save() skips existing step numbers)
    assert not os.path.isdir(os.path.join(w0, "6"))
    assert os.path.isdir(os.path.join(w0, ".abandoned", "6"))
    assert fl.valid_steps(w0) == [2, 4]


def test_fleet_flags_restore_divergence(tmp_path):
    """A relaunched worker whose restore landed on a DIFFERENT step
    than the gang ceiling (quarantined copy, fresh init) is a
    gang-consistency failure, not a silent split gang."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    w0 = str(tmp_path / "ck0")
    _fake_ckpt_step(w0, 4)
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append(p)
        if incarnation == 2:
            # worker claims it restored step 2, but the gang ceiling is 4
            _beat(fleet_dir, i, 2, clk, step=8, phase="train", restore=2)
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1,
                                max_restarts=1, ckpt_dirs=[w0])
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=4))
    sc.at(2.0, lambda: setattr(launches[0], "rc", -9))
    with pytest.raises(fl.FleetExhausted) as ei:
        fleet.run()
    assert ei.value.cause == rz.TRANSIENT
    assert "divergence" in str(ei.value)
    assert fr.contains_in_order(rec.events(), [
        ("fleet_restart", {}),  # never emitted for the diverged gang
    ]) is False
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {"cause": rz.TRANSIENT}),
        ("fleet_gang_stop", {}),
        ("fleet_worker_dead", {"cause": rz.TRANSIENT}),
        ("fleet_exhausted", {}),
    ])


def test_fleet_interrupt_wakes_default_wait():
    import time as time_lib

    fleet = fl.FleetSupervisor(lambda i, k: FakeProc(), 1, "/tmp/unused-fleet",
                               flightrec=FlightRecorder(), registry=Registry())
    fleet.interrupt()
    t0 = time_lib.monotonic()
    fleet._wait(30.0)
    assert time_lib.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# Elastic resize: plan file, worker client, supervisor state machine
# ---------------------------------------------------------------------------


def test_shard_plan_roundtrip_and_validation(tmp_path):
    d = str(tmp_path / "fleet")
    assert fl.read_shard_plan(d) is None
    plan = fl.ShardPlan(version=3, phase=fl.PLAN_STEADY, world=2,
                        ranks={0: 0, 2: 1}, barrier_step=7, incarnation=1,
                        fleet_size=3)
    fl.write_shard_plan(d, plan)
    assert fl.read_shard_plan(d) == plan
    hold = fl.ShardPlan(version=4, phase=fl.PLAN_HOLD, world=2,
                        ranks={0: 0, 2: 1}, barrier_step=7, hold=(0, 2))
    fl.write_shard_plan(d, hold)
    assert fl.read_shard_plan(d).hold == (0, 2)
    fl.clear_shard_plan(d)
    assert fl.read_shard_plan(d) is None
    # garbage reads as absent (conservative: keep the last applied plan)
    with open(os.path.join(d, "SHARD_PLAN"), "w") as f:
        f.write("{broken")
    assert fl.read_shard_plan(d) is None
    with pytest.raises(ValueError, match="phase"):
        fl.ShardPlan(version=1, phase="frozen", world=1, ranks={0: 0},
                     barrier_step=0)
    with pytest.raises(ValueError, match="bijection"):
        fl.ShardPlan(version=1, phase=fl.PLAN_STEADY, world=2,
                     ranks={0: 0, 1: 2}, barrier_step=0)
    with pytest.raises(ValueError, match="must be served"):
        # an unserved rank would silently drop a slice of every batch
        fl.ShardPlan(version=1, phase=fl.PLAN_STEADY, world=3,
                     ranks={0: 0, 1: 1}, barrier_step=0)


def test_fleet_config_validates_elastic_knobs(tmp_path):
    """Satellite: the new elastic knobs fail fast with actionable
    messages."""
    with pytest.raises(ValueError, match="min_workers must be >= 1"):
        fl.FleetConfig(elastic=True, min_workers=0)
    with pytest.raises(ValueError, match="rejoin_grace_s must be > 0"):
        fl.FleetConfig(elastic=True, rejoin_grace_s=0.0)
    with pytest.raises(ValueError, match="hold_timeout_s must be > 0"):
        fl.FleetConfig(elastic=True, hold_timeout_s=-1.0)
    with pytest.raises(ValueError, match="incompatible with num_workers=1"):
        fl.FleetSupervisor(lambda i, k: FakeProc(), 1, str(tmp_path),
                           fl.FleetConfig(elastic=True),
                           flightrec=FlightRecorder(), registry=Registry())
    with pytest.raises(ValueError, match="min_workers=5 exceeds"):
        fl.FleetSupervisor(lambda i, k: FakeProc(), 2, str(tmp_path),
                           fl.FleetConfig(elastic=True, min_workers=5),
                           flightrec=FlightRecorder(), registry=Registry())


def test_newest_common_valid_step_over_subset(tmp_path):
    """Satellite: the N-1 gang case — the ceiling is computed over the
    dirs you PASS. A dead worker's behind dir, not passed, must not
    veto; a member dir whose newer steps were evicted above a ceiling
    must."""
    w0, w1, dead = (str(tmp_path / n) for n in ("w0", "w1", "dead"))
    for s in (2, 4, 6):
        _fake_ckpt_step(w0, s)
        _fake_ckpt_step(w1, s)
    _fake_ckpt_step(dead, 2)  # died long ago, far behind
    # full gang: the dead dir drags the ceiling down to 2
    assert fl.newest_common_valid_step([w0, w1, dead]) == 2
    # N-1 live members only: the dead worker cannot veto
    assert fl.newest_common_valid_step([w0, w1]) == 6
    # but an eviction DOES bind: once w1 rolled back to 4, the shrunken
    # gang's ceiling must follow it
    assert fl.evict_steps_above(w1, 4) == [6]
    assert fl.newest_common_valid_step([w0, w1]) == 4
    assert fl.valid_steps(w1) == [2, 4]


def test_monitor_barrier_phase_is_not_a_stall(tmp_path):
    """A member paused at a resize barrier beats with a frozen step for
    as long as the fleet holds it — sanctioned, never a stall (the
    fleet bounds holds with hold_timeout_s)."""
    path = str(tmp_path / "hb.json")
    clk = rz.FaultClock()
    w = fl.HeartbeatWriter(path, incarnation=1, clock=clk)
    m = _monitor(path, clk)
    w.beat(step=5, phase="train")
    assert m.check() == fl.LIVE
    w.beat(phase="barrier")
    for _ in range(8):               # way past the 10s stall budget
        clk.advance(3.0)
        w.beat()
        assert m.check() == fl.LIVE
    # released: train phase resumes, the stall clock rearms from here
    w.beat(step=6, phase="train")
    assert m.check() == fl.LIVE


def test_elastic_worker_applies_steady_plan(tmp_path):
    d = str(tmp_path / "fleet")
    writer = fl.HeartbeatWriter(fl.heartbeat_path(d, 0), incarnation=1,
                                clock=rz.FaultClock())
    applied = []
    ew = fl.ElasticWorker(d, 0, writer,
                          on_reshard=lambda r, w, at: applied.append(
                              (r, w, at)))
    ew.poll(1)  # no plan yet
    assert applied == []
    fl.write_shard_plan(d, fl.ShardPlan(
        version=1, phase=fl.PLAN_STEADY, world=3, ranks={0: 0, 1: 1, 2: 2},
        barrier_step=0))
    ew.poll(1)
    ew.poll(2)  # same version: applied exactly once
    assert applied == [(0, 3, 0)]
    assert ew.assignment == (0, 3)
    # a non-member (catching-up replacement) applies rank None
    fl.write_shard_plan(d, fl.ShardPlan(
        version=2, phase=fl.PLAN_STEADY, world=2, ranks={1: 0, 2: 1},
        barrier_step=4))
    ew.poll(3)
    assert applied[-1] == (None, 2, 4)
    hb = fl.read_heartbeat(fl.heartbeat_path(d, 0))
    assert hb.plan_version == 2 and hb.world == 2


def test_elastic_worker_holds_until_release(tmp_path):
    """A hold naming this worker pauses poll() — heartbeat phase
    ``barrier``, seq still ticking — until the release, whose sharding
    is then applied; a hold not naming it is ignored."""
    d = str(tmp_path / "fleet")
    clk = rz.FaultClock()
    writer = fl.HeartbeatWriter(fl.heartbeat_path(d, 0), incarnation=1,
                                clock=clk)
    writer.beat(step=3, phase="train")
    applied = []
    polls = {"n": 0}

    def sleep(s):
        clk.advance(s)
        polls["n"] += 1
        if polls["n"] == 3:  # release arrives while holding
            fl.write_shard_plan(d, fl.ShardPlan(
                version=3, phase=fl.PLAN_STEADY, world=1, ranks={0: 0},
                barrier_step=5))

    ew = fl.ElasticWorker(d, 0, writer, clock=clk, sleep=sleep,
                          on_reshard=lambda r, w, at: applied.append(
                              (r, w, at)))
    # a hold entered during an async save window (phase 'save') must
    # NOT re-instate 'save' after the release: the save's restore
    # thread refuses to clobber the barrier, so a re-instated 'save'
    # would stick forever and force every later death down the
    # mid-checkpoint gang-stop path
    writer.beat(phase="save")
    fl.write_shard_plan(d, fl.ShardPlan(
        version=2, phase=fl.PLAN_HOLD, world=2, ranks={0: 0, 1: 1},
        barrier_step=0, hold=(0,)))
    ew.poll(3)
    assert applied == [(0, 1, 5)]
    hb = fl.read_heartbeat(fl.heartbeat_path(d, 0))
    assert hb.phase == "train" and hb.plan_version == 3  # never "save"
    # a hold for OTHER workers does not pause us
    fl.write_shard_plan(d, fl.ShardPlan(
        version=4, phase=fl.PLAN_HOLD, world=1, ranks={0: 0},
        barrier_step=5, hold=(1,)))
    ew.poll(4)  # returns immediately
    assert applied == [(0, 1, 5)]


def test_elastic_worker_abandoned_hold_raises_transient(tmp_path):
    d = str(tmp_path / "fleet")
    clk = rz.FaultClock()
    writer = fl.HeartbeatWriter(fl.heartbeat_path(d, 0), incarnation=1,
                                clock=clk)
    ew = fl.ElasticWorker(d, 0, writer, clock=clk,
                          sleep=lambda s: clk.advance(s),
                          hold_timeout_s=5.0)
    fl.write_shard_plan(d, fl.ShardPlan(
        version=2, phase=fl.PLAN_HOLD, world=2, ranks={0: 0, 1: 1},
        barrier_step=0, hold=(0,)))
    with pytest.raises(OSError, match="hold abandoned"):
        ew.poll(3)
    assert rz.classify_failure(OSError("elastic hold abandoned")) \
        == rz.TRANSIENT


def _elastic_fleet(tmp_path, launch, clk, sc, *, n=3, **kw):
    kw.setdefault("elastic", True)
    kw.setdefault("min_workers", 2)
    kw.setdefault("rejoin_grace_s", 20.0)
    kw.setdefault("hold_timeout_s", 50.0)
    return _mk_fleet(tmp_path, launch, clk, sc, n=n, **kw)


def test_elastic_shrink_and_rejoin_scripted(tmp_path):
    """The full elastic state machine on scripted workers: death →
    hold → survivor barrier acks → shrink release at the max paused
    step → replacement launched, proves life → rejoin hold → release
    at N with the rank map restored — zero gang restarts, zero
    restart_recovery waste, the resize window booked as
    elastic_resize."""
    from distributed_tensorflow_tpu.obs import goodput

    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append((i, incarnation, p))
        return p

    fleet, rec, reg = _elastic_fleet(tmp_path, launch, clk, sc)
    writers = {}

    def w(i):
        if i not in writers:
            writers[i] = fl.HeartbeatWriter(
                fl.heartbeat_path(fleet_dir, i), incarnation=1, clock=clk)
        return writers[i]

    def ack(i, version, world, step, phase):
        w(i).note_plan(version, world)
        w(i).beat(step=step, phase=phase)

    def fin(i, launch_slot, step):
        w(i).beat(step=step, phase="done")
        launches[launch_slot][2].rc = 0

    # t1: gang live at v1; t2: worker 1 dies hard
    sc.at(1.0, lambda: [w(i).beat(step=2, phase="train") for i in (0, 1, 2)])
    sc.at(2.0, lambda: setattr(launches[1][2], "rc", 86))
    # survivors ack the hold (v2) at their paused steps 3 and 4
    sc.at(3.0, lambda: (ack(0, 2, 3, 3, "barrier"),
                        ack(2, 2, 3, 4, "barrier")))
    # release (v3) applied: members resume at world 2
    sc.at(4.0, lambda: (ack(0, 3, 2, 5, "train"), ack(2, 3, 2, 5, "train")))
    # the replacement (slot 3 in launches) restores and proves life
    def joiner_up():
        del writers[1]  # fleet removed the corpse's file; fresh writer
        jw = w(1)
        jw.note_restore(2, fallback=True)
        jw.beat(step=2, phase="train")
    sc.at(5.0, joiner_up)
    # members ack the rejoin hold (v4) at step 6
    sc.at(6.0, lambda: (ack(0, 4, 2, 6, "barrier"),
                        ack(2, 4, 2, 6, "barrier"), w(1).beat(step=3)))
    # rejoin release (v5): everyone at world 3
    sc.at(7.0, lambda: (ack(0, 5, 3, 7, "train"), ack(2, 5, 3, 7, "train"),
                        ack(1, 5, 3, 4, "train")))
    sc.at(8.0, lambda: (fin(0, 0, 8), fin(2, 2, 8), fin(1, 3, 8)))

    out = fleet.run()
    assert out == {"restarts": 0, "incarnation": 1, "resizes": 2}
    # four launches: the initial gang + one replacement, all incarnation 1
    assert [(i, inc) for i, inc, _ in launches] == [
        (0, 1), (1, 1), (2, 1), (1, 1)]
    plan = fl.read_shard_plan(fleet_dir)
    assert plan.version == 5 and plan.phase == fl.PLAN_STEADY
    assert plan.world == 3 and plan.ranks == {0: 0, 1: 1, 2: 2}
    assert plan.barrier_step == 6 and plan.fleet_size == 3
    assert fr.contains_in_order(rec.events(), [
        ("fleet_start", {"workers": 3}),
        ("fleet_worker_dead", {"worker": 1, "cause": rz.TRANSIENT}),
        ("fleet_launch", {"worker": 1, "rejoin": True}),
        ("fleet_shrink", {"worker": 1, "world": 2, "barrier": 4,
                          "cause": rz.TRANSIENT}),
        ("fleet_rejoin", {"worker": 1, "world": 3, "barrier": 6}),
        ("fleet_done", {"incarnation": 1}),
    ]), rec.events()
    # no gang stop, no gang restart anywhere in the timeline
    assert not fr.contains_in_order(rec.events(), ["fleet_gang_stop"])
    assert not fr.contains_in_order(rec.events(), ["fleet_restart"])
    assert reg.get(fl.FLEET_RESIZES_TOTAL, direction="shrink").value == 1
    assert reg.get(fl.FLEET_RESIZES_TOTAL, direction="rejoin").value == 1
    assert reg.get(fl.FLEET_SIZE).value == 3
    assert reg.get(fl.FLEET_WORKER_DEATHS_TOTAL).value == 1
    rr = reg.get(goodput.WASTED_SECONDS, cause=goodput.WASTE_RESTART_RECOVERY)
    assert rr is None or rr.value == 0.0
    resize_waste = reg.get(goodput.WASTED_SECONDS,
                           cause=goodput.WASTE_ELASTIC_RESIZE)
    assert resize_waste is not None and resize_waste.value > 0


def test_elastic_waste_drops_10x_vs_gang_restart(tmp_path):
    """The goodput acceptance, scripted on the injected clock: the same
    single-death schedule costs the gang-restart baseline its whole
    outage window (stop → backoff → relaunch → restore → live) in
    restart_recovery, while the elastic path books zero there — well
    past the 10x bar."""
    from distributed_tensorflow_tpu.obs import goodput

    # -- baseline: elastic OFF, relaunch takes 13 simulated seconds ----
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append(p)
        if incarnation == 2:
            # the relaunched worker needs real (simulated) time for
            # spawn + imports + restore before it proves life
            sc.at(15.0, lambda: _beat(fleet_dir, i, 2, clk, step=8,
                                      phase="done", restore=2))
            sc.at(15.0, lambda: setattr(p, "rc", 0))
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=2)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=2))
    sc.at(1.0, lambda: _beat(fleet_dir, 1, 1, clk, step=2))
    sc.at(2.0, lambda: setattr(launches[1], "rc", 86))
    fleet.run()
    baseline = reg.get(goodput.WASTED_SECONDS,
                       cause=goodput.WASTE_RESTART_RECOVERY)
    assert baseline is not None and baseline.value >= 10.0

    # -- elastic: same death schedule, survivors never stop ------------
    clk2 = rz.FaultClock()
    sc2 = Scenario(clk2)
    fleet_dir2 = str(tmp_path / "fleet2")
    launches2 = []

    def launch2(i, incarnation):
        p = FakeProc()
        launches2.append((i, p))
        return p

    fleet2, rec2, reg2 = _elastic_fleet(
        tmp_path / "e", launch2, clk2, sc2, n=2, min_workers=1)
    fleet_dir2 = fleet2.workdir
    writers = {}

    def w(i):
        if i not in writers:
            writers[i] = fl.HeartbeatWriter(
                fl.heartbeat_path(fleet_dir2, i), incarnation=1, clock=clk2)
        return writers[i]

    sc2.at(1.0, lambda: [w(i).beat(step=2, phase="train") for i in (0, 1)])
    sc2.at(2.0, lambda: setattr(launches2[1][1], "rc", 86))
    def hold_ack():
        w(0).note_plan(2, 2)
        w(0).beat(step=3, phase="barrier")
    sc2.at(3.0, hold_ack)
    def release_ack():
        w(0).note_plan(3, 1)
        w(0).beat(step=4, phase="train")
    sc2.at(4.0, release_ack)
    # the member keeps TRAINING (and beating) through the whole window
    # the baseline spent relaunching — that is the entire point
    for t, s in ((6.0, 5), (8.0, 6), (10.0, 7), (12.0, 8), (14.0, 9)):
        sc2.at(t, lambda s=s: w(0).beat(step=s, phase="train"))
    def joiner_up():
        del writers[1]
        jw = w(1)
        jw.beat(step=2, phase="train")
    sc2.at(15.0, joiner_up)  # replacement takes just as long to come up
    def rejoin_acks():
        w(0).note_plan(4, 1)
        w(0).beat(step=9, phase="barrier")
    sc2.at(16.0, rejoin_acks)
    def rejoin_apply():
        w(0).note_plan(5, 2)
        w(0).beat(step=10, phase="train")
        w(1).note_plan(5, 2)
        w(1).beat(step=3, phase="train")
    sc2.at(17.0, rejoin_apply)
    def fins():
        w(0).beat(step=12, phase="done")
        launches2[0][1].rc = 0
        w(1).beat(step=12, phase="done")
        launches2[2][1].rc = 0
    sc2.at(18.0, fins)
    out = fleet2.run()
    assert out["restarts"] == 0 and out["resizes"] == 2
    rr = reg2.get(goodput.WASTED_SECONDS,
                  cause=goodput.WASTE_RESTART_RECOVERY)
    elastic_rr = rr.value if rr is not None else 0.0
    # the acceptance bar: >= 10x drop for the same death schedule
    assert elastic_rr * 10 <= baseline.value
    # while the survivors' only cost is the barrier window, booked
    # under the dedicated cause
    assert reg2.get(goodput.WASTED_SECONDS,
                    cause=goodput.WASTE_ELASTIC_RESIZE).value > 0


def test_outage_window_spans_chained_gang_restarts(tmp_path):
    """A relaunched worker dying again before the gang confirms live
    must not restart the outage clock: restart_recovery spans the FIRST
    gang stop to the first gang that actually comes live."""
    from distributed_tensorflow_tpu.obs import goodput

    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")

    def launch(i, incarnation):
        p = FakeProc()
        if incarnation == 2:
            # dies during restore, before ever beating
            sc.at(5.0, lambda: setattr(p, "rc", 86))
        if incarnation == 3:
            sc.at(10.0, lambda: _beat(fleet_dir, i, 3, clk, step=8,
                                      phase="done", restore=0))
            sc.at(10.0, lambda: setattr(p, "rc", 0))
        if incarnation == 1:
            sc.at(2.0, lambda: setattr(p, "rc", 86))
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1,
                                max_restarts=3)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=2))
    out = fleet.run()
    assert out["restarts"] == 2
    rr = reg.get(goodput.WASTED_SECONDS,
                 cause=goodput.WASTE_RESTART_RECOVERY)
    # first death at t=2, gang live at t=10: the full ~8s window is
    # booked, not just the second restart's ~5s tail
    assert rr is not None and rr.value >= 7.0, rr and rr.value


def test_death_during_pending_gang_restart_is_not_absorbed(tmp_path):
    """A worker dying while a gang restart is still CONFIRMING must take
    another gang pass, never an elastic shrink: the relaunched members
    may not have read their restore ceiling yet, and a hold would name
    workers still in build/restore."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")

    def launch(i, incarnation):
        p = FakeProc()
        if incarnation == 1 and i == 1:
            # mid-checkpoint death: forces the GANG path first
            def die_saving():
                _beat(fleet_dir, 1, 1, clk, step=2, phase="save")
                p.rc = 86
            sc.at(2.0, die_saving)
        if incarnation == 2:
            if i == 1:
                # dies again BEFORE the restarted gang confirms live
                sc.at(4.0, lambda: setattr(p, "rc", 86))
            else:
                sc.at(3.0, lambda i=i: _beat(fleet_dir, i, 2, clk, step=2,
                                             phase="train", restore=0))
        if incarnation == 3:
            sc.at(8.0, lambda i=i: _beat(fleet_dir, i, 3, clk, step=8,
                                         phase="done", restore=0))
            sc.at(8.0, lambda: setattr(p, "rc", 0))
        return p

    fleet, rec, reg = _elastic_fleet(tmp_path, launch, clk, sc, n=3,
                                     max_restarts=3)
    for i in (0, 1, 2):
        sc.at(1.0, lambda i=i: _beat(fleet_dir, i, 1, clk, step=2))
    out = fleet.run()
    assert out["restarts"] == 2 and out["resizes"] == 0
    assert not fr.contains_in_order(rec.events(), ["fleet_shrink"])
    assert not fr.contains_in_order(rec.events(), ["fleet_launch",
                                                   "fleet_shrink"])


def test_exhausted_chain_still_books_recovery_waste(tmp_path):
    """A chain that dies before any gang confirms live (FleetExhausted)
    must still book the outage into restart_recovery — the ledger a
    dead run's postmortem is read against."""
    from distributed_tensorflow_tpu.obs import goodput

    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")

    def launch(i, incarnation):
        p = FakeProc()
        if incarnation == 1:
            sc.at(2.0, lambda: setattr(p, "rc", 86))
        # incarnation 2 never beats: dead at launch grace, budget spent
        return p

    fleet, rec, reg = _mk_fleet(tmp_path, launch, clk, sc, n=1,
                                max_restarts=1)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=2))
    with pytest.raises(fl.FleetExhausted):
        fleet.run()
    rr = reg.get(goodput.WASTED_SECONDS,
                 cause=goodput.WASTE_RESTART_RECOVERY)
    # first death at t=2, exhaustion at the relaunch's ~20s launch
    # grace: the whole dead window is booked
    assert rr is not None and rr.value >= 18.0, rr and rr.value


def test_elastic_falls_back_below_min_workers(tmp_path):
    """A death that would shrink past min_workers takes the gang-stop
    path (with the restore ceiling machinery), never a shrink."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append(p)
        if incarnation == 2:
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=0)
            p.rc = 0
        return p

    fleet, rec, reg = _elastic_fleet(tmp_path, launch, clk, sc, n=2,
                                     min_workers=2)
    sc.at(1.0, lambda: _beat(fleet_dir, 0, 1, clk, step=2))
    sc.at(1.0, lambda: _beat(fleet_dir, 1, 1, clk, step=2))
    sc.at(2.0, lambda: setattr(launches[1], "rc", 86))
    out = fleet.run()
    assert out["restarts"] == 1 and out["resizes"] == 0
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {}), ("fleet_gang_stop", {}),
        ("fleet_restart", {}), ("fleet_done", {})])
    assert not fr.contains_in_order(rec.events(), ["fleet_shrink"])


def test_elastic_falls_back_when_death_lands_mid_checkpoint(tmp_path):
    """A worker whose last heartbeat phase is ``save`` died inside a
    checkpoint write: its newest step dir may be torn, so the fleet
    gang-stops (manifest-verified common ceiling) instead of shrinking
    around unverified state."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append(p)
        if incarnation == 2:
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=0)
            p.rc = 0
        return p

    fleet, rec, reg = _elastic_fleet(tmp_path, launch, clk, sc, n=3)
    for i in (0, 1, 2):
        sc.at(1.0, lambda i=i: _beat(fleet_dir, i, 1, clk, step=2))
    def die_saving():
        _beat(fleet_dir, 1, 1, clk, step=4, phase="save")
        launches[1].rc = 86
    sc.at(2.0, die_saving)
    out = fleet.run()
    assert out["restarts"] == 1 and out["resizes"] == 0
    assert not fr.contains_in_order(rec.events(), ["fleet_shrink"])
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {"worker": 1}), ("fleet_gang_stop", {})])


def test_elastic_hold_timeout_falls_back_to_gang_restart(tmp_path):
    """Survivors that never reach the barrier (hung in a long step, or
    the plan file is unreadable to them) must not hold the fleet
    hostage: past hold_timeout_s the resize is abandoned for the
    gang-stop path."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append(p)
        if incarnation == 2:
            _beat(fleet_dir, i, 2, clk, step=8, phase="done", restore=0)
            p.rc = 0
        return p

    fleet, rec, reg = _elastic_fleet(tmp_path, launch, clk, sc, n=3,
                                     hold_timeout_s=6.0)
    # survivors beat (stay live) but never ack the hold
    for t in range(1, 12):
        for i in (0, 2):
            sc.at(float(t), lambda i=i, t=t: _beat(
                fleet_dir, i, 1, clk, step=2 + t))
    sc.at(2.0, lambda: setattr(launches[1], "rc", 86))
    out = fleet.run()
    assert out["restarts"] == 1 and out["resizes"] == 0
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {"worker": 1}),
        ("fleet_gang_stop", {"cause": rz.TRANSIENT}),
        ("fleet_restart", {}), ("fleet_done", {})])


def test_elastic_dead_replacement_is_relaunched(tmp_path):
    """A replacement that dies while catching up is relaunched (bounded
    by the restart budget) without disturbing the members."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    fleet_dir = str(tmp_path / "fleet")
    launches = []

    def launch(i, incarnation):
        p = FakeProc()
        launches.append((i, p))
        return p

    fleet, rec, reg = _elastic_fleet(tmp_path, launch, clk, sc, n=2,
                                     min_workers=1)
    writers = {}

    def w(i):
        if i not in writers:
            writers[i] = fl.HeartbeatWriter(
                fl.heartbeat_path(fleet_dir, i), incarnation=1, clock=clk)
        return writers[i]

    sc.at(1.0, lambda: [w(i).beat(step=2, phase="train") for i in (0, 1)])
    sc.at(2.0, lambda: setattr(launches[1][1], "rc", 86))
    def hold_ack():
        w(0).note_plan(2, 2)
        w(0).beat(step=3, phase="barrier")
    sc.at(3.0, hold_ack)
    def release_ack():
        w(0).note_plan(3, 1)
        w(0).beat(step=4, phase="train")
    sc.at(4.0, release_ack)
    # first replacement dies before ever beating
    sc.at(5.0, lambda: setattr(launches[2][1], "rc", 86))
    # second replacement comes up and finishes with the member
    def joiner2_up():
        del writers[1]
        w(1).beat(step=2, phase="train")
    sc.at(7.0, joiner2_up)
    def rejoin_flow():
        w(0).note_plan(4, 1)
        w(0).beat(step=6, phase="barrier")
    sc.at(8.0, rejoin_flow)
    def rejoin_apply():
        w(0).note_plan(5, 2)
        w(0).beat(step=7, phase="train")
        w(1).note_plan(5, 2)
        w(1).beat(step=3, phase="train")
    sc.at(9.0, rejoin_apply)
    def fins():
        w(0).beat(step=8, phase="done")
        launches[0][1].rc = 0
        w(1).beat(step=8, phase="done")
        launches[3][1].rc = 0
    sc.at(10.0, fins)
    out = fleet.run()
    assert out == {"restarts": 0, "incarnation": 1, "resizes": 2}
    # two deaths observed (member + replacement), two relaunches of slot 1
    assert reg.get(fl.FLEET_WORKER_DEATHS_TOTAL).value == 2
    assert [i for i, _ in launches] == [0, 1, 1, 1]


# ---------------------------------------------------------------------------
# Subprocess E2E: missed-heartbeat death → gang restart → bit-identity
# ---------------------------------------------------------------------------


def _env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the worker sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_straight(workdir, out, timeout=240):
    p = subprocess.run(
        [sys.executable, WORKER, str(workdir), "--steps", "8", "--out", out],
        capture_output=True, text=True, timeout=timeout, env=_env(),
    )
    assert p.returncode == 0, f"rc={p.returncode}:\n{p.stdout}\n{p.stderr}"
    assert "CHAOS-DONE step=8" in p.stdout, p.stdout


def test_fleet_e2e_gang_restart_bit_identical(tmp_path):
    """THE fleet acceptance gate: worker 1 hangs mid-run (heartbeats
    stop, process alive), the FleetSupervisor detects the death by
    missed heartbeats, gang-restarts with a bumped incarnation from the
    latest common valid checkpoint, and every worker's final params are
    bit-identical to an uninterrupted same-seed run."""
    straight_out = str(tmp_path / "straight.npz")
    _run_straight(tmp_path / "straight_ckpt", straight_out)

    fleet_dir = str(tmp_path / "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    ckpt_dirs = [str(tmp_path / f"ckpt{i}") for i in range(2)]
    outs = [str(tmp_path / f"out{i}.npz") for i in range(2)]

    def launch(i, incarnation):
        args = [sys.executable, WORKER, ckpt_dirs[i], "--fleet",
                "--fleet-dir", fleet_dir, "--worker-index", str(i),
                "--steps", "8", "--out", outs[i]]
        if i == 1:
            args += ["--hang-at", "3"]  # gated to incarnation 1
        log = open(os.path.join(fleet_dir, f"worker{i}-inc{incarnation}.log"),
                   "w")
        try:
            return subprocess.Popen(args, stdout=log,
                                    stderr=subprocess.STDOUT, env=_env())
        finally:
            log.close()

    rec = FlightRecorder()
    reg = Registry()
    fleet = fl.FleetSupervisor(
        launch, 2, fleet_dir,
        fl.FleetConfig(max_restarts=2,
                       backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0),
                       poll_s=0.2, heartbeat_timeout_s=20.0,
                       stall_timeout_s=600.0, launch_grace_s=180.0,
                       term_grace_s=5.0),
        ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec)
    out = fleet.run()

    assert out["restarts"] == 1, _logs(fleet_dir)
    assert out["incarnation"] == 2
    assert fl.read_incarnation(fleet_dir) == 2
    # the hung worker had saved step 2 (cadence 2, hang at 3): the
    # common valid step the gang restarted from must honor it
    assert fl.read_restore_step(fleet_dir) == 2
    assert fr.contains_in_order(rec.events(), [
        ("fleet_worker_dead", {"worker": 1, "cause": rz.TRANSIENT}),
        ("fleet_gang_stop", {"cause": rz.TRANSIENT}),
        ("ckpt_restore", {"fallback": True, "relayed": True}),
        ("fleet_restart", {"restart": 1, "incarnation": 2}),
        ("fleet_done", {}),
    ]), rec.events()
    assert reg.get(fl.FLEET_WORKER_DEATHS_TOTAL).value == 1

    a = np.load(straight_out)
    for o in outs:
        b = np.load(o)
        assert sorted(a.files) == sorted(b.files) and a.files
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k])  # BIT-identical


def _logs(fleet_dir):
    chunks = []
    for n in sorted(os.listdir(fleet_dir)):
        if n.endswith(".log"):
            with open(os.path.join(fleet_dir, n)) as f:
                chunks.append(f"--- {n} ---\n{f.read()}")
    return "\n".join(chunks)


def _run_elastic_fleet(tmp_path, tag, steps=8):
    """One real-subprocess elastic round: 3 chaos workers, worker 1
    hard-dies at step 3 on its FIRST launch only (the launcher owns the
    death schedule), the fleet shrinks to 2 and absorbs the relaunched
    replacement back at a barrier."""
    fleet_dir = str(tmp_path / f"fleet_{tag}")
    os.makedirs(fleet_dir, exist_ok=True)
    ckpt_dirs = [str(tmp_path / f"ckpt_{tag}_{i}") for i in range(3)]
    outs = [str(tmp_path / f"out_{tag}_{i}.npz") for i in range(3)]
    launched: dict[int, int] = {}

    def launch(i, incarnation):
        n = launched.get(i, 0)
        launched[i] = n + 1
        args = [sys.executable, WORKER, ckpt_dirs[i], "--fleet", "--elastic",
                "--fleet-dir", fleet_dir, "--worker-index", str(i),
                "--steps", str(steps), "--out", outs[i],
                "--step-sleep", "0.25"]
        if i == 1 and n == 0:
            args += ["--die-at", "3"]  # the scripted death schedule
        log = open(os.path.join(fleet_dir, f"worker{i}-n{n}.log"), "w")
        try:
            return subprocess.Popen(args, stdout=log,
                                    stderr=subprocess.STDOUT, env=_env())
        finally:
            log.close()

    rec = FlightRecorder()
    reg = Registry()
    fleet = fl.FleetSupervisor(
        launch, 3, fleet_dir,
        fl.FleetConfig(max_restarts=2, elastic=True, min_workers=2,
                       backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0),
                       poll_s=0.2, heartbeat_timeout_s=20.0,
                       stall_timeout_s=600.0, launch_grace_s=180.0,
                       rejoin_grace_s=180.0, hold_timeout_s=120.0,
                       term_grace_s=5.0),
        ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec)
    out = fleet.run()
    return out, rec, reg, outs, fleet_dir


def test_fleet_e2e_elastic_shrink_rejoin_bit_identical(tmp_path):
    """THE elastic acceptance gate (real subprocesses): a single
    scripted death with a replacement available is absorbed WITHOUT a
    gang restart — the survivors never stop, `restart_recovery` stays
    zero — and the whole trajectory is deterministic: two same-seed
    runs with the same scripted death schedule, and the uninterrupted
    straight run, all finish with BIT-identical params."""
    from distributed_tensorflow_tpu.obs import goodput

    straight_out = str(tmp_path / "straight.npz")
    _run_straight(tmp_path / "straight_ckpt", straight_out)

    results = [_run_elastic_fleet(tmp_path, tag) for tag in ("a", "b")]
    a = np.load(straight_out)
    for out, rec, reg, outs, fleet_dir in results:
        assert out["restarts"] == 0, _logs(fleet_dir)
        assert out["resizes"] == 2, _logs(fleet_dir)
        assert out["incarnation"] == 1  # never bumped: nobody gang-stopped
        # the causal story: death -> shrink -> replacement -> rejoin ->
        # done, with no gang stop/restart anywhere
        assert fr.contains_in_order(rec.events(), [
            ("fleet_worker_dead", {"worker": 1, "cause": rz.TRANSIENT}),
            ("fleet_launch", {"worker": 1, "rejoin": True}),
            ("fleet_shrink", {"worker": 1, "world": 2}),
            ("fleet_rejoin", {"worker": 1, "world": 3}),
            ("fleet_done", {}),
        ]), rec.events()
        assert not fr.contains_in_order(rec.events(), ["fleet_gang_stop"])
        # survivors never stopped: zero seconds booked to the gang
        # outage bucket (the elastic acceptance bar is a >= 10x drop;
        # the realized drop is total)
        rr = reg.get(goodput.WASTED_SECONDS,
                     cause=goodput.WASTE_RESTART_RECOVERY)
        assert rr is None or rr.value == 0.0
        plan = fl.read_shard_plan(fleet_dir)
        assert plan.world == 3 and plan.phase == fl.PLAN_STEADY
        # bit-identity vs the uninterrupted straight run, every worker
        for o in outs:
            b = np.load(o)
            assert sorted(a.files) == sorted(b.files) and a.files, \
                _logs(fleet_dir)
            for k in a.files:
                np.testing.assert_array_equal(a[k], b[k])
    # and across the two same-seed, same-schedule elastic runs
    for o1, o2 in zip(results[0][3], results[1][3]):
        b1, b2 = np.load(o1), np.load(o2)
        for k in b1.files:
            np.testing.assert_array_equal(b1[k], b2[k])
