"""serve/ subsystem tests: KV-cached decode parity against the uncached
forward (the numerics acceptance gate), scheduler invariants under a
randomized request stream, cache sharding specs, and sampling."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu import serve
from distributed_tensorflow_tpu.models import transformer as tfm
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.serve import scheduler as sched_lib


def tiny_decoder(**kw):
    base = dict(
        vocab_size=128, max_len=96, num_layers=2, d_model=32, num_heads=4,
        d_ff=64, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )
    base.update(kw)
    return tfm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def decoder():
    cfg = tiny_decoder()
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 8)(jax.random.PRNGKey(0))
    return cfg, model, params


# ---------------------------------------------------------------------------
# Numerics: cached decode == uncached forward
# ---------------------------------------------------------------------------


def test_cached_decode_matches_uncached_forward(decoder):
    """Acceptance gate: per-step cached logits match the uncached
    full-context forward to rtol 1e-4 AND the greedy token sequences are
    identical for >= 64 steps."""
    cfg, model, params = decoder
    prompt = [5, 17, 3, 99, 42, 7, 11]
    P_len, steps = len(prompt), 64

    cache = serve.init_cache(cfg, 1, dtype="float32")
    logits, cache = serve.prefill(
        model, params, cache, 0, jnp.asarray(prompt, jnp.int32), P_len
    )
    step = serve.jit_decode_step(model)
    cached_logits, toks = [logits], [int(jnp.argmax(logits))]
    written = P_len
    for _ in range(steps - 1):
        logits, cache = step(
            params, cache,
            jnp.asarray([toks[-1]], jnp.int32),
            jnp.asarray([written], jnp.int32),
        )
        written += 1
        cached_logits.append(logits[0])
        toks.append(int(jnp.argmax(logits[0])))

    # one uncached forward over prompt + all-but-last generated token:
    # position P-1+i predicts token i
    seq = jnp.asarray([prompt + toks[:-1]], jnp.int32)
    full = model.apply({"params": params}, seq)[0, P_len - 1:]
    np.testing.assert_allclose(
        np.stack([np.asarray(l) for l in cached_logits]), np.asarray(full),
        rtol=1e-4, atol=1e-5,
    )
    assert toks == [int(t) for t in jnp.argmax(full, -1)]


def test_prefill_bucket_invariance(decoder):
    """Padding the prompt to a larger bucket must not change the next-
    token logits or the written cache rows."""
    cfg, model, params = decoder
    prompt = jnp.asarray([9, 4, 77, 2, 60], jnp.int32)
    P_len = 5
    outs = []
    for bucket in (8, 16, 32):
        cache = serve.init_cache(cfg, 1, dtype="float32")
        toks = jnp.zeros(bucket, jnp.int32).at[:P_len].set(prompt)
        logits, cache = serve.prefill(
            model, params, cache, 0, toks, P_len
        )
        outs.append((np.asarray(logits), np.asarray(cache.k[:, :, :, :P_len])))
    for logits, krows in outs[1:]:
        np.testing.assert_allclose(logits, outs[0][0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(krows, outs[0][1], rtol=1e-5, atol=1e-6)


def test_engine_request_isolation(decoder):
    """Continuous batching must not leak state across slots: each
    request's greedy completion equals its solo-engine completion, even
    when requests queue and reuse slots."""
    cfg, _, params = decoder
    prompts = [[5, 17, 3], [88, 12, 61, 40, 2], [7], [33, 33, 9, 1]]

    solo = []
    for p in prompts:
        eng = serve.ServeEngine(cfg, params, num_slots=1)
        solo.append(list(eng.stream(p, max_new_tokens=12)))

    eng = serve.ServeEngine(cfg, params, num_slots=2)  # forces queueing
    uids = [eng.submit(p, max_new_tokens=12) for p in prompts]
    done = eng.run()
    assert sorted(done) == sorted(uids)
    for uid, want in zip(uids, solo):
        assert done[uid].generated == want
        assert done[uid].finish_reason == sched_lib.FINISH_MAX_NEW


def test_engine_eos_and_max_len_eviction(decoder):
    """EOS stops a request the step it is sampled; a prompt near the
    cache budget finishes with the max_len reason and never writes out
    of bounds."""
    cfg, _, params = decoder
    # find a token the greedy stream actually emits, then replay with it
    # as the EOS id: the request must stop at its first occurrence
    probe = serve.ServeEngine(cfg, params, num_slots=1)
    toks = list(probe.stream([5, 17, 3], max_new_tokens=10))
    eos = toks[4]
    eng = serve.ServeEngine(cfg, params, num_slots=1)
    uid = eng.submit([5, 17, 3], max_new_tokens=50, eos_id=eos)
    done = eng.run()
    assert done[uid].finish_reason == sched_lib.FINISH_EOS
    assert done[uid].generated[-1] == eos
    assert eos not in done[uid].generated[:-1]

    long_prompt = list(range(1, cfg.max_len - 1))  # P = max_len - 2
    eng = serve.ServeEngine(cfg, params, num_slots=1)
    uid = eng.submit(long_prompt, max_new_tokens=50)
    done = eng.run()
    assert done[uid].finish_reason == sched_lib.FINISH_MAX_LEN
    # g_max: writing token g needs position P + g - 1 <= max_len - 1
    assert len(done[uid].generated) == cfg.max_len - len(long_prompt) + 1


# ---------------------------------------------------------------------------
# Scheduler invariants (no model, no device)
# ---------------------------------------------------------------------------


def test_scheduler_invariants_random_stream():
    """Randomized request stream, fixed seed: no slot leaks, FIFO
    admission, correct eviction reasons, full drain."""
    rng = random.Random(1234)
    num_slots, max_len = 4, 32
    s = sched_lib.Scheduler(num_slots, max_len)
    n_reqs = 40
    eos_id = 7
    uids = []
    for _ in range(n_reqs):
        plen = rng.randint(1, max_len)
        uids.append(s.submit(
            [rng.randrange(100) for _ in range(plen)],
            max_new_tokens=rng.randint(1, 12),
            eos_id=eos_id if rng.random() < 0.5 else None,
        ))
    assert uids == sorted(uids)  # uids are issued in submission order

    admitted_order = []
    for step in range(10_000):
        if not s.has_work:
            break
        placed = s.admit()
        admitted_order.extend(r.uid for _, r in placed)
        # FIFO + full occupancy: with work still queued, no slot is free
        if s.queue:
            assert s.occupancy == 1.0
        # no slot double-booking
        live = [r.uid for r in s.slots if r is not None]
        assert len(live) == len(set(live))
        for slot in s.active_slots():
            s.append_token(slot, rng.randrange(100))
    else:
        pytest.fail("scheduler did not drain")

    assert admitted_order == uids  # FIFO fairness
    assert not s.queue and s.active_slots() == []  # no slot leaks
    assert len(s.finished) == n_reqs
    assert sorted(s.finished) == uids  # keyed by uid, every request lands
    for r in s.finished.values():
        g, p = len(r.generated), len(r.prompt)
        assert 1 <= g <= r.max_new_tokens
        if r.finish_reason == sched_lib.FINISH_EOS:
            assert r.eos_id is not None and r.generated[-1] == r.eos_id
        elif r.finish_reason == sched_lib.FINISH_MAX_NEW:
            assert g == r.max_new_tokens
        elif r.finish_reason == sched_lib.FINISH_MAX_LEN:
            assert p + g > max_len and p + (g - 1) <= max_len
        else:
            pytest.fail(f"unknown finish reason {r.finish_reason}")


def test_scheduler_rejects_invalid():
    s = sched_lib.Scheduler(2, 16)
    with pytest.raises(ValueError):
        s.submit([])
    with pytest.raises(ValueError):
        s.submit(list(range(17)))  # prompt > max_len
    with pytest.raises(ValueError):
        s.submit([1], max_new_tokens=0)
    with pytest.raises(ValueError):
        s.append_token(0, 1)  # empty slot


# ---------------------------------------------------------------------------
# Cache sharding + sampling
# ---------------------------------------------------------------------------


def test_cache_specs_follow_sharding_rules():
    """The cache pytree shards by the same logical rules as the model:
    heads over `model`, slots over the batch axes (docs/serving.md)."""
    spec = serve.cache_specs()
    assert spec.k == P(None, ("data", "fsdp"), "model", None, None)
    assert spec.v == spec.k

    cfg = tiny_decoder()
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, model=2))
    cache = serve.init_cache(cfg, num_slots=4, dtype="float32")
    sharded = serve.shard_cache(cache, mesh)
    assert sharded.k.sharding == NamedSharding(mesh, spec.k)
    # heads=4 over model=2, slots=4 over data*fsdp=4
    assert sharded.k.addressable_shards[0].data.shape == (
        cfg.num_layers, 1, 2, cfg.max_len, cfg.head_dim
    )


def test_sampling_modes():
    logits = jnp.asarray([[0.0, 3.0, 1.0, -2.0], [5.0, 0.1, 0.2, 0.3]])
    greedy = serve.sample(logits)
    assert greedy.tolist() == [1, 0] and greedy.dtype == jnp.int32

    key = jax.random.PRNGKey(0)
    for i in range(20):
        t = serve.sample(
            logits, jax.random.fold_in(key, i), temperature=0.7, top_k=2
        )
        assert t[0] in (1, 2) and t[1] in (0, 3)  # top-2 of each row

    with pytest.raises(ValueError):
        serve.sample(logits, None, temperature=1.0)
