"""serve/ subsystem tests: KV-cached decode parity against the uncached
forward (the numerics acceptance gate), scheduler invariants under a
randomized request stream, cache sharding specs, and sampling."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu import serve
from distributed_tensorflow_tpu.models import transformer as tfm
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.serve import scheduler as sched_lib


def tiny_decoder(**kw):
    base = dict(
        vocab_size=128, max_len=96, num_layers=2, d_model=32, num_heads=4,
        d_ff=64, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )
    base.update(kw)
    return tfm.TransformerConfig(**base)


@pytest.fixture(scope="module")
def decoder():
    cfg = tiny_decoder()
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 8)(jax.random.PRNGKey(0))
    return cfg, model, params


# ---------------------------------------------------------------------------
# Numerics: cached decode == uncached forward
# ---------------------------------------------------------------------------


def test_cached_decode_matches_uncached_forward(decoder):
    """Acceptance gate: per-step cached logits match the uncached
    full-context forward to rtol 1e-4 AND the greedy token sequences are
    identical for >= 64 steps."""
    cfg, model, params = decoder
    prompt = [5, 17, 3, 99, 42, 7, 11]
    P_len, steps = len(prompt), 64

    cache = serve.init_cache(cfg, 1, dtype="float32")
    logits, cache = serve.prefill(
        model, params, cache, 0, jnp.asarray(prompt, jnp.int32), P_len
    )
    step = serve.jit_decode_step(model)
    cached_logits, toks = [logits], [int(jnp.argmax(logits))]
    written = P_len
    for _ in range(steps - 1):
        logits, cache = step(
            params, cache,
            jnp.asarray([toks[-1]], jnp.int32),
            jnp.asarray([written], jnp.int32),
        )
        written += 1
        cached_logits.append(logits[0])
        toks.append(int(jnp.argmax(logits[0])))

    # one uncached forward over prompt + all-but-last generated token:
    # position P-1+i predicts token i
    seq = jnp.asarray([prompt + toks[:-1]], jnp.int32)
    full = model.apply({"params": params}, seq)[0, P_len - 1:]
    np.testing.assert_allclose(
        np.stack([np.asarray(l) for l in cached_logits]), np.asarray(full),
        rtol=1e-4, atol=1e-5,
    )
    assert toks == [int(t) for t in jnp.argmax(full, -1)]


def test_prefill_bucket_invariance(decoder):
    """Padding the prompt to a larger bucket must not change the next-
    token logits or the written cache rows."""
    cfg, model, params = decoder
    prompt = jnp.asarray([9, 4, 77, 2, 60], jnp.int32)
    P_len = 5
    outs = []
    for bucket in (8, 16, 32):
        cache = serve.init_cache(cfg, 1, dtype="float32")
        toks = jnp.zeros(bucket, jnp.int32).at[:P_len].set(prompt)
        logits, cache = serve.prefill(
            model, params, cache, 0, toks, P_len
        )
        outs.append((np.asarray(logits), np.asarray(cache.k[:, :, :, :P_len])))
    for logits, krows in outs[1:]:
        np.testing.assert_allclose(logits, outs[0][0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(krows, outs[0][1], rtol=1e-5, atol=1e-6)


def test_engine_request_isolation(decoder):
    """Continuous batching must not leak state across slots: each
    request's greedy completion equals its solo-engine completion, even
    when requests queue and reuse slots."""
    cfg, _, params = decoder
    prompts = [[5, 17, 3], [88, 12, 61, 40, 2], [7], [33, 33, 9, 1]]

    solo = []
    for p in prompts:
        eng = serve.ServeEngine(cfg, params, num_slots=1)
        solo.append(list(eng.stream(p, max_new_tokens=12)))

    eng = serve.ServeEngine(cfg, params, num_slots=2)  # forces queueing
    uids = [eng.submit(p, max_new_tokens=12) for p in prompts]
    done = eng.run()
    assert sorted(done) == sorted(uids)
    for uid, want in zip(uids, solo):
        assert done[uid].generated == want
        assert done[uid].finish_reason == sched_lib.FINISH_MAX_NEW


def test_engine_eos_and_max_len_eviction(decoder):
    """EOS stops a request the step it is sampled; a prompt near the
    cache budget finishes with the max_len reason and never writes out
    of bounds."""
    cfg, _, params = decoder
    # find a token the greedy stream actually emits, then replay with it
    # as the EOS id: the request must stop at its first occurrence
    probe = serve.ServeEngine(cfg, params, num_slots=1)
    toks = list(probe.stream([5, 17, 3], max_new_tokens=10))
    eos = toks[4]
    eng = serve.ServeEngine(cfg, params, num_slots=1)
    uid = eng.submit([5, 17, 3], max_new_tokens=50, eos_id=eos)
    done = eng.run()
    assert done[uid].finish_reason == sched_lib.FINISH_EOS
    assert done[uid].generated[-1] == eos
    assert eos not in done[uid].generated[:-1]

    long_prompt = list(range(1, cfg.max_len - 1))  # P = max_len - 2
    eng = serve.ServeEngine(cfg, params, num_slots=1)
    uid = eng.submit(long_prompt, max_new_tokens=50)
    done = eng.run()
    assert done[uid].finish_reason == sched_lib.FINISH_MAX_LEN
    # g_max: writing token g needs position P + g - 1 <= max_len - 1
    assert len(done[uid].generated) == cfg.max_len - len(long_prompt) + 1


# ---------------------------------------------------------------------------
# Scheduler invariants (no model, no device)
# ---------------------------------------------------------------------------


def test_scheduler_invariants_random_stream():
    """Randomized request stream, fixed seed: no slot leaks, FIFO
    admission, correct eviction reasons, full drain."""
    rng = random.Random(1234)
    num_slots, max_len = 4, 32
    s = sched_lib.Scheduler(num_slots, max_len)
    n_reqs = 40
    eos_id = 7
    uids = []
    for _ in range(n_reqs):
        plen = rng.randint(1, max_len)
        uids.append(s.submit(
            [rng.randrange(100) for _ in range(plen)],
            max_new_tokens=rng.randint(1, 12),
            eos_id=eos_id if rng.random() < 0.5 else None,
        ))
    assert uids == sorted(uids)  # uids are issued in submission order

    admitted_order = []
    for step in range(10_000):
        if not s.has_work:
            break
        placed = s.admit()
        admitted_order.extend(r.uid for _, r in placed)
        # FIFO + full occupancy: with work still queued, no slot is free
        if s.queue:
            assert s.occupancy == 1.0
        # no slot double-booking
        live = [r.uid for r in s.slots if r is not None]
        assert len(live) == len(set(live))
        for slot in s.active_slots():
            s.append_token(slot, rng.randrange(100))
    else:
        pytest.fail("scheduler did not drain")

    assert admitted_order == uids  # FIFO fairness
    assert not s.queue and s.active_slots() == []  # no slot leaks
    assert len(s.finished) == n_reqs
    assert sorted(s.finished) == uids  # keyed by uid, every request lands
    for r in s.finished.values():
        g, p = len(r.generated), len(r.prompt)
        assert 1 <= g <= r.max_new_tokens
        if r.finish_reason == sched_lib.FINISH_EOS:
            assert r.eos_id is not None and r.generated[-1] == r.eos_id
        elif r.finish_reason == sched_lib.FINISH_MAX_NEW:
            assert g == r.max_new_tokens
        elif r.finish_reason == sched_lib.FINISH_MAX_LEN:
            assert p + g > max_len and p + (g - 1) <= max_len
        else:
            pytest.fail(f"unknown finish reason {r.finish_reason}")


def test_scheduler_rejects_invalid():
    s = sched_lib.Scheduler(2, 16)
    with pytest.raises(ValueError):
        s.submit([])
    with pytest.raises(ValueError):
        s.submit(list(range(17)))  # prompt > max_len
    with pytest.raises(ValueError):
        s.submit([1], max_new_tokens=0)
    with pytest.raises(ValueError):
        s.submit([1], deadline_s=0.0)
    with pytest.raises(ValueError):
        s.append_token(0, 1)  # empty slot
    with pytest.raises(ValueError):
        sched_lib.Scheduler(2, 16, max_queue=0)


# ---------------------------------------------------------------------------
# Admission control: backpressure, deadlines, cancellation, drain
# ---------------------------------------------------------------------------


def test_scheduler_backpressure_and_fifo_across_rejections():
    """QueueFull rejection must not perturb the FIFO order of accepted
    requests, and capacity freed by admission is immediately usable."""
    s = sched_lib.Scheduler(1, 16, max_queue=2)
    a = s.submit([1])
    b = s.submit([2])
    with pytest.raises(sched_lib.QueueFull):
        s.submit([3])  # rejected — never enters the line
    placed = s.admit()  # a takes the slot, queue has room again
    assert [r.uid for _, r in placed] == [a]
    c = s.submit([4])
    s.append_token(0, 9)  # a decodes one token, stays resident
    assert s.cancel(a) is not None  # free the slot

    placed = s.admit()
    assert [r.uid for _, r in placed] == [b]
    assert list(r.uid for r in s.queue) == [c]  # FIFO preserved: b before c


def test_scheduler_deadline_timeout_queued_and_resident():
    from distributed_tensorflow_tpu.resilience import FaultClock

    clk = FaultClock()
    s = sched_lib.Scheduler(1, 16, clock=clk)
    res = s.submit([1], max_new_tokens=8, deadline_s=5.0)
    qd = s.submit([2], deadline_s=1.0)
    nodeadline = s.submit([3])
    assert s.admit()[0][1].uid == res
    assert s.expire() == []  # nothing due yet
    clk.advance(2.0)  # past qd's deadline, not res's
    evicted = s.expire()
    assert [r.uid for r in evicted] == [qd]
    assert s.finished[qd].finish_reason == sched_lib.FINISH_TIMEOUT
    assert s.finished[qd].t_finish == 2.0 and s.finished[qd].t_admit is None
    clk.advance(4.0)  # now res (resident) is past its deadline
    evicted = s.expire()
    assert [r.uid for r in evicted] == [res]
    assert s.finished[res].finish_reason == sched_lib.FINISH_TIMEOUT
    assert s.slots == [None]  # slot freed for the no-deadline request
    assert s.admit()[0][1].uid == nodeadline


def test_scheduler_cancel_everywhere_idempotent():
    s = sched_lib.Scheduler(1, 16)
    a = s.submit([1], max_new_tokens=4)
    b = s.submit([2])
    s.admit()
    s.append_token(0, 7)  # a has one token in flight
    got = s.cancel(a)  # resident cancel frees the slot, keeps the token
    assert got is not None and got.finish_reason == sched_lib.FINISH_CANCELLED
    assert got.generated == [7] and s.slots == [None]
    got = s.cancel(b)  # queued cancel: never admitted
    assert got is not None and got.t_admit is None
    assert s.cancel(a) is None and s.cancel(b) is None  # idempotent
    assert s.cancel(12345) is None  # unknown uid
    assert not s.has_work and sorted(s.finished) == [a, b]


def test_scheduler_close_stops_admission_cancels_queue():
    s = sched_lib.Scheduler(1, 16)
    a = s.submit([1], max_new_tokens=2)
    b = s.submit([2])
    c = s.submit([3])
    s.admit()
    cancelled = s.close()
    assert [r.uid for r in cancelled] == [b, c]
    assert all(r.finish_reason == sched_lib.FINISH_CANCELLED for r in cancelled)
    with pytest.raises(sched_lib.SchedulerClosed):
        s.submit([4])
    assert s.close() == []  # idempotent
    # the resident request still decodes to completion
    s.append_token(0, 1)
    done = s.append_token(0, 2)
    assert done is not None and done.uid == a
    assert done.finish_reason == sched_lib.FINISH_MAX_NEW
    assert not s.has_work


def test_scheduler_invariants_chaos_stream():
    """Randomized stream with deadlines, cancels, and backpressure
    interleaved with token-driven evictions: no slot leaks, admissions
    stay FIFO, every accepted request lands in finished exactly once
    with a coherent reason."""
    from distributed_tensorflow_tpu.resilience import FaultClock

    rng = random.Random(20260803)
    clk = FaultClock()
    num_slots, max_len = 3, 24
    s = sched_lib.Scheduler(num_slots, max_len, clock=clk, max_queue=6)
    accepted, rejected, cancelled_by_us = [], 0, set()
    admitted_order = []

    for step in range(4000):
        # bursty arrivals so the bounded queue actually overflows
        for _ in range(rng.randint(0, 5) if len(accepted) < 120 else 0):
            try:
                accepted.append(s.submit(
                    [rng.randrange(50) for _ in range(rng.randint(1, max_len))],
                    max_new_tokens=rng.randint(1, 6),
                    eos_id=7 if rng.random() < 0.3 else None,
                    deadline_s=rng.uniform(0.5, 5.0)
                    if rng.random() < 0.4 else None,
                ))
            except sched_lib.QueueFull:
                rejected += 1
        if rng.random() < 0.1 and accepted:
            victim = rng.choice(accepted)
            if s.cancel(victim) is not None:
                cancelled_by_us.add(victim)
        clk.advance(rng.uniform(0.0, 0.5))
        s.expire()
        admitted_order.extend(r.uid for _, r in s.admit())
        live = [r.uid for r in s.slots if r is not None]
        assert len(live) == len(set(live))  # no double-booking
        for slot in s.active_slots():
            s.append_token(slot, rng.randrange(50))
        if len(accepted) >= 120 and not s.has_work:
            break
    assert not s.has_work, "chaos stream did not drain"

    assert rejected > 0, "stream never hit backpressure — weak test"
    assert cancelled_by_us and admitted_order
    assert admitted_order == sorted(admitted_order)  # FIFO survives chaos
    assert sorted(s.finished) == sorted(accepted)  # all land exactly once
    reasons = {r.finish_reason for r in s.finished.values()}
    assert reasons <= set(sched_lib.FINISH_REASONS)
    assert sched_lib.FINISH_TIMEOUT in reasons
    assert sched_lib.FINISH_CANCELLED in reasons
    for r in s.finished.values():
        if r.finish_reason == sched_lib.FINISH_TIMEOUT:
            assert r.t_deadline is not None and r.t_finish >= r.t_deadline
        elif r.finish_reason == sched_lib.FINISH_CANCELLED:
            assert r.uid in cancelled_by_us
        else:
            assert r.t_admit is not None  # token-driven finishes were resident


# ---------------------------------------------------------------------------
# Engine-level admission control + telemetry invariant
# ---------------------------------------------------------------------------


def _finished_totals(reg):
    return {
        dict(m.labels)["reason"]: int(m.value)
        for m in reg.collect() if m.name == "serve_finished_total"
    }


def _assert_telemetry_invariant(eng, expect_finished):
    """The PR-2 acceptance gate, extended over the new eviction paths:
    every finished request — including timeout/cancelled — contributes
    exactly one TTFT and one TPOT observation."""
    reg = eng.registry
    total = sum(_finished_totals(reg).values())
    assert total == expect_finished
    assert reg.get("serve_ttft_seconds").count == total
    assert reg.get("serve_tpot_seconds").count == total


def test_engine_timeout_and_cancel_telemetry(decoder):
    from distributed_tensorflow_tpu.resilience import FaultClock

    cfg, _, params = decoder
    clk = FaultClock()
    eng = serve.ServeEngine(cfg, params, num_slots=1, clock=clk)
    a = eng.submit([5, 17, 3], max_new_tokens=4)
    b = eng.submit([9, 9], max_new_tokens=4, deadline_s=1.0)  # starves in queue
    c = eng.submit([4, 4], max_new_tokens=4)
    eng.step()  # a prefills + decodes; b, c wait
    clk.advance(2.0)
    stats = eng.step()  # b times out before ever taking the slot
    assert b in stats.finished
    assert eng.cancel(c) is True and eng.cancel(c) is False
    done = eng.run()
    assert done[a].finish_reason == sched_lib.FINISH_MAX_NEW
    assert done[b].finish_reason == sched_lib.FINISH_TIMEOUT
    assert done[b].generated == [] and done[b].t_admit is None
    assert done[c].finish_reason == sched_lib.FINISH_CANCELLED
    totals = _finished_totals(eng.registry)
    assert totals[sched_lib.FINISH_TIMEOUT] == 1
    assert totals[sched_lib.FINISH_CANCELLED] == 1
    _assert_telemetry_invariant(eng, 3)


def test_engine_cancel_resident_frees_slot(decoder):
    cfg, _, params = decoder
    eng = serve.ServeEngine(cfg, params, num_slots=1)
    a = eng.submit([5, 17, 3], max_new_tokens=50)
    bquiet = eng.submit([8, 1], max_new_tokens=3)
    eng.step()
    eng.step()  # a is mid-decode with a couple of tokens out
    assert eng.cancel(a) is True
    assert eng.sched.active_slots() == []  # slot freed immediately
    done = eng.run()  # bquiet takes the slot and completes
    assert done[a].finish_reason == sched_lib.FINISH_CANCELLED
    assert len(done[a].generated) >= 1  # delivered tokens are kept
    assert done[bquiet].finish_reason == sched_lib.FINISH_MAX_NEW
    _assert_telemetry_invariant(eng, 2)


def test_engine_drain_graceful_shutdown(decoder):
    cfg, _, params = decoder
    eng = serve.ServeEngine(cfg, params, num_slots=1, max_queue=4)
    a = eng.submit([5, 17, 3], max_new_tokens=3)
    b = eng.submit([2, 2], max_new_tokens=3)
    eng.step()  # a resident, b queued
    done = eng.drain()
    assert done[a].finish_reason == sched_lib.FINISH_MAX_NEW  # finished, not killed
    assert done[b].finish_reason == sched_lib.FINISH_CANCELLED  # never ran
    with pytest.raises(sched_lib.SchedulerClosed):
        eng.submit([1])
    assert eng.registry.get("serve_occupancy").value == 0.0
    assert not eng.sched.has_work and eng.sched.finished == {}  # flushed
    _assert_telemetry_invariant(eng, 2)


def test_stream_survives_concurrent_drain(decoder):
    """A stream() consumer mid-iteration when drain() shuts the engine
    down must still receive every token drain() decoded for its request
    — not KeyError after the finished map is handed over."""
    cfg, _, params = decoder
    eng = serve.ServeEngine(cfg, params, num_slots=1)
    it = eng.stream([5, 17, 3], max_new_tokens=5)
    first = next(it)
    done = eng.drain()  # finishes the resident streamed request
    assert len(done) == 1
    req = next(iter(done.values()))
    assert req.finish_reason == sched_lib.FINISH_MAX_NEW
    assert [first] + list(it) == req.generated  # full delivery, no KeyError


def test_engine_deadline_mid_decode_eviction(decoder):
    """FINISH_TIMEOUT for a RESIDENT request: the deadline passes while
    it is decoding; the next step evicts it before more tokens land."""
    from distributed_tensorflow_tpu.resilience import FaultClock

    cfg, _, params = decoder
    clk = FaultClock()
    eng = serve.ServeEngine(cfg, params, num_slots=1, clock=clk)
    a = eng.submit([5, 17, 3], max_new_tokens=50, deadline_s=3.0)
    eng.step()
    g_before = len(eng.sched.slots[0].generated)
    clk.advance(5.0)
    stats = eng.step()
    assert a in stats.finished and stats.decoded_slots == 0
    done = eng.run()
    assert done[a].finish_reason == sched_lib.FINISH_TIMEOUT
    assert len(done[a].generated) == g_before  # nothing delivered post-deadline
    _assert_telemetry_invariant(eng, 1)


# ---------------------------------------------------------------------------
# Paged KV cache (docs/serving.md "Paged KV cache"): block allocator
# invariants, paged/dense parity, prefix reuse + copy-on-write, chunked
# prefill interleave, block-gated admission + preemption
# ---------------------------------------------------------------------------


def _paged_engine(cfg, params, **kw):
    base = dict(paged=True, block_size=8, prefill_chunk=8)
    base.update(kw)
    return serve.ServeEngine(cfg, params, **base)


def test_block_allocator_invariants():
    """Pure host-side accounting: used + free == pool size through
    alloc/incref/decref, refcount errors raise, LRU prefix-cache
    eviction frees exactly the cache-only blocks, flush returns the
    allocator to all-free."""
    a = serve.BlockAllocator(4, block_size=4)
    assert a.blocks_free == 4 and a.blocks_in_use == 0
    b0, b1 = a.alloc(), a.alloc()
    assert (a.blocks_in_use, a.blocks_free) == (2, 2)
    a.incref(b0)
    assert not a.decref(b0) and a.refcount(b0) == 1
    assert a.decref(b0) and a.blocks_free == 3
    with pytest.raises(ValueError):
        a.decref(b0)  # already free
    with pytest.raises(ValueError):
        a.incref(b0)  # can't revive a free block

    # register a 2-block prefix: one full block (cached, +1 ref) and a
    # partial tail (weak, no ref)
    toks = tuple(range(6))  # 4 full + 2 tail at block_size=4
    b2 = a.alloc()
    a.register_prefix(toks, [b1, b2])
    assert a.refcount(b1) == 2 and a.refcount(b2) == 1
    blocks, matched = a.match_prefix(toks)
    assert blocks == [b1, b2] and matched == 6
    assert a.refcount(b1) == 3 and a.refcount(b2) == 2
    for bid in blocks:
        a.decref(bid)
    # the original owner releases; the cache still pins the full block
    a.decref(b1), a.decref(b2)
    assert a.refcount(b1) == 1 and a.refcount(b2) == 0
    assert a.evictable() == 1
    # exhaust the pool: alloc must evict the cached block, not raise
    got = [a.alloc() for _ in range(a.blocks_free + 1)]
    assert a.blocks_free == 0 and a.evictions == 1 and len(got) == 4
    with pytest.raises(serve.NoFreeBlocks):
        a.alloc()
    # a freed-then-reallocated block's weak partial entry is stale
    assert a.match_prefix(toks) == ([], 0)
    for bid in got:
        a.decref(bid)
    assert a.flush_prefix_cache() == 0  # cache was already evicted
    assert a.blocks_free == 4
    assert all(a.refcount(i) == 0 for i in range(4))


def test_block_allocator_partial_entries_bounded_and_longest_match():
    """Weak partial-tail entries pick the LONGEST matching candidate,
    and the map sweeps stale entries so host memory stays bounded even
    for prompts nobody ever repeats."""
    a = serve.BlockAllocator(4, block_size=8)
    b0, b1 = a.alloc(), a.alloc()
    a.register_prefix((1, 2), [b0])          # tail candidate: 2 tokens
    a.register_prefix((1, 5, 6, 7), [b1])    # same first token, 4 tokens
    blocks, matched = a.match_prefix((1, 5, 6, 7, 8))
    assert blocks == [b1] and matched == 4   # longest match, not first
    a.decref(b1)

    bound = max(64, 2 * a.num_blocks)
    for i in range(3 * bound):
        bid = a.alloc()
        a.register_prefix((1000 + i, 1001 + i), [bid])
        a.decref(bid)  # freed immediately: the entry is instantly stale
    assert sum(len(c) for c in a._partial.values()) <= bound + 1


def test_block_allocator_note_write_invalidates_overwritten_tail():
    """A divergent in-place write into a registered partial-tail block
    must kill the weak entry: a later identical prompt would otherwise
    map K/V that no longer holds the registered content."""
    a = serve.BlockAllocator(4, block_size=4)
    b0 = a.alloc()
    a.register_prefix((1, 2), [b0])  # tail content (1, 2) at offsets 0-1
    # sole owner appends at offset 2 (past the registered fill): valid
    a.note_write(b0, 2)
    blocks, matched = a.match_prefix((1, 2, 9))
    assert blocks == [b0] and matched == 2
    for bid in blocks:
        a.decref(bid)
    # sole owner REWRITES offset 1 in place (divergence): entry dies
    a.note_write(b0, 1)
    assert a.match_prefix((1, 2, 9)) == ([], 0)
    a.decref(b0)
    assert a.blocks_free == 4


def test_paged_greedy_parity_with_dense(decoder):
    """Acceptance gate: 64-step greedy decode through the paged path
    (chunked prefill + block-table gather) is token-identical to the
    dense slot cache, which is itself logit-checked against the
    uncached forward above — both paths exercised on the same params."""
    cfg, _, params = decoder
    prompt = [5, 17, 3, 99, 42, 7, 11]
    dense = serve.ServeEngine(cfg, params, num_slots=1, paged=False)
    want = list(dense.stream(prompt, max_new_tokens=64))
    paged = _paged_engine(cfg, params, num_slots=1)
    got = list(paged.stream(prompt, max_new_tokens=64))
    assert len(want) == 64 and got == want

    # a long prompt (multiple chunks) must agree too
    long_prompt = [(7 * i + 3) % cfg.vocab_size for i in range(40)]
    dense = serve.ServeEngine(cfg, params, num_slots=1, paged=False)
    want = list(dense.stream(long_prompt, max_new_tokens=24))
    paged = _paged_engine(cfg, params, num_slots=1)
    got = list(paged.stream(long_prompt, max_new_tokens=24))
    assert got == want


@pytest.mark.parametrize("paged", [True, False])
def test_engine_request_isolation_both_paths(decoder, paged):
    """Slot reuse must not leak state across requests on either cache
    layout: each request's greedy completion equals its solo run."""
    cfg, _, params = decoder
    prompts = [[5, 17, 3], [88, 12, 61, 40, 2], [7], [33, 33, 9, 1]]
    solo = []
    for p in prompts:
        eng = serve.ServeEngine(cfg, params, num_slots=1, paged=paged)
        solo.append(list(eng.stream(p, max_new_tokens=12)))
    eng = serve.ServeEngine(cfg, params, num_slots=2, paged=paged)
    uids = [eng.submit(p, max_new_tokens=12) for p in prompts]
    done = eng.run()
    for uid, want in zip(uids, solo):
        assert done[uid].generated == want


def test_paged_prefix_reuse_and_cow(decoder):
    """Requests sharing a prompt map the same physical blocks (reuse
    hits > 0, strictly lower peak block usage than reuse disabled), the
    first divergent write triggers a copy-on-write block copy, and the
    shared path stays token-identical to the solo run."""
    cfg, _, params = decoder
    sys_prefix = list(range(1, 25))  # 3 full blocks at block_size=8
    warm = sys_prefix + [50]

    def drive(reuse):
        eng = _paged_engine(cfg, params, num_slots=4, prefix_reuse=reuse)
        for _ in eng.stream(warm, max_new_tokens=4):
            pass  # warm request registers the prefix (when enabled)
        uids = [eng.submit(sys_prefix + [60 + i], max_new_tokens=6)
                for i in range(4)]
        peak = 0
        while eng.sched.has_work:
            eng.step()
            peak = max(peak, eng.alloc.blocks_in_use)
        done = eng.sched.drain_finished()
        outs = [done[u].generated for u in uids]
        hits = int(eng.registry.get("prefix_reuse_hits_total").value)
        eng.drain()
        assert eng.alloc.blocks_free == eng.cache.num_blocks  # no leaks
        return outs, peak, hits

    outs_on, peak_on, hits_on = drive(True)
    outs_off, peak_off, hits_off = drive(False)
    assert outs_on == outs_off  # sharing must not change a single token
    assert hits_on > 0 and hits_off == 0
    assert peak_on < peak_off  # strictly lower block usage

    # copy-on-write: an identical prompt maps the sharer's partially
    # filled tail block; the first divergent write must copy it
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]  # 1 full block + 2-token tail
    solo = serve.ServeEngine(cfg, params, num_slots=1, paged=False)
    want = list(solo.stream(prompt, max_new_tokens=12))
    eng = _paged_engine(cfg, params, num_slots=2)
    a = eng.submit(prompt, max_new_tokens=12)
    eng.step(), eng.step()  # A prefilled + registered, mid-decode
    b = eng.submit(prompt, max_new_tokens=12)
    done = eng.run()
    assert eng.alloc.cow_copies >= 1
    assert done[a].generated == want and done[b].generated == want
    eng.drain()
    assert eng.alloc.blocks_free == eng.cache.num_blocks
    assert all(eng.alloc.refcount(i) == 0
               for i in range(eng.cache.num_blocks))


def test_paged_chunked_prefill_interleaves_decode(decoder):
    """A long prompt prefills in fixed-size chunks interleaved with
    decode: the resident request gains one token EVERY step of the long
    prefill (TTFT of residents is bounded by one chunk), and the chunk
    events land in the flight recorder."""
    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder

    cfg, _, params = decoder
    rec = FlightRecorder(capacity=256)
    eng = _paged_engine(cfg, params, num_slots=2, flightrec=rec)
    short = eng.submit([5, 17, 3], max_new_tokens=40)
    eng.step()  # short is resident and decoding
    short_req = eng.sched.slots[eng.sched.active_slots()[0]]
    assert short_req.uid == short
    eng.submit(list(range(1, 65)), max_new_tokens=4)  # 64 tokens: 8 chunks
    chunk_steps = 0
    while True:
        before = len(short_req.generated)
        stats = eng.step()
        if stats.prefill_chunks == 0:
            break  # the long prefill completed on an earlier step
        assert stats.prefill_chunks == 1  # one chunk per pending slot
        assert stats.decoded_slots >= 1  # decode ran in the SAME step
        assert len(short_req.generated) == before + 1  # no starvation
        chunk_steps += 1
    assert chunk_steps == 8  # ceil(64 / prefill_chunk=8)
    # 9 total: the short prompt's own prefill was one chunk too
    assert int(eng.registry.get("prefill_chunks_total").value) == 9
    kinds = [e["kind"] for e in rec.events()]
    assert kinds.count("serve_prefill_chunk") == 9
    eng.drain()
    assert eng.alloc.blocks_free == eng.cache.num_blocks


def test_paged_admission_gated_on_blocks(decoder):
    """Admission is gated on free KV blocks, not free slots: with a
    tight pool a queued request waits even though a slot is empty, and
    is admitted once blocks come home."""
    cfg, _, params = decoder
    # pool of 4 blocks = 32 tokens; max_len=32 so MB=4 (one request may
    # need the whole pool)
    eng = _paged_engine(cfg, params, num_slots=2, max_len=32,
                        num_blocks=4, prefix_reuse=False)
    a = eng.submit([1] * 20, max_new_tokens=4)  # needs 3 blocks
    for _ in range(3):  # 3 chunks: a fully prefilled, holds 3 blocks
        eng.step()
    b = eng.submit([2] * 20, max_new_tokens=4)  # needs 3 more: gated
    stats = eng.step()
    assert stats.admitted == 0  # a slot is free, but the pool is not
    assert eng.sched.slots[1] is None and eng.sched.queue
    done = eng.run()  # a finishes, blocks free, b admits and finishes
    assert done[a].finish_reason == sched_lib.FINISH_MAX_NEW
    assert done[b].finish_reason == sched_lib.FINISH_MAX_NEW
    eng.drain()
    assert eng.alloc.blocks_free == 4

    with pytest.raises(ValueError):
        _paged_engine(cfg, params, max_len=32, num_blocks=3)  # < MB

    # the gate caps its demand at max_len: a full-context prompt (legal;
    # finishes at its first token via max_len) must ADMIT, not wedge the
    # queue head forever asking for ceil((max_len+1)/bs) blocks
    eng = _paged_engine(cfg, params, num_slots=1, max_len=32, num_blocks=4)
    uid = eng.submit([3] * 32, max_new_tokens=8)
    done = eng.run()
    assert done[uid].finish_reason == sched_lib.FINISH_MAX_LEN
    assert len(done[uid].generated) == 1
    eng.drain()
    assert eng.alloc.blocks_free == 4


def test_paged_fully_cached_prompt_never_deadlocks(decoder):
    """When a finished prompt's blocks fill the ENTIRE pool as cache
    entries, resubmitting that exact prompt must still admit and finish
    (evict-matched gate fallback + in-place un-cache when the COW copy
    cannot be allocated) — not wedge the queue head forever."""
    cfg, _, params = decoder
    eng = _paged_engine(cfg, params, num_slots=1, max_len=32, num_blocks=4)
    prompt = [5] * 32  # exactly max_len: 4 full blocks = the whole pool
    a = eng.submit(prompt, max_new_tokens=4)
    done1 = eng.run()
    assert done1[a].finish_reason == sched_lib.FINISH_MAX_LEN
    b = eng.submit(prompt, max_new_tokens=4)
    for _ in range(50):
        eng.step()
        if b in eng.sched.finished:
            break
    else:
        pytest.fail("fully-cached prompt was never admitted (gate wedge)")
    done2 = eng.sched.drain_finished()
    assert done2[b].generated == done1[a].generated
    eng.drain()
    assert eng.alloc.blocks_free == 4


def test_paged_preemption_exact_parity(decoder):
    """Block exhaustion mid-decode preempts the youngest resident back
    to the queue head; it re-prefills prompt + generated and finishes
    with EXACTLY the tokens an uncontended engine produces."""
    cfg, _, params = decoder

    def drive(num_blocks):
        eng = _paged_engine(cfg, params, num_slots=2, max_len=32,
                            num_blocks=num_blocks, prefix_reuse=False)
        uids = [eng.submit([10 + i] * 10, max_new_tokens=20)
                for i in range(3)]
        done = eng.run()
        outs = [done[u].generated for u in uids]
        pre = sum(done[u].preemptions for u in uids)
        eng.drain()
        assert eng.alloc.blocks_free == eng.cache.num_blocks
        return outs, pre

    ample, pre_ample = drive(8)
    tight, pre_tight = drive(5)
    assert pre_ample == 0 and pre_tight > 0
    assert ample == tight  # preemption is invisible in the tokens


def test_paged_block_accounting_chaos(decoder):
    """The block-accounting invariant under a chaotic stream (mixed
    lengths, shared prefixes, deadlines, cancels, preemption pressure):
    used + free == pool size at EVERY step, and every eviction path —
    finish, timeout, cancel, drain/close — returns its blocks."""
    from distributed_tensorflow_tpu.resilience import FaultClock

    cfg, _, params = decoder
    rng = random.Random(20260804)
    clk = FaultClock()
    eng = _paged_engine(cfg, params, num_slots=3, max_len=48,
                        num_blocks=10, max_queue=8, clock=clk)
    shared = [7, 8, 9, 10, 11, 12, 13, 14, 15, 16]
    submitted: list[int] = []
    for step in range(400):
        for _ in range(rng.randint(0, 2) if len(submitted) < 40 else 0):
            plen = rng.choice([3, 9, 18, 30])
            prompt = (shared[:8] + [rng.randrange(100)] * (plen - 8)
                      if plen > 8 and rng.random() < 0.5
                      else [rng.randrange(100) for _ in range(plen)])
            try:
                submitted.append(eng.submit(
                    prompt, max_new_tokens=rng.randint(1, 8),
                    deadline_s=rng.uniform(0.5, 4.0)
                    if rng.random() < 0.3 else None,
                ))
            except sched_lib.QueueFull:
                pass
        if submitted and rng.random() < 0.1:
            eng.cancel(rng.choice(submitted))
        clk.advance(rng.uniform(0.0, 0.4))
        eng.step()
        a = eng.alloc
        assert a.blocks_in_use + a.blocks_free == a.num_blocks
        assert all(a.refcount(i) >= 0 for i in range(a.num_blocks))
        if len(submitted) >= 40 and not eng.sched.has_work:
            break
    assert not eng.sched.has_work, "chaos stream did not drain"
    eng.drain()
    assert eng.alloc.blocks_free == eng.alloc.num_blocks  # zero leaks
    assert all(eng.alloc.refcount(i) == 0
               for i in range(eng.alloc.num_blocks))
    # telemetry invariant survives the paged refactor: one TTFT + one
    # TPOT observation per finished request, whatever evicted it
    _assert_telemetry_invariant(
        eng, sum(_finished_totals(eng.registry).values()))


# ---------------------------------------------------------------------------
# Speculative decoding (PR 20)
# ---------------------------------------------------------------------------


def test_block_allocator_release_tail():
    """Speculation rollback is a refcount edit, never a copy:
    ``release_tail`` frees exactly the blocks past ``keep``, trims the
    owner's list in place, is a no-op when nothing hangs over, and the
    double-free tripwire still fires on a rolled-back block."""
    a = serve.BlockAllocator(6, block_size=4)
    blocks = [a.alloc() for _ in range(4)]
    dropped = blocks[2:]
    a.release_tail(blocks, keep=2)
    assert len(blocks) == 2
    assert a.blocks_in_use == 2 and a.blocks_free == 4
    for bid in dropped:
        assert a.refcount(bid) == 0
        with pytest.raises(ValueError):
            a.decref(bid)  # rollback already freed it
    a.release_tail(blocks, keep=2)  # nothing hangs over: no-op
    assert a.blocks_in_use == 2
    with pytest.raises(ValueError):
        a.release_tail(blocks, keep=-1)
    # a tail block with a second holder survives the rollback: only THIS
    # owner's reference is dropped
    shared = blocks[1]
    a.incref(shared)
    a.release_tail(blocks, keep=1)
    assert blocks == blocks[:1] and a.refcount(shared) == 1
    assert a.blocks_in_use == 2  # blocks[0] + the still-held tail
    a.decref(shared), a.decref(blocks[0])
    assert a.blocks_free == 6


def test_spec_engine_validation(decoder):
    """Speculation requires the paged path (rollback is a block-table
    edit) and sane knobs — misconfigurations fail at construction."""
    cfg, _, params = decoder
    with pytest.raises(ValueError, match="paged"):
        serve.ServeEngine(cfg, params, num_slots=1, paged=False, spec_k=2)
    with pytest.raises(ValueError, match="spec_k"):
        _paged_engine(cfg, params, num_slots=1, spec_k=-1)
    with pytest.raises(ValueError, match="spec_ngram"):
        _paged_engine(cfg, params, num_slots=1, spec_k=2, spec_ngram=0)


def test_spec_greedy_exact_parity(decoder):
    """Acceptance gate: greedy streams with speculative decoding on are
    BIT-IDENTICAL to the non-spec paged engine (itself parity-gated
    against dense above), short and multi-chunk-long prompts — rejected
    drafts roll back without a trace, accepted ones are the same tokens
    the target would have emitted one step at a time."""
    cfg, _, params = decoder
    prompts = [
        [5, 17, 3, 99, 42, 7, 11],
        [(7 * i + 3) % cfg.vocab_size for i in range(40)],  # 5 chunks
    ]
    for prompt in prompts:
        plain = _paged_engine(cfg, params, num_slots=1)
        want = list(plain.stream(prompt, max_new_tokens=48))
        spec = _paged_engine(cfg, params, num_slots=1, spec_k=4)
        got = list(spec.stream(prompt, max_new_tokens=48))
        assert got == want
        spec.drain()
        assert spec.alloc.blocks_free == spec.cache.num_blocks


def test_spec_telemetry_and_flightrec(decoder):
    """Observability closes over speculation: proposed/accepted counters
    add up, the acceptance-rate gauge is their ratio, every verify step
    lands a ``serve_spec_step`` event, per-request ``spec_accepted``
    sums to the counter — and the PR-2 invariant holds under MULTI-token
    steps: exactly one TTFT and one TPOT observation per finished
    request (TPOT normalizes by tokens delivered, not steps)."""
    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder

    cfg, _, params = decoder
    rec = FlightRecorder(capacity=512)
    eng = _paged_engine(cfg, params, num_slots=2, spec_k=4, flightrec=rec)
    # a highly repetitive prompt: the n-gram drafter should land several
    # multi-token acceptances, exercising multi-token delivery
    uids = [eng.submit([1, 2, 3, 1, 2, 3, 1, 2], max_new_tokens=16)
            for _ in range(2)]
    done = eng.run()
    reg = eng.registry
    prop = int(reg.get("spec_tokens_proposed_total").value)
    acc = int(reg.get("spec_tokens_accepted_total").value)
    assert prop > 0 and 0 <= acc <= prop
    assert reg.get("spec_acceptance_rate").value == pytest.approx(acc / prop)
    evs = [e for e in rec.events() if e["kind"] == "serve_spec_step"]
    assert evs and all(0 <= e["accepted"] <= e["proposed"] for e in evs)
    assert sum(e["proposed"] for e in evs) == prop
    assert sum(e["accepted"] for e in evs) == acc
    assert sum(done[u].spec_accepted for u in uids) == acc
    assert all(len(done[u].generated) == 16 for u in uids)
    _assert_telemetry_invariant(eng, 2)
    eng.drain()
    assert eng.alloc.blocks_free == eng.cache.num_blocks


def test_spec_preemption_and_rollback_block_accounting(decoder):
    """The PR-13 accounting invariant extended over speculation: with a
    tight pool forcing preemption AND rejected drafts forcing rollback,
    used + free == pool size at EVERY step, the drain leaves the
    allocator all-free, and the greedy tokens still match the
    uncontended non-spec run exactly."""
    cfg, _, params = decoder

    def drive(num_blocks, spec_k):
        eng = _paged_engine(cfg, params, num_slots=2, max_len=32,
                            num_blocks=num_blocks, prefix_reuse=False,
                            spec_k=spec_k)
        uids = [eng.submit([10 + i] * 10, max_new_tokens=20)
                for i in range(3)]
        while eng.sched.has_work:
            eng.step()
            a = eng.alloc
            assert a.blocks_in_use + a.blocks_free == a.num_blocks
            assert all(a.refcount(i) >= 0 for i in range(a.num_blocks))
        done = eng.sched.drain_finished()
        outs = [done[u].generated for u in uids]
        pre = sum(done[u].preemptions for u in uids)
        eng.drain()
        assert eng.alloc.blocks_free == eng.cache.num_blocks
        assert all(eng.alloc.refcount(i) == 0
                   for i in range(eng.cache.num_blocks))
        return outs, pre

    plain, _ = drive(8, spec_k=0)
    ample, _ = drive(8, spec_k=4)
    tight, pre_tight = drive(5, spec_k=4)
    assert ample == plain  # speculation is invisible in greedy tokens
    assert tight == plain  # ... even under preemption pressure
    assert pre_tight > 0


def test_spec_sample_matches_target_distribution():
    """The acceptance rule is distribution-preserving: over many trials
    the first emitted token's empirical distribution matches straight
    temperature sampling from the target row — whether the deterministic
    draft is the target's most- or least-likely token. (Accept d with
    p(d), else resample the renormalized residual: the marginal is p.)"""
    from distributed_tensorflow_tpu.serve import sampling

    rng = np.random.default_rng(20260807)
    logits = np.asarray([[2.0, 1.0, 0.0, -1.0]] * 2)
    temperature = 0.8
    p = np.exp(logits[0] / temperature)
    p /= p.sum()
    n = 20000
    for draft_tok in (0, 3):
        counts = np.zeros(4)
        for _ in range(n):
            emitted, _ = sampling.spec_verify_sample(
                logits, [draft_tok], rng, temperature=temperature)
            counts[emitted[0]] += 1
        np.testing.assert_allclose(counts / n, p, atol=0.02)


def test_paged_cache_specs_follow_sharding_rules():
    """The pool shards heads over `model` like the dense cache; the
    blocks dim is replicated (blocks are shared across requests, so
    they must not scatter over the batch axes)."""
    spec = serve.paged_cache_specs()
    assert spec.k == P(None, None, "model", None, None)
    assert spec.v == spec.k


# ---------------------------------------------------------------------------
# Cache sharding + sampling
# ---------------------------------------------------------------------------


def test_cache_specs_follow_sharding_rules():
    """The cache pytree shards by the same logical rules as the model:
    heads over `model`, slots over the batch axes (docs/serving.md)."""
    spec = serve.cache_specs()
    assert spec.k == P(None, ("data", "fsdp"), "model", None, None)
    assert spec.v == spec.k

    cfg = tiny_decoder()
    mesh = build_mesh(MeshSpec(data=2, fsdp=2, model=2))
    cache = serve.init_cache(cfg, num_slots=4, dtype="float32")
    sharded = serve.shard_cache(cache, mesh)
    assert sharded.k.sharding == NamedSharding(mesh, spec.k)
    # heads=4 over model=2, slots=4 over data*fsdp=4
    assert sharded.k.addressable_shards[0].data.shape == (
        cfg.num_layers, 1, 2, cfg.max_len, cfg.head_dim
    )


def test_sampling_modes():
    logits = jnp.asarray([[0.0, 3.0, 1.0, -2.0], [5.0, 0.1, 0.2, 0.3]])
    greedy = serve.sample(logits)
    assert greedy.tolist() == [1, 0] and greedy.dtype == jnp.int32

    key = jax.random.PRNGKey(0)
    for i in range(20):
        t = serve.sample(
            logits, jax.random.fold_in(key, i), temperature=0.7, top_k=2
        )
        assert t[0] in (1, 2) and t[1] in (0, 3)  # top-2 of each row

    with pytest.raises(ValueError):
        serve.sample(logits, None, temperature=1.0)


def test_cache_specs_match_rules_table():
    """Migration parity (PR 14): the KV_CACHE_RULES table derives the
    exact spec tree the pre-engine logical-rules path produced."""
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.serve import kv_cache as kv

    table_specs = serve.cache_specs()  # default: the rules table
    legacy = sh.spec_from_logical(kv.CACHE_LOGICAL, sh.TP_RULES)
    assert table_specs.k == legacy and table_specs.v == legacy
    # the explicit logical-rules escape hatch still resolves identically
    assert serve.cache_specs(sh.TP_RULES) == table_specs


def test_paged_cache_specs_match_rules_table():
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.serve import kv_cache as kv

    table_specs = serve.paged_cache_specs()
    legacy = sh.spec_from_logical(kv.PAGED_CACHE_LOGICAL, sh.TP_RULES)
    assert table_specs.k == legacy and table_specs.v == legacy
    assert serve.paged_cache_specs(sh.TP_RULES) == table_specs
