"""Tier-A budget invariants of the on-chip session script (VERDICT r4
item 2): the decisive prefix must stay inside a 41-minute window's
first 25 minutes, and the probe step must leave room for its own
retry — the r5 dryrun showed an outer budget below 2x the inner probe
timeout kills the retry before its verdict reaches the shared cache."""

import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tools", "onchip_round5.sh")


def _tier_a_steps():
    """(name, timeout_s) for every `run` step before the tier-A/B split."""
    text = open(SCRIPT).read()
    # the section marker line, not the mention in the header comment
    tier_a = text.split("# ---------------- TIER B", 1)[0]
    return re.findall(r"^run (\w+) (\d+) ", tier_a, flags=re.M)


def test_tier_a_exists_and_is_complete():
    steps = dict((n, int(t)) for n, t in _tier_a_steps())
    # the decisive prefix the round-4 verdict demanded, in order
    assert list(steps) == ["probe", "hbm", "bench_auto", "bert"], steps


def test_tier_a_worst_case_fits_25_minutes():
    total = sum(int(t) for _, t in _tier_a_steps())
    assert total <= 1500, (
        f"tier-A worst case {total}s exceeds the 25-min budget; the only "
        "observed healthy window was 41 min (PERF_NOTES)")


def test_probe_outer_budget_covers_inner_retry():
    m = re.search(r"^run probe (\d+) python -u tools/probe\.py (\d+)",
                  open(SCRIPT).read(), flags=re.M)
    assert m, "probe step must use tools/probe.py"
    outer, inner = int(m.group(1)), int(m.group(2))
    # probe.py retries one hang: worst case 2x inner, plus spawn margin
    assert outer >= 2 * inner + 10, (outer, inner)


def test_reprobe_abort_covers_sigkill_exits():
    # rc=124 (TERM on timeout) AND rc>=128 (KILL of a TERM-ignoring
    # wedged step) must both reach the dead-relay reprobe; match the
    # actual guard statement, not a comment quoting it
    assert re.search(r"^\s*if \[ \$rc -ge 124 \]", open(SCRIPT).read(),
                     flags=re.M), (
        "hang detection must cover --kill-after exits (rc=137), not "
        "just rc=124")


def test_dryrun_isolates_probe_cache():
    # a CPU rehearsal must never write DOWN into the real probe cache:
    # the DRY setup block must redirect the cache path (match the
    # if-block up to its own terminator line, not a bare "fi" substring)
    text = open(SCRIPT).read()
    m = re.search(r'if \[ -n "\$DRY" \]; then\n(.*?)^fi$', text,
                  flags=re.M | re.S)
    assert m, "DRY setup block not found"
    assert 'export DTF_PROBE_CACHE="$OUT/' in m.group(1)
