import os

import numpy as np
import pytest

from distributed_tensorflow_tpu.data import (
    DataConfig,
    ElasticStream,
    Prefetcher,
    SyntheticClassification,
    WorkerShard,
    local_batch_size,
)


def test_synthetic_deterministic():
    cfg = DataConfig(global_batch_size=32, image_size=8, channels=1, seed=3)
    a = SyntheticClassification(cfg).batch(5)
    b = SyntheticClassification(cfg).batch(5)
    np.testing.assert_array_equal(a["image"], b["image"])
    np.testing.assert_array_equal(a["label"], b["label"])
    c = SyntheticClassification(cfg).batch(6)
    assert not np.array_equal(a["image"], c["image"])


def test_synthetic_learnable():
    """Labels come from a linear teacher → classes are balanced-ish and
    predictable from inputs (sanity for convergence tests)."""
    cfg = DataConfig(global_batch_size=512, image_size=8, num_classes=10)
    ds = SyntheticClassification(cfg)
    batch = ds.batch(0)
    # teacher recovers its own labels
    pred = np.argmax(
        batch["image"].reshape(512, -1) @ ds.teacher, axis=-1
    )
    np.testing.assert_array_equal(pred, batch["label"])
    assert len(np.unique(batch["label"])) > 3


def test_local_batch_size_divisibility(monkeypatch):
    assert local_batch_size(128) == 128  # single process
    import distributed_tensorflow_tpu.data.pipeline as pl

    monkeypatch.setattr(pl.jax, "process_count", lambda: 4)
    assert local_batch_size(128) == 32
    with pytest.raises(ValueError, match="not divisible"):
        local_batch_size(30)


def test_npz_dataset_bounded_and_offset(tmp_path):
    from distributed_tensorflow_tpu.data import NpzDataset

    n = 100
    path = str(tmp_path / "d.npz")
    np.savez(path, image=np.arange(n * 4).reshape(n, 4).astype(np.float32),
             label=np.arange(n).astype(np.int32) % 10)
    cfg = DataConfig(global_batch_size=10)
    ds = NpzDataset(path, cfg, num_batches=7)
    batches = list(ds)
    assert len(batches) == 7  # bounded, no infinite loop
    # offset stream continues where the first left off (same shuffle epoch)
    cont = list(NpzDataset(path, cfg, num_batches=3, index_offset=7))
    straight = list(NpzDataset(path, cfg, num_batches=10))
    np.testing.assert_array_equal(cont[0]["image"], straight[7]["image"])


def _global_batches(i0):
    """QuarantineFilter/ElasticStream contract: first batch is global
    index i0 + 1; batch i is a pure function of i."""
    i = i0
    while True:
        i += 1
        yield {"x": np.arange(12, dtype=np.int64) * 100 + i,
               "y": np.full(12, i)}


def test_worker_shard_slices_partition_the_batch():
    batch = next(_global_batches(0))
    shards = [WorkerShard(r, 3) for r in range(3)]
    pieces = [s.slice(batch) for s in shards]
    # disjoint, union == the global batch (order-insensitive), and
    # well-defined for 12 % 3 == 0 AND ragged worlds
    got = np.sort(np.concatenate([p["x"] for p in pieces]))
    np.testing.assert_array_equal(got, np.sort(batch["x"]))
    ragged = [WorkerShard(r, 5).slice(batch)["x"] for r in range(5)]
    assert sorted(len(p) for p in ragged) == [2, 2, 2, 3, 3]
    np.testing.assert_array_equal(
        np.sort(np.concatenate(ragged)), np.sort(batch["x"]))
    with pytest.raises(ValueError, match="rank"):
        WorkerShard(3, 3)
    with pytest.raises(ValueError, match="world"):
        WorkerShard(0, 0)


def test_elastic_stream_live_reshard_is_pure_in_schedule():
    """The determinism contract: a live reshard at index B delivers
    EXACTLY the slices a fresh stream built with the same schedule
    would — the trajectory is a pure function of (seed, schedule)."""
    live = ElasticStream(_global_batches, WorkerShard(0, 3))
    out = [next(live) for _ in range(3)]          # batches 1..3 at 0/3
    live.reshard(WorkerShard(0, 2), at_index=5)   # shrink binds to >5
    out += [next(live) for _ in range(4)]         # 4,5 at 0/3; 6,7 at 0/2
    live.reshard(WorkerShard(1, 3), at_index=8)   # rejoin, new rank
    out += [next(live) for _ in range(3)]         # 8 at 0/2; 9,10 at 1/3
    assert live.schedule == [(5, 0, 2), (8, 1, 3)]

    def replay(i):
        shard = (WorkerShard(0, 3) if i <= 5
                 else WorkerShard(0, 2) if i <= 8 else WorkerShard(1, 3))
        return shard.slice(
            {"x": np.arange(12, dtype=np.int64) * 100 + i,
             "y": np.full(12, i)})

    for i, got in enumerate(out, start=1):
        want = replay(i)
        np.testing.assert_array_equal(got["x"], want["x"])
        np.testing.assert_array_equal(got["y"], want["y"])


def test_elastic_stream_reshard_behind_cursor_applies_now():
    s = ElasticStream(_global_batches, WorkerShard(0, 2), start_index=4)
    first = next(s)                      # batch 5 at 0/2
    assert first["y"][0] == 5 and len(first["x"]) == 6
    s.reshard(WorkerShard(1, 4), at_index=3)  # barrier already behind
    nxt = next(s)                        # batch 6, new shard immediately
    assert len(nxt["x"]) == 3
    np.testing.assert_array_equal(
        nxt["x"], (np.arange(12, dtype=np.int64) * 100 + 6)[1::4])


def test_elastic_stream_none_shard_is_replica_mode():
    """shard=None yields the FULL global batch — the collective-free
    test rig's stand-in for the data-parallel allreduce."""
    s = ElasticStream(_global_batches, None)
    assert len(next(s)["x"]) == 12
    s.reshard(WorkerShard(0, 2), at_index=1)
    assert len(next(s)["x"]) == 6
    s.reshard(None, at_index=2)
    assert len(next(s)["x"]) == 12
    assert s.schedule == [(1, 0, 2), (2, None, None)]


def test_elastic_stream_newer_plan_supersedes_pending():
    s = ElasticStream(_global_batches, WorkerShard(0, 2))
    s.reshard(WorkerShard(0, 3), at_index=4)
    s.reshard(WorkerShard(0, 4), at_index=2)  # newer plan, earlier barrier
    out = [next(s) for _ in range(4)]
    assert [len(b["x"]) for b in out] == [6, 6, 3, 3]
    assert s.schedule == [(2, 0, 4)]  # the superseded switch never fired


def test_prefetcher_order_and_completion():
    src = [{"i": np.asarray(i)} for i in range(10)]
    out = list(Prefetcher(src, depth=3))
    assert [int(b["i"]) for b in out] == list(range(10))


def test_prefetcher_propagates_errors():
    def bad():
        yield {"i": np.asarray(0)}
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(Prefetcher(bad(), depth=2))


def test_prefetcher_early_stop_does_not_hang():
    def infinite():
        i = 0
        while True:
            yield {"i": np.asarray(i)}
            i += 1

    it = iter(Prefetcher(infinite(), depth=2))
    for _ in range(5):
        next(it)
    it.close()  # generator close must not deadlock the worker


@pytest.mark.slow
def test_tfdata_adapter_host_stream():
    """tf.data -> host-batch contract: numpy dicts at the local batch
    size, resume via start_index (batch skip), deterministic shuffle, and
    end-to-end through a Trainer step."""
    tf = pytest.importorskip("tensorflow")

    from distributed_tensorflow_tpu.data import tfdata

    n = 64
    images = (np.arange(n)[:, None] * np.ones((1, 4))).astype(np.float32)
    labels = (np.arange(n) % 3).astype(np.int32)

    def make_ds():
        return tf.data.Dataset.from_tensor_slices(
            {"image": images, "label": labels}
        )

    stream = tfdata.host_stream(make_ds, global_batch_size=8, repeat=False)
    batches = list(stream)
    assert len(batches) == 8
    assert batches[0]["image"].shape == (8, 4)
    assert batches[0]["image"].dtype == np.float32
    np.testing.assert_array_equal(batches[0]["label"], labels[:8])

    # start_index skips whole batches (the runner's resume offset)
    resumed = list(tfdata.host_stream(make_ds, 8, start_index=3,
                                      repeat=False))
    np.testing.assert_array_equal(resumed[0]["image"], batches[3]["image"])

    # shuffle is seeded/deterministic and preserves the set of examples
    s1 = list(tfdata.host_stream(make_ds, 8, shuffle_buffer=64, seed=7,
                                 repeat=False))
    s2 = list(tfdata.host_stream(make_ds, 8, shuffle_buffer=64, seed=7,
                                 repeat=False))
    np.testing.assert_array_equal(s1[0]["image"], s2[0]["image"])
    assert not np.array_equal(s1[0]["image"], batches[0]["image"])


class TestTokenFileMLM:
    def _token_file(self, tmp_path, n=5000, vocab=300):
        import numpy as np

        path = str(tmp_path / "corpus.npy")
        np.save(path, np.random.RandomState(0).randint(
            0, vocab, n).astype(np.int32))
        return path

    def test_gathered_format_and_determinism(self, tmp_path):
        import numpy as np

        from distributed_tensorflow_tpu.data.text import (
            TextDataConfig, make_text_dataset,
        )

        path = self._token_file(tmp_path)
        cfg = TextDataConfig(dataset=f"tokens_mlm:{path}",
                             global_batch_size=8, seq_len=32,
                             vocab_size=300, max_predictions=5)
        b = make_text_dataset(cfg).batch(3)
        assert set(b) == {"input_ids", "masked_positions", "masked_labels"}
        assert b["input_ids"].shape == (8, 32)
        assert b["masked_positions"].shape == (8, 5)
        # labels must be the ORIGINAL tokens at the masked positions —
        # TokenFileLM shares the window RNG, so its uncorrupted batch at
        # the same index IS the original token view
        cfg_lm = TextDataConfig(dataset=f"tokens:{path}",
                                global_batch_size=8, seq_len=32,
                                vocab_size=300)
        original = make_text_dataset(cfg_lm).batch(3)["input_ids"]
        np.testing.assert_array_equal(
            b["masked_labels"],
            np.take_along_axis(original, b["masked_positions"], axis=1))
        # same index -> identical batch (resume contract)
        b2 = make_text_dataset(cfg).batch(3)
        for k in b:
            np.testing.assert_array_equal(b[k], b2[k])
        # different index -> different masking
        b3 = make_text_dataset(cfg).batch(4)
        assert not np.array_equal(b["input_ids"], b3["input_ids"])

    def test_dense_format_ignores_unmasked(self, tmp_path):
        import numpy as np

        from distributed_tensorflow_tpu.data.text import (
            IGNORE_INDEX, TextDataConfig, make_text_dataset,
        )

        path = self._token_file(tmp_path)
        cfg = TextDataConfig(dataset=f"tokens_mlm:{path}",
                             global_batch_size=4, seq_len=64,
                             vocab_size=300, max_predictions=0,
                             mask_prob=0.15)
        b = make_text_dataset(cfg).batch(0)
        assert set(b) == {"input_ids", "labels"}
        frac = float((b["labels"] != IGNORE_INDEX).mean())
        assert 0.05 < frac < 0.3  # ~mask_prob of positions carry labels

    def test_bert_workload_trains_on_token_file(self, tmp_path):
        """End-to-end: bert_pretrain consumes a real token file through
        the MLM stream (the reference's create_pretraining_data ->
        TFRecord -> train path, collapsed to .npy -> tokens_mlm)."""
        from distributed_tensorflow_tpu import workloads

        path = self._token_file(tmp_path, n=20000, vocab=256)
        result = workloads.run_workload(
            "bert_pretrain",
            [
                f"--data.dataset=tokens_mlm:{path}",
                "--data.global_batch_size=8",
                "--data.seq_len=32",
                "--data.vocab_size=256",
                "--data.mask_token=103",
                "--data.max_predictions=5",
                "--model.vocab_size=256",
                "--model.num_layers=2",
                "--model.d_model=32",
                "--model.num_heads=2",
                "--model.d_ff=64",
                "--model.max_len=32",
                "--train.num_steps=4",
                "--train.log_every=2",
                "--train.eval_batches=0",
                "--checkpoint.directory=",
            ],
        )
        assert all(
            h["loss"] == h["loss"] for h in result.history  # finite
        )


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestMakeTokenFile:
    def test_wordpiece_greedy_longest_match(self, tmp_path):
        vocab = tmp_path / "vocab.txt"
        vocab.write_text(
            "[PAD]\n[UNK]\n[CLS]\n[SEP]\n[MASK]\nhello\n,\n!\n.\nworld\n"
            "un\n##afford\n##able\ntoken\n##ization\n"
        )
        import sys
        sys.path.insert(0, str(REPO))
        from tools.make_token_file import WordPiece

        enc = WordPiece(str(vocab))
        # basic tokenization lowercases + splits punctuation; greedy
        # longest-match-first resolves subwords with ## continuations
        assert enc.encode("Hello, world!") == [5, 6, 9, 7]
        assert enc.encode("unaffordable tokenization.") == [
            10, 11, 12, 13, 14, 8]
        # un-tokenizable word -> [UNK]
        assert enc.encode("xyzzy") == [1]

    def test_wordpiece_requires_unk(self, tmp_path):
        vocab = tmp_path / "vocab.txt"
        vocab.write_text("hello\nworld\n")
        import sys
        sys.path.insert(0, str(REPO))
        from tools.make_token_file import WordPiece

        with pytest.raises(SystemExit, match="UNK"):
            WordPiece(str(vocab))

    def test_byte_mode_roundtrip(self, tmp_path):
        import subprocess
        import sys

        import numpy as np

        src = tmp_path / "t.txt"
        src.write_text("Hi!\n")
        out = tmp_path / "tok.npy"
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "make_token_file.py"),
             str(out), str(src)],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        ids = np.load(out)
        assert ids.tolist() == list(b"Hi!\n")
        # the printed training hint must carry the byte [MASK] id (260),
        # not the default 103 (= byte 'g') — a silent-degradation trap
        assert "--data.mask_token=260" in proc.stderr
