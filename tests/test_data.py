import numpy as np
import pytest

from distributed_tensorflow_tpu.data import (
    DataConfig,
    Prefetcher,
    SyntheticClassification,
    local_batch_size,
)


def test_synthetic_deterministic():
    cfg = DataConfig(global_batch_size=32, image_size=8, channels=1, seed=3)
    a = SyntheticClassification(cfg).batch(5)
    b = SyntheticClassification(cfg).batch(5)
    np.testing.assert_array_equal(a["image"], b["image"])
    np.testing.assert_array_equal(a["label"], b["label"])
    c = SyntheticClassification(cfg).batch(6)
    assert not np.array_equal(a["image"], c["image"])


def test_synthetic_learnable():
    """Labels come from a linear teacher → classes are balanced-ish and
    predictable from inputs (sanity for convergence tests)."""
    cfg = DataConfig(global_batch_size=512, image_size=8, num_classes=10)
    ds = SyntheticClassification(cfg)
    batch = ds.batch(0)
    # teacher recovers its own labels
    pred = np.argmax(
        batch["image"].reshape(512, -1) @ ds.teacher, axis=-1
    )
    np.testing.assert_array_equal(pred, batch["label"])
    assert len(np.unique(batch["label"])) > 3


def test_local_batch_size_divisibility(monkeypatch):
    assert local_batch_size(128) == 128  # single process
    import distributed_tensorflow_tpu.data.pipeline as pl

    monkeypatch.setattr(pl.jax, "process_count", lambda: 4)
    assert local_batch_size(128) == 32
    with pytest.raises(ValueError, match="not divisible"):
        local_batch_size(30)


def test_npz_dataset_bounded_and_offset(tmp_path):
    from distributed_tensorflow_tpu.data import NpzDataset

    n = 100
    path = str(tmp_path / "d.npz")
    np.savez(path, image=np.arange(n * 4).reshape(n, 4).astype(np.float32),
             label=np.arange(n).astype(np.int32) % 10)
    cfg = DataConfig(global_batch_size=10)
    ds = NpzDataset(path, cfg, num_batches=7)
    batches = list(ds)
    assert len(batches) == 7  # bounded, no infinite loop
    # offset stream continues where the first left off (same shuffle epoch)
    cont = list(NpzDataset(path, cfg, num_batches=3, index_offset=7))
    straight = list(NpzDataset(path, cfg, num_batches=10))
    np.testing.assert_array_equal(cont[0]["image"], straight[7]["image"])


def test_prefetcher_order_and_completion():
    src = [{"i": np.asarray(i)} for i in range(10)]
    out = list(Prefetcher(src, depth=3))
    assert [int(b["i"]) for b in out] == list(range(10))


def test_prefetcher_propagates_errors():
    def bad():
        yield {"i": np.asarray(0)}
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(Prefetcher(bad(), depth=2))


def test_prefetcher_early_stop_does_not_hang():
    def infinite():
        i = 0
        while True:
            yield {"i": np.asarray(i)}
            i += 1

    it = iter(Prefetcher(infinite(), depth=2))
    for _ in range(5):
        next(it)
    it.close()  # generator close must not deadlock the worker


@pytest.mark.slow
def test_tfdata_adapter_host_stream():
    """tf.data -> host-batch contract: numpy dicts at the local batch
    size, resume via start_index (batch skip), deterministic shuffle, and
    end-to-end through a Trainer step."""
    tf = pytest.importorskip("tensorflow")

    from distributed_tensorflow_tpu.data import tfdata

    n = 64
    images = (np.arange(n)[:, None] * np.ones((1, 4))).astype(np.float32)
    labels = (np.arange(n) % 3).astype(np.int32)

    def make_ds():
        return tf.data.Dataset.from_tensor_slices(
            {"image": images, "label": labels}
        )

    stream = tfdata.host_stream(make_ds, global_batch_size=8, repeat=False)
    batches = list(stream)
    assert len(batches) == 8
    assert batches[0]["image"].shape == (8, 4)
    assert batches[0]["image"].dtype == np.float32
    np.testing.assert_array_equal(batches[0]["label"], labels[:8])

    # start_index skips whole batches (the runner's resume offset)
    resumed = list(tfdata.host_stream(make_ds, 8, start_index=3,
                                      repeat=False))
    np.testing.assert_array_equal(resumed[0]["image"], batches[3]["image"])

    # shuffle is seeded/deterministic and preserves the set of examples
    s1 = list(tfdata.host_stream(make_ds, 8, shuffle_buffer=64, seed=7,
                                 repeat=False))
    s2 = list(tfdata.host_stream(make_ds, 8, shuffle_buffer=64, seed=7,
                                 repeat=False))
    np.testing.assert_array_equal(s1[0]["image"], s2[0]["image"])
    assert not np.array_equal(s1[0]["image"], batches[0]["image"])
