"""dtflint — every rule: positive fixture (detected, right file:line,
right rule id), negative fixture (clean code passes), suppression
fixture (marker silences it); plus the CLI exit-code contract and the
shipped-tree-is-clean acceptance gate."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from distributed_tensorflow_tpu.analysis import (
    RULES, Finding, lint_paths, lint_sources,
)
from distributed_tensorflow_tpu.analysis import fixtures

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "dtf_lint.py")

ALL_RULES = sorted(RULES)


def lint_snippet(src, path="snippet.py", rules=None):
    return lint_sources({path: textwrap.dedent(src)}, rules=rules)


# ---- the shipped fixture corpus ----------------------------------------


def test_every_rule_ships_all_three_fixtures():
    for rule in ALL_RULES:
        assert rule in fixtures.POSITIVE, rule
        assert rule in fixtures.NEGATIVE, rule
        assert rule in fixtures.SUPPRESSED, rule


@pytest.mark.parametrize("rule", ALL_RULES)
def test_positive_fixture_fires_at_marked_line(rule):
    src = fixtures.POSITIVE[rule]
    want_line = fixtures.expected_line(src)
    found = lint_sources({f"pos_{rule}.py": src})
    assert found, f"{rule}: positive fixture produced nothing"
    assert all(f.rule == rule for f in found), found
    assert any(f.line == want_line for f in found), (
        f"{rule}: fired at {[f.line for f in found]}, want {want_line}")
    # findings carry the path they were given (file:line anchoring)
    assert all(f.path == f"pos_{rule}.py" for f in found)


@pytest.mark.parametrize("rule", ALL_RULES)
def test_negative_fixture_is_clean(rule):
    found = lint_sources({"neg.py": fixtures.NEGATIVE[rule]})
    assert found == [], [f.format() for f in found]


@pytest.mark.parametrize("rule", ALL_RULES)
def test_suppression_comment_silences(rule):
    found = lint_sources({"sup.py": fixtures.SUPPRESSED[rule]})
    assert found == [], [f.format() for f in found]


def test_file_level_suppression():
    src = ("# dtflint: disable-file=exception-hygiene\n"
           + fixtures.POSITIVE["exception-hygiene"])
    assert lint_sources({"f.py": src}) == []


def test_self_check_green():
    assert fixtures.self_check() == []


# ---- rule-specific behaviors beyond the basic corpus -------------------


def test_host_sync_step_name_convention():
    # train_step is jitted by a factory in ANOTHER module; the naming
    # convention must make it reachable without a local jax.jit
    found = lint_snippet(
        """
        import numpy as onp

        def train_step(state, batch):
            host = onp.asarray(batch["x"])
            return state, {"x": host}
        """,
        rules=["host-sync-in-step"],
    )
    assert len(found) == 1 and found[0].rule == "host-sync-in-step"
    assert "asarray" in found[0].message


def test_host_sync_transitive_helper_and_item():
    found = lint_snippet(
        """
        import jax

        def helper(x):
            return x.mean().item()

        @jax.jit
        def decode(tokens):
            return helper(tokens)

        fast = jax.jit(decode)
        """,
        rules=["host-sync-in-step"],
    )
    # helper is reachable from the jitted decode
    assert [f.rule for f in found] == ["host-sync-in-step"]
    assert ".item()" in found[0].message


def test_host_sync_float_on_constant_is_static_config():
    found = lint_snippet(
        """
        import jax

        @jax.jit
        def train_step(state, batch):
            eps = float("1e-6")
            return state, eps
        """,
        rules=["host-sync-in-step"],
    )
    assert found == []


def test_donation_framework_factory_convention():
    # jit_prefill's donate_argnums lives in serve/decode.py — the rule
    # must know the factory contract without seeing that module
    found = lint_snippet(
        """
        from distributed_tensorflow_tpu.serve import decode as decode_lib

        class Eng:
            def __init__(self, model):
                self._prefill = decode_lib.jit_prefill(model)

            def bad(self, params, cache, toks):
                logits, new_cache = self._prefill(params, cache, 0, toks, 3)
                stale = cache.k  # the donated pytree
                return logits, stale
        """,
        rules=["donation-after-use"],
    )
    assert len(found) == 1
    assert "'cache'" in found[0].message


def test_donation_same_line_rebind_is_clean():
    found = lint_snippet(
        """
        import jax

        def _step(s, b):
            return s

        step = jax.jit(_step, donate_argnums=(0,))

        class T:
            def fit(self, batch):
                self.state, metrics = step(self.state, batch)
                return self.state
        """,
        rules=["donation-after-use"],
    )
    assert found == []


def test_lock_discipline_prefix_registry_get_regression():
    # the exact pre-fix Registry.get shape: lock-free dict read while
    # merge() inserts under the lock (fixed in this PR)
    found = lint_snippet(
        """
        import threading

        class Registry:
            def __init__(self):
                self._metrics = {}
                self._lock = threading.Lock()

            def register(self, key, m):
                with self._lock:
                    self._metrics[key] = m

            def get(self, key):
                return self._metrics.get(key)
        """,
        rules=["lock-discipline"],
    )
    assert len(found) == 1 and "_metrics" in found[0].message


def test_lock_discipline_unlocked_helper_convention():
    found = lint_snippet(
        """
        import threading

        class Registry:
            def __init__(self):
                self._metrics = {}
                self._lock = threading.Lock()

            def register(self, key, m):
                with self._lock:
                    self._metrics[key] = m

            def _dump_unlocked(self):
                return dict(self._metrics)

            def dump(self):
                with self._lock:
                    return self._dump_unlocked()
        """,
        rules=["lock-discipline"],
    )
    assert found == []


def test_vocab_metric_name_must_be_documented():
    path = "distributed_tensorflow_tpu/serve/fake_engine.py"
    found = lint_sources({path: textwrap.dedent(
        """
        class E:
            def __init__(self, r):
                self._m = r.counter("serve_undocumented_total", "nope")
        """
    )}, rules=["closed-vocab"])
    assert len(found) == 1 and "docs/observability.md" in found[0].message
    # the same registration OUTSIDE the package (tools, tests) is fine:
    # smoke checks register scratch names
    assert lint_sources({"tools/fake_check.py": textwrap.dedent(
        """
        def main(r):
            r.counter("scratch_smoke_total", "x").inc()
        """
    )}, rules=["closed-vocab"]) == []


def test_vocab_single_mfu_multiplier_site():
    src = """
    from distributed_tensorflow_tpu.utils import flops as flops_lib

    def my_mfu(fwd, sps):
        return fwd * flops_lib.train_flops_multiplier() * sps
    """
    found = lint_sources(
        {"tools/fake_bench.py": textwrap.dedent(src)},
        rules=["closed-vocab"])
    assert len(found) == 1 and "ONE site" in found[0].message
    # the real site is allowed
    assert lint_sources(
        {"distributed_tensorflow_tpu/obs/goodput.py": textwrap.dedent(src)},
        rules=["closed-vocab"]) == []


def test_vocab_waste_cause():
    found = lint_snippet(
        """
        from distributed_tensorflow_tpu.obs import goodput

        def lose_time(reg):
            goodput.note_wasted("bikeshedding", 1.0, registry=reg)
        """,
        rules=["closed-vocab"],
    )
    assert len(found) == 1 and "WASTE_CAUSES" in found[0].message


def test_exception_seam_narrow_silent_flagged():
    seam = "distributed_tensorflow_tpu/resilience/fake_seam.py"
    src = """
    def restore(path):
        try:
            return open(path).read()
        except OSError:
            pass
    """
    found = lint_sources({seam: textwrap.dedent(src)},
                         rules=["exception-hygiene"])
    assert len(found) == 1 and "seam" in found[0].message
    # identical code outside the seams is accepted (best-effort cleanup)
    assert lint_sources({"distributed_tensorflow_tpu/utils/fake.py":
                         textwrap.dedent(src)},
                        rules=["exception-hygiene"]) == []


def test_donation_taint_never_crosses_scope_boundaries():
    # a closure's same-named variable is a DIFFERENT binding, and line
    # order says nothing about execution order across scopes: exactly
    # one finding (the inner use-after-donate), nothing on the outer
    # call that textually follows it
    found = lint_snippet(
        """
        import jax

        def _step(s, b):
            return s

        step = jax.jit(_step, donate_argnums=(0,))

        def outer(state, batch):
            def inner(state, batch):
                new = step(state, batch)
                print(state.params)
                return new
            return inner(state, batch)
        """,
        rules=["donation-after-use"],
    )
    assert len(found) == 1, [f.format() for f in found]
    assert found[0].line == 12  # the inner print, once


def test_suppression_markers_inside_strings_are_inert():
    # a disable-file marker in a DOCSTRING must not disarm the rule —
    # only real comment tokens count (the silent-rot hole otherwise)
    src = (
        '"""docs quoting the syntax: # dtflint: disable-file=lock-discipline"""\n'
        + fixtures.POSITIVE["lock-discipline"]
    )
    found = lint_sources({"doc.py": src})
    assert [f.rule for f in found] == ["lock-discipline"]


def test_cli_is_stdlib_only():
    """The linter must run without the framework: no jax, no numpy, no
    distributed_tensorflow_tpu package import (whose __init__ pulls
    both and runs the chip-lock pin side effect)."""
    code = (
        "import sys, runpy\n"
        f"sys.argv = ['dtf_lint.py', '--list-rules']\n"
        "try:\n"
        f"    runpy.run_path({LINT!r}, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "for mod in ('jax', 'numpy', 'distributed_tensorflow_tpu'):\n"
        "    assert mod not in sys.modules, f'linter imported {mod}'\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr


def test_finding_format_and_json():
    f = Finding("closed-vocab", "a/b.py", 12, 4, "boom")
    assert f.format() == "a/b.py:12:4: closed-vocab: boom"
    assert f.to_json() == {"rule": "closed-vocab", "path": "a/b.py",
                           "line": 12, "col": 4, "message": "boom"}


def test_unknown_rule_name_raises():
    with pytest.raises(KeyError):
        lint_snippet("x = 1", rules=["no-such-rule"])


# ---- CLI exit-code contract + acceptance gate --------------------------


def _run_cli(*args, cwd=REPO):
    return subprocess.run([sys.executable, LINT, *args],
                          capture_output=True, text=True, timeout=120,
                          cwd=cwd)


def test_cli_flags_injected_fixture_with_rule_and_location(tmp_path):
    """The acceptance contract: inject any shipped positive fixture into
    a linted tree → non-zero exit naming the rule id and file:line."""
    pkg = tmp_path / "victim"
    pkg.mkdir()
    (pkg / "clean.py").write_text("x = 1\n")
    for rule, src in fixtures.POSITIVE.items():
        bad = pkg / f"bad_{rule.replace('-', '_')}.py"
        bad.write_text(src)
        want_line = fixtures.expected_line(src)
        proc = _run_cli("--strict", str(pkg))
        assert proc.returncode == 1, (rule, proc.stdout, proc.stderr)
        assert f"{bad}:{want_line}" in proc.stdout, (rule, proc.stdout)
        assert f" {rule}: " in proc.stdout, (rule, proc.stdout)
        bad.unlink()
    proc = _run_cli("--strict", str(pkg))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(fixtures.POSITIVE["exception-hygiene"])
    proc = _run_cli("--json", str(bad))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload and payload[0]["rule"] == "exception-hygiene"
    assert payload[0]["line"] == fixtures.expected_line(
        fixtures.POSITIVE["exception-hygiene"])


def test_cli_usage_errors():
    assert _run_cli().returncode == 2  # no paths
    assert _run_cli("--rules", "bogus", "tools").returncode == 2
    assert _run_cli("/no/such/path").returncode == 2


def test_cli_self_check_green():
    proc = _run_cli("--self-check")
    assert proc.returncode == 0, proc.stderr
    assert "self-check OK" in proc.stderr


def test_shipped_tree_is_clean():
    """The CI gate's exact invocation must pass on the shipped tree —
    every violation the new rules found was fixed (or carries a
    reviewed suppression)."""
    found = lint_paths([
        os.path.join(REPO, "distributed_tensorflow_tpu"),
        os.path.join(REPO, "tools"),
        os.path.join(REPO, "bench.py"),
    ])
    assert found == [], "\n".join(f.format() for f in found)
