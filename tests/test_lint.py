"""dtflint — every rule: positive fixture (detected, right file:line,
right rule id), negative fixture (clean code passes), suppression
fixture (marker silences it); plus the CLI exit-code contract and the
shipped-tree-is-clean acceptance gate."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from distributed_tensorflow_tpu.analysis import (
    RULES, Finding, lint_paths, lint_sources,
)
from distributed_tensorflow_tpu.analysis import fixtures

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "dtf_lint.py")

ALL_RULES = sorted(RULES)


def lint_snippet(src, path="snippet.py", rules=None):
    return lint_sources({path: textwrap.dedent(src)}, rules=rules)


# ---- the shipped fixture corpus ----------------------------------------


def test_every_rule_ships_all_three_fixtures():
    for rule in ALL_RULES:
        assert rule in fixtures.POSITIVE, rule
        assert rule in fixtures.NEGATIVE, rule
        assert rule in fixtures.SUPPRESSED, rule


@pytest.mark.parametrize("rule", ALL_RULES)
def test_positive_fixture_fires_at_marked_line(rule):
    src = fixtures.POSITIVE[rule]
    want_line = fixtures.expected_line(src)
    path = fixtures.fixture_path(rule, "positive")
    found = lint_sources({path: src})
    assert found, f"{rule}: positive fixture produced nothing"
    assert all(f.rule == rule for f in found), found
    assert any(f.line == want_line for f in found), (
        f"{rule}: fired at {[f.line for f in found]}, want {want_line}")
    # findings carry the path they were given (file:line anchoring)
    assert all(f.path == path for f in found)


@pytest.mark.parametrize("rule", ALL_RULES)
def test_negative_fixture_is_clean(rule):
    found = lint_sources(
        {fixtures.fixture_path(rule, "negative"): fixtures.NEGATIVE[rule]})
    assert found == [], [f.format() for f in found]


@pytest.mark.parametrize("rule", ALL_RULES)
def test_suppression_comment_silences(rule):
    found = lint_sources(
        {fixtures.fixture_path(rule, "suppressed"):
         fixtures.SUPPRESSED[rule]})
    assert found == [], [f.format() for f in found]


def test_file_level_suppression():
    src = ("# dtflint: disable-file=exception-hygiene\n"
           + fixtures.POSITIVE["exception-hygiene"])
    assert lint_sources({"f.py": src}) == []


def test_self_check_green():
    assert fixtures.self_check() == []


# ---- rule-specific behaviors beyond the basic corpus -------------------


def test_host_sync_step_name_convention():
    # train_step is jitted by a factory in ANOTHER module; the naming
    # convention must make it reachable without a local jax.jit
    found = lint_snippet(
        """
        import numpy as onp

        def train_step(state, batch):
            host = onp.asarray(batch["x"])
            return state, {"x": host}
        """,
        rules=["host-sync-in-step"],
    )
    assert len(found) == 1 and found[0].rule == "host-sync-in-step"
    assert "asarray" in found[0].message


def test_host_sync_transitive_helper_and_item():
    found = lint_snippet(
        """
        import jax

        def helper(x):
            return x.mean().item()

        @jax.jit
        def decode(tokens):
            return helper(tokens)

        fast = jax.jit(decode)
        """,
        rules=["host-sync-in-step"],
    )
    # helper is reachable from the jitted decode
    assert [f.rule for f in found] == ["host-sync-in-step"]
    assert ".item()" in found[0].message


def test_host_sync_float_on_constant_is_static_config():
    found = lint_snippet(
        """
        import jax

        @jax.jit
        def train_step(state, batch):
            eps = float("1e-6")
            return state, eps
        """,
        rules=["host-sync-in-step"],
    )
    assert found == []


def test_donation_framework_factory_convention():
    # jit_prefill's donate_argnums lives in serve/decode.py — the rule
    # must know the factory contract without seeing that module
    found = lint_snippet(
        """
        from distributed_tensorflow_tpu.serve import decode as decode_lib

        class Eng:
            def __init__(self, model):
                self._prefill = decode_lib.jit_prefill(model)

            def bad(self, params, cache, toks):
                logits, new_cache = self._prefill(params, cache, 0, toks, 3)
                stale = cache.k  # the donated pytree
                return logits, stale
        """,
        rules=["donation-after-use"],
    )
    assert len(found) == 1
    assert "'cache'" in found[0].message


def test_donation_same_line_rebind_is_clean():
    found = lint_snippet(
        """
        import jax

        def _step(s, b):
            return s

        step = jax.jit(_step, donate_argnums=(0,))

        class T:
            def fit(self, batch):
                self.state, metrics = step(self.state, batch)
                return self.state
        """,
        rules=["donation-after-use"],
    )
    assert found == []


def test_lock_discipline_prefix_registry_get_regression():
    # the exact pre-fix Registry.get shape: lock-free dict read while
    # merge() inserts under the lock (fixed in this PR)
    found = lint_snippet(
        """
        import threading

        class Registry:
            def __init__(self):
                self._metrics = {}
                self._lock = threading.Lock()

            def register(self, key, m):
                with self._lock:
                    self._metrics[key] = m

            def get(self, key):
                return self._metrics.get(key)
        """,
        rules=["lock-discipline"],
    )
    assert len(found) == 1 and "_metrics" in found[0].message


def test_lock_discipline_unlocked_helper_convention():
    found = lint_snippet(
        """
        import threading

        class Registry:
            def __init__(self):
                self._metrics = {}
                self._lock = threading.Lock()

            def register(self, key, m):
                with self._lock:
                    self._metrics[key] = m

            def _dump_unlocked(self):
                return dict(self._metrics)

            def dump(self):
                with self._lock:
                    return self._dump_unlocked()
        """,
        rules=["lock-discipline"],
    )
    assert found == []


def test_vocab_metric_name_must_be_documented():
    path = "distributed_tensorflow_tpu/serve/fake_engine.py"
    found = lint_sources({path: textwrap.dedent(
        """
        class E:
            def __init__(self, r):
                self._m = r.counter("serve_undocumented_total", "nope")
        """
    )}, rules=["closed-vocab"])
    assert len(found) == 1 and "docs/observability.md" in found[0].message
    # the same registration OUTSIDE the package (tools, tests) is fine:
    # smoke checks register scratch names
    assert lint_sources({"tools/fake_check.py": textwrap.dedent(
        """
        def main(r):
            r.counter("scratch_smoke_total", "x").inc()
        """
    )}, rules=["closed-vocab"]) == []


def test_vocab_single_mfu_multiplier_site():
    src = """
    from distributed_tensorflow_tpu.utils import flops as flops_lib

    def my_mfu(fwd, sps):
        return fwd * flops_lib.train_flops_multiplier() * sps
    """
    found = lint_sources(
        {"tools/fake_bench.py": textwrap.dedent(src)},
        rules=["closed-vocab"])
    assert len(found) == 1 and "ONE site" in found[0].message
    # the real site is allowed
    assert lint_sources(
        {"distributed_tensorflow_tpu/obs/goodput.py": textwrap.dedent(src)},
        rules=["closed-vocab"]) == []


def test_vocab_waste_cause():
    found = lint_snippet(
        """
        from distributed_tensorflow_tpu.obs import goodput

        def lose_time(reg):
            goodput.note_wasted("bikeshedding", 1.0, registry=reg)
        """,
        rules=["closed-vocab"],
    )
    assert len(found) == 1 and "WASTE_CAUSES" in found[0].message


def test_exception_seam_narrow_silent_flagged():
    seam = "distributed_tensorflow_tpu/resilience/fake_seam.py"
    src = """
    def restore(path):
        try:
            return open(path).read()
        except OSError:
            pass
    """
    found = lint_sources({seam: textwrap.dedent(src)},
                         rules=["exception-hygiene"])
    assert len(found) == 1 and "seam" in found[0].message
    # identical code outside the seams is accepted (best-effort cleanup)
    assert lint_sources({"distributed_tensorflow_tpu/utils/fake.py":
                         textwrap.dedent(src)},
                        rules=["exception-hygiene"]) == []


def test_donation_taint_never_crosses_scope_boundaries():
    # a closure's same-named variable is a DIFFERENT binding, and line
    # order says nothing about execution order across scopes: exactly
    # one finding (the inner use-after-donate), nothing on the outer
    # call that textually follows it
    found = lint_snippet(
        """
        import jax

        def _step(s, b):
            return s

        step = jax.jit(_step, donate_argnums=(0,))

        def outer(state, batch):
            def inner(state, batch):
                new = step(state, batch)
                print(state.params)
                return new
            return inner(state, batch)
        """,
        rules=["donation-after-use"],
    )
    assert len(found) == 1, [f.format() for f in found]
    assert found[0].line == 12  # the inner print, once


# ---- the v2 cross-module engine (analysis/callgraph.py) ----------------


HELPER_MOD = """
def helper(x):
    return x.mean().item()
"""

STEP_MOD = """
import jax

from helper_mod import helper


@jax.jit
def decode(tokens):
    return helper(tokens)
"""


def test_cross_module_reachability_v1_provably_missed():
    """A step fn in one module calling a host-syncing helper in
    another: the helper module ALONE is clean (nothing jit-roots it —
    exactly the v1 per-module blind spot), but linted together the
    finding lands in the helper's file."""
    alone = lint_sources({"helper_mod.py": HELPER_MOD},
                         rules=["host-sync-in-step"])
    assert alone == [], [f.format() for f in alone]

    both = lint_sources(
        {"helper_mod.py": HELPER_MOD, "step_mod.py": STEP_MOD},
        rules=["host-sync-in-step"])
    assert len(both) == 1, [f.format() for f in both]
    assert both[0].path == "helper_mod.py"
    assert ".item()" in both[0].message
    # the finding explains WHERE jit-ness came from
    assert "step_mod" in both[0].message


def test_cross_module_jit_wrap_and_partial():
    # jax.jit(partial(fn, model)) in one module roots fn in another,
    # through a module alias — the serve/decode.py factory shape
    found = lint_sources({
        "kernels.py": """
import numpy as np


def prefill_impl(model, params, tokens):
    return np.asarray(tokens)
""",
        "factory.py": """
import jax
from functools import partial

import kernels


def make(model):
    return jax.jit(partial(kernels.prefill_impl, model),
                   donate_argnums=(1,))
""",
    }, rules=["host-sync-in-step"])
    assert len(found) == 1 and found[0].path == "kernels.py"
    assert "asarray" in found[0].message


def test_cross_module_donation_via_import():
    # the donating binding lives in another module; the import carries
    # its donate_argnums with it
    srcs = {
        "steplib.py": """
import jax


def _step(state, batch):
    return state


jitted_step = jax.jit(_step, donate_argnums=(0,))
""",
        "driver.py": """
from steplib import jitted_step


def run_once(state, batch):
    new_state = jitted_step(state, batch)
    print(state.params)
    return new_state
""",
    }
    found = lint_sources(srcs, rules=["donation-after-use"])
    assert len(found) == 1 and found[0].path == "driver.py"
    assert "'state'" in found[0].message
    # module-alias call form resolves too
    srcs["driver.py"] = """
import steplib


def run_once(state, batch):
    new_state = steplib.jitted_step(state, batch)
    print(state.params)
    return new_state
"""
    found = lint_sources(srcs, rules=["donation-after-use"])
    assert len(found) == 1 and found[0].path == "driver.py"


def test_cross_module_relative_imports_resolve_in_package():
    # the real package layout: a step helper under the package root,
    # reached through `from ..ops import helpers`
    found = lint_sources({
        "distributed_tensorflow_tpu/ops/helpers.py": """
def fetch_scalar(x):
    return float(x.sum())
""",
        "distributed_tensorflow_tpu/serve/dec.py": """
import jax

from ..ops import helpers


def decode_step(cache, tokens):
    return helpers.fetch_scalar(tokens)
""",
    }, rules=["host-sync-in-step"])
    assert len(found) == 1
    assert found[0].path == "distributed_tensorflow_tpu/ops/helpers.py"


def test_step_name_contract_still_roots_without_jit():
    # the v1 naming-convention behavior survives the engine swap
    found = lint_snippet(
        """
        import numpy as onp

        def train_step(state, batch):
            host = onp.asarray(batch["x"])
            return state, {"x": host}
        """,
        rules=["host-sync-in-step"],
    )
    assert len(found) == 1 and "asarray" in found[0].message


# ---- wall-clock-in-seam ------------------------------------------------


def test_wall_clock_fires_only_in_seams():
    src = """
    import time

    def build(index):
        return {"t": time.monotonic()}
    """
    seam = lint_sources(
        {"distributed_tensorflow_tpu/data/records2.py":
         textwrap.dedent(src)}, rules=["wall-clock-in-seam"])
    assert len(seam) == 1 and "wall clock" in seam[0].message
    # identical code outside the seams: telemetry's whole job
    assert lint_sources(
        {"distributed_tensorflow_tpu/obs/clocky.py": textwrap.dedent(src)},
        rules=["wall-clock-in-seam"]) == []


def test_wall_clock_seams_are_segment_anchored():
    src = """
    import os
    import time

    def f():
        return time.time(), os.urandom(4)
    """
    # package-relative invocation (cwd inside the package) still a seam
    rel = lint_sources({"resilience/x.py": textwrap.dedent(src)},
                       rules=["wall-clock-in-seam"])
    assert len(rel) == 2, [f.format() for f in rel]
    # look-alike segments are NOT seams: neither strict nor scaffolding
    for path in ("myresilience/x.py", "latests/x.py", "testdata/x.py"):
        found = lint_sources({path: textwrap.dedent(src)},
                             rules=["wall-clock-in-seam"])
        assert found == [], (path, [f.format() for f in found])


def test_wall_clock_seeded_rng_and_injectable_default_clean():
    found = lint_sources({
        "distributed_tensorflow_tpu/data/aug2.py": """
import time

import numpy as np


def make(seed, index, clock=time.monotonic):
    rng = np.random.RandomState(seed + index)
    r2 = np.random.default_rng(seed)
    return rng.uniform(size=(2,)), r2, clock()
""",
    }, rules=["wall-clock-in-seam"])
    assert found == [], [f.format() for f in found]


def test_wall_clock_unseeded_randomness_and_aliases():
    found = lint_sources({
        "distributed_tensorflow_tpu/resilience/jitterbug.py": """
import random
from time import monotonic as now

import numpy as np


def schedule():
    a = random.random()
    b = np.random.default_rng()
    c = now()
    return a, b, c
""",
    }, rules=["wall-clock-in-seam"])
    msgs = [f.message for f in found]
    assert len(found) == 3, msgs
    assert any("random.random" in m for m in msgs)
    assert any("default_rng() without a seed" in m for m in msgs)
    assert any("wall clock" in m for m in msgs)


def test_wall_clock_test_scaffolding_tier_relaxed():
    # tests/: deadlines are process control (clean); entropy is not
    src = """
    import os
    import time

    def wait_and_corrupt(path):
        deadline = time.monotonic() + 5
        return os.urandom(8), deadline
    """
    found = lint_sources({"tests/test_fake.py": textwrap.dedent(src)},
                         rules=["wall-clock-in-seam"])
    assert len(found) == 1 and "urandom" in found[0].message
    # chaos_worker is the bit-identity oracle: full strictness
    strict = lint_sources({"tests/chaos_worker.py": textwrap.dedent(src)},
                          rules=["wall-clock-in-seam"])
    assert len(strict) == 2, [f.format() for f in strict]


# ---- atomic-durable-write ----------------------------------------------


def test_durable_write_keyword_trigger_and_atomic_shape():
    bare = """
    import json
    import os

    def dump_quarantine(directory, doc):
        path = os.path.join(directory, "quarantine.json")
        with open(path, "w") as f:
            json.dump(doc, f)
    """
    found = lint_sources({"anywhere.py": textwrap.dedent(bare)},
                         rules=["atomic-durable-write"])
    assert len(found) == 1 and "tmp" in found[0].message
    atomic = """
    import json
    import os

    def dump_quarantine(directory, doc):
        path = os.path.join(directory, "quarantine.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    """
    assert lint_sources({"anywhere.py": textwrap.dedent(atomic)},
                        rules=["atomic-durable-write"]) == []


def test_durable_write_module_trigger_and_append_exempt():
    # in a durable-state module EVERY truncating write is in scope,
    # no keyword needed — but append-mode streams stay exempt
    src = """
    def note(path, text):
        with open(path, "w") as f:
            f.write(text)

    def stream(path, text):
        with open(path, "a") as f:
            f.write(text)
    """
    found = lint_sources(
        {"distributed_tensorflow_tpu/resilience/fleet.py":
         textwrap.dedent(src)}, rules=["atomic-durable-write"])
    assert len(found) == 1 and found[0].line == 3
    # same code in a neutral module without durable keywords: clean
    assert lint_sources({"distributed_tensorflow_tpu/utils/scratch.py":
                         textwrap.dedent(src)},
                        rules=["atomic-durable-write"]) == []


def test_durable_write_judged_per_write_not_per_function():
    # a bare in-place manifest write must NOT be blessed by a correct
    # atomic write of a DIFFERENT file in the same function
    src = """
    import json
    import os

    def save_checkpoint_meta(d, manifest, extra):
        with open(os.path.join(d, "MANIFEST.json"), "w") as f:
            json.dump(manifest, f)
        tmp = os.path.join(d, "extra.json") + ".tmp"
        with open(tmp, "w") as f:
            json.dump(extra, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(d, "extra.json"))
    """
    found = lint_sources({"anywhere.py": textwrap.dedent(src)},
                         rules=["atomic-durable-write"])
    assert len(found) == 1 and found[0].line == 6, (
        [f.format() for f in found])


# ---- metric-naming -----------------------------------------------------


def test_metric_naming_counter_and_histogram_shapes():
    found = lint_snippet(
        """
        def setup(r):
            a = r.counter("serve_retries", "retries")
            b = r.histogram("serve_wait", "queue wait in seconds")
            c = r.gauge("serve_depth_total", "queue depth")
            d = r.histogram("serve_lat_ms", "latency")
        """,
        rules=["metric-naming"],
    )
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 4, msgs
    assert "_total" in msgs and "_seconds" in msgs and "sub-second" in msgs


def test_metric_naming_subsecond_token_not_just_suffix():
    # "ms" hidden before the counter suffix must still be flagged
    found = lint_snippet(
        """
        def setup(r):
            a = r.counter("serve_lat_ms_total", "latency")
        """,
        rules=["metric-naming"],
    )
    assert len(found) == 1 and "sub-second" in found[0].message
    # ...but ordinary words containing the letters are fine
    clean = lint_snippet(
        """
        def setup(r):
            a = r.counter("serve_status_checks_total", "status probes")
        """,
        rules=["metric-naming"],
    )
    assert clean == [], [f.format() for f in clean]


def test_metric_naming_resolves_constants_and_accepts_clean():
    found = lint_snippet(
        """
        STEPS_TOTAL = "train_widget_steps_total"

        def setup(r):
            a = r.counter(STEPS_TOTAL, "steps")
            b = r.histogram("widget_step_seconds", "wall seconds per step")
            c = r.gauge("widget_occupancy", "slots in use")
        """,
        rules=["metric-naming"],
    )
    assert found == [], [f.format() for f in found]


def test_metric_naming_kind_must_match_docs_table():
    # goodput_fraction is documented as a gauge; registering it as a
    # counter is vocabulary drift (and a shape violation to boot)
    found = lint_snippet(
        """
        def setup(r):
            g = r.counter("goodput_fraction", "productive share")
        """,
        rules=["metric-naming"],
    )
    assert any("documents it as a gauge" in f.message for f in found), (
        [f.format() for f in found])


def test_suppression_markers_inside_strings_are_inert():
    # a disable-file marker in a DOCSTRING must not disarm the rule —
    # only real comment tokens count (the silent-rot hole otherwise)
    src = (
        '"""docs quoting the syntax: # dtflint: disable-file=lock-discipline"""\n'
        + fixtures.POSITIVE["lock-discipline"]
    )
    found = lint_sources({"doc.py": src})
    assert [f.rule for f in found] == ["lock-discipline"]


def test_cli_is_stdlib_only():
    """The linter must run without the framework: no jax, no numpy, no
    distributed_tensorflow_tpu package import (whose __init__ pulls
    both and runs the chip-lock pin side effect)."""
    code = (
        "import sys, runpy\n"
        f"sys.argv = ['dtf_lint.py', '--list-rules']\n"
        "try:\n"
        f"    runpy.run_path({LINT!r}, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "for mod in ('jax', 'numpy', 'distributed_tensorflow_tpu'):\n"
        "    assert mod not in sys.modules, f'linter imported {mod}'\n"
    )
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr


def test_finding_format_and_json():
    f = Finding("closed-vocab", "a/b.py", 12, 4, "boom")
    assert f.format() == "a/b.py:12:4: closed-vocab: boom"
    assert f.to_json() == {"rule": "closed-vocab", "path": "a/b.py",
                           "line": 12, "col": 4, "message": "boom"}


def test_unknown_rule_name_raises():
    with pytest.raises(KeyError):
        lint_snippet("x = 1", rules=["no-such-rule"])


# ---- CLI exit-code contract + acceptance gate --------------------------


def _run_cli(*args, cwd=REPO):
    return subprocess.run([sys.executable, LINT, *args],
                          capture_output=True, text=True, timeout=120,
                          cwd=cwd)


def test_cli_flags_injected_fixture_with_rule_and_location(tmp_path):
    """The acceptance contract: inject any shipped positive fixture into
    a linted tree → non-zero exit naming the rule id and file:line.
    Seam rules inject at their seam-shaped relative path
    (fixtures.injection_path)."""
    pkg = tmp_path / "victim"
    pkg.mkdir()
    (pkg / "clean.py").write_text("x = 1\n")
    for rule, src in fixtures.POSITIVE.items():
        bad = pkg / fixtures.injection_path(rule)
        bad.parent.mkdir(parents=True, exist_ok=True)
        bad.write_text(src)
        want_line = fixtures.expected_line(src)
        proc = _run_cli("--strict", str(pkg))
        assert proc.returncode == 1, (rule, proc.stdout, proc.stderr)
        assert f"{bad}:{want_line}" in proc.stdout, (rule, proc.stdout)
        assert f" {rule}: " in proc.stdout, (rule, proc.stdout)
        bad.unlink()
    proc = _run_cli("--strict", str(pkg))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(fixtures.POSITIVE["exception-hygiene"])
    proc = _run_cli("--json", str(bad))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload and payload[0]["rule"] == "exception-hygiene"
    assert payload[0]["line"] == fixtures.expected_line(
        fixtures.POSITIVE["exception-hygiene"])


def test_cli_usage_errors():
    assert _run_cli().returncode == 2  # no paths
    assert _run_cli("--rules", "bogus", "tools").returncode == 2
    assert _run_cli("/no/such/path").returncode == 2


def test_cli_self_check_green():
    proc = _run_cli("--self-check")
    assert proc.returncode == 0, proc.stderr
    assert "self-check OK" in proc.stderr


def test_cli_changed_only_reports_only_the_diff(tmp_path):
    """--changed-only lints the whole tree for cross-module context but
    reports (and exits on) only files changed vs --base — a committed
    violation stays out of the report, an uncommitted one fails it."""
    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       capture_output=True,
                       env={**os.environ,
                            "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                            "GIT_COMMITTER_NAME": "t",
                            "GIT_COMMITTER_EMAIL": "t@t"})

    git("init", "-q")
    committed_bad = tmp_path / "old_violation.py"
    committed_bad.write_text(fixtures.POSITIVE["exception-hygiene"])
    (tmp_path / "clean.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-qm", "seed")

    # nothing changed: fast-path success, committed violation not relinted
    proc = _run_cli("--changed-only", "--strict", ".", cwd=tmp_path)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no python/docs files changed" in proc.stderr

    # an uncommitted (untracked) violation IS reported; the committed
    # one still is not
    new_bad = tmp_path / "new_violation.py"
    new_bad.write_text(fixtures.POSITIVE["lock-discipline"])
    proc = _run_cli("--changed-only", "--strict", ".", cwd=tmp_path)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "new_violation.py" in proc.stdout
    assert "old_violation.py" not in proc.stdout

    # a bogus base ref is a usage error, not a silent full lint
    proc = _run_cli("--changed-only", "--base", "no-such-ref", ".",
                    cwd=tmp_path)
    assert proc.returncode == 2


def test_shipped_tree_is_clean():
    """The CI gate's exact invocation must pass on the shipped tree —
    every violation the new rules found was fixed (or carries a
    reviewed suppression)."""
    found = lint_paths([
        os.path.join(REPO, "distributed_tensorflow_tpu"),
        os.path.join(REPO, "tools"),
        os.path.join(REPO, "bench.py"),
    ])
    assert found == [], "\n".join(f.format() for f in found)


# ---- v3 partitioning family (PR 14) ------------------------------------


def test_shard_rules_table_name_must_be_unique_across_run():
    table = '''
        from jax.sharding import PartitionSpec as P
        from distributed_tensorflow_tpu.parallel.sharding import \\
            partition_rules

        T = partition_rules(
            "dup-model", ((r".*", P()),), coverage=("a/kernel",))
    '''
    found = lint_sources({
        "models/a.py": textwrap.dedent(table),
        "models/b.py": textwrap.dedent(table),
    }, rules=["shard-rules-coverage"])
    assert [f.rule for f in found] == ["shard-rules-coverage"]
    assert "already defined at models/a.py" in found[0].message
    assert found[0].path == "models/b.py"


def test_shard_rules_missing_coverage_fixture_flagged():
    found = lint_snippet('''
        from jax.sharding import PartitionSpec as P
        from distributed_tensorflow_tpu.parallel.sharding import \\
            partition_rules

        T = partition_rules("no-cov", ((r".*", P()),))
    ''', rules=["shard-rules-coverage"])
    assert len(found) == 1
    assert "ships no coverage fixture" in found[0].message


def test_shard_rules_catch_all_constant_resolved_not_opaque():
    """A symbolic sharding.CATCH_ALL final row must not disable the
    simulation — the dead rule hiding above it is still found."""
    found = lint_snippet('''
        from jax.sharding import PartitionSpec as P
        from distributed_tensorflow_tpu.parallel import sharding

        T = sharding.partition_rules(
            "cdm",
            (
                (r"kernel$", P(None, "model")),
                (r"kernle$", P("model")),
                (sharding.CATCH_ALL, sharding.REPLICATED),
            ),
            coverage=("layer/kernel", "layer/bias"),
        )
    ''', rules=["shard-rules-coverage"])
    assert len(found) == 1
    assert "'kernle$'" in found[0].message
    assert "dead rule" in found[0].message


def test_shard_rules_shadowed_row_is_dead_the_wide_deep_regression():
    """The pre-engine wide&deep bug, now a lint error: an unanchored
    earlier row swallows every path the later row was written for."""
    found = lint_snippet('''
        from jax.sharding import PartitionSpec as P
        from distributed_tensorflow_tpu.parallel.sharding import \\
            partition_rules

        T = partition_rules(
            "wd-regression",
            (
                (r"table_\\d+", P("model", None)),
                (r"wide_table_\\d+", P("model", None)),
                (r".*", P()),
            ),
            coverage=("table_0", "wide_table_0", "deep_0/kernel"),
        )
    ''', rules=["shard-rules-coverage"])
    assert len(found) == 1
    assert "wide_table_" in found[0].message
    assert "shadowed" in found[0].message


def test_shard_rules_coverage_resolves_module_constant():
    found = lint_snippet('''
        from jax.sharding import PartitionSpec as P
        from distributed_tensorflow_tpu.parallel.sharding import \\
            partition_rules

        _COV = ("layer/kernel", "layer/bias")

        T = partition_rules(
            "const-cov", ((r"kernel$", P(None, "model")),), coverage=_COV)
    ''', rules=["shard-rules-coverage"])
    # bias path unmatched — found THROUGH the constant reference
    assert len(found) == 1
    assert "'layer/bias'" in found[0].message and "not total" in found[0].message


def test_mesh_axis_vocab_tuple_entries_and_scope():
    src = '''
        from jax.sharding import PartitionSpec as P

        GOOD = P(("data", "fsdp"), None)
        BAD = P(("data", "fsdpp"), None)
    '''
    # in scope: only the typo'd tuple entry fires
    found = lint_sources(
        {"distributed_tensorflow_tpu/train/x.py": textwrap.dedent(src)},
        rules=["mesh-axis-closed-vocab"])
    assert [f.line for f in found] == [5]
    assert "'fsdpp'" in found[0].message
    # outside the mesh-consuming dirs: silent
    assert lint_sources(
        {"distributed_tensorflow_tpu/obs/x.py": textwrap.dedent(src)},
        rules=["mesh-axis-closed-vocab"]) == []


def test_mesh_axis_collective_positional_and_keyword():
    found = lint_sources({"ops/x.py": textwrap.dedent('''
        from jax import lax

        from distributed_tensorflow_tpu.parallel import collectives as col


        def f(x):
            a = lax.psum(x, "data")            # fine
            b = col.all_reduce(x, "modell")    # typo, positional
            return lax.pmean(b, axis_name="bad_axis")
    ''')}, rules=["mesh-axis-closed-vocab"])
    assert [(f.line, "modell" in f.message or "bad_axis" in f.message)
            for f in found] == [(9, True), (10, True)]


def test_mesh_axis_silent_when_vocab_unreadable(tmp_path):
    """No mesh.py to parse (foreign tree) → stay silent, never guess."""
    found = lint_sources(
        {"parallel/x.py": 'from jax import lax\n'
                          'def f(x):\n'
                          '    return lax.psum(x, "dtaa")\n'},
        rules=["mesh-axis-closed-vocab"], root=str(tmp_path))
    assert found == []


def test_seam_bypass_carve_outs_and_scope():
    body = '''
        from jax.sharding import NamedSharding, PartitionSpec as P
        from distributed_tensorflow_tpu.utils.compat import shard_map


        def attn_rules():
            return ((r"kernel$", P(None, "model")),)


        def island(mesh, x):
            f = shard_map(lambda a: a, mesh=mesh,
                          in_specs=P("data"), out_specs=P("data"))
            return f(x)


        def bypass(mesh, x):
            import jax
            return jax.device_put(x, NamedSharding(mesh, P("data")))
    '''
    found = lint_sources(
        {"distributed_tensorflow_tpu/serve/x.py": textwrap.dedent(body)},
        rules=["sharding-seam-bypass"])
    # only the bypass function fires (NamedSharding + P on line 18)
    assert {f.line for f in found} == {18}
    assert len(found) == 2
    # the seam file itself, analysis/, and tests/ are exempt
    for exempt in ("distributed_tensorflow_tpu/parallel/sharding.py",
                   "distributed_tensorflow_tpu/analysis/x.py",
                   "tests/x.py"):
        assert lint_sources(
            {exempt: textwrap.dedent(body)},
            rules=["sharding-seam-bypass"]) == [], exempt


def test_seam_bypass_rules_table_rows_exempt():
    found = lint_sources({"distributed_tensorflow_tpu/models/m.py":
        textwrap.dedent('''
        from jax.sharding import PartitionSpec as P

        from ..parallel import sharding

        TABLE = sharding.partition_rules(
            "m", ((r"kernel$", P(None, "model")),
                  (sharding.CATCH_ALL, sharding.REPLICATED)),
            coverage=("a/kernel", "a/bias"))
    ''')}, rules=["sharding-seam-bypass"])
    assert found == []


def test_shard_rules_coverage_resolves_annotated_constant():
    """An annotated module constant (`_COV: tuple = (...)`) must not
    silently opt the table out of the simulation."""
    found = lint_snippet('''
        from jax.sharding import PartitionSpec as P
        from distributed_tensorflow_tpu.parallel.sharding import \\
            partition_rules

        _COV: tuple = ("layer/kernel", "layer/bias")

        T = partition_rules(
            "ann-cov", ((r"kernel$", P(None, "model")),), coverage=_COV)
    ''', rules=["shard-rules-coverage"])
    assert len(found) == 1
    assert "'layer/bias'" in found[0].message and "not total" in found[0].message
