"""FLOPs/MFU accounting contract (VERDICT round-1 item 2, SURVEY.md §6).

Every workload's declared ``flops_per_step`` must be FORWARD-only model
arithmetic. Oracle: XLA's own cost analysis of the jitted *forward* (loss)
computation — an independent count the declaration can't copy from. A
workload that bakes the ×3 train multiplier into its declaration lands at
ratio ≈ 3 and fails loudly; an understated (e.g. fwd/3) one lands ≈ 0.33.

Measured ratios at the shrunk shapes used here (2026-07, jax 0.9 CPU):
mlp 1.00, cnn 1.09, resnet 1.08, bert 0.99, wide_deep 0.87.
"""

import jax
import pytest

from distributed_tensorflow_tpu import workloads
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.utils import config as config_lib

BATCH = 8
SHRINK = {
    "mnist_mlp": [],
    "cifar10_cnn": [],
    "resnet50_imagenet": ["--data.image_size=64"],
    "bert_pretrain": ["--data.seq_len=64"],
    "wide_deep": [],
}


SLOW_PARAMS = {"resnet50_imagenet", "bert_pretrain", "cifar10_cnn",
               "wide_deep"}  # 70s+/27s/9s/7s shapes; mnist_mlp + gpt_lm
               # keep the contract itself exercised in the fast tier


@pytest.mark.parametrize("name", [
    pytest.param(n, marks=[pytest.mark.slow] if n in SLOW_PARAMS else [])
    for n in sorted(SHRINK)
])
def test_declared_flops_are_forward_only(name):
    mod = workloads.get(name)
    cfg = config_lib.apply_overrides(
        mod.default_config(),
        [f"--data.global_batch_size={BATCH}", *SHRINK[name]],
    )
    parts = mod.build(cfg, build_mesh(MeshSpec(data=-1)))
    batch = next(iter(parts.dataset_fn(0)))
    params, mstate = parts.init_fn(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)

    lowered = jax.jit(
        lambda p, m, b: parts.loss_fn(p, m, b, rng)[0]
    ).lower(params, mstate, batch)
    from distributed_tensorflow_tpu.utils.compat import cost_analysis_dict
    xla_fwd = cost_analysis_dict(lowered.compile()).get("flops")
    if not xla_fwd or xla_fwd != xla_fwd:  # backend returned none/NaN
        pytest.skip("cost_analysis unavailable on this backend")

    ratio = parts.flops_per_step / xla_fwd
    assert 0.7 < ratio < 1.4, (
        f"{name}: declared flops_per_step is {ratio:.2f}x XLA's forward "
        f"count — the declaration must be forward-only (the ×3 train "
        f"multiplier is applied by MetricsLogger/bench, not workloads)"
    )


def test_train_multiplier_single_site():
    """The ×3 multiplier must have exactly ONE call site —
    obs/goodput.train_mfu, the shared MFU helper that MetricsLogger and
    bench.py both route through — grep-level guard against
    reintroducing it in models, workloads, or report scripts."""
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1]
    call = "flops_lib.train_flops_multiplier()"
    hits = []
    for sub in ("distributed_tensorflow_tpu", "tools"):
        for py in (root / sub).rglob("*.py"):
            if call in py.read_text():
                hits.append(py.relative_to(root).as_posix())
    hits += ["bench.py"] if call in (root / "bench.py").read_text() else []
    assert sorted(hits) == [
        "distributed_tensorflow_tpu/obs/goodput.py",
    ], hits
