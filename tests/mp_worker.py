"""Multi-process test worker (driven by tests/test_multiprocess.py).

One forked localhost process per "host": jax.distributed over CPU devices
— the MultiProcessRunner analog ($TF/python/distribute/
multi_process_runner.py:107; SURVEY.md §4.3). Scenario selected by argv.
"""

import os
import sys

# must precede any jax import in this process
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    scenario, coord, num, pid, workdir = sys.argv[1:6]
    num, pid = int(num), int(pid)

    from distributed_tensorflow_tpu.parallel import cluster

    cluster.initialize(cluster.ClusterConfig(
        coordinator_address=coord, num_processes=num, process_id=pid,
    ))
    assert jax.process_count() == num, jax.process_count()
    assert jax.device_count() == 2 * num

    if scenario == "psum":
        scenario_psum()
    elif scenario == "hybrid":
        scenario_hybrid()
    elif scenario == "divergence":
        scenario_divergence(pid)
    elif scenario == "pipeline":
        scenario_pipeline()
    elif scenario == "checkpoint":
        scenario_checkpoint(workdir, resume="--resume" in sys.argv)
    elif scenario == "preempt":
        scenario_preempt(workdir)
    else:
        raise ValueError(scenario)


def scenario_psum() -> None:
    """Global-mesh allreduce across processes: the DCN init smoke test."""
    import jax.numpy as jnp

    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from jax.sharding import NamedSharding

    mesh = build_mesh(MeshSpec(data=-1))  # all global devices
    n = mesh.size
    from jax.experimental import multihost_utils

    local = np.arange(2, dtype=np.float32) + 2 * jax.process_index()
    arr = multihost_utils.host_local_array_to_global_array(
        local, mesh, sh.batch_spec(1)
    )
    total = jax.jit(
        lambda x: jnp.sum(x),
        in_shardings=NamedSharding(mesh, sh.batch_spec(1)),
        out_shardings=NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )(arr)
    want = sum(range(2 * jax.process_count()))
    got = float(jax.device_get(total))
    assert got == want, (got, want)
    print(f"PSUM-OK {jax.process_index()} {got}", flush=True)


def scenario_hybrid() -> None:
    """2-process ICI×DCN hybrid mesh (VERDICT round-1 item 5): each
    process plays one 'slice' (dcn_data=2), runs a full train step over
    the hybrid data axis, and checks the loss agrees across hosts."""
    import jax.numpy as jnp
    import optax

    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )

    mesh = build_mesh(MeshSpec(data=4, dcn_data=2))
    assert mesh.shape["data"] == 4

    def linear_init(rng):
        return {"w": jnp.ones((4, 2)), "b": jnp.zeros((2,))}, {}

    def linear_loss(params, mstate, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, (mstate, {})

    tx = optax.sgd(0.1)
    state, specs = init_train_state(
        linear_init, tx, mesh, jax.random.PRNGKey(0)
    )
    step = jit_train_step(make_train_step(linear_loss, tx), mesh, specs)
    rng = np.random.RandomState(0)  # same seed: identical global batch halves
    local = {
        "x": rng.randn(8, 4).astype(np.float32)[
            jax.process_index() * 4:(jax.process_index() + 1) * 4],
        "y": np.zeros((4, 2), np.float32),
    }
    batch = sh.put_host_batch(mesh, local)
    state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)
    from distributed_tensorflow_tpu.utils import multihost

    multihost.assert_same_across_hosts(
        {"loss": np.asarray(loss, np.float32)}, "hybrid-loss"
    )
    print(f"HYBRID-OK {jax.process_index()} {loss:.6f}", flush=True)


def scenario_divergence(pid: int) -> None:
    """assert_same_across_hosts must trip when one host diverges."""
    from distributed_tensorflow_tpu.utils import multihost

    multihost.assert_same_across_hosts({"step": np.asarray(7)}, "agree")
    print(f"AGREE-OK {pid}", flush=True)
    try:
        multihost.assert_same_across_hosts(
            {"step": np.asarray(7 + (1 if pid == 1 else 0))}, "diverge"
        )
        print(f"DIVERGE-MISSED {pid}", flush=True)
    except AssertionError:
        print(f"DIVERGE-CAUGHT {pid}", flush=True)


def scenario_pipeline() -> None:
    """Pipeline stages on DIFFERENT hosts: dcn_pipe=2 forces the pipe
    axis across the process boundary, so every ppermute hop (activations
    stage->stage, fwd AND transposed bwd) crosses DCN. One stochastic
    (dropout) pipelined train step; loss finite and host-agreeing."""
    import jax.numpy as jnp
    import optax

    from distributed_tensorflow_tpu.models import transformer as tfm
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )

    mesh = build_mesh(MeshSpec(pipe=2, data=2, dcn_pipe=2))
    cfg = tfm.TransformerConfig(
        vocab_size=32, max_len=8, num_layers=2, d_model=16, num_heads=2,
        d_ff=32, causal=True, pre_ln=True, dropout=0.1, dtype="float32",
    )
    init_fn = tfm.make_pipelined_init_fn(cfg, n_stages=2, seq_len=8)
    specs = tfm.pipeline_param_specs(
        jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0])
    tx = optax.sgd(0.05)
    state, sspecs = init_train_state(
        init_fn, tx, mesh, jax.random.PRNGKey(0), param_specs=specs)
    step = jit_train_step(
        make_train_step(tfm.pipelined_lm_loss_fn(cfg, mesh, 2), tx,
                        StepOptions()),
        mesh, sspecs,
    )
    rng = np.random.RandomState(0)  # same seed: agreed global batch
    ids = rng.randint(0, 32, (8, 8)).astype(np.int32)
    # the data axis is INTRA-process here (pipe spans the hosts), so each
    # host's addressable shards cover every batch row: pass the full
    # pipe-replicated batch, not a per-host slice
    batch = sh.put_host_batch(mesh, {"input_ids": ids})
    state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss), loss
    from distributed_tensorflow_tpu.utils import multihost

    multihost.assert_same_across_hosts(
        {"loss": np.asarray(loss, np.float32)}, "pipeline-loss"
    )
    print(f"PIPELINE-OK {jax.process_index()} {loss:.6f}", flush=True)


def scenario_checkpoint(workdir: str, resume: bool) -> None:
    """Every host writes its shards; resume restores step + params."""
    import optax

    from distributed_tensorflow_tpu.models import MLP, MLPConfig, common
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.train import (
        CheckpointConfig, Checkpointer, StepOptions, Trainer, callbacks as cb,
        init_or_restore, jit_train_step, make_train_step,
    )

    mesh = build_mesh(MeshSpec(data=-1, fsdp=1))
    cfg = MLPConfig(hidden_sizes=(16,), num_classes=4)
    model = MLP(cfg)
    tx = optax.adam(1e-2)
    ckpt = Checkpointer(
        CheckpointConfig(directory=workdir, save_interval_steps=5,
                         async_save=False),
        mesh,
    )
    state, specs, restored = init_or_restore(
        ckpt, common.make_init_fn(model, (8,)), tx, mesh, jax.random.PRNGKey(0)
    )
    start = int(state.step)
    if resume:
        assert restored and start == 10, (restored, start)
    trainer = Trainer(
        make_train_step(common.classification_loss_fn(model), tx,
                        StepOptions()),
        state, mesh, specs, callbacks=[cb.CheckpointCallback(ckpt)],
    )

    def batches():
        rng = np.random.RandomState(0)
        while True:
            yield {
                "image": rng.randn(8, 8).astype(np.float32),
                "label": rng.randint(0, 4, 8).astype(np.int32),
            }

    # num_steps is the absolute target step (StopAtStepHook's last_step
    # semantics): resume runs from the restored step up to start+10
    state = trainer.fit(batches(), num_steps=start + 10)
    ckpt.wait()
    assert int(state.step) == start + 10, (
        int(state.step), start, trainer._stop_reason, trainer.failed
    )
    assert ckpt.latest_step() == start + 10, (
        ckpt.latest_step(), start, ckpt.manager.all_steps()
    )
    ckpt.close()
    print(f"CKPT-OK {jax.process_index()} step={int(state.step)}", flush=True)


def scenario_preempt(workdir: str) -> None:
    """Host 0 is SIGTERMed mid-run; every host must coordinate one final
    save and exit cleanly (PreemptionSaved)."""
    import optax

    from distributed_tensorflow_tpu.models import MLP, MLPConfig, common
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.train import (
        CheckpointConfig, Checkpointer, StepOptions, Trainer, callbacks as cb,
        init_or_restore, make_train_step,
    )

    mesh = build_mesh(MeshSpec(data=-1))
    cfg = MLPConfig(hidden_sizes=(16,), num_classes=4)
    model = MLP(cfg)
    tx = optax.adam(1e-2)
    ckpt = Checkpointer(
        CheckpointConfig(directory=workdir, save_interval_steps=10**6,
                         async_save=False, preemption_check_every=2),
        mesh,
    )
    state, specs, _ = init_or_restore(
        ckpt, common.make_init_fn(model, (8,)), tx, mesh, jax.random.PRNGKey(0)
    )
    trainer = Trainer(
        make_train_step(common.classification_loss_fn(model), tx,
                        StepOptions()),
        state, mesh, specs, callbacks=[cb.CheckpointCallback(ckpt)],
    )

    print(f"READY {jax.process_index()}", flush=True)  # parent sends SIGTERM

    def batches():
        rng = np.random.RandomState(0)
        import time

        while True:
            time.sleep(0.05)  # slow steps so the signal lands mid-run
            yield {
                "image": rng.randn(8, 8).astype(np.float32),
                "label": rng.randint(0, 4, 8).astype(np.int32),
            }

    # Trainer converts PreemptionSaved into a clean stop (loop.py)
    trainer.fit(batches(), num_steps=2000)
    saved = ckpt.latest_step()
    ckpt.close()
    if (not trainer.failed and saved is not None
            and "preempted" in (trainer._stop_reason or "")):
        print(f"PREEMPT-SAVED {jax.process_index()} step={saved}", flush=True)
    else:
        print(f"PREEMPT-MISSED {jax.process_index()} reason="
              f"{trainer._stop_reason!r} saved={saved}", flush=True)


if __name__ == "__main__":
    main()
