import jax
import pytest

from distributed_tensorflow_tpu.parallel import (
    AXIS_NAMES,
    MeshSpec,
    build_mesh,
    describe,
    mesh_axis_size,
    rescale_for_world,
    single_device_mesh,
)


def test_rescale_for_world_batch_axes_only():
    """Elastic resize seam: only the batch axes absorb a worker-count
    change — a wildcard data axis passes through, an explicit one
    scales exactly, and non-integral scalings are refused with the fix
    named."""
    wild = MeshSpec(data=-1)
    assert rescale_for_world(wild, 3, 2) is wild          # absorbs
    assert rescale_for_world(MeshSpec(data=6), 3, 3).data == 6  # no-op
    assert rescale_for_world(MeshSpec(data=6), 3, 2).data == 4  # shrink
    assert rescale_for_world(MeshSpec(data=4), 2, 3).data == 6  # grow
    # fsdp is a batch axis too: when data cannot absorb the change
    # (extent 1, or non-integral), fsdp does
    out = rescale_for_world(MeshSpec(data=1, fsdp=8), 4, 3)
    assert (out.data, out.fsdp) == (1, 6)
    # model/pipe extents ride along untouched (parameter layouts)
    spec = MeshSpec(data=4, model=2, pipe=1)
    out = rescale_for_world(spec, 2, 1)
    assert (out.data, out.model) == (2, 2)
    with pytest.raises(ValueError, match="data=-1"):
        rescale_for_world(MeshSpec(data=3), 2, 1)
    with pytest.raises(ValueError, match=">= 1"):
        rescale_for_world(MeshSpec(), 0, 2)


def test_axis_names_order():
    assert AXIS_NAMES == ("pipe", "data", "fsdp", "seq", "expert", "model")


def test_resolve_wildcard():
    spec = MeshSpec(data=-1, model=2).resolve(8)
    assert spec.data == 4 and spec.model == 2


def test_resolve_exact():
    spec = MeshSpec(pipe=2, data=2, model=2).resolve(8)
    assert spec.data == 2


def test_resolve_errors():
    with pytest.raises(ValueError):
        MeshSpec(data=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=3).resolve(8)


def test_from_dict_rejects_unknown():
    with pytest.raises(ValueError):
        MeshSpec.from_dict({"tensor": 2})


def test_pod_topology_two_level_spec():
    """Fault-domain descriptor → flat mesh: the data axis grows
    num_pods-fold and the pod boundary is declared DCN, everything
    intra-pod rides along untouched."""
    from distributed_tensorflow_tpu.parallel import PodTopology

    topo = PodTopology(num_pods=2, pod_spec=MeshSpec(data=2, model=2))
    flat = topo.to_mesh_spec()
    assert (flat.data, flat.model) == (4, 2)
    assert flat.dcn_data == 2 and flat.num_slices == 2
    resolved = topo.resolve(8)
    assert resolved.devices_per_pod == 4
    # a pod_spec wildcard resolves against the PER-POD device count
    wild = PodTopology(num_pods=2, pod_spec=MeshSpec(data=-1)).resolve(8)
    assert wild.pod_spec.data == 4
    assert wild.to_mesh_spec().data == 8
    assert "2 pod(s)" in wild.describe()
    rt = PodTopology.from_dict({"num_pods": 2, "pod": {"data": 2}})
    assert rt.num_pods == 2 and rt.pod_spec.data == 2


def test_pod_topology_validation():
    from distributed_tensorflow_tpu.parallel import PodTopology

    with pytest.raises(ValueError, match="num_pods"):
        PodTopology(num_pods=0)
    # the pod_spec is ONE pod's ICI mesh — its own dcn factors are
    # meaningless (the only inter-pod dimension is num_pods)
    with pytest.raises(ValueError, match="dcn"):
        PodTopology(num_pods=2, pod_spec=MeshSpec(data=2, dcn_data=2))
    with pytest.raises(ValueError, match="divisible"):
        PodTopology(num_pods=3, pod_spec=MeshSpec(data=2)).resolve(8)
    with pytest.raises(ValueError, match="resolve"):
        _ = PodTopology(num_pods=2, pod_spec=MeshSpec()).devices_per_pod
    with pytest.raises(ValueError, match="Unknown"):
        PodTopology.from_dict({"num_pods": 2, "pods": {}})


def test_pod_topology_mesh_builds(devices):
    """The two-level descriptor builds a real hybrid mesh: cross-pod
    hops only on the outermost data sub-dimension."""
    from distributed_tensorflow_tpu.parallel import PodTopology

    topo = PodTopology(num_pods=2, pod_spec=MeshSpec(data=2, model=2))
    mesh = build_mesh(topo.to_mesh_spec(), devices[:8])
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2
    assert mesh.size == 8


def test_build_mesh_shape(mesh_dp4_tp2):
    assert mesh_dp4_tp2.shape["data"] == 4
    assert mesh_dp4_tp2.shape["model"] == 2
    assert mesh_dp4_tp2.size == 8
    assert mesh_axis_size(mesh_dp4_tp2, ("data", "fsdp")) == 4


def test_single_device_mesh():
    m = single_device_mesh()
    assert m.size == 1
    assert set(m.shape.keys()) == set(AXIS_NAMES)


def test_describe(mesh8):
    s = describe(mesh8)
    assert "data=8" in s and "8 devices" in s


def test_all_devices_used(mesh_dp4_tp2):
    ids = sorted(d.id for d in mesh_dp4_tp2.devices.flat)
    assert ids == sorted(d.id for d in jax.devices()[:8])


# ---- hybrid ICI x DCN mesh (SURVEY.md §2d; VERDICT round-1 item 5) ----

def test_hybrid_mesh_dcn_data_blocks(devices):
    """dcn_data=2 over 8 devices: the data axis splits into 2 DCN blocks
    of 4 ICI-contiguous devices — slice 0's devices fill data rows 0-3."""
    m = build_mesh(MeshSpec(data=8, dcn_data=2), devices[:8])
    assert m.shape["data"] == 8
    ids = [d.id for d in m.devices.reshape(8)]
    base = sorted(d.id for d in devices[:8])
    # first half of the data axis = first 4 devices (slice-major order)
    assert sorted(ids[:4]) == base[:4]
    assert sorted(ids[4:]) == base[4:]


def test_hybrid_mesh_mixed_axes(devices):
    """data(total 4, dcn 2) x model 2: ICI data=2 within a slice; model
    stays entirely intra-slice (per-layer TP must never cross DCN)."""
    m = build_mesh(MeshSpec(data=4, model=2, dcn_data=2), devices[:8])
    arr = m.devices.reshape(4, 2)  # (data, model)
    base = sorted(d.id for d in devices[:8])
    slice0 = set(base[:4])
    # data rows 0-1 (slice 0): all their devices come from slice 0
    got = {d.id for d in arr[:2].flat}
    assert got == slice0, (got, slice0)


def test_hybrid_requires_divisible():
    with pytest.raises(ValueError, match="DCN factor"):
        MeshSpec(data=3, dcn_data=2).resolve(3)


def test_hybrid_step_trains(devices):
    """A dp step over a hybrid dcn_data=2 mesh runs and matches the flat
    dp8 mesh (same math, different collective layout)."""
    import numpy as np
    import optax

    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )
    from test_step import linear_init, linear_loss, make_batch

    results = []
    for spec in (MeshSpec(data=8), MeshSpec(data=8, dcn_data=2)):
        mesh = build_mesh(spec, devices[:8])
        tx = optax.sgd(0.1)
        state, specs = init_train_state(
            linear_init, tx, mesh, jax.random.PRNGKey(0)
        )
        step = jit_train_step(make_train_step(linear_loss, tx), mesh, specs)
        batch = jax.tree.map(
            lambda x: jax.device_put(
                x, jax.sharding.NamedSharding(mesh, sh.batch_spec(x.ndim))
            ),
            make_batch(16),
        )
        state, metrics = step(state, batch)
        results.append(float(metrics["loss"]))
    assert np.isclose(results[0], results[1], rtol=1e-6), results
