import jax
import pytest

from distributed_tensorflow_tpu.parallel import (
    AXIS_NAMES,
    MeshSpec,
    build_mesh,
    describe,
    mesh_axis_size,
    single_device_mesh,
)


def test_axis_names_order():
    assert AXIS_NAMES == ("pipe", "data", "fsdp", "seq", "expert", "model")


def test_resolve_wildcard():
    spec = MeshSpec(data=-1, model=2).resolve(8)
    assert spec.data == 4 and spec.model == 2


def test_resolve_exact():
    spec = MeshSpec(pipe=2, data=2, model=2).resolve(8)
    assert spec.data == 2


def test_resolve_errors():
    with pytest.raises(ValueError):
        MeshSpec(data=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=3).resolve(8)


def test_from_dict_rejects_unknown():
    with pytest.raises(ValueError):
        MeshSpec.from_dict({"tensor": 2})


def test_build_mesh_shape(mesh_dp4_tp2):
    assert mesh_dp4_tp2.shape["data"] == 4
    assert mesh_dp4_tp2.shape["model"] == 2
    assert mesh_dp4_tp2.size == 8
    assert mesh_axis_size(mesh_dp4_tp2, ("data", "fsdp")) == 4


def test_single_device_mesh():
    m = single_device_mesh()
    assert m.size == 1
    assert set(m.shape.keys()) == set(AXIS_NAMES)


def test_describe(mesh8):
    s = describe(mesh8)
    assert "data=8" in s and "8 devices" in s


def test_all_devices_used(mesh_dp4_tp2):
    ids = sorted(d.id for d in mesh_dp4_tp2.devices.flat)
    assert ids == sorted(d.id for d in jax.devices()[:8])
