"""Pipeline-memory guard (VERDICT r4 item 8a): a pipelined transformer
run whose estimated per-device working set presses v5e HBM warns with
the measured mitigation (train.grad_accum_steps=2) before training
starts — the M=64 pod-grid rows measurably do not fit
(artifacts/podshape_r4/memory_grid.jsonl)."""

import json
import os
import subprocess
import sys

import jax
import pytest

from distributed_tensorflow_tpu.models import transformer as tfm
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.workloads import runner as runner_lib
from distributed_tensorflow_tpu.workloads.runner import (
    RunConfig, TrainSection, _pipeline_memory_guard,
)
from distributed_tensorflow_tpu.data.text import TextDataConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "pipeline_memory_analysis.py")

TINY = tfm.TransformerConfig(
    vocab_size=512, max_len=64, num_layers=4, d_model=64, num_heads=4,
    d_ff=128, causal=False, pre_ln=False, dtype="float32", remat=True,
)


def _cfg(mesh_pipe=2, **train_kw):
    return RunConfig(
        workload="bert_pretrain", model=TINY,
        mesh=MeshSpec(pipe=mesh_pipe, data=2),
        data=TextDataConfig(dataset="synthetic_mlm", global_batch_size=16,
                            seq_len=64, vocab_size=512),
        train=TrainSection(**train_kw),
    )


@pytest.fixture()
def pipe_mesh(devices):
    return build_mesh(MeshSpec(pipe=2, data=2), devices[:4])


def test_guard_skips_on_cpu_backend(pipe_mesh, monkeypatch):
    # the test rig IS the cpu backend: any subprocess launch is a bug
    def boom(*a, **k):
        raise AssertionError("estimator subprocess launched on cpu rig")

    monkeypatch.setattr(subprocess, "run", boom)
    _pipeline_memory_guard(_cfg(), pipe_mesh)


def test_guard_warns_with_mitigation(pipe_mesh, monkeypatch, caplog):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    seen = {}

    def fake_run(argv, **kw):
        seen["req"] = json.loads(argv[argv.index("--check") + 1])
        seen["env"] = kw.get("env", {})

        class P:
            stdout = json.dumps({"gib": 15.8, "fits_v5e": False}) + "\n"
        return P()

    monkeypatch.setattr(subprocess, "run", fake_run)
    with caplog.at_level("WARNING", logger=runner_lib.__name__):
        _pipeline_memory_guard(_cfg(), pipe_mesh)
    assert "grad_accum_steps" in caplog.text and "15.8" in caplog.text
    # request carries the run's real shape, per DATA-SHARD batch
    assert seen["req"]["S"] == 2 and seen["req"]["batch"] == 8
    assert seen["req"]["M"] == 4  # auto rule: 2 * pipe * virtual
    assert seen["req"]["mlm"] is True
    # the estimator child must never touch the accelerator
    assert seen["env"]["JAX_PLATFORMS"] == "cpu"
    assert "PALLAS_AXON_POOL_IPS" not in seen["env"]


def test_guard_quiet_when_fits(pipe_mesh, monkeypatch, caplog):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def fake_run(argv, **kw):
        class P:
            stdout = json.dumps({"gib": 11.5, "fits_v5e": True}) + "\n"
        return P()

    monkeypatch.setattr(subprocess, "run", fake_run)
    with caplog.at_level("WARNING", logger=runner_lib.__name__):
        _pipeline_memory_guard(_cfg(), pipe_mesh)
    assert "EXCEEDS" not in caplog.text


def test_guard_disabled_and_failure_tolerant(pipe_mesh, monkeypatch, caplog):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def boom(*a, **k):
        raise AssertionError("launched despite check_pipeline_memory=False")

    monkeypatch.setattr(subprocess, "run", boom)
    _pipeline_memory_guard(_cfg(check_pipeline_memory=False), pipe_mesh)

    # estimator failure must never kill the run
    def broken(*a, **k):
        raise OSError("no such tool")

    monkeypatch.setattr(subprocess, "run", broken)
    with caplog.at_level("INFO", logger=runner_lib.__name__):
        _pipeline_memory_guard(_cfg(), pipe_mesh)
    assert "estimate unavailable" in caplog.text


@pytest.mark.slow
def test_check_mode_end_to_end():
    """The --check CLI the guard shells out to: real XLA memory analysis
    of the tiny pipelined config, one JSON row out (both objectives)."""
    for mlm in (True, False):
        req = {"model": {"vocab_size": 512, "max_len": 64, "num_layers": 4,
                         "d_model": 64, "num_heads": 4, "d_ff": 128,
                         "causal": not mlm, "pre_ln": False,
                         "dtype": "float32", "remat": True},
               "S": 2, "V": 1, "M": 4, "batch": 8, "seq": 64, "mlm": mlm}
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, TOOL, "--check", json.dumps(req)],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        assert row["S"] == 2 and row["M"] == 4
        assert row["per_device_bytes"] > 0
        assert isinstance(row["fits_v5e"], bool)
