"""Single-process chaos worker (tests/test_resilience.py, tools/chaos_smoke.py).

Trains a tiny MLP on batches derived deterministically from the GLOBAL
step index, with an optional FaultPlan. The kill→restart→resume oracle:

    run A (straight):    --steps N                → params_a.npz
    run B (interrupted): --steps N --sigterm-at K → PreemptionSaved exit
    run C (resume, same workdir as B): --steps N  → params_b.npz

A and C must produce BIT-IDENTICAL params: the preemption save captured
the full state exactly, and resume replays exactly the batches the
straight run would have seen (batch i feeds global step i, i seeded).

Markers on stdout (the drivers assert on these):
    CHAOS-DONE step=N        run reached the target step
    CHAOS-PREEMPTED step=K   clean PreemptionSaved exit, checkpoint at K
    CHAOS-DATAFAULT saved=K  injected IOError; emergency checkpoint at K
"""

import argparse
import os
import sys

# must precede any jax import in this process
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent compile cache: OPT-IN only, mirroring tests/conftest.py —
# cache-deserialized executables corrupt donated buffers on this jaxlib
# (silent NaN params on resume), which this worker exists to catch
_cache_dir = os.environ.get("DTF_TEST_CACHE", "0")
if _cache_dir != "0":
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)

import numpy as np  # noqa: E402


def global_step_batch(i: int) -> dict:
    """The batch that feeds global step ``i`` — a pure function of i, so
    straight and resumed runs see identical data."""
    rng = np.random.RandomState(1000 + i)
    return {
        "image": rng.randn(8, 8).astype(np.float32),
        "label": rng.randint(0, 4, 8).astype(np.int32),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("workdir", help="checkpoint directory")
    ap.add_argument("--steps", type=int, default=8,
                    help="absolute target step (StopAtStepHook semantics)")
    ap.add_argument("--sigterm-at", type=int, default=None,
                    help="SIGTERM ourselves after this GLOBAL step")
    ap.add_argument("--data-error-at", type=int, default=None,
                    help="data iterator raises IOError feeding this GLOBAL step")
    ap.add_argument("--out", default=None,
                    help="write final params to this .npz on completion")
    args = ap.parse_args(argv)

    import optax

    from distributed_tensorflow_tpu.models import MLP, MLPConfig, common
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.resilience import (
        DataError, FaultPlan, Sigterm,
    )
    from distributed_tensorflow_tpu.train import (
        CheckpointConfig, Checkpointer, StepOptions, Trainer,
        callbacks as cb, init_or_restore, make_train_step,
    )

    mesh = build_mesh(MeshSpec(data=-1))
    model = MLP(MLPConfig(hidden_sizes=(16,), num_classes=4))
    tx = optax.adam(1e-2)
    ckpt = Checkpointer(
        CheckpointConfig(directory=args.workdir, save_interval_steps=10**6,
                         async_save=False, preemption_check_every=1),
        mesh,
    )
    state, specs, restored = init_or_restore(
        ckpt, common.make_init_fn(model, (8,)), tx, mesh, jax.random.PRNGKey(0)
    )
    start = int(state.step)

    faults = []
    if args.sigterm_at is not None:
        # FaultCallback sees the trainer's GLOBAL step — no offset
        if args.sigterm_at <= start:
            raise SystemExit(f"--sigterm-at {args.sigterm_at} is already "
                             f"behind the restored step {start}")
        faults.append(Sigterm(args.sigterm_at))
    if args.data_error_at is not None:
        # iterator batches are 1-based PER PROCESS: batch i = step start+i
        if args.data_error_at <= start:
            raise SystemExit(f"--data-error-at {args.data_error_at} is "
                             f"already behind the restored step {start}")
        faults.append(DataError(args.data_error_at - start))
    plan = FaultPlan(tuple(faults))

    trainer = Trainer(
        make_train_step(common.classification_loss_fn(model), tx,
                        StepOptions()),
        state, mesh, specs,
        callbacks=[cb.CheckpointCallback(ckpt), plan.callback()],
    )

    def batches():
        i = start
        while True:
            i += 1
            yield global_step_batch(i)

    try:
        state = trainer.fit(plan.wrap(batches()), num_steps=args.steps)
    except IOError:
        saved = ckpt.latest_step()
        ckpt.close()
        print(f"CHAOS-DATAFAULT saved={saved}", flush=True)
        return 0
    saved = ckpt.latest_step()
    ckpt.close()
    if "preempted" in (trainer._stop_reason or ""):
        print(f"CHAOS-PREEMPTED step={saved}", flush=True)
        return 0
    if int(state.step) != args.steps:
        print(f"CHAOS-SHORT step={int(state.step)} want={args.steps}",
              flush=True)
        return 1
    if args.out:
        leaves = jax.tree.leaves(jax.device_get(state.params))
        np.savez(args.out, **{f"p{i}": np.asarray(x)
                              for i, x in enumerate(leaves)})
    print(f"CHAOS-DONE step={int(state.step)}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
