"""Single-process chaos worker (tests/test_resilience.py, tools/chaos_smoke.py).

Trains a tiny MLP on batches derived deterministically from the GLOBAL
step index, with an optional FaultPlan. The kill→restart→resume oracle:

    run A (straight):    --steps N                → params_a.npz
    run B (interrupted): --steps N --sigterm-at K → PreemptionSaved exit
    run C (resume, same workdir as B): --steps N  → params_b.npz

A and C must produce BIT-IDENTICAL params: the preemption save captured
the full state exactly, and resume replays exactly the batches the
straight run would have seen (batch i feeds global step i, i seeded).

With ``--supervise`` the whole run goes through the in-process
``resilience.Supervisor`` instead: SIGTERM → coordinated save →
in-process restart, ``--corrupt-at-restart`` truncates the newest
checkpoint at the restart boundary (fallback restore must quarantine it
and land on an older valid step), and transient data faults are absorbed
by a re-seeking ``RetryingIterator`` — one process, every recovery path.

With ``--fleet`` the process is ONE WORKER of a FleetSupervisor gang
(resilience/fleet.py): it reads the fleet incarnation and restore
ceiling from ``--fleet-dir``, heartbeats to its per-worker file through
the Supervisor attempt seam + HeartbeatCallback step seam, and speaks
the fleet exit-code protocol (0 done / EXIT_PREEMPTED / EXIT_FAILED).
Injected faults are gated on ``--fault-incarnation`` (default 1): the
incarnation counter is the cross-process analog of the plan's
fire-once state, so a relaunched gang does not re-injure itself.

Markers on stdout (the drivers assert on these):
    CHAOS-DONE step=N        run reached the target step
    CHAOS-PREEMPTED step=K   clean PreemptionSaved exit, checkpoint at K
    CHAOS-DATAFAULT saved=K  injected IOError; emergency checkpoint at K
    CHAOS-SUPERVISED step=N restarts=R finite=F quarantined=Q ordered=O
                             supervised run finished; F/Q/O are 0/1 flags
                             (O: flight-recorder timeline causal order)
    CHAOS-ANOMALY skipped=N quarantined=I,J refused=R
                             numeric-anomaly defense (--anomaly): batches
                             skipped in-graph, quarantine-file indices,
                             R=1 if any save was refused by validation
    CHAOS-POSTMORTEM path=P events=N ordered=O
                             flight recorder dumped to P (--flightrec)
    CHAOS-GOODPUT fraction=F productive_s=P wall_s=W ok=K
                             goodput gauge vs measured wall-clock
    FLEET-DONE step=N incarnation=K restarts=R
                             fleet worker reached the target step
    FLEET-PREEMPTED step=K   fleet worker exited via a preemption save
    FLEET-FAILED cause=C     fleet worker's in-process supervision exhausted
    FLEET-DYING step=K       scripted --die-at hard exit (elastic rounds)

With ``--elastic`` the worker additionally follows the fleet's
SHARD_PLAN (resilience/fleet.ElasticWorker): it pauses at resize
barriers, acknowledges plans through its heartbeat, and appends every
applied ``(rank, world, at)`` to ``<workdir>/reshard_log.jsonl`` — the
consistency oracle the elastic E2E reads. The rig is collective-free,
so every worker trains on the FULL global batch (the stand-in for the
data-parallel allreduce); the recorded schedule, not the tensors, is
what a resize changes here.
"""

import argparse
import os
import sys

# must precede any jax import in this process
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent compile cache: OPT-IN only, mirroring tests/conftest.py —
# cache-deserialized executables corrupt donated buffers on this jaxlib
# (silent NaN params on resume), which this worker exists to catch
_cache_dir = os.environ.get("DTF_TEST_CACHE", "0")
if _cache_dir != "0":
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)

import numpy as np  # noqa: E402


def global_step_batch(i: int) -> dict:
    """The batch that feeds global step ``i`` — a pure function of i, so
    straight and resumed runs see identical data."""
    rng = np.random.RandomState(1000 + i)
    return {
        "image": rng.randn(8, 8).astype(np.float32),
        "label": rng.randint(0, 4, 8).astype(np.int32),
    }


def _supervised(args, mesh, model, tx) -> int:
    """One supervised run: faults from the CLI become a FaultPlan, every
    recovery path (retrying data, preemption restart, fallback restore)
    runs in THIS process under resilience.Supervisor — and the flight
    recorder + goodput ledger must agree with what actually happened:
    the postmortem timeline is asserted to contain the injected fault,
    the restart, and the fallback restore IN CAUSAL ORDER, and the
    exported ``goodput_fraction`` gauge to equal productive-step seconds
    over total wall-clock within tolerance."""
    import logging
    import time

    import optax  # noqa: F401  (kept symmetric with main's imports)

    from distributed_tensorflow_tpu.data.pipeline import (
        QuarantineFilter, RetryingIterator,
    )
    from distributed_tensorflow_tpu.models import common
    from distributed_tensorflow_tpu.obs import flightrec as fr
    from distributed_tensorflow_tpu.obs import goodput
    from distributed_tensorflow_tpu.obs.registry import default_registry
    from distributed_tensorflow_tpu.resilience import (
        AnomalyConfig, AnomalyPolicy, CorruptCheckpoint, FaultPlan,
        NaNBatch, RetryPolicy, Sigterm, Supervisor, SupervisorConfig,
        TransientIOError, load_quarantine,
    )
    from distributed_tensorflow_tpu.train import (
        CheckpointConfig, Checkpointer, StepOptions, Trainer,
        callbacks as cb, init_or_restore, make_train_step,
    )

    faults = []
    if args.sigterm_at is not None:
        faults.append(Sigterm(args.sigterm_at))
    if args.transient_io_at is not None:
        faults.append(TransientIOError(args.transient_io_at, times=2))
    if args.corrupt_at_restart:
        faults.append(CorruptCheckpoint(restart=1))
    if args.nan_at is not None:
        # recurring: the index is bad on EVERY fetch, every incarnation —
        # only the quarantine-aware stream never fetching it ends it
        faults.append(NaNBatch(args.nan_at, recur=True))
    plan = FaultPlan(tuple(faults))
    loss_fn = common.classification_loss_fn(model)

    # "validate_before_save never refuses a save" is part of the anomaly
    # acceptance: the in-graph guard means poisoned params never exist
    refused = {"n": 0}

    class _RefusalCounter(logging.Handler):
        def emit(self, record):
            if "refusing to checkpoint" in record.getMessage():
                refused["n"] += 1

    logging.getLogger(
        "distributed_tensorflow_tpu.train.checkpoint"
    ).addHandler(_RefusalCounter())

    def batches_from(i0: int):
        i = i0
        while True:
            i += 1
            yield global_step_batch(i)

    def build(restart_index: int):
        ckpt = Checkpointer(
            CheckpointConfig(directory=args.workdir, save_interval_steps=2,
                             async_save=False, preemption_check_every=1),
            mesh,
        )
        state, specs, _ = init_or_restore(
            ckpt, common.make_init_fn(model, (8,)), tx, mesh,
            jax.random.PRNGKey(0), fallback=True,
        )
        start = int(state.step)

        def retrying(raw):
            return RetryingIterator(
                lambda i: plan.wrap(batches_from(i), start=i),
                RetryPolicy(max_attempts=4, base_s=0.0, jitter=0.0),
                start_index=raw, sleep=lambda s: None,
            )

        policy = None
        if args.anomaly:
            # quarantine holes re-read from disk at every attempt
            # boundary; the policy blames via the stream's raw cursor
            data = QuarantineFilter(retrying, load_quarantine(args.workdir),
                                    start_step=start)
            policy = AnomalyPolicy(
                args.workdir, AnomalyConfig(skip_budget=args.skip_budget),
                index_fn=lambda: data.raw,
            )
        else:
            data = retrying(start)
        trainer = Trainer(
            make_train_step(loss_fn, tx,
                            StepOptions(skip_nonfinite=args.anomaly)),
            state, mesh, specs,
            # telemetry FIRST: maybe_save raises PreemptionSaved from
            # CheckpointCallback, skipping later callbacks for that step
            callbacks=[cb.TelemetryCallback(every_n=10 ** 6),
                       cb.CheckpointCallback(ckpt), plan.callback()],
            anomaly_policy=policy,
        )
        return trainer, data, ckpt

    sup = Supervisor(
        build, num_steps=args.steps,
        cfg=SupervisorConfig(max_restarts=args.max_restarts,
                             backoff=RetryPolicy(base_s=0.0, jitter=0.0)),
        on_restart=[plan.restart_hook(args.workdir)],
        sleep=lambda s: None,
    )
    # reviewed: measuring REAL elapsed wall time is this oracle's job —
    # wall_s is the reference the goodput ledger is checked against, not
    # a trajectory input (params stay bit-identical regardless)
    t_run0 = time.monotonic()  # dtflint: disable=wall-clock-in-seam
    state = sup.run()
    wall_s = time.monotonic() - t_run0  # dtflint: disable=wall-clock-in-seam
    leaves = [np.asarray(x) for x in
              jax.tree.leaves(jax.device_get(state.params))]
    finite = all(np.isfinite(x).all() for x in leaves)
    quarantined = os.path.isdir(os.path.join(args.workdir, ".corrupt"))
    if args.out:
        np.savez(args.out, **{f"p{i}": x for i, x in enumerate(leaves)})

    # -- flight-recorder causal-order assertion (ISSUE 6 acceptance) ------
    events = fr.default_recorder().events()
    ordered = True
    if args.sigterm_at is not None and args.corrupt_at_restart:
        # the postmortem timeline must tell the recovery story in order:
        # injected SIGTERM → preemption (emergency) checkpoint → restart
        # → corruption fault at the boundary → quarantine → fallback
        # restore onto an older valid step
        ordered = fr.contains_in_order(events, [
            ("fault_fired", {"fault": "sigterm"}),
            ("ckpt_save", {"trigger": "preemption"}),
            ("sup_restart", {}),
            ("fault_fired", {"fault": "ckpt_corrupt"}),
            ("ckpt_quarantine", {}),
            ("ckpt_restore", {"fallback": True}),
        ])
    if args.nan_at is not None and args.anomaly:
        # the anomaly-defense causal chain: recurring bad batch fired →
        # skipped in-graph → blamed into the quarantine file — and, when
        # a SIGTERM also restarts the run, the recovery restores and
        # replays around the hole (tools/chaos_smoke.py nan-blame round)
        specs = [
            ("fault_fired", {"fault": "nan_batch"}),
            ("anomaly_skip", {"index": args.nan_at}),
            ("anomaly_blame", {"index": args.nan_at}),
        ]
        if args.sigterm_at is not None:
            specs += [("ckpt_save", {"trigger": "preemption"}),
                      ("sup_restart", {}), ("ckpt_restore", {})]
        ordered = ordered and fr.contains_in_order(events, specs)
    if args.flightrec:
        fr.default_recorder().dump(args.flightrec, reason="chaos_worker")
        print(f"CHAOS-POSTMORTEM path={args.flightrec} "
              f"events={len(events)} ordered={int(ordered)}", flush=True)

    # -- goodput accounting vs real wall-clock (ISSUE 6 acceptance) -------
    reg = default_registry()
    productive = reg.total(goodput.PRODUCTIVE_SECONDS)
    frac_gauge = reg.get(goodput.GOODPUT_FRACTION)
    frac = frac_gauge.value if frac_gauge is not None else float("nan")
    # the tracked buckets partition sup.run()'s wall time up to small
    # untracked slivers (classification, final save, ckpt.close), so the
    # exported fraction must track productive/wall within tolerance
    goodput_ok = (0.0 < frac <= 1.0
                  and abs(frac - productive / wall_s) <= 0.15)
    print(
        f"CHAOS-GOODPUT fraction={frac:.4f} productive_s={productive:.4f} "
        f"wall_s={wall_s:.4f} ok={int(goodput_ok)}", flush=True,
    )

    ok = (int(state.step) == args.steps and finite and ordered
          and goodput_ok)
    if args.anomaly:
        q = sorted(load_quarantine(args.workdir))
        m = reg.get("anomaly_skipped_batches_total", cause="nonfinite")
        print(
            f"CHAOS-ANOMALY skipped={int(m.value if m else 0)} "
            f"quarantined={','.join(map(str, q)) or '-'} "
            f"refused={refused['n']}",
            flush=True,
        )
        ok = ok and refused["n"] == 0
    print(
        f"CHAOS-SUPERVISED step={int(state.step)} restarts={sup.restarts} "
        f"finite={int(finite)} quarantined={int(quarantined)} "
        f"ordered={int(ordered)}",
        flush=True,
    )
    return 0 if ok else 1


def _fleet(args, mesh, model, tx) -> int:
    """One fleet-gang worker: in-process Supervisor for transient/
    poisoned/stalled failures, but PREEMPTION exits the process (the
    FleetSupervisor owns process-level restarts), heartbeats through
    both production seams, restore capped at the fleet's common-step
    ceiling."""
    import optax  # noqa: F401  (kept symmetric with main's imports)

    from distributed_tensorflow_tpu.models import common
    from distributed_tensorflow_tpu.resilience import (
        AsyncCommitKill, ControlPlanePartition, FaultPlan, Hang, PodOutage,
        RetryPolicy, Sigterm, SlowControlPlane, SlowWriter,
        Supervisor, SupervisorConfig, SupervisorExhausted,
        fleet as fleet_lib, podfleet as podfleet_lib,
    )
    from distributed_tensorflow_tpu.resilience.supervisor import (
        POISONED, STALLED, TRANSIENT,
    )
    from distributed_tensorflow_tpu.train import (
        CheckpointConfig, Checkpointer, StepOptions, Trainer,
        callbacks as cb, init_or_restore, make_train_step,
    )

    class _DieAt(cb.Callback):
        """Hard, uncoordinated death at an exact global step — the
        elastic round's scripted fault. os._exit skips every handler
        and atexit hook: no preemption save, no final heartbeat — the
        fleet sees a raw nonzero exit (classified transient). The
        launcher owns the schedule (pass --die-at only to the launch
        that should die)."""

        def __init__(self, step):
            self.step = step

        def on_step_end(self, trainer, step, metrics):
            if step == self.step:
                print(f"FLEET-DYING step={step}", flush=True)
                os._exit(86)

    class _StepSleep(cb.Callback):
        """Slow the loop so real-subprocess elastic rounds overlap: the
        members must still be training when the replacement comes up.
        Pure pacing — wall time never feeds the trajectory."""

        def __init__(self, seconds):
            self.seconds = seconds

        def on_step_end(self, trainer, step, metrics):
            import time

            time.sleep(self.seconds)

    from distributed_tensorflow_tpu.obs import fleetview, flightrec as fr

    incarnation = fleet_lib.read_incarnation(args.fleet_dir)
    # pod mode (resilience/podfleet.py): --fleet-dir is one pod's
    # subdirectory and the GLOBAL_EPOCH file lives one level up; the
    # (global_epoch, pod_incarnation) pair is the two-level fence, so
    # fault flags are additionally gated on --fault-epoch — a pod
    # relaunched under a NEW epoch never re-injures itself even though
    # its per-pod incarnation counter restarted
    epoch = None
    if args.pod is not None:
        epoch = podfleet_lib.read_global_epoch(
            os.path.dirname(os.path.abspath(args.fleet_dir)))
    writer = fleet_lib.HeartbeatWriter(
        fleet_lib.heartbeat_path(args.fleet_dir, args.worker_index),
        incarnation=incarnation,
        # pod mode pulses: the partition-fencing judgment (pod
        # supervisor: frozen heartbeat + live pid = fenced, not dead)
        # is only sound when silence really means partition — a pulsed
        # writer beats through compile/restore windows, so the ONLY
        # thing that freezes the file is the control plane itself
        pulse_interval_s=0.5 if args.pod is not None else None,
    )
    # fleet observatory (obs/fleetview.py): periodic telemetry snapshots
    # next to the heartbeat, and a flight-recorder dump on every exit
    # path — identity-stamped so postmortem.py --merge can align this
    # process's clock with the fleet's
    exporter = fleetview.SnapshotExporter(
        fleetview.fleetsnap_path(args.fleet_dir, args.worker_index),
        worker=args.worker_index, incarnation=incarnation)

    def dump_flightrec() -> None:
        if not args.flightrec_dir:
            return
        os.makedirs(args.flightrec_dir, exist_ok=True)
        stem = (f"flightrec-p{args.pod}w{args.worker_index}i{incarnation}"
                if args.pod is not None
                else f"flightrec-w{args.worker_index}i{incarnation}")
        base = os.path.join(args.flightrec_dir, stem)
        # never clobber: an elastic replacement reuses (worker,
        # incarnation), and overwriting would destroy the dead
        # process's dump — the one artifact the merge exists to
        # explain. Two dumps for one slot make the merge fail LOUDLY
        # with a label collision instead, which is the truthful outcome.
        path, n = f"{base}.jsonl", 0
        while os.path.exists(path):
            n += 1
            path = f"{base}-{n}.jsonl"
        extra = {"worker": args.worker_index, "incarnation": incarnation}
        if args.pod is not None:
            extra["pod"] = args.pod
        fr.default_recorder().dump(
            path, reason="fleet_worker_exit", extra=extra)
    ceiling = fleet_lib.read_restore_step(args.fleet_dir)
    elastic_client = None
    if args.elastic:
        plan = fleet_lib.read_shard_plan(args.fleet_dir)
        if plan is not None and args.worker_index not in plan.ranks:
            # we are a catching-up replacement (elastic shrink relaunch),
            # not a gang-restarted member: any RESTORE_STEP on disk
            # belongs to an earlier gang restart and must not roll our
            # restore back below our own newest valid step
            ceiling = None
            if args.p2p_catchup:
                # ask a live survivor for its newest valid step before
                # building: a successful import becomes OUR newest valid
                # step, so the restore below lands on it and the
                # deterministic replay shrinks to the tail the survivor
                # had not yet checkpointed. No answer within the budget
                # = replay from our own newest, exactly as before.
                fleet_lib.request_catchup(
                    args.fleet_dir, args.worker_index, incarnation,
                    args.workdir, budget_s=args.catchup_budget)

        # replica-mode reshard seam: the collective-free rig trains
        # every worker on the FULL global batch (the stand-in for the
        # data-parallel allreduce), so a reshard changes no tensor —
        # the realized schedule is recorded for the E2E consistency
        # oracle instead (same (world, barrier) sequence on every
        # survivor, ranks a bijection)
        reshard_log = os.path.join(args.workdir, "reshard_log.jsonl")

        def on_reshard(rank, world, at):
            import json

            os.makedirs(args.workdir, exist_ok=True)
            with open(reshard_log, "a") as f:
                f.write(json.dumps(
                    {"rank": rank, "world": world, "at": at,
                     "incarnation": incarnation}) + "\n")

        elastic_client = fleet_lib.ElasticWorker(
            args.fleet_dir, args.worker_index, writer,
            on_reshard=on_reshard,
            # serve peer catch-up requests from the step seam and from
            # inside resize-barrier holds (p2p rounds only)
            ckpt_dir=args.workdir if args.p2p_catchup else None)
    faults = []
    # the incarnation counter is the cross-process fired-state: a gang
    # relaunched after this fault must not re-fire it; under a pod
    # coordinator the gate is TWO-level — (--fault-epoch,
    # --fault-incarnation) — because a pod restart resets neither alone
    gate = incarnation == args.fault_incarnation
    if args.fault_epoch is not None:
        gate = gate and epoch == args.fault_epoch
    if gate:
        if args.hang_at is not None:
            faults.append(Hang(args.hang_at))
        if args.sigterm_at is not None:
            faults.append(Sigterm(args.sigterm_at))
        if args.async_kill_at is not None:
            faults.append(AsyncCommitKill(args.async_kill_at))
        if args.slow_writer_at is not None:
            faults.append(SlowWriter(args.slow_writer_at,
                                     delay_s=args.slow_writer_delay))
        if args.pod_outage_at is not None:
            faults.append(PodOutage(args.pod_outage_at))
        if args.partition_at is not None:
            faults.append(ControlPlanePartition(
                args.partition_at, steps=args.partition_steps))
        if args.slow_beat_at is not None:
            faults.append(SlowControlPlane(
                args.slow_beat_at, delay_s=args.slow_beat_delay,
                steps=args.slow_beat_steps))
    plan = FaultPlan(tuple(faults))
    loss_fn = common.classification_loss_fn(model)

    def batches_from(i0: int):
        i = i0
        while True:
            i += 1
            yield global_step_batch(i)

    def build(restart_index: int):
        ckpt = Checkpointer(
            CheckpointConfig(directory=args.workdir, save_interval_steps=2,
                             max_to_keep=10, async_save=args.async_save,
                             preemption_check_every=1),
            mesh,
            # elastic: saves beat phase "save" so a death landing
            # mid-checkpoint makes the fleet gang-stop, never shrink
            # around a possibly-torn step dir (async: the bracket spans
            # the whole background commit window)
            heartbeat=writer if args.elastic else None,
        )
        # production fault seam: AsyncCommitKill/SlowWriter fire inside
        # the background writer's commit stages; the flight recorder is
        # flushed BEFORE the SIGKILL so the postmortem can prove where
        # the death landed
        ckpt.save_hooks.append(plan.save_hook(flush=dump_flightrec))
        fb = not args.strict_restore
        state, specs, restored = init_or_restore(
            ckpt, common.make_init_fn(model, (8,)), tx, mesh,
            jax.random.PRNGKey(0), fallback=fb,
            # the gang ceiling binds the incarnation's FIRST restore
            # only: an in-process restart later in the same incarnation
            # must resume from its own newest valid step, not replay
            # from (or re-init below) the gang restart point
            step=ceiling if restart_index == 0 else None,
        )
        start = int(state.step)
        if restored:
            writer.note_restore(start, fallback=fb)
        # heartbeat FIRST: it must record the step even when
        # CheckpointCallback raises PreemptionSaved (which skips every
        # later callback for that step), and before the fault callback
        # can hang the loop; the elastic poll sits between heartbeat and
        # checkpoint so a resize hold lands between steps
        # telemetry BEFORE the snapshot export so each snapshot already
        # carries the step it was cut at; heartbeat stays first (it must
        # record the step even when a later callback raises)
        callbacks = [cb.HeartbeatCallback(
                         writer,
                         # slow-control-plane seam: bounded delay on the
                         # beat path only when the round scripts it
                         pace=(plan.beat_pace()
                               if args.slow_beat_at is not None else None)),
                     cb.TelemetryCallback(every_n=10 ** 6),
                     cb.FleetSnapshotCallback(exporter)]
        if elastic_client is not None:
            callbacks.append(cb.ElasticCallback(elastic_client))
        # writer: the ControlPlanePartition redirect seam; flush: the
        # flight recording must reach disk before PodOutage's SIGKILL
        callbacks += [cb.CheckpointCallback(ckpt),
                      plan.callback(writer=writer, flush=dump_flightrec)]
        if args.die_at is not None:
            callbacks.append(_DieAt(args.die_at))
        if args.step_sleep > 0:
            callbacks.append(_StepSleep(args.step_sleep))
        trainer = Trainer(
            make_train_step(loss_fn, tx, StepOptions()), state, mesh, specs,
            callbacks=callbacks,
        )
        return trainer, plan.wrap(batches_from(start), start=start), ckpt

    sup = Supervisor(
        build, num_steps=args.steps,
        cfg=SupervisorConfig(
            max_restarts=args.max_restarts,
            # PREEMPTION deliberately absent: a SIGTERM means the fleet
            # is tearing the gang down — exit so it can relaunch us
            restart_on=(TRANSIENT, POISONED, STALLED),
            backoff=RetryPolicy(base_s=0.0, jitter=0.0),
        ),
        heartbeat=writer,
    )
    try:
        state = sup.run()
    except SupervisorExhausted as e:
        writer.finish("failed", cause=e.cause)
        dump_flightrec()
        print(f"FLEET-FAILED cause={e.cause}", flush=True)
        return fleet_lib.EXIT_FAILED
    except BaseException as e:
        # non-restartable classes are RE-RAISED by the Supervisor, not
        # wrapped: without this they'd crash rc=1 and the fleet would
        # misclassify a deterministic fatal bug as a transient death and
        # burn its whole gang-restart budget replaying it
        from distributed_tensorflow_tpu.resilience import classify_failure

        import traceback

        traceback.print_exc()
        cause = classify_failure(e)
        writer.finish("failed", cause=cause)
        dump_flightrec()
        print(f"FLEET-FAILED cause={cause}", flush=True)
        return fleet_lib.EXIT_FAILED
    if int(state.step) < args.steps:
        writer.finish("preempted")
        dump_flightrec()
        print(f"FLEET-PREEMPTED step={int(state.step)}", flush=True)
        return fleet_lib.EXIT_PREEMPTED
    if args.out:
        leaves = jax.tree.leaves(jax.device_get(state.params))
        np.savez(args.out, **{f"p{i}": np.asarray(x)
                              for i, x in enumerate(leaves)})
    writer.finish("done")
    dump_flightrec()
    print(f"FLEET-DONE step={int(state.step)} incarnation={incarnation} "
          f"restarts={sup.restarts}", flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("workdir", help="checkpoint directory")
    ap.add_argument("--steps", type=int, default=8,
                    help="absolute target step (StopAtStepHook semantics)")
    ap.add_argument("--sigterm-at", type=int, default=None,
                    help="SIGTERM ourselves after this GLOBAL step")
    ap.add_argument("--data-error-at", type=int, default=None,
                    help="data iterator raises IOError feeding this GLOBAL step")
    ap.add_argument("--out", default=None,
                    help="write final params to this .npz on completion")
    ap.add_argument("--supervise", action="store_true",
                    help="run under resilience.Supervisor (in-process "
                         "restarts, fallback restore, retrying data)")
    ap.add_argument("--corrupt-at-restart", action="store_true",
                    help="supervised mode: truncate the newest checkpoint "
                         "at the first restart boundary")
    ap.add_argument("--transient-io-at", type=int, default=None,
                    help="supervised mode: data fetch for this GLOBAL step "
                         "raises IOError twice, then succeeds")
    ap.add_argument("--nan-at", type=int, default=None,
                    help="supervised mode: the batch feeding this GLOBAL "
                         "step is NaN-poisoned on EVERY fetch (recurring "
                         "bad index — the quarantine target)")
    ap.add_argument("--anomaly", action="store_true",
                    help="supervised mode: enable the numeric-anomaly "
                         "defense (in-graph no-update-on-nonfinite guard, "
                         "AnomalyPolicy skip budget, quarantine-aware "
                         "stream)")
    ap.add_argument("--skip-budget", type=int, default=4,
                    help="anomaly mode: non-finite batches skipped before "
                         "the poisoned escalation")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--flightrec", default=None,
                    help="supervised mode: dump the flight recorder to this "
                         "JSONL path at the end of the run")
    ap.add_argument("--fleet", action="store_true",
                    help="run as one worker of a resilience.FleetSupervisor "
                         "gang (heartbeats, incarnation, exit-code protocol)")
    ap.add_argument("--fleet-dir", default=None,
                    help="fleet control dir (INCARNATION, RESTORE_STEP, "
                         "heartbeat files)")
    ap.add_argument("--worker-index", type=int, default=0)
    ap.add_argument("--hang-at", type=int, default=None,
                    help="fleet mode: hang the host loop after this GLOBAL "
                         "step (heartbeats stop, process stays alive)")
    ap.add_argument("--fault-incarnation", type=int, default=1,
                    help="fleet mode: inject faults only when the fleet "
                         "incarnation equals this (default 1 — first launch)")
    ap.add_argument("--elastic", action="store_true",
                    help="fleet mode: follow the fleet's SHARD_PLAN "
                         "(elastic resize client: barrier holds, reshard "
                         "schedule recorded to <workdir>/reshard_log.jsonl)")
    ap.add_argument("--die-at", type=int, default=None,
                    help="fleet mode: hard os._exit at this GLOBAL step "
                         "(no save, no final heartbeat — the elastic "
                         "round's scripted death; the LAUNCHER gates which "
                         "launch gets it)")
    ap.add_argument("--step-sleep", type=float, default=0.0,
                    help="fleet mode: sleep this long after every step "
                         "(pacing for real-subprocess elastic rounds)")
    ap.add_argument("--flightrec-dir", default=None,
                    help="fleet mode: dump the flight recorder as "
                         "flightrec-w<i>i<incarnation>.jsonl into this "
                         "dir on every exit path (postmortem --merge "
                         "input)")
    ap.add_argument("--async-save", action="store_true",
                    help="fleet mode: cadence saves go through the "
                         "background snapshot-then-commit writer "
                         "(emergency/preemption/final stay synchronous)")
    ap.add_argument("--async-kill-at", type=int, default=None,
                    help="fleet mode: SIGKILL inside the async commit "
                         "window (shards written, manifest NOT yet "
                         "published) of the first async save at/after "
                         "this GLOBAL step; gated on --fault-incarnation")
    ap.add_argument("--slow-writer-at", type=int, default=None,
                    help="fleet mode: stall the background writer before "
                         "the first async commit at/after this GLOBAL "
                         "step; gated on --fault-incarnation")
    ap.add_argument("--slow-writer-delay", type=float, default=1.0,
                    help="seconds --slow-writer-at stalls the writer")
    ap.add_argument("--strict-restore", action="store_true",
                    help="fleet mode: restore with fallback=False — the "
                         "ceiling step must verify and restore directly "
                         "(the async-kill round's proof that the torn "
                         "step is invisible, not quarantined)")
    ap.add_argument("--pod", type=int, default=None,
                    help="fleet mode: this worker's POD index under a "
                         "resilience/podfleet.py coordinator — --fleet-dir "
                         "is the pod's subdirectory, the GLOBAL_EPOCH file "
                         "lives one level up, and flight-recorder dumps "
                         "are named flightrec-p<pod>w<i>i<k>.jsonl")
    ap.add_argument("--fault-epoch", type=int, default=None,
                    help="pod mode: inject faults only when the global "
                         "epoch ALSO equals this — the second half of the "
                         "two-level (epoch, incarnation) fire-once fence")
    ap.add_argument("--pod-outage-at", type=int, default=None,
                    help="fleet mode: SIGKILL at this GLOBAL step (flight "
                         "recorder flushed first); give the same flag to "
                         "every worker of one pod and the pod dies as a "
                         "unit — the PodOutage round's scripted fault")
    ap.add_argument("--partition-at", type=int, default=None,
                    help="fleet mode: redirect heartbeat writes to a "
                         "shadow file starting at this GLOBAL step — the "
                         "control-plane partition: the process keeps "
                         "training while its liveness record goes stale")
    ap.add_argument("--partition-steps", type=int, default=3,
                    help="steps the --partition-at window lasts before the "
                         "real heartbeat path is restored (plus an "
                         "immediate beat)")
    ap.add_argument("--slow-beat-at", type=int, default=None,
                    help="fleet mode: delay every heartbeat write by "
                         "--slow-beat-delay for --slow-beat-steps steps "
                         "from this GLOBAL step (SlowControlPlane gray "
                         "failure — beats late but regular)")
    ap.add_argument("--slow-beat-delay", type=float, default=0.2,
                    help="seconds each slowed heartbeat write is delayed")
    ap.add_argument("--slow-beat-steps", type=int, default=3,
                    help="steps the --slow-beat-at window lasts")
    ap.add_argument("--p2p-catchup", action="store_true",
                    help="elastic mode: a rejoining replacement requests "
                         "the newest valid step from a live survivor "
                         "(resilience/fleet.request_catchup) before "
                         "restoring; survivors serve peer requests from "
                         "the step seam")
    ap.add_argument("--catchup-budget", type=float, default=15.0,
                    help="p2p mode: seconds the joiner waits for a "
                         "survivor's offer before falling back to "
                         "deterministic replay")
    args = ap.parse_args(argv)
    if args.fleet and not args.fleet_dir:
        raise SystemExit("--fleet requires --fleet-dir")

    import optax

    from distributed_tensorflow_tpu.models import MLP, MLPConfig, common
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.resilience import (
        DataError, FaultPlan, Sigterm,
    )
    from distributed_tensorflow_tpu.train import (
        CheckpointConfig, Checkpointer, StepOptions, Trainer,
        callbacks as cb, init_or_restore, make_train_step,
    )

    mesh = build_mesh(MeshSpec(data=-1))
    model = MLP(MLPConfig(hidden_sizes=(16,), num_classes=4))
    tx = optax.adam(1e-2)

    if args.fleet:
        return _fleet(args, mesh, model, tx)
    if args.supervise:
        return _supervised(args, mesh, model, tx)
    ckpt = Checkpointer(
        CheckpointConfig(directory=args.workdir, save_interval_steps=10**6,
                         async_save=False, preemption_check_every=1),
        mesh,
    )
    state, specs, restored = init_or_restore(
        ckpt, common.make_init_fn(model, (8,)), tx, mesh, jax.random.PRNGKey(0)
    )
    start = int(state.step)

    faults = []
    if args.sigterm_at is not None:
        # FaultCallback sees the trainer's GLOBAL step — no offset
        if args.sigterm_at <= start:
            raise SystemExit(f"--sigterm-at {args.sigterm_at} is already "
                             f"behind the restored step {start}")
        faults.append(Sigterm(args.sigterm_at))
    if args.data_error_at is not None:
        # iterator batches are 1-based PER PROCESS: batch i = step start+i
        if args.data_error_at <= start:
            raise SystemExit(f"--data-error-at {args.data_error_at} is "
                             f"already behind the restored step {start}")
        faults.append(DataError(args.data_error_at - start))
    plan = FaultPlan(tuple(faults))

    trainer = Trainer(
        make_train_step(common.classification_loss_fn(model), tx,
                        StepOptions()),
        state, mesh, specs,
        callbacks=[cb.CheckpointCallback(ckpt), plan.callback()],
    )

    def batches():
        i = start
        while True:
            i += 1
            yield global_step_batch(i)

    try:
        state = trainer.fit(plan.wrap(batches()), num_steps=args.steps)
    except IOError:
        saved = ckpt.latest_step()
        ckpt.close()
        print(f"CHAOS-DATAFAULT saved={saved}", flush=True)
        return 0
    saved = ckpt.latest_step()
    ckpt.close()
    if "preempted" in (trainer._stop_reason or ""):
        print(f"CHAOS-PREEMPTED step={saved}", flush=True)
        return 0
    if int(state.step) != args.steps:
        print(f"CHAOS-SHORT step={int(state.step)} want={args.steps}",
              flush=True)
        return 1
    if args.out:
        leaves = jax.tree.leaves(jax.device_get(state.params))
        np.savez(args.out, **{f"p{i}": np.asarray(x)
                              for i, x in enumerate(leaves)})
    print(f"CHAOS-DONE step={int(state.step)}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
