"""Hierarchical fault domains (resilience/podfleet.py): two-level
``(global_epoch, pod_incarnation)`` fencing on the POD_PLAN channel,
the hierarchical restore-ceiling math (property-style: two-level ==
flat intersection when every pod is live; a dead pod's stale dirs can
never veto a healthy pod's quorum — the PR 12 subset invariants, one
level up), the partition-fencing judgment driven by scripted fake
workers on an injected clock (fence on stale-file + live-process,
unfence on heal, escalate past the fence budget), pod-local restart at
the pod's OWN quorum, and a 2-pod coordinator run to one global
``fleet_done``."""

import os
import random

from distributed_tensorflow_tpu import resilience as rz
from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.resilience import fleet as fl
from distributed_tensorflow_tpu.resilience import podfleet as pf
from distributed_tensorflow_tpu.runtime import io as io_lib

import pytest


# ---------------------------------------------------------------------------
# Control-plane files: global epoch, POD_PLAN epoch fencing
# ---------------------------------------------------------------------------


def test_global_epoch_file(tmp_path):
    d = str(tmp_path)
    assert pf.read_global_epoch(d) == 0          # absent reads as 0
    pf.write_global_epoch(d, 3)
    assert pf.read_global_epoch(d) == 3
    (tmp_path / pf.GLOBAL_EPOCH_FILE).write_text("{garbage")
    assert pf.read_global_epoch(d) == 0          # unreadable == absent


def test_pod_plan_roundtrip_and_epoch_fencing(tmp_path):
    d = str(tmp_path)
    plan = pf.PodPlan(version=2, phase=fl.PLAN_STEADY, world=2,
                      ranks={0: 0, 1: 1}, barrier_step=4, epoch=5,
                      num_pods=2)
    pf.write_pod_plan(d, plan)
    assert pf.read_pod_plan(d, epoch=5) == plan
    # THE two-level fencing rule: a plan stamped with another global
    # epoch reads as ABSENT — a survivor of epoch 6 can never act on
    # epoch 5's leftover hold, and a partitioned pod's stale plan can
    # never be mistaken for current
    assert pf.read_pod_plan(d, epoch=6) is None
    assert pf.read_pod_plan(d, epoch=4) is None
    assert pf.read_pod_plan(d) == plan           # unfenced read for tools
    pf.clear_pod_plan(d)
    assert pf.read_pod_plan(d) is None


def test_pod_plan_validation(tmp_path):
    with pytest.raises(ValueError):
        pf.PodPlan(version=0, phase=fl.PLAN_STEADY, world=1, ranks={0: 0},
                   barrier_step=0)
    with pytest.raises(ValueError):  # ranks must be a bijection
        pf.PodPlan(version=1, phase=fl.PLAN_STEADY, world=2,
                   ranks={0: 0, 1: 0}, barrier_step=0)
    (tmp_path / pf._POD_PLAN_FILE).write_text("{not json")
    assert pf.read_pod_plan(str(tmp_path)) is None  # unreadable == absent


# ---------------------------------------------------------------------------
# Hierarchical ceiling math (property-style)
# ---------------------------------------------------------------------------


def _fake_ckpt_step(ckpt_dir, step, nbytes=64):
    d = os.path.join(ckpt_dir, str(step))
    os.makedirs(d, exist_ok=True)
    shard = os.path.join(d, "shard.bin")
    with open(shard, "wb") as f:
        # seeded: fabricated evidence must be reproducible under replay
        f.write(random.Random(1000 + step).randbytes(nbytes))
    payload = (
        '{"step": %d, "files": [{"path": "shard.bin", "bytes": %d}]}'
        % (step, nbytes)
    ).encode()
    io_lib.write_payload(os.path.join(d, "MANIFEST.dtf"), payload)
    return shard


def test_two_level_ceiling_equals_flat_when_all_live(tmp_path):
    """All pods healthy ⇒ the hierarchical ceiling (cross-pod
    intersection of per-pod quorum sets) IS the flat all-worker
    intersection: set intersection is associative, so grouping by pod
    must change nothing. Property-checked over seeded random step
    layouts."""
    rng = random.Random(19)
    for trial in range(20):
        base = tmp_path / f"t{trial}"
        num_pods = rng.randint(1, 3)
        pod_dirs: dict[int, list[str]] = {}
        flat: list[str] = []
        for p in range(num_pods):
            dirs = []
            for w in range(rng.randint(1, 3)):
                ck = str(base / f"p{p}w{w}")
                for step in rng.sample(range(1, 9), rng.randint(0, 5)):
                    _fake_ckpt_step(ck, step)
                os.makedirs(ck, exist_ok=True)
                dirs.append(ck)
            pod_dirs[p] = dirs
            flat.extend(dirs)
        assert pf.hierarchical_common_step(pod_dirs) \
            == fl.newest_common_valid_step(flat), (trial, pod_dirs)


def test_per_pod_quorum_is_flat_intersection_within_pod(tmp_path):
    w0, w1 = str(tmp_path / "w0"), str(tmp_path / "w1")
    for s in (2, 4, 6):
        _fake_ckpt_step(w0, s)
    for s in (2, 4):
        _fake_ckpt_step(w1, s)
    assert pf.pod_quorum_step([w0, w1]) == 4
    assert pf.pod_valid_step_sets({0: [w0, w1]}) == {0: {2, 4}}


def test_dead_pod_cannot_veto_live_quorum(tmp_path):
    """The one-level-up mirror of the PR 12 subset invariant: a dead
    pod's stale checkpoint dirs are EXCLUDED from the cross-pod
    intersection, so they can never drag a healthy pod's restore
    ceiling down (or pin it to a fresh start)."""
    a0, a1 = str(tmp_path / "a0"), str(tmp_path / "a1")
    b0, b1 = str(tmp_path / "b0"), str(tmp_path / "b1")
    for s in (2, 4, 6):
        _fake_ckpt_step(a0, s)
        _fake_ckpt_step(a1, s)
    _fake_ckpt_step(b0, 2)  # pod 1 died long ago, holding only step 2
    os.makedirs(b1, exist_ok=True)  # ...and one member with NOTHING
    dirs = {0: [a0, a1], 1: [b0, b1]}
    # flat view: pod 1's empty member pins everyone to a fresh start
    assert pf.hierarchical_common_step(dirs) == 0
    # hierarchical view: pod 1 is dead — pod 0 restores ITS OWN quorum
    assert pf.hierarchical_common_step(dirs, live_pods={0}) == 6
    # and a dead pod that never wrote anything is just as harmless
    assert pf.hierarchical_common_step({0: [a0, a1], 1: []},
                                       live_pods={0}) == 6
    # degenerate inputs keep the flat contract: no pods -> None
    assert pf.hierarchical_common_step({}) is None
    assert pf.hierarchical_common_step(dirs, live_pods=set()) is None


# ---------------------------------------------------------------------------
# PodSupervisor: scripted fake workers on an injected clock
# ---------------------------------------------------------------------------


class FakeProc:
    """The Popen control surface the fleet drives, fully scripted."""

    _next_pid = 2000

    def __init__(self):
        FakeProc._next_pid += 1
        self.pid = FakeProc._next_pid
        self.rc = None

    def poll(self):
        return self.rc

    def terminate(self):
        if self.rc is None:
            self.rc = fl.EXIT_PREEMPTED

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class Scenario:
    """Deterministic world driver: the fleet's injected ``sleep``
    advances the FaultClock and fires scheduled actions."""

    def __init__(self, clk):
        self.clk = clk
        self._events = []

    def at(self, t, fn):
        self._events.append([float(t), fn, False])

    def sleep(self, s):
        self.clk.advance(s)
        for ev in sorted(self._events, key=lambda e: e[0]):
            if not ev[2] and self.clk.t >= ev[0]:
                ev[2] = True
                ev[1]()


def _beat(workdir, worker, incarnation, clk, *, step=None, phase="train",
          restore=None, cause=None):
    w = fl.HeartbeatWriter(fl.heartbeat_path(workdir, worker),
                           incarnation=incarnation, clock=clk)
    if restore is not None:
        w.note_restore(restore, fallback=False)
    if cause is not None:
        w.finish(phase, cause=cause)
    else:
        w.beat(step=step, phase=phase)


def _mk_pod(tmp_path, launch, clk, scenario, *, pod=1, max_restarts=2,
            ckpt_dirs=None, pod_cfg=None, **cfg_kw):
    rec = FlightRecorder(clock=clk)
    reg = Registry()
    global_dir = str(tmp_path)
    pf.write_global_epoch(global_dir, 1)
    cfg = fl.FleetConfig(
        max_restarts=max_restarts,
        backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0),
        poll_s=1.0, heartbeat_timeout_s=5.0, stall_timeout_s=100.0,
        launch_grace_s=20.0, term_grace_s=4.0, **cfg_kw)
    sup = pf.PodSupervisor(
        pod, global_dir, 1, launch, 1, pf.pod_dir(global_dir, pod), cfg,
        pod_cfg=pod_cfg or pf.PodFleetConfig(),
        ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec, clock=clk,
        sleep=scenario.sleep)
    return sup, rec, reg


def test_pod_supervisor_fences_on_partition_and_unfences(tmp_path):
    """Heartbeat file frozen + process alive + beats seen before ⇒
    FENCE, not restart: the supervisor must take no action on the
    stale record. When the file thaws, it unfences — one fence, one
    unfence, ZERO restarts, and the fence window measured from its
    true start (no per-poll flapping)."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    workdir = str(tmp_path / "pod-1")
    procs = []

    def launch(i, incarnation):
        p = FakeProc()
        procs.append(p)
        _beat(workdir, i, incarnation, clk, step=3, phase="train")
        return p

    sup, rec, reg = _mk_pod(tmp_path, launch, clk, sc)
    # the file stays frozen past heartbeat_timeout_s (DEAD at t≈6)...
    # then thaws at t=12 and the worker finishes cleanly at t=14
    sc.at(12, lambda: _beat(workdir, 0, 1, clk, step=9, phase="train"))

    def finish():
        _beat(workdir, 0, 1, clk, step=10, phase="done")
        procs[0].rc = 0

    sc.at(14, finish)
    out = sup.run()
    assert out["restarts"] == 0, out
    kinds = [e["kind"] for e in rec.events()]
    assert kinds.count("pod_fence") == 1, kinds
    assert kinds.count("pod_unfence") == 1, kinds
    assert "pod_outage" not in kinds and "fleet_gang_stop" not in kinds
    unfence = next(e for e in rec.events() if e["kind"] == "pod_unfence")
    assert unfence["fenced_s"] > 1.0, unfence  # t0 survived the window
    # every pod-supervisor event carries the fault-domain tag
    assert all(e.get("pod") == 1 for e in rec.events()), rec.events()


def test_pod_supervisor_fence_timeout_escalates_to_outage(tmp_path):
    """A fence is a JUDGMENT WINDOW, not amnesty: past fence_timeout_s
    the stale-but-alive worker is treated as the outage it probably
    is. The gang path kills the live handle BEFORE relaunching — the
    no-split-brain guarantee even when the judgment was wrong."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    workdir = str(tmp_path / "pod-1")
    procs = []

    def launch(i, incarnation):
        p = FakeProc()
        procs.append(p)
        if incarnation == 1:
            _beat(workdir, i, incarnation, clk, step=3, phase="train")
            # ...then the heartbeat freezes forever
        else:
            _beat(workdir, i, incarnation, clk, step=3, phase="done")
            p.rc = 0
        return p

    sup, rec, reg = _mk_pod(
        tmp_path, launch, clk, sc,
        pod_cfg=pf.PodFleetConfig(fence_timeout_s=8.0))
    out = sup.run()
    assert out["restarts"] == 1, out
    kinds = [e["kind"] for e in rec.events()]
    assert "pod_fence" in kinds and "pod_outage" in kinds
    assert kinds.index("pod_fence") < kinds.index("pod_outage")
    # the fenced original was killed before its replacement launched:
    # both ran, but never concurrently
    assert procs[0].rc is not None and len(procs) == 2
    outage = next(e for e in rec.events() if e["kind"] == "pod_outage")
    assert "fence timeout" in str(outage.get("detail", "")) \
        or outage.get("cause"), outage


def test_pod_supervisor_fence_disabled_passes_through(tmp_path):
    clk = rz.FaultClock()
    sc = Scenario(clk)
    workdir = str(tmp_path / "pod-1")

    def launch(i, incarnation):
        p = FakeProc()
        if incarnation == 1:
            _beat(workdir, i, incarnation, clk, step=3, phase="train")
        else:
            _beat(workdir, i, incarnation, clk, step=3, phase="done")
            p.rc = 0
        return p

    sup, rec, reg = _mk_pod(
        tmp_path, launch, clk, sc,
        pod_cfg=pf.PodFleetConfig(fence_on_partition=False))
    out = sup.run()
    assert out["restarts"] == 1, out
    kinds = [e["kind"] for e in rec.events()]
    assert "pod_fence" not in kinds and "pod_outage" in kinds


def test_pod_outage_restarts_at_own_quorum(tmp_path):
    """A pod restart resumes at the POD's newest common valid step —
    the per-pod quorum, nobody else's intersection — and books the
    restart under its failure class."""
    clk = rz.FaultClock()
    sc = Scenario(clk)
    workdir = str(tmp_path / "pod-0")
    ck = str(tmp_path / "ckpt0")
    for s in (2, 4):
        _fake_ckpt_step(ck, s)
    procs = []

    def launch(i, incarnation):
        p = FakeProc()
        procs.append(p)
        if incarnation == 1:
            _beat(workdir, i, incarnation, clk, step=3, phase="train")
            sc.at(clk.t + 2, p.kill)  # SIGKILL mid-run
        else:
            # the restarted worker honours the ceiling and says so
            _beat(workdir, i, incarnation, clk, step=6, phase="done",
                  restore=4)
            p.rc = 0
        return p

    sup, rec, reg = _mk_pod(tmp_path, launch, clk, sc, pod=0,
                            ckpt_dirs=[ck])
    out = sup.run()
    assert out["restarts"] == 1, out
    assert fl.read_restore_step(workdir) == 4
    restart = next(e for e in rec.events() if e["kind"] == "pod_restart")
    assert restart["ceiling"] == 4 and restart["cause"] == rz.TRANSIENT
    assert restart["pod"] == 0
    counter = reg.get(pf.POD_RESTARTS_TOTAL, cause=rz.TRANSIENT)
    assert counter is not None and counter.value == 1


# ---------------------------------------------------------------------------
# PodFleetSupervisor: 2 pods to one global fleet_done
# ---------------------------------------------------------------------------


def test_coordinator_two_pods_run_to_global_done(tmp_path):
    """Two pods of already-done workers: one global epoch is minted
    and fenced into the plan, each pod supervisor runs under its pod
    tag, and the coordinator closes ONE untagged fleet_done — the
    merged timeline's single cross-pod upper anchor."""
    workdir = str(tmp_path / "fleet")

    def launch(p, i, incarnation):
        proc = FakeProc()
        w = fl.HeartbeatWriter(
            fl.heartbeat_path(pf.pod_dir(workdir, p), i),
            incarnation=incarnation)
        w.beat(step=5, phase="done")
        proc.rc = 0
        return proc

    rec = FlightRecorder()
    reg = Registry()
    cfg = fl.FleetConfig(
        max_restarts=1, backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0),
        poll_s=0.02, heartbeat_timeout_s=20.0, stall_timeout_s=600.0,
        launch_grace_s=60.0, term_grace_s=1.0)
    sup = pf.PodFleetSupervisor(
        launch, 2, 1, workdir, cfg=cfg,
        pod_cfg=pf.PodFleetConfig(poll_s=0.02),
        registry=reg, flightrec=rec)
    out = sup.run()
    assert out["epoch"] == 1 and out["restarts"] == 0, out
    assert out["pod_restarts"] == {0: 0, 1: 0}, out
    assert pf.read_global_epoch(workdir) == 1
    events = rec.events()
    done = [e for e in events if e["kind"] == "fleet_done"]
    # one untagged global fleet_done; each pod's own is pod-tagged
    assert sorted(e.get("pod") for e in done
                  if e.get("pod") is not None) == [0, 1]
    assert sum(1 for e in done if e.get("pod") is None) == 1
    assert reg.get(pf.FLEET_PODS_LIVE).value == 0
    # a second run mints the NEXT epoch — stale plans read as absent
    out2 = pf.PodFleetSupervisor(
        launch, 2, 1, workdir, cfg=cfg,
        pod_cfg=pf.PodFleetConfig(poll_s=0.02),
        registry=reg, flightrec=FlightRecorder()).run()
    assert out2["epoch"] == 2
    assert pf.read_pod_plan(workdir, epoch=1) is None
