"""tools/show_sharding.py — the placement-inspection surface referenced
by MIGRATION.md. Run as a subprocess (the tool owns its own device-count
setup), assert the plan it prints."""

import os
import subprocess
import pytest
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "show_sharding.py")


def _run(*args):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, TOOL, *args], capture_output=True, text=True,
        env=env, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


@pytest.mark.slow
def test_bert_tp_fsdp_plan():
    out = _run("bert_pretrain", "--mesh.data=2", "--mesh.fsdp=2",
               "--mesh.model=2")
    # megatron rules visible: qkv column-parallel, attn_out row-parallel
    assert "PartitionSpec(None, 'model')" in out
    assert "PartitionSpec('model', None)" in out
    # sharding actually reduces per-device bytes
    line = [l for l in out.splitlines() if "reduction" in l][0]
    factor = float(line.split("(")[1].split("x")[0])
    assert factor > 1.5, line


@pytest.mark.slow
def test_pipelined_plan_uses_explicit_specs():
    out = _run(
        "bert_pretrain", "--mesh.pipe=2", "--mesh.model=2", "--mesh.data=2",
        "--model.num_layers=2", "--model.d_model=32", "--model.num_heads=4",
        "--model.d_ff=64", "--model.vocab_size=128", "--data.vocab_size=128",
        "--data.seq_len=16", "--model.max_len=16",
    )
    # stacked [S, V, ...] leaves: pipe leads, model on kernel dims
    assert "PartitionSpec('pipe', None, None, 'model')" in out  # qkv kernel
    assert "PartitionSpec('pipe', None, 'model', None)" in out  # attn_out


def test_wildcard_mesh_with_nondividing_fixed_axis():
    """A -1 wildcard with a fixed axis that doesn't divide 8 (e.g.
    pipe=3) must still size a representable fake mesh (ADVICE r2:
    previously max(8, 3)=8, which 3 doesn't divide -> build_mesh fail)."""
    out = _run(
        "bert_pretrain", "--mesh.pipe=3", "--mesh.data=-1",
        "--model.num_layers=3", "--model.d_model=32", "--model.num_heads=4",
        "--model.d_ff=64", "--model.vocab_size=128", "--data.vocab_size=128",
        "--data.seq_len=16", "--model.max_len=16",
    )
    assert "pipe=3" in out


def test_rules_attribution_view():
    """--rules prints which table row won each param (index, regex,
    spec) — the coverage-failure debugging surface."""
    out = _run("wide_deep", "--rules", "--mesh.data=2", "--mesh.model=4")
    assert "table 'wide-deep': 3 rule(s)" in out
    assert (
        "table_0  <-  rule[0] '(^|/)table_\\\\d+$' "
        "-> PartitionSpec('model', None)" in out
    )
    assert (
        "wide_table_0  <-  rule[1] '(^|/)wide_table_\\\\d+$' "
        "-> PartitionSpec('model', None)" in out
    )
    assert "deep_0/kernel  <-  rule[2] '.*' -> PartitionSpec()" in out
    assert "UNMATCHED" not in out and "DEAD" not in out
