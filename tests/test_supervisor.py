"""In-process Supervisor (resilience/supervisor.py): failure
classification, restart budget, fallback restore at restart boundaries,
the reproducible-recovery acceptance gate, and the telemetry
merge-not-reset invariant across restarts."""

import signal

import jax
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu import resilience as rz
from distributed_tensorflow_tpu.data.pipeline import RetryingIterator
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.train import (
    CheckpointConfig,
    Checkpointer,
    Trainer,
    callbacks as cb,
    init_or_restore,
    make_train_step,
)

from test_step import linear_init, linear_loss, make_batch


def _global_batch(i):
    """The batch feeding GLOBAL step i — pure function of i, so resumed
    attempts replay exactly what the straight run would have seen."""
    return make_batch(16, seed=1000 + i)


def _batches_from(i0):
    i = i0
    while True:
        i += 1
        yield _global_batch(i)


def _fast_cfg(**kw):
    base = dict(backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0))
    base.update(kw)
    return rz.SupervisorConfig(**base)


def _builder(workdir, mesh, plan, registry, *, tx, save_every=1,
             retry_policy=None, extra_cbs=lambda: [], starts=None):
    """A production-shaped attempt builder: fresh Checkpointer (fresh
    signal watcher), fallback restore, Trainer + fault seams, data
    re-seekable at the restored step."""

    def build(restart_index):
        ckpt = Checkpointer(
            CheckpointConfig(directory=str(workdir),
                             save_interval_steps=save_every,
                             async_save=False, save_on_preemption=True,
                             preemption_check_every=1),
            mesh, registry=registry,
        )
        state, specs, _ = init_or_restore(
            ckpt, linear_init, tx, mesh, jax.random.PRNGKey(0),
            fallback=True,
        )
        start = int(state.step)
        if starts is not None:
            starts.append(start)
        # observers (telemetry) go FIRST: maybe_save raises
        # PreemptionSaved from CheckpointCallback.on_step_end, which
        # skips every later callback for that step — a sink placed after
        # it would miss the final, checkpointed step of the attempt
        trainer = Trainer(
            make_train_step(linear_loss, tx), state, mesh, specs,
            callbacks=extra_cbs()
            + [cb.CheckpointCallback(ckpt), plan.callback()],
        )
        data = RetryingIterator(
            lambda i: plan.wrap(_batches_from(i), start=i),
            retry_policy or rz.RetryPolicy(max_attempts=4, base_s=0.0,
                                           jitter=0.0),
            start_index=start, registry=registry, sleep=lambda s: None,
        )
        return trainer, data, ckpt

    return build


def _params(state):
    return [np.asarray(x) for x in
            jax.tree.leaves(jax.device_get(state.params))]


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def test_classify_failure():
    assert rz.classify_failure(IOError("io")) == rz.TRANSIENT
    assert rz.classify_failure(TimeoutError("t")) == rz.TRANSIENT
    assert rz.classify_failure(FloatingPointError("nan")) == rz.POISONED
    assert rz.classify_failure(ValueError("bug")) == rz.FATAL
    assert rz.classify_failure(KeyboardInterrupt()) == rz.FATAL
    ex = rz.RetryExhausted("s", 3, "attempt budget", IOError("x"))
    ex.__cause__ = IOError("x")
    assert rz.classify_failure(ex) == rz.TRANSIENT
    with pytest.raises(ValueError):
        rz.SupervisorConfig(restart_on=("meteor",))


# ---------------------------------------------------------------------------
# restart paths
# ---------------------------------------------------------------------------


def test_supervisor_restarts_after_preemption(mesh8, tmp_path):
    orig = signal.getsignal(signal.SIGTERM)
    try:
        reg = Registry()
        plan = rz.FaultPlan((rz.Sigterm(3),))
        starts = []
        sup = rz.Supervisor(
            _builder(tmp_path / "p", mesh8, plan, reg, tx=optax.sgd(0.1),
                     save_every=2, starts=starts),
            num_steps=8, cfg=_fast_cfg(), registry=reg,
            sleep=lambda s: None,
        )
        state = sup.run()
        assert int(state.step) == 8
        assert sup.restarts == 1
        # SIGTERM after step 3 → coordinated save at 4 → resume from 4
        assert starts == [0, 4]
        assert reg.get("supervisor_restarts_total",
                       cause="preemption").value == 1.0
    finally:
        signal.signal(signal.SIGTERM, orig)


def test_supervisor_fatal_passes_through_no_restart(mesh8, tmp_path):
    reg = Registry()

    class Boom(cb.Callback):
        def on_step_end(self, trainer, step, metrics):
            if step == 2:
                raise ValueError("a bug, not the weather")

    sup = rz.Supervisor(
        _builder(tmp_path / "f", mesh8, rz.FaultPlan(), reg,
                 tx=optax.sgd(0.1), extra_cbs=lambda: [Boom()]),
        num_steps=8, cfg=_fast_cfg(), registry=reg, sleep=lambda s: None,
    )
    with pytest.raises(ValueError, match="a bug"):
        sup.run()
    assert sup.restarts == 0
    assert reg.total("supervisor_restarts_total") == 0.0


def test_supervisor_transient_build_failure_earns_restart(mesh8, tmp_path):
    """A transient failure during attempt CONSTRUCTION (e.g. restore-time
    IO) is classified and restarted like one during fit — build runs
    inside the supervised attempt."""
    reg = Registry()
    flaky = {"n": 1}
    inner = _builder(tmp_path / "b", mesh8, rz.FaultPlan(), reg,
                     tx=optax.sgd(0.1), save_every=2)

    def build(restart_index):
        if flaky["n"] > 0:
            flaky["n"] -= 1
            raise IOError("restore-time blip")
        return inner(restart_index)

    sup = rz.Supervisor(build, num_steps=4, cfg=_fast_cfg(), registry=reg,
                        sleep=lambda s: None)
    state = sup.run()
    assert int(state.step) == 4
    assert sup.restarts == 1
    assert reg.get("supervisor_restarts_total",
                   cause="transient").value == 1.0


def test_supervisor_retry_exhausted_classified_and_counted(mesh8, tmp_path):
    """Acceptance gate, exhaustion half: a permanent IO fault exhausts
    the data retry budget in every attempt, the supervisor restarts it
    as `transient` until ITS budget exhausts, and the counters account
    for every give-up exactly."""
    reg = Registry()
    plan = rz.FaultPlan((rz.TransientIOError(batch=3, times=10 ** 9),))
    sup = rz.Supervisor(
        _builder(tmp_path / "x", mesh8, plan, reg, tx=optax.sgd(0.1),
                 save_every=2,
                 retry_policy=rz.RetryPolicy(max_attempts=3, base_s=0.0,
                                             jitter=0.0)),
        num_steps=8, cfg=_fast_cfg(max_restarts=2), registry=reg,
        sleep=lambda s: None,
    )
    with pytest.raises(rz.SupervisorExhausted) as ei:
        sup.run()
    assert ei.value.cause == rz.TRANSIENT
    assert ei.value.restarts == 2
    assert isinstance(ei.value.__cause__, rz.RetryExhausted)
    # 3 attempts (1 + 2 restarts), each exhausting one data retry budget
    assert reg.get("retry_exhausted_total", site="data").value == 3.0
    # each attempt burned max_attempts-1 = 2 re-seeks
    assert reg.get("retry_attempts_total", site="data").value == 6.0
    assert reg.get("supervisor_restarts_total",
                   cause="transient").value == 2.0


def test_supervisor_transient_hook_failure_earns_restart(mesh8, tmp_path):
    """An on_restart hook that hits transient IO at the restart boundary
    is classified and restarted like any attempt failure — and re-runs
    on the next attempt (hooks must be idempotent)."""
    orig = signal.getsignal(signal.SIGTERM)
    try:
        reg = Registry()
        plan = rz.FaultPlan((rz.Sigterm(3),))
        hook_calls = []

        def flaky_hook(restart_index, cause):
            hook_calls.append((restart_index, cause))
            if len(hook_calls) == 1:
                raise IOError("boundary disk blip")

        sup = rz.Supervisor(
            _builder(tmp_path / "h", mesh8, plan, reg, tx=optax.sgd(0.1),
                     save_every=2),
            num_steps=8, cfg=_fast_cfg(), registry=reg,
            on_restart=[flaky_hook], sleep=lambda s: None,
        )
        state = sup.run()
        assert int(state.step) == 8
        # restart 1: preemption; its hook raised -> restart 2: transient;
        # the hook re-ran (idempotently) and the run completed
        assert sup.restarts == 2
        assert hook_calls == [(1, "preemption"), (2, "transient")]
        assert reg.get("supervisor_restarts_total",
                       cause="preemption").value == 1.0
        assert reg.get("supervisor_restarts_total",
                       cause="transient").value == 1.0
    finally:
        signal.signal(signal.SIGTERM, orig)


# ---------------------------------------------------------------------------
# THE acceptance criterion: seeded multi-fault recovery, bit-identical
# ---------------------------------------------------------------------------


def _seeded_recovery_run(workdir, mesh, seed, registry):
    # seed 1 at 10 steps places: TransientIOError(batch=3, times=2)
    # (absorbed mid-attempt by the re-seeking iterator), Sigterm(step=4)
    # (preemption save at 5, in-process restart), CorruptCheckpoint at
    # the restart boundary (fallback restore must quarantine the newest
    # step and land on an older valid one).
    plan = rz.FaultPlan.seeded(
        seed, 10, kinds=("sigterm", "transient_io", "ckpt_corrupt"))
    sup = rz.Supervisor(
        _builder(workdir, mesh, plan, registry, tx=optax.adam(1e-2),
                 save_every=1),
        num_steps=10, cfg=_fast_cfg(max_restarts=4), registry=registry,
        on_restart=[plan.restart_hook(str(workdir))],
        sleep=lambda s: None,
    )
    return sup.run(), sup


def test_supervisor_seeded_recovery_bit_identical(mesh8, tmp_path):
    orig = signal.getsignal(signal.SIGTERM)
    try:
        reg_a, reg_b = Registry(), Registry()
        state_a, sup_a = _seeded_recovery_run(tmp_path / "a", mesh8, 1, reg_a)
        state_b, sup_b = _seeded_recovery_run(tmp_path / "b", mesh8, 1, reg_b)
        assert int(state_a.step) == int(state_b.step) == 10
        assert sup_a.restarts == sup_b.restarts == 1
        # the corrupt newest checkpoint was quarantined, not reused
        assert (tmp_path / "a" / ".corrupt").is_dir()
        assert (tmp_path / "b" / ".corrupt").is_dir()
        # the transient data fault was absorbed by re-seek, not a restart
        assert reg_a.get("retry_attempts_total", site="data").value == 2.0
        assert reg_a.get("retry_exhausted_total", site="data").value == 0.0
        # the corrupt newest step IS one exhausted verify budget — real
        # corruption survives the transient-blip retries, then quarantines
        assert reg_a.get("retry_exhausted_total",
                         site="ckpt_verify").value == 1.0
        # recovery is exactly reproducible: params BIT-identical
        pa, pb = _params(state_a), _params(state_b)
        assert len(pa) == len(pb) and pa
        for a, b in zip(pa, pb):
            np.testing.assert_array_equal(a, b)
    finally:
        signal.signal(signal.SIGTERM, orig)


# ---------------------------------------------------------------------------
# telemetry invariant across restarts (registry merges, never resets)
# ---------------------------------------------------------------------------


def test_telemetry_accumulates_across_supervised_restarts(mesh8, tmp_path):
    orig = signal.getsignal(signal.SIGTERM)
    try:
        reg = Registry()
        plan = rz.FaultPlan((rz.Sigterm(3),))
        sup = rz.Supervisor(
            _builder(
                tmp_path / "t", mesh8, plan, reg, tx=optax.sgd(0.1),
                save_every=2,
                extra_cbs=lambda: [cb.TelemetryCallback(registry=reg,
                                                        every_n=100)],
            ),
            num_steps=8, cfg=_fast_cfg(), registry=reg,
            sleep=lambda s: None,
        )
        state = sup.run()
        assert int(state.step) == 8 and sup.restarts == 1
        attempts = sup.restarts + 1
        # attempt 0 executed steps 1..4, attempt 1 resumed 4 → 5..8:
        # every completed step ticked the counter exactly once — the
        # PR 3 invariant holds ACROSS the restart boundary because the
        # shared registry merges; a reset would drop attempt 0's 4 steps
        steps_total = reg.get("train_steps_total").value
        assert steps_total == 8.0
        # the per-step latency histogram observes every step except the
        # first of each attempt (no previous dispatch to measure from)
        hist = reg.get("train_step_seconds")
        assert hist.count == steps_total - attempts
        assert reg.get("supervisor_restarts_total",
                       cause="preemption").value == 1.0
    finally:
        signal.signal(signal.SIGTERM, orig)


# ---------------------------------------------------------------------------
# Stalled attempts (Watchdog abort_on_stall) + interruptible backoff
# (ISSUE 8 satellites)
# ---------------------------------------------------------------------------


def test_classify_stalled():
    assert rz.classify_failure(rz.StalledError()) == rz.STALLED
    assert rz.STALLED in rz.SupervisorConfig().restart_on
    rz.SupervisorConfig(restart_on=(rz.STALLED,))  # accepted explicitly


def test_supervisor_restarts_on_stalled_attempt(mesh8, tmp_path):
    """A hung step (Hang fault spinning the loop) is converted by the
    Watchdog's abort_on_stall into a StalledError, classified 'stalled',
    and restarted from the last checkpoint — the attempt finishes at the
    target step on its second life."""
    import threading

    reg = Registry()
    fclk = rz.FaultClock()
    plan = rz.FaultPlan((rz.Hang(3, advance=600.0),))
    tx = optax.sgd(0.1)

    def build(restart_index):
        ckpt = Checkpointer(
            CheckpointConfig(directory=str(tmp_path), save_interval_steps=1,
                             async_save=False, preemption_check_every=1),
            mesh8, registry=reg,
        )
        state, specs, _ = init_or_restore(
            ckpt, linear_init, tx, mesh8, jax.random.PRNGKey(0),
            fallback=True,
        )
        start = int(state.step)
        wd = cb.Watchdog(budget_s=300.0, registry=reg, poll_s=0.005,
                         clock=fclk, abort_on_stall=True)
        trainer = Trainer(
            make_train_step(linear_loss, tx), state, mesh8, specs,
            # checkpoint BEFORE the fault callback: step 3 is saved
            # before the hang, so the restart resumes past it
            callbacks=[wd, cb.CheckpointCallback(ckpt),
                       plan.callback(clock=fclk)],
        )
        return trainer, _batches_from(start), ckpt

    sup = rz.Supervisor(build, num_steps=6, cfg=_fast_cfg(max_restarts=2),
                        registry=reg, sleep=lambda s: None)
    state = sup.run()
    assert int(state.step) == 6
    assert sup.restarts == 1
    assert reg.get("supervisor_restarts_total", cause=rz.STALLED).value == 1
    assert reg.get("train_watchdog_stalls_total").value >= 1
    assert threading.active_count() < 20  # watchdog threads joined


def test_backoff_wait_wakes_on_sigterm_and_redelivers():
    """SIGTERM during a restart backoff must wake the sleep immediately
    and re-deliver to the handler that owned the signal before the
    backoff — the preemption is processed at once, not after up to a
    full backoff interval."""
    import os
    import threading
    import time

    received = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: received.append(s))
    try:
        sup = rz.Supervisor(lambda i: (None, [], None), num_steps=1)
        threading.Timer(
            0.2, lambda: os.kill(os.getpid(), signal.SIGTERM)).start()
        t0 = time.monotonic()
        sup._backoff_wait(60.0)
        assert time.monotonic() - t0 < 30.0
        deadline = time.monotonic() + 5.0
        while not received and time.monotonic() < deadline:
            time.sleep(0.001)  # re-delivered signal is async
        assert received == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_backoff_wait_interrupt_not_lost_but_consumed():
    import time

    sup = rz.Supervisor(lambda i: (None, [], None), num_steps=1)
    sup.interrupt()  # before the wait: the wakeup must not be lost
    t0 = time.monotonic()
    sup._backoff_wait(60.0)
    assert time.monotonic() - t0 < 30.0
    # ...but it is consumed: the NEXT backoff waits its delay again
    # (a sticky event would turn every later restart into a zero-delay
    # restart storm)
    t0 = time.monotonic()
    sup._backoff_wait(0.3)
    assert time.monotonic() - t0 >= 0.25


def test_backoff_wait_injected_sleep_bypasses_signals():
    slept = []
    sup = rz.Supervisor(lambda i: (None, [], None), num_steps=1,
                        sleep=slept.append)
    sup._backoff_wait(3.5)
    assert slept == [3.5]
