"""Flight recorder + goodput accounting (obs/flightrec.py, obs/goodput.py):
ring-buffer eviction under overflow, emit thread-safety under concurrent
emitters, dump schema round-trip through the shared validator,
dump-on-SupervisorExhausted, and the goodput/MFU arithmetic the gauges
promise (ISSUE 6)."""

import json
import threading

import pytest

from distributed_tensorflow_tpu import obs
from distributed_tensorflow_tpu.obs import flightrec as fr
from distributed_tensorflow_tpu.obs import goodput


class TickClock:
    """Deterministic monotonic clock: +dt per call."""

    def __init__(self, dt=1.0, t0=0.0):
        self.t, self.dt = t0, dt

    def __call__(self):
        self.t += self.dt
        return self.t


# ---------------------------------------------------------------------------
# Ring semantics
# ---------------------------------------------------------------------------


def test_ring_eviction_under_overflow():
    rec = fr.FlightRecorder(capacity=3, clock=TickClock())
    for i in range(1, 8):
        rec.emit("step_end", step=i)
    assert len(rec) == 3
    assert rec.dropped == 4
    # newest-capacity survive, oldest first
    assert [e["step"] for e in rec.events()] == [5, 6, 7]
    rec.clear()
    assert len(rec) == 0 and rec.dropped == 0


def test_emit_rejects_unknown_kind_and_reserved_attrs():
    rec = fr.FlightRecorder(capacity=4)
    with pytest.raises(ValueError, match="unknown flight-recorder"):
        rec.emit("definitely_not_a_kind")
    with pytest.raises(ValueError, match="reserved"):
        rec.emit("note", kind_of="bad", t=1.0)
    with pytest.raises(ValueError):
        fr.FlightRecorder(capacity=0)


def test_emit_thread_safety_under_concurrent_emitters():
    """N threads hammering one ring: no exception, no lost accounting
    (len + dropped == total emits), timestamps non-decreasing in ring
    order — the invariant the dump validator enforces."""
    rec = fr.FlightRecorder(capacity=64)
    n_threads, per_thread = 8, 200
    barrier = threading.Barrier(n_threads)

    def emitter(k):
        barrier.wait()
        for i in range(per_thread):
            rec.emit("note", step=i, worker=k)

    threads = [threading.Thread(target=emitter, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = rec.events()
    assert len(events) == 64
    assert len(rec) + rec.dropped == n_threads * per_thread
    ts = [e["t"] for e in events]
    assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# Dump + validation
# ---------------------------------------------------------------------------


def test_dump_schema_roundtrip_and_validation(tmp_path):
    rec = fr.FlightRecorder(capacity=8, clock=TickClock(dt=0.5))
    rec.emit("train_start", step=0)
    rec.emit("fault_fired", step=3, fault="sigterm")
    rec.emit("train_stop", step=3, reason="preempted")
    path = rec.dump(str(tmp_path / "pm.jsonl"), reason="unit")
    assert fr.validate_dump(path) == []
    lines = [json.loads(line) for line in open(path)]
    header, events = lines[0], lines[1:]
    assert header["schema"] == fr.SCHEMA
    assert header["reason"] == "unit"
    assert header["events"] == 3 and header["dropped"] == 0
    assert [e["kind"] for e in events] == [
        "train_start", "fault_fired", "train_stop"]
    assert events[1]["fault"] == "sigterm" and events[1]["step"] == 3


def test_dump_unique_never_overwrites(tmp_path):
    rec = fr.FlightRecorder(capacity=4)
    rec.emit("note", msg="first")
    p1 = rec.dump_unique(str(tmp_path), reason="a")
    p2 = rec.dump_unique(str(tmp_path), reason="b")
    assert p1 != p2
    assert p1.endswith("postmortem.jsonl")
    assert p2.endswith("postmortem-1.jsonl")
    assert json.loads(open(p1).readline())["reason"] == "a"
    assert json.loads(open(p2).readline())["reason"] == "b"


def test_validate_dump_catches_violations(tmp_path):
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        json.dumps({"schema": "wrong", "events": 1}) + "\n"
        + '{"t": 2.0, "kind": "no_such_kind"}\n'
        + '{"t": 1.0, "kind": "note"}\n'
    )
    failures = fr.validate_dump(str(bad))
    assert any("schema" in f for f in failures)
    assert any("unknown event kind" in f for f in failures)
    assert any("decreases" in f for f in failures)
    assert any("dump has" in f for f in failures)
    assert fr.validate_dump(str(tmp_path / "missing.jsonl"))  # unreadable


def test_contains_in_order():
    events = [
        {"kind": "fault_fired", "fault": "sigterm", "t": 1},
        {"kind": "ckpt_save", "trigger": "preemption", "t": 2},
        {"kind": "sup_restart", "restart": 1, "t": 3},
        {"kind": "ckpt_restore", "fallback": True, "t": 4},
    ]
    assert fr.contains_in_order(events, ["fault_fired", "ckpt_restore"])
    assert fr.contains_in_order(events, [
        ("ckpt_save", {"trigger": "preemption"}),
        ("ckpt_restore", {"fallback": "True"}),  # str-compared: CLI-safe
    ])
    assert not fr.contains_in_order(events, ["ckpt_restore", "fault_fired"])
    assert not fr.contains_in_order(
        events, [("ckpt_save", {"trigger": "cadence"})])


# ---------------------------------------------------------------------------
# Dump on SupervisorExhausted
# ---------------------------------------------------------------------------


def test_supervisor_exhausted_dumps_postmortem(tmp_path):
    """When the restart budget runs out the Supervisor must leave a
    postmortem in the run dir: every attempt, its classified failure,
    the restarts, and the final sup_exhausted — in causal order and
    passing the shared schema validator."""
    from distributed_tensorflow_tpu import resilience as rz

    rec = fr.FlightRecorder(capacity=256)
    reg = obs.Registry()

    def build(restart_index):
        raise IOError(f"disk is gone (attempt {restart_index})")

    sup = rz.Supervisor(
        build, num_steps=4,
        cfg=rz.SupervisorConfig(
            max_restarts=2, backoff=rz.RetryPolicy(base_s=0.0, jitter=0.0)),
        registry=reg, sleep=lambda s: None, flightrec=rec,
        postmortem_dir=str(tmp_path),
    )
    with pytest.raises(rz.SupervisorExhausted):
        sup.run()
    dump = tmp_path / "postmortem.jsonl"
    assert dump.exists()
    assert fr.validate_dump(str(dump)) == []
    assert fr.contains_in_order(rec.events(), [
        ("sup_attempt", {"attempt": 0}),
        ("sup_failure", {"attempt": 0, "cause": "transient"}),
        ("sup_restart", {"restart": 1}),
        ("sup_attempt", {"attempt": 2}),
        ("sup_exhausted", {"cause": "transient", "restarts": 2}),
    ])


def test_supervisor_fatal_failure_recorded_not_dumped(tmp_path):
    """A non-restartable failure re-raises immediately: classified in
    the ring (sup_failure cause=fatal) but no exhaustion dump — the
    Trainer's own exception path owns that postmortem."""
    from distributed_tensorflow_tpu import resilience as rz

    rec = fr.FlightRecorder(capacity=64)

    def build(restart_index):
        raise ValueError("a bug, not the weather")

    sup = rz.Supervisor(
        build, num_steps=4, registry=obs.Registry(),
        sleep=lambda s: None, flightrec=rec, postmortem_dir=str(tmp_path),
    )
    with pytest.raises(ValueError):
        sup.run()
    assert not (tmp_path / "postmortem.jsonl").exists()
    assert fr.contains_in_order(
        rec.events(), [("sup_failure", {"cause": "fatal"})])


# ---------------------------------------------------------------------------
# Goodput accounting
# ---------------------------------------------------------------------------


def test_goodput_fraction_math_and_merge_survival():
    reg = obs.Registry()
    goodput.note_productive(6.0, registry=reg)
    goodput.note_wasted(goodput.WASTE_COMPILE_WARMUP, 1.0, registry=reg)
    goodput.note_wasted(goodput.WASTE_RETRY_BACKOFF, 0.5, registry=reg)
    goodput.note_wasted(goodput.WASTE_RESTART_RECOVERY, 0.5, registry=reg)
    assert reg.get(goodput.GOODPUT_FRACTION).value == pytest.approx(0.75)
    assert goodput.goodput_fraction(reg) == pytest.approx(0.75)
    assert reg.total(goodput.WASTED_SECONDS) == pytest.approx(2.0)
    with pytest.raises(ValueError):
        goodput.note_wasted("weather", 1.0, registry=reg)
    # seconds buckets are COUNTERS: an aggregator merge ADDS them, so
    # the accounting survives restart boundaries (merge-not-reset)
    agg = obs.Registry()
    agg.merge(reg)
    agg.merge(reg)
    assert agg.total(goodput.PRODUCTIVE_SECONDS) == pytest.approx(12.0)
    assert agg.total(goodput.WASTED_SECONDS) == pytest.approx(4.0)
    # gauge is point-in-time: merged latest-wins, still the true ratio
    assert agg.get(goodput.GOODPUT_FRACTION).value == pytest.approx(0.75)


def test_goodput_empty_registry_is_nan():
    import math

    assert math.isnan(goodput.goodput_fraction(obs.Registry()))


def test_train_mfu_applies_training_multiplier_and_sets_gauge():
    from distributed_tensorflow_tpu.utils import flops as flops_lib

    reg = obs.Registry()
    mfu = goodput.train_mfu(2e12, 1.0, n_chips=2, peak_per_chip=6e12,
                            registry=reg)
    # fwd 2e12 × ×3 × 1 step/s over 2 × 6e12 peak = 0.5
    assert mfu == pytest.approx(
        2e12 * flops_lib.train_flops_multiplier() / (2 * 6e12))
    assert mfu == pytest.approx(0.5)
    assert reg.get(goodput.MFU).value == pytest.approx(0.5)
    # registry=None: pure computation, no gauge side effect
    reg2 = obs.Registry()
    goodput.train_mfu(2e12, 1.0, n_chips=2, peak_per_chip=6e12)
    assert reg2.get(goodput.MFU) is None


def test_flops_from_compiled_cost_analysis():
    """The cost-analysis shim path: a compiled matmul's reported FLOPs
    feed the same MFU formula as the analytic count."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    x = np.zeros((8, 16), np.float32)
    w = np.zeros((16, 4), np.float32)
    compiled = jax.jit(lambda a, b: a @ b).lower(x, w).compile()
    flops = goodput.flops_per_step_from_compiled(compiled)
    if flops is None:
        pytest.skip("backend offers no cost analysis")
    # 2·m·n·k, exactly what the backend should report for one matmul
    assert flops == pytest.approx(2 * 8 * 16 * 4, rel=0.5)
    assert goodput.train_mfu(flops, 10.0, n_chips=1, peak_per_chip=1e12) \
        == pytest.approx(flops * 3 * 10.0 / 1e12)


def test_latency_percentiles_ms_matches_histogram():
    reg = obs.Registry()
    h = reg.histogram("lat_seconds")
    for v in (0.001, 0.004, 0.02, 0.1, 0.5):
        h.observe(v)
    out = goodput.latency_percentiles_ms(
        reg, "lat_seconds", quantiles=(0.5, 0.9, 0.99))
    assert set(out) == {"p50_ms", "p90_ms", "p99_ms"}
    for q, key in ((0.5, "p50_ms"), (0.9, "p90_ms"), (0.99, "p99_ms")):
        assert out[key] == pytest.approx(
            round(float(h.percentile(q)) * 1e3, 3))
    with pytest.raises(KeyError):
        goodput.latency_percentiles_ms(reg, "no_such_histogram")


def test_telemetry_callback_books_warmup_then_productive():
    """First completed step of an attempt books compile_warmup; later
    steps book productive seconds — and the fraction gauge tracks."""
    from distributed_tensorflow_tpu.train import callbacks as cb

    reg = obs.Registry()
    clock = TickClock(dt=1.0)
    tc = cb.TelemetryCallback(registry=reg, every_n=10 ** 6, clock=clock)
    tc.on_train_start(None)
    for step in (1, 2, 3, 4):
        tc.on_step_end(None, step, {})
    # start→step1 = 1s warmup; steps 2..4 = 3 × 1s productive
    assert reg.get(
        goodput.WASTED_SECONDS,
        cause=goodput.WASTE_COMPILE_WARMUP).value == pytest.approx(1.0)
    assert reg.get(goodput.PRODUCTIVE_SECONDS).value == pytest.approx(3.0)
    assert reg.get(goodput.GOODPUT_FRACTION).value == pytest.approx(0.75)
    # opt-out leaves the ledger untouched
    reg2 = obs.Registry()
    tc2 = cb.TelemetryCallback(registry=reg2, every_n=10 ** 6,
                               clock=TickClock(), track_goodput=False)
    tc2.on_train_start(None)
    for step in (1, 2):
        tc2.on_step_end(None, step, {})
    assert reg2.get(goodput.PRODUCTIVE_SECONDS) is None


def test_retry_backoff_feeds_wasted_seconds():
    """The ledger books ELAPSED wall time around the (injectable) sleep
    — a fake clock that the fake sleep advances sees exactly the backoff
    schedule; a no-op sleep under the same clock books ~nothing."""
    from distributed_tensorflow_tpu.resilience import RetryPolicy, retry_call

    reg = obs.Registry()
    rec = fr.FlightRecorder(capacity=16)
    calls = {"n": 0}
    t = {"now": 0.0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise IOError("blip")
        return "ok"

    policy = RetryPolicy(max_attempts=4, base_s=0.25, multiplier=2.0,
                         jitter=0.0)
    out = retry_call(
        flaky, policy=policy, site="unit", registry=reg, flightrec=rec,
        clock=lambda: t["now"],
        sleep=lambda s: t.__setitem__("now", t["now"] + s),
    )
    assert out == "ok"
    # two backoffs: 0.25 + 0.5 of fake wall time booked as waste
    assert reg.get(
        goodput.WASTED_SECONDS,
        cause=goodput.WASTE_RETRY_BACKOFF).value == pytest.approx(0.75)
    assert fr.contains_in_order(rec.events(), [
        ("retry_attempt", {"site": "unit", "failures": 1}),
        ("retry_attempt", {"site": "unit", "failures": 2}),
    ])
    # no-op sleep, frozen clock: nothing was actually waited → no waste
    reg2 = obs.Registry()
    calls["n"] = 0
    retry_call(flaky, policy=policy, site="unit", registry=reg2,
               clock=lambda: 7.0, sleep=lambda s: None)
    assert reg2.get(goodput.WASTED_SECONDS,
                    cause=goodput.WASTE_RETRY_BACKOFF) is None
