"""Native async snapshot-then-commit checkpointing (ISSUE 18).

The subprocess chaos round (tools/chaos_smoke.py async-kill) proves the
death-at-any-instant contract end-to-end; these tests pin the in-process
invariants it rests on: the staging dir keeps in-flight commits out of
retention's sight, back-to-back saves racing a slow writer still
converge to the retention set, the commit window is genuinely invisible
(shards staged, no digit dir, manifest last), and a failed background
commit poisons the run at the next save boundary instead of silently
skipping a step.
"""

import os
import threading

import jax
import optax
import pytest

from distributed_tensorflow_tpu.resilience import (
    AsyncCommitKill,
    FaultPlan,
    RetryExhausted,
    RetryPolicy,
    SlowWriter,
)
from distributed_tensorflow_tpu.train import (
    CheckpointConfig,
    Checkpointer,
    init_or_restore,
)

from test_step import linear_init


def _build(tmp_path, mesh8, name, **cfg_kw):
    cfg_kw.setdefault("async_save", True)
    cfg_kw.setdefault("save_on_preemption", False)
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / name), **cfg_kw),
        mesh8,
        io_retry=RetryPolicy(max_attempts=1, base_s=0.0),
    )
    state, specs, _ = init_or_restore(
        ckpt, linear_init, optax.sgd(0.1), mesh8, jax.random.PRNGKey(0)
    )
    return ckpt, state


def test_back_to_back_async_saves_race_retention(mesh8, tmp_path):
    """Three async saves queued while the FIRST commit is stalled by a
    SlowWriter (injected sleep seam — an Event, so the race is
    deterministic), with max_to_keep=2. Retention runs after each
    commit but only ever sees PUBLISHED digit dirs, so the stalled and
    queued writes are untouchable: after the drain the directory holds
    exactly the newest two steps, no staging residue, no quarantine."""
    ckpt, state = _build(tmp_path, mesh8, "race",
                         max_to_keep=2, save_interval_steps=2)
    release = threading.Event()
    plan = FaultPlan([SlowWriter(0, delay_s=10.0)])
    # fired-once: only the FIRST commit (step 2) blocks on the event
    ckpt.save_hooks.append(plan.save_hook(sleep=lambda s: release.wait(30)))

    assert ckpt.save(2, state, force=True)
    assert ckpt.save(4, state, force=True)  # queues behind the stall
    assert ckpt.save(6, state, force=True)
    base = tmp_path / "race"
    assert not (base / "2").exists()  # still staged, not published
    release.set()
    ckpt.wait()

    assert sorted(int(n) for n in os.listdir(base) if n.isdigit()) == [4, 6]
    pending = base / ".pending"
    assert not pending.exists() or not os.listdir(pending)
    assert not (base / ".corrupt").exists()
    assert ckpt.verify_manifest(4) is True
    assert ckpt.verify_manifest(6) is True
    assert ckpt.latest_step() == 6
    ckpt.close()


def test_commit_window_is_invisible_until_publish(mesh8, tmp_path):
    """Probed through the production hook seam at ``shards_done`` — the
    exact instant AsyncCommitKill SIGKILLs in the chaos round: every
    shard is already durable under ``.pending/<step>``, the manifest is
    NOT yet written, and no digit dir exists, so a death here leaves
    nothing any step-listing consumer can see."""
    ckpt, state = _build(tmp_path, mesh8, "win")
    seen = {}

    def probe(stage, step):
        if stage == "shards_done":
            pending = tmp_path / "win" / ".pending" / str(step)
            names = sorted(os.listdir(pending))
            seen["shards"] = [n for n in names if n.endswith(".dtf")]
            seen["manifest_staged"] = "MANIFEST.dtf" in names
            seen["published"] = (tmp_path / "win" / str(step)).exists()

    ckpt.save_hooks.append(probe)
    assert ckpt.save(2, state, force=True)
    ckpt.wait()
    assert seen["shards"], "no shards staged at shards_done"
    assert seen["manifest_staged"] is False  # manifest written LAST
    assert seen["published"] is False        # rename is the commit point
    assert ckpt.verify_manifest(2) is True   # ...and after it, all there
    ckpt.close()


def test_failed_background_commit_poisons_next_save(mesh8, tmp_path):
    """A background commit that exhausts its retry budget must fail the
    RUN at the next save()/wait() — raise-once with the original error —
    and leave no staging residue behind."""
    ckpt, state = _build(tmp_path, mesh8, "err")
    armed = [True]

    def explode(stage, step):
        if stage == "shards_done" and armed[0]:
            armed[0] = False
            raise OSError("disk gone mid-commit")

    ckpt.save_hooks.append(explode)
    assert ckpt.save(2, state, force=True)
    # surfaced as the retry layer's exhaustion, original OSError chained
    with pytest.raises(RetryExhausted, match="disk gone"):
        ckpt.wait()
    ckpt.wait()  # raise-once: the error was surfaced, not resurfaced
    assert not (tmp_path / "err" / "2").exists()  # never published
    pending = tmp_path / "err" / ".pending"
    assert not pending.exists() or not os.listdir(pending)
    # the writer is not wedged: the next save commits normally
    assert ckpt.save(4, state, force=True)
    ckpt.wait()
    assert ckpt.latest_step() == 4
    ckpt.close()


def test_async_commit_kill_fires_once_at_shards_done(mesh8, tmp_path):
    """The AsyncCommitKill seam itself (SIGKILL replaced by recording —
    the real kill is the chaos round's job): it must trigger at
    ``shards_done`` of the armed step and never again on a rebuilt
    hook list, the fire-once contract every plan fault carries."""
    fired = []

    class _Recorder(FaultPlan):
        pass

    plan = _Recorder([AsyncCommitKill(4)])
    # monkeypatch the kill: record instead of dying
    import distributed_tensorflow_tpu.resilience.faults as faults_mod

    orig_kill = faults_mod.os.kill
    faults_mod.os.kill = lambda pid, sig: fired.append(sig)
    try:
        ckpt, state = _build(tmp_path, mesh8, "kill")
        ckpt.save_hooks.append(plan.save_hook())
        assert ckpt.save(2, state, force=True)
        ckpt.wait()
        assert fired == []  # below the armed step
        assert ckpt.save(4, state, force=True)
        ckpt.wait()
        assert len(fired) == 1
        # a rebuilt hook list (supervisor restart) must not re-fire
        ckpt.save_hooks[:] = [plan.save_hook()]
        assert ckpt.save(6, state, force=True)
        ckpt.wait()
        assert len(fired) == 1
        ckpt.close()
    finally:
        faults_mod.os.kill = orig_kill
