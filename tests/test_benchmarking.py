"""utils/benchmarking — the shared harness scaffolding both benches
(bench.py, tools/bench_bert.py) depend on for honest numbers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.utils import benchmarking as bm


def test_describe_devices_cpu_rig():
    devices, n, platform, on_tpu = bm.describe_devices()
    assert n == len(jax.devices()) >= 1
    assert platform == "cpu" and not on_tpu


def test_timed_steps_counts_and_syncs():
    calls = []

    def step(state, batch):
        calls.append(batch)
        return state + batch, {"loss": jnp.asarray(float(state + batch))}

    state, sps, loss = bm.timed_steps(
        step, 0.0, lambda: 1.0, warmup=2, measured=5,
    )
    # warmup + measured steps all ran; state chained through every one
    assert len(calls) == 7
    assert state == 7.0
    assert loss == 7.0
    assert sps > 0


def test_timed_steps_rejects_nonfinite_loss():
    def step(state, batch):
        return state, {"loss": jnp.asarray(float("nan"))}

    # RuntimeError, not assert: must fire even under `python -O`
    with pytest.raises(RuntimeError, match="non-finite"):
        bm.timed_steps(step, None, lambda: None, warmup=1, measured=1)


def test_timed_steps_warmup_zero():
    """warmup=0 is public API: no boundary sync to a metrics dict that
    doesn't exist yet (timing then includes compile — caller's choice)."""
    def step(state, batch):
        return state + 1, {"loss": jnp.asarray(1.0)}

    state, sps, loss = bm.timed_steps(
        step, 0, lambda: None, warmup=0, measured=3,
    )
    assert state == 3 and loss == 1.0 and sps > 0


def test_timed_steps_pulls_fresh_batches():
    """next_batch is called once per step — the pipeline-fed window
    contract (a prefetcher iterator advances per step)."""
    it = iter(range(100))

    def step(state, batch):
        return state, {"loss": jnp.asarray(float(batch))}

    _, _, loss = bm.timed_steps(
        step, None, lambda: next(it), warmup=3, measured=4,
    )
    assert loss == 6.0  # 7th value pulled (0-indexed)


def test_sync_by_value_forces_scalar():
    assert bm.sync_by_value({"loss": jnp.asarray(2.5)}) == 2.5
    assert isinstance(bm.sync_by_value({"loss": jnp.asarray(1)}), float)
