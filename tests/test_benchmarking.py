"""utils/benchmarking — the shared harness scaffolding both benches
(bench.py, tools/bench_bert.py) depend on for honest numbers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.utils import benchmarking as bm


def test_describe_devices_cpu_rig():
    devices, n, platform, on_tpu = bm.describe_devices()
    assert n == len(jax.devices()) >= 1
    assert platform == "cpu" and not on_tpu


def test_timed_steps_counts_and_syncs():
    calls = []

    def step(state, batch):
        calls.append(batch)
        return state + batch, {"loss": jnp.asarray(float(state + batch))}

    state, sps, loss = bm.timed_steps(
        step, 0.0, lambda: 1.0, warmup=2, measured=5,
    )
    # warmup + measured steps all ran; state chained through every one
    assert len(calls) == 7
    assert state == 7.0
    assert loss == 7.0
    assert sps > 0


def test_timed_steps_rejects_nonfinite_loss():
    def step(state, batch):
        return state, {"loss": jnp.asarray(float("nan"))}

    # RuntimeError, not assert: must fire even under `python -O`
    with pytest.raises(RuntimeError, match="non-finite"):
        bm.timed_steps(step, None, lambda: None, warmup=1, measured=1)


def test_timed_steps_warmup_zero():
    """warmup=0 is public API: no boundary sync to a metrics dict that
    doesn't exist yet (timing then includes compile — caller's choice)."""
    def step(state, batch):
        return state + 1, {"loss": jnp.asarray(1.0)}

    state, sps, loss = bm.timed_steps(
        step, 0, lambda: None, warmup=0, measured=3,
    )
    assert state == 3 and loss == 1.0 and sps > 0


def test_timed_steps_pulls_fresh_batches():
    """next_batch is called once per step — the pipeline-fed window
    contract (a prefetcher iterator advances per step)."""
    it = iter(range(100))

    def step(state, batch):
        return state, {"loss": jnp.asarray(float(batch))}

    _, _, loss = bm.timed_steps(
        step, None, lambda: next(it), warmup=3, measured=4,
    )
    assert loss == 6.0  # 7th value pulled (0-indexed)


def test_sync_by_value_forces_scalar():
    assert bm.sync_by_value({"loss": jnp.asarray(2.5)}) == 2.5
    assert isinstance(bm.sync_by_value({"loss": jnp.asarray(1)}), float)


@pytest.mark.slow
def test_bench_py_json_contract(tmp_path):
    """The driver consumes bench.py's stdout as ONE JSON line with the
    BASELINE metric schema; a regression here silently costs the round
    its artifact. Runs the real script (CPU fallback path) at tiny step
    counts and validates the contract."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "BENCH_STEPS": "3"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be exactly one line: {lines}"
    row = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mfu",
                "platform", "n_chips", "global_batch", "block_impl",
                "pipeline_efficiency"):
        assert key in row, key
    assert row["metric"] == "resnet50_images_per_sec_per_chip"
    assert row["value"] > 0 and row["unit"] == "images/sec/chip"

    # the unpinned-TPU A/B selection path (forced on CPU): must still be
    # one JSON line, now with the losing variant recorded
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "BENCH_STEPS": "3",
             "BENCH_FORCE_AB": "1"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1
    row = json.loads(lines[0])
    assert row["block_impl"] in ("fused", "standard")
    assert row["alt_block_impl"] in ("fused", "standard")
    assert row["alt_block_impl"] != row["block_impl"]
    assert row["alt_images_per_sec_per_chip"] > 0
