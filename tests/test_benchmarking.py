"""utils/benchmarking — the shared harness scaffolding both benches
(bench.py, tools/bench_bert.py) depend on for honest numbers."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.utils import benchmarking as bm


def test_describe_devices_cpu_rig():
    devices, n, platform, on_tpu = bm.describe_devices()
    assert n == len(jax.devices()) >= 1
    assert platform == "cpu" and not on_tpu


def test_timed_steps_counts_and_syncs():
    calls = []

    def step(state, batch):
        calls.append(batch)
        return state + batch, {"loss": jnp.asarray(float(state + batch))}

    state, sps, loss = bm.timed_steps(
        step, 0.0, lambda: 1.0, warmup=2, measured=5,
    )
    # warmup + measured steps all ran; state chained through every one
    assert len(calls) == 7
    assert state == 7.0
    assert loss == 7.0
    assert sps > 0


def test_timed_steps_rejects_nonfinite_loss():
    def step(state, batch):
        return state, {"loss": jnp.asarray(float("nan"))}

    # RuntimeError, not assert: must fire even under `python -O`
    with pytest.raises(RuntimeError, match="non-finite"):
        bm.timed_steps(step, None, lambda: None, warmup=1, measured=1)


def test_timed_steps_warmup_zero():
    """warmup=0 is public API: no boundary sync to a metrics dict that
    doesn't exist yet (timing then includes compile — caller's choice)."""
    def step(state, batch):
        return state + 1, {"loss": jnp.asarray(1.0)}

    state, sps, loss = bm.timed_steps(
        step, 0, lambda: None, warmup=0, measured=3,
    )
    assert state == 3 and loss == 1.0 and sps > 0


def test_timed_steps_pulls_fresh_batches():
    """next_batch is called once per step — the pipeline-fed window
    contract (a prefetcher iterator advances per step)."""
    it = iter(range(100))

    def step(state, batch):
        return state, {"loss": jnp.asarray(float(batch))}

    _, _, loss = bm.timed_steps(
        step, None, lambda: next(it), warmup=3, measured=4,
    )
    assert loss == 6.0  # 7th value pulled (0-indexed)


def test_sync_by_value_forces_scalar():
    assert bm.sync_by_value({"loss": jnp.asarray(2.5)}) == 2.5
    assert isinstance(bm.sync_by_value({"loss": jnp.asarray(1)}), float)


# ---- relay-probe cache (VERDICT r4 item 3) -----------------------------
# The driver-invoked bench must reuse the watcher's last probe verdict
# instead of burning a healthy window (or hanging 150 s on a dead relay)
# re-deriving it. These tests monkeypatch the subprocess probe: the
# ladder's decisions are what is being pinned, not backend init.


@pytest.fixture()
def probe_env(tmp_path, monkeypatch):
    """Ambient-platform env (no pin) with an isolated cache path, plus a
    recording fake for the subprocess probe."""
    monkeypatch.setenv("DTF_PROBE_CACHE", str(tmp_path / "probe.json"))
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("BENCH_SKIP_PROBE", raising=False)
    monkeypatch.setenv("DTF_CHIP_LOCK", str(tmp_path / "chip.lock"))
    monkeypatch.delenv("DTF_CHIP_SESSION", raising=False)
    calls = []

    def fake(verdicts):
        def _probe(timeout_s, log):
            calls.append(timeout_s)
            return verdicts[min(len(calls), len(verdicts)) - 1]
        monkeypatch.setattr(bm, "_probe_subprocess", _probe)
        return calls

    yield fake
    # the fallback path mutates global jax config; restore the rig pin
    jax.config.update("jax_platforms", "cpu")


def test_probe_cache_roundtrip_and_ttl(tmp_path, monkeypatch):
    monkeypatch.setenv("DTF_PROBE_CACHE", str(tmp_path / "probe.json"))
    assert bm.read_probe_cache(300) is None  # absent
    bm.write_probe_cache(True)
    assert bm.read_probe_cache(300) is True
    bm.write_probe_cache(False)
    assert bm.read_probe_cache(300) is False
    assert bm.read_probe_cache(0) is None  # stale: ttl exceeded
    (tmp_path / "probe.json").write_text("not json")
    assert bm.read_probe_cache(300) is None  # unreadable


def test_probe_cache_foreign_owner_not_trusted(tmp_path, monkeypatch):
    """ADVICE r5: the cache default lives in world-writable /tmp, so a
    verdict from a file this uid does not own must read as NO cache —
    a poisoned DOWN written by another user would otherwise pin every
    bench to CPU for the whole TTL without a single probe."""
    monkeypatch.setenv("DTF_PROBE_CACHE", str(tmp_path / "probe.json"))
    bm.write_probe_cache(False)
    assert bm.read_probe_cache(300) is False  # own file: believed

    # simulate "the file belongs to someone else" by shifting our
    # apparent uid — equivalent to a poisoned file another user wrote
    real_uid = os.getuid()
    monkeypatch.setattr(os, "getuid", lambda: real_uid + 1)
    assert bm.read_probe_cache(300) is None  # foreign DOWN: not believed
    monkeypatch.setattr(os, "getuid", lambda: real_uid)
    bm.write_probe_cache(True)
    monkeypatch.setattr(os, "getuid", lambda: real_uid + 1)
    assert bm.read_probe_cache(300) is None  # foreign HEALTHY: same rule


def test_foreign_down_cache_falls_through_to_probe(probe_env, monkeypatch):
    """The poisoned-DOWN scenario end-to-end: with a foreign-owned DOWN
    verdict on disk, the ladder probes for real instead of pinning CPU
    sight-unseen."""
    calls = probe_env([True])
    bm.write_probe_cache(False)
    real_uid = os.getuid()
    monkeypatch.setattr(os, "getuid", lambda: real_uid + 1)
    assert bm.fall_back_to_cpu_if_unreachable(timeout_s=90) is False
    assert calls == [90]  # full-budget probe ran; verdict not pre-trusted


def test_fresh_down_cache_skips_probe_entirely(probe_env):
    calls = probe_env([True])  # would report healthy if ever consulted
    bm.write_probe_cache(False)
    assert bm.fall_back_to_cpu_if_unreachable() is True
    assert calls == []  # zero probe latency on a known-dead relay
    import os

    assert os.environ["JAX_PLATFORMS"] == "cpu"


def test_fresh_healthy_cache_short_confirm(probe_env):
    calls = probe_env([True])
    bm.write_probe_cache(True)
    assert bm.fall_back_to_cpu_if_unreachable(timeout_s=90) is False
    # exactly one SHORT confirming probe — never the full budget
    assert calls == [45.0]
    import os

    # children of this harness skip the duplicate probe
    assert os.environ["BENCH_SKIP_PROBE"] == "1"
    assert bm.read_probe_cache(300) is True


def test_healthy_cache_but_relay_died(probe_env):
    # confirm hangs twice: relay died inside the TTL. The hung SHORT
    # confirm gets one full-budget retry before poisoning the cache.
    calls = probe_env([None, None])
    bm.write_probe_cache(True)
    assert bm.fall_back_to_cpu_if_unreachable(timeout_s=90) is True
    assert calls == [45.0, 90]
    # the verdict flips so the NEXT harness skips straight to CPU
    assert bm.read_probe_cache(300) is False


def test_healthy_cache_slow_confirm_recovers(probe_env):
    # short confirm hangs but the full-budget retry reaches the chip:
    # a single slow probe must not flip a healthy verdict
    calls = probe_env([None, True])
    bm.write_probe_cache(True)
    assert bm.fall_back_to_cpu_if_unreachable(timeout_s=90) is False
    assert calls == [45.0, 90]
    assert bm.read_probe_cache(300) is True


def test_healthy_cache_definitive_confirm_failure(probe_env):
    # a definitive init/compile failure (not a hang) is believed at once
    calls = probe_env([False])
    bm.write_probe_cache(True)
    assert bm.fall_back_to_cpu_if_unreachable() is True
    assert calls == [45.0]
    assert bm.read_probe_cache(300) is False


def test_no_cache_hang_retries_once(probe_env):
    calls = probe_env([None, True])  # one slow probe must not cost a window
    assert bm.fall_back_to_cpu_if_unreachable(timeout_s=90) is False
    assert calls == [90, 90]
    assert bm.read_probe_cache(300) is True


def test_no_cache_down_no_retry(probe_env):
    # a definitive failure (backend init returned nonzero) is not a hang;
    # retrying it would just double the driver's wait
    calls = probe_env([False])
    assert bm.fall_back_to_cpu_if_unreachable() is True
    assert calls == [90]
    assert bm.read_probe_cache(300) is False


def test_live_chip_session_pins_cpu_without_probing(probe_env, tmp_path):
    import subprocess
    import sys

    calls = probe_env([True])
    holder = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"])
    try:
        (tmp_path / "chip.lock").write_text(str(holder.pid))
        assert bm.fall_back_to_cpu_if_unreachable() is True
        assert calls == []  # the probe itself would contend for the lease
    finally:
        holder.kill()
        holder.wait()


def test_explicit_pin_wins_untouched(probe_env, monkeypatch):
    calls = probe_env([None])
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert bm.fall_back_to_cpu_if_unreachable() is False
    assert calls == []


def test_probe_tool_writes_cache_and_respects_lock(tmp_path, monkeypatch):
    """tools/probe.py — the canonical probe: verdict lands in the cache;
    a live session makes it refuse to probe (exit 2)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache = tmp_path / "probe.json"
    env = {k: v for k, v in os.environ.items()
           # PALLAS_AXON_POOL_IPS must go too: with it set, the probe
           # child's sitecustomize overrides the env cpu pin and dials
           # the relay (the measured round-5 finding) — flaky hang
           if k not in ("DTF_CHIP_SESSION", "PALLAS_AXON_POOL_IPS")}
    env.update({"DTF_PROBE_CACHE": str(cache),
                "DTF_CHIP_LOCK": str(tmp_path / "chip.lock"),
                # CPU devices: probe's platform assert fails => DOWN
                "JAX_PLATFORMS": "cpu"})
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "probe.py"), "60"],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo,
    )
    assert proc.returncode == 1, (proc.stdout, proc.stderr)
    assert proc.stdout.strip() == "DOWN"
    monkeypatch.setenv("DTF_PROBE_CACHE", str(cache))
    assert bm.read_probe_cache(300) is False

    holder = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"])
    try:
        (tmp_path / "chip.lock").write_text(str(holder.pid))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "probe.py")],
            capture_output=True, text=True, timeout=120, env=env, cwd=repo,
        )
        assert proc.returncode == 2, (proc.stdout, proc.stderr)
        assert "not probing" in proc.stderr
    finally:
        holder.kill()
        holder.wait()


@pytest.mark.slow
def test_bench_row_stamps_live_chip_session(tmp_path):
    """A driver-captured CPU row that ran concurrently with an on-chip
    session must say so (chip_session_live) — it is not a relay-down
    row; the TPU evidence is landing in artifacts/ at that moment."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    holder = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(240)"])
    try:
        lock = tmp_path / "chip.lock"
        lock.write_text(str(holder.pid))
        env = {k: v for k, v in os.environ.items()
               # PALLAS_AXON_POOL_IPS: measured flaky-hang cause for
               # child interpreters (see the probe-tool test above)
               if k not in ("DTF_CHIP_SESSION", "JAX_PLATFORMS",
                            "PALLAS_AXON_POOL_IPS", "DTF_CHIP_PINNED")}
        env.update({"DTF_CHIP_LOCK": str(lock), "BENCH_STEPS": "3",
                    "DTF_PROBE_CACHE": str(tmp_path / "probe.json")})
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py")],
            capture_output=True, text=True, timeout=600, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        row = json.loads(proc.stdout.strip().splitlines()[-1])
        assert row["platform"] == "cpu"
        assert row["chip_session_live"] is True
        assert "pinning this process to CPU" in proc.stderr
    finally:
        holder.kill()
        holder.wait()


@pytest.mark.slow
def test_bench_py_json_contract(tmp_path):
    """The driver consumes bench.py's stdout as ONE JSON line with the
    BASELINE metric schema; a regression here silently costs the round
    its artifact. Runs the real script (CPU fallback path) at tiny step
    counts and validates the contract."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "BENCH_STEPS": "3"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be exactly one line: {lines}"
    row = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mfu",
                "platform", "n_chips", "global_batch", "block_impl",
                "pipeline_efficiency"):
        assert key in row, key
    assert row["metric"] == "resnet50_images_per_sec_per_chip"
    assert row["value"] > 0 and row["unit"] == "images/sec/chip"

    # the unpinned-TPU A/B selection path (forced on CPU): must still be
    # one JSON line, now with the losing variant recorded
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "BENCH_STEPS": "3",
             "BENCH_FORCE_AB": "1"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1
    row = json.loads(lines[0])
    assert row["block_impl"] in ("fused", "standard")
    assert row["alt_block_impl"] in ("fused", "standard")
    assert row["alt_block_impl"] != row["block_impl"]
    assert row["alt_images_per_sec_per_chip"] > 0
