"""Numeric-anomaly defense (resilience/anomaly.py + the in-graph
no-update-on-nonfinite guard in train/step.py + the quarantine-aware
stream in data/pipeline.py): skip, blame, quarantine — and the
acceptance oracle that a recurring bad batch at a fixed index is
survived with bit-identical same-seed finals and zero refused saves."""

import itertools
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_tensorflow_tpu import resilience as rz
from distributed_tensorflow_tpu.data.pipeline import (
    QuarantineFilter,
    quarantined_raw_start,
)
from distributed_tensorflow_tpu.obs.flightrec import (
    FlightRecorder,
    contains_in_order,
    default_recorder,
)
from distributed_tensorflow_tpu.obs.registry import Registry
from distributed_tensorflow_tpu.parallel import sharding as sh
from distributed_tensorflow_tpu.resilience import anomaly as an
from distributed_tensorflow_tpu.train import (
    CheckpointConfig,
    Checkpointer,
    StepOptions,
    Trainer,
    callbacks as cb,
    init_or_restore,
    jit_train_step,
    make_train_step,
)

from test_step import linear_init, linear_loss, make_batch


def _global_batch(i):
    """The batch feeding GLOBAL step i — pure function of i (the
    re-seek soundness contract)."""
    return make_batch(16, seed=1000 + i)


def _batches_from(i0):
    i = i0
    while True:
        i += 1
        yield _global_batch(i)


def _put(batch, mesh):
    return sh.put_host_batch(mesh, batch)


def _state_leaves(state):
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(state))]


def _poisoned(batch, rows=slice(0, 1)):
    out = dict(batch)
    x = batch["x"].copy()
    x[rows] = np.nan
    out["x"] = x
    return out


# ---------------------------------------------------------------------------
# The in-graph guard (train/step.py StepOptions.skip_nonfinite)
# ---------------------------------------------------------------------------


def _guarded_step(mesh, tx, accum=1):
    from distributed_tensorflow_tpu.train import init_train_state

    state, specs = init_train_state(linear_init, tx, mesh,
                                    jax.random.PRNGKey(0))
    step = jit_train_step(
        make_train_step(linear_loss, tx,
                        StepOptions(grad_accum_steps=accum,
                                    skip_nonfinite=True)),
        mesh, specs,
    )
    return state, step


def test_guard_skips_nonfinite_single_batch(mesh8):
    tx = optax.adam(1e-2)
    state, step = _guarded_step(mesh8, tx)
    state, m = step(state, _put(_global_batch(1), mesh8))
    assert float(m["nonfinite"]) == 0.0 and int(state.step) == 1
    snap = jax.device_get(state)  # BEFORE the call: donation invalidates
    state, m = step(state, _put(_poisoned(_global_batch(2)), mesh8))
    assert float(m["nonfinite"]) == 1.0
    # the whole state — params, opt_state, model_state AND the step
    # counter — is bit-identical to the pre-step state: the poisoned
    # batch provably vanished from the trajectory
    before, after = _state_leaves(snap), _state_leaves(state)
    assert len(before) == len(after) and before
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)
    assert int(state.step) == 1
    # the run continues: a clean batch advances from the preserved state
    state, m = step(state, _put(_global_batch(2), mesh8))
    assert float(m["nonfinite"]) == 0.0 and int(state.step) == 2


def test_guard_skips_nonfinite_microbatch_in_accum_scan(mesh8):
    """ISSUE 9 satellite: ONE NaN microbatch inside a grad_accum_steps=4
    scan must leave the returned state bit-identical to the pre-step
    state and raise the flag — the guard covers the scan path, not just
    the single-batch one."""
    tx = optax.adam(1e-2)
    state, step = _guarded_step(mesh8, tx, accum=4)
    batch = make_batch(32, seed=7)
    state, m = step(state, _put(batch, mesh8))
    assert float(m["nonfinite"]) == 0.0 and int(state.step) == 1
    snap = jax.device_get(state)
    # poison exactly the second microbatch (rows 8..15 of the
    # reshape(4, 8, ...) split)
    state, m = step(state, _put(_poisoned(batch, rows=slice(8, 16)), mesh8))
    assert float(m["nonfinite"]) == 1.0
    for a, b in zip(_state_leaves(snap), _state_leaves(state)):
        np.testing.assert_array_equal(a, b)
    assert int(state.step) == 1
    state, m = step(state, _put(batch, mesh8))
    assert float(m["nonfinite"]) == 0.0 and int(state.step) == 2


def test_guard_flag_absent_without_option(mesh8):
    tx = optax.sgd(0.1)
    from distributed_tensorflow_tpu.train import init_train_state

    state, specs = init_train_state(linear_init, tx, mesh8,
                                    jax.random.PRNGKey(0))
    step = jit_train_step(make_train_step(linear_loss, tx), mesh8, specs)
    _, m = step(state, _put(_global_batch(1), mesh8))
    assert "nonfinite" not in m


def test_guard_without_policy_fails_fast_with_clean_state(mesh8, tmp_path):
    """skip_nonfinite WITHOUT an AnomalyPolicy must not silently count
    the no-op step (that would desync the host mirror from the device
    step counter and mislabel every later checkpoint by one): the loop
    raises immediately — classified poisoned — with the state still the
    last healthy one, so the emergency save lands under its true step."""
    from distributed_tensorflow_tpu.train import init_train_state

    tx = optax.adam(1e-2)
    state, specs = init_train_state(linear_init, tx, mesh8,
                                    jax.random.PRNGKey(0))
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "nf"),
                         save_interval_steps=1, async_save=False,
                         save_on_preemption=False),
        mesh8,
    )
    trainer = Trainer(
        make_train_step(linear_loss, tx, StepOptions(skip_nonfinite=True)),
        state, mesh8, specs, callbacks=[cb.CheckpointCallback(ckpt)],
    )

    def data():
        yield _global_batch(1)
        yield _global_batch(2)
        yield _poisoned(_global_batch(3))

    try:
        with pytest.raises(FloatingPointError, match="step 3"):
            trainer.fit(data(), num_steps=5)
        assert int(trainer.state.step) == 2  # guard kept the clean state
        assert ckpt.latest_step() == 2       # nothing mislabeled as 3
    finally:
        ckpt.close()


# ---------------------------------------------------------------------------
# Quarantine-aware stream (data/pipeline.py)
# ---------------------------------------------------------------------------


def test_quarantined_raw_start_translation():
    assert quarantined_raw_start(0, ()) == 0
    assert quarantined_raw_start(5, ()) == 5
    assert quarantined_raw_start(5, {3}) == 6
    assert quarantined_raw_start(5, {3, 7}) == 6
    assert quarantined_raw_start(2, {1, 2}) == 4
    assert quarantined_raw_start(2, {2}) == 3


def test_quarantine_filter_skips_around_holes():
    reg = Registry()
    # "batches" are the raw indices themselves: make_source(i) yields
    # i+1, i+2, ... (the RetryingIterator contract)
    builds = []

    def make_source(i):
        builds.append(i)
        return itertools.count(i + 1)

    f = QuarantineFilter(make_source, {3, 4, 8}, registry=reg)
    assert list(itertools.islice(f, 6)) == [1, 2, 5, 6, 7, 9]
    assert f.raw == 9
    # the holes were re-seeked AROUND (source rebuilt past them), never
    # fetched: builds at 0 (init), 4 (past 3-4), 8 (past 8)
    assert builds == [0, 4, 8]
    assert reg.get("anomaly_skipped_batches_total",
                   cause="quarantined").value == 3.0


def test_quarantine_filter_start_step_is_effective():
    f = QuarantineFilter(lambda i: itertools.count(i + 1), {2},
                         start_step=2, registry=Registry())
    # 2 effective batches consumed == raws 1,3; the next delivery is 4
    assert next(f) == 4


# ---------------------------------------------------------------------------
# Quarantine file (atomic blame record)
# ---------------------------------------------------------------------------


def test_quarantine_file_roundtrip_and_idempotence(tmp_path):
    d = str(tmp_path / "run")
    assert an.load_quarantine(d) == frozenset()
    rec = FlightRecorder()
    assert an.quarantine_index(d, 7, step=5, cause="nonfinite",
                               flightrec=rec) is True
    assert an.quarantine_index(d, 3, cause="bisect", flightrec=rec) is True
    # idempotent: re-blaming (hook re-runs) must not duplicate
    assert an.quarantine_index(d, 7, flightrec=rec) is False
    assert an.load_quarantine(d) == frozenset({3, 7})
    doc = an.read_quarantine(d)
    assert doc["indices"] == [3, 7]
    assert [e["cause"] for e in doc["entries"]] == ["nonfinite", "bisect"]
    assert not (tmp_path / "run" / "quarantine.json.tmp").exists()
    kinds = [e["kind"] for e in rec.events()]
    assert kinds == ["anomaly_blame", "anomaly_blame"]


# ---------------------------------------------------------------------------
# AnomalyPolicy (host consumer of the flag)
# ---------------------------------------------------------------------------


def test_policy_requires_the_flag(tmp_path):
    pol = rz.AnomalyPolicy(str(tmp_path), registry=Registry(),
                           flightrec=FlightRecorder())
    with pytest.raises(RuntimeError, match="skip_nonfinite"):
        pol.observe(1, {"loss": np.float32(1.0)})


def test_policy_skips_blames_and_exhausts(tmp_path):
    reg, rec = Registry(), FlightRecorder()
    idx = {"i": 0}
    pol = rz.AnomalyPolicy(
        str(tmp_path), rz.AnomalyConfig(skip_budget=2),
        index_fn=lambda: idx["i"], registry=reg, flightrec=rec)
    ok = {"nonfinite": np.float32(0.0)}
    bad = {"nonfinite": np.float32(1.0)}
    idx["i"] = 1
    assert pol.observe(1, ok) is False
    idx["i"] = 2
    assert pol.observe(2, bad) is True
    idx["i"] = 3
    assert pol.observe(2, bad) is True  # retried step, next batch also bad
    assert pol.skipped == 2
    assert an.load_quarantine(str(tmp_path)) == frozenset({2, 3})
    idx["i"] = 4
    with pytest.raises(rz.SkipBudgetExhausted) as ei:
        pol.observe(2, bad)
    assert ei.value.index == 4
    # the budget-buster is STILL blamed — restart recovery re-seeks
    # around it instead of rediscovering it
    assert an.load_quarantine(str(tmp_path)) == frozenset({2, 3, 4})
    assert rz.classify_failure(ei.value) == rz.POISONED
    assert reg.get("anomaly_skipped_batches_total",
                   cause="nonfinite").value == 2.0
    assert contains_in_order(rec.events(), [
        ("anomaly_skip", {"index": 2}), ("anomaly_blame", {"index": 2}),
        ("anomaly_skip", {"index": 3}), ("anomaly_blame", {"index": 3}),
        ("anomaly_blame", {"index": 4}),
    ])


def test_policy_ewma_spike_detection(tmp_path):
    reg, rec = Registry(), FlightRecorder()
    pol = rz.AnomalyPolicy(
        str(tmp_path),
        rz.AnomalyConfig(spike_factor=3.0, spike_warmup_steps=3,
                         spike_ewma_alpha=0.5),
        registry=reg, flightrec=rec)
    for s in range(1, 6):
        assert pol.observe(
            s, {"nonfinite": np.float32(0.0), "loss": np.float32(1.0)}
        ) is False
    assert pol.spikes == 0
    pol.observe(6, {"nonfinite": np.float32(0.0), "loss": np.float32(50.0)})
    assert pol.spikes == 1
    assert reg.get("anomaly_spikes_total").value == 1.0
    spike = [e for e in rec.events() if e["kind"] == "anomaly_spike"]
    assert len(spike) == 1 and spike[0]["loss"] == 50.0
    # a spike never drags the baseline toward itself: the next normal
    # loss is not itself flagged as a dip-relative anomaly
    pol.observe(7, {"nonfinite": np.float32(0.0), "loss": np.float32(1.0)})
    assert pol.spikes == 1


def test_policy_fail_on_spike(tmp_path):
    pol = rz.AnomalyPolicy(
        str(tmp_path),
        rz.AnomalyConfig(spike_factor=2.0, spike_warmup_steps=1,
                         fail_on_spike=True),
        registry=Registry(), flightrec=FlightRecorder())
    pol.observe(1, {"nonfinite": np.float32(0.0), "loss": np.float32(1.0)})
    pol.observe(2, {"nonfinite": np.float32(0.0), "loss": np.float32(1.0)})
    with pytest.raises(FloatingPointError, match="spike"):
        pol.observe(3, {"nonfinite": np.float32(0.0),
                        "loss": np.float32(9.0)})


# ---------------------------------------------------------------------------
# NaNGuard reads the per-step flag (cadence hole closed)
# ---------------------------------------------------------------------------


def test_nan_guard_flag_overrides_cadence():
    class T:
        def request_stop(self, r=""):
            self.reason = r

    guard = cb.NaNGuard(every_n=10, fail_fast=True)
    # step 3 is NOT a cadence step — the flag is still honored
    guard.on_step_end(T(), 3, {"nonfinite": np.float32(0.0),
                               "loss": np.float32(np.nan)})  # flag wins: ok
    with pytest.raises(FloatingPointError, match="step 3"):
        guard.on_step_end(T(), 3, {"nonfinite": np.float32(1.0)})


# ---------------------------------------------------------------------------
# validate_before_save covers opt_state (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


def test_validate_before_save_checks_opt_state(mesh8, tmp_path):
    from distributed_tensorflow_tpu.train import init_train_state

    tx = optax.adam(1e-2)
    state, specs = init_train_state(linear_init, tx, mesh8,
                                    jax.random.PRNGKey(0))
    ckpt = Checkpointer(
        CheckpointConfig(directory=str(tmp_path / "v"), async_save=False,
                         save_on_preemption=False),
        mesh8, spec_tree=specs,
    )
    try:
        assert ckpt._params_finite(state) is True
        # poisoned Adam moments, params still finite: the pre-fix check
        # (params only) would have passed this state into `latest`
        bad_opt = jax.tree.map(
            lambda x: x * jnp.nan
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            state.opt_state,
        )
        bad = state.replace(opt_state=bad_opt)
        assert all(np.isfinite(x).all()
                   for x in _state_leaves(bad.params))
        assert ckpt._params_finite(bad) is False
        assert ckpt.save(1, bad, force=True) is False  # refused
        assert ckpt.latest_step() is None
        assert ckpt.save(1, state, force=True) is True
    finally:
        ckpt.close()


# ---------------------------------------------------------------------------
# Blame bisection
# ---------------------------------------------------------------------------


def test_bisect_blame_finds_first_poisoned_step():
    calls = []

    def probe(m):
        calls.append(m)
        return m >= 7  # poison propagates: replay through >=7 is bad

    assert an.bisect_blame(probe, 2, 20) == 7
    assert len(calls) <= 6  # logarithmic, not a linear scan
    assert an.bisect_blame(lambda m: False, 2, 20) is None
    assert an.bisect_blame(lambda m: True, 5, 5) is None


def test_blame_hook_quarantines_raw_index(tmp_path):
    d = tmp_path / "run"
    (d / "3").mkdir(parents=True)  # newest step dir on disk == 3
    an.quarantine_index(str(d), 4, cause="nonfinite",
                        flightrec=FlightRecorder())
    probed = []

    def probe(lo, m):
        probed.append((lo, m))
        return m >= 5  # first poisoned EFFECTIVE step is 5

    hook = rz.blame_hook(str(d), probe, window=8,
                         flightrec=FlightRecorder())
    hook(1, rz.TRANSIENT)  # not poisoned: no probing, no blame
    assert not probed
    hook(1, rz.POISONED)
    assert all(lo == 3 for lo, _ in probed)
    # effective step 5 behind the existing hole at raw 4 -> raw index 6
    assert an.load_quarantine(str(d)) == frozenset({4, 6})


# ---------------------------------------------------------------------------
# E2E: recurring bad index under the Supervisor (the acceptance oracle)
# ---------------------------------------------------------------------------


def _anomaly_builder(workdir, mesh, plan, registry, *, tx, skip_budget=4,
                     guard=True, save_every=1, extra_cbs=lambda: []):
    """Production-shaped attempt builder with the anomaly defense wired:
    guard-enabled step, quarantine-filtered stream re-read from disk at
    every attempt boundary, per-attempt AnomalyPolicy blaming through
    the stream's raw cursor."""

    def build(restart_index):
        ckpt = Checkpointer(
            CheckpointConfig(directory=str(workdir),
                             save_interval_steps=save_every,
                             async_save=False, save_on_preemption=True,
                             preemption_check_every=1),
            mesh, registry=registry,
        )
        state, specs, _ = init_or_restore(
            ckpt, linear_init, tx, mesh, jax.random.PRNGKey(0),
            fallback=True,
        )
        start = int(state.step)
        stream = QuarantineFilter(
            lambda raw: plan.wrap(_batches_from(raw), start=raw),
            rz.load_quarantine(str(workdir)), start_step=start,
            registry=registry,
        )
        policy = rz.AnomalyPolicy(
            str(workdir), rz.AnomalyConfig(skip_budget=skip_budget),
            index_fn=lambda: stream.raw, registry=registry,
        ) if guard else None
        trainer = Trainer(
            make_train_step(linear_loss, tx,
                            StepOptions(skip_nonfinite=guard)),
            state, mesh, specs,
            callbacks=extra_cbs() + [cb.CheckpointCallback(ckpt),
                                     plan.callback()],
            anomaly_policy=policy,
        )
        return trainer, stream, ckpt

    return build


def _run_recurring_nan(workdir, mesh, registry):
    plan = rz.FaultPlan((rz.NaNBatch(3, recur=True), rz.Sigterm(5)))
    sup = rz.Supervisor(
        _anomaly_builder(workdir, mesh, plan, registry, tx=optax.adam(1e-2),
                         save_every=2),
        num_steps=10,
        cfg=rz.SupervisorConfig(max_restarts=3,
                                backoff=rz.RetryPolicy(base_s=0.0,
                                                       jitter=0.0)),
        registry=registry, sleep=lambda s: None,
    )
    return sup.run(), sup


def test_recurring_nan_skipped_quarantined_bit_identical(mesh8, tmp_path,
                                                         caplog):
    """THE acceptance criterion: a NaNBatch recurring at a fixed index on
    every incarnation finishes under the Supervisor with the index
    quarantined, final params BIT-identical across two same-seed runs —
    and validate_before_save never refuses a save, because the in-graph
    guard means poisoned params never exist to refuse."""
    import logging

    orig = signal.getsignal(signal.SIGTERM)
    caplog.set_level(logging.ERROR,
                     logger="distributed_tensorflow_tpu.train.checkpoint")
    try:
        reg_a, reg_b = Registry(), Registry()
        state_a, sup_a = _run_recurring_nan(tmp_path / "a", mesh8, reg_a)
        state_b, sup_b = _run_recurring_nan(tmp_path / "b", mesh8, reg_b)
        assert int(state_a.step) == int(state_b.step) == 10
        # one restart each — the SIGTERM preemption; the NaN batch cost
        # NO restart (skipped in-graph, not aborted)
        assert sup_a.restarts == sup_b.restarts == 1
        for reg in (reg_a, reg_b):
            assert reg.get("supervisor_restarts_total",
                           cause="preemption").value == 1.0
            assert reg.get("anomaly_skipped_batches_total",
                           cause="nonfinite").value == 1.0
        # the bad raw index is on file in both runs
        assert rz.load_quarantine(str(tmp_path / "a")) == frozenset({3})
        assert rz.load_quarantine(str(tmp_path / "b")) == frozenset({3})
        # no save was ever refused: poisoned params never existed
        assert not [r for r in caplog.records
                    if "refusing to checkpoint" in r.getMessage()]
        # bit-identical finals: the trajectory is a pure function of
        # (seed, quarantine set)
        pa = [np.asarray(x) for x in
              jax.tree.leaves(jax.device_get(state_a.params))]
        pb = [np.asarray(x) for x in
              jax.tree.leaves(jax.device_get(state_b.params))]
        assert pa and len(pa) == len(pb)
        for a, b in zip(pa, pb):
            np.testing.assert_array_equal(a, b)
        # the flight recorder tells the whole causal story in order
        assert contains_in_order(default_recorder().events(), [
            ("fault_fired", {"fault": "nan_batch"}),
            ("anomaly_skip", {"index": 3}),
            ("anomaly_blame", {"index": 3}),
            ("ckpt_save", {"trigger": "preemption"}),
            ("sup_restart", {"cause": "preemption"}),
            ("ckpt_restore", {"fallback": True}),
        ])
    finally:
        signal.signal(signal.SIGTERM, orig)


def test_skip_budget_exhausted_restart_reseeks_around_quarantine(mesh8,
                                                                 tmp_path):
    """Budget 0: the first non-finite flag raises SkipBudgetExhausted
    (poisoned) with the index already blamed; the restarted attempt's
    stream re-seeks AROUND the quarantined index and the run converges
    — one restart, not an exhausted budget of futile replays."""
    reg = Registry()
    plan = rz.FaultPlan((rz.NaNBatch(4, recur=True),))
    sup = rz.Supervisor(
        _anomaly_builder(tmp_path / "q", mesh8, plan, reg,
                         tx=optax.adam(1e-2), skip_budget=0),
        num_steps=8,
        cfg=rz.SupervisorConfig(max_restarts=2,
                                backoff=rz.RetryPolicy(base_s=0.0,
                                                       jitter=0.0)),
        registry=reg, sleep=lambda s: None,
    )
    state = sup.run()
    assert int(state.step) == 8
    assert sup.restarts == 1
    assert reg.get("supervisor_restarts_total", cause="poisoned").value == 1.0
    assert rz.load_quarantine(str(tmp_path / "q")) == frozenset({4})
    # the restarted stream skipped the hole (never fetched it)
    assert reg.get("anomaly_skipped_batches_total",
                   cause="quarantined").value >= 1.0
    assert all(np.isfinite(x).all() for x in
               [np.asarray(v) for v in
                jax.tree.leaves(jax.device_get(state.params))])


def test_guardless_poisoned_restart_converges_via_bisection(mesh8, tmp_path):
    """Tier 2 — poisoning discovered only at abort time (no in-graph
    guard, NaNGuard cadence detection): the Supervisor's poisoned
    restart runs the blame hook, which bisects the window since the
    last-good checkpoint by deterministic re-seek replay, quarantines
    the exact index, and the next attempt finishes — today's futile
    poisoned loop, made convergent."""
    workdir = tmp_path / "g"
    reg = Registry()
    tx = optax.adam(1e-2)
    plan = rz.FaultPlan((rz.NaNBatch(4, recur=True),))

    def probe(lo, hi):
        # deterministic re-seek replay WITHOUT the guard: restore the
        # newest checkpoint (== lo), run effective steps (lo, hi] over
        # the quarantine-filtered stream, report whether the end state
        # is poisoned — NaNs propagate through every optax update, so
        # the predicate is monotone and bisectable
        ck = Checkpointer(
            CheckpointConfig(directory=str(workdir),
                             save_interval_steps=10 ** 9, async_save=False,
                             save_on_preemption=False),
            mesh8,
        )
        try:
            state, specs, _ = init_or_restore(
                ck, linear_init, tx, mesh8, jax.random.PRNGKey(0),
                fallback=True)
        finally:
            ck.close()
        step_fn = jit_train_step(make_train_step(linear_loss, tx), mesh8,
                                 specs)
        stream = QuarantineFilter(
            lambda raw: plan.wrap(_batches_from(raw), start=raw),
            rz.load_quarantine(str(workdir)), start_step=int(state.step),
            registry=reg,
        )
        for _ in range(hi - int(state.step)):
            state, _ = step_fn(state, _put(next(stream), mesh8))
        return not all(
            np.isfinite(np.asarray(x)).all()
            for x in jax.tree.leaves(jax.device_get(state.params)))

    sup = rz.Supervisor(
        _anomaly_builder(workdir, mesh8, plan, reg, tx=tx, guard=False,
                         extra_cbs=lambda: [cb.NaNGuard(every_n=1)]),
        num_steps=8,
        cfg=rz.SupervisorConfig(max_restarts=2,
                                backoff=rz.RetryPolicy(base_s=0.0,
                                                       jitter=0.0)),
        registry=reg,
        on_restart=[rz.blame_hook(str(workdir), probe, window=8)],
        sleep=lambda s: None,
    )
    state = sup.run()
    assert int(state.step) == 8
    assert sup.restarts == 1  # ONE restart, not max_restarts of replays
    assert reg.get("supervisor_restarts_total", cause="poisoned").value == 1.0
    assert rz.load_quarantine(str(workdir)) == frozenset({4})
    doc = an.read_quarantine(str(workdir))
    assert doc["entries"][0]["cause"] == "bisect"
