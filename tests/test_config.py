import dataclasses

import pytest

from distributed_tensorflow_tpu.utils import config as cfg_lib


@dataclasses.dataclass(frozen=True)
class Inner:
    lr: float = 0.1
    steps: int = 100
    name: str = "sgd"
    flag: bool = False
    dims: tuple = (1, 2)


@dataclasses.dataclass(frozen=True)
class Outer:
    inner: Inner = dataclasses.field(default_factory=Inner)
    seed: int = 0


def test_apply_overrides_nested():
    cfg = cfg_lib.apply_overrides(
        Outer(), ["inner.lr=0.5", "inner.steps=7", "seed=42"]
    )
    assert cfg.inner.lr == 0.5
    assert cfg.inner.steps == 7
    assert cfg.seed == 42


def test_override_types():
    cfg = cfg_lib.apply_overrides(
        Outer(), ["inner.flag=true", "inner.name=adam", "inner.dims=[3,4]"]
    )
    assert cfg.inner.flag is True
    assert cfg.inner.name == "adam"
    assert cfg.inner.dims == (3, 4)


def test_override_unknown_key():
    with pytest.raises(ValueError, match="Unknown config key"):
        cfg_lib.apply_overrides(Outer(), ["inner.nope=1"])


def test_roundtrip_json():
    cfg = Outer(inner=Inner(lr=0.3, dims=(5, 6)), seed=9)
    d = cfg_lib.to_dict(cfg)
    back = cfg_lib.from_dict(Outer, d)
    assert back == cfg


def test_parse_argv_ignores_positional():
    cfg = cfg_lib.parse_argv(Outer(), ["prog", "--seed=5", "positional"])
    assert cfg.seed == 5


def test_overrides_on_future_annotations_config():
    """Package configs use `from __future__ import annotations`; overrides
    must resolve their string type annotations (regression: NameError on
    'float' when builtins were blanked)."""
    from distributed_tensorflow_tpu.train import OptimizerConfig

    cfg = cfg_lib.apply_overrides(
        OptimizerConfig(), ["learning_rate=0.5", "warmup_steps=3", "nesterov=true"]
    )
    assert cfg.learning_rate == 0.5
    assert cfg.warmup_steps == 3
    assert cfg.nesterov is True


def test_optional_none_override():
    @dataclasses.dataclass(frozen=True)
    class C:
        limit: int | None = 5

    cfg = cfg_lib.apply_overrides(C(), ["limit=none"])
    assert cfg.limit is None
    cfg = cfg_lib.apply_overrides(C(), ["limit=7"])
    assert cfg.limit == 7


def test_empty_string_override():
    """`--key=` (empty value) must parse as an empty string, not crash in
    the JSON branch — it's the idiom for disabling a path-valued option
    (e.g. --checkpoint.directory=)."""
    from distributed_tensorflow_tpu.workloads import runner

    cfg = cfg_lib.apply_overrides(
        runner.RunConfig(), ["--checkpoint.directory="]
    )
    assert cfg.checkpoint.directory == ""
