"""Collective-verb correctness on the fake 8-device mesh — the
'distributed-correctness oracle' pattern (SURVEY.md §4: assert allreduce
across k fake replicas equals the single-replica reduction)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from distributed_tensorflow_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel import collectives as col


def smap(mesh, fn, in_specs, out_specs):
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def test_all_reduce_sum(mesh8):
    x = jnp.arange(8.0).reshape(8, 1)
    out = smap(mesh8, lambda v: col.all_reduce(v, "data"), P("data"), P("data"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))


def test_all_reduce_mean_matches_single_device(mesh8):
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    out = smap(
        mesh8, lambda v: col.all_reduce_mean(v, "data"), P("data"), P("data")
    )(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out)[0], x.mean(0), rtol=1e-6)


def test_all_reduce_groups(mesh8):
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    x = jnp.arange(8.0).reshape(8, 1)
    out = smap(
        mesh8,
        lambda v: col.all_reduce(v, "data", groups=groups),
        P("data"),
        P("data"),
    )(x)
    np.testing.assert_allclose(np.asarray(out)[:, 0], [6, 6, 6, 6, 22, 22, 22, 22])


def test_all_gather(mesh8):
    x = jnp.arange(16.0).reshape(8, 2)
    out = smap(
        mesh8,
        lambda v: col.all_gather(v, "data"),
        P("data"),
        P("data", None),
    )(x)
    # each shard gathers the full array along dim 0
    assert out.shape == (64, 2)
    np.testing.assert_allclose(np.asarray(out)[:8], np.asarray(x))


def test_reduce_scatter_roundtrip(mesh8):
    rng = np.random.RandomState(1)
    x = rng.randn(8, 8).astype(np.float32)

    def fn(v):  # v: (1, 8)
        scattered = col.reduce_scatter(v, "data", scatter_axis=1)  # (1, 1)
        return scattered

    out = smap(mesh8, fn, P("data", None), P("data", None))(jnp.asarray(x))
    np.testing.assert_allclose(
        np.asarray(out).ravel(), x.sum(0), rtol=1e-5
    )


def test_broadcast(mesh8):
    x = jnp.arange(8.0).reshape(8, 1)
    out = smap(
        mesh8, lambda v: col.broadcast(v, "data", src=3), P("data"), P("data")
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.0))


def test_barrier(mesh8):
    out = smap(mesh8, lambda: col.barrier("data"), (), P())()
    assert int(out) == 8


def test_all_to_all(mesh8):
    # 8 shards each hold (1, 8); all_to_all transposes the sharding.
    x = jnp.arange(64.0).reshape(8, 8)
    out = smap(
        mesh8,
        lambda v: col.all_to_all(v, "data", split_axis=1, concat_axis=0),
        P("data", None),
        P(None, "data"),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))  # global transpose of sharding, same values


def test_ring_permute(mesh8):
    x = jnp.arange(8.0).reshape(8, 1)
    out = smap(
        mesh8, lambda v: col.ring_permute(v, "data", shift=1), P("data"), P("data")
    )(x)
    np.testing.assert_allclose(np.asarray(out)[:, 0], [7, 0, 1, 2, 3, 4, 5, 6])


def test_all_gather_groups(mesh8):
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    x = jnp.arange(8.0).reshape(8, 1)
    out = smap(
        mesh8,
        lambda v: col.all_gather(v, "data", groups=groups),
        P("data"),
        P("data", None),
    )(x)
    # each device gathers its group's 4 shards → global (32, 1)
    assert out.shape == (32, 1)
    np.testing.assert_allclose(np.asarray(out)[:4, 0], [0, 1, 2, 3])
    np.testing.assert_allclose(np.asarray(out)[16:20, 0], [4, 5, 6, 7])


def test_reduce_scatter_groups(mesh8):
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    x = jnp.ones((8, 4), jnp.float32)

    def fn(v):  # (1, 4) per device
        return col.reduce_scatter(v, "data", scatter_axis=1, groups=groups)

    out = smap(mesh8, fn, P("data", None), P("data", None))(x)
    # each group of 4 sums 4 ones → each device holds one chunk of value 4
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 4.0))


def test_subgroup_collective_on_2d_mesh(mesh_dp4_tp2):
    # psum over 'model' only: pairs of devices reduce independently.
    x = jnp.arange(8.0).reshape(4, 2)

    def fn(v):
        return col.all_reduce(v, "model")

    out = shard_map(
        fn, mesh=mesh_dp4_tp2, in_specs=P("data", "model"), out_specs=P("data", "model")
    )(x)
    expected = np.asarray(x).reshape(4, 2).sum(1, keepdims=True).repeat(2, 1)
    np.testing.assert_allclose(np.asarray(out), expected)


def test_factor_mesh_axis_numerics(mesh8):
    """Factored sub-axis psum == emulated grouped all_reduce with the
    matching contiguous groups (mesh.factor_mesh_axis API, VERDICT item 10)."""
    from distributed_tensorflow_tpu.parallel import factor_mesh_axis

    x = jnp.arange(8.0)
    groups = [[0, 1], [2, 3], [4, 5], [6, 7]]
    emulated = shard_map(
        lambda v: col.all_reduce(v, "data", groups=groups),
        mesh=mesh8, in_specs=P("data"), out_specs=P("data"),
    )(x)

    sub = factor_mesh_axis(mesh8, "data", {"outer": 4, "inner": 2})
    factored = shard_map(
        lambda v: col.all_reduce(v, "inner"),
        mesh=sub, in_specs=P(("outer", "inner")), out_specs=P(("outer", "inner")),
    )(x)
    np.testing.assert_allclose(np.asarray(factored), np.asarray(emulated))


def test_factored_axis_avoids_full_gather(mesh8):
    """The factored path must compile to a subgroup all-reduce with NO
    full-axis all-gather; the emulated path provably contains one."""
    from distributed_tensorflow_tpu.parallel import factor_mesh_axis

    x = jnp.arange(8.0)
    sub = factor_mesh_axis(mesh8, "data", {"outer": 4, "inner": 2})
    factored = jax.jit(shard_map(
        lambda v: col.all_reduce(v, "inner"),
        mesh=sub, in_specs=P(("outer", "inner")), out_specs=P(("outer", "inner")),
    ))
    hlo = factored.lower(x).compile().as_text()
    assert "all-reduce" in hlo, hlo[:2000]
    assert "all-gather" not in hlo, "factored subgroup reduce gathered the full axis"
    # replica groups of size 2, not 8
    import re

    m = re.search(r"replica_groups=\{(\{[\d,]+\})", hlo)
    assert m is not None, hlo[:2000]
    first_group = m.group(1)
    assert len(first_group.strip("{}").split(",")) == 2, first_group

    groups = [[0, 1], [2, 3], [4, 5], [6, 7]]
    emulated = jax.jit(shard_map(
        lambda v: col.all_reduce(v, "data", groups=groups),
        mesh=mesh8, in_specs=P("data"), out_specs=P("data"),
    ))
    hlo_e = emulated.lower(x).compile().as_text()
    assert "all-gather" in hlo_e  # documents why factoring is the fast path


def test_factor_mesh_axis_validation(mesh8):
    from distributed_tensorflow_tpu.parallel import factor_mesh_axis

    import pytest as _pytest

    with _pytest.raises(ValueError, match="no axis"):
        factor_mesh_axis(mesh8, "nope", {"a": 2})
    with _pytest.raises(ValueError, match="multiply"):
        factor_mesh_axis(mesh8, "data", {"a": 3})
    with _pytest.raises(ValueError, match="already in mesh"):
        factor_mesh_axis(mesh8, "data", {"model": 8})


def test_emulated_groups_warn_and_cap(mesh8, monkeypatch):
    """The emulated groups= path is fenced (VERDICT r2 Weak #5): it warns
    on every use, and past EMULATED_GROUP_AXIS_LIMIT it refuses outright,
    pointing at factor_mesh_axis."""
    import re

    import pytest

    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    x = jnp.arange(8.0).reshape(8, 1)
    run = smap(
        mesh8, lambda v: col.all_reduce(v, "data", groups=groups),
        P("data"), P("data"),
    )
    with pytest.warns(UserWarning, match="emulated"):
        run(x)

    monkeypatch.setattr(col, "EMULATED_GROUP_AXIS_LIMIT", 4)
    for verb in (
        lambda v: col.all_reduce(v, "data", groups=groups),
        lambda v: col.all_gather(v, "data", groups=groups),
        lambda v: col.reduce_scatter(v, "data", scatter_axis=1,
                                     groups=groups),
    ):
        with pytest.raises(ValueError,
                           match=re.escape("factor_mesh_axis")):
            smap(mesh8, verb, P("data"), P("data"))(
                jnp.arange(32.0).reshape(8, 4))
