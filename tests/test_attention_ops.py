"""Numerics oracle tests: blockwise and Pallas flash attention vs the O(S²)
reference (SURVEY.md §4 "numerical parity oracles"), forward and grad."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_tpu.ops import (
    attention_reference,
    blockwise_attention,
    flash_attention,
)


def make_qkv(key, B=2, H=3, S=256, D=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, S, D), dtype)
    k = jax.random.normal(kk, (B, H, S, D), dtype)
    v = jax.random.normal(kv, (B, H, S, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_blockwise_matches_reference(causal, masked):
    q, k, v = make_qkv(jax.random.PRNGKey(0))
    kv_mask = None
    if masked:
        # mask out a ragged tail per batch row (BERT-style padding)
        lens = np.array([200, 137])
        kv_mask = jnp.asarray(np.arange(256)[None, :] < lens[:, None])
    ref = attention_reference(q, k, v, causal=causal, kv_mask=kv_mask)
    out = blockwise_attention(
        q, k, v, causal=causal, kv_mask=kv_mask, block_k=64
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_blockwise_ragged_block_padding():
    # Sk not a multiple of block_k: internal padding path
    q, k, v = make_qkv(jax.random.PRNGKey(1), S=100)
    ref = attention_reference(q, k, v)
    out = blockwise_attention(q, k, v, block_k=64)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_blockwise_grads_match_reference():
    q, k, v = make_qkv(jax.random.PRNGKey(2), B=1, H=2, S=128)

    def loss_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def loss_blk(q, k, v):
        return blockwise_attention(q, k, v, causal=True, block_k=32).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_flash_forward_matches_reference(causal, masked):
    q, k, v = make_qkv(jax.random.PRNGKey(3), B=2, H=2, S=256)
    kv_mask = None
    if masked:
        lens = np.array([256, 130])
        kv_mask = jnp.asarray(np.arange(256)[None, :] < lens[:, None])
    ref = attention_reference(q, k, v, causal=causal, kv_mask=kv_mask)
    out = flash_attention(
        q, k, v, causal=causal, kv_mask=kv_mask, block_q=128, block_k=128
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    q, k, v = make_qkv(jax.random.PRNGKey(4), B=1, H=2, S=128)
    lens = np.array([128])
    kv_mask = jnp.asarray(np.arange(128)[None, :] < lens[:, None])

    def loss_ref(q, k, v):
        out = attention_reference(q, k, v, causal=causal, kv_mask=kv_mask)
        return (out * out).sum()  # non-trivial cotangent

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=causal, kv_mask=kv_mask, block_q=64, block_k=64
        )
        return (out * out).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_flash_bf16_close_to_f32_reference():
    q, k, v = make_qkv(jax.random.PRNGKey(5), S=128, dtype=jnp.bfloat16)
    ref = attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out.astype(np.float32), ref, atol=3e-2, rtol=3e-2
    )


def test_flash_rejects_ragged_seq():
    q, k, v = make_qkv(jax.random.PRNGKey(6), S=100)
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_flash_fully_masked_rows_are_zero():
    q, k, v = make_qkv(jax.random.PRNGKey(7), B=1, H=1, S=128)
    kv_mask = jnp.zeros((1, 128), bool)  # nothing to attend to
    out = flash_attention(q, k, v, kv_mask=kv_mask, block_q=64, block_k=64)
    np.testing.assert_allclose(out, np.zeros_like(out), atol=1e-6)


def test_blockwise_fully_masked_rows_are_zero():
    # must match flash_attention semantics (blockwise is its CPU fallback)
    q, k, v = make_qkv(jax.random.PRNGKey(8), B=1, H=1, S=128)
    kv_mask = jnp.zeros((1, 128), bool)
    out = blockwise_attention(q, k, v, kv_mask=kv_mask, block_k=32)
    np.testing.assert_allclose(out, np.zeros_like(out), atol=1e-6)


def test_flash_env_block_fallback(monkeypatch):
    # ADVICE r3: DTF_FLASH_BLOCK_Q/K are process-global trace-time knobs;
    # a sweep value that doesn't divide some OTHER call site's seq len
    # must fall back to the 128 default with a warning, not raise.
    # 384 % 256 != 0 (and 256 < 384, so min() doesn't clamp it away),
    # while the 128 fallback divides — the ADVICE finding's exact example
    q, k, v = make_qkv(jax.random.PRNGKey(7), B=1, H=2, S=384)
    ref = attention_reference(q, k, v)
    monkeypatch.setenv("DTF_FLASH_BLOCK_Q", "256")
    monkeypatch.setenv("DTF_FLASH_BLOCK_K", "256")
    with pytest.warns(UserWarning, match="falling back to 128"):
        out = flash_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    # an EXPLICIT non-dividing block argument still errors loudly
    with pytest.raises(ValueError, match="multiples of block sizes"):
        flash_attention(q, k, v, block_q=256, block_k=256)


def test_paged_attention_impls_match_gather_oracle():
    """Every paged attention impl answers identically (PR 20): the fused
    block-layout einsum and the Pallas kernel (interpreter off-TPU) must
    match the PR-13 gather+cached_attention oracle on random pools with
    ragged positions, sentinel table entries, an idle all-sentinel row,
    and verify-shaped (S>1) queries — the shapes the serve engine feeds
    the dispatch in decode, chunked prefill, and speculative verify."""
    from distributed_tensorflow_tpu.ops.attention import paged_attention

    key = jax.random.PRNGKey(7)
    B, H, D, bs, NB, MB = 3, 2, 16, 8, 10, 4
    kq, kk, kv = jax.random.split(key, 3)
    k_pool = jax.random.normal(kk, (NB, H, bs, D))
    v_pool = jax.random.normal(kv, (NB, H, bs, D))
    table = np.full((B, MB), NB, np.int32)
    table[0, :3] = [4, 9, 1]      # 3 live blocks, non-contiguous
    table[1, :1] = [0]            # 1 live block
    # row 2 stays all-sentinel: an idle slot (its output is garbage the
    # engine discards, but every impl must compute the SAME garbage)
    table = jnp.asarray(table)
    oob = MB * bs
    for S, q_pos in (
        (1, jnp.asarray([[17], [0], [oob]], jnp.int32)),
        (5, jnp.asarray([[17, 18, 19, 20, 21], [0, 1, 2, 3, 4],
                         [oob] * 5], jnp.int32)),
    ):
        q = jax.random.normal(kq, (B, H, S, D))
        want = paged_attention(
            q, k_pool, v_pool, table, q_pos=q_pos, impl="gather")
        for impl in ("fused", "pallas"):
            got = paged_attention(
                q, k_pool, v_pool, table, q_pos=q_pos, impl=impl)
            np.testing.assert_allclose(
                got, want, atol=2e-5, rtol=2e-5,
                err_msg=f"impl={impl} S={S}")
    with pytest.raises(ValueError, match="impl"):
        paged_attention(q, k_pool, v_pool, table, q_pos=q_pos, impl="nope")
