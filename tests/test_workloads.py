"""End-to-end workload tests (SURVEY.md §4.5): MNIST-MLP to convergence on
fake devices, CIFAR-CNN sync-DP smoke — the M6 'smallest thing that proves
the framework'."""

import os

import numpy as np
import pytest

from distributed_tensorflow_tpu import workloads

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_registry():
    assert "mnist_mlp" in workloads.available()
    with pytest.raises(ValueError, match="Unknown workload"):
        workloads.get("nope")


def test_mnist_mlp_converges(tmp_path):
    result = workloads.run_workload(
        "mnist_mlp",
        [
            "--train.num_steps=60",
            "--train.log_every=10",
            "--train.eval_batches=4",
            "--data.global_batch_size=256",
            "--optimizer.learning_rate=0.3",
            f"--checkpoint.directory={tmp_path}/ck",
            "--checkpoint.save_interval_steps=50",
            "--checkpoint.async_save=false",
            "--checkpoint.save_on_preemption=false",
        ],
    )
    hist = result.history
    assert hist[0]["loss"] > hist[-1]["loss"], "loss did not decrease"
    # linear-teacher task: must beat 10-class chance comfortably
    assert result.eval_metrics["accuracy"] > 0.3
    # top-5 dominates top-1 and must beat it on a 10-class head
    assert (result.eval_metrics["top5_accuracy"]
            >= result.eval_metrics["accuracy"])
    assert result.eval_metrics["top5_accuracy"] > 0.7
    assert int(result.state.step) == 60
    # checkpoint written and config serialized
    assert (tmp_path / "ck" / "config.json").exists()


@pytest.mark.slow
def test_cifar10_cnn_sync_dp8_smoke():
    result = workloads.run_workload(
        "cifar10_cnn",
        [
            "--train.num_steps=6",
            "--train.log_every=3",
            "--train.eval_batches=2",
            "--train.debug_metrics=true",
            "--data.global_batch_size=64",
            "--mesh.data=8",
        ],
    )
    assert int(result.state.step) == 6
    assert all(
        h["grads_finite"] == 1.0 for h in result.history
    ), "non-finite grads in CNN smoke"


def test_unimplemented_workload_friendly_error(monkeypatch):
    monkeypatch.setitem(workloads._REGISTRY, "ghost", ".ghost")
    with pytest.raises(ValueError, match="not implemented"):
        workloads.get("ghost")


def test_mid_train_eval_runs(caplog):
    import logging

    with caplog.at_level(logging.INFO, logger="distributed_tensorflow_tpu.workloads.runner"):
        workloads.run_workload(
            "mnist_mlp",
            ["--train.num_steps=4", "--train.log_every=2",
             "--train.eval_every=2", "--train.eval_batches=1",
             "--data.global_batch_size=64"],
        )
    assert any("eval @ step" in r.message for r in caplog.records), (
        "mid-train eval callback never fired"
    )


def test_resume_advances_data_stream(tmp_path):
    """After restore at step N, the runner must feed batch N, not batch 0."""
    from distributed_tensorflow_tpu.workloads import mnist_mlp, runner

    cfg = mnist_mlp.default_config()
    parts = mnist_mlp.build(cfg)
    b0 = next(iter(parts.dataset_fn(0)))
    b5 = next(iter(parts.dataset_fn(5)))
    import numpy as np

    assert not np.array_equal(b0["image"], b5["image"])
    # and the offset stream matches the straight stream at the same index
    straight = parts.dataset_fn(0)
    it = iter(straight)
    for _ in range(5):
        next(it)
    np.testing.assert_array_equal(next(it)["image"], b5["image"])


def test_anomaly_defense_wiring_runs(tmp_path):
    """train.anomaly_defense=true engages the in-graph guard + policy +
    quarantine-filtered stream through the runner: a clean run finishes
    with the flag reporting 0 and nothing quarantined."""
    from distributed_tensorflow_tpu.resilience import load_quarantine

    result = workloads.run_workload(
        "mnist_mlp",
        [
            "--train.num_steps=6",
            "--train.log_every=3",
            "--train.eval_batches=2",
            "--train.anomaly_defense=true",
            "--data.global_batch_size=64",
            f"--checkpoint.directory={tmp_path}/ck",
            "--checkpoint.save_interval_steps=100",
            "--checkpoint.async_save=false",
            "--checkpoint.save_on_preemption=false",
        ],
    )
    assert int(result.state.step) == 6
    # the per-step flag rides the fetched metrics; every step was clean
    assert result.history[-1]["nonfinite"] == 0.0
    assert load_quarantine(str(tmp_path / "ck")) == frozenset()


def test_elastic_fleet_wiring_slices_and_live_reshards(tmp_path, monkeypatch):
    """fleet.elastic=true engages the production elastic seam in the
    runner: the worker's data stream is its SHARD_PLAN slice of every
    global batch (ElasticStream), heartbeats + plan acks flow from the
    step seam, and a NEW plan written mid-run reshards the live stream
    exactly at its barrier index."""
    import dataclasses

    import distributed_tensorflow_tpu.data.pipeline as pl
    from distributed_tensorflow_tpu.resilience import fleet as fl
    from distributed_tensorflow_tpu.train import callbacks as cb
    from distributed_tensorflow_tpu.workloads import mnist_mlp, runner

    fleet_dir = str(tmp_path / "fleet")
    fl.write_shard_plan(fleet_dir, fl.ShardPlan(
        version=1, phase=fl.PLAN_STEADY, world=2, ranks={0: 0, 1: 1},
        barrier_step=0, fleet_size=2))

    sizes = []

    class Spy(pl.ElasticStream):
        def __next__(self):
            b = super().__next__()
            sizes.append(len(b["image"]))
            return b

    monkeypatch.setattr(pl, "ElasticStream", Spy)

    class RejoinAt2(cb.Callback):
        """Plays the fleet: after step 2 the gang is back at world 1
        (this worker absorbs everything), binding to batches > 2."""

        def on_step_end(self, trainer, step, metrics):
            if step == 2:
                fl.write_shard_plan(fleet_dir, fl.ShardPlan(
                    version=2, phase=fl.PLAN_STEADY, world=1, ranks={0: 0},
                    barrier_step=2, fleet_size=2))

    cfg = mnist_mlp.default_config()
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, num_steps=4, log_every=2,
                                  eval_batches=2),
        data=dataclasses.replace(cfg.data, global_batch_size=32),
        fleet=runner.FleetSection(dir=fleet_dir, worker=0, elastic=True),
    )
    result = runner.run(cfg, mnist_mlp.build,
                        extra_callbacks=[RejoinAt2()])
    assert int(result.state.step) == 4
    # steps 1-2 trained rank 0 of 2 (16 of 32); the live reshard at
    # barrier 2 restored the full batch for steps 3-4
    assert sizes == [16, 16, 32, 32]
    hb = fl.read_heartbeat(fl.heartbeat_path(fleet_dir, 0))
    assert hb.step == 4 and hb.plan_version == 2 and hb.world == 1


def test_elastic_runner_restarted_mid_hold_still_reaches_barrier(tmp_path):
    """A worker (re)started while a resize HOLD naming it is on disk
    must enter the barrier at train start — pre-acking the hold would
    leave the fleet waiting until hold_timeout_s and spuriously
    escalate the resize to a gang restart."""
    import threading
    import time

    import dataclasses

    from distributed_tensorflow_tpu.resilience import fleet as fl
    from distributed_tensorflow_tpu.workloads import mnist_mlp, runner

    fleet_dir = str(tmp_path / "fleet")
    fl.write_shard_plan(fleet_dir, fl.ShardPlan(
        version=1, phase=fl.PLAN_STEADY, world=2, ranks={0: 0, 1: 1},
        barrier_step=0, fleet_size=2))
    # a resize is in flight: the hold names worker 0
    fl.write_shard_plan(fleet_dir, fl.ShardPlan(
        version=2, phase=fl.PLAN_HOLD, world=2, ranks={0: 0, 1: 1},
        barrier_step=0, hold=(0,), fleet_size=2))
    hb_path = fl.heartbeat_path(fleet_dir, 0)
    saw_barrier = []

    def fleet_side():
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            hb = fl.read_heartbeat(hb_path)
            if hb is not None and hb.phase == "barrier" \
                    and hb.plan_version == 2:
                saw_barrier.append(hb.step)
                fl.write_shard_plan(fleet_dir, fl.ShardPlan(
                    version=3, phase=fl.PLAN_STEADY, world=1, ranks={0: 0},
                    barrier_step=hb.step or 0, fleet_size=2))
                return
            time.sleep(0.02)

    t = threading.Thread(target=fleet_side)
    t.start()
    cfg = mnist_mlp.default_config()
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, num_steps=2, log_every=1,
                                  eval_batches=2),
        data=dataclasses.replace(cfg.data, global_batch_size=32),
        fleet=runner.FleetSection(dir=fleet_dir, worker=0, elastic=True),
    )
    result = runner.run(cfg, mnist_mlp.build)
    t.join(timeout=5)
    assert saw_barrier, "worker never acknowledged the hold"
    assert int(result.state.step) == 2
    hb = fl.read_heartbeat(hb_path)
    assert hb.plan_version == 3 and hb.world == 1


def test_elastic_fleet_cli_knobs_and_anomaly_exclusion(tmp_path):
    """The fleet section parses from the CLI like every other config
    section, and the elastic stream refuses to share the raw cursor
    with the anomaly defense."""
    from distributed_tensorflow_tpu.resilience import fleet as fl

    fleet_dir = str(tmp_path / "fleet")
    fl.write_shard_plan(fleet_dir, fl.ShardPlan(
        version=1, phase=fl.PLAN_STEADY, world=2, ranks={0: 0, 1: 1},
        barrier_step=0, fleet_size=2))
    result = workloads.run_workload(
        "mnist_mlp",
        ["--train.num_steps=2", "--train.log_every=1",
         "--train.eval_batches=2", "--data.global_batch_size=32",
         f"--fleet.dir={fleet_dir}", "--fleet.worker=1",
         "--fleet.elastic=true"],
    )
    assert int(result.state.step) == 2
    hb = fl.read_heartbeat(fl.heartbeat_path(fleet_dir, 1))
    assert hb.step == 2 and hb.world == 2
    with pytest.raises(ValueError, match="mutually exclusive"):
        workloads.run_workload(
            "mnist_mlp",
            ["--train.num_steps=2", "--train.anomaly_defense=true",
             f"--checkpoint.directory={tmp_path}/ck",
             f"--fleet.dir={fleet_dir}", "--fleet.elastic=true"],
        )
    # ragged worker slices cannot shard over the mesh batch axes: a
    # non-dividing (global batch, world) pair fails at CONFIG time with
    # the fix named, not at the first step with a shape error
    fl.write_shard_plan(fleet_dir, fl.ShardPlan(
        version=2, phase=fl.PLAN_STEADY, world=3,
        ranks={0: 0, 1: 1, 2: 2}, barrier_step=0, fleet_size=3))
    with pytest.raises(ValueError, match="not divisible by elastic world"):
        workloads.run_workload(
            "mnist_mlp",
            ["--train.num_steps=2", "--data.global_batch_size=32",
             f"--fleet.dir={fleet_dir}", "--fleet.worker=0",
             "--fleet.elastic=true"],
        )
    # a uniform slice that does not divide the mesh batch-axes extent
    # (8 fake devices) fails the same way
    fl.write_shard_plan(fleet_dir, fl.ShardPlan(
        version=3, phase=fl.PLAN_STEADY, world=2, ranks={0: 0, 1: 1},
        barrier_step=0, fleet_size=2))
    with pytest.raises(ValueError, match="mesh batch-axes extent"):
        workloads.run_workload(
            "mnist_mlp",
            ["--train.num_steps=2", "--data.global_batch_size=8",
             f"--fleet.dir={fleet_dir}", "--fleet.worker=0",
             "--fleet.elastic=true"],
        )
    from distributed_tensorflow_tpu.workloads import runner

    with pytest.raises(ValueError, match="hold_timeout_s"):
        runner.FleetSection(dir=fleet_dir, elastic=True, hold_timeout_s=0)


def test_anomaly_defense_requires_checkpoint_dir():
    with pytest.raises(ValueError, match="anomaly_defense"):
        workloads.run_workload(
            "mnist_mlp",
            ["--train.num_steps=2", "--train.anomaly_defense=true"],
        )


def test_mnist_grad_accum_runs():
    result = workloads.run_workload(
        "mnist_mlp",
        [
            "--train.num_steps=4",
            "--train.log_every=2",
            "--train.grad_accum_steps=4",
            "--data.global_batch_size=64",
        ],
    )
    assert int(result.state.step) == 4


def test_summary_event_files_written(tmp_path):
    """SummarySaverHook analog (SURVEY.md §5.5): a short fit with
    train.summary_dir set leaves TensorBoard scalar events on disk."""
    logdir = str(tmp_path / "tb")
    workloads.run_workload(
        "mnist_mlp",
        [
            "--train.num_steps=6",
            "--train.log_every=2",
            f"--train.summary_dir={logdir}",
            "--data.global_batch_size=64",
        ],
    )
    from tensorboard.backend.event_processing import event_accumulator

    acc = event_accumulator.EventAccumulator(logdir)
    acc.Reload()
    tags = acc.Tags()["scalars"]
    assert "train/loss" in tags, tags
    assert "train/steps_per_sec" in tags, tags
    events = acc.Scalars("train/loss")
    assert len(events) >= 2
    assert all(np.isfinite(e.value) for e in events)
    steps = [e.step for e in events]
    assert steps == sorted(steps)


def test_eval_from_checkpoint_matches_live(tmp_path):
    """SURVEY.md §3.5: train 3 steps + save, then evaluate from disk with
    no Trainer; numbers must match the live eval at train end."""
    ckdir = str(tmp_path / "ck")
    args = [
        "--train.num_steps=3",
        "--train.log_every=2",
        "--train.eval_batches=2",
        "--data.global_batch_size=64",
        f"--checkpoint.directory={ckdir}",
        "--checkpoint.save_interval_steps=1",
        "--checkpoint.async_save=false",
    ]
    live = workloads.run_workload("mnist_mlp", args)
    assert live.eval_metrics is not None
    offline = workloads.eval_workload("mnist_mlp", args)
    assert offline["step"] == 3
    assert abs(offline["accuracy"] - live.eval_metrics["accuracy"]) < 1e-6
    assert abs(offline["top5_accuracy"]
               - live.eval_metrics["top5_accuracy"]) < 1e-6
    assert abs(offline["loss"] - live.eval_metrics["loss"]) < 1e-5


def test_eval_from_checkpoint_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="no checkpoint"):
        workloads.eval_workload("mnist_mlp", [
            f"--checkpoint.directory={tmp_path / 'empty'}",
        ])


@pytest.mark.slow
def test_gpt_lm_workload_trains_and_long_context_preset():
    """The sixth workload: causal LM through the full runner; the
    long-context preset wires ring attention + remat + a seq-wildcard
    mesh."""
    from distributed_tensorflow_tpu import workloads
    from distributed_tensorflow_tpu.workloads import gpt_lm

    result = workloads.run_workload(
        "gpt_lm",
        [
            "--train.num_steps=40",
            "--train.log_every=10",
            "--mesh.data=4",
            "--mesh.model=2",
            "--data.global_batch_size=32",
            "--data.seq_len=16",
            "--data.vocab_size=48",
            "--model.vocab_size=48",
            "--model.max_len=16",
            "--model.num_layers=2",
            "--model.d_model=32",
            "--model.num_heads=4",
            "--model.d_ff=64",
            "--model.dropout=0.0",
            "--model.dtype=float32",
            "--optimizer.learning_rate=3e-3",
            "--optimizer.warmup_steps=5",
            "--optimizer.total_steps=40",
        ],
    )
    hist = result.history
    assert hist[-1]["loss"] < hist[0]["loss"], hist

    lc = gpt_lm.long_context(seq_len=4096)
    assert lc.model.seq_impl == "ring" and lc.model.remat
    assert lc.model.max_len == 4096 and lc.data.seq_len == 4096
    assert lc.mesh.seq == -1


def test_profiler_callback_writes_trace(tmp_path):
    """The Profiler callback (ProfilerHook analog) leaves an XPlane trace
    on disk for TensorBoard after its start/stop window."""
    import os

    from distributed_tensorflow_tpu import workloads

    logdir = tmp_path / "prof"
    workloads.run_workload(
        "mnist_mlp",
        [
            "--train.num_steps=20",
            "--train.log_every=10",
            "--train.profile=true",
            f"--train.profile_dir={logdir}",
            "--data.global_batch_size=16",
        ],
    )
    traces = [
        os.path.join(r, f)
        for r, _, fs in os.walk(logdir) for f in fs
        if f.endswith(".xplane.pb")
    ]
    assert traces, f"no xplane trace under {logdir}"


@pytest.mark.slow
def test_convergence_demo_machinery(tmp_path):
    """tools/convergence_demo.py end to end at smoke scale: real digit
    scans -> JPEG records -> run_workload (decode+augment+train+ckpt) ->
    eval_workload restore on the held-out pair. The committed 400-step
    run reaches 98.4% (PERF_NOTES.md); here 20 steps must beat 3x chance
    and the machinery must produce valid JSON."""
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "convergence_demo.py"),
         "--steps", "20", "--workdir", str(tmp_path), "--min-top1", "0.3"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["eval_top1"] > 0.3, result


def test_clip_grad_norm_knob_gives_same_step_nan_signal():
    """--train.clip_grad_norm clips AND yields the free grads_finite
    metric (derived from the global norm) without debug_metrics."""
    result = workloads.run_workload("mnist_mlp", [
        "--train.num_steps=3", "--train.log_every=1",
        "--train.clip_grad_norm=1.0", "--data.global_batch_size=16",
        "--mesh.data=-1",
    ])
    last = result.history[-1]
    assert "grad_norm" in last and "grads_finite" in last
    assert last["grads_finite"] == 1.0


@pytest.mark.slow
def test_convergence_demo_ctr_machinery():
    """tools/convergence_demo_ctr.py end to end at smoke scale:
    teacher-labeled Criteo-format TSV -> make_ctr_records.py -> ctr:
    training through the native loader -> held-out AUC. The committed
    600-step run reaches AUC 0.77 (PERF_NOTES.md); here 40 steps must
    clear a weak above-chance gate and emit valid JSON."""
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "convergence_demo_ctr.py"),
         "--steps", "40", "--min-auc", "0.55"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["eval_auc"] > 0.55, result


@pytest.mark.slow
def test_convergence_demo_mlm_machinery():
    """tools/convergence_demo_mlm.py at smoke scale: repo .md prose ->
    byte token files -> tokens_mlm: training -> held-out masked-byte
    accuracy. The committed 1600-step run reaches 0.50 (PERF_NOTES.md);
    here 60 steps must beat the unigram floor and emit valid JSON."""
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "convergence_demo_mlm.py"),
         "--steps", "60", "--min-acc", "0.1"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["eval_masked_acc"] > 0.1, result


@pytest.mark.slow
def test_convergence_demo_long_ring_machinery():
    """The --long variant (causal LM at seq 256 THROUGH ring attention
    on a seq=4 mesh + remat) at smoke scale: the arg plumbing, ring mesh
    build, and extended JSON shape must work before a multi-hour run
    depends on them. The committed 3600-step run reaches 0.303
    (artifacts/lm_long_ring_r4.json)."""
    import json
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "convergence_demo_mlm.py"),
         "--long", "--steps", "12", "--min-acc", "0.0"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["objective"] == "lm_long_ring", result
    assert result["seq_len"] == 256 and result["seq_impl"] == "ring", result
    # conflicting flags error loudly
    bad = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "convergence_demo_mlm.py"),
         "--long", "--objective", "mlm"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert bad.returncode != 0 and "causal-LM variant" in bad.stderr


@pytest.mark.slow
def test_train_and_eval_cli_scripts(tmp_path):
    """The examples/{train,eval}.py SCRIPTS (not the API): the exact
    commands the README/MIGRATION show users, run as subprocesses with a
    checkpoint handoff between them. The round-3b on-chip profile step
    drives examples/train.py directly, so script-level rot would cost a
    chip window."""
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    ck = str(tmp_path / "ck")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "train.py"),
         "mnist_mlp", "--train.num_steps=4", "--train.log_every=2",
         "--data.global_batch_size=32", f"--checkpoint.directory={ck}",
         "--checkpoint.async_save=false",
         "--checkpoint.save_on_preemption=false",
         "--train.eval_batches=0", "--mesh.data=-1"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "eval.py"),
         "mnist_mlp", f"--checkpoint.directory={ck}",
         "--train.eval_batches=2", "--data.global_batch_size=32",
         "--mesh.data=-1"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "accuracy" in proc.stdout or "accuracy" in proc.stderr
