"""Transformer family tests: shapes, TP parity (sharded step == replicated
step — the strategy_test_lib oracle pattern, SURVEY.md §4.4), seq-parallel
integration, and BERT-MLM convergence through the workload runner."""

import dataclasses
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.data import TextDataConfig, SyntheticMLM
from distributed_tensorflow_tpu.models import transformer as tfm
from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
from distributed_tensorflow_tpu.parallel import sharding as sh
from distributed_tensorflow_tpu.train import (
    StepOptions, init_train_state, jit_train_step, make_train_step,
)


def tiny_cfg(**kw):
    base = dict(
        vocab_size=64, max_len=32, num_layers=2, d_model=32, num_heads=4,
        d_ff=64, dropout=0.0, dtype="float32",
    )
    base.update(kw)
    return tfm.TransformerConfig(**base)


def test_forward_shapes_and_mask():
    cfg = tiny_cfg()
    model = tfm.Transformer(cfg)
    ids = jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % cfg.vocab_size
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    # padding a masked-out position must not change real-token logits
    mask = jnp.ones((2, 16), jnp.int32).at[:, -4:].set(0)
    out1 = model.apply({"params": params}, ids, mask)
    ids2 = ids.at[:, -1].set((ids[:, -1] + 7) % cfg.vocab_size)
    out2 = model.apply({"params": params}, ids2, mask)
    np.testing.assert_allclose(out1[:, :12], out2[:, :12], atol=1e-5)


def test_causal_no_future_leak():
    cfg = tiny_cfg(causal=True, pre_ln=True)
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    ids = jnp.arange(16, dtype=jnp.int32)[None] % cfg.vocab_size
    out1 = model.apply({"params": params}, ids)
    ids2 = ids.at[:, -1].set((ids[:, -1] + 3) % cfg.vocab_size)
    out2 = model.apply({"params": params}, ids2)
    # positions before the change see identical logits
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], atol=1e-5)
    assert not np.allclose(out1[:, -1], out2[:, -1])


def _run_steps(mesh, param_rules, n_steps=3, seq_impl=None, mesh_for_model=None, **cfg_kw):
    cfg = tiny_cfg(seq_impl=seq_impl, **cfg_kw)
    model = tfm.Transformer(cfg, mesh_for_model)
    tx = optax.adam(1e-3)
    state, specs = init_train_state(
        tfm.make_init_fn(model, 16), tx, mesh, jax.random.PRNGKey(0),
        param_rules=param_rules,
    )
    step = jit_train_step(
        make_train_step(tfm.mlm_loss_fn(model), tx,
                        StepOptions(check_grads_finite=True)), mesh, specs
    )
    rng = np.random.RandomState(0)
    losses = []
    for i in range(n_steps):
        ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        labels = np.where(rng.rand(8, 16) < 0.3, ids, -100).astype(np.int32)
        batch = {"input_ids": ids, "labels": labels}
        batch = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, sh.batch_spec(x.ndim))
            ),
            batch,
        )
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
        assert float(metrics["grads_finite"]) == 1.0
    return losses, state


@pytest.mark.slow
def test_tp_matches_replicated(devices):
    """dp8 (params replicated) and dp4×tp2 (megatron rules) produce the
    same losses on the same batches."""
    mesh_dp = build_mesh(MeshSpec(data=8), devices[:8])
    mesh_tp = build_mesh(MeshSpec(data=4, model=2), devices[:8])
    losses_dp, _ = _run_steps(mesh_dp, None)
    losses_tp, state = _run_steps(mesh_tp, tfm.tp_rules())
    np.testing.assert_allclose(losses_dp, losses_tp, rtol=2e-4)
    # TP actually sharded something: qkv kernels live on the model axis
    qk = state.params["layer_0"]["attn"]["query"]["kernel"]
    assert qk.sharding.spec == P(None, "model")


@pytest.mark.slow
def test_seq_parallel_training_step(devices):
    """Training with ring-attention seq parallelism (seq=4) matches the
    dense dp run."""
    mesh_dp = build_mesh(MeshSpec(data=2), devices[:2])
    mesh_sp = build_mesh(MeshSpec(data=2, seq=4), devices[:8])
    losses_dense, _ = _run_steps(mesh_dp, None)
    losses_sp, _ = _run_steps(
        mesh_sp, None, seq_impl="ring", mesh_for_model=mesh_sp
    )
    np.testing.assert_allclose(losses_dense, losses_sp, rtol=2e-4)


@pytest.mark.slow
def test_seq_parallel_composes_with_remat(devices):
    """cfg.remat (nn.remat around each Block) nests the ring-attention
    shard_map island inside jax.checkpoint; the composed program must
    match the plain dense dp run exactly like the non-remat SP test."""
    mesh_dp = build_mesh(MeshSpec(data=2), devices[:2])
    mesh_sp = build_mesh(MeshSpec(data=2, seq=4), devices[:8])
    losses_dense, _ = _run_steps(mesh_dp, None)
    losses_sp_remat, _ = _run_steps(
        mesh_sp, None, seq_impl="ring", mesh_for_model=mesh_sp, remat=True
    )
    np.testing.assert_allclose(losses_dense, losses_sp_remat, rtol=2e-4)


@pytest.mark.slow
def test_lm_loss_decreases():
    cfg = tiny_cfg(causal=True, pre_ln=True)
    mesh = build_mesh(MeshSpec(data=1), jax.devices()[:1])
    model = tfm.Transformer(cfg)
    tx = optax.adam(3e-3)
    state, specs = init_train_state(
        tfm.make_init_fn(model, 16), tx, mesh, jax.random.PRNGKey(0)
    )
    step = jit_train_step(
        make_train_step(tfm.lm_loss_fn(model), tx,
                        StepOptions(check_grads_finite=True)), mesh, specs
    )
    # deterministic walk: ids[t+1] = (ids[t]+1) % V — learnable
    rng = np.random.RandomState(0)
    losses = []
    for i in range(30):
        start = rng.randint(0, cfg.vocab_size, (8, 1))
        ids = (start + np.arange(16)[None]) % cfg.vocab_size
        state, metrics = step(state, {"input_ids": ids.astype(np.int32)})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses


def test_lm_loss_ignores_padding_targets():
    """The last real token of a right-padded sequence must not be trained
    to predict the pad token: zeroing the pad's mask position removes its
    label from the loss."""
    cfg = tiny_cfg(causal=True, pre_ln=True)
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 8)(jax.random.PRNGKey(0))
    loss_fn = tfm.lm_loss_fn(model)
    ids = jnp.asarray([[5, 6, 7, 1, 0, 0, 0, 0]], jnp.int32)
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.int32)
    rng = jax.random.PRNGKey(1)
    loss_masked, _ = loss_fn(params, {}, {"input_ids": ids,
                                          "attention_mask": mask}, rng)
    # manual oracle: only labels at positions 0..2 (targets ids[1..3]) count
    logits = model.apply({"params": params}, ids, mask, train=True,
                         rngs={"dropout": rng})
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    want = -(lp[0, 0, 6] + lp[0, 1, 7] + lp[0, 2, 1]) / 3
    np.testing.assert_allclose(float(loss_masked), float(want), rtol=1e-5)


def test_synthetic_mlm_dataset():
    cfg = TextDataConfig(global_batch_size=4, seq_len=12, vocab_size=32,
                         mask_prob=0.5, mask_token=0)
    ds = SyntheticMLM(cfg, num_batches=2)
    batches = list(ds)
    assert len(batches) == 2
    b = batches[0]
    assert b["input_ids"].shape == (4, 12)
    assert b["labels"].shape == (4, 12)
    masked = b["labels"] != -100
    assert masked.any() and not masked.all()
    # determinism
    b2 = SyntheticMLM(cfg, num_batches=1).batch(0)
    np.testing.assert_array_equal(b["input_ids"], b2["input_ids"])


@pytest.mark.slow
def test_bert_workload_converges():
    """Tiny BERT through the full runner on 8 fake devices with dp4×tp2 —
    MLM on the permutation corpus must beat chance clearly."""
    from distributed_tensorflow_tpu import workloads

    result = workloads.run_workload(
        "bert_pretrain",
        [
            "--train.num_steps=40",
            "--train.log_every=10",
            "--mesh.data=4",
            "--mesh.model=2",
            "--data.global_batch_size=64",
            "--data.seq_len=16",
            "--data.vocab_size=48",
            "--data.mask_token=0",
            "--model.vocab_size=48",
            "--model.max_len=16",
            "--model.num_layers=2",
            "--model.d_model=32",
            "--model.num_heads=4",
            "--model.d_ff=64",
            "--model.dropout=0.0",
            "--model.dtype=float32",
            "--optimizer.learning_rate=3e-3",
            "--optimizer.warmup_steps=5",
            "--optimizer.total_steps=40",
        ],
    )
    hist = result.history
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert int(result.state.step) == 40


@pytest.mark.slow
def test_flash_padding_path_matches_dense():
    """attention_impl=flash with a non-block-multiple seq len (200) pads
    internally and matches the dense reference (Pallas interpret on CPU)."""
    cfg = tiny_cfg(max_len=256, num_layers=1, num_heads=2, d_model=16,
                   attention_impl="flash")
    cfg_dense = dataclasses.replace(cfg, attention_impl="dense")
    model_f = tfm.Transformer(cfg)
    model_d = tfm.Transformer(cfg_dense)
    params, _ = tfm.make_init_fn(model_d, 200)(jax.random.PRNGKey(0))
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (1, 200))
    ids = jnp.asarray(ids, jnp.int32)
    mask = jnp.ones((1, 200), jnp.int32).at[:, -9:].set(0)
    out_f = model_f.apply({"params": params}, ids, mask)
    out_d = model_d.apply({"params": params}, ids, mask)
    np.testing.assert_allclose(out_f[:, :191], out_d[:, :191], atol=2e-4)


def test_param_count_matches_analytic():
    cfg = tiny_cfg()
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    actual = sum(p.size for p in jax.tree.leaves(params))
    assert actual == tfm.param_count(cfg)


def test_param_count_matches_analytic_moe():
    cfg = tiny_cfg(num_layers=4, num_experts=4, moe_every=2, moe_top_k=2)
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    actual = sum(p.size for p in jax.tree.leaves(params))
    assert actual == tfm.param_count(cfg)
    # active params: only top_k of num_experts FFNs per MoE block
    assert tfm.active_param_count(cfg) < tfm.param_count(cfg)
    assert tfm.active_param_count(tiny_cfg()) == tfm.param_count(tiny_cfg())


@pytest.mark.slow
def test_remat_preserves_forward_and_grads():
    """cfg.remat wraps blocks in nn.remat (jax.checkpoint): identical
    param tree, bit-equal-at-f32-tolerance forward, and matching grads —
    only the backward's memory/recompute schedule may differ."""
    cfg = tiny_cfg()
    m_plain = tfm.Transformer(cfg)
    m_remat = tfm.Transformer(dataclasses.replace(cfg, remat=True))
    ids = (jnp.arange(4 * 16, dtype=jnp.int32).reshape(4, 16)
           % cfg.vocab_size)
    params, _ = tfm.make_init_fn(m_plain, 16)(jax.random.PRNGKey(0))
    # same tree: remat is a lifted transform, not a reparameterization
    p2, _ = tfm.make_init_fn(m_remat, 16)(jax.random.PRNGKey(0))
    assert jax.tree_util.tree_structure(params) == \
        jax.tree_util.tree_structure(p2)

    def loss(m):
        def go(p):
            lg = m.apply({"params": p}, ids, train=False)
            return (lg.astype(jnp.float32) ** 2).mean()
        return go

    np.testing.assert_allclose(
        jax.jit(loss(m_remat))(params), jax.jit(loss(m_plain))(params),
        rtol=1e-6)
    g_r = jax.jit(jax.grad(loss(m_remat)))(params)
    g_p = jax.jit(jax.grad(loss(m_plain)))(params)
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(g_r),
        jax.tree_util.tree_leaves_with_path(g_p),
    ):
        np.testing.assert_allclose(
            a, b, rtol=2e-5, atol=1e-7,
            err_msg=jax.tree_util.keystr(path))


@pytest.mark.slow
def test_bert_workload_pipelined_pp_tp():
    """--mesh.pipe=2 --mesh.model=2 engages the pipelined family (PP×TP)
    straight from the workload config path; MLM loss must fall like the
    dense run's."""
    from distributed_tensorflow_tpu import workloads

    result = workloads.run_workload(
        "bert_pretrain",
        [
            "--train.num_steps=40",
            "--train.log_every=10",
            "--mesh.pipe=2",
            "--mesh.model=2",
            "--mesh.data=2",
            "--train.pipeline_virtual=2",  # interleaved schedule knob
            "--train.pipeline_microbatches=8",  # explicit (auto would pick 8 too)
            "--data.global_batch_size=64",
            "--data.seq_len=16",
            "--data.vocab_size=48",
            "--data.mask_token=0",
            "--model.vocab_size=48",
            "--model.max_len=16",
            "--model.num_layers=4",  # S*V=4 chunks of one layer
            "--model.d_model=32",
            "--model.num_heads=4",
            "--model.d_ff=64",
            "--model.dropout=0.0",
            "--model.dtype=float32",
            "--optimizer.learning_rate=3e-3",
            "--optimizer.warmup_steps=5",
            "--optimizer.total_steps=40",
        ],
    )
    hist = result.history
    assert hist[-1]["loss"] < hist[0]["loss"], hist
    # the pipelined eval fn runs the same schedule params
    assert result.eval_metrics is not None
    assert 0 < result.eval_metrics["accuracy"] <= 1.0


@pytest.mark.slow
def test_bert_pipelined_checkpoint_eval_roundtrip(tmp_path):
    """The stacked [S,lc,...] pipelined layout survives checkpoint →
    standalone evaluate_from_checkpoint: restored eval stats equal the
    live trainer's exactly."""
    from distributed_tensorflow_tpu import workloads
    from distributed_tensorflow_tpu.utils import config as config_lib
    from distributed_tensorflow_tpu.workloads import bert_pretrain

    overrides = [
        "--train.num_steps=12",
        "--train.log_every=6",
        "--mesh.pipe=2",
        "--mesh.data=4",
        "--data.global_batch_size=32",
        "--data.seq_len=16",
        "--data.vocab_size=48",
        "--data.mask_token=0",
        "--model.vocab_size=48",
        "--model.max_len=16",
        "--model.num_layers=2",
        "--model.d_model=32",
        "--model.num_heads=4",
        "--model.d_ff=64",
        "--model.dropout=0.0",
        "--model.dtype=float32",
        f"--checkpoint.directory={tmp_path}/ck",
        "--checkpoint.save_interval_steps=6",
        "--checkpoint.async_save=false",
        "--checkpoint.save_on_preemption=false",
    ]
    live = workloads.run_workload("bert_pretrain", overrides)
    assert live.eval_metrics is not None
    cfg = config_lib.apply_overrides(
        bert_pretrain.default_config(), overrides
    )
    offline = workloads.evaluate_from_checkpoint(cfg, bert_pretrain.build)
    for k in ("loss", "accuracy", "count"):
        assert abs(offline[k] - live.eval_metrics[k]) < 1e-6, (
            k, offline, live.eval_metrics)


def test_mlm_gathered_head_matches_dense_slice():
    """Transformer(positions=...) must equal the full-seq logits gathered
    at those positions — the head math is identical, only the gather
    moves before the head (the reference's masked_lm_positions path)."""
    cfg = tfm.TransformerConfig(
        vocab_size=64, max_len=16, num_layers=2, d_model=32, num_heads=4,
        d_ff=64, causal=False, pre_ln=False, dtype="float32", dropout=0.0,
    )
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 64, (4, 16)), jnp.int32)
    pos = jnp.asarray(
        np.sort(np.argsort(rng.rand(4, 16), axis=1)[:, :5], axis=1),
        jnp.int32,
    )
    full = model.apply({"params": params}, ids, train=False)
    gathered = model.apply({"params": params}, ids, train=False,
                           positions=pos)
    want = jnp.take_along_axis(full, pos[..., None], axis=1)
    np.testing.assert_allclose(np.asarray(gathered), np.asarray(want),
                               atol=1e-5)
    # causal LMs reject the MLM-head path
    ccfg = dataclasses.replace(cfg, causal=True, pre_ln=True)
    cmodel = tfm.Transformer(ccfg)
    cparams, _ = tfm.make_init_fn(cmodel, 16)(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="causal"):
        cmodel.apply({"params": cparams}, ids, train=False, positions=pos)


def test_synthetic_mlm_gathered_format():
    """max_predictions emits exactly-K positions/labels consistent with
    the corrupted input_ids (labels = original tokens at positions)."""
    from distributed_tensorflow_tpu.data.text import (
        TextDataConfig, resolved_max_predictions, make_text_dataset,
    )

    cfg = TextDataConfig(dataset="synthetic_mlm", global_batch_size=8,
                         seq_len=32, vocab_size=128, max_predictions=-1)
    K = resolved_max_predictions(cfg)
    assert K == round(0.15 * 32)
    b = next(iter(make_text_dataset(cfg, num_batches=1)))
    assert b["masked_positions"].shape == (8, K)
    assert b["masked_labels"].shape == (8, K)
    assert "labels" not in b
    # positions strictly increasing per row (sorted, no duplicates)
    assert (np.diff(b["masked_positions"], axis=1) > 0).all()
    # at keep-corruption positions the label equals the input token;
    # everywhere the label is a valid vocab id
    assert ((0 <= b["masked_labels"]) & (b["masked_labels"] < 128)).all()
    # explicit K wins; K > seq_len rejected
    cfg2 = dataclasses.replace(cfg, max_predictions=7)
    assert resolved_max_predictions(cfg2) == 7
    with pytest.raises(ValueError, match="max_predictions"):
        resolved_max_predictions(
            dataclasses.replace(cfg, max_predictions=64))


def test_fused_qkv_matches_unfused():
    """fused_qkv=True (one [d, 3d] projection) must be numerically
    identical to the three-projection layout when its qkv kernel/bias is
    the concatenation of the unfused query/key/value params — forward
    AND gradients (mapped back through the concatenation)."""
    cfg = tiny_cfg()
    fcfg = tiny_cfg(fused_qkv=True)
    model = tfm.Transformer(cfg)
    fmodel = tfm.Transformer(fcfg)
    ids = jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % cfg.vocab_size
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))

    H, D = cfg.num_heads, cfg.head_dim

    def fuse(tree):
        """query/key/value -> qkv in the fused layout's HEAD-major column
        order ([d] -> [H, 3, D]; see SelfAttention.fused_qkv)."""
        if isinstance(tree, dict):
            if {"query", "key", "value"} <= set(tree):
                d = tree["query"]["kernel"].shape[0]
                qkv = {
                    "kernel": jnp.stack(
                        [tree[n]["kernel"].reshape(d, H, D)
                         for n in ("query", "key", "value")],
                        axis=2).reshape(d, 3 * H * D),
                    "bias": jnp.stack(
                        [tree[n]["bias"].reshape(H, D)
                         for n in ("query", "key", "value")],
                        axis=1).reshape(3 * H * D),
                }
                rest = {k: fuse(v) for k, v in tree.items()
                        if k not in ("query", "key", "value")}
                return {**rest, "qkv": qkv}
            return {k: fuse(v) for k, v in tree.items()}
        return tree

    fparams = fuse(params)
    want = model.apply({"params": params}, ids)
    got = fmodel.apply({"params": fparams}, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    # gradient parity: fused grads, split back, must equal unfused grads
    tgt = jax.random.normal(jax.random.PRNGKey(1), want.shape)

    def loss(m):
        return lambda p: ((m.apply({"params": p}, ids) - tgt) ** 2).mean()

    g_unfused = jax.grad(loss(model))(params)
    g_fused = jax.grad(loss(fmodel))(fparams)

    def check(gu, gf, path=""):
        if isinstance(gu, dict) and {"query", "key", "value"} <= set(gu):
            d = gu["query"]["kernel"].shape[0]
            kf = np.asarray(gf["qkv"]["kernel"]).reshape(d, H, 3, D)
            bf = np.asarray(gf["qkv"]["bias"]).reshape(H, 3, D)
            for i, n in enumerate(("query", "key", "value")):
                np.testing.assert_allclose(
                    kf[:, :, i, :].reshape(d, H * D),
                    np.asarray(gu[n]["kernel"]), atol=2e-5, err_msg=path)
                np.testing.assert_allclose(
                    bf[:, i, :].reshape(H * D),
                    np.asarray(gu[n]["bias"]), atol=2e-5, err_msg=path)
            for k in gu:
                if k not in ("query", "key", "value"):
                    check(gu[k], gf[k], f"{path}/{k}")
        elif isinstance(gu, dict):
            assert set(gu) == set(gf), (path, set(gu), set(gf))
            for k in gu:
                check(gu[k], gf[k], f"{path}/{k}")
        else:
            # every non-attention gradient leaf (embeddings, attn_out,
            # mlp, LayerNorms) must match too — a silent skip here would
            # hide fused-path gradient mispropagation
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gu), atol=2e-5, err_msg=path)

    check(g_unfused, g_fused)

    # guard: fused_qkv + fused_ln_matmul is an explicit error
    bad = tiny_cfg(fused_qkv=True, fused_ln_matmul=True, pre_ln=True,
                   causal=True)
    bmodel = tfm.Transformer(bad)
    with pytest.raises(ValueError, match="mutually exclusive"):
        tfm.make_init_fn(bmodel, 16)(jax.random.PRNGKey(0))


@pytest.mark.slow
def test_tp_matches_replicated_fused_qkv(devices):
    """The fused-qkv TP rules are exercised by the same oracle as the
    unfused layout: dp8 (replicated) == dp4×tp2 (qkv column-sharded),
    and the fused kernel really lives on the model axis."""
    mesh_dp = build_mesh(MeshSpec(data=8), devices[:8])
    mesh_tp = build_mesh(MeshSpec(data=4, model=2), devices[:8])
    losses_dp, _ = _run_steps(mesh_dp, None, fused_qkv=True)
    losses_tp, state = _run_steps(mesh_tp, tfm.tp_rules(), fused_qkv=True)
    np.testing.assert_allclose(losses_dp, losses_tp, rtol=2e-4)
    qk = state.params["layer_0"]["attn"]["qkv"]["kernel"]
    assert qk.sharding.spec == P(None, "model")


def test_fused_qkv_tp_hlo_has_no_resharding(devices):
    """The head-major fused-qkv column layout's design claim, pinned at
    the HLO level: under GSPMD TP the q/k/v extraction is shard-local —
    the compiled attention forward contains NO all-to-all and NO
    all-gather (the only collective is attn_out's row-parallel
    all-reduce, which unfused TP needs too)."""
    mesh = build_mesh(MeshSpec(model=2), devices[:2])
    cfg = tiny_cfg(fused_qkv=True, attention_impl="dense")
    sa = tfm.SelfAttention(cfg, None)
    x = jnp.zeros((2, 16, cfg.d_model), jnp.float32)
    params = sa.init(jax.random.PRNGKey(0), x, None, train=False)["params"]
    specs = sh.specs_from_path_rules(params, tfm.tp_rules())
    put = jax.device_put(params, sh.tree_shardings(mesh, specs))

    fwd = jax.jit(lambda p, x: sa.apply({"params": p}, x, None, train=False))
    with mesh:
        hlo = fwd.lower(put, x).compile().as_text()
    assert "all-to-all" not in hlo, "q/k/v extraction resharded"
    assert "all-gather" not in hlo, "projection output gathered"
    assert "all-reduce" in hlo  # TP really distributed the math


def test_chunked_lm_loss_matches_dense():
    """chunked_lm_loss_fn (scan over sequence chunks, logits never
    materialized at [B,S,V]) is numerically identical to lm_loss_fn:
    loss, accuracy, and every gradient leaf — including the tied
    embedding, whose gradient accumulates across chunks."""
    cfg = tiny_cfg(causal=True, pre_ln=True)
    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 16)(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(1)
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 16)),
        jnp.int32)
    mask = jnp.ones((4, 16), jnp.int32).at[:, -3:].set(0)  # ragged tail
    batch = {"input_ids": ids, "attention_mask": mask}

    dense = tfm.lm_loss_fn(model)
    (ld, (_, md)), gd = jax.value_and_grad(
        lambda p: dense(p, {}, batch, rng), has_aux=True)(params)
    for chunk in (4, 8, 16):  # multi-chunk, mid, single-chunk edge
        chunked = tfm.chunked_lm_loss_fn(model, chunk)
        (lc, (_, mc)), gc = jax.value_and_grad(
            lambda p: chunked(p, {}, batch, rng), has_aux=True)(params)
        np.testing.assert_allclose(float(lc), float(ld), rtol=1e-6)
        np.testing.assert_allclose(float(mc["accuracy"]),
                                   float(md["accuracy"]), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5),
            gc, gd)

    # non-dividing chunk errors loudly
    with pytest.raises(ValueError, match="not divisible"):
        tfm.chunked_lm_loss_fn(model, 5)(params, {}, batch, rng)

    # the chunked EVAL stats match the dense eval exactly too (a
    # large-vocab run must not OOM at its own final eval)
    se_dense = tfm.lm_eval_fn(model)(params, {}, batch)
    se_chunk = tfm.lm_eval_fn(model, 4)(params, {}, batch)
    for k in se_dense:
        np.testing.assert_allclose(
            float(se_chunk[k]), float(se_dense[k]), rtol=1e-6, err_msg=k)


def test_bf16_head_dtype():
    """head_dtype="bfloat16" (fast-MXU vocab projection, f32 accum):
    close to the exact f32 head, identical between the dense and chunked
    paths (both route through _head_projection), and f32 remains
    bit-identical to the historical Embed.attend path by construction."""
    cfg32 = tiny_cfg(causal=True, pre_ln=True)
    cfg16 = tiny_cfg(causal=True, pre_ln=True, head_dtype="bfloat16")
    m32, m16 = tfm.Transformer(cfg32), tfm.Transformer(cfg16)
    params, _ = tfm.make_init_fn(m32, 16)(jax.random.PRNGKey(0))
    ids = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg32.vocab_size, (2, 16)),
        jnp.int32)
    l32 = m32.apply({"params": params}, ids)
    l16 = m16.apply({"params": params}, ids)
    assert l32.dtype == l16.dtype == jnp.float32
    # bf16 rounding of ~unit-scale logits: loose absolute tolerance
    np.testing.assert_allclose(np.asarray(l16), np.asarray(l32),
                               atol=0.05, rtol=0.05)
    assert not np.array_equal(np.asarray(l16), np.asarray(l32))

    # chunked loss == dense loss EXACTLY at bf16 head too (same
    # _head_projection on both sides)
    batch = {"input_ids": ids}
    rng = jax.random.PRNGKey(2)
    ld, _ = tfm.lm_loss_fn(m16)(params, {}, batch, rng)
    lc, _ = tfm.chunked_lm_loss_fn(m16, 4)(params, {}, batch, rng)
    np.testing.assert_allclose(float(lc), float(ld), rtol=1e-6)


@pytest.mark.slow
def test_rules_table_training_bit_identical_to_legacy_path_rules(devices):
    """PR 14 migration acceptance: the same-seed short train run under
    the strict transformer_rules table is BIT-identical (params and
    losses) to the run under the frozen pre-engine soft path rules."""
    legacy_rules = (
        (r"(^|/)w_in$", P("expert", None, "model")),
        (r"(^|/)b_in$", P("expert", "model")),
        (r"(^|/)w_out$", P("expert", "model", None)),
        (r"(^|/)b_out$", P("expert", None)),
        (r"(query|key|value)/kernel", P(None, "model")),
        (r"(query|key|value)/bias", P("model")),
        (r"qkv/kernel", P(None, "model")),
        (r"qkv/bias", P("model")),
        (r"attn_out/kernel", P("model", None)),
        (r"mlp_in/kernel", P(None, "model")),
        (r"mlp_in/bias", P("model")),
        (r"mlp_out/kernel", P("model", None)),
        (r"tok_embed/embedding", P("model", None)),
        (r"mlm_bias", P("model")),
    )
    mesh = build_mesh(MeshSpec(data=4, model=2), devices[:8])
    table = tfm.transformer_rules(tiny_cfg())
    losses_t, state_t = _run_steps(mesh, table)
    losses_l, state_l = _run_steps(mesh, legacy_rules)
    assert losses_t == losses_l  # float-exact, not allclose
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(state_t.params),
        jax.tree_util.tree_leaves_with_path(state_l.params),
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=str(pa))
        assert a.sharding == b.sharding, pa
