"""Shipped lint fixtures — the self-check corpus.

For every rule: a POSITIVE snippet (must produce exactly that rule at
the line marked ``# fires-here``), a NEGATIVE snippet (the idiomatic
clean version — must produce nothing), and a SUPPRESSED snippet (the
positive plus a ``# dtflint: disable=<rule>`` marker — must produce
nothing). ``tools/dtf_lint.py --self-check`` runs all three for every
rule before the tree lint, so the CI gate can never rot silently: a
rule that stops firing on its own positive fixture fails the gate even
though the (now-unprotected) tree still lints clean.

tests/test_lint.py drives the same corpus through the library API and
additionally pins file:line anchoring and the exit-code contract.
"""

from __future__ import annotations

FIRES_MARKER = "# fires-here"

#: rules whose fixtures are PATH-SENSITIVE (seam rules fire only on
#: seam paths) lint under a seam-shaped path instead of the default
#: ``<fixture:rule:corpus>`` pseudo-path
FIXTURE_PATHS: dict[str, str] = {
    "wall-clock-in-seam":
        "distributed_tensorflow_tpu/data/_fixture_{corpus}.py",
    # axis literals are checked only inside the mesh-consuming dirs
    "mesh-axis-closed-vocab":
        "distributed_tensorflow_tpu/parallel/_fixture_{corpus}.py",
    # placement constructions are checked across the package dirs,
    # outside the seam file itself
    "sharding-seam-bypass":
        "distributed_tensorflow_tpu/serve/_fixture_{corpus}.py",
}


def fixture_path(rule: str, corpus: str) -> str:
    """The path a fixture lints under (seam rules need seam paths)."""
    tmpl = FIXTURE_PATHS.get(rule)
    if tmpl is None:
        return f"<fixture:{rule}:{corpus}>"
    return tmpl.format(corpus=corpus)


def injection_path(rule: str) -> str:
    """Relative on-disk path at which the positive fixture must fire
    when a tree containing it is linted (tests/test_lint.py's CLI
    injection gate writes fixtures at these paths)."""
    tmpl = FIXTURE_PATHS.get(rule)
    if tmpl is None:
        return f"bad_{rule.replace('-', '_')}.py"
    return tmpl.format(corpus="positive")


def expected_line(source: str) -> int:
    """1-based line carrying the ``# fires-here`` marker."""
    for i, line in enumerate(source.splitlines(), 1):
        if FIRES_MARKER in line:
            return i
    raise ValueError("fixture has no fires-here marker")


POSITIVE: dict[str, str] = {
    "host-sync-in-step": '''\
import jax
import numpy as np


@jax.jit
def train_step(state, batch):
    grads = batch["x"] * 2.0
    loss = float(grads.sum())  # fires-here
    return state, {"loss": loss}
''',
    "donation-after-use": '''\
import jax


def _step(state, batch):
    return state


step = jax.jit(_step, donate_argnums=(0,))


def run_once(state, batch):
    new_state = step(state, batch)
    print(state.params)  # fires-here
    return new_state
''',
    "lock-discipline": '''\
import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def size(self):
        return len(self._items)  # fires-here
''',
    "closed-vocab": '''\
class Engine:
    def __init__(self, flightrec):
        self.flightrec = flightrec

    def poke(self):
        self.flightrec.emit("warp_core_breach", step=1)  # fires-here
''',
    "exception-hygiene": '''\
def best_effort_cleanup(path):
    try:
        open(path).close()
    except:  # fires-here
        pass
''',
    "wall-clock-in-seam": '''\
import time


def stamp_batch(batch):
    batch["t"] = time.time()  # fires-here
    return batch
''',
    "atomic-durable-write": '''\
import json
import os


def write_manifest(directory, doc):
    path = os.path.join(directory, "MANIFEST.json")
    with open(path, "w") as f:  # fires-here
        json.dump(doc, f)
''',
    "metric-naming": '''\
class Worker:
    def __init__(self, registry):
        self._m_restarts = registry.counter(  # fires-here
            "worker_restarts", "restarts observed")
''',
    "shard-rules-coverage": '''\
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel.sharding import partition_rules

TABLE = partition_rules(
    "fixture-model",
    (
        (r"kernel$", P(None, "model")),
        (r"kernle$", P("model")),  # fires-here
        (r".*", P()),
    ),
    coverage=("layer_0/kernel", "layer_0/bias"),
)
''',
    "mesh-axis-closed-vocab": '''\
from jax import lax


def global_sum(x):
    return lax.psum(x, "dtaa")  # fires-here
''',
    "sharding-seam-bypass": '''\
import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def place_batch(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P("data")))  # fires-here
''',
}


NEGATIVE: dict[str, str] = {
    "host-sync-in-step": '''\
import jax
import jax.numpy as jnp


@jax.jit
def train_step(state, batch):
    grads = batch["x"] * 2.0
    loss = jnp.sum(grads)
    return state, {"loss": loss}


def report(metrics):
    # host side, outside the jitted step: syncing is the point
    return float(metrics["loss"])
''',
    "donation-after-use": '''\
import jax


def _step(state, batch):
    return state


step = jax.jit(_step, donate_argnums=(0,))


def run_once(state, batch):
    state = step(state, batch)
    return state.params
''',
    "lock-discipline": '''\
import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def size(self):
        with self._lock:
            return len(self._items)

    def _size_unlocked(self):
        return len(self._items)
''',
    "closed-vocab": '''\
class Engine:
    def __init__(self, flightrec, reqtrace):
        self.flightrec = flightrec
        self.reqtrace = reqtrace

    def poke(self):
        self.flightrec.emit("serve_admit", uid=1, slot=0)
        self.reqtrace.transition(7, "decode_gap", n=1)
''',
    "exception-hygiene": '''\
import logging

logger = logging.getLogger(__name__)


def best_effort_cleanup(path):
    try:
        open(path).close()
    except OSError:
        logger.exception("cleanup of %s failed", path)
''',
    "wall-clock-in-seam": '''\
import time

import numpy as np


def make_batch(seed, index, clock=time.monotonic):
    # the sanctioned idioms: seeded generator, injectable clock seam
    rng = np.random.RandomState((seed + index) & 0x7FFFFFFF)
    return {"x": rng.uniform(size=(4,)), "queued_at": clock()}
''',
    "atomic-durable-write": '''\
import json
import os


def write_manifest(directory, doc):
    path = os.path.join(directory, "MANIFEST.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
''',
    "metric-naming": '''\
class Worker:
    def __init__(self, registry):
        self._m_restarts = registry.counter(
            "worker_restarts_total", "restarts observed")
        self._m_step = registry.histogram(
            "worker_step_seconds", "wall-clock seconds per step")
        self._m_occupancy = registry.gauge(
            "worker_occupancy", "active slots at the last step")
''',
    "shard-rules-coverage": '''\
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel.sharding import partition_rules

TABLE = partition_rules(
    "fixture-model",
    (
        (r"kernel$", P(None, "model")),
        (r".*", P()),
    ),
    coverage=("layer_0/kernel", "layer_0/bias"),
)
''',
    "mesh-axis-closed-vocab": '''\
from jax import lax

from ..parallel import mesh as mesh_lib


def global_sum(x):
    # vocabulary axes — as literals or (better) the mesh_lib constants
    partial = lax.psum(x, "data")
    return lax.psum(partial, mesh_lib.MODEL)
''',
    "sharding-seam-bypass": '''\
from jax.sharding import PartitionSpec as P

from ..parallel import sharding
from ..utils.compat import shard_map


def cache_rules():
    # carve-out (a): *_rules row builders compose partition tables
    return ((r"(^|/)(k|v)$", P(None, "model")),)


def island_mean(mesh, x):
    # carve-out (b): specs inside a shard_map island describe the
    # island's local view, not persistent placement
    f = shard_map(lambda a: a.mean(), mesh=mesh,
                  in_specs=P("data"), out_specs=P())
    return f(x)


def place_batch(mesh, x):
    # persistent placement goes through the seam helpers
    return sharding.shard_leading_dim(x, mesh, "data")
''',
}


SUPPRESSED: dict[str, str] = {
    "host-sync-in-step": '''\
import jax


@jax.jit
def train_step(state, batch):
    loss = float(batch.sum())  # dtflint: disable=host-sync-in-step
    return state, {"loss": loss}
''',
    "donation-after-use": '''\
import jax


def _step(state, batch):
    return state


step = jax.jit(_step, donate_argnums=(0,))


def run_once(state, batch):
    new_state = step(state, batch)
    # dtflint: disable=donation-after-use
    print(state.params)
    return new_state
''',
    "lock-discipline": '''\
import threading


class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def size(self):
        return len(self._items)  # dtflint: disable=lock-discipline
''',
    "closed-vocab": '''\
class Engine:
    def __init__(self, flightrec):
        self.flightrec = flightrec

    def poke(self):
        # deliberate negative-path probe, e.g. a must-raise test
        self.flightrec.emit("warp_core_breach")  # dtflint: disable=closed-vocab
''',
    "exception-hygiene": '''\
def best_effort_cleanup(path):
    try:
        open(path).close()
    except:  # dtflint: disable=exception-hygiene
        pass
''',
    "wall-clock-in-seam": '''\
import time


def stamp_batch(batch):
    # informational metadata, reviewed: not a trajectory input
    batch["t"] = time.time()  # dtflint: disable=wall-clock-in-seam
    return batch
''',
    "atomic-durable-write": '''\
import json
import os


def write_manifest(directory, doc):
    path = os.path.join(directory, "MANIFEST.json")
    # reviewed: freshness over durability, torn records detected upstream
    with open(path, "w") as f:  # dtflint: disable=atomic-durable-write
        json.dump(doc, f)
''',
    "metric-naming": '''\
class Worker:
    def __init__(self, registry):
        # legacy dashboard name, reviewed
        self._m_restarts = registry.counter(  # dtflint: disable=metric-naming
            "worker_restarts", "restarts observed")
''',
    "shard-rules-coverage": '''\
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_tpu.parallel.sharding import partition_rules

TABLE = partition_rules(
    "fixture-model",
    (
        (r"kernel$", P(None, "model")),
        # variant row kept for an out-of-run tree, reviewed
        (r"kernle$", P("model")),  # dtflint: disable=shard-rules-coverage
        (r".*", P()),
    ),
    coverage=("layer_0/kernel", "layer_0/bias"),
)
''',
    "mesh-axis-closed-vocab": '''\
from jax import lax


def global_sum(x):
    # dynamically bound sub-axis, reviewed
    return lax.psum(x, "dtaa")  # dtflint: disable=mesh-axis-closed-vocab
''',
    "sharding-seam-bypass": '''\
import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def place_batch(mesh, x):
    # transitional call site, reviewed — migrating to the seam next PR
    # dtflint: disable=sharding-seam-bypass
    return jax.device_put(x, NamedSharding(mesh, P("data")))
''',
}


def self_check() -> list[str]:
    """Run every fixture through the real rule set; returns failure
    descriptions (empty == the lint layer is alive and precise)."""
    from .core import RULES, lint_sources

    failures: list[str] = []
    for rule in sorted(RULES):
        for corpus, name in ((POSITIVE, "positive"), (NEGATIVE, "negative"),
                             (SUPPRESSED, "suppressed")):
            if rule not in corpus:
                failures.append(f"{rule}: no {name} fixture shipped")
    for rule, src in POSITIVE.items():
        want_line = expected_line(src)
        found = lint_sources({fixture_path(rule, "positive"): src})
        hits = [f for f in found if f.rule == rule]
        if not hits:
            failures.append(
                f"{rule}: positive fixture produced no finding — the "
                f"rule went dead")
        elif all(f.line != want_line for f in hits):
            failures.append(
                f"{rule}: positive fixture fired at line(s) "
                f"{[f.line for f in hits]}, expected {want_line}")
        for f in found:
            if f.rule != rule:
                failures.append(
                    f"{rule}: positive fixture also tripped {f.rule} "
                    f"at line {f.line} — fixtures must isolate one rule")
    for rule, src in NEGATIVE.items():
        found = lint_sources({fixture_path(rule, "negative"): src})
        if found:
            failures.append(
                f"{rule}: negative fixture not clean: "
                f"{[f.format() for f in found]}")
    for rule, src in SUPPRESSED.items():
        found = lint_sources({fixture_path(rule, "suppressed"): src})
        if found:
            failures.append(
                f"{rule}: suppression marker ignored: "
                f"{[f.format() for f in found]}")
    return failures
