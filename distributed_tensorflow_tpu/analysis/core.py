"""dtflint core — findings, rule registry, suppressions, the lint driver.

PRs 1-6 each ended with a review round catching the same invariant
classes by hand: a torn lock-free ``Registry.snapshot``, donated-buffer
reuse after a jitted call, the "exactly one ×3 MFU multiplier site"
contract, host syncs hiding in jit-traced step functions, swallowed
exceptions defeating the supervisor's fault taxonomy. This package turns
those review catches into *mechanical*, CI-gated checks: every rule here
encodes one invariant the framework already relies on, phrased as an
AST query over the repo's own idioms.

Design constraints:

- **stdlib-only.** The analyzer imports nothing heavy — no jax, no
  numpy — so ``tools/dtf_lint.py`` runs on a bare CI box in well under a
  second. Framework vocabularies (flight-recorder event kinds, waste
  causes, the docs metric tables) are extracted by *parsing* the source
  files, never by importing them.
- **Heuristic, but precise on this repo's idioms.** Rules are
  intraprocedural/module-local where whole-program analysis would be
  needed for exactness; the heuristics are tuned so the shipped tree
  lints clean without drowning real violations in noise. Each rule's
  docstring states its approximations.
- **Suppressible, loudly.** ``# dtflint: disable=<rule>[,<rule>...]``
  on the flagged line (or the line directly above it) suppresses a
  finding — the reviewable, greppable escape hatch for deliberate
  negatives (e.g. tools/obs_check.py's must-raise vocabulary tests).
  ``# dtflint: disable-file=<rule>`` anywhere in a file suppresses the
  rule for the whole file.

Exit-code contract (tools/dtf_lint.py): 0 = clean, 1 = findings (or a
failed ``--self-check``), 2 = usage/internal error.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Callable, Iterable, Iterator

__all__ = [
    "Finding",
    "Module",
    "LintContext",
    "Rule",
    "RULES",
    "register",
    "lint_paths",
    "lint_sources",
    "repo_root",
]

#: ``# dtflint: disable=a,b`` / ``# dtflint: disable-file=a,b``
_SUPPRESS_RE = re.compile(
    r"#\s*dtflint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)"
)


def repo_root() -> str:
    """The repository this analyzer ships in (vocabulary files and
    docs tables are resolved relative to it)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source line."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file plus its suppression table."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        #: line -> set of rule names disabled on that line
        self.line_disables: dict[int, set[str]] = {}
        #: rules disabled for the entire file
        self.file_disables: set[str] = set()
        # suppressions bind to real COMMENT tokens only — a per-line
        # regex would also match marker text inside string literals
        # (docstrings, fixture corpora), silently disabling rules for
        # the whole file
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []  # ast parsed it, so this is effectively dead
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            names = {n.strip() for n in m.group(2).split(",") if n.strip()}
            if m.group(1) == "disable-file":
                self.file_disables |= names
            else:
                self.line_disables.setdefault(
                    tok.start[0], set()).update(names)

    def suppressed(self, finding: Finding) -> bool:
        """A finding is suppressed by a marker on its own line, on the
        line directly above it, or by a file-level disable."""
        for names in (self.file_disables,
                      self.line_disables.get(finding.line, ()),
                      self.line_disables.get(finding.line - 1, ())):
            if finding.rule in names or "all" in names:
                return True
        return False

    def constant_strings(self) -> dict[str, str]:
        """Module-level ``NAME = "literal"`` bindings — lets rules see
        through the repo's metric-name/site-name constants."""
        out: dict[str, str] = {}
        for node in self.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                out[node.targets[0].id] = node.value.value
        return out


class LintContext:
    """Shared state across one lint run: the repo root (for vocabulary
    extraction), the full parsed module set (``lint_sources`` populates
    it before any rule runs — project-scope engines like
    ``analysis/callgraph.py`` build over it), and a free-form scratch
    dict rules use to accumulate across modules before ``finalize``."""

    def __init__(self, root: str | None = None):
        self.root = root if root is not None else repo_root()
        self.scratch: dict = {}
        #: every Module in this lint run, set by the driver BEFORE the
        #: first check_module call — cross-module rules see the whole
        #: run even while being handed one module at a time
        self.modules: list[Module] = []

    def read_repo_file(self, relpath: str) -> str | None:
        try:
            with open(os.path.join(self.root, relpath)) as f:
                return f.read()
        except OSError:
            return None


class Rule:
    """One lint rule. Subclasses set ``name``/``summary`` and implement
    ``check_module``; project-scope rules may also implement
    ``finalize`` (runs once after every module was scanned)."""

    name: str = ""
    summary: str = ""

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        return iter(())

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        return iter(())


RULES: dict[str, Rule] = {}


def register(rule) -> Rule:
    """Register a Rule (instances and classes both accepted, so rules
    can use ``@register`` as a class decorator)."""
    if isinstance(rule, type):
        rule = rule()
    if not rule.name:
        raise ValueError("rule must have a name")
    if rule.name in RULES:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    RULES[rule.name] = rule
    return rule


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def _iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if not d.startswith(".") and d != "__pycache__"
                )
                for n in sorted(names):
                    if n.endswith(".py"):
                        yield os.path.join(root, n)
        else:
            raise FileNotFoundError(p)


def _active_rules(rules: Iterable[str] | None) -> list[Rule]:
    from . import rules as _rules  # noqa: F401 — registration side effect

    if rules is None:
        return list(RULES.values())
    missing = [r for r in rules if r not in RULES]
    if missing:
        raise KeyError(f"unknown rule(s): {missing} (known: {sorted(RULES)})")
    return [RULES[r] for r in rules]


def lint_sources(
    sources: dict[str, str],
    rules: Iterable[str] | None = None,
    root: str | None = None,
    on_parse_error: Callable[[str, SyntaxError], None] | None = None,
) -> list[Finding]:
    """Lint in-memory ``{path: source}`` pairs (tests, fixtures, and
    the file driver below all funnel through here). Findings come back
    sorted by (path, line, rule); suppressed findings are dropped."""
    active = _active_rules(rules)
    ctx = LintContext(root=root)
    findings: list[Finding] = []
    modules: list[Module] = []
    for path, source in sources.items():
        try:
            modules.append(Module(path, source))
        except SyntaxError as e:
            if on_parse_error is not None:
                on_parse_error(path, e)
            else:
                raise
    ctx.modules = modules  # the whole run, before any rule sees a module
    for module in modules:
        for rule in active:
            for f in rule.check_module(module, ctx):
                if not module.suppressed(f):
                    findings.append(f)
    by_path = {m.path: m for m in modules}
    for rule in active:
        for f in rule.finalize(ctx):
            m = by_path.get(f.path)
            if m is None or not m.suppressed(f):
                findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def lint_paths(
    paths: Iterable[str],
    rules: Iterable[str] | None = None,
    root: str | None = None,
    on_parse_error: Callable[[str, SyntaxError], None] | None = None,
) -> list[Finding]:
    """Lint files/directories on disk. Paths are reported as given
    (relative in → relative out, the CI-log-friendly form)."""
    sources: dict[str, str] = {}
    for path in _iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            sources[path] = f.read()
    return lint_sources(sources, rules=rules, root=root,
                        on_parse_error=on_parse_error)


# ---------------------------------------------------------------------------
# small AST helpers shared by the rules
# ---------------------------------------------------------------------------


def seam_match(path: str, seams: Iterable[str]) -> bool:
    """Segment-anchored seam matching shared by the path-seam rules
    (exception-hygiene, wall-clock-in-seam, atomic-durable-write).

    A seam like ``"resilience/"`` or ``"train/step.py"`` matches when it
    appears at a path-segment boundary — so both the repo-rooted form
    (``distributed_tensorflow_tpu/resilience/x.py``) and a
    package-relative lint invocation (``resilience/x.py``) hit, while
    look-alike segments (``myresilience/``, ``latests/`` vs
    ``tests/``) do not."""
    p = "/" + path.replace("\\", "/").lstrip("./")
    return any(f"/{s}" in p for s in seams)


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)
