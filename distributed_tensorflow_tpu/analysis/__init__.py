"""dtflint — framework-aware static analysis for this repo.

An AST-based lint layer that mechanically enforces the invariants the
review rounds caught by hand: host syncs inside jit-traced step
functions and reuse of donated pytrees (both resolved on the
PROJECT-SCOPE call graph in :mod:`analysis.callgraph` — reachability
and donating bindings follow imports across modules), lock-guarded
state touched outside its lock, closed-vocabulary drift
(flight-recorder kinds, metric names vs docs, the single ×3
MFU-multiplier site), swallowed exceptions in the fault-classification
seams, wall-clock/unseeded-randomness reads inside the deterministic
seams (the bit-identical-replay contract), durable state written
outside the tmp+fsync+os.replace idiom, and misshapen metric names
(counters end ``_total``, second-valued histograms end ``_seconds``).

Entry points:

- ``tools/dtf_lint.py`` — the CLI (``--strict`` gates tools/ci_fast.sh;
  ``--self-check`` proves every rule still fires on its shipped
  fixtures and that the tree is clean; ``--changed-only`` narrows
  reporting to the git diff for the dev loop).
- :func:`lint_paths` / :func:`lint_sources` — the library API
  (tests/test_lint.py drives the fixtures through these).

Rule catalog, engine contract, suppression syntax, and pre-fix
examples: docs/static-analysis.md.
"""

from .core import (  # noqa: F401
    Finding,
    LintContext,
    Module,
    Rule,
    RULES,
    lint_paths,
    lint_sources,
    register,
    repo_root,
)
from . import rules  # noqa: F401 — registers the rule set
from . import fixtures  # noqa: F401 — the self-check corpus

__all__ = [
    "Finding",
    "LintContext",
    "Module",
    "Rule",
    "RULES",
    "lint_paths",
    "lint_sources",
    "register",
    "repo_root",
]
