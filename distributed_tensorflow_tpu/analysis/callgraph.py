"""Project-scope call graph — the v2 engine under the jit-reachability rules.

PR 7's `host-sync-in-step` and `donation-after-use` computed their call
closure per module: a step function that calls a helper in ANOTHER
module went blind at the module boundary, exactly where the framework
puts its helpers (serve/decode.py jits functions that live next to the
models, train/step.py's factories hand their products across files).
This module builds ONE graph over every file in the lint run so
reachability and donated-callable resolution follow calls across
modules.

What is resolved (documented approximation — this is a linter, not an
interpreter):

- **Module identity.** A file's dotted module name is derived from its
  path: anything under ``distributed_tensorflow_tpu/`` keeps its real
  package path (``…/serve/decode.py`` → ``distributed_tensorflow_tpu
  .serve.decode``); anything else (tools/, tests/, in-memory fixtures)
  is its bare stem — fixture files ``a.py``/``b.py`` resolve ``from a
  import helper`` against each other.
- **Imports.** ``import pkg.mod [as m]``, ``from pkg import mod [as
  m]``, ``from pkg.mod import fn [as f]``, and relative forms
  (``from ..ops.attention import flash_attention``) are resolved
  against the modules *in this lint run*. Star imports and imports of
  modules outside the run resolve to nothing (conservative).
- **Calls.** Bare names (local def or from-imported symbol), dotted
  names through module aliases (``sh.specs_from_path_rules``,
  ``ops.attention.cached_attention`` — submodule chains are walked),
  and ``self.``/``cls.`` method calls (name-union within the module).
- **Function references.** ``functools.partial(f, …)`` targets, and
  function refs passed to the trace-context primitives (``lax.scan`` /
  ``cond`` / ``while_loop`` / ``vmap`` / ``grad`` / ``remat`` …) count
  as calls from the enclosing function: their bodies run under the
  caller's trace.
- **Jit roots.** Functions decorated with / passed to ``jax.jit`` /
  ``pjit`` / ``pmap`` (through ``partial`` and across modules), plus
  the framework step-name contract (``train_step`` / ``eval_step`` /
  ``decode_step`` / ``prefill`` — jitted by factories the scan may not
  see).
- **Donating symbols.** Module-level bindings of
  ``jax.jit(…, donate_argnums=…)`` results (and the donating-factory
  products) are importable: ``from train.step import jitted_step``
  carries the donated positions with it.

Attribute calls on *objects* (``model.apply``, ``tx.update``) stay
unresolved — binding method receivers is whole-program analysis. The
closure is therefore an under-approximation of true reachability and an
over-approximation of nothing: every edge corresponds to a syntactic
call path.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .core import LintContext, Module, dotted_name

__all__ = [
    "CallGraph",
    "ModuleNode",
    "get_callgraph",
    "module_name",
    "JIT_WRAPPERS",
    "STEP_FUNCTION_NAMES",
]

#: the package whose internal layout survives into module names
PACKAGE = "distributed_tensorflow_tpu"

JIT_WRAPPERS = frozenset({
    "jit", "jax.jit", "pjit", "jax.pjit", "jax.pmap", "pmap",
})

#: functions jitted by factories in other modules — the framework's
#: step-function naming contract (train/step.jit_train_step,
#: serve/decode.jit_prefill / jit_decode_step)
STEP_FUNCTION_NAMES = frozenset({
    "train_step", "eval_step", "decode_step", "prefill",
})

#: last path component of callables whose function-ref arguments run
#: under the caller's trace (jax.lax control flow, functional
#: transforms) — a ref passed to these is an edge, not just a value
_TRACE_ARG_TAKERS = frozenset({
    "scan", "cond", "while_loop", "fori_loop", "switch", "map",
    "vmap", "pmap", "grad", "value_and_grad", "remat", "checkpoint",
    "named_call", "associative_scan",
})

_PARTIALS = ("partial", "functools.partial")


def module_name(path: str) -> str:
    """Dotted module name for a lint path (see module docstring)."""
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [seg for seg in p.split("/") if seg and seg != "."]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if PACKAGE in parts:
        return ".".join(parts[parts.index(PACKAGE):])
    return parts[-1] if parts else p


def partial_target(call: ast.Call) -> ast.AST | None:
    """``partial(f, …)`` / ``functools.partial(f, …)`` → f."""
    if dotted_name(call.func) in _PARTIALS and call.args:
        return call.args[0]
    return None


def unwrap_ref(node: ast.AST) -> ast.AST | None:
    """Peel ``partial`` layers off a function reference; returns the
    Name/Attribute underneath, or None for anything unresolvable."""
    while isinstance(node, ast.Call):
        inner = partial_target(node)
        if inner is None:
            return None
        node = inner
    if isinstance(node, (ast.Name, ast.Attribute)):
        return node
    return None


class ModuleNode:
    """One module's symbols: defs (name-union over every scope, as in
    the v1 per-module index) and its resolved-to-dotted import table."""

    def __init__(self, module: Module):
        self.module = module
        self.name = module_name(module.path)
        #: bare def name -> every def node sharing it (conservative union)
        self.defs: dict[str, list[ast.AST]] = {}
        #: local alias -> ("module", dotted) | ("from", base_dotted, leaf)
        self.imports: dict[str, tuple] = {}
        self._index()

    def _index(self) -> None:
        # a package __init__ IS its package (module_name dropped the
        # "__init__" segment); a plain module's package is its parent
        p = self.module.path.replace("\\", "/")
        if p.endswith("/__init__.py") or p == "__init__.py":
            pkg_parts = self.name.split(".")
        else:
            pkg_parts = self.name.split(".")[:-1]
        for node in ast.walk(self.module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.imports[a.asname] = ("module", a.name)
                    else:
                        head = a.name.split(".")[0]
                        self.imports[head] = ("module", head)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    up = len(pkg_parts) - (node.level - 1)
                    if up < 0:
                        continue  # escapes the lint run; unresolvable
                    base_parts = pkg_parts[:up]
                else:
                    base_parts = []
                if node.module:
                    base_parts = base_parts + node.module.split(".")
                base = ".".join(base_parts)
                if not base:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports[a.asname or a.name] = ("from", base, a.name)


class CallGraph:
    """The cross-module graph: nodes are ``(module_name, def_name)``
    pairs; edges are resolved syntactic calls (see module docstring)."""

    def __init__(self, modules: list[Module]):
        self.nodes: dict[str, ModuleNode] = {}
        for m in modules:
            self.nodes[module_name(m.path)] = ModuleNode(m)
        self._edge_cache: dict[tuple[str, str], frozenset] = {}

    # -- name resolution ---------------------------------------------------

    def _import_module(self, imp: tuple) -> str | None:
        """An import-table entry read as a MODULE, when it names one."""
        if imp[0] == "module":
            return imp[1]
        base, leaf = imp[1], imp[2]
        cand = f"{base}.{leaf}"
        if cand in self.nodes:
            return cand
        # `from pkg import mod` where pkg/__init__ isn't in the run:
        # cand still names the module if any linted file has that name
        return cand if any(n.startswith(cand + ".") for n in self.nodes) \
            else None

    def _import_symbol(self, imp: tuple) -> tuple[str, str] | None:
        """An import-table entry read as a SYMBOL of a known module —
        a def, or a module-level binding (donating callables are
        assignments, not defs; reachability simply finds no defs for
        them)."""
        if imp[0] != "from":
            return None
        base, leaf = imp[1], imp[2]
        if base in self.nodes:
            return (base, leaf)
        return None

    def resolve_callable(self, mnode: ModuleNode,
                         dn: str | None) -> tuple[str, str] | None:
        """Resolve a dotted call/reference name inside ``mnode`` to a
        ``(module, function)`` node, or None."""
        if dn is None:
            return None
        parts = dn.split(".")
        head = parts[0]
        if head in ("self", "cls"):
            if len(parts) == 2 and parts[1] in mnode.defs:
                return (mnode.name, parts[1])
            return None
        if len(parts) == 1:
            if head in mnode.defs:
                return (mnode.name, head)
            imp = mnode.imports.get(head)
            return self._import_symbol(imp) if imp else None
        imp = mnode.imports.get(head)
        if imp is None:
            return None
        mod = self._import_module(imp)
        if mod is None:
            # `from pkg.mod import fn` used as a bare prefix can't be
            # extended with attributes — fn.x is an object attribute
            return None
        i = 1
        while i < len(parts) - 1 and f"{mod}.{parts[i]}" in self.nodes:
            mod = f"{mod}.{parts[i]}"
            i += 1
        if i != len(parts) - 1:
            return None
        if mod in self.nodes:
            return (mod, parts[-1])
        return None

    def resolve_ref(self, mnode: ModuleNode,
                    node: ast.AST) -> tuple[str, str] | None:
        """Resolve a function REFERENCE (possibly partial-wrapped)."""
        ref = unwrap_ref(node)
        return self.resolve_callable(mnode, dotted_name(ref)) \
            if ref is not None else None

    # -- edges and reachability --------------------------------------------

    def callees(self, key: tuple[str, str]) -> frozenset:
        """Every resolved target called (or trace-referenced) from the
        defs of ``key`` — nested defs included, since their bodies run
        (or are traced) under the enclosing function."""
        cached = self._edge_cache.get(key)
        if cached is not None:
            return cached
        mnode = self.nodes.get(key[0])
        out: set[tuple[str, str]] = set()
        if mnode is not None:
            for d in mnode.defs.get(key[1], ()):
                for node in ast.walk(d):
                    if not isinstance(node, ast.Call):
                        continue
                    target = self.resolve_callable(
                        mnode, dotted_name(node.func))
                    if target is not None:
                        out.add(target)
                    inner = partial_target(node)
                    if inner is not None:
                        target = self.resolve_ref(mnode, inner)
                        if target is not None:
                            out.add(target)
                    dn = dotted_name(node.func)
                    if dn is not None \
                            and dn.rpartition(".")[2] in _TRACE_ARG_TAKERS:
                        for arg in list(node.args) + [
                                kw.value for kw in node.keywords]:
                            target = self.resolve_ref(mnode, arg)
                            if target is not None:
                                out.add(target)
        result = frozenset(out)
        self._edge_cache[key] = result
        return result

    def jit_roots(self) -> set[tuple[str, str]]:
        """Every function the run can prove (or the framework contract
        declares) enters a jit trace."""
        roots: set[tuple[str, str]] = set()
        for mname, mnode in self.nodes.items():
            for name, defs in mnode.defs.items():
                if name in STEP_FUNCTION_NAMES:
                    roots.add((mname, name))
                for d in defs:
                    for dec in d.decorator_list:
                        target = dec.func if isinstance(dec, ast.Call) \
                            else dec
                        dn = dotted_name(target)
                        if dn in JIT_WRAPPERS:
                            roots.add((mname, name))
                        elif isinstance(dec, ast.Call) and dn in _PARTIALS:
                            inner = dec.args[0] if dec.args else None
                            if dotted_name(inner) in JIT_WRAPPERS:
                                roots.add((mname, name))
            for node in ast.walk(mnode.module.tree):
                if isinstance(node, ast.Call) \
                        and dotted_name(node.func) in JIT_WRAPPERS \
                        and node.args:
                    target = self.resolve_ref(mnode, node.args[0])
                    if target is not None:
                        roots.add(target)
        return roots

    def reachable_from(
        self, roots: set[tuple[str, str]],
    ) -> dict[tuple[str, str], tuple[str, str] | None]:
        """Transitive closure over :meth:`callees`; returns
        ``node -> parent`` (None for roots) so rules can explain HOW a
        cross-module function became reachable."""
        parents: dict[tuple[str, str], tuple[str, str] | None] = {}
        frontier = sorted(r for r in roots if r[0] in self.nodes
                          and r[1] in self.nodes[r[0]].defs)
        for r in frontier:
            parents[r] = None
        while frontier:
            key = frontier.pop()
            for callee in sorted(self.callees(key)):
                if callee not in parents:
                    parents[callee] = key
                    frontier.append(callee)
        return parents

    def jit_reachable(
        self,
    ) -> dict[tuple[str, str], tuple[str, str] | None]:
        return self.reachable_from(self.jit_roots())

    # -- donating symbols --------------------------------------------------

    def donator_symbols(
        self, factory_donations: dict[str, tuple[int, ...]],
        donated_positions,
    ) -> dict[tuple[str, str], tuple[int, ...]]:
        """Module-level (importable) bindings of donating callables:
        ``step = jax.jit(_step, donate_argnums=(0,))`` and the factory
        products. ``donated_positions`` is rules.donation's literal
        ``donate_argnums`` extractor (kept there with its contract)."""
        out: dict[tuple[str, str], tuple[int, ...]] = {}
        for mname, mnode in self.nodes.items():
            for stmt in mnode.module.tree.body:
                if not isinstance(stmt, ast.Assign) \
                        or not isinstance(stmt.value, ast.Call):
                    continue
                positions = donated_positions(stmt.value)
                if positions is None:
                    callee = dotted_name(stmt.value.func)
                    if callee is not None:
                        positions = factory_donations.get(
                            callee.rpartition(".")[2])
                if not positions:
                    continue
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        out[(mname, target.id)] = positions
        return out


def get_callgraph(ctx: LintContext) -> CallGraph:
    """The one graph of this lint run, built lazily over every module
    ``core.lint_sources`` parsed and cached on the context."""
    graph = ctx.scratch.get("callgraph")
    if graph is None:
        graph = CallGraph(getattr(ctx, "modules", []) or [])
        ctx.scratch["callgraph"] = graph
    return graph


def iter_defs(graph: CallGraph, key: tuple[str, str]) -> Iterator[ast.AST]:
    mnode = graph.nodes.get(key[0])
    if mnode is not None:
        yield from mnode.defs.get(key[1], ())
