"""donation-after-use — never touch a pytree a jitted call donated.

``donate_argnums`` lets XLA alias an input's buffers into the output —
the in-place update that halves train-state HBM (train/step.py) and
removes the per-token KV-cache copy (serve/decode.py). The contract is
that the caller REBINDS and never reads the donated pytree again; a read
after the call sees deleted buffers at best and, with the buggy
cache-deserialized executables the ROADMAP documents, heap corruption
and silently-NaN params at worst. This rule makes the contract
mechanical.

Detection (intraprocedural per function; donating-callable resolution
is PROJECT-SCOPE since the v2 engine):

- **Donating callables.** Any local binding of the form
  ``f = jax.jit(..., donate_argnums=...)`` (including ``self.attr``
  targets and the decorator form), plus the framework's donating
  factories — ``jit_train_step`` (donates position 0, the TrainState)
  and ``jit_prefill`` / ``jit_decode_step`` (donate position 1, the
  KVCache) — whose wrapping happens in another module where a local
  scan can't see the ``donate_argnums``. Additionally, module-level
  donating bindings are importable: ``from serve.decode import
  jitted_step`` (or ``decode_lib.jitted_step(...)`` through a module
  alias) carries its donated positions into the importing module via
  the call graph (analysis/callgraph.py).
- **Consumption.** A call to a donating callable taints the plain-name
  or ``self.attr`` argument at each donated position.
- **Violation.** Any later read of the tainted name in the same
  function, before a rebind. The canonical clean pattern — rebinding in
  the call's own assignment, ``state, metrics = step(state, batch)`` —
  untaints immediately.

Per-line ordering: uses are judged against consumption from *earlier*
lines, so a same-line rebind is never a false positive; a use-then-
consume loop body can evade the rule (it is a linter, not a verifier).
"""

from __future__ import annotations

import ast
from typing import Iterator

from .. import callgraph as cg
from ..core import Finding, LintContext, Module, Rule, dotted_name, register

#: framework factories that return donating callables: name -> donated
#: positional indices of the RETURNED callable (train/step.py,
#: serve/decode.py keep these contracts)
FACTORY_DONATIONS: dict[str, tuple[int, ...]] = {
    "jit_train_step": (0,),
    "jit_prefill": (1,),
    "jit_decode_step": (1,),
}


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    """donate_argnums of a ``jax.jit(...)`` call, when literal."""
    if dotted_name(call.func) not in ("jax.jit", "jit", "pjit", "jax.pjit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
                else:
                    return None  # non-literal: cannot resolve
            return tuple(out)
        return None
    return None


def _binding_repr(node: ast.AST) -> str | None:
    """A trackable lvalue/rvalue: plain name or dotted self-attr."""
    dn = dotted_name(node)
    if dn is None:
        return None
    head = dn.split(".", 1)[0]
    if head in ("self", "cls") or "." not in dn:
        return dn
    return None


def _donating_call_positions(call: ast.Call,
                             donators: dict[str, tuple[int, ...]],
                             resolver=None,
                             ) -> tuple[int, ...] | None:
    """Donated positions when ``call`` invokes a known donating
    callable (bound name, framework factory product, or — through
    ``resolver`` — an imported module-level donating binding)."""
    dn = dotted_name(call.func)
    if dn is not None and dn in donators:
        return donators[dn]
    if isinstance(call.func, ast.Call):
        # immediately-invoked form: jax.jit(f, donate_argnums=...)(x, y)
        inline = _donated_positions(call.func)
        if inline:
            return inline
    if resolver is not None:
        return resolver(call)
    return None


class _FunctionLister(ast.NodeVisitor):
    def __init__(self):
        self.functions: list[ast.AST] = []

    def visit_FunctionDef(self, node):
        self.functions.append(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _scope_walk(fn: ast.AST):
    """Walk one function's own scope: nested defs are skipped (each is
    analyzed independently) — line-order taint must never leak across
    scope boundaries, where a same-named variable is a different
    binding and textual order says nothing about execution order."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class DonationRule(Rule):
    name = "donation-after-use"
    summary = ("a pytree passed at a donate_argnums position is read "
               "again after the jitted call consumed it")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        graph = cg.get_callgraph(ctx)
        symbols = ctx.scratch.get("donator_symbols")
        if symbols is None:
            symbols = graph.donator_symbols(
                FACTORY_DONATIONS, _donated_positions)
            ctx.scratch["donator_symbols"] = symbols
        mnode = graph.nodes.get(cg.module_name(module.path))
        if mnode is None or mnode.module is not module:
            # duplicate module names in one run: fall back to a solo
            # graph so this module still resolves its own bindings
            graph = cg.CallGraph([module])
            mnode = graph.nodes[cg.module_name(module.path)]
            symbols = graph.donator_symbols(
                FACTORY_DONATIONS, _donated_positions)

        def resolver(call: ast.Call) -> tuple[int, ...] | None:
            target = graph.resolve_callable(mnode, dotted_name(call.func))
            return symbols.get(target) if target is not None else None

        donators = self._collect_donators(module.tree)
        lister = _FunctionLister()
        lister.visit(module.tree)
        for fn in lister.functions:
            yield from self._check_function(fn, donators, module, resolver)

    @staticmethod
    def _collect_donators(tree: ast.Module) -> dict[str, tuple[int, ...]]:
        """binding repr -> donated positions, from assignments of
        ``jax.jit(..., donate_argnums=...)`` or framework factories."""
        donators: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            positions = _donated_positions(call)
            if positions is None:
                callee = dotted_name(call.func)
                if callee is not None:
                    positions = FACTORY_DONATIONS.get(
                        callee.rpartition(".")[2])
            if not positions:
                continue
            for target in node.targets:
                rep = _binding_repr(target)
                if rep is not None:
                    donators[rep] = positions
        return donators

    def _check_function(self, fn, donators: dict[str, tuple[int, ...]],
                        module: Module, resolver=None) -> Iterator[Finding]:
        # events per line: (kind, repr, node); processed line-by-line as
        # uses -> consumes -> rebinds so same-line rebinding stays clean
        consumes: dict[int, list[tuple[str, str]]] = {}
        uses: dict[int, list[tuple[str, ast.AST]]] = {}
        rebinds: dict[int, list[str]] = {}

        for node in _scope_walk(fn):
            if isinstance(node, ast.Call):
                positions = _donating_call_positions(node, donators, resolver)
                if positions:
                    callee = dotted_name(node.func) or "<jitted>"
                    for pos in positions:
                        if pos < len(node.args):
                            rep = _binding_repr(node.args[pos])
                            if rep is not None:
                                consumes.setdefault(node.lineno, []).append(
                                    (rep, callee))
            elif isinstance(node, (ast.Name, ast.Attribute)):
                rep = _binding_repr(node)
                if rep is None:
                    continue
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    rebinds.setdefault(node.lineno, []).append(rep)
                elif isinstance(node.ctx, ast.Load):
                    uses.setdefault(node.lineno, []).append((rep, node))

        tainted: dict[str, tuple[int, str]] = {}  # repr -> (line, callee)
        for line in sorted(set(consumes) | set(uses) | set(rebinds)):
            for rep, node in uses.get(line, ()):
                # an Attribute load also loads its prefixes; check the
                # exact repr and any tainted prefix (state.params after
                # `state` was donated)
                for t_rep, (t_line, callee) in tainted.items():
                    if (rep == t_rep or rep.startswith(t_rep + ".")) \
                            and line > t_line:
                        yield Finding(
                            self.name, module.path, node.lineno,
                            node.col_offset,
                            f"{rep!r} was donated to {callee!r} on line "
                            f"{t_line} (donate_argnums) and must not be "
                            f"read afterwards: XLA aliased its buffers "
                            f"into the result — rebind the output "
                            f"instead",
                        )
                        break
            for rep, callee in consumes.get(line, ()):
                tainted[rep] = (line, callee)
            for rep in rebinds.get(line, ()):
                tainted.pop(rep, None)
