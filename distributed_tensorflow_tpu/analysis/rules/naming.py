"""metric-naming — registered metrics are SHAPED like their kind.

PR 7's closed-vocab rule pins WHICH metric names may exist (the
docs/observability.md tables are the vocabulary). This rule pins how
names are SHAPED, so the scrape surface stays mechanically queryable:

- **counters end ``_total``** (`serve_admitted_total`,
  `retry_attempts_total`) — the Prometheus convention every dashboard
  and the goodput ledger's keyed lookups rely on;
- **gauges and histograms never end ``_total``** — a `_total` gauge
  reads as a counter and silently breaks rate() queries;
- **second-valued histograms end ``_seconds``** — a histogram whose
  help text says seconds/latency/duration/wall-clock must carry the
  unit in its name (`train_step_seconds`, `serve_ttft_seconds`);
- **no sub-second unit tokens** (``ms`` / ``us`` / ``ns`` /
  ``millis`` … anywhere between underscores, so ``lat_ms_total``
  can't hide one before the counter suffix): the exposition base unit
  is seconds; milliseconds live in *presentation* (tools/bench_serve's
  p50/p99 report), never in a registered name;
- **registration kind matches the documented kind**: registering
  `goodput_fraction` as a counter when the docs table says gauge is
  vocabulary drift the membership check can't see;
- **the docs tables themselves obey the shape rules** — the
  vocabulary and its convention move together, so a misshapen name
  cannot enter through the documentation side either.

Names are literals or module-level string constants (same resolution
as closed-vocab); dynamic names (`f"train_{key}"`) are invisible by
design. The docs tables are parsed, never imported: rows of the form
``| `name{labels}` | counter/gauge/histogram | … |``, with multiple
backticked names per row sharing the row's kind.
"""

from __future__ import annotations

import re
from typing import Iterator

import ast

from ..core import Finding, LintContext, Module, Rule, register

DOCS_PATH = "docs/observability.md"

_KINDS = ("counter", "gauge", "histogram")

_NAME_RE = re.compile(r"`([A-Za-z_][A-Za-z0-9_]*)")

_SECONDS_HELP_RE = re.compile(
    r"\b(seconds|latency|duration|wall[- ]?clock|wall time)\b",
    re.IGNORECASE,
)

#: sub-second unit TOKENS — banned anywhere in a name, not just as a
#: suffix, so "serve_lat_ms_total" can't smuggle milliseconds past the
#: counter suffix
_SUBSECOND_TOKENS = frozenset({
    "ms", "millis", "milliseconds", "us", "usec", "micros",
    "microseconds", "ns", "nanos", "nanoseconds",
})


def _shape_problem(name: str, kind: str, help_text: str | None) -> str | None:
    """The convention violation for (name, kind), or None."""
    if kind == "counter" and not name.endswith("_total"):
        return (f"counter {name!r} must end in '_total' (Prometheus "
                f"convention; the goodput ledger and every rate() query "
                f"rely on it)")
    if kind in ("gauge", "histogram") and name.endswith("_total"):
        return (f"{kind} {name!r} ends in '_total', the counter suffix — "
                f"it will read as a counter on the scrape surface; drop "
                f"the suffix (or register a counter)")
    bad_units = _SUBSECOND_TOKENS.intersection(name.split("_"))
    if bad_units:
        return (f"metric {name!r} carries a sub-second unit token "
                f"{sorted(bad_units)[0]!r} — the exposition base unit "
                f"is seconds; record seconds and keep millisecond "
                f"formatting in presentation code")
    if kind == "histogram" and help_text is not None \
            and _SECONDS_HELP_RE.search(help_text) \
            and not name.endswith("_seconds"):
        return (f"histogram {name!r} observes seconds (per its help "
                f"text) but does not end in '_seconds' — the unit "
                f"belongs in the name")
    return None


def _docs_kinds(ctx: LintContext) -> dict[str, tuple[str, int]]:
    """``name -> (kind, docs line)`` parsed from the metric tables."""
    cached = ctx.scratch.get("docs_metric_kinds")
    if cached is not None:
        return cached
    out: dict[str, tuple[str, int]] = {}
    docs = ctx.read_repo_file(DOCS_PATH)
    if docs:
        for lineno, line in enumerate(docs.splitlines(), 1):
            cells = [c.strip() for c in line.split("|")]
            # a table row is "| cell | cell | cell |": split yields
            # leading/trailing empties
            if len(cells) < 4 or cells[0] or cells[2].lower() not in _KINDS:
                continue
            kind = cells[2].lower()
            for name in _NAME_RE.findall(cells[1]):
                out[name] = (kind, lineno)
    ctx.scratch["docs_metric_kinds"] = out
    return out


@register
class MetricNamingRule(Rule):
    name = "metric-naming"
    summary = ("counters end _total, second-valued histograms end "
               "_seconds, no sub-second suffixes, and registration "
               "kinds match the docs/observability.md tables")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        docs = _docs_kinds(ctx)
        constants = module.constant_strings()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or node.func.attr not in _KINDS or not node.args:
                continue
            kind = node.func.attr
            name = self._literal(node.args[0], constants)
            if name is None:
                continue
            help_text = None
            if len(node.args) >= 2:
                help_text = self._literal(node.args[1], constants)
            problem = _shape_problem(name, kind, help_text)
            if problem is not None:
                yield Finding(self.name, module.path, node.lineno,
                              node.col_offset, problem)
            documented = docs.get(name)
            if documented is not None and documented[0] != kind:
                yield Finding(
                    self.name, module.path, node.lineno, node.col_offset,
                    f"{name!r} is registered as a {kind} but "
                    f"{DOCS_PATH}:{documented[1]} documents it as a "
                    f"{documented[0]} — the table is the contract; fix "
                    f"the registration or the docs",
                )

    @staticmethod
    def _literal(node: ast.AST, constants: dict[str, str]) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return constants.get(node.id)
        return None

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        # the documentation side of the vocabulary obeys the same shape
        # rules — checked once per run, anchored at the docs line
        for name, (kind, lineno) in sorted(_docs_kinds(ctx).items()):
            problem = _shape_problem(name, kind, help_text=None)
            if problem is not None:
                yield Finding(self.name, DOCS_PATH, lineno, 0,
                              f"{problem} (documented in the metric "
                              f"table)")
