"""The sharding seam's three static contracts (PR 14, ROADMAP item 3).

The partition-rules engine (parallel/sharding.py ``partition_rules`` /
``match_partition_rules``) makes sharding assignment declarative: every
shipped model resolves its specs through a named (regex → PartitionSpec)
table with a static ``coverage`` param-path fixture. These rules keep
that seam honest without importing jax:

- **shard-rules-coverage** — every statically-readable
  ``partition_rules(...)`` table in the run compiles, ships a coverage
  fixture, and satisfies the totality/liveness contract against it under
  first-match precedence: every coverage path is won by some row, every
  row wins at least one path. A rotted regex (or a row shadowed by an
  earlier one — the pre-engine wide_deep ``table_\\d+`` swallowing every
  ``wide_table_`` param) is a lint error here before it is a runtime
  ``PartitionCoverageError`` anywhere. Table names must be unique across
  the run. ``partition_rules`` calls are resolved through the
  PR 10 cross-module call graph (import-aliased and module-qualified
  spellings all land on ``parallel.sharding.partition_rules``); a bare
  ``partition_rules``/``*.partition_rules`` call that the graph cannot
  resolve is still checked (fixtures, scratch trees). Rows whose pattern
  is not a string literal make a table non-simulatable: its match checks
  are skipped (regexes that ARE literal still compile-check).

- **mesh-axis-closed-vocab** — every axis name appearing as a STRING
  LITERAL in a ``PartitionSpec(...)`` construction or in a collective's
  axis argument (``lax.psum(x, "data")``, ``axis_name=...``) inside the
  mesh-consuming dirs (parallel/, ops/, train/, serve/, models/ — the
  rules tables live there) must belong to
  the declared vocabulary ``parallel/mesh.AXIS_NAMES`` (parsed, never
  imported). A typo'd axis is a lint error, not a runtime unbound-axis
  crash — or worse, a collective over the wrong axis that HANGS a pod.
  Axis names carried by ``mesh_lib.MODEL``-style constants are already
  import-checked; dynamic names (``factor_mesh_axis`` sub-axes) are
  invisible by design.

- **sharding-seam-bypass** — constructing ``NamedSharding`` or
  ``PartitionSpec`` inside the package, outside the seam, is an error:
  all persistent-state placement flows through parallel/sharding.py and
  the rules tables. Two reviewed carve-outs, both structural: (a) rows
  of a rules table — arguments of a ``partition_rules(...)`` call, or
  any function named ``*_rules`` (the composable row builders:
  ops/moe.moe_rules); (b) shard_map island layouts — specs built inside
  a function that itself calls ``shard_map`` describe that island's
  local view, not persistent placement (ring_attention / pipeline /
  fused-BN entry specs). Everything else routes through the seam's
  helpers (``named_sharding`` / ``tree_shardings`` / ``shard_tree`` /
  ``replicated_specs`` / ``shard_leading_dim``) — pre-fix examples:
  ops/embedding.to_mod_sharded's ad-hoc device_put,
  train/checkpoint._restore_step's inline NamedSharding map.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..callgraph import get_callgraph, module_name
from ..core import (
    Finding, LintContext, Module, Rule, dotted_name, register, seam_match,
)

MESH_PATH = "distributed_tensorflow_tpu/parallel/mesh.py"
SHARDING_MODULE = "distributed_tensorflow_tpu.parallel.sharding"

#: dirs whose code consumes mesh axes (the mesh-axis-closed-vocab
#: scope): the ISSUE-named four plus models/ — the rules tables living
#: there spell axes as mesh_lib constants, but a literal typo in a
#: table row would be exactly the crash class this rule exists to stop
AXIS_SCOPE = ("parallel/", "ops/", "train/", "serve/", "models/")

#: the seam file — the one place free to construct placement objects
SEAM_FILE = ("parallel/sharding.py",)

#: package dirs in the seam-bypass scope: the repo-rooted package plus
#: its subpackages, so package-relative invocations (``dtf_lint serve/``)
#: stay covered, mirroring core.seam_match's contract
PACKAGE_DIRS = (
    "distributed_tensorflow_tpu/", "models/", "ops/", "parallel/",
    "serve/", "train/", "data/", "obs/", "resilience/", "runtime/",
    "workloads/", "utils/",
)

#: collective verbs whose axis argument (2nd positional, or the
#: axis/axis_name keyword) names mesh axes — jax.lax primitives plus the
#: parallel/collectives.py vocabulary built on them
COLLECTIVE_NAMES = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "axis_index", "axis_size",
    "all_reduce", "all_mean", "reduce_scatter", "broadcast_from",
    "barrier_sum",
})

_AXIS_KEYWORDS = frozenset({"axis", "axis_name", "axis_names"})


# ---------------------------------------------------------------------------
# shared extraction helpers
# ---------------------------------------------------------------------------


def _axis_vocab(ctx: LintContext) -> frozenset | None:
    """parallel/mesh.AXIS_NAMES, parsed once per run."""
    if "mesh_axis_vocab" in ctx.scratch:
        return ctx.scratch["mesh_axis_vocab"]
    vocab = None
    src = ctx.read_repo_file(MESH_PATH)
    if src:
        for node in ast.parse(src).body:
            # both spellings: AXIS_NAMES = (...) and the annotated
            # AXIS_NAMES: tuple[str, ...] = (...) mesh.py actually uses
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            else:
                continue
            if (isinstance(target, ast.Name)
                    and target.id == "AXIS_NAMES"
                    and isinstance(value, (ast.Tuple, ast.List))):
                vals = [e.value for e in value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
                vocab = frozenset(vals)
    ctx.scratch["mesh_axis_vocab"] = vocab
    return vocab


def _spec_ctor_names(module: Module) -> dict[str, str]:
    """Local name → canonical ctor ('PartitionSpec'/'NamedSharding')
    for names this module binds from jax.sharding (``from jax.sharding
    import PartitionSpec as P``), read off the import statements — no
    jax import needed."""
    names: dict[str, str] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module == "jax.sharding":
            for a in node.names:
                if a.name in ("PartitionSpec", "NamedSharding"):
                    names[a.asname or a.name] = a.name
    return names


def _ctor_kind(call: ast.Call, ctors: dict[str, str]) -> str | None:
    """'PartitionSpec' / 'NamedSharding' when ``call`` constructs one:
    a name this module imported from jax.sharding (any alias), or a
    dotted spelling whose LEAF is the canonical class name
    (``jax.sharding.PartitionSpec``). A module-local rebind
    (``SpecCls = PartitionSpec``) or a re-exported alias on another
    module (``somemod.P``) is not resolved — the repo idiom is the
    direct import, and the heuristic is documented as such."""
    dn = dotted_name(call.func)
    if dn is None:
        return None
    if dn in ctors:
        return ctors[dn]
    leaf = dn.rpartition(".")[2]
    if leaf in ("PartitionSpec", "NamedSharding") and "." in dn:
        return leaf
    return None


def _is_partition_rules_call(call: ast.Call, module: Module,
                             ctx: LintContext) -> bool:
    """Does ``call`` invoke parallel.sharding.partition_rules? Resolved
    through the cross-module call graph when the import chain is in the
    run; name-matched otherwise (fixtures lint standalone)."""
    dn = dotted_name(call.func)
    if dn is None or dn.rpartition(".")[2] != "partition_rules":
        return False
    graph = get_callgraph(ctx)
    mnode = graph.nodes.get(module_name(module.path))
    if mnode is not None:
        target = graph.resolve_callable(mnode, dn)
        if target is not None:
            tmod, tfn = target
            # package-relative invocations (``dtf_lint parallel/``)
            # name the seam module without the repo-rooted prefix
            return tfn == "partition_rules" \
                and tmod.endswith("parallel.sharding")
    return True  # unresolvable: trust the distinctive name


def _literal_strings(node: ast.AST) -> Iterator[tuple[str, ast.AST]]:
    """String constants in ``node``, descending through tuples/lists
    (PartitionSpec entries may be tuples of axis names)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            yield from _literal_strings(e)


# ---------------------------------------------------------------------------
# shard-rules-coverage
# ---------------------------------------------------------------------------


class _TableRow:
    def __init__(self, node: ast.AST, pattern: str | None):
        self.node = node
        self.pattern = pattern  # None = dynamic (not a string literal)


def _module_constant_node(module: Module, name: str) -> ast.AST | None:
    """The value node of a module-level ``NAME = <expr>`` binding —
    plain or annotated (``NAME: tuple[str, ...] = <expr>``), like
    ``_axis_vocab``, so an annotated coverage constant cannot silently
    opt a table out of the simulation."""
    for node in module.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name):
            return node.value
        if (isinstance(node, ast.AnnAssign) and node.value is not None
                and isinstance(node.target, ast.Name)
                and node.target.id == name):
            return node.value
    return None


def _extract_table(call: ast.Call, module: Module):
    """(name, rows, coverage, coverage_node) from a partition_rules
    call. A ``coverage=NAME`` reference resolves through the module's
    own constants (the shipped tables freeze their fixture as a literal
    module-level tuple next to the table). ``coverage`` comes back as a
    list of (path, anchor-node) pairs, ``None`` when the expression is
    not statically readable."""
    name = None
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        name = call.args[0].value
    rules_node = call.args[1] if len(call.args) >= 2 else None
    coverage_node = None
    for kw in call.keywords:
        if kw.arg == "rules" and rules_node is None:
            rules_node = kw.value
        if kw.arg == "coverage":
            coverage_node = kw.value
    rows: list[_TableRow] = []
    if isinstance(rules_node, (ast.Tuple, ast.List)):
        for elt in rules_node.elts:
            if isinstance(elt, (ast.Tuple, ast.List)) and elt.elts:
                first = elt.elts[0]
                if isinstance(first, ast.Constant) \
                        and isinstance(first.value, str):
                    pattern = first.value
                elif dotted_name(first) is not None and \
                        dotted_name(first).rpartition(".")[2] == "CATCH_ALL":
                    # the seam's declared catch-all constant — resolve it
                    # so the conventional final row does not turn the
                    # whole table non-simulatable
                    pattern = r".*"
                else:
                    pattern = None
                rows.append(_TableRow(elt, pattern))
            else:
                rows.append(_TableRow(elt, None))
    resolved = coverage_node
    if isinstance(resolved, ast.Name):
        resolved = _module_constant_node(module, resolved.id)
    coverage: list[tuple[str, ast.AST]] | None = []
    if isinstance(resolved, (ast.Tuple, ast.List)):
        for s, n in _literal_strings(resolved):
            coverage.append((s, n))
        if len(coverage) != len(resolved.elts):
            coverage = None  # some entries are computed: opaque
    elif coverage_node is not None:
        coverage = None
    return name, rows, coverage, coverage_node


@register
class ShardRulesCoverageRule(Rule):
    name = "shard-rules-coverage"
    summary = ("every partition_rules table compiles, ships a coverage "
               "fixture, and is total with no dead rules against it "
               "(first-match precedence)")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        tables = ctx.scratch.setdefault("partition_tables", {})
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) \
                    or not _is_partition_rules_call(node, module, ctx):
                continue
            name, rows, coverage, coverage_node = _extract_table(
                node, module)
            if name is not None:
                prev = tables.get(name)
                if prev is not None and prev != (module.path, node.lineno):
                    yield Finding(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"partition rules table name {name!r} is already "
                        f"defined at {prev[0]}:{prev[1]} — table names "
                        f"are the attribution/debugging handle and must "
                        f"be unique across the tree",
                    )
                else:
                    tables[name] = (module.path, node.lineno)
            compiled: list[re.Pattern | None] = []
            simulatable = True
            for row in rows:
                if row.pattern is None:
                    simulatable = False
                    compiled.append(None)
                    continue
                try:
                    compiled.append(re.compile(row.pattern))
                except re.error as e:
                    yield Finding(
                        self.name, module.path, row.node.lineno,
                        row.node.col_offset,
                        f"rule pattern {row.pattern!r} in table "
                        f"{name!r} does not compile: {e}",
                    )
                    simulatable = False
                    compiled.append(None)
            if coverage_node is None or coverage == []:
                yield Finding(
                    self.name, module.path, node.lineno, node.col_offset,
                    f"partition rules table {name!r} ships no coverage "
                    f"fixture — with no static param-path listing, "
                    f"totality and dead-rule liveness cannot be checked "
                    f"until a training run crashes; freeze the served "
                    f"tree's paths into coverage=(...)",
                )
                continue
            if coverage is None or not simulatable or not rows:
                continue  # opaque coverage/rows: compile checks only
            won: set[int] = set()
            for path, pnode in coverage:
                for i, rx in enumerate(compiled):
                    if rx is not None and rx.search(path):
                        won.add(i)
                        break
                else:
                    yield Finding(
                        self.name, module.path, pnode.lineno,
                        pnode.col_offset,
                        f"coverage path {path!r} matches NO rule of "
                        f"table {name!r} — the table is not total; at "
                        f"runtime this param would raise "
                        f"PartitionCoverageError (declare the "
                        f"replicated remainder with a catch-all row)",
                    )
            for i, row in enumerate(rows):
                if i not in won and row.pattern is not None:
                    yield Finding(
                        self.name, module.path, row.node.lineno,
                        row.node.col_offset,
                        f"rule {row.pattern!r} in table {name!r} wins "
                        f"no coverage path under first-match precedence "
                        f"— a dead rule is a typo or is shadowed by an "
                        f"earlier row; fix the pattern, reorder, or "
                        f"delete it",
                    )


# ---------------------------------------------------------------------------
# mesh-axis-closed-vocab
# ---------------------------------------------------------------------------


@register
class MeshAxisClosedVocabRule(Rule):
    name = "mesh-axis-closed-vocab"
    summary = ("axis-name string literals in PartitionSpec constructions "
               "and collective axis arguments (parallel/, ops/, train/, "
               "serve/, models/) must be in parallel/mesh.AXIS_NAMES")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        if not seam_match(module.path, AXIS_SCOPE):
            return
        vocab = _axis_vocab(ctx)
        if not vocab:
            return  # vocabulary unreadable: stay silent, never guess
        ctors = _spec_ctor_names(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            leaf = dn.rpartition(".")[2] if dn else None
            kind = _ctor_kind(node, ctors)
            checks: list[tuple[str, ast.AST, str]] = []
            if kind == "PartitionSpec":
                for s, n in (x for a in node.args
                             for x in _literal_strings(a)):
                    checks.append((s, n, "PartitionSpec entry"))
            if leaf in COLLECTIVE_NAMES:
                if len(node.args) >= 2:
                    for s, n in _literal_strings(node.args[1]):
                        checks.append((s, n, f"{leaf}() axis"))
            for kw in node.keywords:
                if kw.arg in _AXIS_KEYWORDS and (
                        leaf in COLLECTIVE_NAMES
                        or kind == "PartitionSpec"):
                    for s, n in _literal_strings(kw.value):
                        checks.append((s, n, f"{kw.arg}="))
            for axis, anchor, where in checks:
                if axis not in vocab:
                    yield Finding(
                        self.name, module.path, anchor.lineno,
                        anchor.col_offset,
                        f"axis name {axis!r} ({where}) is not in the "
                        f"declared mesh-axis vocabulary "
                        f"{sorted(vocab)} (parallel/mesh.AXIS_NAMES) — "
                        f"a typo'd axis is an unbound-axis crash or a "
                        f"collective over the WRONG axis that hangs a "
                        f"pod; use the mesh_lib constants",
                    )


# ---------------------------------------------------------------------------
# sharding-seam-bypass
# ---------------------------------------------------------------------------


def _contains_shard_map(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn is not None and dn.rpartition(".")[2] == "shard_map":
                return True
    return False


@register
class ShardingSeamBypassRule(Rule):
    name = "sharding-seam-bypass"
    summary = ("NamedSharding/PartitionSpec are constructed only in "
               "parallel/sharding.py, rules tables, and shard_map "
               "island layouts — placement flows through the seam")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if not seam_match(path, PACKAGE_DIRS) \
                or seam_match(path, SEAM_FILE) \
                or "/analysis/" in f"/{path}" \
                or "/tests/" in f"/{path}":
            return
        ctors = _spec_ctor_names(module)
        if not ctors and "PartitionSpec" not in module.source \
                and "NamedSharding" not in module.source:
            return  # cheap pre-filter: nothing to construct one with
        findings: list[Finding] = []
        shard_map_cache: dict[ast.AST, bool] = {}

        def fn_allows(fn: ast.AST) -> bool:
            if fn not in shard_map_cache:
                shard_map_cache[fn] = (
                    fn.name.endswith("_rules")
                    or _contains_shard_map(fn)
                )
            return shard_map_cache[fn]

        def visit(node: ast.AST, fn_stack: tuple, in_table: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_stack = fn_stack + (node,)
            if isinstance(node, ast.Call):
                if _is_partition_rules_call(node, module, ctx):
                    in_table = True
                kind = _ctor_kind(node, ctors)
                if kind is not None and not in_table \
                        and not any(fn_allows(f) for f in fn_stack):
                    helper = ("sharding.named_sharding / tree_shardings "
                              "/ shard_tree / shard_leading_dim"
                              if kind == "NamedSharding" else
                              "a partition_rules table, "
                              "sharding.REPLICATED / replicated_specs, "
                              "or a seam helper")
                    findings.append(Finding(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"{kind} constructed outside the sharding seam "
                        f"— all placement assignment flows through "
                        f"parallel/sharding.py and the rules tables "
                        f"(carve-outs: *_rules row builders, shard_map "
                        f"island layouts); use {helper}",
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child, fn_stack, in_table)

        visit(module.tree, (), False)
        yield from findings
