"""dtflint rule modules — importing this package registers every rule.

One module per rule family; each rule's docstring is its catalog entry
(docs/static-analysis.md mirrors them with real pre-fix examples).
"""

from . import donation, exceptions, host_sync, locks, vocab  # noqa: F401

__all__ = ["donation", "exceptions", "host_sync", "locks", "vocab"]
