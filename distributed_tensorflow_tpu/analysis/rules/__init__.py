"""dtflint rule modules — importing this package registers every rule.

One module per rule family; each rule's docstring is its catalog entry
(docs/static-analysis.md mirrors them with real pre-fix examples).
The v1 five (host-sync, donation, locks, vocab, exceptions) are joined
by the v2 contract rules (determinism, durability, naming), and the
reachability rules now run on the analysis/callgraph.py project-scope
engine. The v3 partitioning family (shard-rules-coverage,
mesh-axis-closed-vocab, sharding-seam-bypass) audits the sharding seam:
rules tables total and live, axis names in the closed mesh vocabulary,
placement constructed only at parallel/sharding.py.
"""

from . import (  # noqa: F401
    determinism,
    donation,
    durability,
    exceptions,
    host_sync,
    locks,
    naming,
    partitioning,
    vocab,
)

__all__ = [
    "determinism", "donation", "durability", "exceptions",
    "host_sync", "locks", "naming", "partitioning", "vocab",
]
