"""atomic-durable-write — durable state is written tmp+fsync+os.replace.

Recovery walks a chain of on-disk evidence: checkpoint shards and
MANIFEST.dtf (runtime/io.py CRC-verified payloads), quarantine.json
(the trajectory's hole list — a torn write there and every future
incarnation fetches a different stream), heartbeat/INCARNATION/
RESTORE_STEP control files (resilience/fleet.py), postmortem dumps
(obs/flightrec.py), and fleet telemetry snapshots / merged timelines
(obs/fleetview.py). The framework's ONE idiom for all of them:

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(...)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)

A bare ``open(path, "w")`` to durable state can be observed half
written by a concurrent reader AND can survive a crash as a torn file
that *looks* complete — the failure mode PR 4's manifest verifier
exists to catch, reintroduced one layer down.

Detection (heuristic, tuned to this repo's idioms):

- **Where.** Truncating writes (mode ``"w"`` / ``"wb"`` / ``"w+"`` /
  ``"x"`` …) are examined (a) in the durable-state modules —
  train/checkpoint.py, resilience/fleet.py, resilience/anomaly.py,
  obs/flightrec.py, runtime/io.py — and (b) anywhere else when the
  enclosing function's source mentions a durable artifact (checkpoint/
  ckpt/manifest/heartbeat/quarantine/incarnation/restore_step/
  postmortem). Append-mode streams (JSONL event logs) are incremental
  by design and exempt.
- **Clean.** The write itself targets the tmp sibling (its path
  expression names ``tmp`` — the repo's one spelling of the idiom)
  AND the enclosing function calls BOTH ``os.fsync`` and
  ``os.replace`` (or ``os.rename``). Per-WRITE, not per-function: a
  bare ``open(path, "w")`` next to a correct atomic write of another
  file is still a finding — co-location with one atomic write must
  not bless a second, torn one. Delegating to a shared atomic writer
  (runtime/io.write_payload, flightrec's dump) is naturally clean: no
  raw ``open`` in the caller.
- **Reviewed exceptions** use the standard suppression with a comment:
  fleet's heartbeat ``_atomic_write`` deliberately skips fsync (a
  record lost to a crash IS the liveness signal) and carries the
  marker plus its justification.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import (
    Finding, LintContext, Module, Rule, dotted_name, register, seam_match,
)

#: modules whose writes are durable by definition (segment-anchored —
#: core.seam_match)
DURABLE_MODULES = (
    "train/checkpoint.py",
    "resilience/fleet.py",
    "resilience/liveness.py",
    "resilience/anomaly.py",
    "obs/flightrec.py",
    "obs/fleetview.py",
    "runtime/io.py",
)

#: a function elsewhere is IN the durable contract when its source
#: names one of the recovery artifacts
_DURABLE_TOKENS = re.compile(
    r"checkpoint|ckpt|manifest|heartbeat|quarantine|incarnation"
    r"|restore_step|postmortem",
    re.IGNORECASE,
)

_TRUNCATING_MODES = frozenset({
    "w", "wb", "w+", "wb+", "w+b", "x", "xb", "x+", "xb+",
})


def _is_durable_module(path: str) -> bool:
    return seam_match(path, DURABLE_MODULES)


def _write_mode(call: ast.Call) -> str | None:
    """The literal mode of an ``open(...)`` call, when truncating."""
    if dotted_name(call.func) not in ("open", "io.open"):
        return None
    mode_node = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if isinstance(mode_node, ast.Constant) and isinstance(mode_node.value, str):
        mode = mode_node.value.replace("t", "")
        if mode in _TRUNCATING_MODES:
            return mode_node.value
    return None


class _FunctionStack(ast.NodeVisitor):
    """(write-open call, enclosing function or None) pairs."""

    def __init__(self):
        self.hits: list[tuple[ast.Call, str, ast.AST | None]] = []
        self._stack: list[ast.AST] = []

    def visit_FunctionDef(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        mode = _write_mode(node)
        if mode is not None:
            # attribute to the OUTERMOST function: the atomic idiom's
            # fsync/replace legitimately live in the enclosing scope of
            # a nested helper
            self.hits.append(
                (node, mode, self._stack[0] if self._stack else None))
        self.generic_visit(node)


def _fn_source(module: Module, fn: ast.AST) -> str:
    end = getattr(fn, "end_lineno", fn.lineno)
    return "\n".join(module.lines[fn.lineno - 1:end])


def _has_atomic_shape(fn: ast.AST | None) -> bool:
    if fn is None:
        return False
    saw_fsync = saw_replace = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn in ("os.fsync", "fsync"):
                saw_fsync = True
            elif dn in ("os.rename", "os.replace", "rename", "replace"):
                saw_replace = True
    return saw_fsync and saw_replace


def _targets_tmp(call: ast.Call) -> bool:
    """This WRITE opens the tmp sibling: its path expression names
    ``tmp`` (``tmp``, ``path + ".tmp"``, ``f"{path}.tmp"`` — the one
    spelling of the idiom in this repo). Judged per write so a bare
    in-place open next to a correct atomic write stays a finding."""
    if not call.args:
        return False
    try:
        text = ast.unparse(call.args[0])
    except Exception:  # pragma: no cover — unparse of any expr
        return False
    return "tmp" in text.lower()


@register
class AtomicDurableWriteRule(Rule):
    name = "atomic-durable-write"
    summary = ("a truncating open() on durable state (checkpoint/"
               "manifest/heartbeat/quarantine paths) outside the "
               "tmp+fsync+os.replace idiom")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        durable_module = _is_durable_module(module.path)
        scanner = _FunctionStack()
        scanner.visit(module.tree)
        for call, mode, fn in scanner.hits:
            if not durable_module:
                if fn is None or not _DURABLE_TOKENS.search(
                        _fn_source(module, fn)):
                    continue
            if _targets_tmp(call) and _has_atomic_shape(fn):
                continue
            yield Finding(
                self.name, module.path, call.lineno, call.col_offset,
                f"open(..., {mode!r}) writes durable state in place — a "
                f"crash (or a concurrent reader) sees a torn file that "
                f"looks complete; write to a .tmp sibling, flush + "
                f"os.fsync, then os.replace onto the real path "
                f"(runtime/io.write_payload is the shared idiom)",
            )
