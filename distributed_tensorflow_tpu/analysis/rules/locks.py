"""lock-discipline — every access to a lock-guarded attribute holds it.

The repo's concurrency story is a handful of small critical sections:
the flight recorder's ring (emitters on the watchdog thread, manifest
stampers, the train loop), the metrics registry's table (merge vs
snapshot — the torn-sum bug PR 6 fixed), the watchdog's beat/stall
flag pair, the JSONL logger's file handle. Each class owns a
``self._lock``; the invariant is that an attribute *mutated* under that
lock is never touched outside it.

Inference (per class that assigns ``self.<name> = threading.Lock()`` /
``RLock()``):

- **Guarded set** = self-attributes mutated inside a ``with
  self.<lock>:`` block in any method other than ``__init__`` —
  mutation meaning assignment / augmented assignment / deletion,
  a subscript store (``self._metrics[k] = v``), or a call of a known
  mutator method (``append``, ``clear``, ``set``, ``inc``, ``write``,
  …) on the attribute.
- **Violation** = ANY access (read or write) to a guarded attribute
  outside such a block — a lock-free read of merge-mutated state is
  exactly how ``Registry.snapshot`` tore.

Exemptions: ``__init__`` (single-threaded construction), and methods
whose names end in ``_unlocked`` / ``_locked`` — the repo's documented
convention for helpers that require the caller to hold the lock
(``Registry._snapshot_unlocked``).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, LintContext, Module, Rule, dotted_name, register

#: method names that mutate their receiver (dict/list/deque/set plus
#: the obs metric verbs and file-handle writes)
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "inc", "dec", "set", "observe", "reset", "merge_from",
    "write", "put", "put_nowait",
})

_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "Lock", "RLock",
})

_EXEMPT_METHODS = frozenset({"__init__", "__del__", "__repr__"})


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` (one level) → ``X``."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _with_holds_lock(node: ast.With, lock_names: set[str]) -> bool:
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            expr = expr.func  # e.g. a lock factory; keep the chain
        attr = _self_attr(expr)
        if attr is not None and attr in lock_names:
            return True
    return False


class _Access:
    __slots__ = ("attr", "write", "locked", "node", "method")

    def __init__(self, attr, write, locked, node, method):
        self.attr = attr
        self.write = write
        self.locked = locked
        self.node = node
        self.method = method


class _ClassScanner(ast.NodeVisitor):
    """Collect every self-attribute access in a class body, annotated
    with write-ness and whether a ``with self.<lock>`` encloses it."""

    def __init__(self, lock_names: set[str]):
        self.lock_names = lock_names
        self.accesses: list[_Access] = []
        self._method = ""
        self._lock_depth = 0

    def scan(self, cls: ast.ClassDef) -> list[_Access]:
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._method = stmt.name
                self._lock_depth = 0
                self.visit(stmt)
        return self.accesses

    def visit_With(self, node: ast.With):
        held = _with_holds_lock(node, self.lock_names)
        if held:
            self._lock_depth += 1
        self.generic_visit(node)
        if held:
            self._lock_depth -= 1

    visit_AsyncWith = visit_With

    def _record(self, attr: str, write: bool, node: ast.AST):
        if attr in self.lock_names:
            return  # the lock itself is touched to be taken
        self.accesses.append(_Access(
            attr, write, self._lock_depth > 0, node, self._method))

    def visit_Attribute(self, node: ast.Attribute):
        attr = _self_attr(node)
        if attr is not None:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._record(attr, write, node)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        attr = _self_attr(node.value)
        if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            self._record(attr, True, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        # self.X.mutator(...) mutates X
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATOR_METHODS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                self._record(attr, True, node)
        self.generic_visit(node)


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    summary = ("an attribute mutated under self._lock is accessed "
               "outside a with-lock block")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(node, module)

    def _check_class(self, cls: ast.ClassDef,
                     module: Module) -> Iterator[Finding]:
        lock_names: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and dotted_name(node.value.func) in _LOCK_CTORS:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        lock_names.add(attr)
        if not lock_names:
            return

        accesses = _ClassScanner(lock_names).scan(cls)
        guarded = {
            a.attr for a in accesses
            if a.write and a.locked and a.method not in _EXEMPT_METHODS
        }
        if not guarded:
            return
        for a in accesses:
            if a.attr not in guarded or a.locked:
                continue
            if a.method in _EXEMPT_METHODS \
                    or a.method.endswith(("_unlocked", "_locked")):
                continue
            verb = "written" if a.write else "read"
            yield Finding(
                self.name, module.path, a.node.lineno, a.node.col_offset,
                f"self.{a.attr} is {verb} in {cls.name}.{a.method} "
                f"without holding self.{sorted(lock_names)[0]}, but is "
                f"mutated under that lock elsewhere — a lock-free "
                f"access can observe (or cause) a torn update; wrap it "
                f"in `with self.{sorted(lock_names)[0]}:` or rename the "
                f"helper *_unlocked if the caller holds the lock",
            )
