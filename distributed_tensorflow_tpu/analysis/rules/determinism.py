"""wall-clock-in-seam — deterministic seams may not read ambient entropy.

Every resilience guarantee since PR 3 reduces to ONE invariant: the
training trajectory is a pure function of ``(seed, index, quarantine
set)``. That is what makes SIGTERM→restore→resume BIT-identical, what
lets the anomaly bisector replay from the last good checkpoint and
blame the exact raw batch, and what makes the chaos E2Es oracles rather
than flaky approximations. A ``time.time()`` in a batch builder, an
unseeded ``np.random.rand()`` in an augmentation, an ``os.urandom`` in
a replay path — each silently breaks replay while every test that
doesn't cross a restart keeps passing.

Seams and tiers:

- **Strict seams** (wall-clock AND unseeded randomness banned):
  ``data/`` (batches are pure functions of ``(seed, index)`` —
  ``pipeline.batch_rng`` is the idiom), ``train/step.py`` (the step's
  only randomness is ``fold_in(state.rng, step)``), ``resilience/``
  (the replay/bisection machinery itself: FaultPlan schedules, retry
  jitter, supervisor backoff are all seeded; time flows through the
  injectable FaultClock / ``clock=`` parameters), and
  ``tests/chaos_worker.py`` (the bit-identity E2E oracle — a wall
  clock read there weakens exactly what it certifies).
- **Scaffolding seams** (unseeded randomness banned, wall-clock
  allowed): ``tests/`` — test *data* must be reproducible, but
  deadlines and liveness budgets are process control, not trajectory
  inputs.

What fires:

- ``time.time`` / ``time.monotonic`` / ``time.perf_counter`` (and the
  ``_ns`` variants) CALLS in a strict seam. A *reference* as an
  injectable default (``def f(..., clock=time.monotonic)``) is the
  sanctioned idiom and never flagged — only the ambient read is.
- the global-state ``random`` module: ``random.random()``,
  ``random.randint(...)``, ``random.seed(...)`` (global seeding is
  order-dependent shared state), ``random.Random()`` with NO seed,
  ``random.SystemRandom(...)``. ``random.Random(seed)`` is clean.
- global-state numpy randomness: ``np.random.<fn>(...)``,
  ``np.random.RandomState()`` / ``np.random.default_rng()`` with no
  seed. Seeded constructors (``np.random.RandomState(seed)``,
  ``np.random.default_rng(seed)``) are clean — methods on the
  resulting generator are invisible to this rule by design.
- ``os.urandom(...)`` everywhere in a seam (both tiers).

``jax.random`` is exempt: its explicit-key API is the seam. Aliases
are resolved from the module's imports (``import numpy as onp``,
``from time import monotonic as now``).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import (
    Finding, LintContext, Module, Rule, dotted_name, register, seam_match,
)

#: wall-clock AND randomness banned (segment-anchored: core.seam_match,
#: so package-relative lint invocations like ``resilience/`` hit too)
STRICT_SEAMS = (
    "data/",
    "train/step.py",
    "resilience/",
    "chaos_worker.py",
)

#: randomness banned, wall-clock allowed (pure test scaffolding)
SCAFFOLDING_SEAMS = ("tests/",)

_CLOCK_FNS = frozenset({
    "time", "monotonic", "perf_counter",
    "time_ns", "monotonic_ns", "perf_counter_ns",
})

#: np.random constructors that are clean WHEN seeded
_SEEDED_CTORS = frozenset({"RandomState", "default_rng", "Generator"})

_FIX_HINT = ("route it through an injectable clock seam "
             "(resilience/faults.FaultClock, a clock= parameter) or a "
             "seeded generator (data/pipeline.batch_rng, "
             "random.Random(seed), np.random.RandomState(seed))")


def _tier(path: str) -> str | None:
    if seam_match(path, STRICT_SEAMS):
        return "strict"
    if seam_match(path, SCAFFOLDING_SEAMS):
        return "scaffolding"
    return None


class _ImportMap:
    """Local aliases of the entropy-bearing stdlib/numpy namespaces."""

    def __init__(self, tree: ast.Module):
        self.time: set[str] = set()
        self.random: set[str] = set()
        self.numpy: set[str] = set()
        self.numpy_random: set[str] = set()
        self.os: set[str] = set()
        #: from-imported leaf alias -> canonical "module.leaf"
        self.direct: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    if a.name == "time":
                        self.time.add(bound)
                    elif a.name == "random":
                        self.random.add(bound)
                    elif a.name == "numpy":
                        self.numpy.add(bound)
                    elif a.name == "numpy.random" and a.asname:
                        self.numpy_random.add(a.asname)
                    elif a.name == "os":
                        self.os.add(bound)
            elif isinstance(node, ast.ImportFrom) and not node.level:
                if node.module in ("time", "random", "os", "numpy.random"):
                    for a in node.names:
                        if a.name != "*":
                            self.direct[a.asname or a.name] = \
                                f"{node.module}.{a.name}"


@register
class WallClockRule(Rule):
    name = "wall-clock-in-seam"
    summary = ("time.time()/unseeded random/np.random/os.urandom inside "
               "a deterministic seam (data/, train/step.py, resilience/, "
               "test oracles) — replay stops being bit-identical")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        tier = _tier(module.path)
        if tier is None:
            return
        imports = _ImportMap(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = self._entropy_kind(node, imports)
            if hit is None:
                continue
            kind, what = hit
            if kind == "clock" and tier != "strict":
                continue  # scaffolding: deadlines are process control
            yield Finding(
                self.name, module.path, node.lineno, node.col_offset,
                f"{what} inside a deterministic seam — the trajectory "
                f"must be a pure function of (seed, index, quarantine "
                f"set) or replay/bisection stops being bit-identical; "
                f"{_FIX_HINT}",
            )

    @staticmethod
    def _entropy_kind(call: ast.Call,
                      imports: _ImportMap) -> tuple[str, str] | None:
        dn = dotted_name(call.func)
        if dn is None:
            return None
        canonical = imports.direct.get(dn, dn)
        parts = canonical.split(".")
        head = parts[0]
        has_args = bool(call.args or call.keywords)

        if canonical.startswith("time.") or head in imports.time:
            leaf = parts[-1]
            if len(parts) == 2 and leaf in _CLOCK_FNS:
                return ("clock", f"time.{leaf}() reads the ambient wall "
                                 f"clock")
            return None
        if canonical.startswith("random.") or head in imports.random:
            if len(parts) != 2:
                return None
            leaf = parts[-1]
            if leaf == "Random":
                if has_args:
                    return None  # seeded instance: the sanctioned idiom
                return ("random", "random.Random() without a seed")
            if leaf == "SystemRandom":
                return ("random", "random.SystemRandom (OS entropy)")
            return ("random", f"global-state random.{leaf}()")
        np_random = (
            (head in imports.numpy and len(parts) == 3
             and parts[1] == "random")
            or (head in imports.numpy_random and len(parts) == 2)
            or (canonical.startswith("numpy.random.") and len(parts) == 3)
        )
        if np_random:
            leaf = parts[-1]
            if leaf in _SEEDED_CTORS:
                if has_args:
                    return None
                return ("random", f"np.random.{leaf}() without a seed")
            return ("random", f"global-state np.random.{leaf}()")
        if canonical == "os.urandom" or (head in imports.os
                                         and canonical.endswith(".urandom")):
            return ("random", "os.urandom() (OS entropy)")
        return None
