"""exception-hygiene — no swallowed failures in the classification seams.

The recovery stack (resilience/retry.py → supervisor.py →
train/checkpoint.py) is a fault *taxonomy*: OSError means transient,
FloatingPointError means poisoned, everything else is fatal, and
``RetryExhausted.__cause__`` carries the real failure through the
layers. A bare ``except:`` or a silently-swallowed broad handler breaks
that chain — the supervisor restarts on garbage, or a real corruption
is classified as "nothing happened". PR 3-6 reviews policed this by
hand ("never mask the original exception", "log, don't drop"); this
rule does it mechanically.

Checks:

- **bare except** — flagged everywhere. Even on a best-effort path,
  name the exception class (``except Exception``) so ``SystemExit`` /
  ``KeyboardInterrupt`` keep propagating.
- **silent broad handler** — ``except Exception`` / ``BaseException``
  whose body does nothing but ``pass`` / ``...`` / ``continue``:
  flagged everywhere (a broad catch must raise, log, or record).
- **silent handler in a fault-classification seam** — inside
  ``resilience/``, ``train/checkpoint.py``, or ``train/loop.py`` even
  a *narrow* handler may not be silent: these modules ARE the
  classification layer, and a dropped exception there is a dropped
  fault. Handle it, log it, or suppress the finding with a comment
  explaining why the drop is sound.

"Silent" is syntactic: the handler body contains only ``pass`` /
``...`` / bare ``continue`` / string constants. A handler that raises,
returns, logs, assigns, or emits is never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import (
    Finding, LintContext, Module, Rule, dotted_name, register, seam_match,
)

#: modules where even a narrow silent handler defeats fault
#: classification (see module docstring; segment-anchored via
#: core.seam_match, shared with the determinism/durability seams)
SEAM_PATHS = (
    "resilience/",
    "train/checkpoint.py",
    "train/loop.py",
)

_BROAD = frozenset({"Exception", "BaseException"})


def _is_seam(path: str) -> bool:
    return seam_match(path, SEAM_PATHS)


def _caught_names(node: ast.ExceptHandler) -> list[str]:
    t = node.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    names = []
    for e in elts:
        dn = dotted_name(e)
        if dn is not None:
            names.append(dn.rpartition(".")[2])
    return names


def _is_silent(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


@register
class ExceptionHygieneRule(Rule):
    name = "exception-hygiene"
    summary = ("bare except, or a silently-swallowed handler in a "
               "retry/supervisor/checkpoint seam")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        seam = _is_seam(module.path)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Finding(
                    self.name, module.path, node.lineno, node.col_offset,
                    "bare `except:` catches SystemExit and "
                    "KeyboardInterrupt too — name the class (`except "
                    "Exception:` at the broadest) so control-flow "
                    "exceptions keep propagating",
                )
                continue
            if not _is_silent(node.body):
                continue
            names = _caught_names(node)
            if any(n in _BROAD for n in names):
                yield Finding(
                    self.name, module.path, node.lineno, node.col_offset,
                    f"`except {'/'.join(names)}` swallows every failure "
                    f"silently — raise, log, or record it; a silent "
                    f"broad catch hides the exact bug class the "
                    f"supervisor's fault taxonomy exists to classify",
                )
            elif seam:
                yield Finding(
                    self.name, module.path, node.lineno, node.col_offset,
                    f"silent `except {'/'.join(names) or '?'}` inside a "
                    f"fault-classification seam — this layer IS the "
                    f"taxonomy (transient/poisoned/fatal); a dropped "
                    f"exception here is a dropped fault. Log it or "
                    f"suppress with a comment explaining why the drop "
                    f"is sound",
                )
