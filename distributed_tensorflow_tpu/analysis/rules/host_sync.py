"""host-sync-in-step — no device round-trips inside jit-traced code.

The framework's whole performance story is host-drives/device-computes:
the train loop dispatches step N+1 while N executes, the serve engine
keeps one fused decode program hot. A ``float()`` / ``bool()`` /
``.item()`` / ``np.asarray()`` / ``jax.device_get()`` on a traced value
inside a jit-compiled step either fails at trace time (concretization
error) or — worse, when it slips through on a re-traced python value —
silently serializes dispatch with execution, the ~40x step-rate cliff
utils/benchmarking.py documents for tunneled platforms.

What counts as jit-reachable (PROJECT-SCOPE since the v2 engine —
analysis/callgraph.py holds the resolution contract):

- functions decorated with ``jax.jit`` / ``jit`` / ``pjit`` /
  ``jax.pmap`` (bare or via ``functools.partial``);
- functions passed to those wrappers anywhere in the lint run —
  including across modules (``jax.jit(decode_lib.prefill)``,
  ``jax.jit(partial(prefill, model))``);
- the framework's step-function naming convention: ``train_step`` /
  ``eval_step`` / ``decode_step`` / ``prefill``, which are jitted by
  factories in *other* modules (train/step.jit_train_step,
  serve/decode.jit_prefill) — the names are part of the framework
  contract;
- anything those functions call transitively, across module
  boundaries: bare names, from-imported symbols, module-alias dotted
  calls, ``self.`` methods, ``partial`` targets, and function refs
  passed to trace-context primitives (``lax.scan`` bodies run under
  the caller's trace). Nested defs are scanned with their enclosing
  function.

``float()``/``bool()`` on literal constants are ignored (static config
arithmetic, not a sync). Numpy aliases are resolved from the module's
imports; ``jnp.asarray`` is device-side and never flagged. When a
function is reachable only through another module, the finding says
which root reached it — cross-module reachability is exactly what the
v1 per-module engine could not see.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .. import callgraph as cg
from ..core import Finding, LintContext, Module, Rule, dotted_name, register

#: re-exported for compatibility: the naming contract lives with the
#: graph engine now
STEP_FUNCTION_NAMES = cg.STEP_FUNCTION_NAMES
_JIT_WRAPPERS = cg.JIT_WRAPPERS

#: method-call syncs on any receiver
_SYNC_METHODS = frozenset({"item"})


def _numpy_aliases(tree: ast.Module) -> set[str]:
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


@register
class HostSyncRule(Rule):
    name = "host-sync-in-step"
    summary = ("float()/bool()/.item()/np.asarray()/jax.device_get() "
               "inside a jit-reachable step/decode function "
               "(reachability follows calls across modules)")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        graph = cg.get_callgraph(ctx)
        parents = ctx.scratch.get("host_sync_reachable")
        if parents is None:
            parents = graph.jit_reachable()
            ctx.scratch["host_sync_reachable"] = parents
        mname = cg.module_name(module.path)
        mnode = graph.nodes.get(mname)
        if mnode is None or mnode.module is not module:
            # duplicate module names in one run (two files with the same
            # stem): the graph kept one; scan the other module-locally
            # so nothing is silently skipped
            solo = cg.CallGraph([module])
            mnode = solo.nodes[cg.module_name(module.path)]
            parents = solo.jit_reachable()
        np_aliases = _numpy_aliases(module.tree)

        seen_lines: set[tuple[int, int]] = set()
        for key in sorted(parents):
            if key[0] != mnode.name:
                continue
            origin = self._origin(parents, key)
            for d in mnode.defs.get(key[1], ()):
                for node in ast.walk(d):
                    if not isinstance(node, ast.Call):
                        continue
                    hit = self._sync_kind(node, np_aliases)
                    if hit is None:
                        continue
                    pos = (node.lineno, node.col_offset)
                    if pos in seen_lines:
                        continue  # defs overlap when nested
                    seen_lines.add(pos)
                    yield Finding(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"{hit} inside jit-reachable function "
                        f"{key[1]!r}{origin} forces a host sync (or a "
                        f"trace-time concretization error); compute it "
                        f"with jnp on-device or move it outside the "
                        f"jitted step",
                    )

    @staticmethod
    def _origin(parents, key) -> str:
        """' (reached from X in mod)' when jit-ness arrived from another
        module — the provenance the per-module v1 engine couldn't name."""
        node = key
        while parents.get(node) is not None:
            node = parents[node]
        if node[0] == key[0]:
            return ""
        return f" (reached from {node[1]!r} in {node[0]})"

    @staticmethod
    def _sync_kind(call: ast.Call, np_aliases: set[str]) -> str | None:
        dn = dotted_name(call.func)
        if dn in ("float", "bool") and call.args:
            if all(isinstance(a, ast.Constant) for a in call.args):
                return None  # float("inf") etc: static config, no sync
            return f"{dn}() on a traced value"
        if dn in ("jax.device_get", "device_get"):
            return "jax.device_get()"
        if dn is not None and "." in dn:
            head, _, method = dn.rpartition(".")
            if method == "asarray" and head.split(".")[0] in np_aliases | {"np"}:
                return f"{dn}() (numpy materializes the device array)"
            if method == "array" and head.split(".")[0] in np_aliases | {"np"}:
                return f"{dn}() (numpy materializes the device array)"
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _SYNC_METHODS and not call.args:
            return f".{call.func.attr}()"
        return None
