"""host-sync-in-step — no device round-trips inside jit-traced code.

The framework's whole performance story is host-drives/device-computes:
the train loop dispatches step N+1 while N executes, the serve engine
keeps one fused decode program hot. A ``float()`` / ``bool()`` /
``.item()`` / ``np.asarray()`` / ``jax.device_get()`` on a traced value
inside a jit-compiled step either fails at trace time (concretization
error) or — worse, when it slips through on a re-traced python value —
silently serializes dispatch with execution, the ~40x step-rate cliff
utils/benchmarking.py documents for tunneled platforms.

What counts as jit-reachable (module-local, documented approximation):

- functions decorated with ``jax.jit`` / ``jit`` / ``pjit`` /
  ``jax.pmap`` (bare or via ``functools.partial``);
- functions passed to those wrappers anywhere in the module
  (``step = jax.jit(train_step)``, ``jax.jit(partial(fn, model))``);
- the framework's step-function naming convention: ``train_step`` /
  ``eval_step`` / ``decode_step`` / ``prefill``, which are jitted by
  factories in *other* modules (train/step.jit_train_step,
  serve/decode.jit_prefill) — the module-local scan cannot see that
  wrapping, so the names are part of the framework contract;
- anything those functions call by bare name in the same module
  (transitive), including nested defs (a ``lax.scan`` body is traced).

``float()``/``bool()`` on literal constants are ignored (static config
arithmetic, not a sync). Numpy aliases are resolved from the module's
imports; ``jnp.asarray`` is device-side and never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, LintContext, Module, Rule, dotted_name, register

#: functions jitted by factories in other modules — the framework's
#: step-function naming contract (see module docstring)
STEP_FUNCTION_NAMES = frozenset({
    "train_step", "eval_step", "decode_step", "prefill",
})

_JIT_WRAPPERS = frozenset({
    "jit", "jax.jit", "pjit", "jax.pjit", "jax.pmap", "pmap",
})

#: method-call syncs on any receiver
_SYNC_METHODS = frozenset({"item"})


def _numpy_aliases(tree: ast.Module) -> set[str]:
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


def _partial_target(call: ast.Call) -> ast.AST | None:
    """``partial(f, ...)`` / ``functools.partial(f, ...)`` → f."""
    if dotted_name(call.func) in ("partial", "functools.partial") and call.args:
        return call.args[0]
    return None


def _wrapped_function_name(node: ast.AST) -> str | None:
    """The bare name of the function being jit-wrapped, if resolvable."""
    if isinstance(node, ast.Call):
        inner = _partial_target(node)
        if inner is not None:
            return _wrapped_function_name(inner)
        return None
    if isinstance(node, ast.Name):
        return node.id
    return None


class _FunctionIndex(ast.NodeVisitor):
    """name -> FunctionDef nodes (module, class, and nested scopes; a
    name maps to every def sharing it — conservative union)."""

    def __init__(self):
        self.defs: dict[str, list[ast.AST]] = {}

    def visit_FunctionDef(self, node):
        self.defs.setdefault(node.name, []).append(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


@register
class HostSyncRule(Rule):
    name = "host-sync-in-step"
    summary = ("float()/bool()/.item()/np.asarray()/jax.device_get() "
               "inside a jit-reachable step/decode function")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        tree = module.tree
        index = _FunctionIndex()
        index.visit(tree)
        np_aliases = _numpy_aliases(tree)

        roots: set[str] = set()
        for name, defs in index.defs.items():
            if name in STEP_FUNCTION_NAMES:
                roots.add(name)
            for d in defs:
                for dec in d.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    dn = dotted_name(target)
                    if dn in _JIT_WRAPPERS:
                        roots.add(name)
                    elif isinstance(dec, ast.Call) and dn in (
                            "partial", "functools.partial"):
                        inner = dec.args[0] if dec.args else None
                        if dotted_name(inner) in _JIT_WRAPPERS:
                            roots.add(name)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and dotted_name(node.func) in _JIT_WRAPPERS and node.args:
                wrapped = _wrapped_function_name(node.args[0])
                if wrapped and wrapped in index.defs:
                    roots.add(wrapped)

        # transitive closure over bare-name calls within the module
        reachable: set[str] = set()
        frontier = sorted(roots & set(index.defs))
        while frontier:
            name = frontier.pop()
            if name in reachable:
                continue
            reachable.add(name)
            for d in index.defs[name]:
                for node in ast.walk(d):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Name) \
                            and node.func.id in index.defs \
                            and node.func.id not in reachable:
                        frontier.append(node.func.id)

        seen_lines: set[tuple[int, int]] = set()
        for name in sorted(reachable):
            for d in index.defs[name]:
                for node in ast.walk(d):
                    if not isinstance(node, ast.Call):
                        continue
                    hit = self._sync_kind(node, np_aliases)
                    if hit is None:
                        continue
                    key = (node.lineno, node.col_offset)
                    if key in seen_lines:
                        continue  # defs overlap when nested
                    seen_lines.add(key)
                    yield Finding(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"{hit} inside jit-reachable function "
                        f"{name!r} forces a host sync (or a trace-time "
                        f"concretization error); compute it with jnp "
                        f"on-device or move it outside the jitted step",
                    )

    @staticmethod
    def _sync_kind(call: ast.Call, np_aliases: set[str]) -> str | None:
        dn = dotted_name(call.func)
        if dn in ("float", "bool") and call.args:
            if all(isinstance(a, ast.Constant) for a in call.args):
                return None  # float("inf") etc: static config, no sync
            return f"{dn}() on a traced value"
        if dn in ("jax.device_get", "device_get"):
            return "jax.device_get()"
        if dn is not None and "." in dn:
            head, _, method = dn.rpartition(".")
            if method == "asarray" and head.split(".")[0] in np_aliases | {"np"}:
                return f"{dn}() (numpy materializes the device array)"
            if method == "array" and head.split(".")[0] in np_aliases | {"np"}:
                return f"{dn}() (numpy materializes the device array)"
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _SYNC_METHODS and not call.args:
            return f".{call.func.attr}()"
        return None
