"""closed-vocab — emitted names must come from the declared vocabularies.

The framework keeps several CLOSED vocabularies whose whole value is
that code, validators, and docs can never drift: the flight-recorder
event kinds (``obs/flightrec.EVENT_KINDS`` — ``emit`` rejects unknowns
at runtime, but only when the line actually executes), the goodput
waste causes (``obs/goodput.WASTE_CAUSES``), the metric-name tables in
``docs/observability.md``, and the FLOPs contract's single ×3
multiplier site (``obs/goodput.train_mfu`` — the generalization of
tests/test_flops_contract.py into the lint layer). This rule checks all
of them statically, so a typo'd event kind in a rarely-taken error path
fails CI instead of raising mid-postmortem.

Checks:

- ``<flightrec>.emit("<kind>", ...)`` — a literal kind must be in
  ``EVENT_KINDS`` (receivers recognized by the repo's naming idiom:
  ``self.flightrec`` / ``rec`` / ``recorder`` / ``default_recorder()``).
- ``<reqtrace>.transition(rid, "<phase>", ...)`` — a literal request
  lifecycle phase must be in ``obs/reqtrace.PHASES`` (receivers by the
  same idiom: ``self.reqtrace`` / ``reqtrace`` / ``rt`` /
  ``router_trace`` / ``eng_trace``), and every ``PHASES`` entry must
  appear in ``docs/observability.md`` — the request-tracing phase
  table is part of the vocabulary's contract.
- ``note_wasted("<cause>", ...)`` — a literal cause must be in
  ``WASTE_CAUSES``.
- registry registrations ``.counter/.gauge/.histogram("<name>", ...)``
  inside the package (tools and tests excluded — smoke checks register
  scratch names) must appear in the ``docs/observability.md`` tables;
  names bound through module-level string constants are resolved.
- every ``EVENT_KINDS`` entry must appear in ``docs/observability.md``
  (the event table is part of the vocabulary's contract).
- ``train_flops_multiplier()`` is called from exactly one site:
  ``obs/goodput.py`` (the shared ``train_mfu``). Any other call site
  re-applies the ×3 multiplier and double-counts MFU.

Vocabularies are extracted by PARSING the framework sources (no
imports), so the linter stays stdlib-only and lints the tree it is
looking at.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import (
    Finding, LintContext, Module, Rule, call_name, dotted_name, register,
)

#: the one module allowed to call train_flops_multiplier()
MFU_SITE = "distributed_tensorflow_tpu/obs/goodput.py"

_FLIGHTREC_RECEIVERS = frozenset({"flightrec", "rec", "recorder"})

_REQTRACE_RECEIVERS = frozenset(
    {"reqtrace", "rt", "router_trace", "eng_trace"})

_DOCS_NAME_RE = re.compile(r"`([A-Za-z_][A-Za-z0-9_:]*)")


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _load_vocab(ctx: LintContext) -> dict:
    """Parse the framework vocabularies once per lint run."""
    if "vocab" in ctx.scratch:
        return ctx.scratch["vocab"]
    vocab = {"event_kinds": None, "waste_causes": None, "docs_names": None,
             "phases": None}

    src = ctx.read_repo_file("distributed_tensorflow_tpu/obs/flightrec.py")
    if src:
        vocab["event_kinds"] = _string_tuple_constant(src, "EVENT_KINDS")

    src = ctx.read_repo_file("distributed_tensorflow_tpu/obs/reqtrace.py")
    if src:
        vocab["phases"] = _string_tuple_constant(src, "PHASES")

    src = ctx.read_repo_file("distributed_tensorflow_tpu/obs/goodput.py")
    if src:
        causes = []
        for node in ast.parse(src).body:
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.startswith("WASTE_")
                    and node.targets[0].id != "WASTE_CAUSES"
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                causes.append(node.value.value)
        vocab["waste_causes"] = frozenset(causes) if causes else None

    docs = ctx.read_repo_file("docs/observability.md")
    if docs:
        vocab["docs_names"] = frozenset(_DOCS_NAME_RE.findall(docs))

    ctx.scratch["vocab"] = vocab
    return vocab


def _string_tuple_constant(src: str, name: str) -> frozenset[str] | None:
    for node in ast.parse(src).body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, (ast.Tuple, ast.List))):
            vals = [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            return frozenset(vals)
    return None


def _is_flightrec_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        dn = dotted_name(node.func)
        return dn is not None and dn.rpartition(".")[2] == "default_recorder"
    dn = dotted_name(node)
    if dn is None:
        return False
    return dn.rpartition(".")[2] in _FLIGHTREC_RECEIVERS


def _is_reqtrace_receiver(node: ast.AST) -> bool:
    dn = dotted_name(node)
    if dn is None:
        return False
    return dn.rpartition(".")[2] in _REQTRACE_RECEIVERS


def _in_package(module: Module, ctx: LintContext) -> bool:
    p = _norm(module.path)
    return ("distributed_tensorflow_tpu/" in p or
            p.startswith("distributed_tensorflow_tpu")) \
        and "/analysis/" not in p


@register
class ClosedVocabRule(Rule):
    name = "closed-vocab"
    summary = ("flight-recorder kinds, waste causes, metric names, and "
               "the single MFU-multiplier site must match the declared "
               "vocabularies")

    def check_module(self, module: Module,
                     ctx: LintContext) -> Iterator[Finding]:
        vocab = _load_vocab(ctx)
        constants = module.constant_strings()
        sites = ctx.scratch.setdefault("mfu_sites", [])
        in_pkg = _in_package(module, ctx)
        if _norm(module.path).endswith("obs/flightrec.py"):
            ctx.scratch["flightrec_module"] = module.path
        if _norm(module.path).endswith("obs/reqtrace.py"):
            ctx.scratch["reqtrace_module"] = module.path

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = call_name(node)

            if dn is not None \
                    and dn.rpartition(".")[2] == "train_flops_multiplier":
                sites.append((module.path, node.lineno, node.col_offset))

            # flight-recorder event kinds
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "emit" \
                    and _is_flightrec_receiver(node.func.value) \
                    and node.args:
                kind = self._literal(node.args[0], constants)
                kinds = vocab["event_kinds"]
                if kind is not None and kinds and kind not in kinds:
                    yield Finding(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"flight-recorder event kind {kind!r} is not in "
                        f"obs/flightrec.EVENT_KINDS — emit() will raise "
                        f"at runtime; extend the closed vocabulary (and "
                        f"the docs/observability.md event table) to add "
                        f"a kind",
                    )

            # request-trace lifecycle phases
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "transition" \
                    and _is_reqtrace_receiver(node.func.value) \
                    and len(node.args) >= 2:
                phase = self._literal(node.args[1], constants)
                phases = vocab["phases"]
                if phase is not None and phases and phase not in phases:
                    yield Finding(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"request-trace phase {phase!r} is not in "
                        f"obs/reqtrace.PHASES — transition() will raise "
                        f"at runtime; extend the closed vocabulary (and "
                        f"the docs/observability.md phase table) to add "
                        f"a phase",
                    )

            # goodput waste causes
            if dn is not None and dn.rpartition(".")[2] == "note_wasted" \
                    and node.args:
                cause = self._literal(node.args[0], constants)
                causes = vocab["waste_causes"]
                if cause is not None and causes and cause not in causes:
                    yield Finding(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"waste cause {cause!r} is not in "
                        f"obs/goodput.WASTE_CAUSES — note_wasted() will "
                        f"raise at runtime",
                    )

            # metric registrations vs the docs tables (package only)
            if in_pkg and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("counter", "gauge", "histogram") \
                    and node.args:
                mname = self._literal(node.args[0], constants)
                docs = vocab["docs_names"]
                if mname is not None and docs and mname not in docs:
                    yield Finding(
                        self.name, module.path, node.lineno,
                        node.col_offset,
                        f"metric {mname!r} is registered in the package "
                        f"but absent from docs/observability.md — the "
                        f"metric tables are the closed vocabulary; "
                        f"document the metric (or fix the name)",
                    )

    @staticmethod
    def _literal(node: ast.AST, constants: dict[str, str]) -> str | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return constants.get(node.id)
        return None

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        vocab = _load_vocab(ctx)

        # single ×3 multiplier site
        sites = ctx.scratch.get("mfu_sites", [])
        goodput_sites = [s for s in sites if _norm(s[0]).endswith(MFU_SITE)]
        for path, line, col in sites:
            if not _norm(path).endswith(MFU_SITE):
                yield Finding(
                    self.name, path, line, col,
                    "train_flops_multiplier() called outside "
                    "obs/goodput.py — the fwd+bwd ×3 multiplier has "
                    "exactly ONE site (goodput.train_mfu); route MFU "
                    "math through it or bench/log/scrape numbers will "
                    "disagree",
                )
        for path, line, col in goodput_sites[1:]:
            yield Finding(
                self.name, path, line, col,
                "train_flops_multiplier() called more than once in "
                "obs/goodput.py — the multiplier contract is one "
                "application per MFU computation, in train_mfu only",
            )

        # every EVENT_KIND documented
        fr_path = ctx.scratch.get("flightrec_module")
        kinds = vocab["event_kinds"]
        docs = vocab["docs_names"]
        if fr_path and kinds and docs:
            for kind in sorted(kinds - docs):
                yield Finding(
                    self.name, fr_path, 1, 0,
                    f"EVENT_KINDS entry {kind!r} is missing from the "
                    f"docs/observability.md event table — the closed "
                    f"vocabulary and its docs must move together",
                )

        # every request-trace PHASE documented
        rt_path = ctx.scratch.get("reqtrace_module")
        phases = vocab["phases"]
        if rt_path and phases and docs:
            for phase in sorted(phases - docs):
                yield Finding(
                    self.name, rt_path, 1, 0,
                    f"PHASES entry {phase!r} is missing from the "
                    f"docs/observability.md request-tracing phase table "
                    f"— the closed vocabulary and its docs must move "
                    f"together",
                )
