"""Process-liveness protocol — the ONE implementation shared by every
fleet-shaped supervisor (docs/resilience.md "Fleet", docs/serving.md
"Serve fleet").

The training gang (resilience/fleet.FleetSupervisor) and the serving
fleet (serve/fleet.ServeFleetSupervisor) supervise worker PROCESSES
over the same collective-free control plane: per-worker heartbeat files
rewritten atomically, incarnation fencing, staleness judged on the
MONITOR's clock, and a terminate→grace→kill→reap teardown of launch-seam
handles. Factoring it here keeps the protocol from drifting into two
copies — the liveness semantics below are load-bearing for BOTH
supervisors' death detection:

- **Atomic, deliberately non-durable writes.** Heartbeats are rewritten
  tmp + rename so a reader never sees a torn record, but NOT fsynced: a
  record lost to a crash IS the signal the protocol detects (a beat
  that never reached disk reads as a missed beat, which is the truth).
- **Incarnation fencing.** Supervisors bump an incarnation before every
  (re)launch; writers stamp every beat with theirs. A beat from an
  older incarnation — a straggler the teardown hasn't reaped yet — is
  treated as *absent*, never as liveness.
- **Monitor-clock staleness.** Writer timestamps never cross processes
  (monotonic clocks don't compare); the monitor times out on the
  moments it OBSERVES ``seq`` change, on its own clock.
- **Launch seam.** Supervisors launch workers through an injected
  callable returning a Popen-shaped handle
  (``poll/terminate/kill/wait/pid``); ``ensure_dead``/``reap`` are the
  shared teardown of one such handle.

Clocks and sleeps are injectable (``FaultClock`` drop-in) so every
liveness edge case — stale-but-ticking vs absent vs stale-incarnation —
is deterministically testable without real processes.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Callable

logger = logging.getLogger(__name__)

INCARNATION_FILE = "INCARNATION"


def atomic_write(path: str, text: str) -> None:
    """tmp + rename so a reader never sees a torn record; no fsync —
    these files trade durability for freshness (a record lost to a
    crash IS the signal the protocol detects: a heartbeat that didn't
    reach disk reads as a missed beat, which is the truth)."""
    tmp = f"{path}.tmp"
    # reviewed: deliberately NOT the fsync idiom — see docstring; an
    # fsync per beat would put a disk flush on the liveness hot path
    with open(tmp, "w") as f:  # dtflint: disable=atomic-durable-write
        f.write(text)
    os.replace(tmp, path)


def heartbeat_path(fleet_dir: str, worker: int) -> str:
    """The one heartbeat file of worker ``worker`` under the fleet dir —
    the single definition of the layout, shared by writer and monitor."""
    return os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)),
        f"heartbeat-{worker}.json",
    )


def read_incarnation(fleet_dir: str) -> int:
    """Current fleet incarnation (0 when no fleet has ever run here).
    Workers call this at startup and stamp every heartbeat with it."""
    path = os.path.join(
        os.path.abspath(os.path.expanduser(fleet_dir)), INCARNATION_FILE)
    try:
        with open(path) as f:
            return int(f.read().strip())
    except FileNotFoundError:
        return 0
    except (OSError, ValueError) as e:
        logger.warning("unreadable incarnation file %s (%s); assuming 0",
                       path, e)
        return 0


def write_incarnation(fleet_dir: str, incarnation: int) -> None:
    d = os.path.abspath(os.path.expanduser(fleet_dir))
    os.makedirs(d, exist_ok=True)
    atomic_write(os.path.join(d, INCARNATION_FILE), f"{int(incarnation)}\n")


# ---------------------------------------------------------------------------
# Heartbeats: writer (worker side) and monitor (supervisor side)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Heartbeat:
    """One decoded heartbeat record. ``t`` is the WRITER's clock —
    informational only; staleness is judged by the monitor observing
    ``seq`` changes on its OWN clock, because monotonic clocks are not
    comparable across processes."""

    pid: int
    seq: int
    t: float
    step: int
    attempt: int
    incarnation: int
    phase: str
    cause: str | None = None
    restore_step: int | None = None
    restore_fallback: bool | None = None
    #: elastic plan acknowledgment: the newest ShardPlan version this
    #: worker has applied (or is holding at), and its sharded world size
    plan_version: int | None = None
    world: int | None = None


def read_heartbeat(path: str) -> Heartbeat | None:
    """Decode the heartbeat at ``path``; None when absent or unreadable
    (an unreadable heartbeat is indistinguishable from a missing one —
    both mean 'no proof of life')."""
    try:
        with open(path) as f:
            data = json.load(f)
        return Heartbeat(
            pid=int(data["pid"]), seq=int(data["seq"]),
            t=float(data["t"]), step=int(data.get("step", 0)),
            attempt=int(data.get("attempt", 0)),
            incarnation=int(data.get("incarnation", 0)),
            phase=str(data.get("phase", "init")),
            cause=data.get("cause"),
            restore_step=data.get("restore_step"),
            restore_fallback=data.get("restore_fallback"),
            plan_version=data.get("plan_version"),
            world=data.get("world"),
        )
    except FileNotFoundError:
        return None
    except (OSError, ValueError, KeyError, TypeError) as e:
        logger.warning("unreadable heartbeat %s (%s); treating as absent",
                       path, e)
        return None


class HeartbeatWriter:
    """Worker-side heartbeat emitter: every ``beat()`` bumps ``seq`` and
    atomically rewrites the file with the latest known
    ``{step, attempt, phase, restore...}``. Fields persist across beats,
    so a supervisor that only samples the newest record still sees the
    restore note from an earlier one. Thread-safe (the optional pulse
    thread and the work loop both beat)."""

    def __init__(self, path: str, incarnation: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 pulse_interval_s: float | None = None):
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        self.path = path
        self.incarnation = int(incarnation)
        self.clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._step = 0
        self._attempt = 0
        self._phase = "init"
        self._cause: str | None = None
        self._restore: tuple[int, bool] | None = None
        self._plan: tuple[int, int] | None = None  # (version, world)
        self._stop = threading.Event()
        self._redirect: str | None = None
        self._pulse: threading.Thread | None = None
        if pulse_interval_s is not None:
            if pulse_interval_s <= 0:
                raise ValueError("pulse_interval_s must be positive")
            self._pulse = threading.Thread(
                target=self._pulse_loop, args=(pulse_interval_s,),
                daemon=True, name="fleet-heartbeat-pulse")
            self._pulse.start()

    def beat(self, step: int | None = None, attempt: int | None = None,
             phase: str | None = None) -> None:
        """Write one heartbeat; omitted fields keep their last value."""
        with self._lock:
            if step is not None:
                self._step = int(step)
            if attempt is not None:
                self._attempt = int(attempt)
            if phase is not None:
                self._phase = str(phase)
            self._seq += 1
            rec: dict[str, Any] = {
                "pid": os.getpid(), "seq": self._seq,
                "t": float(self.clock()), "step": self._step,
                "attempt": self._attempt, "incarnation": self.incarnation,
                "phase": self._phase, "cause": self._cause,
            }
            if self._restore is not None:
                rec["restore_step"], rec["restore_fallback"] = self._restore
            if self._plan is not None:
                rec["plan_version"], rec["world"] = self._plan
            # write INSIDE the lock: beats from the pulse thread and the
            # work loop serialize, so seq order on disk == write order
            atomic_write(self._redirect or self.path, json.dumps(rec))

    def redirect(self, path: str | None) -> None:
        """Point subsequent beats at ``path`` instead of the real
        heartbeat file — the control-plane partition seam
        (resilience/faults.ControlPlanePartition): a writer whose beats
        land in a shadow file is indistinguishable, to its monitor,
        from one behind an unreachable directory, while the process
        keeps working. ``None`` restores the real path; callers beat
        right after so recovery is observable immediately."""
        with self._lock:
            self._redirect = path

    def note_restore(self, step: int, fallback: bool) -> None:
        """Record which checkpoint this incarnation restored from — the
        fleet relays it into its timeline as the gang's ``ckpt_restore``
        evidence."""
        with self._lock:
            self._restore = (int(step), bool(fallback))
        self.beat()

    def note_plan(self, version: int, world: int) -> None:
        """Record the newest ShardPlan this worker has applied (or is
        holding at) — the fleet's resize-acknowledgment signal. The
        caller beats separately (usually with the matching phase)."""
        with self._lock:
            self._plan = (int(version), int(world))

    @property
    def phase(self) -> str:
        """Last beaten phase — lets a transient phase (``save``) restore
        what it replaced instead of guessing."""
        with self._lock:
            return self._phase

    def finish(self, phase: str, cause: str | None = None) -> None:
        """Terminal beat (``done`` / ``preempted`` / ``failed``) — the
        record the supervisor reads after the process exits."""
        with self._lock:
            self._cause = cause
        self.close()
        self.beat(phase=phase)

    def _pulse_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self.beat()

    def close(self) -> None:
        """Stop the pulse thread (idempotent; the file is left behind —
        its staleness is the death signal)."""
        self._stop.set()
        if self._pulse is not None:
            self._pulse.join(timeout=5.0)
            self._pulse = None


#: HeartbeatMonitor.check() statuses
WAITING = "waiting"   # no beat yet, launch grace not exceeded
LIVE = "live"
DEAD = "dead"         # no (current-incarnation) beat within the budget
STALLED_HB = "stalled"  # beats ticking, no progress past the budget

#: phases after which a frozen step is expected (the process is exiting)
TERMINAL_PHASES = ("done", "preempted", "failed")

#: phases during which a frozen step is SANCTIONED: the supervisor
#: itself is holding the worker (e.g. at a resize barrier) and bounds
#: the hold with its own timeout — the stall budget must not race it
HOLD_PHASES = ("barrier",)


class HeartbeatMonitor:
    """Supervisor-side liveness judgment for ONE worker's heartbeat file.

    Staleness is measured on the MONITOR's clock from the moments it
    *observes* the heartbeat change — never from the heartbeat's own
    timestamp (monotonic clocks don't compare across processes). A
    heartbeat stamped with a different incarnation is ignored entirely:
    a straggler from the previous gang writing right up until its
    SIGKILL must read as *absent*, not alive.

    Stall = ``seq`` still ticking (the pulse thread, or any beat
    source) while (step, attempt, phase) make NO progress past the
    stall budget, outside the terminal phases — so a pulsed worker hung
    in build/restore (phase ``init``) is just as detectable as one hung
    mid-work. Size ``stall_timeout_s`` above the longest legitimate
    restore + first-step compile.
    """

    def __init__(self, path: str, incarnation: int,
                 clock: Callable[[], float] = time.monotonic,
                 heartbeat_timeout_s: float = 30.0,
                 stall_timeout_s: float = 120.0,
                 launch_grace_s: float = 120.0):
        if heartbeat_timeout_s <= 0 or stall_timeout_s <= 0 \
                or launch_grace_s <= 0:
            raise ValueError("liveness budgets must be positive")
        self.path = path
        self.incarnation = int(incarnation)
        self.clock = clock
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.stall_timeout_s = stall_timeout_s
        self.launch_grace_s = launch_grace_s
        self.heartbeat: Heartbeat | None = None  # last ACCEPTED record
        self._t0 = clock()
        self._last_seq: int | None = None
        self._t_seq = self._t0
        self._last_progress: tuple | None = None  # (step, attempt, phase)
        self._t_progress = self._t0

    def check(self) -> str:
        """One liveness poll: WAITING / LIVE / DEAD / STALLED_HB."""
        now = self.clock()
        hb = read_heartbeat(self.path)
        if hb is not None and hb.incarnation == self.incarnation:
            self.heartbeat = hb
            if hb.seq != self._last_seq:
                self._last_seq, self._t_seq = hb.seq, now
            progress = (hb.step, hb.attempt, hb.phase)
            if progress != self._last_progress:
                self._last_progress, self._t_progress = progress, now
        if self._last_seq is None:
            # nothing (of this incarnation) ever beat: grant the launch
            # grace — process spawn + interpreter + framework import
            return DEAD if now - self._t0 > self.launch_grace_s else WAITING
        if now - self._t_seq > self.heartbeat_timeout_s:
            return DEAD
        if (self.heartbeat is not None
                and self.heartbeat.phase not in TERMINAL_PHASES
                and self.heartbeat.phase not in HOLD_PHASES
                and now - self._t_progress > self.stall_timeout_s):
            return STALLED_HB
        return LIVE


# ---------------------------------------------------------------------------
# Launch-seam handle teardown (Popen-shaped: poll/terminate/kill/wait/pid)
# ---------------------------------------------------------------------------


def ensure_dead(handle, term_grace_s: float, poll_s: float,
                clock: Callable[[], float] = time.monotonic,
                sleep: Callable[[float], None] = time.sleep) -> None:
    """Make one launch-seam handle's death final before its slot is
    rewired: terminate (grace for a coordinated shutdown), kill past
    the grace, reap."""
    if handle.poll() is None:
        handle.terminate()
        deadline = clock() + term_grace_s
        while handle.poll() is None and clock() < deadline:
            sleep(min(poll_s, term_grace_s / 4))
        if handle.poll() is None:
            handle.kill()
    reap(handle)


def reap(handle, timeout_s: float = 5.0) -> bool:
    """Wait on one handle so no zombie outlives its supervisor. Must be
    called even on a just-SIGKILLed child whose ``poll()`` still reads
    None (the kernel hasn't finished tearing it down). Best-effort
    bookkeeping: returns False instead of raising."""
    try:
        handle.wait(timeout=timeout_s)
        return True
    except Exception as e:
        logger.warning("liveness: reaping pid %s failed: %r",
                       getattr(handle, "pid", "?"), e)
        return False
